"""SQL DDL: CREATE/DROP TABLE|DATABASE, SHOW, DESCRIBE.

The reference exposes DDL through each engine's catalog integration
(FlinkCatalog.java createTable / SparkCatalog) using Flink/Spark SQL
grammar; this is the engine-neutral analog over the same Catalog API, so a
reference runbook's DDL ports by string edit::

    CREATE TABLE db.t (k BIGINT NOT NULL, v STRING, dt STRING,
                       PRIMARY KEY (k, dt) NOT ENFORCED)
        PARTITIONED BY (dt) WITH ('bucket' = '2')
    CREATE TABLE IF NOT EXISTS db.t (...)
    DROP TABLE [IF EXISTS] db.t
    CREATE DATABASE [IF NOT EXISTS] db   /  DROP DATABASE db
    SHOW DATABASES / SHOW TABLES [IN db] / SHOW CREATE TABLE db.t
    DESCRIBE db.t

Types accept the reference's SQL names (BIGINT, INT, STRING, VARCHAR(n),
DECIMAL(p,s), TIMESTAMP(p), DOUBLE, FLOAT, BOOLEAN, DATE, BYTES, ...) via
types.parse_type.
"""

from __future__ import annotations

import re
from typing import TYPE_CHECKING, Any

from ..types import DataField, RowType, parse_type

if TYPE_CHECKING:
    from ..catalog import Catalog

__all__ = ["ddl", "DdlError"]


class DdlError(ValueError):
    pass


_CREATE_TABLE_HEAD_RE = re.compile(
    r"^\s*CREATE\s+TABLE\s+(?P<ine>IF\s+NOT\s+EXISTS\s+)?`?(?P<name>[\w.]+)`?\s*\(",
    re.I | re.S,
)
_CREATE_TABLE_TAIL_RE = re.compile(
    r"^\s*(?:PARTITIONED\s+BY\s*\((?P<parts>[^)]*)\)\s*)?"
    r"(?:WITH\s*\((?P<opts>.*)\)\s*)?;?\s*$",
    re.I | re.S,
)
_DROP_TABLE_RE = re.compile(
    r"^\s*DROP\s+TABLE\s+(?P<ife>IF\s+EXISTS\s+)?`?(?P<name>[\w.]+)`?\s*;?\s*$", re.I
)
_CREATE_DB_RE = re.compile(
    r"^\s*CREATE\s+DATABASE\s+(?P<ine>IF\s+NOT\s+EXISTS\s+)?`?(?P<name>\w+)`?\s*;?\s*$", re.I
)
_DROP_DB_RE = re.compile(
    r"^\s*DROP\s+DATABASE\s+(?P<ife>IF\s+EXISTS\s+)?`?(?P<name>\w+)`?\s*;?\s*$", re.I
)
_SHOW_DBS_RE = re.compile(r"^\s*SHOW\s+DATABASES\s*;?\s*$", re.I)
_SHOW_TABLES_RE = re.compile(r"^\s*SHOW\s+TABLES(?:\s+(?:IN|FROM)\s+`?(?P<db>\w+)`?)?\s*;?\s*$", re.I)
_SHOW_CREATE_RE = re.compile(r"^\s*SHOW\s+CREATE\s+TABLE\s+`?(?P<name>[\w.]+)`?\s*;?\s*$", re.I)
_DESCRIBE_RE = re.compile(r"^\s*(?:DESCRIBE|DESC)\s+`?(?P<name>[\w.$]+)`?\s*;?\s*$", re.I)
_ALTER_RE = re.compile(
    r"^\s*ALTER\s+TABLE\s+`?(?P<name>[\w.]+)`?\s+(?P<rest>.*?);?\s*$", re.I | re.S
)
_ANALYZE_RE = re.compile(
    r"^\s*ANALYZE\s+TABLE\s+`?(?P<name>[\w.]+)`?"
    r"\s+COMPUTE\s+STATISTICS(?P<cols>\s+FOR\s+ALL\s+COLUMNS)?\s*;?\s*$",
    re.I,
)


def _get_table(catalog: "Catalog", name: str):
    try:
        return catalog.get_table(name)
    except FileNotFoundError:
        raise DdlError(f"table {name} does not exist") from None


def _split_top(body: str) -> list[str]:
    """Split on top-level commas. Parens (DECIMAL(10,2)), angle brackets
    (ARRAY<INT>) and single-quoted literals ('a,b', COMMENT 'x(y') guard."""
    out, depth, buf = [], 0, []
    i, n = 0, len(body)
    while i < n:
        c = body[i]
        if c == "'":
            j = i + 1
            while j < n:
                if body[j] == "'":
                    if j + 1 < n and body[j + 1] == "'":
                        j += 2
                        continue
                    break
                j += 1
            if j >= n:
                raise DdlError(f"unterminated string literal in {body!r}")
            buf.append(body[i : j + 1])
            i = j + 1
            continue
        if c in "(<":
            depth += 1
        elif c in ")>":
            depth -= 1
        if c == "," and depth == 0:
            out.append("".join(buf).strip())
        else:
            buf.append(c)
        i += 1
        if c == "," and depth == 0:
            buf = []
    tail = "".join(buf).strip()
    if tail:
        out.append(tail)
    return out


def _parse_sql_type(text: str):
    """SQL type text -> DataType, including nested ARRAY<T> / MAP<K, V>."""
    from ..types import ArrayType, MapType

    t = text.strip()
    nullable = True
    if re.search(r"\s+NOT\s+NULL$", t, re.I):
        nullable = False
        t = re.sub(r"\s+NOT\s+NULL$", "", t, flags=re.I).strip()
    m = re.match(r"^ARRAY\s*<(?P<inner>.*)>$", t, re.I | re.S)
    if m:
        return ArrayType(_parse_sql_type(m.group("inner")), nullable)
    m = re.match(r"^MAP\s*<(?P<inner>.*)>$", t, re.I | re.S)
    if m:
        parts = _split_top(m.group("inner"))
        if len(parts) != 2:
            raise DdlError(f"MAP needs exactly key and value types: {text!r}")
        return MapType(_parse_sql_type(parts[0]), _parse_sql_type(parts[1]), nullable)
    try:
        return parse_type(re.sub(r"\s+", "", t).upper() + ("" if nullable else " NOT NULL"))
    except ValueError as e:
        raise DdlError(str(e)) from None


def _sql_type_text(dtype) -> str:
    """DataType -> DDL type text (inverse of _parse_sql_type)."""
    from ..types import ArrayType, MapType, TypeRoot

    if isinstance(dtype, ArrayType):
        base = f"ARRAY<{_sql_type_text(dtype.element)}>"
    elif isinstance(dtype, MapType):
        base = f"MAP<{_sql_type_text(dtype.key)}, {_sql_type_text(dtype.value)}>"
    elif dtype.root == TypeRoot.ROW:
        raise DdlError("ROW column types are not expressible in DDL text")
    else:
        s = dtype.serialize()
        return s  # scalar serialize() already carries NOT NULL
    return base if dtype.nullable else base + " NOT NULL"


def _parse_columns(body: str) -> tuple[list[DataField], list[str]]:
    fields: list[DataField] = []
    pks: list[str] = []
    for item in _split_top(body):
        pk = re.match(r"^PRIMARY\s+KEY\s*\(([^)]*)\)(?:\s+NOT\s+ENFORCED)?$", item, re.I)
        if pk:
            pks = [c.strip().strip("`") for c in pk.group(1).split(",") if c.strip()]
            continue
        m = re.match(
            r"^`?(?P<name>\w+)`?\s+(?P<type>[A-Za-z]+(?:\s*[(<].*[)>])?)"
            r"(?P<notnull>\s+NOT\s+NULL)?(?:\s+COMMENT\s+'(?P<comment>(?:[^']|'')*)')?$",
            item.strip(), re.I | re.S,
        )
        if not m:
            raise DdlError(f"cannot parse column definition {item!r}")
        type_text = m.group("type") + (" NOT NULL" if m.group("notnull") else "")
        dtype = _parse_sql_type(type_text)
        comment = m.group("comment").replace("''", "'") if m.group("comment") else None
        fields.append(DataField(len(fields), m.group("name"), dtype, description=comment))
    return fields, pks


def _parse_options(opts: str | None) -> dict[str, str]:
    if not opts:
        return {}
    out = {}
    for item in _split_top(opts):
        m = re.match(r"^'(?P<k>[^']+)'\s*=\s*'(?P<v>[^']*)'$", item.strip())
        if not m:
            raise DdlError(f"cannot parse WITH option {item!r} (expect 'key' = 'value')")
        out[m.group("k")] = m.group("v")
    return out


def _show_batch(name: str, rows: list[str]):
    from ..data.batch import ColumnBatch
    from ..types import STRING

    schema = RowType((DataField(0, name, STRING()),))
    return ColumnBatch.from_pydict(schema, {name: rows})


def ddl(catalog: "Catalog", statement: str) -> Any:
    """Execute one DDL statement. Returns a dict (create/drop), a ColumnBatch
    (SHOW/DESCRIBE), or a string (SHOW CREATE TABLE)."""
    m = _CREATE_TABLE_HEAD_RE.match(statement)
    if m:
        # balanced scan of the column list (types carry their own parens:
        # DECIMAL(10, 2); a single regex cannot pick the closing paren);
        # quoted literals (COMMENT 'a(b') never affect the depth
        depth, i = 1, m.end()
        while i < len(statement) and depth:
            c = statement[i]
            if c == "'":
                j = statement.find("'", i + 1)
                while j != -1 and statement[j : j + 2] == "''":
                    j = statement.find("'", j + 2)
                if j == -1:
                    raise DdlError(f"unterminated string literal in {statement!r}")
                i = j + 1
                continue
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
            i += 1
        if depth:
            raise DdlError(f"unbalanced parentheses in {statement!r}")
        body = statement[m.end() : i - 1]
        tail = _CREATE_TABLE_TAIL_RE.match(statement[i:])
        if not tail:
            raise DdlError(f"cannot parse CREATE TABLE tail: {statement[i:]!r}")
        fields, pks = _parse_columns(body)
        parts = [p.strip().strip("`") for p in (tail.group("parts") or "").split(",") if p.strip()]
        opts = _parse_options(tail.group("opts"))
        try:
            catalog.create_table(
                m.group("name"), RowType(tuple(fields)),
                primary_keys=pks, partition_keys=tuple(parts), options=opts,
                ignore_if_exists=bool(m.group("ine")),
            )
        except (FileExistsError, ValueError) as e:
            if "exists" in str(e):
                raise DdlError(f"table {m.group('name')} already exists") from None
            raise DdlError(str(e)) from e
        return {"created": m.group("name")}
    m = _DROP_TABLE_RE.match(statement)
    if m:
        try:
            exists = catalog.get_table(m.group("name")) is not None
        except FileNotFoundError:
            exists = False
        if not exists:
            if not m.group("ife"):
                raise DdlError(f"table {m.group('name')} does not exist")
            return {"dropped": None}
        catalog.drop_table(m.group("name"))
        return {"dropped": m.group("name")}
    m = _CREATE_DB_RE.match(statement)
    if m:
        catalog.create_database(m.group("name"), ignore_if_exists=bool(m.group("ine")))
        return {"created_database": m.group("name")}
    m = _DROP_DB_RE.match(statement)
    if m:
        # existence check up front: FileIO.delete is a no-op on missing paths,
        # so the catalog's drop never raises by itself
        if m.group("name") not in catalog.list_databases():
            if not m.group("ife"):
                raise DdlError(f"database {m.group('name')} does not exist")
            return {"dropped_database": None}
        catalog.drop_database(m.group("name"))
        return {"dropped_database": m.group("name")}
    if _SHOW_DBS_RE.match(statement):
        return _show_batch("database_name", sorted(catalog.list_databases()))
    m = _SHOW_TABLES_RE.match(statement)
    if m:
        dbs = [m.group("db")] if m.group("db") else sorted(catalog.list_databases())
        rows = [f"{db}.{t}" for db in dbs for t in sorted(catalog.list_tables(db))]
        return _show_batch("table_name", rows)
    m = _SHOW_CREATE_RE.match(statement)
    if m:
        t = _get_table(catalog, m.group("name"))
        cols = []
        for f in t.row_type.fields:
            comment = ""
            if getattr(f, "description", None):
                comment = f" COMMENT '{f.description.replace(chr(39), chr(39) * 2)}'"
            cols.append(f"  `{f.name}` {_sql_type_text(f.type)}{comment}")
        if t.primary_keys:
            cols.append(f"  PRIMARY KEY ({', '.join(t.primary_keys)}) NOT ENFORCED")
        out = f"CREATE TABLE {m.group('name')} (\n" + ",\n".join(cols) + "\n)"
        if t.partition_keys:
            out += f" PARTITIONED BY ({', '.join(t.partition_keys)})"
        opts = {k: v for k, v in t.options.options.to_map().items() if k != "path"}
        if opts:
            out += " WITH (" + ", ".join(f"'{k}' = '{v}'" for k, v in sorted(opts.items())) + ")"
        return out
    m = _DESCRIBE_RE.match(statement)
    if m:
        t = _get_table(catalog, m.group("name"))
        from ..data.batch import ColumnBatch
        from ..types import STRING

        # system tables (_StaticTable) have a row_type but no key metadata
        pks = getattr(t, "primary_keys", None) or ()
        parts = getattr(t, "partition_keys", None) or ()
        schema = RowType((
            DataField(0, "name", STRING()), DataField(1, "type", STRING()),
            DataField(2, "key", STRING()),
        ))
        return ColumnBatch.from_pydict(schema, {
            "name": [f.name for f in t.row_type.fields],
            "type": [str(f.type) for f in t.row_type.fields],
            "key": ["PRI" if f.name in pks else ("PART" if f.name in parts else "")
                    for f in t.row_type.fields],
        })
    m = _ALTER_RE.match(statement)
    if m:
        return _alter(catalog, m.group("name"), m.group("rest"))
    m = _ANALYZE_RE.match(statement)
    if m:
        # Spark's ANALYZE TABLE ... COMPUTE STATISTICS [FOR ALL COLUMNS]
        # (reference PaimonAnalyzeTableColumnCommand.scala)
        from ..table.statistics import analyze_table

        t = _get_table(catalog, m.group("name"))
        stats = analyze_table(t, with_columns=bool(m.group("cols")))
        return {"analyzed": m.group("name"), "rows": stats.merged_record_count,
                "columns": sorted(stats.col_stats) if stats.col_stats else []}
    raise DdlError(f"unrecognized DDL statement: {statement!r}")


def _alter(catalog: "Catalog", name: str, rest: str) -> dict:
    """ALTER TABLE t ADD COLUMN c TYPE | DROP COLUMN c | RENAME COLUMN a TO b
    | MODIFY c TYPE | SET ('k' = 'v', ...) | RESET ('k', ...) — lowered onto
    SchemaChange (reference SchemaChange.java ops)."""
    from ..core.schema import SchemaChange

    changes = []
    add = re.match(
        r"^ADD\s+COLUMN\s+`?(\w+)`?\s+([A-Za-z]+(?:\s*\([\d\s,]*\))?)(\s+NOT\s+NULL)?$",
        rest.strip(), re.I,
    )
    drop = re.match(r"^DROP\s+COLUMN\s+`?(\w+)`?$", rest.strip(), re.I)
    ren = re.match(r"^RENAME\s+COLUMN\s+`?(\w+)`?\s+TO\s+`?(\w+)`?$", rest.strip(), re.I)
    mod = re.match(
        r"^MODIFY\s+(?:COLUMN\s+)?`?(\w+)`?\s+([A-Za-z]+(?:\s*\([\d\s,]*\))?)$",
        rest.strip(), re.I,
    )
    set_m = re.match(r"^SET\s*\((?P<opts>.*)\)$", rest.strip(), re.I | re.S)
    reset_m = re.match(r"^RESET\s*\((?P<keys>.*)\)$", rest.strip(), re.I | re.S)
    if add:
        type_text = re.sub(r"\s+", "", add.group(2)).upper() + (" NOT NULL" if add.group(3) else "")
        try:
            changes.append(SchemaChange.add_column(add.group(1), parse_type(type_text)))
        except ValueError as e:
            raise DdlError(str(e)) from None
    elif drop:
        changes.append(SchemaChange.drop_column(drop.group(1)))
    elif ren:
        changes.append(SchemaChange.rename_column(ren.group(1), ren.group(2)))
    elif mod:
        try:
            changes.append(SchemaChange.update_column_type(
                mod.group(1), parse_type(re.sub(r"\s+", "", mod.group(2)).upper())
            ))
        except ValueError as e:
            raise DdlError(str(e)) from None
    elif set_m:
        for k, v in _parse_options(set_m.group("opts")).items():
            changes.append(SchemaChange.set_option(k, v))
    elif reset_m:
        for item in _split_top(reset_m.group("keys")):
            km = re.match(r"^'([^']+)'$", item.strip())
            if not km:
                raise DdlError(f"RESET expects quoted option keys, got {item!r}")
            changes.append(SchemaChange.remove_option(km.group(1)))
    else:
        raise DdlError(f"unsupported ALTER TABLE clause: {rest!r}")
    try:
        schema = catalog.alter_table(name, *changes)
    except (ValueError, KeyError) as e:
        raise DdlError(str(e)) from e
    return {"altered": name, "schema_id": schema.id}
