"""SQL boolean/value expression parser shared by the string procedures.

The reference's procedures take SQL expression strings — ``delete`` a WHERE
clause (DeleteAction), ``merge_into`` merge/matched/not-matched conditions and
SET lists (MergeIntoProcedure.java:96) — and hand them to the engine's
planner. This module is the engine-neutral analog: a small recursive-descent
parser over the comparison/boolean grammar those procedures actually use,
with two lowerings:

- :func:`to_predicate` — single-table mode: the AST lowers onto the
  :mod:`paimon_tpu.data.predicate` algebra (stats-prunable, pushdown-capable),
  so ``delete`` / ``SELECT`` strings drive the same file-skipping as
  programmatic predicates.
- :func:`eval_mask` / :func:`eval_value` — two-table mode for MERGE INTO:
  column refs may be qualified with the source/target aliases and evaluate
  against aligned ColumnBatches (the engine-neutral rowops contract).

Grammar (case-insensitive keywords)::

    expr    := or ;  or := and (OR and)* ;  and := not (AND not)*
    not     := NOT not | primary
    primary := '(' expr ')' | TRUE | FALSE | comparison
    cmp     := operand (('='|'<>'|'!='|'<'|'<='|'>'|'>=') operand
               | IS [NOT] NULL | [NOT] IN '(' lit (',' lit)* ')'
               | [NOT] LIKE string | BETWEEN operand AND operand)
    operand := term (('+'|'-') term)* ; term := factor (('*'|'/'|'%') factor)*
    factor  := '-' factor | literal | ref | '(' operand ')'
    ref     := [`]?alias[`]? '.' [`]?name[`]? | [`]?name[`]?
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import numpy as np

__all__ = [
    "ExprError",
    "parse_expr",
    "parse_assignments",
    "to_predicate",
    "eval_mask",
    "eval_value",
]


class ExprError(ValueError):
    pass


# --------------------------------------------------------------------------
# tokenizer
# --------------------------------------------------------------------------

_KEYWORDS = {"and", "or", "not", "in", "is", "null", "like", "between", "true", "false"}
_OPS = ("<>", "!=", "<=", ">=", "=", "<", ">", "(", ")", ",", "+", "-", "*", "/", "%", ".")


def _tokenize(s: str) -> list[tuple[str, Any]]:
    """-> [(kind, value)]: kind in {'num','str','name','kw','op'}."""
    toks: list[tuple[str, Any]] = []
    i, n = 0, len(s)
    while i < n:
        c = s[i]
        if c.isspace():
            i += 1
            continue
        if c == "'":
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise ExprError(f"unterminated string literal at offset {i}: {s!r}")
                if s[j] == "'":
                    if j + 1 < n and s[j + 1] == "'":  # '' escape
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(s[j])
                j += 1
            toks.append(("str", "".join(buf)))
            i = j + 1
            continue
        if c == "`":
            j = s.find("`", i + 1)
            if j < 0:
                raise ExprError(f"unterminated backquote at offset {i}: {s!r}")
            toks.append(("name", s[i + 1 : j]))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and s[i + 1].isdigit()):
            j = i
            while j < n and (s[j].isdigit() or s[j] in ".eE" or (s[j] in "+-" and s[j - 1] in "eE")):
                j += 1
            text = s[i:j]
            try:
                toks.append(("num", int(text)))
            except ValueError:
                try:
                    toks.append(("num", float(text)))
                except ValueError:
                    raise ExprError(f"bad number {text!r}") from None
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (s[j].isalnum() or s[j] == "_"):
                j += 1
            word = s[i:j]
            toks.append(("kw", word.lower()) if word.lower() in _KEYWORDS else ("name", word))
            i = j
            continue
        for op in _OPS:
            if s.startswith(op, i):
                toks.append(("op", op))
                i += len(op)
                break
        else:
            raise ExprError(f"unexpected character {c!r} at offset {i} in {s!r}")
    return toks


# --------------------------------------------------------------------------
# parser -> AST tuples
#   ('lit', v) ('col', alias|None, name) ('neg', x) ('arith', op, l, r)
#   ('cmp', op, l, r) ('and', [..]) ('or', [..]) ('not', x)
#   ('isnull', operand, negated) ('in', operand, [vals], negated)
#   ('like', operand, pattern, negated) ('between', operand, lo, hi)
# --------------------------------------------------------------------------


class _Parser:
    def __init__(self, toks: list[tuple[str, Any]], src: str):
        self.toks = toks
        self.src = src
        self.i = 0

    def peek(self, k: int = 0):
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else ("eof", None)

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def expect(self, kind: str, value=None):
        t = self.next()
        if t[0] != kind or (value is not None and t[1] != value):
            raise ExprError(f"expected {value or kind} at token {self.i - 1} in {self.src!r}, got {t}")
        return t

    # boolean levels ------------------------------------------------------
    def parse_expr(self):
        node = self.parse_and()
        parts = [node]
        while self.peek() == ("kw", "or"):
            self.next()
            parts.append(self.parse_and())
        return parts[0] if len(parts) == 1 else ("or", parts)

    def parse_and(self):
        parts = [self.parse_not()]
        while self.peek() == ("kw", "and"):
            self.next()
            parts.append(self.parse_not())
        return parts[0] if len(parts) == 1 else ("and", parts)

    def parse_not(self):
        if self.peek() == ("kw", "not"):
            self.next()
            return ("not", self.parse_not())
        return self.parse_primary()

    def parse_primary(self):
        t = self.peek()
        if t == ("kw", "true"):
            self.next()
            return ("lit", True)
        if t == ("kw", "false"):
            self.next()
            return ("lit", False)
        if t == ("op", "("):
            # boolean group or parenthesized operand: backtrack on failure
            mark = self.i
            self.next()
            try:
                inner = self.parse_expr()
                self.expect("op", ")")
                if self._at_cmp_op():
                    raise ExprError("operand paren")  # '(a+b) > c': redo as operand
                return inner
            except ExprError:
                self.i = mark
        return self.parse_comparison()

    def _at_cmp_op(self) -> bool:
        t = self.peek()
        return (t[0] == "op" and t[1] in ("=", "<>", "!=", "<", "<=", ">", ">=")) or (
            t[0] == "kw" and t[1] in ("is", "in", "like", "between", "not")
        )

    def parse_comparison(self):
        left = self.parse_operand()
        t = self.peek()
        if t[0] == "op" and t[1] in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            return ("cmp", t[1], left, self.parse_operand())
        if t == ("kw", "is"):
            self.next()
            negated = False
            if self.peek() == ("kw", "not"):
                self.next()
                negated = True
            self.expect("kw", "null")
            return ("isnull", left, negated)
        negated = False
        if t == ("kw", "not"):
            self.next()
            negated = True
            t = self.peek()
        if t == ("kw", "in"):
            self.next()
            self.expect("op", "(")
            vals = [self._literal_value()]
            while self.peek() == ("op", ","):
                self.next()
                vals.append(self._literal_value())
            self.expect("op", ")")
            return ("in", left, vals, negated)
        if t == ("kw", "like"):
            self.next()
            pat = self.next()
            if pat[0] != "str":
                raise ExprError(f"LIKE needs a string pattern in {self.src!r}")
            return ("like", left, pat[1], negated)
        if t == ("kw", "between"):
            self.next()
            lo = self.parse_operand()
            self.expect("kw", "and")
            node = ("between", left, lo, self.parse_operand())
            return ("not", node) if negated else node
        if negated:
            raise ExprError(f"dangling NOT in {self.src!r}")
        # bare operand as boolean (e.g. a boolean column)
        return left

    def _literal_value(self):
        node = self.parse_operand()
        v = _const_fold(node)
        if v is _NOT_CONST:
            raise ExprError(f"IN list elements must be literals in {self.src!r}")
        return v

    # arithmetic levels ---------------------------------------------------
    def parse_operand(self):
        node = self.parse_term()
        while self.peek()[0] == "op" and self.peek()[1] in ("+", "-"):
            op = self.next()[1]
            node = ("arith", op, node, self.parse_term())
        return node

    def parse_term(self):
        node = self.parse_factor()
        while self.peek()[0] == "op" and self.peek()[1] in ("*", "/", "%"):
            op = self.next()[1]
            node = ("arith", op, node, self.parse_factor())
        return node

    def parse_factor(self):
        t = self.peek()
        if t == ("op", "-"):
            self.next()
            return ("neg", self.parse_factor())
        if t == ("op", "("):
            self.next()
            node = self.parse_operand()
            self.expect("op", ")")
            return node
        if t[0] == "num" or t[0] == "str":
            self.next()
            return ("lit", t[1])
        if t == ("kw", "null"):
            self.next()
            return ("lit", None)
        if t == ("kw", "true"):
            self.next()
            return ("lit", True)
        if t == ("kw", "false"):
            self.next()
            return ("lit", False)
        if t[0] == "name":
            self.next()
            if self.peek() == ("op", "."):
                self.next()
                name = self.expect("name")[1]
                return ("col", t[1], name)
            return ("col", None, t[1])
        raise ExprError(f"unexpected token {t} in {self.src!r}")


_NOT_CONST = object()


def _const_fold(node):
    kind = node[0]
    if kind == "lit":
        return node[1]
    if kind == "neg":
        v = _const_fold(node[1])
        return _NOT_CONST if v is _NOT_CONST else -v
    if kind == "arith":
        left, right = _const_fold(node[2]), _const_fold(node[3])
        if left is _NOT_CONST or right is _NOT_CONST:
            return _NOT_CONST
        return _APPLY[node[1]](left, right)
    return _NOT_CONST


_APPLY = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "%": lambda a, b: a % b,
}


def parse_expr(s: str):
    """WHERE-clause string -> AST."""
    p = _Parser(_tokenize(s), s)
    node = p.parse_expr()
    if p.peek()[0] != "eof":
        raise ExprError(f"trailing tokens after expression in {s!r}")
    return node


def parse_assignments(s: str) -> list[tuple[str, Any]]:
    """SET-list string 'a = expr, b = expr' -> [(col, value_ast)].
    The special string '*' returns [('*', None)] (take all source columns)."""
    if s.strip() == "*":
        return [("*", None)]
    p = _Parser(_tokenize(s), s)
    out: list[tuple[str, Any]] = []
    while True:
        tgt = p.expect("name")[1]
        if p.peek() == ("op", "."):  # optional target alias prefix
            p.next()
            tgt = p.expect("name")[1]
        p.expect("op", "=")
        out.append((tgt, p.parse_operand()))
        if p.peek() == ("op", ","):
            p.next()
            continue
        if p.peek()[0] == "eof":
            return out
        raise ExprError(f"trailing tokens in assignment list {s!r}")


# --------------------------------------------------------------------------
# lowering 1: single-table AST -> Predicate (pushdown-capable)
# --------------------------------------------------------------------------


def _col_name(node, src: str) -> str:
    if node[0] != "col":
        raise ExprError(f"expected a column reference in {src!r}")
    return node[2]


def to_predicate(node, src: str = ""):
    """AST -> data.predicate.Predicate. Comparisons must be `col op literal`
    (either side); arithmetic is allowed only among literals (folded)."""
    from ..data import predicate as P

    kind = node[0]
    if kind == "and":
        return P.and_(*[to_predicate(x, src) for x in node[1]])
    if kind == "or":
        return P.or_(*[to_predicate(x, src) for x in node[1]])
    if kind == "not":
        inner = node[1]
        if inner[0] == "cmp":
            flip = {"=": "<>", "<>": "=", "!=": "=", "<": ">=", "<=": ">", ">": "<=", ">=": "<"}
            return to_predicate(("cmp", flip[inner[1]], inner[2], inner[3]), src)
        if inner[0] == "isnull":
            return to_predicate(("isnull", inner[1], not inner[2]), src)
        if inner[0] == "in":
            return to_predicate(("in", inner[1], inner[2], not inner[3]), src)
        if inner[0] == "like":
            return to_predicate(("like", inner[1], inner[2], not inner[3]), src)
        if inner[0] == "not":  # double negation
            return to_predicate(inner[1], src)
        if inner[0] == "and":  # De Morgan
            return to_predicate(("or", [("not", x) for x in inner[1]]), src)
        if inner[0] == "or":
            return to_predicate(("and", [("not", x) for x in inner[1]]), src)
        if inner[0] == "between":
            # NOT (x BETWEEN lo AND hi) = x < lo OR x > hi; reuses the cmp
            # lowering (and its bounds validation)
            return to_predicate(
                ("or", [("cmp", "<", inner[1], inner[2]), ("cmp", ">", inner[1], inner[3])]),
                src,
            )
        raise ExprError(f"NOT over this construct is not supported in {src!r}")
    if kind == "cmp":
        op, left, right = node[1], node[2], node[3]
        lv, rv = _const_fold(left), _const_fold(right)
        if lv is _NOT_CONST and rv is not _NOT_CONST:
            col, lit = _col_name(left, src), rv
        elif rv is _NOT_CONST and lv is not _NOT_CONST:
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "<>": "<>", "!=": "!="}
            col, lit, op = _col_name(right, src), lv, flip[op]
        else:
            raise ExprError(f"comparison must be column vs literal in {src!r}")
        fns = {"=": P.equal, "<>": P.not_equal, "!=": P.not_equal, "<": P.less_than,
               "<=": P.less_or_equal, ">": P.greater_than, ">=": P.greater_or_equal}
        return fns[op](col, lit)
    if kind == "isnull":
        col = _col_name(node[1], src)
        return P.is_not_null(col) if node[2] else P.is_null(col)
    if kind == "in":
        col = _col_name(node[1], src)
        return P.not_in(col, node[2]) if node[3] else P.in_(col, node[2])
    if kind == "like":
        col, pat, negated = _col_name(node[1], src), node[2], node[3]
        body = pat.strip("%")
        if "%" in body or "_" in pat:
            raise ExprError(f"only prefix/suffix/contains LIKE patterns are supported: {pat!r}")
        if pat.startswith("%") and pat.endswith("%"):
            pred = P.contains(col, body)
        elif pat.endswith("%"):
            pred = P.starts_with(col, body)
        elif pat.startswith("%"):
            pred = P.ends_with(col, body)
        else:
            pred = P.equal(col, pat)
        if negated:
            pred = pred.negate()
            if pred is None:
                raise ExprError(f"NOT LIKE cannot be expressed for {pat!r}")
        return pred
    if kind == "between":
        col = _col_name(node[1], src)
        lo, hi = _const_fold(node[2]), _const_fold(node[3])
        if lo is _NOT_CONST or hi is _NOT_CONST:
            raise ExprError(f"BETWEEN bounds must be literals in {src!r}")
        return P.between(col, lo, hi)
    if kind == "lit":
        if node[1] is True:
            return None  # TRUE -> no filter (caller treats None as match-all)
        raise ExprError(f"constant {node[1]!r} is not a usable filter in {src!r}")
    raise ExprError(f"cannot lower {kind!r} to a predicate in {src!r}")


def parse_where(s: str):
    """WHERE string -> Predicate (None for 'TRUE')."""
    return to_predicate(parse_expr(s), s)


# --------------------------------------------------------------------------
# lowering 2: two-table evaluation for MERGE INTO
# --------------------------------------------------------------------------

Resolver = Callable[[Any, str], tuple[np.ndarray, np.ndarray | None]]
"""(alias, column) -> (values, validity|None); alias None = unqualified."""


def _eval_vv(node, resolve: Resolver, n: int):
    """Value AST -> (values, valid) where valid=None means all rows known.
    Unknown rows carry garbage values (columns store sentinel fills); the
    boolean layer masks them via Kleene `known` tracking."""
    kind = node[0]
    if kind == "lit":
        if node[1] is None:
            return np.zeros(n), np.zeros(n, dtype=bool)
        return np.full(n, node[1]), None
    if kind == "col":
        return resolve(node[1], node[2])
    if kind == "neg":
        v, k = _eval_vv(node[1], resolve, n)
        return -v, k
    if kind == "arith":
        lv, lk = _eval_vv(node[2], resolve, n)
        rv, rk = _eval_vv(node[3], resolve, n)
        return _APPLY[node[1]](lv, rv), _and_valid(lk, rk)
    raise ExprError(f"cannot evaluate {kind!r} as a value")


def _and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


_CMP = {"=": lambda a, b: a == b, "<>": lambda a, b: a != b, "!=": lambda a, b: a != b,
        "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
        ">": lambda a, b: a > b, ">=": lambda a, b: a >= b}


def eval_value(node, resolve: Resolver, n: int):
    """Value AST -> ndarray of length n (literals broadcast). Rows whose
    value is unknown (NULL operands anywhere in the expression) come back as
    None — SET v = NULL writes NULL, not the storage sentinel."""
    v, k = _eval_vv(node, resolve, n)
    if k is None or k.all():
        return v
    out = np.asarray(v, dtype=object).copy()
    out[~k] = None
    return out


def _eval_tk(node, resolve: Resolver, n: int):
    """Boolean AST -> (truth, known) under SQL/Kleene three-valued logic;
    known=None means every row is known."""
    kind = node[0]
    if kind == "lit":
        if isinstance(node[1], bool):
            return np.full(n, node[1], dtype=bool), None
        raise ExprError(f"constant {node[1]!r} is not a boolean")
    if kind in ("and", "or"):
        t, k = _eval_tk(node[1][0], resolve, n)
        for x in node[1][1:]:
            t2, k2 = _eval_tk(x, resolve, n)
            if kind == "and":
                # known iff both known, or either is known-False
                nk = None if (k is None and k2 is None) else (
                    _bool(k, n) & _bool(k2, n)
                    | (_bool(k, n) & ~t)
                    | (_bool(k2, n) & ~t2)
                )
                t = t & t2
            else:
                nk = None if (k is None and k2 is None) else (
                    _bool(k, n) & _bool(k2, n)
                    | (_bool(k, n) & t)
                    | (_bool(k2, n) & t2)
                )
                t = t | t2
            k = nk
        return t, k
    if kind == "not":
        t, k = _eval_tk(node[1], resolve, n)
        return ~t, k
    if kind == "cmp":
        lv, lk = _eval_vv(node[2], resolve, n)
        rv, rk = _eval_vv(node[3], resolve, n)
        return np.asarray(_CMP[node[1]](lv, rv), dtype=bool), _and_valid(lk, rk)
    if kind == "isnull":
        # IS NULL is always KNOWN, and applies to any operand: unknownness of
        # the operand expression IS the nullness being tested
        _, lk = _eval_vv(node[1], resolve, n)
        null = ~_bool(lk, n)
        return (~null if node[2] else null), None
    if kind == "in":
        lv, lk = _eval_vv(node[1], resolve, n)
        mask = np.isin(lv, np.asarray(node[2]))
        return (~mask if node[3] else mask), lk
    if kind == "between":
        lv, lk = _eval_vv(node[1], resolve, n)
        lov, lok = _eval_vv(node[2], resolve, n)
        hiv, hik = _eval_vv(node[3], resolve, n)
        return (lv >= lov) & (lv <= hiv), _and_valid(lk, _and_valid(lok, hik))
    if kind == "like":
        lv, lk = _eval_vv(node[1], resolve, n)
        pat, negated = node[2], node[3]
        body = pat.strip("%")
        s = np.asarray(lv, dtype=object)
        if pat.startswith("%") and pat.endswith("%"):
            mask = np.array([body in (x or "") for x in s], dtype=bool)
        elif pat.endswith("%"):
            mask = np.array([(x or "").startswith(body) for x in s], dtype=bool)
        elif pat.startswith("%"):
            mask = np.array([(x or "").endswith(body) for x in s], dtype=bool)
        else:
            mask = np.asarray(s == pat, dtype=bool)
        return (~mask if negated else mask), lk
    raise ExprError(f"cannot evaluate {kind!r} as a mask")


def _bool(k, n):
    return np.ones(n, dtype=bool) if k is None else k


def eval_mask(node, resolve: Resolver, n: int) -> np.ndarray:
    """Boolean AST -> bool ndarray of length n. SQL WHERE semantics: a row
    passes only when the expression is known TRUE (UNKNOWN filters out) —
    Kleene logic carried through NOT/AND/OR, same as the predicate path."""
    t, k = _eval_tk(node, resolve, n)
    return t if k is None else (t & k)


def batch_resolver(aliases: Mapping[str, Any]) -> Resolver:
    """Resolver over named ColumnBatches: aliases maps alias -> ColumnBatch.
    Unqualified refs try each batch in insertion order (first hit wins)."""

    def resolve(alias, name):
        if alias is not None:
            b = aliases.get(alias)
            if b is None:
                raise ExprError(f"unknown table alias {alias!r} (have {sorted(aliases)})")
            c = b.column(name)
            return np.asarray(c.values), c.validity
        for b in aliases.values():
            if name in b.schema:
                c = b.column(name)
                return np.asarray(c.values), c.validity
        raise ExprError(f"unknown column {name!r}")

    return resolve
