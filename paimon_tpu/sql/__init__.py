"""SQL CALL procedures: the string entry surface (VERDICT r3 missing #2).

Every reference interaction path is SQL — Flink registers its actions as
``CALL sys.<proc>(...)`` procedures
(/root/reference/paimon-flink/paimon-flink-common/src/main/java/org/apache/
paimon/flink/procedure/ProcedureUtil.java lists them; ProcedureBase.java
binds each to the catalog), and Spark mirrors the same set. This module is
the engine-neutral analog: :func:`call` parses one ``CALL`` statement
(positional args, Flink's ``name => value`` named args, SQL literals) and
dispatches onto the SAME Table-API code paths the CLI actions use — so a
runbook written against the reference's procedures ports by string edit,
not rewrite.

    >>> from paimon_tpu.sql import call
    >>> call(catalog, "CALL sys.create_tag('db.t', 'v1')")
    >>> call(catalog, "CALL sys.compact(`table` => 'db.t', `full` => true)")

Procedures operate through a live Catalog exactly like the reference's
(ProcedureBase.catalog); results come back as plain dicts (the reference
returns string rows — dicts carry the same fields, typed).
"""

from __future__ import annotations

import re
from typing import TYPE_CHECKING, Any, Callable

if TYPE_CHECKING:
    from ..catalog import Catalog

__all__ = ["call", "parse_call", "procedures", "query", "cluster_query",
           "execute", "execute_script", "split_statements"]

_CALL_RE = re.compile(r"^\s*CALL\s+(?:`?sys`?\.)?`?(\w+)`?\s*\((.*)\)\s*;?\s*$", re.I | re.S)


class ProcedureError(ValueError):
    pass


def _tokenize_args(body: str) -> list[str]:
    """Split the argument body on top-level commas, honoring single-quoted
    SQL strings (with '' escaping) and backquoted identifiers."""
    parts: list[str] = []
    buf: list[str] = []
    i, n = 0, len(body)
    while i < n:
        c = body[i]
        if c == "'":
            buf.append(c)
            i += 1
            closed = False
            while i < n:
                buf.append(body[i])
                if body[i] == "'":
                    if i + 1 < n and body[i + 1] == "'":  # '' escape
                        buf.append("'")
                        i += 2
                        continue
                    i += 1
                    closed = True
                    break
                i += 1
            if not closed:
                raise ProcedureError(f"unterminated string literal in arguments: {body!r}")
            continue
        if c == "`":
            j = body.find("`", i + 1)
            if j < 0:
                raise ProcedureError(f"unterminated backquote in arguments: {body!r}")
            buf.append(body[i : j + 1])
            i = j + 1
            continue
        if c == ",":
            parts.append("".join(buf).strip())
            buf = []
            i += 1
            continue
        buf.append(c)
        i += 1
    tail = "".join(buf).strip()
    if tail:
        parts.append(tail)
    return parts


def _literal(tok: str) -> Any:
    """One SQL literal -> python value."""
    t = tok.strip()
    if t.startswith("'") and t.endswith("'"):
        return t[1:-1].replace("''", "'")
    low = t.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    if low == "null":
        return None
    try:
        return int(t)
    except ValueError:
        pass
    try:
        return float(t)
    except ValueError:
        raise ProcedureError(f"unsupported literal: {tok!r}") from None


def parse_call(statement: str) -> tuple[str, list[Any], dict[str, Any]]:
    """'CALL sys.proc(a, k => v)' -> (proc, [a], {k: v})."""
    m = _CALL_RE.match(statement)
    if not m:
        raise ProcedureError(f"not a CALL statement: {statement!r}")
    name = m.group(1).lower()
    args: list[Any] = []
    kwargs: dict[str, Any] = {}
    for tok in _tokenize_args(m.group(2)):
        nm = re.match(r"^`?(\w+)`?\s*=>\s*(.+)$", tok, re.S)
        if nm:
            kwargs[nm.group(1).lower()] = _literal(nm.group(2))
        else:
            if kwargs:
                raise ProcedureError("positional argument after named argument")
            args.append(_literal(tok))
    return name, args, kwargs


# --------------------------------------------------------------------------
# procedure implementations (reference paimon-flink-common/.../procedure/*)
# --------------------------------------------------------------------------

def _t(cat: "Catalog", ident: str):
    return cat.get_table(ident)


def _proc_compact(cat, table: str, partitions: str | None = None,
                  order_strategy: str | None = None, order_by: str | None = None,
                  full: bool = False):
    """CompactProcedure.java: plain compaction, or clustered when an order
    strategy is given (zorder/hilbert/order)."""
    t = _t(cat, table)
    if order_strategy:
        from ..table.sort_compact import sort_compact

        cols = [c.strip() for c in (order_by or "").split(",") if c.strip()]
        if not cols:
            raise ProcedureError("order_by is required with order_strategy")
        n = sort_compact(t, cols, order=order_strategy)
        return {"rows_clustered": n, "strategy": order_strategy}
    from ..table.compactor import DedicatedCompactor

    return {"compacted": DedicatedCompactor(t).run_once(full=full), "full": full}


def _proc_compact_database(cat, including_databases: str | None = None,
                           mode: str | None = None,
                           including_tables: str | None = None,
                           excluding_tables: str | None = None,
                           full: bool = False):
    from ..table.compactor import DedicatedCompactor

    db_pat = re.compile(including_databases or ".*")
    inc = re.compile(including_tables or ".*")
    exc = re.compile(excluding_tables) if excluding_tables else None
    compacted, skipped = [], []
    for db in cat.list_databases():
        if not db_pat.fullmatch(db):
            continue
        for name in cat.list_tables(db):
            ident = f"{db}.{name}"
            if not (inc.fullmatch(ident) or inc.fullmatch(name)):
                continue
            if exc and (exc.fullmatch(ident) or exc.fullmatch(name)):
                continue
            t = cat.get_table(ident)
            try:
                # pk tables and append (unaware-bucket) tables both compact —
                # reference CompactDatabaseAction covers both kinds
                if DedicatedCompactor(t).run_once(full=full):
                    compacted.append(ident)
            except (ValueError, NotImplementedError) as e:
                skipped.append({"table": ident, "reason": str(e)})
    return {"compacted": compacted, "skipped": skipped}


def _proc_create_tag(cat, table: str, tag: str, snapshot_id: int | None = None):
    _t(cat, table).create_tag(tag, snapshot_id=snapshot_id)
    return {"tag": tag}


def _proc_delete_tag(cat, table: str, tag: str):
    _t(cat, table).delete_tag(tag)
    return {"deleted_tag": tag}


def _proc_rollback_to(cat, table: str, snapshot_or_tag):
    target = snapshot_or_tag
    if isinstance(target, str) and target.isdigit():
        target = int(target)
    _t(cat, table).rollback_to(target)
    return {"rolled_back_to": target}


def _proc_create_branch(cat, table: str, branch: str, tag: str | None = None):
    from ..table.branch import BranchManager

    t = _t(cat, table)
    BranchManager(t.file_io, t.path).create(branch, from_tag=tag)
    return {"branch": branch}


def _proc_delete_branch(cat, table: str, branch: str):
    from ..table.branch import BranchManager

    t = _t(cat, table)
    BranchManager(t.file_io, t.path).delete(branch)
    return {"deleted_branch": branch}


def _proc_fast_forward(cat, table: str, branch: str):
    from ..table.branch import BranchManager

    t = _t(cat, table)
    BranchManager(t.file_io, t.path).fast_forward(branch)
    return {"fast_forwarded": branch}


def _proc_expire_snapshots(cat, table: str, retain_max: int | None = None,
                           retain_min: int | None = None,
                           older_than: str | None = None,
                           max_deletes: int | None = None):
    t = _t(cat, table)
    overrides = {}
    if retain_max is not None:
        overrides["snapshot.num-retained.max"] = str(retain_max)
    if retain_min is not None:
        overrides["snapshot.num-retained.min"] = str(retain_min)
    if max_deletes is not None:
        overrides["snapshot.expire.limit"] = str(max_deletes)
    if overrides:
        t = t.copy(overrides)
    return {"expired": t.expire_snapshots()}


def _proc_expire_partitions(cat, table: str, expiration_time: str,
                            timestamp_formatter: str = "%Y-%m-%d",
                            timestamp_pattern: str | None = None):
    from ..options import parse_duration_millis
    from ..table.maintenance import expire_partitions

    t = _t(cat, table)
    expired = expire_partitions(
        t,
        parse_duration_millis(expiration_time),
        time_col=timestamp_pattern,
        pattern=timestamp_formatter,
    )
    return {"expired_partitions": [list(p) for p in expired]}


def _parse_partition_specs(partitions: str) -> list[dict]:
    """Reference partition-string syntax: 'k1=v1,k2=v2;k1=v3' (';' separates
    multiple specs)."""
    specs = []
    for spec in partitions.split(";"):
        if spec.strip():
            specs.append(dict(kv.strip().split("=", 1) for kv in spec.split(",")))
    return specs


def _proc_drop_partition(cat, table: str, partitions: str):
    from ..table.maintenance import drop_partition

    dropped = drop_partition(_t(cat, table), *_parse_partition_specs(partitions))
    return {"dropped_partitions": [list(p) for p in dropped]}


def _proc_mark_partition_done(cat, table: str, partitions: str):
    from ..table.maintenance import mark_partition_done

    paths = mark_partition_done(_t(cat, table), _parse_partition_specs(partitions))
    return {"markers": paths}


def _proc_remove_orphan_files(cat, table: str, older_than_hours: float = 24.0,
                              dry_run: bool = False):
    from ..table.maintenance import remove_orphan_files

    removed = remove_orphan_files(
        _t(cat, table),
        older_than_millis=int(float(older_than_hours) * 3600_000),
        dry_run=dry_run,
    )
    return {"orphans": removed, "dry_run": dry_run}


def _proc_reset_consumer(cat, table: str, consumer_id: str,
                         next_snapshot_id: int | None = None):
    from ..table.consumer import ConsumerManager

    t = _t(cat, table)
    cm = ConsumerManager(t.file_io, t.path)
    if next_snapshot_id is None:
        cm.delete(consumer_id)
        return {"deleted_consumer": consumer_id}
    cm.reset(consumer_id, next_snapshot_id)
    return {"consumer": consumer_id, "next_snapshot": next_snapshot_id}


def _parse_where(where: str):
    """WHERE argument -> Predicate|None. SQL expression strings are the
    reference contract (DeleteAction takes a SQL filter); the legacy JSON
    blob the CLI accepted stays supported for back-compat."""
    where = where.strip()
    if where.startswith("{"):
        import json as _json

        from ..data import predicate as P

        d = _json.loads(where)
        op = d.get("op", "=")
        fns = {"=": P.equal, "!=": P.not_equal, ">": P.greater_than,
               ">=": P.greater_or_equal, "<": P.less_than, "<=": P.less_or_equal}
        if op == "in":
            return P.in_(d["field"], d["value"])
        if op == "is_null":
            return P.is_null(d["field"])
        return fns[op](d["field"], d["value"])
    from .expr import ExprError, parse_where

    try:
        return parse_where(where)
    except ExprError as e:
        raise ProcedureError(str(e)) from e


def _proc_delete(cat, table: str, where: str):
    """DeleteAction analog; `where` is a SQL expression ("dt = '2024-01-01'
    AND hh >= 10"), matching the reference's delete procedure contract."""
    pred = _parse_where(where)
    if pred is None:
        raise ProcedureError("refusing unconditional DELETE; pass an explicit WHERE")
    return {"rows_deleted": _t(cat, table).delete_where(pred)}


def _proc_merge_into(cat, target_table: str, target_alias: str = "",
                     source_sqls: str = "", source_table: str = "",
                     merge_condition: str = "",
                     matched_upsert_condition: str = "",
                     matched_upsert_setting: str = "",
                     not_matched_insert_condition: str = "",
                     not_matched_insert_values: str = "",
                     matched_delete_condition: str = ""):
    """MergeIntoProcedure.java:96 — string surface onto table.rowops.MergeInto.
    '' is the placeholder for unused arguments (reference convention). The
    short delete form `CALL sys.merge_into(tgt, alias, '', src, cond, del)`
    is handled by _merge_into_dispatch on the POSITIONAL shape only — a
    named matched_upsert_condition is never reinterpreted as a delete."""
    from .expr import ExprError, batch_resolver, eval_mask, eval_value, parse_assignments, parse_expr

    if source_sqls:
        raise ProcedureError(
            "source_sqls is not supported (no SQL DDL engine); register the "
            "source as a catalog table and pass source_table"
        )
    if not source_table:
        raise ProcedureError("source_table is required")
    if matched_upsert_condition and not matched_upsert_setting:
        raise ProcedureError("matched-upsert must set the 'matched_upsert_setting' argument")

    t = _t(cat, target_table)
    src_t = _t(cat, source_table)
    rb = src_t.new_read_builder()
    source = rb.new_read().read_all(rb.new_scan().plan())

    tgt_names = {a for a in (target_alias, target_table.split(".")[-1], "tgt", "t") if a}
    src_names = {a for a in (source_table.split(".")[-1], "src", "s") if a} - tgt_names

    def make_resolver(src_b, tgt_b):
        def resolve(alias, name):
            order = []
            if alias is None:
                order = [b for b in (src_b, tgt_b) if b is not None]
            elif alias in src_names:
                order = [src_b]
            elif alias in tgt_names:
                if tgt_b is None:
                    raise ProcedureError(f"'{alias}.{name}': no target row in NOT MATCHED clause")
                order = [tgt_b]
            else:
                raise ProcedureError(f"unknown table alias {alias!r} in merge_into")
            for b in order:
                if name in b.schema:
                    c = b.column(name)
                    import numpy as _np

                    return _np.asarray(c.values), c.validity
            raise ProcedureError(f"unknown column {name!r} in merge_into")

        return resolve

    def cond_fn(expr_text):
        if not expr_text or expr_text.strip().upper() == "TRUE":
            return None
        ast = parse_expr(expr_text)

        def fn(src_b, tgt_b=None):
            return eval_mask(ast, make_resolver(src_b, tgt_b), src_b.num_rows)

        return fn

    def value_fn(ast):
        def fn(src_b, tgt_b=None):
            return eval_value(ast, make_resolver(src_b, tgt_b), src_b.num_rows)

        return fn

    # the merge condition must equi-join on the full target primary key —
    # the same restriction the reference enforces for PK tables
    if merge_condition:
        ast = parse_expr(merge_condition)
        parts = ast[1] if ast[0] == "and" else [ast]
        joined = set()
        for p in parts:
            ok = (
                p[0] == "cmp" and p[1] == "=" and p[2][0] == "col" and p[3][0] == "col"
                and p[2][2] == p[3][2]
            )
            if not ok:
                raise ProcedureError(
                    f"merge_condition must be an equi-join on the primary key, got {merge_condition!r}"
                )
            joined.add(p[2][2])
        if joined != set(t.primary_keys):
            raise ProcedureError(
                f"merge_condition must cover the full primary key {sorted(t.primary_keys)}, got {sorted(joined)}"
            )

    from ..table.rowops import MergeInto

    m = MergeInto(t, source)
    try:
        if matched_upsert_setting:
            assigns = parse_assignments(matched_upsert_setting)
            if assigns and assigns[0][0] == "*":
                set_map = {
                    f.name: f"src.{f.name}"
                    for f in t.row_type.fields
                    if f.name not in t.primary_keys and f.name in source.schema
                }
            else:
                set_map = {col: value_fn(ast) for col, ast in assigns}
            m.when_matched_update(set_map, condition=cond_fn(matched_upsert_condition))
        if matched_delete_condition:
            m.when_matched_delete(condition=cond_fn(matched_delete_condition))
        if not_matched_insert_values:
            if not_matched_insert_values.strip() == "*":
                values = None
            else:
                if "=" in not_matched_insert_values:  # 'col = expr, ...' form
                    values = {
                        col: value_fn(ast)
                        for col, ast in parse_assignments(not_matched_insert_values)
                    }
                else:
                    # positional list over the target schema (reference syntax)
                    from .expr import _Parser, _tokenize  # noqa: SLF001

                    p = _Parser(_tokenize(not_matched_insert_values), not_matched_insert_values)
                    asts = [p.parse_operand()]
                    while p.peek() == ("op", ","):
                        p.next()
                        asts.append(p.parse_operand())
                    fields = t.row_type.fields
                    if len(asts) != len(fields):
                        raise ProcedureError(
                            f"not_matched_insert_values has {len(asts)} expressions; "
                            f"target has {len(fields)} columns"
                        )
                    values = {f.name: value_fn(a) for f, a in zip(fields, asts)}
            m.when_not_matched_insert(values=values, condition=cond_fn(not_matched_insert_condition))
        r = m.execute()
    except ExprError as e:
        raise ProcedureError(str(e)) from e
    return {"rows_updated": r.rows_updated, "rows_deleted": r.rows_deleted,
            "rows_inserted": r.rows_inserted}


def _merge_into_dispatch(cat, *args, **kwargs):
    """The reference's positional dispatch rule, applied ONLY to positional
    calls: exactly 6 positional arguments = the short delete form
    (tgt, alias, sqls, src, merge_cond, delete_cond). Named arguments always
    mean what they say."""
    if len(args) == 6 and not kwargs:
        return _proc_merge_into(
            cat, args[0], args[1], args[2], args[3], args[4],
            matched_delete_condition=args[5],
        )
    return _proc_merge_into(cat, *args, **kwargs)


def _infer_migrate_row_type(path: str, file_format: str):
    if file_format == "parquet":
        import pyarrow.parquet as pq

        from ..data.batch import ColumnBatch

        return ColumnBatch.row_type_from_arrow(pq.read_schema(path))
    if file_format == "orc":
        import pyarrow.orc as po

        from ..data.batch import ColumnBatch

        with open(path, "rb") as fh:
            return ColumnBatch.row_type_from_arrow(po.ORCFile(fh).schema)
    raise ProcedureError(f"cannot infer schema from format {file_format!r}")


def _proc_migrate_table(cat, table: str, source_dir: str, file_format: str = "parquet",
                        options: str = ""):
    """MigrateTableProcedure: adopt a directory of foreign-format files as a
    table without rewriting them (file-level adoption commit)."""
    import glob as _glob

    from ..table.migrate import migrate_files

    candidates = sorted(_glob.glob(f"{_glob.escape(source_dir)}/*.{file_format}"))
    if not candidates:
        raise ProcedureError(f"no *.{file_format} files found in {source_dir}")
    row_type = _infer_migrate_row_type(candidates[0], file_format)
    t = migrate_files(cat, table, source_dir, row_type, file_format=file_format)
    return {"migrated": table, "snapshot": t.store.snapshot_manager.latest_snapshot_id()}


def _proc_migrate_database(cat, database: str, source_dir: str, file_format: str = "parquet"):
    """MigrateDatabaseProcedure: one migrate_table per subdirectory."""
    import os as _os

    migrated = []
    for entry in sorted(_os.listdir(source_dir)):
        sub = _os.path.join(source_dir, entry)
        if _os.path.isdir(sub) and any(f.endswith(f".{file_format}") for f in _os.listdir(sub)):
            _proc_migrate_table(cat, f"{database}.{entry}", sub, file_format)
            migrated.append(f"{database}.{entry}")
    return {"migrated": migrated}


def _proc_migrate_file(cat, source_table: str, target_table: str,
                       delete_origin: bool = True):
    """MigrateFileProcedure: move the data files of one append table into
    another existing append table (same schema) as an adoption commit."""
    from ..table.migrate import adopt_table_files

    try:
        moved = adopt_table_files(cat, source_table, target_table)
    except ValueError as e:
        raise ProcedureError(str(e)) from e
    if delete_origin:
        cat.drop_table(source_table)
    return {"migrated_into": target_table, "files": moved,
            "origin_deleted": bool(delete_origin)}


def _proc_repair(cat, identifier: str | None = None):
    """RepairProcedure: sync catalog metadata with the filesystem truth."""
    repair = getattr(cat, "repair", None)
    if repair is None:
        raise ProcedureError(f"catalog {type(cat).__name__} does not support repair")
    return repair() if identifier is None else repair(identifier)


def _proc_query_service(cat, table: str, serve_seconds: float | None = None,
                        host: str = "127.0.0.1", port: int = 0):
    """QueryServiceProcedure: start the KV query service for a table. Unlike
    the reference's (which parks a streaming job), this returns after
    `serve_seconds` (None = return immediately, server runs as a daemon)."""
    import time as _time

    from ..service import KvQueryServer

    server = KvQueryServer(_t(cat, table), host=host, port=port)
    h, p = server.start()
    if serve_seconds:
        _time.sleep(float(serve_seconds))
        server.shutdown()
        return {"service": "kv-query", "host": h, "port": p, "stopped": True}
    return {"service": "kv-query", "host": h, "port": p, "server": server}


def _proc_rewrite_file_index(cat, table: str, partitions: str | None = None):
    """RewriteFileIndexProcedure.java:50 — build file indexes for data files
    written BEFORE indexing was enabled (or with a different index config).
    Scans the latest snapshot, (re)builds the configured bloom indexes for
    files lacking them, and commits a COMPACT-kind metadata-only replacement
    (same data file, new extra_files/embedded_index)."""
    import dataclasses

    from ..format.fileindex import build_index_payload, index_path, resolve_key_bloom
    from ..options import CoreOptions

    t = _t(cat, table)
    opts = t.options
    cols_opt = opts.options.get(CoreOptions.FILE_INDEX_BLOOM_COLUMNS)
    # composite key bloom (ISSUE 13): tables that enabled the primary-key
    # index AFTER writing data backfill it through the same procedure
    key_bloom = (
        resolve_key_bloom(opts.options.get(CoreOptions.FILE_INDEX_BLOOM_KEY_ENABLED))
        and t.is_primary_key_table
    )
    if not cols_opt and not key_bloom:
        raise ProcedureError(
            "table has no file-index.bloom-filter.columns (or primary-key "
            "bloom) configured; set the option, then CALL sys.rewrite_file_index"
        )
    bloom_cols = [c.strip() for c in cols_opt.split(",") if c.strip()] if cols_opt else []
    fpp = opts.options.get(CoreOptions.FILE_INDEX_BLOOM_FPP)
    threshold = opts.options.get(CoreOptions.FILE_INDEX_IN_MANIFEST_THRESHOLD)
    part_filter = _parse_partition_specs(partitions) if partitions else None

    store = t.store
    snap = store.snapshot_manager.latest_snapshot_id()
    if snap is None:
        return {"rewritten": 0}
    plan = store.new_scan().plan()
    from ..core.manifest import CommitMessage

    by_pb: dict[tuple, CommitMessage] = {}
    rewritten = 0
    for e in plan.entries:
        f = e.file
        if f.embedded_index is not None or any(x.endswith(".index") for x in f.extra_files):
            continue  # already indexed
        if part_filter is not None:
            part_names = t.partition_keys
            spec_match = any(
                all(str(dict(zip(part_names, e.partition)).get(k)) == v for k, v in spec.items())
                for spec in part_filter
            )
            if not spec_match:
                continue
        rf = store.reader_factory(e.partition, e.bucket)
        present = [c for c in bloom_cols if c in t.row_type]
        if not present and not key_bloom:
            continue
        read_fields = sorted(set(present) | (set(store.key_names) if key_bloom else set()))
        kv = rf.read(f, fields=read_fields, system_columns=False)
        hashes = None
        if key_bloom:
            from ..table.bucket import key_hashes

            hashes = key_hashes(kv.data, store.key_names)
        payload = build_index_payload(kv.data, present, fpp, key_hashes=hashes)
        if payload is None:
            continue
        extra = list(f.extra_files)
        embedded = None
        if len(payload) <= threshold:
            embedded = payload
        else:
            data_path = f"{rf.bucket_dir}/{f.file_name}"
            t.file_io.write_bytes(index_path(data_path), payload, overwrite=True)
            extra.append(f.file_name + ".index")
        new_meta = dataclasses.replace(f, extra_files=tuple(extra), embedded_index=embedded)
        key = (e.partition, e.bucket)
        msg = by_pb.get(key)
        if msg is None:
            msg = by_pb[key] = CommitMessage(
                partition=e.partition, bucket=e.bucket, total_buckets=e.total_buckets
            )
        msg.compact_before.append(f)
        msg.compact_after.append(new_meta)
        rewritten += 1
    if by_pb:
        from ..table.write import BatchWriteBuilder, TableCommit

        TableCommit(t).commit_messages(BatchWriteBuilder.COMMIT_IDENTIFIER, list(by_pb.values()))
    return {"rewritten": rewritten, "columns": bloom_cols}


# --- privilege procedures (reference procedure/privilege/*) ----------------


def _priv(cat):
    from ..catalog.privilege import PrivilegeManager

    mgr = getattr(cat, "privilege_manager", None) or getattr(cat, "manager", None)
    if not isinstance(mgr, PrivilegeManager):
        raise ProcedureError(
            "catalog has no privilege support; open it as a PrivilegedCatalog"
        )
    return mgr


def _proc_init_file_based_privilege(cat, root_password: str):
    _priv(cat).init(root_password)
    return {"initialized": True}


def _proc_create_privileged_user(cat, user: str, password: str):
    _priv(cat).create_user(user, password)
    return {"user": user}


def _proc_drop_privileged_user(cat, user: str):
    _priv(cat).drop_user(user)
    return {"dropped_user": user}


def _proc_grant_privilege_to_user(cat, user: str, privilege: str,
                                  database: str | None = None,
                                  table: str | None = None):
    obj = f"{database}.{table}" if database and table else (database or "*")
    _priv(cat).grant(user, obj, privilege)
    return {"user": user, "granted": privilege, "on": obj}


def _proc_revoke_privilege_from_user(cat, user: str, privilege: str,
                                     database: str | None = None,
                                     table: str | None = None):
    obj = f"{database}.{table}" if database and table else (database or "*")
    _priv(cat).revoke(user, obj, privilege)
    return {"user": user, "revoked": privilege, "on": obj}


procedures: dict[str, Callable[..., Any]] = {
    "compact": _proc_compact,
    "compact_database": _proc_compact_database,
    "create_tag": _proc_create_tag,
    "delete_tag": _proc_delete_tag,
    "rollback_to": _proc_rollback_to,
    "create_branch": _proc_create_branch,
    "delete_branch": _proc_delete_branch,
    "fast_forward": _proc_fast_forward,
    "expire_snapshots": _proc_expire_snapshots,
    "expire_partitions": _proc_expire_partitions,
    "drop_partition": _proc_drop_partition,
    "mark_partition_done": _proc_mark_partition_done,
    "remove_orphan_files": _proc_remove_orphan_files,
    "reset_consumer": _proc_reset_consumer,
    "delete": _proc_delete,
    "merge_into": _merge_into_dispatch,
    "migrate_table": _proc_migrate_table,
    "migrate_database": _proc_migrate_database,
    "migrate_file": _proc_migrate_file,
    "repair": _proc_repair,
    "query_service": _proc_query_service,
    "rewrite_file_index": _proc_rewrite_file_index,
    "init_file_based_privilege": _proc_init_file_based_privilege,
    "create_privileged_user": _proc_create_privileged_user,
    "drop_privileged_user": _proc_drop_privileged_user,
    "grant_privilege_to_user": _proc_grant_privilege_to_user,
    "revoke_privilege_from_user": _proc_revoke_privilege_from_user,
}


def call(catalog: "Catalog", statement: str) -> Any:
    """Execute one ``CALL sys.<proc>(...)`` statement against a catalog."""
    name, args, kwargs = parse_call(statement)
    fn = procedures.get(name)
    if fn is None:
        raise ProcedureError(
            f"unknown procedure {name!r}; available: {sorted(procedures)}"
        )
    try:
        return fn(catalog, *args, **kwargs)
    except TypeError as e:
        # surface signature mistakes as procedure errors with the usage
        raise ProcedureError(f"CALL {name}: {e}") from e


def query(catalog: "Catalog", statement: str):
    """Execute one SELECT statement (see sql.select for the grammar)."""
    from .select import query as _query

    return _query(catalog, statement)


def cluster_query(
    catalog: "Catalog", statement: str, client, busy_wait_s: float = 10.0, scan_frag_fn=None
):
    """Execute one SELECT across cluster-service workers (scatter-gather
    scan fragments with code-domain partial aggregation; see sql.cluster).
    `client` is a service.cluster.ClusterClient; results are bit-identical
    to :func:`query` on the same catalog. `scan_frag_fn` swaps the
    per-fragment RPC (the gateway's hedged variant rides this seam)."""
    from .cluster import cluster_query as _cquery

    return _cquery(catalog, statement, client, busy_wait_s=busy_wait_s, scan_frag_fn=scan_frag_fn)


def split_statements(script: str) -> list[str]:
    """Split a SQL script on top-level semicolons. ONE scanner pass with
    quote state carried across newlines: single-quoted literals (with ''
    escapes, including multi-line literals) and backticked identifiers keep
    their ';' and '--'; `-- line comments` outside quotes are stripped."""
    stmts: list[str] = []
    buf: list[str] = []
    i, n = 0, len(script)
    while i < n:
        c = script[i]
        if c == "'":
            j = script.find("'", i + 1)
            while j != -1 and script[j : j + 2] == "''":
                j = script.find("'", j + 2)
            if j == -1:  # unterminated: keep verbatim; the parser reports it
                buf.append(script[i:])
                break
            buf.append(script[i : j + 1])
            i = j + 1
            continue
        if c == "`":
            j = script.find("`", i + 1)
            if j == -1:
                buf.append(script[i:])
                break
            buf.append(script[i : j + 1])
            i = j + 1
            continue
        if script[i : i + 2] == "--":
            j = script.find("\n", i)
            i = n if j == -1 else j  # keep the newline as whitespace
            continue
        if c == ";":
            stmts.append("".join(buf).strip())
            buf = []
            i += 1
            continue
        buf.append(c)
        i += 1
    tail = "".join(buf).strip()
    if tail:
        stmts.append(tail)
    return [s for s in stmts if s]


def execute_script(catalog: "Catalog", script: str) -> list[Any]:
    """Run a multi-statement SQL script in order; returns one result per
    statement. A failure stops the script (statements already executed have
    committed — same per-statement atomicity as the reference's engines)."""
    return [execute(catalog, s) for s in split_statements(script)]


def execute(catalog: "Catalog", statement: str) -> Any:
    """One string entry point: SELECT -> ColumnBatch, CALL -> procedure
    dict, DDL (CREATE/DROP/SHOW/DESCRIBE) -> dict | ColumnBatch | str."""
    if re.match(r"^\s*(EXPLAIN\s+)?SELECT\b", statement, re.I):
        return query(catalog, statement)
    if re.match(r"^\s*(CREATE|DROP|ALTER|SHOW|DESC(RIBE)?|ANALYZE)\b", statement, re.I):
        from .ddl import ddl as _ddl

        return _ddl(catalog, statement)
    if re.match(r"^\s*INSERT\b", statement, re.I):
        from .dml import insert

        return insert(catalog, statement)
    if re.match(r"^\s*UPDATE\b", statement, re.I):
        from .dml import update

        return update(catalog, statement)
    if re.match(r"^\s*DELETE\s+FROM\b", statement, re.I):
        from .dml import delete as dml_delete

        return dml_delete(catalog, statement)
    if re.match(r"^\s*TRUNCATE\b", statement, re.I):
        from .dml import truncate

        return truncate(catalog, statement)
    return call(catalog, statement)
