"""SQL CALL procedures: the string entry surface (VERDICT r3 missing #2).

Every reference interaction path is SQL — Flink registers its actions as
``CALL sys.<proc>(...)`` procedures
(/root/reference/paimon-flink/paimon-flink-common/src/main/java/org/apache/
paimon/flink/procedure/ProcedureUtil.java lists them; ProcedureBase.java
binds each to the catalog), and Spark mirrors the same set. This module is
the engine-neutral analog: :func:`call` parses one ``CALL`` statement
(positional args, Flink's ``name => value`` named args, SQL literals) and
dispatches onto the SAME Table-API code paths the CLI actions use — so a
runbook written against the reference's procedures ports by string edit,
not rewrite.

    >>> from paimon_tpu.sql import call
    >>> call(catalog, "CALL sys.create_tag('db.t', 'v1')")
    >>> call(catalog, "CALL sys.compact(`table` => 'db.t', `full` => true)")

Procedures operate through a live Catalog exactly like the reference's
(ProcedureBase.catalog); results come back as plain dicts (the reference
returns string rows — dicts carry the same fields, typed).
"""

from __future__ import annotations

import re
from typing import TYPE_CHECKING, Any, Callable

if TYPE_CHECKING:
    from ..catalog import Catalog

__all__ = ["call", "parse_call", "procedures"]

_CALL_RE = re.compile(r"^\s*CALL\s+(?:`?sys`?\.)?`?(\w+)`?\s*\((.*)\)\s*;?\s*$", re.I | re.S)


class ProcedureError(ValueError):
    pass


def _tokenize_args(body: str) -> list[str]:
    """Split the argument body on top-level commas, honoring single-quoted
    SQL strings (with '' escaping) and backquoted identifiers."""
    parts: list[str] = []
    buf: list[str] = []
    i, n = 0, len(body)
    while i < n:
        c = body[i]
        if c == "'":
            buf.append(c)
            i += 1
            while i < n:
                buf.append(body[i])
                if body[i] == "'":
                    if i + 1 < n and body[i + 1] == "'":  # '' escape
                        buf.append("'")
                        i += 2
                        continue
                    i += 1
                    break
                i += 1
            continue
        if c == "`":
            j = body.index("`", i + 1)
            buf.append(body[i : j + 1])
            i = j + 1
            continue
        if c == ",":
            parts.append("".join(buf).strip())
            buf = []
            i += 1
            continue
        buf.append(c)
        i += 1
    tail = "".join(buf).strip()
    if tail:
        parts.append(tail)
    return parts


def _literal(tok: str) -> Any:
    """One SQL literal -> python value."""
    t = tok.strip()
    if t.startswith("'") and t.endswith("'"):
        return t[1:-1].replace("''", "'")
    low = t.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    if low == "null":
        return None
    try:
        return int(t)
    except ValueError:
        pass
    try:
        return float(t)
    except ValueError:
        raise ProcedureError(f"unsupported literal: {tok!r}") from None


def parse_call(statement: str) -> tuple[str, list[Any], dict[str, Any]]:
    """'CALL sys.proc(a, k => v)' -> (proc, [a], {k: v})."""
    m = _CALL_RE.match(statement)
    if not m:
        raise ProcedureError(f"not a CALL statement: {statement!r}")
    name = m.group(1).lower()
    args: list[Any] = []
    kwargs: dict[str, Any] = {}
    for tok in _tokenize_args(m.group(2)):
        nm = re.match(r"^`?(\w+)`?\s*=>\s*(.+)$", tok, re.S)
        if nm:
            kwargs[nm.group(1).lower()] = _literal(nm.group(2))
        else:
            if kwargs:
                raise ProcedureError("positional argument after named argument")
            args.append(_literal(tok))
    return name, args, kwargs


# --------------------------------------------------------------------------
# procedure implementations (reference paimon-flink-common/.../procedure/*)
# --------------------------------------------------------------------------

def _t(cat: "Catalog", ident: str):
    return cat.get_table(ident)


def _proc_compact(cat, table: str, partitions: str | None = None,
                  order_strategy: str | None = None, order_by: str | None = None,
                  full: bool = False):
    """CompactProcedure.java: plain compaction, or clustered when an order
    strategy is given (zorder/hilbert/order)."""
    t = _t(cat, table)
    if order_strategy:
        from ..table.sort_compact import sort_compact

        cols = [c.strip() for c in (order_by or "").split(",") if c.strip()]
        if not cols:
            raise ProcedureError("order_by is required with order_strategy")
        n = sort_compact(t, cols, order=order_strategy)
        return {"rows_clustered": n, "strategy": order_strategy}
    from ..table.compactor import DedicatedCompactor

    return {"compacted": DedicatedCompactor(t).run_once(full=full), "full": full}


def _proc_compact_database(cat, including_databases: str | None = None,
                           mode: str | None = None,
                           including_tables: str | None = None,
                           excluding_tables: str | None = None,
                           full: bool = False):
    from ..table.compactor import DedicatedCompactor

    db_pat = re.compile(including_databases or ".*")
    inc = re.compile(including_tables or ".*")
    exc = re.compile(excluding_tables) if excluding_tables else None
    compacted = []
    for db in cat.list_databases():
        if not db_pat.fullmatch(db):
            continue
        for name in cat.list_tables(db):
            ident = f"{db}.{name}"
            if not (inc.fullmatch(ident) or inc.fullmatch(name)):
                continue
            if exc and (exc.fullmatch(ident) or exc.fullmatch(name)):
                continue
            t = cat.get_table(ident)
            if not t.primary_keys:
                continue
            if DedicatedCompactor(t).run_once(full=full):
                compacted.append(ident)
    return {"compacted": compacted}


def _proc_create_tag(cat, table: str, tag: str, snapshot_id: int | None = None):
    _t(cat, table).create_tag(tag, snapshot_id=snapshot_id)
    return {"tag": tag}


def _proc_delete_tag(cat, table: str, tag: str):
    _t(cat, table).delete_tag(tag)
    return {"deleted_tag": tag}


def _proc_rollback_to(cat, table: str, snapshot_or_tag):
    target = snapshot_or_tag
    if isinstance(target, str) and target.isdigit():
        target = int(target)
    _t(cat, table).rollback_to(target)
    return {"rolled_back_to": target}


def _proc_create_branch(cat, table: str, branch: str, tag: str | None = None):
    from ..table.branch import BranchManager

    t = _t(cat, table)
    BranchManager(t.file_io, t.path).create(branch, from_tag=tag)
    return {"branch": branch}


def _proc_delete_branch(cat, table: str, branch: str):
    from ..table.branch import BranchManager

    t = _t(cat, table)
    BranchManager(t.file_io, t.path).delete(branch)
    return {"deleted_branch": branch}


def _proc_fast_forward(cat, table: str, branch: str):
    from ..table.branch import BranchManager

    t = _t(cat, table)
    BranchManager(t.file_io, t.path).fast_forward(branch)
    return {"fast_forwarded": branch}


def _proc_expire_snapshots(cat, table: str, retain_max: int | None = None,
                           retain_min: int | None = None,
                           older_than: str | None = None,
                           max_deletes: int | None = None):
    t = _t(cat, table)
    overrides = {}
    if retain_max is not None:
        overrides["snapshot.num-retained.max"] = str(retain_max)
    if retain_min is not None:
        overrides["snapshot.num-retained.min"] = str(retain_min)
    if max_deletes is not None:
        overrides["snapshot.expire.limit"] = str(max_deletes)
    if overrides:
        t = t.copy(overrides)
    return {"expired": t.expire_snapshots()}


def _proc_expire_partitions(cat, table: str, expiration_time: str,
                            timestamp_formatter: str = "%Y-%m-%d",
                            timestamp_pattern: str | None = None):
    from ..options import parse_duration_millis
    from ..table.maintenance import expire_partitions

    t = _t(cat, table)
    expired = expire_partitions(
        t,
        parse_duration_millis(expiration_time),
        time_col=timestamp_pattern,
        pattern=timestamp_formatter,
    )
    return {"expired_partitions": [list(p) for p in expired]}


def _parse_partition_specs(partitions: str) -> list[dict]:
    """Reference partition-string syntax: 'k1=v1,k2=v2;k1=v3' (';' separates
    multiple specs)."""
    specs = []
    for spec in partitions.split(";"):
        if spec.strip():
            specs.append(dict(kv.strip().split("=", 1) for kv in spec.split(",")))
    return specs


def _proc_drop_partition(cat, table: str, partitions: str):
    from ..table.maintenance import drop_partition

    dropped = drop_partition(_t(cat, table), *_parse_partition_specs(partitions))
    return {"dropped_partitions": [list(p) for p in dropped]}


def _proc_mark_partition_done(cat, table: str, partitions: str):
    from ..table.maintenance import mark_partition_done

    paths = mark_partition_done(_t(cat, table), _parse_partition_specs(partitions))
    return {"markers": paths}


def _proc_remove_orphan_files(cat, table: str, older_than_hours: float = 24.0,
                              dry_run: bool = False):
    from ..table.maintenance import remove_orphan_files

    removed = remove_orphan_files(
        _t(cat, table),
        older_than_millis=int(float(older_than_hours) * 3600_000),
        dry_run=dry_run,
    )
    return {"orphans": removed, "dry_run": dry_run}


def _proc_reset_consumer(cat, table: str, consumer_id: str,
                         next_snapshot_id: int | None = None):
    from ..table.consumer import ConsumerManager

    t = _t(cat, table)
    cm = ConsumerManager(t.file_io, t.path)
    if next_snapshot_id is None:
        cm.delete(consumer_id)
        return {"deleted_consumer": consumer_id}
    cm.reset(consumer_id, next_snapshot_id)
    return {"consumer": consumer_id, "next_snapshot": next_snapshot_id}


def _proc_delete(cat, table: str, where: str):
    """DeleteAction analog; `where` is the predicate-json the CLI accepts."""
    import json as _json

    from ..data import predicate as P

    d = _json.loads(where)
    op = d.get("op", "=")
    fns = {"=": P.equal, "!=": P.not_equal, ">": P.greater_than,
           ">=": P.greater_or_equal, "<": P.less_than, "<=": P.less_or_equal}
    if op == "in":
        pred = P.in_(d["field"], d["value"])
    elif op == "is_null":
        pred = P.is_null(d["field"])
    else:
        pred = fns[op](d["field"], d["value"])
    return {"rows_deleted": _t(cat, table).delete_where(pred)}


procedures: dict[str, Callable[..., Any]] = {
    "compact": _proc_compact,
    "compact_database": _proc_compact_database,
    "create_tag": _proc_create_tag,
    "delete_tag": _proc_delete_tag,
    "rollback_to": _proc_rollback_to,
    "create_branch": _proc_create_branch,
    "delete_branch": _proc_delete_branch,
    "fast_forward": _proc_fast_forward,
    "expire_snapshots": _proc_expire_snapshots,
    "expire_partitions": _proc_expire_partitions,
    "drop_partition": _proc_drop_partition,
    "mark_partition_done": _proc_mark_partition_done,
    "remove_orphan_files": _proc_remove_orphan_files,
    "reset_consumer": _proc_reset_consumer,
    "delete": _proc_delete,
}


def call(catalog: "Catalog", statement: str) -> Any:
    """Execute one ``CALL sys.<proc>(...)`` statement against a catalog."""
    name, args, kwargs = parse_call(statement)
    fn = procedures.get(name)
    if fn is None:
        raise ProcedureError(
            f"unknown procedure {name!r}; available: {sorted(procedures)}"
        )
    try:
        return fn(catalog, *args, **kwargs)
    except TypeError as e:
        # surface signature mistakes as procedure errors with the usage
        raise ProcedureError(f"CALL {name}: {e}") from e
