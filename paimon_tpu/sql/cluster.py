"""Distributed SQL (ISSUE 16): scatter-gather scans with compressed-domain
partial aggregation on the cluster-service workers.

The coordinator side of the fragment protocol. One SELECT plans exactly like
the local evaluator (sql.select.parse_select — every semantic decision is
shared), then the scan splits scatter to the workers owning their buckets
over the cluster-service wire (service.cluster `scan_frag` beside
get_batch/join_part). Each worker scans its splits with predicate +
projection pushdown and:

* aggregate queries — segment-reduces the fragment into ONE partial
  aggregate per group on device (ops.aggregates.segment_reduce keyed on
  dictionary codes), shipping the group keys back as (pruned pool, uint32
  codes, partial rows). The coordinator combines in the code domain:
  ops.dicts.unify_pools merges the per-worker pools, remap_codes re-ranks
  the codes, and a second segment_reduce over the partial rows composes
  counts/sums by addition and min/max by min/max (_KERNEL_COMBINE). Row
  positions are global (split seq << 40 + row), so the combined
  first-appearance order is exactly the single-process one — results are
  bit-identical to the local oracle by construction.
* non-aggregate queries — streams the row batches back Arrow-encoded; the
  coordinator reassembles them in global split order and runs the same
  ORDER/LIMIT/projection tail.

`sql.cluster.code-domain` (or PAIMON_TPU_SQL_CODE_DOMAIN) toggles the
compressed combine: off, workers expand group-key values on the wire and
the coordinator re-encodes them through the identical ops.dicts path — the
verify stage forces both and asserts equal results.

Failover: a fragment whose worker dies (ConnectionError) returns its splits
to the pending pool; the coordinator refreshes the route (the cluster
coordinator reassigns the dead worker's buckets on missed heartbeats) and
re-dispatches to the new owners until `sql.cluster.retry-timeout` expires.
Typed-BUSY sheds (`sql.cluster.scan.max-inflight`) retry inside
ClusterClient.scan_frag with the server-advertised backoff.

Shuffle aggregation (ISSUE 20): when the estimated distinct-group count
(from the planned splits' file stats — zero extra IO) exceeds
`sql.cluster.shuffle.threshold`, the combine itself scales out. Each worker
hash-partitions its fragment partial by group-key VALUE
(ops.dicts.partition_rows — hashes agree across workers despite disjoint
per-worker code spaces) into R ranges, ships partition i to range i's owner
over the `exchange_part` RPC, and answers a summary instead of the partial.
Every range owner then unifies pools and segment-reduces ITS range in the
code domain (`exchange_combine`), so the coordinator only concatenates R
already-reduced, value-disjoint ranges — no second reduce — and runs the
shared _finish tail. first_pos min-reduces inside each range, so global
first-appearance order survives the shuffle bit-exactly. A range owner
dying mid-shuffle is healed under the same retry deadline: the range moves
to a live worker, sources reship their buffered parts (`exchange_reship`),
and a source whose buffer died with it re-executes its fragment — partial
content is deterministic and delivery is keyed (qid, range, src), so
re-runs and gateway hedges overwrite idempotently. PAIMON_TPU_SQL_SHUFFLE
forces the path on/off (the verify stage runs the parity suite both ways).
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import TYPE_CHECKING

import numpy as np

from .expr import ExprError, parse_expr, to_predicate
from .select import (
    _EXPLAIN_RE,
    _KERNEL_COMBINE,
    QueryError,
    _agg_kernel_plan,
    _assemble_group_batch,
    _engine_for,
    _finish,
    _order_cols,
    explain_plan,
    parse_select,
    plan_batch,
    query,
)

if TYPE_CHECKING:
    from ..catalog import Catalog

__all__ = [
    "cluster_query",
    "clear_fragment_cache",
    "resolve_code_domain",
    "resolve_shuffle",
    "encode_fragment",
    "decode_fragment",
    "encode_partial",
    "decode_partial",
    "combine_partials",
    "wire_partial_bytes",
]


def resolve_code_domain(enabled) -> bool:
    """One resolution order (the ops.dicts.resolve_dict_domain shape): the
    PAIMON_TPU_SQL_CODE_DOMAIN env var (verify forces both paths) beats the
    sql.cluster.code-domain option value, which beats the default (on)."""
    env = os.environ.get("PAIMON_TPU_SQL_CODE_DOMAIN", "").strip().lower()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    if enabled is None:
        return True
    if isinstance(enabled, str):
        return enabled.strip().lower() in ("1", "on", "true")
    return bool(enabled)


def resolve_shuffle() -> "bool | None":
    """Tri-state shuffle override: PAIMON_TPU_SQL_SHUFFLE "1"/"on"/"true"
    forces the exchange path, "0"/"off"/"false" forces coordinator combine,
    unset (None) defers to the sql.cluster.shuffle.threshold estimate."""
    env = os.environ.get("PAIMON_TPU_SQL_SHUFFLE", "").strip().lower()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    return None


# ---------------------------------------------------------------------------
# wire codecs: fragments coordinator->worker, partials worker->coordinator
# (length-prefixed JSON transport: arrays ride base64, row batches Arrow IPC)
# ---------------------------------------------------------------------------
def _b64(arr: np.ndarray) -> dict:
    a = np.ascontiguousarray(arr)
    return {"d": base64.b64encode(a.tobytes()).decode(), "t": str(a.dtype), "s": list(a.shape)}


def _unb64(d: dict) -> np.ndarray:
    return np.frombuffer(base64.b64decode(d["d"]), dtype=np.dtype(d["t"])).reshape(d["s"])


def _encode_pool(pool: np.ndarray) -> dict:
    if pool.dtype == np.dtype(object):
        return {"obj": pool.tolist()}
    return {"arr": _b64(pool)}


def _decode_pool(d: dict) -> np.ndarray:
    if "obj" in d:
        pool = np.empty(len(d["obj"]), dtype=object)
        for i, v in enumerate(d["obj"]):
            pool[i] = v
        return pool
    return _unb64(d["arr"])


def encode_fragment(frag: dict) -> dict:
    """Fragment -> JSON-safe wire dict (splits are already DataSplit.to_dict
    payloads; kern tuples flatten to lists)."""
    wire = dict(frag)
    if wire.get("kern") is not None:
        wire["kern"] = [list(k) for k in wire["kern"]]
    return wire


def decode_fragment(d: dict) -> dict:
    """Wire dict -> fragment (table.query.execute_scan_fragment re-tuples
    kern and rebuilds the DataSplits itself)."""
    return dict(d)


def encode_partial(part: dict, code_domain: bool = True) -> dict:
    """Worker-side: numpy-level partial -> wire dict. Aggregate partials
    ship pools+codes in the code domain (or expanded values when the toggle
    is off); row partials ship per-split Arrow IPC streams."""
    if part["mode"] == "rows":
        import pyarrow as pa

        batches = []
        for seq, b in part["batches"]:
            at = b.to_arrow()
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, at.schema) as w:
                w.write_table(at)
            batches.append([int(seq), base64.b64encode(sink.getvalue().to_pybytes()).decode()])
        return {"mode": "rows", "rows": int(part["rows"]), "batches": batches}
    enc = {
        "mode": "agg",
        "rows": int(part["rows"]),
        "rows_reduced_device": int(part.get("rows_reduced_device", 0)),
        "outs": [_b64(np.asarray(o)) for o in part["outs"]],
        "anyv": [_b64(np.asarray(a)) for a in part["anyv"]],
        "first_pos": _b64(part["first_pos"]),
    }
    if code_domain:
        enc["pools"] = [_encode_pool(p) for p in part["pools"]]
        enc["group_codes"] = [_b64(c) for c in part["group_codes"]]
    else:
        vals = []
        for pool, codes in zip(part["pools"], part["group_codes"]):
            sent = len(pool)
            col = []
            for c in codes.tolist():
                if c == sent:
                    col.append(None)
                else:
                    v = pool[c]
                    col.append(v.item() if hasattr(v, "item") else v)
            vals.append(col)
        enc["vals"] = vals
    return enc


def decode_partial(d: dict, schema, group_cols=()) -> dict:
    """Coordinator-side: wire dict -> numpy-level partial. Expanded group
    keys (code-domain off) re-encode through the SAME ops.dicts.encode_column
    path the workers use, so the combine below is identical either way."""
    if d["mode"] == "rows":
        import pyarrow as pa

        from ..data.batch import ColumnBatch

        batches = []
        for seq, blob in d["batches"]:
            at = pa.ipc.open_stream(pa.BufferReader(base64.b64decode(blob))).read_all()
            batches.append((int(seq), ColumnBatch.from_arrow(at, schema)))
        return {"mode": "rows", "rows": int(d["rows"]), "batches": batches}
    out = {
        "mode": "agg",
        "rows": int(d["rows"]),
        "rows_reduced_device": int(d.get("rows_reduced_device", 0)),
        "outs": [_unb64(o) for o in d["outs"]],
        "anyv": [_unb64(a) for a in d["anyv"]],
        "first_pos": _unb64(d["first_pos"]),
    }
    if "vals" in d:
        from ..data.batch import Column
        from ..ops.dicts import encode_column

        pools, group_codes = [], []
        for g, vs in zip(group_cols, d["vals"]):
            pool, codes = encode_column(Column.from_pylist(vs, schema.field(g).type))
            pools.append(pool)
            group_codes.append(codes)
        out["pools"], out["group_codes"] = pools, group_codes
    else:
        out["pools"] = [_decode_pool(p) for p in d.get("pools", [])]
        out["group_codes"] = [
            _unb64(c).astype(np.uint32, copy=False) for c in d.get("group_codes", [])
        ]
    return out


def wire_partial_bytes(enc: dict) -> int:
    """Approximate wire size of one ENCODED partial: b64 payload lengths
    plus a rough object-pool/expanded-value estimate. The exchange_bytes
    accounting — close enough for capacity planning without paying a second
    json.dumps per shipped part."""
    n = 0
    for key in ("outs", "anyv", "group_codes"):
        for d in enc.get(key) or []:
            n += len(d.get("d", ""))
    fp = enc.get("first_pos")
    if isinstance(fp, dict):
        n += len(fp.get("d", ""))
    for pd in enc.get("pools") or []:
        if "arr" in pd:
            n += len(pd["arr"].get("d", ""))
        else:
            n += sum(len(str(v)) + 3 for v in pd.get("obj", ()))
    for col in enc.get("vals") or []:
        n += sum(len(str(v)) + 3 for v in col)
    return n


# ---------------------------------------------------------------------------
# coordinator: plan -> scatter -> combine
# ---------------------------------------------------------------------------
class _LocalFallback(Exception):
    """Raised mid-plan when a query shape cannot route through fragments
    (non-numeric aggregate argument: the host reduceat path owns it) — the
    caller falls back to the single-process evaluator."""


def _scatter(
    client,
    pending: dict,
    template: dict,
    retry_ms: int,
    busy_wait_s: float,
    scan_frag_fn=None,
    decorate=None,
):
    """Dispatch one fragment per owning worker, failover on dead
    connections: failed fragments' splits return to the pool, the route
    refreshes (the coordinator reassigns dead workers' buckets) and the
    splits regroup under their new owners until retry_ms expires.

    `scan_frag_fn` swaps the per-fragment RPC (same (wid, frag,
    busy_wait_s) contract as ClusterClient.scan_frag) — the gateway
    threads its hedged variant through here so scan fragments race a
    secondary worker past the hedge deadline.

    `decorate(frag, wid, items)` rewrites each fragment dict just before
    encoding, once per DISPATCH ATTEMPT (retries included) — the shuffle
    planner mints a fresh source id per attempt so partial deliveries
    from a dead attempt can never be mistaken for a live one's."""
    from ..metrics import sql_metrics

    g = sql_metrics()
    call = scan_frag_fn if scan_frag_fn is not None else client.scan_frag
    deadline = time.monotonic() + retry_ms / 1000.0
    results: list[dict] = []
    round_no = 0

    def _frag(wid, items):
        frag = dict(template, splits=items)
        if decorate is not None:
            frag = decorate(frag, wid, items)
        return encode_fragment(frag)

    while pending:
        g.counter("fragments").inc(len(pending))
        if round_no:
            g.counter("fragments_retried").inc(len(pending))
        round_no += 1
        with ThreadPoolExecutor(max_workers=max(len(pending), 1)) as ex:
            futs = {
                wid: ex.submit(call, wid, _frag(wid, items), busy_wait_s)
                for wid, items in pending.items()
            }
            failed: list = []
            for wid, fut in futs.items():
                try:
                    results.append(fut.result())
                except (ConnectionError, OSError, TimeoutError):
                    failed.extend(pending[wid])
                    client.drop_conn(wid)
        if not failed:
            break
        # regroup under the refreshed route; the dead worker's buckets move
        # once the coordinator times out its heartbeats, so keep trying
        while True:
            if time.monotonic() >= deadline:
                raise QueryError(
                    f"scan fragments undeliverable after {retry_ms} ms "
                    f"({len(failed)} splits pending)"
                )
            time.sleep(0.05)
            try:
                client.refresh_route()
                regrouped: dict = {}
                for seq, sd in failed:
                    wid = client.owner_of(int(sd["bucket"]))
                    regrouped.setdefault(wid, []).append((seq, sd))
                pending = regrouped
                break
            except (KeyError, ConnectionError, OSError):
                continue
    return results


def _sentinel_remap(remap, pool_len: int, unified_len: int) -> np.ndarray:
    """Extend a unify_pools gather table with the NULL sentinel: input code
    `pool_len` (NULL) maps to unified code `unified_len`."""
    base = remap if remap is not None else np.arange(pool_len, dtype=np.int64)
    return np.concatenate([np.asarray(base, dtype=np.int64), [unified_len]]).astype(np.uint32)


def _unify_partials(parts, n_group_cols: int):
    """Put N decoded partials in ONE code space: unify each group column's
    pools, re-rank every partial's codes through the sentinel-extended
    gather tables, concatenate. Returns (pools, lane-stacked codes)."""
    from ..ops.dicts import remap_codes, unify_pools

    pools_f, codes_f = [], []
    for gi in range(n_group_cols):
        unified, remaps = unify_pools([q["pools"][gi] for q in parts])
        mapped = [
            remap_codes(
                _sentinel_remap(rm, len(q["pools"][gi]), len(unified)),
                q["group_codes"][gi],
            )
            for q, rm in zip(parts, remaps)
        ]
        pools_f.append(unified)
        codes_f.append(np.concatenate(mapped).astype(np.uint32, copy=False))
    return pools_f, codes_f


def combine_partials(parts, n_group_cols: int, kern, engine: str):
    """Second-stage reduce over N partials' rows, keyed on the UNIFIED code
    domain; returns (pools, group codes, outs, anyv, first_pos) in the
    _assemble_group_batch contract. Shared verbatim by the coordinator's
    single-point combine and every shuffle range owner's per-range fold —
    one reducer, one set of semantics, bit-identical results either way."""
    from ..ops.aggregates import segment_reduce

    pools_f, codes_f = _unify_partials(parts, n_group_cols)
    rows = sum(len(q["first_pos"]) for q in parts)
    lanes = np.column_stack(codes_f) if n_group_cols else np.zeros((rows, 1), np.uint32)
    cols2 = [
        (
            np.concatenate([q["outs"][ki] for q in parts]),
            np.concatenate([q["anyv"][ki] for q in parts]),
        )
        for ki in range(len(kern))
    ]
    fns2 = tuple(_KERNEL_COMBINE[fn] for fn, _ in kern)
    pos = np.concatenate([q["first_pos"] for q in parts])
    rep, outs, anyv, first_pos = segment_reduce(lanes, cols2, fns2, pos=pos, engine=engine)
    return pools_f, [c[rep] for c in codes_f], outs, anyv, first_pos


def _concat_ranges(parts, n_group_cols: int):
    """Concatenate R already-reduced shuffle ranges — the coordinator's
    ENTIRE combine under shuffle, and the reason the path scales: ranges
    partition the group domain by VALUE, so no group key appears in two
    parts and no second segment_reduce is needed. Only pool unification
    (pure code re-ranking) runs here; outs/anyv/first_pos concatenate
    as-is and _assemble_group_batch's stable argsort over the min-reduced
    first_pos restores the exact single-process emission order."""
    pools_f, codes_f = _unify_partials(parts, n_group_cols)
    outs = [
        np.concatenate([q["outs"][ki] for q in parts])
        for ki in range(len(parts[0]["outs"]))
    ]
    anyv = [
        np.concatenate([q["anyv"][ki] for q in parts])
        for ki in range(len(parts[0]["anyv"]))
    ]
    first_pos = np.concatenate([q["first_pos"] for q in parts])
    return pools_f, codes_f, outs, anyv, first_pos


def _estimate_group_count(t, by_wid: dict, group_cols) -> int:
    """Distinct-group upper estimate from the planned splits' file stats
    (DataFileMeta valueStats min/max/nullCount), ZERO extra IO: an integer
    key column estimates global max−min+1 (+1 when any file holds nulls);
    a column with no usable stats falls back to the total row count.
    Multi-column estimates multiply, clipped at total rows — GROUP BY a, b
    can never exceed the row count. Deliberately an upper bound: crossing
    the threshold costs one extra exchange round-trip, underestimating
    costs a coordinator-side combine of millions of partial rows."""
    total_rows = 0
    num_kinds = {}
    for g in group_cols:
        try:
            num_kinds[g] = np.dtype(t.row_type.field(g).type.numpy_dtype()).kind
        except Exception:  # noqa: BLE001 — unknown type: row-count fallback
            num_kinds[g] = "O"
    lo: dict = {}
    hi: dict = {}
    nulls: dict = {}
    usable = {g: num_kinds[g] in "iu" for g in group_cols}
    for items in by_wid.values():
        for _seq, sd in items:
            for f in sd.get("files", []):
                total_rows += int(f.get("rowCount") or 0)
                vs = f.get("valueStats") or {}
                for g in group_cols:
                    if not usable[g]:
                        continue
                    st = vs.get(g)
                    mn = st.get("min") if isinstance(st, dict) else None
                    mx = st.get("max") if isinstance(st, dict) else None
                    if not isinstance(mn, int) or not isinstance(mx, int):
                        usable[g] = False  # pruned/absent stats: fall back
                        continue
                    lo[g] = mn if g not in lo else min(lo[g], mn)
                    hi[g] = mx if g not in hi else max(hi[g], mx)
                    if int((st or {}).get("nullCount") or 0) > 0:
                        nulls[g] = True
    est = 1
    for g in group_cols:
        if usable.get(g) and g in lo:
            col = hi[g] - lo[g] + 1 + (1 if nulls.get(g) else 0)
        else:
            col = total_rows
        est = min(est * max(col, 1), max(total_rows, 1))
    return int(est if group_cols else 0)


def _decide_shuffle(t, client, opts, group_cols, by_wid: dict):
    """(shuffle on?, estimated groups, human reason) — the planner's call,
    shared by cluster_query and EXPLAIN so the surfaced plan IS the
    executed one. Needs a GROUP BY and ≥2 live workers (a lone worker
    exchanging with itself only adds RPC hops); then the env force-switch,
    then the stats estimate against sql.cluster.shuffle.threshold."""
    from ..options import CoreOptions

    est = _estimate_group_count(t, by_wid, group_cols) if group_cols else 0
    if not group_cols:
        return False, est, "no GROUP BY key"
    live = client.live_workers()
    if len(live) < 2:
        return False, est, f"only {len(live)} live worker(s)"
    forced = resolve_shuffle()
    if forced is False:
        return False, est, "forced off (PAIMON_TPU_SQL_SHUFFLE)"
    if forced is True:
        return True, est, "forced on (PAIMON_TPU_SQL_SHUFFLE)"
    thresh = int(opts.get(CoreOptions.SQL_CLUSTER_SHUFFLE_THRESHOLD))
    if est >= thresh:
        return True, est, f"estimated groups {est} >= threshold {thresh}"
    return False, est, f"estimated groups {est} < threshold {thresh}"


def _range_table(client, opts) -> list:
    """[[wid, host, port], ...] — shuffle range i's owner and serving
    address under the CURRENT route. sql.cluster.shuffle.ranges sizes R
    (0 = one range per live worker); ranges deal round-robin so every
    worker folds ~1/W of the group domain."""
    from ..options import CoreOptions

    live = client.live_workers()
    if not live:
        raise ConnectionError("no live workers for shuffle range assignment")
    nr = int(opts.get(CoreOptions.SQL_CLUSTER_SHUFFLE_RANGES)) or len(live)
    return [[w, *client.addr_of(w)] for w in (live[i % len(live)] for i in range(nr))]


# test seam: callable(stage, ctx) invoked at named points of the shuffle
# orchestration ("post-scatter" — after summaries, before any combine).
# The mid-shuffle-death tests kill a range owner here; None in production.
_SHUFFLE_TEST_HOOK = None


# ---------------------------------------------------------------------------
# fragment result cache: aggregate partials are immutable once the snapshot
# they scanned is pinned, so repeated aggregates over an unchanged table skip
# the scatter entirely. Keyed per table path on (snapshot_id, bucket-layout
# epoch, signature); a plan at a NEWER snapshot or a DIFFERENT layout purges
# the table's older entries. The layout key closes the live-rescale hole
# (ISSUE 20 satellite): an 8→16 rescale rewrites every bucket's file set
# under a schema bump — a coordinator still holding the pre-rescale table
# object must never serve its stale split set's partials from cache.
# ---------------------------------------------------------------------------
_FRAG_CACHE_LOCK = threading.Lock()
_FRAG_CACHE: dict[str, tuple[int, str, dict[str, list]]] = {}


def clear_fragment_cache() -> None:
    """Drop every cached partial (tests / manual invalidation)."""
    with _FRAG_CACHE_LOCK:
        _FRAG_CACHE.clear()


def _table_layout(t) -> str:
    """Bucket-layout (rescale) epoch of a table object: schema id + bucket
    count. table.rescale commits the new count as a schema bump, so a
    cached partial planned under the old layout keys differently even when
    its data snapshot id coincides."""
    try:
        return f"{int(t.schema.id)}:{int(t.store.options.bucket)}"
    except Exception:  # noqa: BLE001 — no stable layout: cache still snap-keyed
        return "?"


def _fragment_signature(template: dict, by_wid: dict, layout: str = "?"):
    """(snapshot_id, layout, sha1) identity of one aggregate scatter: the
    template's semantic core plus every planned split (seq, partition,
    bucket, files) under the table's bucket-layout epoch. Returns None when
    any split carries no snapshot pin — nothing stable to key on — so
    unpinned plans always scatter."""
    snaps: set = set()
    ids: list = []
    for wid in sorted(by_wid):
        for seq, sd in by_wid[wid]:
            snap = sd.get("snapshotId")
            if snap is None:
                return None
            snaps.add(int(snap))
            ids.append(
                [
                    int(seq),
                    list(sd.get("partition") or []),
                    int(sd["bucket"]),
                    sorted(
                        json.dumps(f, sort_keys=True, default=str)
                        for f in sd.get("files", [])
                    ),
                ]
            )
    if not snaps:
        return None
    core = {
        k: template.get(k)
        for k in ("mode", "where", "projection", "group_cols", "kern", "engine", "code_domain")
    }
    blob = json.dumps([core, ids, layout], sort_keys=True, default=str)
    return max(snaps), layout, hashlib.sha1(blob.encode()).hexdigest()


def _frag_cache_get(path: str, key):
    if key is None:
        return None
    snap, layout, sig = key
    with _FRAG_CACHE_LOCK:
        ent = _FRAG_CACHE.get(path)
        if ent is not None and ent[0] == snap and ent[1] == layout:
            return ent[2].get(sig)
    return None


def _frag_cache_put(path: str, key, raw: list) -> None:
    if key is None:
        return
    snap, layout, sig = key
    with _FRAG_CACHE_LOCK:
        ent = _FRAG_CACHE.get(path)
        if ent is None or ent[0] < snap or (ent[0] == snap and ent[1] != layout):
            # snapshot advanced OR layout rescaled at the same snapshot:
            # purge — partials planned under the old layout are unreachable
            ent = (snap, layout, {})
            _FRAG_CACHE[path] = ent
        if ent[0] == snap and ent[1] == layout:
            ent[2][sig] = raw


def _explain_cluster(catalog: "Catalog", statement: str, client):
    """EXPLAIN through the cluster planner: the local explain lines (files
    pruned, pushed predicates/projection/LIMIT) plus the fragment -> worker
    assignment under the current route and the code-domain toggle."""
    from ..options import CoreOptions

    plan, t, lines, splits = explain_plan(catalog, statement)
    lines = list(lines)
    fm = plan.from_match
    if (
        plan.is_join
        or t is None
        or fm is None
        or fm.group("hints")
        or fm.group("tt_kind")
        or not hasattr(t, "new_read_builder")
        or t.path != client.table.path
    ):
        lines.append("cluster: local fallback (shape not served by the fragment protocol)")
        return plan_batch(lines)
    opts = t.store.options.options
    code_domain = resolve_code_domain(opts.get(CoreOptions.SQL_CLUSTER_CODE_DOMAIN))
    lines.append(f"cluster: code-domain {'on' if code_domain else 'off'}")
    by_wid: dict = {}
    for sp in splits or []:
        by_wid.setdefault(client.owner_of(int(sp.bucket)), []).append(sp)
    if not by_wid:
        lines.append("cluster: no splits to scatter")
    for wid in sorted(by_wid):
        sps = by_wid[wid]
        files = sum(len(sp.files) for sp in sps)
        buckets = ", ".join(str(b) for b in sorted({int(sp.bucket) for sp in sps}))
        lines.append(
            f"fragment -> worker {wid}: {len(sps)} splits, {files} files (buckets {buckets})"
        )
    # shuffle plan (ISSUE 20 satellite): the SAME decision code the executor
    # runs, so what EXPLAIN prints is what cluster_query will do
    if plan.group_cols and not plan.is_join:
        by_wid_d = {
            wid: [(i, sp.to_dict()) for i, sp in enumerate(sps)]
            for wid, sps in by_wid.items()
        }
        on, est, why = _decide_shuffle(t, client, opts, plan.group_cols, by_wid_d)
        if on:
            ranges = _range_table(client, opts)
            lines.append(
                f"shuffle: on ({why}), estimated groups {est}, {len(ranges)} ranges"
            )
            for i, (w, _h, _p) in enumerate(ranges):
                lines.append(f"  range {i} -> worker {w}")
        else:
            lines.append(f"shuffle: off ({why})")
    return plan_batch(lines)


def cluster_query(
    catalog: "Catalog",
    statement: str,
    client,
    busy_wait_s: float = 10.0,
    scan_frag_fn=None,
):
    """Execute one SELECT across the cluster-service workers; returns the
    result ColumnBatch, bit-identical to sql.select.query on the same
    catalog. Falls back to the single-process evaluator for shapes the
    fragment protocol does not cover (system tables, per-query OPTIONS
    hints / time travel, a table the client does not serve, non-numeric
    aggregate arguments). JOIN queries distribute through the ops.join
    partition-executor seam (worker-side join_part kernels) instead."""
    from ..data.batch import ColumnBatch, concat_batches
    from ..metrics import sql_metrics
    from ..options import CoreOptions

    m = _EXPLAIN_RE.match(statement)
    if m:
        return _explain_cluster(catalog, statement[m.end():], client)
    p = parse_select(statement)
    if p.is_join:
        from ..ops.join import partition_executor

        with partition_executor(client.partition_executor()):
            return query(catalog, statement)
    fm = p.from_match
    if fm.group("hints") or fm.group("tt_kind"):
        return query(catalog, statement)
    t = catalog.get_table(p.table_name)
    if not hasattr(t, "new_read_builder") or t.path != client.table.path:
        return query(catalog, statement)

    opts = t.store.options.options
    code_domain = resolve_code_domain(opts.get(CoreOptions.SQL_CLUSTER_CODE_DOMAIN))
    retry_ms = int(opts.get(CoreOptions.SQL_CLUSTER_RETRY_TIMEOUT))
    frag_cache = bool(opts.get(CoreOptions.SQL_CLUSTER_FRAGMENT_CACHE))
    engine = _engine_for(t)
    g = sql_metrics()
    # coordinator-side serial combine work (ms) accumulated across the query:
    # payload decode + second-stage combine (or shuffle range concat) — the
    # stage the shuffle plane exists to shrink, surfaced as sql{combine_ms}.
    # list.append is atomic, so the shuffle fetch threads share it safely.
    ser_ms: list = []
    if p.where_text:  # surface parse errors before any RPC, like query()
        try:
            to_predicate(parse_expr(p.where_text), p.where_text)
        except ExprError as e:
            raise QueryError(str(e)) from e

    def _plan_frags(projection, limit_push):
        rb = t.new_read_builder()
        if p.where_text:
            rb = rb.with_filter(to_predicate(parse_expr(p.where_text), p.where_text))
        if projection is not None:
            for n in projection:
                if n not in t.row_type:
                    raise QueryError(f"unknown column {n!r} in {p.table_name}")
            rb = rb.with_projection(list(projection))
        if limit_push is not None:
            rb = rb.with_limit(limit_push)
        by_wid: dict = {}
        for seq, sp in enumerate(rb.new_scan().plan()):
            by_wid.setdefault(client.owner_of(int(sp.bucket)), []).append((seq, sp.to_dict()))
        return by_wid

    def _kern_or_fallback(aggs2):
        kern, imap = _agg_kernel_plan(aggs2)
        for fn, col in kern:
            if fn == "count" and col == "*":
                continue
            if col not in t.row_type:
                raise QueryError(f"unknown column {col!r} in {p.table_name}")
            if fn != "count" and np.dtype(t.row_type.field(col).type.numpy_dtype()).kind not in "iuf":
                raise _LocalFallback
        return kern, imap

    def _gather_agg(projection, group_cols, kern, by_wid=None):
        template = {
            "mode": "agg",
            "where": p.where_text,
            "projection": projection,
            "group_cols": group_cols,
            "kern": kern,
            "engine": engine,
            "code_domain": code_domain,
        }
        if by_wid is None:
            by_wid = _plan_frags(projection, None)
        key = _fragment_signature(template, by_wid, _table_layout(t)) if frag_cache else None
        raw = _frag_cache_get(str(t.path), key)
        if raw is not None:
            g.counter("fragment_cache_hits").inc(1)
        else:
            t0 = time.perf_counter()
            raw = _scatter(client, by_wid, template, retry_ms, busy_wait_s, scan_frag_fn)
            g.histogram("scatter_ms").update((time.perf_counter() - t0) * 1000)
            _frag_cache_put(str(t.path), key, raw)
        schema = t.row_type.project(projection)
        t1 = time.perf_counter()
        parts = [decode_partial(r, schema, group_cols) for r in raw]
        ser_ms.append((time.perf_counter() - t1) * 1000)
        parts = [q for q in parts if q["rows"]]
        for q in parts:
            g.counter("rows_reduced_device").inc(q["rows_reduced_device"])
        return schema, parts

    def _combine(parts, group_cols, kern):
        """Second-stage reduce over the partial rows, keyed on the UNIFIED
        code domain (combine_partials, shared with the shuffle range
        owners); returns (pools, codes, outs, anyv, first_pos) in the
        _assemble_group_batch contract."""
        t1 = time.perf_counter()
        out = combine_partials(parts, len(group_cols), kern, engine)
        ser_ms.append((time.perf_counter() - t1) * 1000)
        g.counter("partials_combined").inc(len(parts))
        if code_domain and group_cols:
            g.counter("code_domain_groups").inc(sum(len(q["first_pos"]) for q in parts))
        return out

    def _shuffle_agg(projection, group_cols, kern, by_wid, schema):
        """The ISSUE 20 tentpole orchestration. Scatter shuffle-mode
        fragments (each worker partitions its partial by group-key value
        and ships range i to range i's owner, answering a summary), build
        the per-range expectation lists from the summaries' sent maps
        (empty parts are never shipped, so only shipped parts are waited
        on), fold every range at its owner in parallel, and concatenate the
        R reduced ranges. A dead range owner re-homes to a live worker and
        the sources reship their buffered parts; a dead source re-executes
        its fragment under the SAME src id (content deterministic, delivery
        keyed — overwrites are idempotent), all under retry_ms."""
        qid = f"q{os.urandom(8).hex()}"
        ranges = _range_table(client, opts)
        t0 = time.perf_counter()
        g.counter("shuffle_rounds").inc()
        deadline = time.monotonic() + retry_ms / 1000.0
        src_info: dict = {}
        ctr = [0]

        def decorate(frag, wid, items):
            ctr[0] += 1
            src = f"{qid}#{ctr[0]}"
            src_info[src] = {"wid": wid, "splits": items}
            return dict(frag, src=src, shuffle={"qid": qid, "ranges": [list(r) for r in ranges]})

        template = {
            "mode": "agg",
            "where": p.where_text,
            "projection": projection,
            "group_cols": group_cols,
            "kern": kern,
            "engine": engine,
            "code_domain": code_domain,
        }
        raw = _scatter(
            client, by_wid, template, retry_ms, busy_wait_s, scan_frag_fn, decorate=decorate
        )
        summaries = [r for r in raw if r.get("mode") == "shuffle"]
        expects: dict = {r: [] for r in range(len(ranges))}
        for s in summaries:
            g.counter("rows_reduced_device").inc(int(s.get("rows_reduced_device", 0)))
            g.counter("parts_exchanged").inc(len(s.get("sent") or {}))
            g.counter("exchange_bytes").inc(int(s.get("bytes", 0)))
            for rs in s.get("sent") or {}:
                expects[int(rs)].append(s["src"])
        hook = _SHUFFLE_TEST_HOOK
        if hook is not None:
            hook("post-scatter", {"qid": qid, "ranges": ranges, "expects": expects})

        def _reexec_src(src, call):
            """Re-run one source fragment whole on ANY live worker (shared
            FS serves any split anywhere) — all its splits in ONE fragment,
            or two workers would overwrite each other under one src id."""
            frag = encode_fragment(
                dict(
                    template,
                    splits=src_info[src]["splits"],
                    src=src,
                    shuffle={"qid": qid, "ranges": [list(r) for r in ranges]},
                )
            )
            while True:
                for w in client.live_workers():
                    try:
                        rsp = call(w, frag, busy_wait_s)
                    except (ConnectionError, OSError, TimeoutError):
                        client.drop_conn(w)
                        continue
                    if rsp.get("mode") == "shuffle":
                        g.counter("parts_exchanged").inc(len(rsp.get("sent") or {}))
                        g.counter("exchange_bytes").inc(int(rsp.get("bytes", 0)))
                    return
                if time.monotonic() >= deadline:
                    raise TimeoutError(f"shuffle source {src} unrecoverable")
                time.sleep(0.05)
                try:
                    client.refresh_route()
                except (ConnectionError, OSError):
                    continue

        def _replace_owner(rng):
            """Re-home a dead range onto a live worker under a refreshed
            route; its expected parts reship/re-execute on the next probe."""
            while True:
                if time.monotonic() >= deadline:
                    raise TimeoutError(f"shuffle range {rng} owner unrecoverable")
                time.sleep(0.05)
                try:
                    client.refresh_route()
                    live = client.live_workers()
                    if not live:
                        continue
                    w = live[rng % len(live)]
                    ranges[rng] = [w, *client.addr_of(w)]
                    return
                except (ConnectionError, OSError):
                    continue

        call = scan_frag_fn if scan_frag_fn is not None else client.scan_frag

        def _fetch_range(rng):
            """Fold range `rng` at its owner, healing owner death and
            missing parts until the deadline. Returns the decoded partial."""
            while True:
                wid = int(ranges[rng][0])
                try:
                    partial, missing = client.exchange_combine(
                        wid,
                        qid,
                        rng,
                        expects[rng],
                        group_cols,
                        kern,
                        engine,
                        code_domain,
                        projection,
                        busy_wait_s=busy_wait_s,
                    )
                except (ConnectionError, OSError, TimeoutError):
                    client.drop_conn(wid)
                    if time.monotonic() >= deadline:
                        raise
                    g.counter("shuffle_retried").inc()
                    _replace_owner(rng)
                    continue
                if partial is not None:
                    td = time.perf_counter()
                    dec = decode_partial(partial, schema, group_cols)
                    ser_ms.append((time.perf_counter() - td) * 1000)
                    return dec
                if time.monotonic() >= deadline:
                    raise TimeoutError(f"shuffle range {rng}: parts missing {missing}")
                # in-flight delivery loss or a fresh replacement owner:
                # reship each missing part from its source's buffer, falling
                # back to fragment re-execution when the buffer died too
                g.counter("shuffle_retried").inc()
                host, port = ranges[rng][1], int(ranges[rng][2])
                for src in missing:
                    info = src_info.get(src)
                    shipped = info is not None and client.exchange_reship(
                        info["wid"], qid, rng, src, host, port
                    )
                    if not shipped:
                        _reexec_src(src, call)
                time.sleep(0.02)

        pending = [r for r in range(len(ranges)) if expects[r]]
        parts = []
        try:
            if pending:
                with ThreadPoolExecutor(max_workers=len(pending)) as ex:
                    futs = [ex.submit(_fetch_range, r) for r in pending]
                    parts = [f.result() for f in futs]
            parts = [q for q in parts if q["rows"]]
            if not parts:
                return None
            tc = time.perf_counter()
            out = _concat_ranges(parts, len(group_cols))
            ser_ms.append((time.perf_counter() - tc) * 1000)
            g.counter("partials_combined").inc(len(parts))
            if code_domain and group_cols:
                g.counter("code_domain_groups").inc(sum(len(q["first_pos"]) for q in parts))
            g.histogram("shuffle_ms").update((time.perf_counter() - t0) * 1000)
            return out
        finally:
            involved = {int(r[0]) for r in ranges} | {
                i["wid"] for i in src_info.values()
            }
            client.exchange_close(qid, sorted(involved))

    def group_reduce(items2, aggs2):
        from .select import _group_aggregate

        for gc in p.group_cols:
            if gc not in t.row_type:
                raise QueryError(f"unknown GROUP BY column {gc!r}")
        kern, imap = _kern_or_fallback(aggs2)
        projection = list(
            dict.fromkeys(p.group_cols + [c for fn, c in kern if c != "*"])
        )
        by_wid = _plan_frags(projection, None)
        shuffle_on, _est, _why = _decide_shuffle(t, client, opts, p.group_cols, by_wid)
        if shuffle_on:
            schema = t.row_type.project(projection)
            combined = _shuffle_agg(projection, p.group_cols, kern, by_wid, schema)
            if combined is None:
                return _group_aggregate(
                    ColumnBatch.empty(schema), items2, aggs2, p.group_cols, engine=engine
                )
            pools, codes, outs, anyv, first_pos = combined
            t1 = time.perf_counter()
            out = _assemble_group_batch(
                t.row_type, items2, aggs2, imap, p.group_cols, pools, codes, outs, anyv, first_pos
            )
            g.histogram("combine_ms").update(
                sum(ser_ms) + (time.perf_counter() - t1) * 1000
            )
            return out
        schema, parts = _gather_agg(projection, p.group_cols, kern, by_wid)
        if not parts:
            return _group_aggregate(
                ColumnBatch.empty(schema), items2, aggs2, p.group_cols, engine=engine
            )
        pools, codes, outs, anyv, first_pos = _combine(parts, p.group_cols, kern)
        t1 = time.perf_counter()
        out = _assemble_group_batch(
            t.row_type, items2, aggs2, imap, p.group_cols, pools, codes, outs, anyv, first_pos
        )
        g.histogram("combine_ms").update(
            sum(ser_ms) + (time.perf_counter() - t1) * 1000
        )
        return out

    def scalar_reduce(items, aggs):
        from .select import _aggregate

        from ..types import BIGINT, DOUBLE, DataField, RowType

        kern, imap = _kern_or_fallback(aggs)
        projection = list(dict.fromkeys(c for _, c in kern if c != "*"))
        if not projection:
            projection = [t.row_type.field_names[0]]
        schema, parts = _gather_agg(projection, [], kern)
        if not parts:
            return _aggregate(ColumnBatch.empty(schema), items, aggs)
        _, _, outs, anyv, _ = _combine(parts, [], kern)
        t1 = time.perf_counter()
        # reproduce sql.select._aggregate's scalar semantics exactly: one
        # row always; an aggregate with no valid input is NULL typed DOUBLE
        names, types, values = [], [], []
        for item, agg, spec in zip(items, aggs, imap):
            label = re.sub(r"\s+", "", item).lower()
            if spec[0] == "count":
                v, ty = int(outs[spec[1]][0]), BIGINT()
            elif spec[0] == "avg":
                c = outs[spec[2]][0]
                v = float(outs[spec[1]][0] / c) if c else None
                ty = DOUBLE()
            else:
                ki = spec[1]
                if bool(anyv[ki][0]):
                    v, ty = outs[ki][0].item(), t.row_type.field(agg[1]).type
                else:
                    v, ty = None, DOUBLE()
            names.append(label)
            types.append(ty)
            values.append(v)
        rt = RowType(
            tuple(DataField(i, nm, ty) for i, (nm, ty) in enumerate(zip(names, types)))
        )
        out = ColumnBatch.from_pydict(rt, {nm: [v] for nm, v in zip(names, values)})
        g.histogram("combine_ms").update(
            sum(ser_ms) + (time.perf_counter() - t1) * 1000
        )
        return out

    if p.group_cols or p.is_agg:
        try:
            return _finish(
                None,
                p.items,
                p.aggs,
                p.is_agg,
                p.group_cols,
                p.order_text,
                p.limit,
                p.cols_text,
                having_text=p.having_text,
                engine=engine,
                group_reduce=group_reduce if p.group_cols else None,
                scalar_reduce=scalar_reduce if not p.group_cols else None,
            )
        except _LocalFallback:
            return query(catalog, statement)

    # ---- non-aggregate: stream row batches back, finish at the coordinator
    projection = None
    if p.cols_text != "*":
        names = [i.strip("`") for i in p.items]
        for n in names:
            if n not in t.row_type:
                raise QueryError(f"unknown column {n!r} in {p.table_name}")
        projection = list(dict.fromkeys(names + _order_cols(p.order_text)))
    limit_push = p.limit if p.order_text is None else None
    template = {
        "mode": "rows",
        "where": p.where_text,
        "projection": projection,
        "limit": limit_push,
        "engine": engine,
    }
    t0 = time.perf_counter()
    raw = _scatter(
        client, _plan_frags(projection, limit_push), template, retry_ms, busy_wait_s, scan_frag_fn
    )
    g.histogram("scatter_ms").update((time.perf_counter() - t0) * 1000)
    schema = t.row_type.project(projection) if projection is not None else t.row_type
    t1 = time.perf_counter()
    batches: list = []
    total = 0
    for r in raw:
        dec = decode_partial(r, schema)
        batches.extend(dec["batches"])
        total += dec["rows"]
    batches.sort(key=lambda sb: sb[0])  # global row order = split seq order
    out = concat_batches([b for _, b in batches]) if batches else ColumnBatch.empty(schema)
    g.counter("rows_streamed").inc(total)
    out = _finish(out, p.items, p.aggs, False, [], p.order_text, p.limit, p.cols_text, engine=engine)
    g.histogram("combine_ms").update((time.perf_counter() - t1) * 1000)
    return out
