"""Distributed SQL (ISSUE 16): scatter-gather scans with compressed-domain
partial aggregation on the cluster-service workers.

The coordinator side of the fragment protocol. One SELECT plans exactly like
the local evaluator (sql.select.parse_select — every semantic decision is
shared), then the scan splits scatter to the workers owning their buckets
over the cluster-service wire (service.cluster `scan_frag` beside
get_batch/join_part). Each worker scans its splits with predicate +
projection pushdown and:

* aggregate queries — segment-reduces the fragment into ONE partial
  aggregate per group on device (ops.aggregates.segment_reduce keyed on
  dictionary codes), shipping the group keys back as (pruned pool, uint32
  codes, partial rows). The coordinator combines in the code domain:
  ops.dicts.unify_pools merges the per-worker pools, remap_codes re-ranks
  the codes, and a second segment_reduce over the partial rows composes
  counts/sums by addition and min/max by min/max (_KERNEL_COMBINE). Row
  positions are global (split seq << 40 + row), so the combined
  first-appearance order is exactly the single-process one — results are
  bit-identical to the local oracle by construction.
* non-aggregate queries — streams the row batches back Arrow-encoded; the
  coordinator reassembles them in global split order and runs the same
  ORDER/LIMIT/projection tail.

`sql.cluster.code-domain` (or PAIMON_TPU_SQL_CODE_DOMAIN) toggles the
compressed combine: off, workers expand group-key values on the wire and
the coordinator re-encodes them through the identical ops.dicts path — the
verify stage forces both and asserts equal results.

Failover: a fragment whose worker dies (ConnectionError) returns its splits
to the pending pool; the coordinator refreshes the route (the cluster
coordinator reassigns the dead worker's buckets on missed heartbeats) and
re-dispatches to the new owners until `sql.cluster.retry-timeout` expires.
Typed-BUSY sheds (`sql.cluster.scan.max-inflight`) retry inside
ClusterClient.scan_frag with the server-advertised backoff.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import TYPE_CHECKING

import numpy as np

from .expr import ExprError, parse_expr, to_predicate
from .select import (
    _EXPLAIN_RE,
    _KERNEL_COMBINE,
    QueryError,
    _agg_kernel_plan,
    _assemble_group_batch,
    _engine_for,
    _finish,
    _order_cols,
    explain_plan,
    parse_select,
    plan_batch,
    query,
)

if TYPE_CHECKING:
    from ..catalog import Catalog

__all__ = [
    "cluster_query",
    "clear_fragment_cache",
    "resolve_code_domain",
    "encode_fragment",
    "decode_fragment",
    "encode_partial",
    "decode_partial",
]


def resolve_code_domain(enabled) -> bool:
    """One resolution order (the ops.dicts.resolve_dict_domain shape): the
    PAIMON_TPU_SQL_CODE_DOMAIN env var (verify forces both paths) beats the
    sql.cluster.code-domain option value, which beats the default (on)."""
    env = os.environ.get("PAIMON_TPU_SQL_CODE_DOMAIN", "").strip().lower()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    if enabled is None:
        return True
    if isinstance(enabled, str):
        return enabled.strip().lower() in ("1", "on", "true")
    return bool(enabled)


# ---------------------------------------------------------------------------
# wire codecs: fragments coordinator->worker, partials worker->coordinator
# (length-prefixed JSON transport: arrays ride base64, row batches Arrow IPC)
# ---------------------------------------------------------------------------
def _b64(arr: np.ndarray) -> dict:
    a = np.ascontiguousarray(arr)
    return {"d": base64.b64encode(a.tobytes()).decode(), "t": str(a.dtype), "s": list(a.shape)}


def _unb64(d: dict) -> np.ndarray:
    return np.frombuffer(base64.b64decode(d["d"]), dtype=np.dtype(d["t"])).reshape(d["s"])


def _encode_pool(pool: np.ndarray) -> dict:
    if pool.dtype == np.dtype(object):
        return {"obj": pool.tolist()}
    return {"arr": _b64(pool)}


def _decode_pool(d: dict) -> np.ndarray:
    if "obj" in d:
        pool = np.empty(len(d["obj"]), dtype=object)
        for i, v in enumerate(d["obj"]):
            pool[i] = v
        return pool
    return _unb64(d["arr"])


def encode_fragment(frag: dict) -> dict:
    """Fragment -> JSON-safe wire dict (splits are already DataSplit.to_dict
    payloads; kern tuples flatten to lists)."""
    wire = dict(frag)
    if wire.get("kern") is not None:
        wire["kern"] = [list(k) for k in wire["kern"]]
    return wire


def decode_fragment(d: dict) -> dict:
    """Wire dict -> fragment (table.query.execute_scan_fragment re-tuples
    kern and rebuilds the DataSplits itself)."""
    return dict(d)


def encode_partial(part: dict, code_domain: bool = True) -> dict:
    """Worker-side: numpy-level partial -> wire dict. Aggregate partials
    ship pools+codes in the code domain (or expanded values when the toggle
    is off); row partials ship per-split Arrow IPC streams."""
    if part["mode"] == "rows":
        import pyarrow as pa

        batches = []
        for seq, b in part["batches"]:
            at = b.to_arrow()
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, at.schema) as w:
                w.write_table(at)
            batches.append([int(seq), base64.b64encode(sink.getvalue().to_pybytes()).decode()])
        return {"mode": "rows", "rows": int(part["rows"]), "batches": batches}
    enc = {
        "mode": "agg",
        "rows": int(part["rows"]),
        "rows_reduced_device": int(part.get("rows_reduced_device", 0)),
        "outs": [_b64(np.asarray(o)) for o in part["outs"]],
        "anyv": [_b64(np.asarray(a)) for a in part["anyv"]],
        "first_pos": _b64(part["first_pos"]),
    }
    if code_domain:
        enc["pools"] = [_encode_pool(p) for p in part["pools"]]
        enc["group_codes"] = [_b64(c) for c in part["group_codes"]]
    else:
        vals = []
        for pool, codes in zip(part["pools"], part["group_codes"]):
            sent = len(pool)
            col = []
            for c in codes.tolist():
                if c == sent:
                    col.append(None)
                else:
                    v = pool[c]
                    col.append(v.item() if hasattr(v, "item") else v)
            vals.append(col)
        enc["vals"] = vals
    return enc


def decode_partial(d: dict, schema, group_cols=()) -> dict:
    """Coordinator-side: wire dict -> numpy-level partial. Expanded group
    keys (code-domain off) re-encode through the SAME ops.dicts.encode_column
    path the workers use, so the combine below is identical either way."""
    if d["mode"] == "rows":
        import pyarrow as pa

        from ..data.batch import ColumnBatch

        batches = []
        for seq, blob in d["batches"]:
            at = pa.ipc.open_stream(pa.BufferReader(base64.b64decode(blob))).read_all()
            batches.append((int(seq), ColumnBatch.from_arrow(at, schema)))
        return {"mode": "rows", "rows": int(d["rows"]), "batches": batches}
    out = {
        "mode": "agg",
        "rows": int(d["rows"]),
        "rows_reduced_device": int(d.get("rows_reduced_device", 0)),
        "outs": [_unb64(o) for o in d["outs"]],
        "anyv": [_unb64(a) for a in d["anyv"]],
        "first_pos": _unb64(d["first_pos"]),
    }
    if "vals" in d:
        from ..data.batch import Column
        from ..ops.dicts import encode_column

        pools, group_codes = [], []
        for g, vs in zip(group_cols, d["vals"]):
            pool, codes = encode_column(Column.from_pylist(vs, schema.field(g).type))
            pools.append(pool)
            group_codes.append(codes)
        out["pools"], out["group_codes"] = pools, group_codes
    else:
        out["pools"] = [_decode_pool(p) for p in d.get("pools", [])]
        out["group_codes"] = [
            _unb64(c).astype(np.uint32, copy=False) for c in d.get("group_codes", [])
        ]
    return out


# ---------------------------------------------------------------------------
# coordinator: plan -> scatter -> combine
# ---------------------------------------------------------------------------
class _LocalFallback(Exception):
    """Raised mid-plan when a query shape cannot route through fragments
    (non-numeric aggregate argument: the host reduceat path owns it) — the
    caller falls back to the single-process evaluator."""


def _scatter(
    client,
    pending: dict,
    template: dict,
    retry_ms: int,
    busy_wait_s: float,
    scan_frag_fn=None,
):
    """Dispatch one fragment per owning worker, failover on dead
    connections: failed fragments' splits return to the pool, the route
    refreshes (the coordinator reassigns dead workers' buckets) and the
    splits regroup under their new owners until retry_ms expires.

    `scan_frag_fn` swaps the per-fragment RPC (same (wid, frag,
    busy_wait_s) contract as ClusterClient.scan_frag) — the gateway
    threads its hedged variant through here so scan fragments race a
    secondary worker past the hedge deadline."""
    from ..metrics import sql_metrics

    g = sql_metrics()
    call = scan_frag_fn if scan_frag_fn is not None else client.scan_frag
    deadline = time.monotonic() + retry_ms / 1000.0
    results: list[dict] = []
    round_no = 0
    while pending:
        g.counter("fragments").inc(len(pending))
        if round_no:
            g.counter("fragments_retried").inc(len(pending))
        round_no += 1
        with ThreadPoolExecutor(max_workers=max(len(pending), 1)) as ex:
            futs = {
                wid: ex.submit(
                    call,
                    wid,
                    encode_fragment(dict(template, splits=items)),
                    busy_wait_s,
                )
                for wid, items in pending.items()
            }
            failed: list = []
            for wid, fut in futs.items():
                try:
                    results.append(fut.result())
                except (ConnectionError, OSError, TimeoutError):
                    failed.extend(pending[wid])
                    client.drop_conn(wid)
        if not failed:
            break
        # regroup under the refreshed route; the dead worker's buckets move
        # once the coordinator times out its heartbeats, so keep trying
        while True:
            if time.monotonic() >= deadline:
                raise QueryError(
                    f"scan fragments undeliverable after {retry_ms} ms "
                    f"({len(failed)} splits pending)"
                )
            time.sleep(0.05)
            try:
                client.refresh_route()
                regrouped: dict = {}
                for seq, sd in failed:
                    wid = client.owner_of(int(sd["bucket"]))
                    regrouped.setdefault(wid, []).append((seq, sd))
                pending = regrouped
                break
            except (KeyError, ConnectionError, OSError):
                continue
    return results


def _sentinel_remap(remap, pool_len: int, unified_len: int) -> np.ndarray:
    """Extend a unify_pools gather table with the NULL sentinel: input code
    `pool_len` (NULL) maps to unified code `unified_len`."""
    base = remap if remap is not None else np.arange(pool_len, dtype=np.int64)
    return np.concatenate([np.asarray(base, dtype=np.int64), [unified_len]]).astype(np.uint32)


# ---------------------------------------------------------------------------
# fragment result cache: aggregate partials are immutable once the snapshot
# they scanned is pinned, so repeated aggregates over an unchanged table skip
# the scatter entirely. Keyed per table path on (snapshot_id, signature);
# any plan at a NEWER snapshot purges the table's older entries.
# ---------------------------------------------------------------------------
_FRAG_CACHE_LOCK = threading.Lock()
_FRAG_CACHE: dict[str, tuple[int, dict[str, list]]] = {}


def clear_fragment_cache() -> None:
    """Drop every cached partial (tests / manual invalidation)."""
    with _FRAG_CACHE_LOCK:
        _FRAG_CACHE.clear()


def _fragment_signature(template: dict, by_wid: dict):
    """(snapshot_id, sha1) identity of one aggregate scatter: the template's
    semantic core plus every planned split (seq, partition, bucket, files).
    Returns None when any split carries no snapshot pin — nothing stable to
    key on — so unpinned plans always scatter."""
    snaps: set = set()
    ids: list = []
    for wid in sorted(by_wid):
        for seq, sd in by_wid[wid]:
            snap = sd.get("snapshotId")
            if snap is None:
                return None
            snaps.add(int(snap))
            ids.append(
                [
                    int(seq),
                    list(sd.get("partition") or []),
                    int(sd["bucket"]),
                    sorted(
                        json.dumps(f, sort_keys=True, default=str)
                        for f in sd.get("files", [])
                    ),
                ]
            )
    if not snaps:
        return None
    core = {
        k: template.get(k)
        for k in ("mode", "where", "projection", "group_cols", "kern", "engine", "code_domain")
    }
    blob = json.dumps([core, ids], sort_keys=True, default=str)
    return max(snaps), hashlib.sha1(blob.encode()).hexdigest()


def _frag_cache_get(path: str, key):
    if key is None:
        return None
    snap, sig = key
    with _FRAG_CACHE_LOCK:
        ent = _FRAG_CACHE.get(path)
        if ent is not None and ent[0] == snap:
            return ent[1].get(sig)
    return None


def _frag_cache_put(path: str, key, raw: list) -> None:
    if key is None:
        return
    snap, sig = key
    with _FRAG_CACHE_LOCK:
        ent = _FRAG_CACHE.get(path)
        if ent is None or ent[0] < snap:  # snapshot advanced: purge stale partials
            ent = (snap, {})
            _FRAG_CACHE[path] = ent
        if ent[0] == snap:
            ent[1][sig] = raw


def _explain_cluster(catalog: "Catalog", statement: str, client):
    """EXPLAIN through the cluster planner: the local explain lines (files
    pruned, pushed predicates/projection/LIMIT) plus the fragment -> worker
    assignment under the current route and the code-domain toggle."""
    from ..options import CoreOptions

    plan, t, lines, splits = explain_plan(catalog, statement)
    lines = list(lines)
    fm = plan.from_match
    if (
        plan.is_join
        or t is None
        or fm is None
        or fm.group("hints")
        or fm.group("tt_kind")
        or not hasattr(t, "new_read_builder")
        or t.path != client.table.path
    ):
        lines.append("cluster: local fallback (shape not served by the fragment protocol)")
        return plan_batch(lines)
    opts = t.store.options.options
    code_domain = resolve_code_domain(opts.get(CoreOptions.SQL_CLUSTER_CODE_DOMAIN))
    lines.append(f"cluster: code-domain {'on' if code_domain else 'off'}")
    by_wid: dict = {}
    for sp in splits or []:
        by_wid.setdefault(client.owner_of(int(sp.bucket)), []).append(sp)
    if not by_wid:
        lines.append("cluster: no splits to scatter")
    for wid in sorted(by_wid):
        sps = by_wid[wid]
        files = sum(len(sp.files) for sp in sps)
        buckets = ", ".join(str(b) for b in sorted({int(sp.bucket) for sp in sps}))
        lines.append(
            f"fragment -> worker {wid}: {len(sps)} splits, {files} files (buckets {buckets})"
        )
    return plan_batch(lines)


def cluster_query(
    catalog: "Catalog",
    statement: str,
    client,
    busy_wait_s: float = 10.0,
    scan_frag_fn=None,
):
    """Execute one SELECT across the cluster-service workers; returns the
    result ColumnBatch, bit-identical to sql.select.query on the same
    catalog. Falls back to the single-process evaluator for shapes the
    fragment protocol does not cover (system tables, per-query OPTIONS
    hints / time travel, a table the client does not serve, non-numeric
    aggregate arguments). JOIN queries distribute through the ops.join
    partition-executor seam (worker-side join_part kernels) instead."""
    from ..data.batch import ColumnBatch, concat_batches
    from ..metrics import sql_metrics
    from ..options import CoreOptions

    m = _EXPLAIN_RE.match(statement)
    if m:
        return _explain_cluster(catalog, statement[m.end():], client)
    p = parse_select(statement)
    if p.is_join:
        from ..ops.join import partition_executor

        with partition_executor(client.partition_executor()):
            return query(catalog, statement)
    fm = p.from_match
    if fm.group("hints") or fm.group("tt_kind"):
        return query(catalog, statement)
    t = catalog.get_table(p.table_name)
    if not hasattr(t, "new_read_builder") or t.path != client.table.path:
        return query(catalog, statement)

    opts = t.store.options.options
    code_domain = resolve_code_domain(opts.get(CoreOptions.SQL_CLUSTER_CODE_DOMAIN))
    retry_ms = int(opts.get(CoreOptions.SQL_CLUSTER_RETRY_TIMEOUT))
    frag_cache = bool(opts.get(CoreOptions.SQL_CLUSTER_FRAGMENT_CACHE))
    engine = _engine_for(t)
    g = sql_metrics()
    if p.where_text:  # surface parse errors before any RPC, like query()
        try:
            to_predicate(parse_expr(p.where_text), p.where_text)
        except ExprError as e:
            raise QueryError(str(e)) from e

    def _plan_frags(projection, limit_push):
        rb = t.new_read_builder()
        if p.where_text:
            rb = rb.with_filter(to_predicate(parse_expr(p.where_text), p.where_text))
        if projection is not None:
            for n in projection:
                if n not in t.row_type:
                    raise QueryError(f"unknown column {n!r} in {p.table_name}")
            rb = rb.with_projection(list(projection))
        if limit_push is not None:
            rb = rb.with_limit(limit_push)
        by_wid: dict = {}
        for seq, sp in enumerate(rb.new_scan().plan()):
            by_wid.setdefault(client.owner_of(int(sp.bucket)), []).append((seq, sp.to_dict()))
        return by_wid

    def _kern_or_fallback(aggs2):
        kern, imap = _agg_kernel_plan(aggs2)
        for fn, col in kern:
            if fn == "count" and col == "*":
                continue
            if col not in t.row_type:
                raise QueryError(f"unknown column {col!r} in {p.table_name}")
            if fn != "count" and np.dtype(t.row_type.field(col).type.numpy_dtype()).kind not in "iuf":
                raise _LocalFallback
        return kern, imap

    def _gather_agg(projection, group_cols, kern):
        template = {
            "mode": "agg",
            "where": p.where_text,
            "projection": projection,
            "group_cols": group_cols,
            "kern": kern,
            "engine": engine,
            "code_domain": code_domain,
        }
        by_wid = _plan_frags(projection, None)
        key = _fragment_signature(template, by_wid) if frag_cache else None
        raw = _frag_cache_get(str(t.path), key)
        if raw is not None:
            g.counter("fragment_cache_hits").inc(1)
        else:
            t0 = time.perf_counter()
            raw = _scatter(client, by_wid, template, retry_ms, busy_wait_s, scan_frag_fn)
            g.histogram("scatter_ms").update((time.perf_counter() - t0) * 1000)
            _frag_cache_put(str(t.path), key, raw)
        schema = t.row_type.project(projection)
        parts = [decode_partial(r, schema, group_cols) for r in raw]
        parts = [q for q in parts if q["rows"]]
        for q in parts:
            g.counter("rows_reduced_device").inc(q["rows_reduced_device"])
        return schema, parts

    def _combine(parts, group_cols, kern):
        """Second-stage reduce over the partial rows, keyed on the UNIFIED
        code domain; returns (pools, codes, outs, anyv, first_pos) in the
        _assemble_group_batch contract."""
        from ..ops.aggregates import segment_reduce
        from ..ops.dicts import remap_codes, unify_pools

        pools_f, codes_f = [], []
        for gi in range(len(group_cols)):
            unified, remaps = unify_pools([q["pools"][gi] for q in parts])
            mapped = [
                remap_codes(
                    _sentinel_remap(rm, len(q["pools"][gi]), len(unified)),
                    q["group_codes"][gi],
                )
                for q, rm in zip(parts, remaps)
            ]
            pools_f.append(unified)
            codes_f.append(np.concatenate(mapped).astype(np.uint32, copy=False))
        rows = sum(len(q["first_pos"]) for q in parts)
        lanes = np.column_stack(codes_f) if group_cols else np.zeros((rows, 1), np.uint32)
        cols2 = [
            (
                np.concatenate([q["outs"][ki] for q in parts]),
                np.concatenate([q["anyv"][ki] for q in parts]),
            )
            for ki in range(len(kern))
        ]
        fns2 = tuple(_KERNEL_COMBINE[fn] for fn, _ in kern)
        pos = np.concatenate([q["first_pos"] for q in parts])
        rep, outs, anyv, first_pos = segment_reduce(lanes, cols2, fns2, pos=pos, engine=engine)
        g.counter("partials_combined").inc(len(parts))
        if code_domain and group_cols:
            g.counter("code_domain_groups").inc(rows)
        return pools_f, [c[rep] for c in codes_f], outs, anyv, first_pos

    def group_reduce(items2, aggs2):
        from .select import _group_aggregate

        for gc in p.group_cols:
            if gc not in t.row_type:
                raise QueryError(f"unknown GROUP BY column {gc!r}")
        kern, imap = _kern_or_fallback(aggs2)
        projection = list(
            dict.fromkeys(p.group_cols + [c for fn, c in kern if c != "*"])
        )
        schema, parts = _gather_agg(projection, p.group_cols, kern)
        if not parts:
            return _group_aggregate(
                ColumnBatch.empty(schema), items2, aggs2, p.group_cols, engine=engine
            )
        t1 = time.perf_counter()
        pools, codes, outs, anyv, first_pos = _combine(parts, p.group_cols, kern)
        out = _assemble_group_batch(
            t.row_type, items2, aggs2, imap, p.group_cols, pools, codes, outs, anyv, first_pos
        )
        g.histogram("combine_ms").update((time.perf_counter() - t1) * 1000)
        return out

    def scalar_reduce(items, aggs):
        from .select import _aggregate

        from ..types import BIGINT, DOUBLE, DataField, RowType

        kern, imap = _kern_or_fallback(aggs)
        projection = list(dict.fromkeys(c for _, c in kern if c != "*"))
        if not projection:
            projection = [t.row_type.field_names[0]]
        schema, parts = _gather_agg(projection, [], kern)
        if not parts:
            return _aggregate(ColumnBatch.empty(schema), items, aggs)
        t1 = time.perf_counter()
        _, _, outs, anyv, _ = _combine(parts, [], kern)
        # reproduce sql.select._aggregate's scalar semantics exactly: one
        # row always; an aggregate with no valid input is NULL typed DOUBLE
        names, types, values = [], [], []
        for item, agg, spec in zip(items, aggs, imap):
            label = re.sub(r"\s+", "", item).lower()
            if spec[0] == "count":
                v, ty = int(outs[spec[1]][0]), BIGINT()
            elif spec[0] == "avg":
                c = outs[spec[2]][0]
                v = float(outs[spec[1]][0] / c) if c else None
                ty = DOUBLE()
            else:
                ki = spec[1]
                if bool(anyv[ki][0]):
                    v, ty = outs[ki][0].item(), t.row_type.field(agg[1]).type
                else:
                    v, ty = None, DOUBLE()
            names.append(label)
            types.append(ty)
            values.append(v)
        rt = RowType(
            tuple(DataField(i, nm, ty) for i, (nm, ty) in enumerate(zip(names, types)))
        )
        out = ColumnBatch.from_pydict(rt, {nm: [v] for nm, v in zip(names, values)})
        g.histogram("combine_ms").update((time.perf_counter() - t1) * 1000)
        return out

    if p.group_cols or p.is_agg:
        try:
            return _finish(
                None,
                p.items,
                p.aggs,
                p.is_agg,
                p.group_cols,
                p.order_text,
                p.limit,
                p.cols_text,
                having_text=p.having_text,
                engine=engine,
                group_reduce=group_reduce if p.group_cols else None,
                scalar_reduce=scalar_reduce if not p.group_cols else None,
            )
        except _LocalFallback:
            return query(catalog, statement)

    # ---- non-aggregate: stream row batches back, finish at the coordinator
    projection = None
    if p.cols_text != "*":
        names = [i.strip("`") for i in p.items]
        for n in names:
            if n not in t.row_type:
                raise QueryError(f"unknown column {n!r} in {p.table_name}")
        projection = list(dict.fromkeys(names + _order_cols(p.order_text)))
    limit_push = p.limit if p.order_text is None else None
    template = {
        "mode": "rows",
        "where": p.where_text,
        "projection": projection,
        "limit": limit_push,
        "engine": engine,
    }
    t0 = time.perf_counter()
    raw = _scatter(
        client, _plan_frags(projection, limit_push), template, retry_ms, busy_wait_s, scan_frag_fn
    )
    g.histogram("scatter_ms").update((time.perf_counter() - t0) * 1000)
    schema = t.row_type.project(projection) if projection is not None else t.row_type
    t1 = time.perf_counter()
    batches: list = []
    total = 0
    for r in raw:
        dec = decode_partial(r, schema)
        batches.extend(dec["batches"])
        total += dec["rows"]
    batches.sort(key=lambda sb: sb[0])  # global row order = split seq order
    out = concat_batches([b for _, b in batches]) if batches else ColumnBatch.empty(schema)
    g.counter("rows_streamed").inc(total)
    out = _finish(out, p.items, p.aggs, False, [], p.order_text, p.limit, p.cols_text, engine=engine)
    g.histogram("combine_ms").update((time.perf_counter() - t1) * 1000)
    return out
