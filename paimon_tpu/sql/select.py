"""Minimal SELECT over tables: the query half of the SQL surface.

The reference leaves SELECT to host engines (Flink/Spark/Hive load tables via
their connector factories — FlinkTableFactory.java, PaimonInputFormat.java);
this rig has no installable engine (zero-egress: no duckdb/polars wheels —
see README "engine integration"), so the protocol-level surface
(`arrow_dataset`, Arrow Flight) is paired with this self-contained evaluator
covering the query shapes maintenance runbooks actually use::

    SELECT a, b FROM db.t WHERE k >= 10 AND s LIKE 'x%' ORDER BY a DESC LIMIT 5
    SELECT * FROM db.t$snapshots                    -- system tables work too
    SELECT count(*), sum(v), min(v) FROM db.t WHERE k < 100
    SELECT region, count(*), avg(amount) FROM db.t GROUP BY region ORDER BY region

Pushdown is real, not cosmetic: WHERE lowers onto the predicate algebra
(file/row-group skipping via stats + bloom indexes), the projection prunes
column decode, and a bare LIMIT n stops the scan early — the same paths a
planner-bearing engine would drive through `arrow_dataset`.
"""

from __future__ import annotations

import re
from typing import TYPE_CHECKING, Any

import numpy as np

from .expr import ExprError, _Parser, _tokenize, parse_expr, to_predicate

if TYPE_CHECKING:
    from ..catalog import Catalog
    from ..data.batch import ColumnBatch

__all__ = ["query", "QueryError"]


class QueryError(ValueError):
    pass


_SELECT_RE = re.compile(
    r"^\s*SELECT\s+(?:(?P<distinct>DISTINCT)\s+)?(?P<cols>.*?)\s+FROM\s+(?P<table>`?[\w.$]+`?)"
    r"(?:\s*/\*\+\s*OPTIONS\s*\((?P<hints>.*?)\)\s*\*/)?"
    r"(?:\s+FOR\s+(?P<tt_kind>VERSION|TIMESTAMP|TAG)\s+AS\s+OF\s+(?P<tt_val>'[^']*'|[^\s;]+))?"
    r"(?:\s+WHERE\s+(?P<where>.*?))?"
    r"(?:\s+GROUP\s+BY\s+(?P<group>.*?))?"
    r"(?:\s+ORDER\s+BY\s+(?P<order>.*?))?"
    r"(?:\s+LIMIT\s+(?P<limit>\d+))?\s*;?\s*$",
    re.I | re.S,
)

_AGG_FNS = ("count", "sum", "min", "max", "avg")


def _split_select_list(cols: str) -> list[str]:
    """Split the projection list on top-level commas (parens guard fn args)."""
    parts, depth, buf = [], 0, []
    for c in cols:
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        if c == "," and depth == 0:
            parts.append("".join(buf).strip())
            buf = []
        else:
            buf.append(c)
    tail = "".join(buf).strip()
    if tail:
        parts.append(tail)
    return parts


def _parse_agg(item: str):
    """'sum(v)' -> ('sum', 'v') | 'count(*)' -> ('count', '*') | None."""
    m = re.match(r"^(\w+)\s*\(\s*(\*|`?\w+`?)\s*\)$", item)
    if m and m.group(1).lower() in _AGG_FNS:
        return m.group(1).lower(), m.group(2).strip("`")
    return None


def query(catalog: "Catalog", statement: str) -> "ColumnBatch":
    """Execute one SELECT statement; returns the result as a ColumnBatch."""
    m = _SELECT_RE.match(statement)
    if not m:
        raise QueryError(f"not a SELECT statement: {statement!r}")
    table_name = m.group("table").strip("`")
    t = catalog.get_table(table_name)

    # per-query dynamic options: OPTIONS hints + time travel accumulate into
    # ONE table copy
    dynamic: dict[str, str] = {}
    if m.group("hints") is not None:
        # Flink's dynamic table options: SELECT ... FROM t /*+ OPTIONS('k'='v') */
        # (reference FlinkConnectorOptions dynamic hints) — per-query overrides
        # of ANY table option: scan modes, time travel, merge knobs
        from .ddl import DdlError, _parse_options

        try:
            hints = _parse_options(m.group("hints"))
        except DdlError as e:
            raise QueryError(f"cannot parse OPTIONS hint: {e}") from e
        if not hints:
            raise QueryError("empty OPTIONS hint")
        dynamic.update(hints)

    if m.group("tt_kind"):
        # time travel (Spark grammar: FOR VERSION|TIMESTAMP AS OF; TAG as an
        # explicit alias): lowers onto the scan options
        kind = m.group("tt_kind").upper()
        val = m.group("tt_val").strip("'")
        if not val:
            raise QueryError(f"FOR {kind} AS OF requires a non-empty value")
        if kind == "VERSION":
            # scan.version resolves a snapshot id OR a tag name — the same
            # unified semantic the reference gives Spark's VERSION AS OF
            dynamic["scan.version"] = val
        elif kind == "TAG":
            dynamic["scan.tag-name"] = val
        elif val.isdigit():
            dynamic["scan.timestamp-millis"] = val
        else:
            import datetime as _dt

            try:
                _dt.datetime.fromisoformat(val)
            except ValueError:
                raise QueryError(
                    f"TIMESTAMP AS OF expects epoch millis or "
                    f"'YYYY-MM-DD[ HH:MM:SS]', got {val!r}"
                ) from None
            dynamic["scan.timestamp"] = val

    if dynamic:
        if not hasattr(t, "copy"):
            raise QueryError(
                "OPTIONS hints / time travel apply to data tables, not system tables"
            )
        t = t.copy(dynamic)

    where_text = m.group("where")
    pred = None
    if where_text:
        try:
            pred = to_predicate(parse_expr(where_text), where_text)
        except ExprError as e:
            raise QueryError(str(e)) from e

    cols_text = m.group("cols").strip()
    items = _split_select_list(cols_text)
    aggs = [_parse_agg(i) for i in items]
    is_agg = any(a is not None for a in aggs)
    group_text = m.group("group")
    group_cols = [g.strip().strip("`") for g in group_text.split(",")] if group_text else []
    if m.group("distinct"):
        # SELECT DISTINCT a, b = GROUP BY a, b with no aggregates
        if is_agg or group_cols:
            raise QueryError("DISTINCT cannot combine with aggregates or GROUP BY")
        if cols_text == "*":
            raise QueryError("DISTINCT requires an explicit column list")
        group_cols = [i.strip("`") for i in items]
    if group_cols:
        bad = [i for i, a in zip(items, aggs) if a is None and i.strip("`") not in group_cols]
        if bad:
            raise QueryError(f"non-aggregate select items must appear in GROUP BY: {bad}")
    elif is_agg and not all(a is not None for a in aggs):
        raise QueryError("cannot mix aggregate and plain columns without GROUP BY")

    order_text = m.group("order")
    limit = int(m.group("limit")) if m.group("limit") else None

    if not hasattr(t, "new_read_builder"):
        # system tables ($snapshots, $files, ...) are static batches:
        # evaluate the clauses directly, no scan pushdown to drive
        out = t.read()
        if pred is not None:
            mask = pred.eval(out)
            if not mask.all():
                out = out.filter(mask)
    else:
        rb = t.new_read_builder()
        if pred is not None:
            rb = rb.with_filter(pred)
        if group_cols:
            # decode only what the aggregation consumes
            needed = list(dict.fromkeys(
                group_cols
                + [a[1] for a in aggs if a is not None and a[1] != "*"]
                + [c for c in _order_cols(order_text) if c in t.row_type]
            ))
            for n in needed:
                if n not in t.row_type:
                    raise QueryError(f"unknown column {n!r} in {table_name}")
            rb = rb.with_projection(needed)
        elif not is_agg:
            if cols_text != "*":
                names = [i.strip("`") for i in items]
                for n in names:
                    if n not in t.row_type:
                        raise QueryError(f"unknown column {n!r} in {table_name}")
                # ORDER BY columns must survive until after the sort
                order_cols = _order_cols(order_text)
                rb = rb.with_projection(list(dict.fromkeys(names + order_cols)))
            if limit is not None and order_text is None:
                rb = rb.with_limit(limit)
        out = rb.new_read().read_all(rb.new_scan().plan())

    if group_cols:
        # ORDER BY may reference group columns outside the select list: carry
        # them as hidden output columns through the sort, then project away
        labels = [i.strip("`") if a is None else re.sub(r"\s+", "", i).lower()
                  for i, a in zip(items, aggs)]
        hidden = [c for c in _order_cols(order_text)
                  if c in group_cols and c not in [i.strip("`") for i, a in zip(items, aggs) if a is None]]
        out = _group_aggregate(out, items + hidden, aggs + [None] * len(hidden), group_cols)
        if order_text:
            out = out.take(_order_index(out, order_text))
        if limit is not None:
            out = out.slice(0, min(limit, out.num_rows))
        return out.select(labels) if hidden else out
    if is_agg:
        return _aggregate(out, items, aggs)

    if order_text:
        idx = _order_index(out, order_text)
        out = out.take(idx)
    if limit is not None:
        out = out.slice(0, min(limit, out.num_rows))
    if cols_text != "*":
        out = out.select([i.strip("`") for i in items])
    return out


def _order_cols(order_text: str | None) -> list[str]:
    if not order_text:
        return []
    cols = []
    for part in order_text.split(","):
        cols.append(part.split()[0].strip("`"))
    return cols


def _order_index(batch: "ColumnBatch", order_text: str) -> np.ndarray:
    keys = []
    for part in reversed([p.strip() for p in order_text.split(",")]):
        toks = part.split()
        name = toks[0].strip("`")
        desc = len(toks) > 1 and toks[1].lower() == "desc"
        if len(toks) > 2 or (len(toks) == 2 and toks[1].lower() not in ("asc", "desc")):
            raise QueryError(f"bad ORDER BY term {part!r}")
        if name not in batch.schema:
            raise QueryError(f"unknown ORDER BY column {name!r}")
        vals = np.asarray(batch.column(name).values)
        if desc:
            if vals.dtype.kind in "iuf":
                vals = -vals
            else:  # lexsort has no per-key descending: rank-invert instead
                _, inv = np.unique(vals, return_inverse=True)
                vals = -inv
        keys.append(vals)
    return np.lexsort(keys)


def _aggregate(batch: "ColumnBatch", items: list[str], aggs) -> "ColumnBatch":
    from ..data.batch import ColumnBatch
    from ..types import BIGINT, DOUBLE, DataField, RowType

    names, types, values = [], [], []
    for item, (fn, col) in zip(items, aggs):
        label = re.sub(r"\s+", "", item).lower()
        if fn == "count":
            if col == "*":
                v: Any = batch.num_rows
            else:
                c = batch.column(col)
                v = int(c.validity.sum()) if c.validity is not None else batch.num_rows
            ty = BIGINT()
        else:
            if col == "*":
                raise QueryError(f"{fn}(*) is not valid")
            c = batch.column(col)
            vals = np.asarray(c.values)
            if c.validity is not None:
                vals = vals[c.validity]
            def _py(x):
                return x.item() if hasattr(x, "item") else x

            if vals.size == 0:
                v, ty = None, DOUBLE()
            elif fn == "sum":
                v, ty = _py(vals.sum()), batch.schema.field(col).type
            elif fn == "min":
                v, ty = _py(vals.min()), batch.schema.field(col).type
            elif fn == "max":
                v, ty = _py(vals.max()), batch.schema.field(col).type
            else:  # avg
                v, ty = float(vals.mean()), DOUBLE()
        names.append(label)
        types.append(ty)
        values.append(v)
    schema = RowType(tuple(DataField(i, n, ty) for i, (n, ty) in enumerate(zip(names, types))))
    return ColumnBatch.from_pydict(schema, {n: [v] for n, v in zip(names, values)})

def _group_aggregate(batch: "ColumnBatch", items, aggs, group_cols) -> "ColumnBatch":
    """Vectorized GROUP BY: per-column inverse codes combined into one group
    id, then reduceat over the group-sorted rows (sum/min/max/count; avg =
    sum/count). Output rows are in first-appearance order of each group's
    key, matching a streaming aggregator."""
    from ..data.batch import ColumnBatch
    from ..types import BIGINT, DOUBLE, DataField, RowType

    n = batch.num_rows
    for g in group_cols:
        if g not in batch.schema:
            raise QueryError(f"unknown GROUP BY column {g!r}")

    def _codes(col):
        """Dense group codes for one column, null-aware: NULL rows form their
        own group (SQL GROUP BY semantics); sentinel-filled values never
        merge with real values."""
        vals = np.asarray(col.values)
        valid = col.validity
        if (valid is None or valid.all()) and vals.dtype != object:
            _, codes = np.unique(vals, return_inverse=True)
            return codes
        if valid is None or valid.all():
            try:  # pure-string object columns sort fine
                _, codes = np.unique(vals, return_inverse=True)
                return codes
            except TypeError:
                pass
        mapping: dict = {}
        codes = np.empty(n, dtype=np.int64)
        vlist = vals.tolist() if vals.dtype != object else vals
        for i in range(n):
            key = None if (valid is not None and not valid[i]) else vlist[i]
            codes[i] = mapping.setdefault(key, len(mapping))
        return codes

    if n == 0:
        gid = np.empty(0, dtype=np.int64)
        uniq_first = np.empty(0, dtype=np.int64)
    else:
        gid = np.zeros(n, dtype=np.int64)
        for g in group_cols:
            codes = _codes(batch.column(g))
            gid = gid * (int(codes.max()) + 1 if len(codes) else 1) + codes
        # remap combined ids to dense group numbers in first-appearance order
        _, first_idx, inv = np.unique(gid, return_index=True, return_inverse=True)
        rank = np.argsort(np.argsort(first_idx))  # unique-id index -> appearance rank
        gid = rank[inv]
        uniq_first = np.sort(first_idx)  # each group's first row, appearance order

    n_groups = len(uniq_first)
    row_order = np.argsort(gid, kind="stable")
    sorted_gid = gid[row_order]
    starts = np.searchsorted(sorted_gid, np.arange(n_groups))
    counts = np.diff(np.concatenate([starts, [n]]))

    names, types, columns = [], [], []
    for item, agg in zip(items, aggs):
        if agg is None:  # a group column: its value at each group's first row
            name = item.strip("`")
            col = batch.column(name)
            arr = np.asarray(col.values)[uniq_first].tolist()
            if col.validity is not None:  # NULL group key surfaces as None
                arr = [None if not col.validity[i] else v for i, v in zip(uniq_first.tolist(), arr)]
            names.append(name)
            types.append(batch.schema.field(name).type)
            columns.append(arr)
            continue
        fn, colname = agg
        label = re.sub(r"\s+", "", item).lower()
        if fn == "count":
            if colname == "*":
                vals_out = counts.astype(np.int64).tolist()
            else:
                c = batch.column(colname)
                valid = c.validity if c.validity is not None else np.ones(n, dtype=bool)
                vals_out = (
                    np.add.reduceat(valid[row_order].astype(np.int64), starts).tolist()
                    if n else []
                )
            names.append(label); types.append(BIGINT()); columns.append(vals_out)
            continue
        if colname == "*":
            raise QueryError(f"{fn}(*) is not valid")
        c = batch.column(colname)
        ty = DOUBLE() if fn == "avg" else batch.schema.field(colname).type
        vals = np.asarray(c.values)[row_order]
        valid = c.validity
        if vals.dtype == object or (valid is not None and not valid.all()):
            # null-aware / object fallback: per-group reduction over the
            # VALID values only (a fully-null group aggregates to NULL)
            sorted_valid = (valid[row_order] if valid is not None else np.ones(n, dtype=bool))
            out = []
            py_vals = vals.tolist() if vals.dtype != object else vals
            for gi in range(n_groups):
                lo = int(starts[gi])
                hi = lo + int(counts[gi])
                vv = [py_vals[i] for i in range(lo, hi) if sorted_valid[i]]
                if not vv:
                    out.append(None)
                elif fn == "sum":
                    out.append(sum(vv))
                elif fn == "min":
                    out.append(min(vv))
                elif fn == "max":
                    out.append(max(vv))
                else:
                    out.append(float(sum(vv)) / len(vv))
        elif fn == "sum":
            out = (np.add.reduceat(vals, starts) if n else np.zeros(0, vals.dtype)).tolist()
        elif fn == "min":
            out = (np.minimum.reduceat(vals, starts) if n else np.zeros(0, vals.dtype)).tolist()
        elif fn == "max":
            out = (np.maximum.reduceat(vals, starts) if n else np.zeros(0, vals.dtype)).tolist()
        else:  # avg
            out = ((np.add.reduceat(vals.astype(np.float64), starts) / counts) if n else np.zeros(0)).tolist()
        names.append(label); types.append(ty); columns.append(out)

    schema = RowType(tuple(DataField(i, nm, ty) for i, (nm, ty) in enumerate(zip(names, types))))
    return ColumnBatch.from_pydict(schema, dict(zip(names, columns)))
