"""Minimal SELECT over tables: the query half of the SQL surface.

The reference leaves SELECT to host engines (Flink/Spark/Hive load tables via
their connector factories — FlinkTableFactory.java, PaimonInputFormat.java);
this rig has no installable engine (zero-egress: no duckdb/polars wheels —
see README "engine integration"), so the protocol-level surface
(`arrow_dataset`, Arrow Flight) is paired with this self-contained evaluator
covering the query shapes maintenance runbooks actually use::

    SELECT a, b FROM db.t WHERE k >= 10 AND s LIKE 'x%' ORDER BY a DESC LIMIT 5
    SELECT * FROM db.t$snapshots                    -- system tables work too
    SELECT count(*), sum(v), min(v) FROM db.t WHERE k < 100
    SELECT region, count(*), avg(amount) FROM db.t GROUP BY region ORDER BY region
    SELECT f.k, d.name, sum(f.v) FROM db.fact f JOIN db.dim d ON f.k = d.id
        WHERE d.region = 'EU' GROUP BY f.k, d.name

Pushdown is real, not cosmetic: WHERE lowers onto the predicate algebra
(file/row-group skipping via stats + bloom indexes), the projection prunes
column decode, and a bare LIMIT n stops the scan early — the same paths a
planner-bearing engine would drive through `arrow_dataset`.

JOIN (ISSUE 12) plans through the same machinery: single-side WHERE
conjuncts push into that side's scan, each side decodes only the columns
the query touches, and the smaller side's join-key statistics prune the
bigger side's scan (an IN list under `join.pushdown-in-limit` distinct
keys, a BETWEEN above it) before the device join kernel
(ops/join.join_batches) matches the rows. Inner and LEFT equi-joins; the
residual (cross-side) WHERE evaluates over the joined batch with SQL
three-valued logic.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any

import numpy as np

from .expr import ExprError, _Parser, _tokenize, eval_mask, parse_expr, to_predicate

if TYPE_CHECKING:
    from ..catalog import Catalog
    from ..data.batch import ColumnBatch

__all__ = ["query", "explain", "QueryError", "SelectPlan", "parse_select"]


class QueryError(ValueError):
    pass


_EXPLAIN_RE = re.compile(r"^\s*EXPLAIN\s+", re.I)


_SELECT_RE = re.compile(
    r"^\s*SELECT\s+(?:(?P<distinct>DISTINCT)\s+)?(?P<cols>.*?)\s+FROM\s+(?P<from>.*?)"
    r"(?:\s+WHERE\s+(?P<where>.*?))?"
    r"(?:\s+GROUP\s+BY\s+(?P<group>.*?))?"
    r"(?:\s+HAVING\s+(?P<having>.*?))?"
    r"(?:\s+ORDER\s+BY\s+(?P<order>.*?))?"
    r"(?:\s+LIMIT\s+(?P<limit>\d+))?\s*;?\s*$",
    re.I | re.S,
)

# the FROM clause: table [hints] [time travel] [alias] [JOIN table [hints]
# [alias] ON <equi conjunction>]
_KEYWORDS_NOT_ALIAS = r"(?!JOIN\b|INNER\b|LEFT\b|ON\b|AS\b)"
_FROM_RE = re.compile(
    r"^(?P<table>`?[\w.$]+`?)"
    r"(?:\s*/\*\+\s*OPTIONS\s*\((?P<hints>.*?)\)\s*\*/)?"
    r"(?:\s+FOR\s+(?P<tt_kind>VERSION|TIMESTAMP|TAG)\s+AS\s+OF\s+(?P<tt_val>'[^']*'|[^\s;]+))?"
    r"(?:\s+(?:AS\s+)?(?P<alias>" + _KEYWORDS_NOT_ALIAS + r"[A-Za-z_]\w*))?"
    r"(?:\s+(?:(?P<jtype>INNER|LEFT(?:\s+OUTER)?)\s+)?JOIN\s+(?P<jtable>`?[\w.$]+`?)"
    r"(?:\s*/\*\+\s*OPTIONS\s*\((?P<jhints>.*?)\)\s*\*/)?"
    r"(?:\s+(?:AS\s+)?(?P<jalias>" + _KEYWORDS_NOT_ALIAS + r"[A-Za-z_]\w*))?"
    r"\s+ON\s+(?P<on>.*))?$",
    re.I | re.S,
)

_AGG_FNS = ("count", "sum", "min", "max", "avg")


def _split_select_list(cols: str) -> list[str]:
    """Split the projection list on top-level commas (parens guard fn args)."""
    parts, depth, buf = [], 0, []
    for c in cols:
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        if c == "," and depth == 0:
            parts.append("".join(buf).strip())
            buf = []
        else:
            buf.append(c)
    tail = "".join(buf).strip()
    if tail:
        parts.append(tail)
    return parts


def _parse_agg(item: str):
    """'sum(v)' -> ('sum', 'v') | 'count(*)' -> ('count', '*') | None.
    Join queries may qualify the column: 'sum(f.v)' -> ('sum', 'f.v')."""
    m = re.match(r"^(\w+)\s*\(\s*(\*|`?[\w.]+`?)\s*\)$", item)
    if m and m.group(1).lower() in _AGG_FNS:
        return m.group(1).lower(), m.group(2).strip("`")
    return None


def _dynamic_options(hints: str | None, tt_kind: str | None, tt_val: str | None) -> dict:
    """OPTIONS hints + time travel accumulate into ONE table copy."""
    dynamic: dict[str, str] = {}
    if hints is not None:
        # Flink's dynamic table options: SELECT ... FROM t /*+ OPTIONS('k'='v') */
        # (reference FlinkConnectorOptions dynamic hints) — per-query overrides
        # of ANY table option: scan modes, time travel, merge knobs
        from .ddl import DdlError, _parse_options

        try:
            parsed = _parse_options(hints)
        except DdlError as e:
            raise QueryError(f"cannot parse OPTIONS hint: {e}") from e
        if not parsed:
            raise QueryError("empty OPTIONS hint")
        dynamic.update(parsed)

    if tt_kind:
        # time travel (Spark grammar: FOR VERSION|TIMESTAMP AS OF; TAG as an
        # explicit alias): lowers onto the scan options
        kind = tt_kind.upper()
        val = (tt_val or "").strip("'")
        if not val:
            raise QueryError(f"FOR {kind} AS OF requires a non-empty value")
        if kind == "VERSION":
            # scan.version resolves a snapshot id OR a tag name — the same
            # unified semantic the reference gives Spark's VERSION AS OF
            dynamic["scan.version"] = val
        elif kind == "TAG":
            dynamic["scan.tag-name"] = val
        elif val.isdigit():
            dynamic["scan.timestamp-millis"] = val
        else:
            import datetime as _dt

            try:
                _dt.datetime.fromisoformat(val)
            except ValueError:
                raise QueryError(
                    f"TIMESTAMP AS OF expects epoch millis or "
                    f"'YYYY-MM-DD[ HH:MM:SS]', got {val!r}"
                ) from None
            dynamic["scan.timestamp"] = val
    return dynamic


def _resolve_table(catalog: "Catalog", name: str, hints, tt_kind, tt_val):
    t = catalog.get_table(name.strip("`"))
    dynamic = _dynamic_options(hints, tt_kind, tt_val)
    if dynamic:
        if not hasattr(t, "copy"):
            raise QueryError(
                "OPTIONS hints / time travel apply to data tables, not system tables"
            )
        t = t.copy(dynamic)
    return t


@dataclass
class SelectPlan:
    """One parsed SELECT, clause by clause — shared by the local evaluator
    (query) and the distributed planner (sql.cluster.cluster_query), so both
    paths agree on every semantic decision before a scan is planned."""

    items: list[str]
    aggs: list
    is_agg: bool
    group_cols: list[str]
    order_text: str | None
    limit: int | None
    where_text: str | None
    having_text: str | None
    cols_text: str
    from_match: Any = field(repr=False)

    @property
    def table_name(self) -> str:
        return self.from_match.group("table").strip("`")

    @property
    def is_join(self) -> bool:
        return self.from_match.group("jtable") is not None


def parse_select(statement: str) -> SelectPlan:
    """Parse one SELECT statement into a SelectPlan (clause validation
    included); raises QueryError on anything the grammar does not cover."""
    m = _SELECT_RE.match(statement)
    if not m:
        raise QueryError(f"not a SELECT statement: {statement!r}")
    fm = _FROM_RE.match(m.group("from").strip())
    if not fm:
        raise QueryError(f"cannot parse FROM clause: {m.group('from')!r}")

    cols_text = m.group("cols").strip()
    items = _split_select_list(cols_text)
    aggs = [_parse_agg(i) for i in items]
    is_agg = any(a is not None for a in aggs)
    group_text = m.group("group")
    group_cols = [g.strip().strip("`") for g in group_text.split(",")] if group_text else []
    if m.group("distinct"):
        # SELECT DISTINCT a, b = GROUP BY a, b with no aggregates
        if is_agg or group_cols:
            raise QueryError("DISTINCT cannot combine with aggregates or GROUP BY")
        if cols_text == "*":
            raise QueryError("DISTINCT requires an explicit column list")
        group_cols = [i.strip("`") for i in items]
    if group_cols:
        bad = [i for i, a in zip(items, aggs) if a is None and i.strip("`") not in group_cols]
        if bad:
            raise QueryError(f"non-aggregate select items must appear in GROUP BY: {bad}")
    elif is_agg and not all(a is not None for a in aggs):
        raise QueryError("cannot mix aggregate and plain columns without GROUP BY")
    if m.group("having") and not group_cols:
        raise QueryError("HAVING requires GROUP BY")

    return SelectPlan(
        items=items,
        aggs=aggs,
        is_agg=is_agg,
        group_cols=group_cols,
        order_text=m.group("order"),
        limit=int(m.group("limit")) if m.group("limit") else None,
        where_text=m.group("where"),
        having_text=m.group("having"),
        cols_text=cols_text,
        from_match=fm,
    )


def _engine_for(table) -> str:
    """Engine for the SQL segment-reduce: an explicit sort-engine choice
    (table option or PAIMON_TPU_SORT_ENGINE) is honored; with no explicit
    choice the jitted XLA kernel runs everywhere — unlike the 1M-row merge
    sort, the group-by reduce's operands are a handful of uint32 lanes, so
    the CPU-adaptive lexsort default of effective_sort_engine would only
    forfeit the device path the distributed plane is built around."""
    import os

    try:
        from ..options import CoreOptions

        opts = table.store.options
        if opts.options.contains(CoreOptions.SORT_ENGINE):
            name = str(opts.sort_engine).lower()
        else:
            name = os.environ.get("PAIMON_TPU_SORT_ENGINE", "").strip().lower() or "xla"
    except Exception:
        name = "xla"
    if "pallas" in name:
        return "pallas"
    if "numpy" in name:
        return "numpy"
    return "xla"


def agg_projection(p: SelectPlan, row_type) -> list[str] | None:
    """Columns an aggregate-only SELECT actually reads (projection pruning
    before the scan is planned): group keys, aggregate arguments, ORDER BY
    keys. A pure count(*) reads a single cheap column — merged row count is
    projection-independent. None = the plan is not aggregate-shaped."""
    if p.group_cols:
        needed = list(
            dict.fromkeys(
                p.group_cols
                + [a[1] for a in p.aggs if a is not None and a[1] != "*"]
                + _having_cols(p.having_text)
                + [c for c in _order_cols(p.order_text) if c in row_type]
            )
        )
    elif p.is_agg:
        needed = list(dict.fromkeys(a[1] for a in p.aggs if a[1] != "*"))
        if not needed:
            needed = [row_type.field_names[0]]
    else:
        return None
    return needed


def explain_plan(catalog: "Catalog", statement: str):
    """Plan facts for one SELECT without executing it: (SelectPlan, table,
    display lines, pushed-down splits). The shared EXPLAIN body — the local
    evaluator renders the lines as-is; sql.cluster appends the
    fragment->worker assignment and the code-domain toggle."""
    p = parse_select(statement)
    if p.is_join:
        jt = p.from_match.group("jtable").strip("`")
        return p, None, [
            f"join query: {p.table_name} JOIN {jt}",
            "plan: per-side WHERE/projection pushdown, join-key stats prune "
            "the bigger side, device join kernel (ops.join.join_batches)",
        ], None
    fm = p.from_match
    t = _resolve_table(
        catalog, fm.group("table"), fm.group("hints"), fm.group("tt_kind"), fm.group("tt_val")
    )
    shape = (
        f"grouped aggregate (group by: {', '.join(p.group_cols)})"
        if p.group_cols
        else "scalar aggregate" if p.is_agg else "rows"
    )
    lines = [f"table: {p.table_name}", f"shape: {shape}"]
    if not hasattr(t, "new_read_builder"):
        lines.append("source: system table (static batch; no scan pushdown)")
        return p, t, lines, None
    pred = None
    if p.where_text:
        try:
            pred = to_predicate(parse_expr(p.where_text), p.where_text)
        except ExprError as e:
            raise QueryError(str(e)) from e
    needed = agg_projection(p, t.row_type)
    if needed is None and not p.is_agg and p.cols_text != "*":
        names = [i.strip("`") for i in p.items]
        needed = list(dict.fromkeys(names + _order_cols(p.order_text)))
    if needed is not None:
        for n in needed:
            if n not in t.row_type:
                raise QueryError(f"unknown column {n!r} in {p.table_name}")
    limit_push = (
        p.limit if (not p.is_agg and not p.group_cols and p.order_text is None) else None
    )
    lines.append(f"engine: {_engine_for(t)}")
    lines.append(f"where (pushed): {p.where_text.strip()}" if p.where_text else "where: none")
    lines.append(
        f"projection (pushed): [{', '.join(needed)}]"
        if needed is not None
        else "projection: * (full row)"
    )
    if limit_push is not None:
        lines.append(f"limit (pushed): {limit_push}")
    elif p.limit is not None:
        lines.append(f"limit: {p.limit} (applied after ORDER BY)")
    if p.order_text:
        lines.append(f"order by: {p.order_text.strip()}")
    if p.having_text:
        lines.append(f"having: {p.having_text.strip()}")
    all_splits = t.new_read_builder().new_scan().plan()
    rb = t.new_read_builder()
    if pred is not None:
        rb = rb.with_filter(pred)
    if needed is not None:
        rb = rb.with_projection(list(needed))
    if limit_push is not None:
        rb = rb.with_limit(limit_push)
    splits = rb.new_scan().plan()
    total_files = sum(len(sp.files) for sp in all_splits)
    files = sum(len(sp.files) for sp in splits)
    lines.append(
        f"splits: {len(splits)} (files {files} of {total_files}, "
        f"{total_files - files} pruned)"
    )
    return p, t, lines, splits


def plan_batch(lines: list) -> "ColumnBatch":
    """EXPLAIN wire shape: one STRING column named 'plan', one line per row."""
    from ..data.batch import ColumnBatch
    from ..types import STRING, RowType

    return ColumnBatch.from_pydict(RowType.of(("plan", STRING())), {"plan": list(lines)})


def explain(catalog: "Catalog", statement: str) -> "ColumnBatch":
    """EXPLAIN SELECT ...: the local plan — files pruned, pushed predicates
    / projection / LIMIT, engine, result shape — as a one-column batch."""
    _, _, lines, _ = explain_plan(catalog, statement)
    return plan_batch(lines)


def query(catalog: "Catalog", statement: str) -> "ColumnBatch":
    """Execute one SELECT statement; returns the result as a ColumnBatch.
    ``EXPLAIN SELECT ...`` returns the plan instead (see :func:`explain`)."""
    m = _EXPLAIN_RE.match(statement)
    if m:
        return explain(catalog, statement[m.end():])
    p = parse_select(statement)
    if p.is_join:
        return _join_query(catalog, p)
    fm = p.from_match

    t = _resolve_table(
        catalog, fm.group("table"), fm.group("hints"), fm.group("tt_kind"), fm.group("tt_val")
    )
    table_name = p.table_name
    pred = None
    if p.where_text:
        try:
            pred = to_predicate(parse_expr(p.where_text), p.where_text)
        except ExprError as e:
            raise QueryError(str(e)) from e

    if not hasattr(t, "new_read_builder"):
        # system tables ($snapshots, $files, ...) are static batches:
        # evaluate the clauses directly, no scan pushdown to drive
        out = t.read()
        if pred is not None:
            mask = pred.eval(out)
            if not mask.all():
                out = out.filter(mask)
        engine = "xla"
    else:
        rb = t.new_read_builder()
        if pred is not None:
            rb = rb.with_filter(pred)
        needed = agg_projection(p, t.row_type)
        if needed is not None:
            # decode only what the aggregation consumes
            for n in needed:
                if n not in t.row_type:
                    raise QueryError(f"unknown column {n!r} in {table_name}")
            rb = rb.with_projection(needed)
        elif not p.is_agg:
            if p.cols_text != "*":
                names = [i.strip("`") for i in p.items]
                for n in names:
                    if n not in t.row_type:
                        raise QueryError(f"unknown column {n!r} in {table_name}")
                # ORDER BY columns must survive until after the sort
                order_cols = _order_cols(p.order_text)
                rb = rb.with_projection(list(dict.fromkeys(names + order_cols)))
            if p.limit is not None and p.order_text is None:
                rb = rb.with_limit(p.limit)
        out = rb.new_read().read_all(rb.new_scan().plan())
        engine = _engine_for(t)

    return _finish(out, p.items, p.aggs, p.is_agg, p.group_cols, p.order_text,
                   p.limit, p.cols_text, having_text=p.having_text, engine=engine)


def _finish(out, items, aggs, is_agg, group_cols, order_text, limit, cols_text,
            having_text=None, engine="xla", group_reduce=None, scalar_reduce=None):
    """The engine-independent tail: GROUP BY / aggregates / HAVING /
    ORDER BY / LIMIT / final projection over an already-scanned (or joined,
    or distributed-combined) batch.

    `group_reduce(items, aggs)` / `scalar_reduce(items, aggs)` replace the
    local aggregation step (sql.cluster's scatter-gather combine plugs in
    here): they receive the FULL item list — select items plus the hidden
    ORDER BY / HAVING columns this tail derives — so distributed plans
    compute exactly what the local evaluator would."""
    if group_cols:
        # ORDER BY may reference group columns outside the select list: carry
        # them as hidden output columns through the sort, then project away.
        # HAVING likewise: its aggregate calls and group-column refs compute
        # as hidden items, filter after grouping, then project away.
        labels = [i.strip("`") if a is None else re.sub(r"\s+", "", i).lower()
                  for i, a in zip(items, aggs)]
        plain = [i.strip("`") for i, a in zip(items, aggs) if a is None]
        hidden_items: list[str] = []
        hidden_aggs: list = []
        for c in _order_cols(order_text):
            if c in group_cols and c not in plain and c not in hidden_items:
                hidden_items.append(c)
                hidden_aggs.append(None)
        having_node, pmap = None, {}
        if having_text:
            having_node, pmap, extra_items, extra_aggs = _rewrite_having(
                having_text, labels, group_cols, plain + hidden_items
            )
            hidden_items += extra_items
            hidden_aggs += extra_aggs
        if group_reduce is not None:
            out = group_reduce(items + hidden_items, aggs + hidden_aggs)
        else:
            out = _group_aggregate(out, items + hidden_items, aggs + hidden_aggs,
                                   group_cols, engine=engine)
        if having_node is not None:
            out = _apply_having(out, having_node, pmap)
        if order_text:
            out = out.take(_order_index(out, order_text))
        if limit is not None:
            out = out.slice(0, min(limit, out.num_rows))
        return out.select(labels) if hidden_items else out
    if is_agg:
        return scalar_reduce(items, aggs) if scalar_reduce is not None else _aggregate(out, items, aggs)

    if order_text:
        idx = _order_index(out, order_text)
        out = out.take(idx)
    if limit is not None:
        out = out.slice(0, min(limit, out.num_rows))
    if cols_text != "*":
        out = out.select([i.strip("`") for i in items])
    return out


# ---------------------------------------------------------------------------
# JOIN planning (ISSUE 12)
# ---------------------------------------------------------------------------


def _conjuncts(node) -> list:
    return list(node[1]) if node[0] == "and" else [node]


def _col_nodes(node, acc: list) -> list:
    """Collect every ('col', alias, name) reference in an AST."""
    if not isinstance(node, tuple):
        return acc
    if node[0] == "col":
        acc.append(node)
        return acc
    for part in node[1:]:
        if isinstance(part, tuple):
            _col_nodes(part, acc)
        elif isinstance(part, list):
            for p in part:
                _col_nodes(p, acc)
    return acc


class _JoinScope:
    """Name resolution over the two joined tables: alias-qualified refs pin
    a side, bare refs resolve by unique membership; canonical output names
    stay bare when unambiguous and qualify as 'alias.col' on collision."""

    def __init__(self, la, t_l, ra, t_r):
        if la == ra:
            raise QueryError(f"duplicate table alias {la!r} in JOIN")
        self.aliases = (la, ra)
        self.tables = (t_l, t_r)

    def resolve_ref(self, alias, name):
        name = name.strip("`")
        if alias is not None:
            if alias not in self.aliases:
                raise QueryError(
                    f"unknown table alias {alias!r} (have {list(self.aliases)})"
                )
            side = self.aliases.index(alias)
            if name not in self.tables[side].row_type:
                raise QueryError(f"unknown column {name!r} in {alias!r}")
            return side, name
        in_l = name in self.tables[0].row_type
        in_r = name in self.tables[1].row_type
        if in_l and in_r:
            raise QueryError(f"ambiguous column {name!r}: qualify with an alias")
        if in_l:
            return 0, name
        if in_r:
            return 1, name
        raise QueryError(f"unknown column {name!r}")

    def resolve_tok(self, tok: str):
        tok = tok.strip().strip("`")
        if "." in tok:
            a, n = tok.split(".", 1)
            return self.resolve_ref(a, n)
        return self.resolve_ref(None, tok)

    def canonical(self, side: int, col: str) -> str:
        other = self.tables[1 - side]
        if col in other.row_type:
            return f"{self.aliases[side]}.{col}"
        return col


def _estimate_rows(splits) -> int:
    return sum(f.row_count for s in splits for f in getattr(s, "files", []))


def _key_prune_predicate(batch, src_col: str, target_col: str, in_limit: int):
    """The small side's join-key statistics as a predicate on the big side:
    an exact IN list under in_limit distinct keys, a BETWEEN envelope above
    it. Code-backed key columns derive both from the pruned POOL — no row
    ever expands. Returns None when nothing can be derived (empty side:
    the caller shortcuts)."""
    from ..data import predicate as P
    from ..ops.dicts import prune_pool

    col = batch.column(src_col)
    if col.is_code_backed:
        pool, codes = col.dict_cache
        pruned, _ = prune_pool(pool, codes, col.validity)
        vals = pruned.tolist()
    else:
        v = col.values
        if col.validity is not None:
            v = v[col.validity]
        if len(v) == 0:
            return None
        try:
            vals = np.unique(v).tolist()
        except TypeError:
            vals = sorted(set(v.tolist()))
    if not vals:
        return None
    if len(vals) <= in_limit:
        return P.in_(target_col, vals)
    return P.between(target_col, vals[0], vals[-1])


def _join_query(catalog, p: SelectPlan):
    from ..data import predicate as P
    from ..ops.join import JoinError, join_batches, materialize_join

    fm = p.from_match
    items, aggs, is_agg = p.items, p.aggs, p.is_agg
    group_cols, order_text, limit, cols_text = p.group_cols, p.order_text, p.limit, p.cols_text
    how = "left" if (fm.group("jtype") or "").strip().upper().startswith("LEFT") else "inner"
    t_l = _resolve_table(
        catalog, fm.group("table"), fm.group("hints"), fm.group("tt_kind"), fm.group("tt_val")
    )
    t_r = _resolve_table(catalog, fm.group("jtable"), fm.group("jhints"), None, None)
    for t in (t_l, t_r):
        if not hasattr(t, "new_read_builder"):
            raise QueryError("JOIN applies to data tables, not system tables")
    la = fm.group("alias") or fm.group("table").strip("`").split(".")[-1]
    ra = fm.group("jalias") or fm.group("jtable").strip("`").split(".")[-1]
    scope = _JoinScope(la, t_l, ra, t_r)

    # ---- ON: a conjunction of cross-side column equalities ---------------
    try:
        on_ast = parse_expr(fm.group("on"))
    except ExprError as e:
        raise QueryError(f"cannot parse ON clause: {e}") from e
    left_keys, right_keys = [], []
    for c in _conjuncts(on_ast):
        if not (c[0] == "cmp" and c[1] == "=" and c[2][0] == "col" and c[3][0] == "col"):
            raise QueryError(
                "JOIN ON supports a conjunction of equalities between the two "
                f"tables' columns, got {fm.group('on')!r}"
            )
        sides = [scope.resolve_ref(c[2][1], c[2][2]), scope.resolve_ref(c[3][1], c[3][2])]
        if {sides[0][0], sides[1][0]} != {0, 1}:
            raise QueryError("each ON equality must reference BOTH tables")
        pair = dict(sides)
        left_keys.append(pair[0])
        right_keys.append(pair[1])

    # ---- WHERE: single-side conjuncts push into that side's scan ---------
    where_text = p.where_text
    side_preds: list[list] = [[], []]
    residual: list = []
    if where_text:
        try:
            where_ast = parse_expr(where_text)
        except ExprError as e:
            raise QueryError(str(e)) from e
        for c in _conjuncts(where_ast):
            refs = {scope.resolve_ref(n[1], n[2]) for n in _col_nodes(c, [])}
            sides = {s for s, _ in refs}
            pushable = sides == {0} or (sides == {1} and how == "inner")
            if pushable:
                # a LEFT join's right-side conjunct must see post-join NULLs,
                # so only the inner case pushes the right side
                try:
                    side_preds[sides.pop()].append(to_predicate(c, where_text))
                    continue
                except ExprError:
                    pass  # not predicate-lowerable (e.g. col vs col): residual
            residual.append(c)

    # ---- needed columns & output naming ----------------------------------
    def out_cols_for_star():
        cols = [(0, n) for n in t_l.row_type.field_names]
        cols += [(1, n) for n in t_r.row_type.field_names]
        return cols

    plain_refs: list[tuple[int, str]] = []  # select-list order
    if cols_text == "*":
        plain_refs = out_cols_for_star()
        items = [scope.canonical(s, n) for s, n in plain_refs]
        aggs = [None] * len(items)
        cols_text = ", ".join(items)
    else:
        new_items = []
        for item, agg in zip(items, aggs):
            if agg is None:
                side, col = scope.resolve_tok(item)
                plain_refs.append((side, col))
                new_items.append(scope.canonical(side, col))
            elif agg[1] == "*":
                new_items.append(re.sub(r"\s+", "", item).lower())
            else:
                side, col = scope.resolve_tok(agg[1])
                plain_refs.append((side, col))
                canon = scope.canonical(side, col)
                new_items.append(f"{agg[0]}({canon})")
        items = new_items
        aggs = [_parse_agg(i) for i in items]
    group_refs = [scope.resolve_tok(g) for g in group_cols]
    group_cols = [scope.canonical(s, n) for s, n in group_refs]
    order_refs = []
    if order_text:
        parts = []
        for part in [p.strip() for p in order_text.split(",")]:
            toks = part.split()
            side, col = scope.resolve_tok(toks[0])
            order_refs.append((side, col))
            parts.append(" ".join([scope.canonical(side, col)] + toks[1:]))
        order_text = ", ".join(parts)
    residual_refs = [
        scope.resolve_ref(n[1], n[2]) for c in residual for n in _col_nodes(c, [])
    ]

    needed: list[list[str]] = [[], []]
    out_pairs: list[list[tuple[str, str]]] = [[], []]
    seen = set()
    for side, col in plain_refs + group_refs + order_refs + residual_refs:
        if (side, col) not in seen:
            seen.add((side, col))
            out_pairs[side].append((col, scope.canonical(side, col)))
        if col not in needed[side]:
            needed[side].append(col)
    for side, keys in ((0, left_keys), (1, right_keys)):
        for col in keys:
            if col not in needed[side]:
                needed[side].append(col)

    # ---- scans: per-side pushdown + small-side key pruning ---------------
    def builder(side):
        t = scope.tables[side]
        rb = t.new_read_builder()
        preds = side_preds[side]
        if preds:
            rb = rb.with_filter(P.and_(*preds) if len(preds) > 1 else preds[0])
        rb = rb.with_projection(list(needed[side]))
        return rb

    rb_l, rb_r = builder(0), builder(1)
    plan_l, plan_r = rb_l.new_scan().plan(), rb_r.new_scan().plan()
    est = (_estimate_rows(plan_l), _estimate_rows(plan_r))
    # which side's key stats prune the other: the smaller one — except a
    # LEFT join must never prune its preserved (left) side
    prune_from = 0 if (how == "left" or est[0] <= est[1]) else 1
    key_pairs = list(zip(left_keys, right_keys))
    from ..options import CoreOptions

    in_limit = t_l.options.options.get(CoreOptions.JOIN_PUSHDOWN_IN_LIMIT)
    if prune_from == 0:
        batch_l = rb_l.new_read().read_all(plan_l)
        prune = [
            _key_prune_predicate(batch_l, lk, rk, in_limit) for lk, rk in key_pairs
        ]
        prune = [p for p in prune if p is not None]
        if prune:
            rb_r = rb_r.with_filter(P.and_(*prune) if len(prune) > 1 else prune[0])
            plan_r = rb_r.new_scan().plan()
        batch_r = rb_r.new_read().read_all(plan_r)
    else:
        batch_r = rb_r.new_read().read_all(plan_r)
        prune = [
            _key_prune_predicate(batch_r, rk, lk, in_limit) for lk, rk in key_pairs
        ]
        prune = [p for p in prune if p is not None]
        if prune:
            rb_l = rb_l.with_filter(P.and_(*prune) if len(prune) > 1 else prune[0])
            plan_l = rb_l.new_scan().plan()
        batch_l = rb_l.new_read().read_all(plan_l)

    # ---- the join itself -------------------------------------------------
    try:
        res = join_batches(
            batch_l, batch_r, left_keys, right_keys, how=how,
            options=t_l.options.options,
        )
    except JoinError as e:
        raise QueryError(str(e)) from e
    joined = materialize_join(batch_l, batch_r, res, out_pairs[0], out_pairs[1])

    # ---- residual WHERE over the joined batch (SQL 3-valued logic) -------
    if residual:

        def resolve(alias, name):
            side, col = scope.resolve_ref(alias, name)
            c = joined.column(scope.canonical(side, col))
            return np.asarray(c.values), c.validity

        node = residual[0] if len(residual) == 1 else ("and", residual)
        try:
            mask = eval_mask(node, resolve, joined.num_rows)
        except ExprError as e:
            raise QueryError(str(e)) from e
        if not mask.all():
            joined = joined.filter(mask)

    # HAVING refs lower onto the joined batch's canonical naming: aggregate
    # arguments resolve through the scope exactly like select items do
    having_text = p.having_text
    if having_text:
        def _canon_call(mo):
            fn = mo.group(1)
            if fn.lower() not in _AGG_FNS:
                return mo.group(0)
            arg = mo.group(2)
            if arg == "*":
                return re.sub(r"\s+", "", mo.group(0)).lower()
            side, col = scope.resolve_tok(arg)
            return f"{fn.lower()}({scope.canonical(side, col)})"

        having_text = _AGG_CALL_RE.sub(_canon_call, having_text)

    return _finish(joined, items, aggs, is_agg, group_cols, order_text, limit, cols_text,
                   having_text=having_text, engine=_engine_for(t_l))


_AGG_CALL_RE = re.compile(r"(\w+)\s*\(\s*(\*|`?[\w.]+`?)\s*\)")


def _having_cols(having_text: str | None) -> list[str]:
    """Table columns a HAVING clause's aggregate calls read (its bare column
    refs must be group columns, which the projection already carries)."""
    if not having_text:
        return []
    return [
        mo.group(2).strip("`")
        for mo in _AGG_CALL_RE.finditer(having_text)
        if mo.group(1).lower() in _AGG_FNS and mo.group(2) != "*"
    ]


def _rewrite_having(having_text, labels, group_cols, present):
    """Lower HAVING onto the grouped batch: each aggregate call becomes a
    placeholder column (an existing select-item label when the same call is
    already selected, a hidden extra aggregate otherwise) and bare refs are
    checked against the GROUP BY list. Returns (expr node, placeholder →
    label map, extra hidden items, extra hidden aggs). Refs must use the
    output's canonical naming (join queries: the same names the select list
    resolves to)."""
    pmap: dict[str, str] = {}
    extra_items: list[str] = []
    extra_aggs: list = []

    def repl(mo):
        if mo.group(1).lower() not in _AGG_FNS:
            return mo.group(0)
        norm = re.sub(r"\s+", "", mo.group(0)).lower().replace("`", "")
        for ph, label in pmap.items():
            if label == norm:
                return ph
        ph = f"__h{len(pmap)}"
        pmap[ph] = norm
        if norm not in labels and norm not in extra_items:
            agg = _parse_agg(norm)
            if agg is None:
                raise QueryError(f"unsupported aggregate in HAVING: {mo.group(0)!r}")
            extra_items.append(norm)
            extra_aggs.append(agg)
        return ph

    rewritten = _AGG_CALL_RE.sub(repl, having_text)
    try:
        node = parse_expr(rewritten)
    except ExprError as e:
        raise QueryError(f"cannot parse HAVING: {e}") from e
    for ref in _col_nodes(node, []):
        name = f"{ref[1]}.{ref[2]}" if ref[1] else ref[2].strip("`")
        if name.startswith("__h"):
            continue
        if name not in group_cols:
            raise QueryError(f"HAVING references non-grouped column {name!r}")
        if name not in present and name not in extra_items:
            extra_items.append(name)
            extra_aggs.append(None)
    return node, pmap, extra_items, extra_aggs


def _apply_having(out, node, pmap):
    """Evaluate a rewritten HAVING over the grouped batch (SQL three-valued
    logic via eval_mask: a NULL comparison drops the group)."""
    def resolve(alias, name):
        label = f"{alias}.{name}" if alias else name
        label = pmap.get(label, label)
        if label not in out.schema:
            raise QueryError(f"HAVING references unknown column {label!r}")
        c = out.column(label)
        return np.asarray(c.values), c.validity

    try:
        mask = eval_mask(node, resolve, out.num_rows)
    except ExprError as e:
        raise QueryError(str(e)) from e
    return out if mask.all() else out.filter(mask)


def _order_cols(order_text: str | None) -> list[str]:
    if not order_text:
        return []
    cols = []
    for part in order_text.split(","):
        cols.append(part.split()[0].strip("`"))
    return cols


def _order_index(batch: "ColumnBatch", order_text: str) -> np.ndarray:
    keys = []
    for part in reversed([p.strip() for p in order_text.split(",")]):
        toks = part.split()
        name = toks[0].strip("`")
        desc = len(toks) > 1 and toks[1].lower() == "desc"
        if len(toks) > 2 or (len(toks) == 2 and toks[1].lower() not in ("asc", "desc")):
            raise QueryError(f"bad ORDER BY term {part!r}")
        if name not in batch.schema:
            raise QueryError(f"unknown ORDER BY column {name!r}")
        vals = np.asarray(batch.column(name).values)
        if desc:
            if vals.dtype.kind in "iuf":
                vals = -vals
            else:  # lexsort has no per-key descending: rank-invert instead
                _, inv = np.unique(vals, return_inverse=True)
                vals = -inv
        keys.append(vals)
    return np.lexsort(keys)


def _aggregate(batch: "ColumnBatch", items: list[str], aggs) -> "ColumnBatch":
    from ..data.batch import ColumnBatch
    from ..types import BIGINT, DOUBLE, DataField, RowType

    names, types, values = [], [], []
    for item, (fn, col) in zip(items, aggs):
        label = re.sub(r"\s+", "", item).lower()
        if fn == "count":
            if col == "*":
                v: Any = batch.num_rows
            else:
                c = batch.column(col)
                v = int(c.validity.sum()) if c.validity is not None else batch.num_rows
            ty = BIGINT()
        else:
            if col == "*":
                raise QueryError(f"{fn}(*) is not valid")
            c = batch.column(col)
            vals = np.asarray(c.values)
            if c.validity is not None:
                vals = vals[c.validity]
            def _py(x):
                return x.item() if hasattr(x, "item") else x

            if vals.size == 0:
                v, ty = None, DOUBLE()
            elif fn == "sum":
                v, ty = _py(vals.sum()), batch.schema.field(col).type
            elif fn == "min":
                v, ty = _py(vals.min()), batch.schema.field(col).type
            elif fn == "max":
                v, ty = _py(vals.max()), batch.schema.field(col).type
            else:  # avg
                v, ty = float(vals.mean()), DOUBLE()
        names.append(label)
        types.append(ty)
        values.append(v)
    schema = RowType(tuple(DataField(i, n, ty) for i, (n, ty) in enumerate(zip(names, types))))
    return ColumnBatch.from_pydict(schema, {n: [v] for n, v in zip(names, values)})

# ---------------------------------------------------------------------------
# GROUP BY kernel plan (ISSUE 16): shared by the single-process evaluator and
# the distributed scatter-gather path — both reduce through the SAME
# ops.aggregates.segment_reduce call, so their per-group results are
# parity-pinned by construction.
# ---------------------------------------------------------------------------

# how a partial aggregate re-reduces at the coordinator: counts and sums add,
# min/min and max/max compose
_KERNEL_COMBINE = {"count": "sum", "sum": "sum", "sum_f64": "sum", "min": "min", "max": "max"}


def _agg_kernel_plan(aggs):
    """(kern, imap): `kern` is the deduplicated list of (fn, col) reductions
    the segment-reduce kernel computes (fn in sum|sum_f64|count — avg splits
    into a float64 sum plus a count); `imap` says how each select item
    assembles from kernel outputs."""
    kern: list[tuple[str, str]] = []
    imap: list[tuple] = []

    def _add(fn, col):
        spec = (fn, col)
        if spec in kern:
            return kern.index(spec)
        kern.append(spec)
        return len(kern) - 1

    for a in aggs:
        if a is None:
            imap.append(("group",))
            continue
        fn, col = a
        if fn == "count":
            imap.append(("count", _add("count", col)))
        elif fn == "avg":
            if col == "*":
                raise QueryError("avg(*) is not valid")
            imap.append(("avg", _add("sum_f64", col), _add("count", col)))
        else:
            if col == "*":
                raise QueryError(f"{fn}(*) is not valid")
            imap.append((fn, _add(fn, col)))
    return kern, imap


def _kernel_routable(batch, kern) -> bool:
    """True when every reduced column is numeric (count only reads validity,
    so its argument may be any type); object/bool columns keep the host
    fallback, zero rows produce zero groups without a kernel."""
    if batch.num_rows == 0:
        return False
    for fn, col in kern:
        if fn == "count":
            continue
        if np.asarray(batch.column(col).values).dtype.kind not in "iuf":
            return False
    return True


def _kernel_columns(batch, kern):
    """Materialize kern specs against a batch: (values, valid) pairs plus
    the segment_reduce fn per column."""
    n = batch.num_rows
    cols, fns = [], []
    for fn, col in kern:
        if fn == "count":
            valid = None if col == "*" else batch.column(col).validity
            cols.append((np.ones(n, np.int64), valid))
            fns.append("sum")
        else:
            c = batch.column(col)
            v = np.asarray(c.values)
            if fn == "sum_f64":
                v = v.astype(np.float64, copy=False)
            cols.append((v, c.validity))
            fns.append("sum" if fn == "sum_f64" else fn)
    return cols, tuple(fns)


def _encode_group_lanes(batch, group_cols):
    """Group keys → uint32 code lanes (ops.dicts.encode_column: code-backed
    columns stay compressed, NULL rows carry the sentinel code)."""
    from ..ops.dicts import encode_column

    pools, codes_list = [], []
    for g in group_cols:
        pool, codes = encode_column(batch.column(g))
        pools.append(pool)
        codes_list.append(codes)
    return pools, codes_list, np.column_stack(codes_list)


def _assemble_group_batch(schema, items, aggs, imap, group_cols, pools, group_codes,
                          outs, anyv, first_pos) -> "ColumnBatch":
    """Kernel outputs → the grouped result batch, rows in first-appearance
    order (argsort of each group's minimum input position — for distributed
    partials the positions are GLOBAL row numbers, so the combined output
    ordering is exactly the single-process one)."""
    from ..data.batch import ColumnBatch
    from ..types import BIGINT, DOUBLE, DataField, RowType

    order = np.argsort(first_pos, kind="stable")
    names, types, columns = [], [], []
    for item, agg, spec in zip(items, aggs, imap):
        if spec[0] == "group":
            name = item.strip("`")
            gi = group_cols.index(name)
            pool = pools[gi]
            sent = len(pool)
            vals = [
                None if c == sent else (pool[c].item() if hasattr(pool[c], "item") else pool[c])
                for c in group_codes[gi][order].tolist()
            ]
            names.append(name)
            types.append(schema.field(name).type)
            columns.append(vals)
            continue
        label = re.sub(r"\s+", "", item).lower()
        if spec[0] == "count":
            names.append(label)
            types.append(BIGINT())
            columns.append(outs[spec[1]][order].astype(np.int64).tolist())
        elif spec[0] == "avg":
            s = outs[spec[1]][order]
            c = outs[spec[2]][order]
            names.append(label)
            types.append(DOUBLE())
            columns.append([float(s[j] / c[j]) if c[j] else None for j in range(len(c))])
        else:  # sum / min / max
            o = outs[spec[1]][order].tolist()
            av = anyv[spec[1]][order]
            names.append(label)
            types.append(schema.field(agg[1]).type)
            columns.append([o[j] if av[j] else None for j in range(len(o))])
    rt = RowType(tuple(DataField(i, nm, ty) for i, (nm, ty) in enumerate(zip(names, types))))
    return ColumnBatch.from_pydict(rt, dict(zip(names, columns)))


def _device_group_aggregate(batch, items, aggs, group_cols, kern, imap, engine):
    from ..ops.aggregates import segment_reduce

    pools, codes_list, lanes = _encode_group_lanes(batch, group_cols)
    cols, fns = _kernel_columns(batch, kern)
    rep, outs, anyv, first_pos = segment_reduce(lanes, cols, fns, engine=engine)
    group_codes = [c[rep] for c in codes_list]
    return _assemble_group_batch(batch.schema, items, aggs, imap, group_cols,
                                 pools, group_codes, outs, anyv, first_pos)


def _group_aggregate(batch: "ColumnBatch", items, aggs, group_cols, engine="xla") -> "ColumnBatch":
    """Vectorized GROUP BY. The main path encodes group keys as uint32 code
    lanes and reduces on device via ops.aggregates.segment_reduce (ISSUE 16:
    the same kernel the cluster workers run for partial aggregates); object
    or bool aggregate arguments and empty inputs keep the host reduceat
    path. Output rows are in first-appearance order of each group's key,
    matching a streaming aggregator."""
    from ..data.batch import ColumnBatch
    from ..types import BIGINT, DOUBLE, DataField, RowType

    n = batch.num_rows
    for g in group_cols:
        if g not in batch.schema:
            raise QueryError(f"unknown GROUP BY column {g!r}")
    kern, imap = _agg_kernel_plan(aggs)
    if _kernel_routable(batch, kern):
        return _device_group_aggregate(batch, items, aggs, group_cols, kern, imap, engine)

    def _codes(col):
        """Dense group codes for one column, null-aware: NULL rows form their
        own group (SQL GROUP BY semantics); sentinel-filled values never
        merge with real values."""
        vals = np.asarray(col.values)
        valid = col.validity
        if (valid is None or valid.all()) and vals.dtype != object:
            _, codes = np.unique(vals, return_inverse=True)
            return codes
        if valid is None or valid.all():
            try:  # pure-string object columns sort fine
                _, codes = np.unique(vals, return_inverse=True)
                return codes
            except TypeError:
                pass
        mapping: dict = {}
        codes = np.empty(n, dtype=np.int64)
        vlist = vals.tolist() if vals.dtype != object else vals
        for i in range(n):
            key = None if (valid is not None and not valid[i]) else vlist[i]
            codes[i] = mapping.setdefault(key, len(mapping))
        return codes

    if n == 0:
        gid = np.empty(0, dtype=np.int64)
        uniq_first = np.empty(0, dtype=np.int64)
    else:
        gid = np.zeros(n, dtype=np.int64)
        for g in group_cols:
            codes = _codes(batch.column(g))
            gid = gid * (int(codes.max()) + 1 if len(codes) else 1) + codes
        # remap combined ids to dense group numbers in first-appearance order
        _, first_idx, inv = np.unique(gid, return_index=True, return_inverse=True)
        rank = np.argsort(np.argsort(first_idx))  # unique-id index -> appearance rank
        gid = rank[inv]
        uniq_first = np.sort(first_idx)  # each group's first row, appearance order

    n_groups = len(uniq_first)
    row_order = np.argsort(gid, kind="stable")
    sorted_gid = gid[row_order]
    starts = np.searchsorted(sorted_gid, np.arange(n_groups))
    counts = np.diff(np.concatenate([starts, [n]]))

    names, types, columns = [], [], []
    for item, agg in zip(items, aggs):
        if agg is None:  # a group column: its value at each group's first row
            name = item.strip("`")
            col = batch.column(name)
            arr = np.asarray(col.values)[uniq_first].tolist()
            if col.validity is not None:  # NULL group key surfaces as None
                arr = [None if not col.validity[i] else v for i, v in zip(uniq_first.tolist(), arr)]
            names.append(name)
            types.append(batch.schema.field(name).type)
            columns.append(arr)
            continue
        fn, colname = agg
        label = re.sub(r"\s+", "", item).lower()
        if fn == "count":
            if colname == "*":
                vals_out = counts.astype(np.int64).tolist()
            else:
                c = batch.column(colname)
                valid = c.validity if c.validity is not None else np.ones(n, dtype=bool)
                vals_out = (
                    np.add.reduceat(valid[row_order].astype(np.int64), starts).tolist()
                    if n else []
                )
            names.append(label); types.append(BIGINT()); columns.append(vals_out)
            continue
        if colname == "*":
            raise QueryError(f"{fn}(*) is not valid")
        c = batch.column(colname)
        ty = DOUBLE() if fn == "avg" else batch.schema.field(colname).type
        vals = np.asarray(c.values)[row_order]
        valid = c.validity
        if vals.dtype == object or (valid is not None and not valid.all()):
            # null-aware / object fallback: per-group reduction over the
            # VALID values only (a fully-null group aggregates to NULL)
            sorted_valid = (valid[row_order] if valid is not None else np.ones(n, dtype=bool))
            out = []
            py_vals = vals.tolist() if vals.dtype != object else vals
            for gi in range(n_groups):
                lo = int(starts[gi])
                hi = lo + int(counts[gi])
                vv = [py_vals[i] for i in range(lo, hi) if sorted_valid[i]]
                if not vv:
                    out.append(None)
                elif fn == "sum":
                    out.append(sum(vv))
                elif fn == "min":
                    out.append(min(vv))
                elif fn == "max":
                    out.append(max(vv))
                else:
                    out.append(float(sum(vv)) / len(vv))
        elif fn == "sum":
            out = (np.add.reduceat(vals, starts) if n else np.zeros(0, vals.dtype)).tolist()
        elif fn == "min":
            out = (np.minimum.reduceat(vals, starts) if n else np.zeros(0, vals.dtype)).tolist()
        elif fn == "max":
            out = (np.maximum.reduceat(vals, starts) if n else np.zeros(0, vals.dtype)).tolist()
        else:  # avg
            out = ((np.add.reduceat(vals.astype(np.float64), starts) / counts) if n else np.zeros(0)).tolist()
        names.append(label); types.append(ty); columns.append(out)

    schema = RowType(tuple(DataField(i, nm, ty) for i, (nm, ty) in enumerate(zip(names, types))))
    return ColumnBatch.from_pydict(schema, dict(zip(names, columns)))
