"""Minimal SELECT over tables: the query half of the SQL surface.

The reference leaves SELECT to host engines (Flink/Spark/Hive load tables via
their connector factories — FlinkTableFactory.java, PaimonInputFormat.java);
this rig has no installable engine (zero-egress: no duckdb/polars wheels —
see README "engine integration"), so the protocol-level surface
(`arrow_dataset`, Arrow Flight) is paired with this self-contained evaluator
covering the query shapes maintenance runbooks actually use::

    SELECT a, b FROM db.t WHERE k >= 10 AND s LIKE 'x%' ORDER BY a DESC LIMIT 5
    SELECT * FROM db.t$snapshots                    -- system tables work too
    SELECT count(*), sum(v), min(v) FROM db.t WHERE k < 100

Pushdown is real, not cosmetic: WHERE lowers onto the predicate algebra
(file/row-group skipping via stats + bloom indexes), the projection prunes
column decode, and a bare LIMIT n stops the scan early — the same paths a
planner-bearing engine would drive through `arrow_dataset`.
"""

from __future__ import annotations

import re
from typing import TYPE_CHECKING, Any

import numpy as np

from .expr import ExprError, _Parser, _tokenize, parse_expr, to_predicate

if TYPE_CHECKING:
    from ..catalog import Catalog
    from ..data.batch import ColumnBatch

__all__ = ["query", "QueryError"]


class QueryError(ValueError):
    pass


_SELECT_RE = re.compile(
    r"^\s*SELECT\s+(?P<cols>.*?)\s+FROM\s+(?P<table>`?[\w.$]+`?)"
    r"(?:\s+WHERE\s+(?P<where>.*?))?"
    r"(?:\s+ORDER\s+BY\s+(?P<order>.*?))?"
    r"(?:\s+LIMIT\s+(?P<limit>\d+))?\s*;?\s*$",
    re.I | re.S,
)

_AGG_FNS = ("count", "sum", "min", "max", "avg")


def _split_select_list(cols: str) -> list[str]:
    """Split the projection list on top-level commas (parens guard fn args)."""
    parts, depth, buf = [], 0, []
    for c in cols:
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        if c == "," and depth == 0:
            parts.append("".join(buf).strip())
            buf = []
        else:
            buf.append(c)
    tail = "".join(buf).strip()
    if tail:
        parts.append(tail)
    return parts


def _parse_agg(item: str):
    """'sum(v)' -> ('sum', 'v') | 'count(*)' -> ('count', '*') | None."""
    m = re.match(r"^(\w+)\s*\(\s*(\*|`?\w+`?)\s*\)$", item)
    if m and m.group(1).lower() in _AGG_FNS:
        return m.group(1).lower(), m.group(2).strip("`")
    return None


def query(catalog: "Catalog", statement: str) -> "ColumnBatch":
    """Execute one SELECT statement; returns the result as a ColumnBatch."""
    m = _SELECT_RE.match(statement)
    if not m:
        raise QueryError(f"not a SELECT statement: {statement!r}")
    table_name = m.group("table").strip("`")
    t = catalog.get_table(table_name)

    where_text = m.group("where")
    pred = None
    if where_text:
        try:
            pred = to_predicate(parse_expr(where_text), where_text)
        except ExprError as e:
            raise QueryError(str(e)) from e

    cols_text = m.group("cols").strip()
    items = _split_select_list(cols_text)
    aggs = [_parse_agg(i) for i in items]
    is_agg = any(a is not None for a in aggs)
    if is_agg and not all(a is not None for a in aggs):
        raise QueryError("cannot mix aggregate and plain columns without GROUP BY")

    order_text = m.group("order")
    limit = int(m.group("limit")) if m.group("limit") else None

    if not hasattr(t, "new_read_builder"):
        # system tables ($snapshots, $files, ...) are static batches:
        # evaluate the clauses directly, no scan pushdown to drive
        out = t.read()
        if pred is not None:
            mask = pred.eval(out)
            if not mask.all():
                out = out.filter(mask)
    else:
        rb = t.new_read_builder()
        if pred is not None:
            rb = rb.with_filter(pred)
        if not is_agg:
            if cols_text != "*":
                names = [i.strip("`") for i in items]
                for n in names:
                    if n not in t.row_type:
                        raise QueryError(f"unknown column {n!r} in {table_name}")
                # ORDER BY columns must survive until after the sort
                order_cols = _order_cols(order_text)
                rb = rb.with_projection(list(dict.fromkeys(names + order_cols)))
            if limit is not None and order_text is None:
                rb = rb.with_limit(limit)
        out = rb.new_read().read_all(rb.new_scan().plan())

    if is_agg:
        return _aggregate(out, items, aggs)

    if order_text:
        idx = _order_index(out, order_text)
        out = out.take(idx)
    if limit is not None:
        out = out.slice(0, min(limit, out.num_rows))
    if cols_text != "*":
        out = out.select([i.strip("`") for i in items])
    return out


def _order_cols(order_text: str | None) -> list[str]:
    if not order_text:
        return []
    cols = []
    for part in order_text.split(","):
        cols.append(part.split()[0].strip("`"))
    return cols


def _order_index(batch: "ColumnBatch", order_text: str) -> np.ndarray:
    keys = []
    for part in reversed([p.strip() for p in order_text.split(",")]):
        toks = part.split()
        name = toks[0].strip("`")
        desc = len(toks) > 1 and toks[1].lower() == "desc"
        if len(toks) > 2 or (len(toks) == 2 and toks[1].lower() not in ("asc", "desc")):
            raise QueryError(f"bad ORDER BY term {part!r}")
        if name not in batch.schema:
            raise QueryError(f"unknown ORDER BY column {name!r}")
        vals = np.asarray(batch.column(name).values)
        if desc:
            if vals.dtype.kind in "iuf":
                vals = -vals
            else:  # lexsort has no per-key descending: rank-invert instead
                _, inv = np.unique(vals, return_inverse=True)
                vals = -inv
        keys.append(vals)
    return np.lexsort(keys)


def _aggregate(batch: "ColumnBatch", items: list[str], aggs) -> "ColumnBatch":
    from ..data.batch import ColumnBatch
    from ..types import BIGINT, DOUBLE, DataField, RowType

    names, types, values = [], [], []
    for item, (fn, col) in zip(items, aggs):
        label = re.sub(r"\s+", "", item).lower()
        if fn == "count":
            if col == "*":
                v: Any = batch.num_rows
            else:
                c = batch.column(col)
                v = int(c.validity.sum()) if c.validity is not None else batch.num_rows
            ty = BIGINT()
        else:
            if col == "*":
                raise QueryError(f"{fn}(*) is not valid")
            c = batch.column(col)
            vals = np.asarray(c.values)
            if c.validity is not None:
                vals = vals[c.validity]
            def _py(x):
                return x.item() if hasattr(x, "item") else x

            if vals.size == 0:
                v, ty = None, DOUBLE()
            elif fn == "sum":
                v, ty = _py(vals.sum()), batch.schema.field(col).type
            elif fn == "min":
                v, ty = _py(vals.min()), batch.schema.field(col).type
            elif fn == "max":
                v, ty = _py(vals.max()), batch.schema.field(col).type
            else:  # avg
                v, ty = float(vals.mean()), DOUBLE()
        names.append(label)
        types.append(ty)
        values.append(v)
    schema = RowType(tuple(DataField(i, n, ty) for i, (n, ty) in enumerate(zip(names, types))))
    return ColumnBatch.from_pydict(schema, {n: [v] for n, v in zip(names, values)})
