"""INSERT statements: the write half of the string surface.

    INSERT INTO db.t VALUES (1, 'x', 2.5), (2, 'y', NULL)
    INSERT INTO db.t (k, s) VALUES (3, 'z')          -- missing columns -> NULL
    INSERT INTO db.t SELECT ... FROM db.src WHERE ...
    INSERT OVERWRITE db.t VALUES (...) / SELECT ...  -- overwrite commit

The reference's engines lower INSERT onto the batch write path
(FlinkTableSink / SparkWrite); this lowers onto the same
`new_batch_write_builder` — upsert semantics on PK tables, append otherwise,
OVERWRITE via the overwrite commit kind.
"""

from __future__ import annotations

import re
from typing import TYPE_CHECKING, Any

from .expr import ExprError, _Parser, _const_fold, _NOT_CONST, _tokenize

if TYPE_CHECKING:
    from ..catalog import Catalog

__all__ = ["insert", "DmlError"]


class DmlError(ValueError):
    pass


_INSERT_RE = re.compile(
    r"^\s*INSERT\s+(?P<mode>INTO|OVERWRITE)\s+`?(?P<name>[\w.]+)`?\s*"
    r"(?:\((?P<cols>[^)]*)\)\s*)?"
    r"(?P<body>VALUES\s*.*|SELECT\s+.*?)\s*;?\s*$",
    re.I | re.S,
)


def _parse_rows(values_text: str, n_cols: int, src: str) -> list[list[Any]]:
    """VALUES (lit, ...), (lit, ...) -> row lists (literals const-folded).
    Every parse failure (tokenizer AND grammar) surfaces as DmlError."""
    try:
        p = _Parser(_tokenize(values_text), src)
        rows: list[list[Any]] = []
        while True:
            p.expect("op", "(")
            row = []
            while True:
                node = p.parse_operand()
                v = _const_fold(node)
                if v is _NOT_CONST:
                    raise DmlError(f"VALUES entries must be literals in {src!r}")
                row.append(v)
                if p.peek() == ("op", ","):
                    p.next()
                    continue
                break
            p.expect("op", ")")
            if len(row) != n_cols:
                raise DmlError(f"row has {len(row)} values, expected {n_cols} in {src!r}")
            rows.append(row)
            if p.peek() == ("op", ","):
                p.next()
                continue
            if p.peek()[0] == "eof":
                return rows
            raise DmlError(f"trailing tokens after VALUES in {src!r}")
    except ExprError as e:
        raise DmlError(str(e)) from e


def insert(catalog: "Catalog", statement: str) -> dict:
    m = _INSERT_RE.match(statement)
    if not m:
        raise DmlError(f"not an INSERT statement: {statement!r}")
    try:
        t = catalog.get_table(m.group("name"))
    except FileNotFoundError:
        raise DmlError(f"table {m.group('name')} does not exist") from None
    overwrite = m.group("mode").upper() == "OVERWRITE"
    cols = (
        [c.strip().strip("`") for c in m.group("cols").split(",") if c.strip()]
        if m.group("cols")
        else t.row_type.field_names
    )
    for c in cols:
        if c not in t.row_type:
            raise DmlError(f"unknown column {c!r} in {m.group('name')}")

    body = m.group("body")
    if re.match(r"^SELECT\b", body, re.I):
        from .select import QueryError, query

        try:
            result = query(catalog, body)
        except QueryError as e:
            raise DmlError(str(e)) from e
        if len(result.schema.field_names) != len(cols):
            raise DmlError(
                f"SELECT produces {len(result.schema.field_names)} columns, "
                f"INSERT target has {len(cols)}"
            )
        data = {}
        for c, src_name in zip(cols, result.schema.field_names):
            col = result.column(src_name)
            if col.validity is not None and not col.validity.all():
                data[c] = col.to_pylist()  # nulls must survive as None
            else:
                data[c] = col.values  # numpy passthrough, no python round trip
        n = result.num_rows
    else:
        rows = _parse_rows(body[len("VALUES"):], len(cols), statement)
        data = {c: [r[i] for r in rows] for i, c in enumerate(cols)}
        n = len(rows)

    missing = [f.name for f in t.row_type.fields if f.name not in cols]
    for name in missing:
        if not t.row_type.field(name).type.nullable:
            raise DmlError(f"column {name!r} is NOT NULL and has no value")
        data[name] = [None] * n
    # explicit NULLs against NOT NULL columns are rejected the same way
    for name in cols:
        if not t.row_type.field(name).type.nullable:
            vals = data[name]
            it = vals.tolist() if hasattr(vals, "tolist") else vals
            if any(v is None for v in it):
                raise DmlError(f"column {name!r} is NOT NULL; NULL value in row")

    wb = t.new_batch_write_builder()
    if overwrite:
        wb = wb.with_overwrite()
    w = wb.new_write()
    w.write({name: data[name] for name in t.row_type.field_names})
    wb.new_commit().commit(w.prepare_commit())
    return {"inserted": n, "table": m.group("name"), "overwrite": overwrite}
