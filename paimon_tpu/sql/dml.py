"""DML statements: the write half of the string surface.

    INSERT INTO db.t VALUES (1, 'x', 2.5), (2, 'y', NULL)
    INSERT INTO db.t (k, s) VALUES (3, 'z')          -- missing columns -> NULL
    INSERT INTO db.t SELECT ... FROM db.src WHERE ...
    INSERT OVERWRITE db.t VALUES (...) / SELECT ...  -- overwrite commit
    UPDATE db.t SET v = v + 1, s = 'x' WHERE k < 10
    DELETE FROM db.t WHERE k >= 100
    TRUNCATE TABLE db.t

The reference's engines lower these onto the batch write path
(FlinkTableSink / SparkWrite; UpdatePaimonTableCommand /
DeleteFromPaimonTableCommand for the row-level commands); this lowers onto
the same `new_batch_write_builder` / rowops — upsert semantics on PK
tables, append otherwise, OVERWRITE/TRUNCATE via the overwrite commit kind.
"""

from __future__ import annotations

import re
from typing import TYPE_CHECKING, Any

from .expr import (
    ExprError,
    _NOT_CONST,
    _Parser,
    _const_fold,
    _tokenize,
    batch_resolver,
    eval_value,
    parse_assignments,
    parse_where,
)

if TYPE_CHECKING:
    from ..catalog import Catalog

__all__ = ["insert", "update", "delete", "truncate", "DmlError"]


class DmlError(ValueError):
    pass


_INSERT_RE = re.compile(
    r"^\s*INSERT\s+(?P<mode>INTO|OVERWRITE)\s+`?(?P<name>[\w.]+)`?\s*"
    r"(?:\((?P<cols>[^)]*)\)\s*)?"
    r"(?P<body>VALUES\s*.*|SELECT\s+.*?)\s*;?\s*$",
    re.I | re.S,
)


def _parse_rows(values_text: str, n_cols: int, src: str) -> list[list[Any]]:
    """VALUES (lit, ...), (lit, ...) -> row lists (literals const-folded).
    Every parse failure (tokenizer AND grammar) surfaces as DmlError."""
    try:
        p = _Parser(_tokenize(values_text), src)
        rows: list[list[Any]] = []
        while True:
            p.expect("op", "(")
            row = []
            while True:
                node = p.parse_operand()
                v = _const_fold(node)
                if v is _NOT_CONST:
                    raise DmlError(f"VALUES entries must be literals in {src!r}")
                row.append(v)
                if p.peek() == ("op", ","):
                    p.next()
                    continue
                break
            p.expect("op", ")")
            if len(row) != n_cols:
                raise DmlError(f"row has {len(row)} values, expected {n_cols} in {src!r}")
            rows.append(row)
            if p.peek() == ("op", ","):
                p.next()
                continue
            if p.peek()[0] == "eof":
                return rows
            raise DmlError(f"trailing tokens after VALUES in {src!r}")
    except ExprError as e:
        raise DmlError(str(e)) from e


def insert(catalog: "Catalog", statement: str) -> dict:
    m = _INSERT_RE.match(statement)
    if not m:
        raise DmlError(f"not an INSERT statement: {statement!r}")
    t = _table(catalog, m.group("name"))
    overwrite = m.group("mode").upper() == "OVERWRITE"
    cols = (
        [c.strip().strip("`") for c in m.group("cols").split(",") if c.strip()]
        if m.group("cols")
        else t.row_type.field_names
    )
    for c in cols:
        if c not in t.row_type:
            raise DmlError(f"unknown column {c!r} in {m.group('name')}")

    body = m.group("body")
    if re.match(r"^SELECT\b", body, re.I):
        from .select import QueryError, query

        try:
            result = query(catalog, body)
        except QueryError as e:
            raise DmlError(str(e)) from e
        if len(result.schema.field_names) != len(cols):
            raise DmlError(
                f"SELECT produces {len(result.schema.field_names)} columns, "
                f"INSERT target has {len(cols)}"
            )
        data = {}
        for c, src_name in zip(cols, result.schema.field_names):
            col = result.column(src_name)
            if col.validity is not None and not col.validity.all():
                data[c] = col.to_pylist()  # nulls must survive as None
            else:
                data[c] = col.values  # numpy passthrough, no python round trip
        n = result.num_rows
    else:
        rows = _parse_rows(body[len("VALUES"):], len(cols), statement)
        data = {c: [r[i] for r in rows] for i, c in enumerate(cols)}
        n = len(rows)

    missing = [f.name for f in t.row_type.fields if f.name not in cols]
    for name in missing:
        if not t.row_type.field(name).type.nullable:
            raise DmlError(f"column {name!r} is NOT NULL and has no value")
        data[name] = [None] * n
    # explicit NULLs against NOT NULL columns are rejected the same way
    for name in cols:
        if not t.row_type.field(name).type.nullable:
            vals = data[name]
            it = vals.tolist() if hasattr(vals, "tolist") else vals
            if any(v is None for v in it):
                raise DmlError(f"column {name!r} is NOT NULL; NULL value in row")

    wb = t.new_batch_write_builder()
    if overwrite:
        wb = wb.with_overwrite()
    w = wb.new_write()
    w.write({name: data[name] for name in t.row_type.field_names})
    wb.new_commit().commit(w.prepare_commit())
    return {"inserted": n, "table": m.group("name"), "overwrite": overwrite}

_UPDATE_HEAD_RE = re.compile(
    r"^\s*UPDATE\s+`?(?P<name>[\w.]+)`?\s+SET\s+(?P<rest>.*?)\s*;?\s*$", re.I | re.S
)


def _split_on_where(text: str) -> tuple[str, str | None]:
    """Split 'SET-list [WHERE expr]' at the top-level WHERE keyword — quote-
    aware, so a string literal containing the word WHERE never splits."""
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "'":
            j = text.find("'", i + 1)
            while j != -1 and text[j : j + 2] == "''":
                j = text.find("'", j + 2)
            if j == -1:
                break  # unterminated: let the expression parser report it
            i = j + 1
            continue
        if text[i : i + 5].upper() == "WHERE" and (i == 0 or not text[i - 1].isalnum()) and (
            i + 5 >= n or not text[i + 5].isalnum()
        ):
            return text[:i].strip(), text[i + 5 :].strip()
        i += 1
    return text.strip(), None
_DELETE_RE = re.compile(
    r"^\s*DELETE\s+FROM\s+`?(?P<name>[\w.]+)`?(?:\s+WHERE\s+(?P<where>.*?))?\s*;?\s*$",
    re.I | re.S,
)
_TRUNCATE_RE = re.compile(r"^\s*TRUNCATE\s+TABLE\s+`?(?P<name>[\w.]+)`?\s*;?\s*$", re.I)


def _table(catalog: "Catalog", name: str):
    try:
        return catalog.get_table(name)
    except FileNotFoundError:
        raise DmlError(f"table {name} does not exist") from None


def update(catalog: "Catalog", statement: str) -> dict:
    """UPDATE t SET a = expr, ... [WHERE ...] -> Table.update_where.
    SET expressions may reference the row's own columns (v = v + 1),
    optionally qualified with the table name."""
    m = _UPDATE_HEAD_RE.match(statement)
    if not m:
        raise DmlError(f"not an UPDATE statement: {statement!r}")
    name = m.group("name")
    t = _table(catalog, name)
    sets_text, where_text = _split_on_where(m.group("rest"))
    try:
        assigns = parse_assignments(sets_text)
        pred = parse_where(where_text) if where_text else None
    except ExprError as e:
        raise DmlError(str(e)) from e
    if assigns and assigns[0][0] == "*":
        raise DmlError("UPDATE SET requires explicit column assignments")
    if pred is None:
        from ..data.predicate import is_not_null, is_null, or_

        # unconditional UPDATE: an always-true predicate (null-safe)
        c = t.row_type.field_names[0]
        pred = or_(is_null(c), is_not_null(c))

    # accept the table's short name, full identifier, and 't' as aliases
    aliases = {a for a in (name, name.split(".")[-1], "t") if a}

    def make_value(ast):
        def fn(batch):
            return eval_value(ast, batch_resolver({a: batch for a in aliases}), batch.num_rows)

        return fn

    assignments = {col: make_value(ast) for col, ast in assigns}
    try:
        n = t.update_where(pred, assignments)
    except (ValueError, KeyError) as e:
        raise DmlError(str(e)) from e
    return {"rows_updated": n, "table": name}


def delete(catalog: "Catalog", statement: str) -> dict:
    """DELETE FROM t WHERE ... -> table.delete_where (an explicit WHERE is
    required; TRUNCATE TABLE is the wipe-everything statement)."""
    m = _DELETE_RE.match(statement)
    if not m:
        raise DmlError(f"not a DELETE statement: {statement!r}")
    t = _table(catalog, m.group("name"))
    if not m.group("where"):
        raise DmlError("DELETE without WHERE: use TRUNCATE TABLE to wipe a table")
    try:
        pred = parse_where(m.group("where"))
    except ExprError as e:
        raise DmlError(str(e)) from e
    if pred is None:
        raise DmlError("DELETE without an effective filter: use TRUNCATE TABLE")
    return {"rows_deleted": t.delete_where(pred), "table": m.group("name")}


def truncate(catalog: "Catalog", statement: str) -> dict:
    """TRUNCATE TABLE t: one overwrite commit with no rows (time travel to
    the pre-truncate snapshot still works, as in the reference). The
    explicit match-all partition filter overrides dynamic-partition-
    overwrite, which would otherwise clear only the (zero) touched
    partitions and silently keep every row of a partitioned table."""
    m = _TRUNCATE_RE.match(statement)
    if not m:
        raise DmlError(f"not a TRUNCATE statement: {statement!r}")
    t = _table(catalog, m.group("name"))
    wb = t.new_batch_write_builder().with_overwrite(lambda p: True)
    w = wb.new_write()
    wb.new_commit().commit(w.prepare_commit())
    return {"truncated": m.group("name")}
