"""Resilience layer: retrying IO, deterministic fault points, crash recovery.

The durability story of the lake rests on one primitive — atomic-rename
snapshot commit over an eventually-flaky filesystem. This package makes every
hot path (plan -> merge read -> commit -> compact -> expire) survive transient
object-store faults and clean up after crashes:

- retry.py      transient-vs-permanent error classification + decorrelated-
                jitter backoff with per-op deadlines (RetryPolicy)
- fileio.py     RetryingFileIO, the FileIO wrapper installed by core/store.py
- faults.py     named crash points (armed by tests to kill a commit at exact
                protocol steps) — the deterministic half of the fault harness
                (the scripted FileIO schedules live in fs/testing.py)
- orphan.py     crash recovery: reachability walk over all live snapshots /
                changelogs / tags / branches and deletion of unreferenced
                files and stale .tmp.* siblings

Parity: the reference wraps object-store FileIOs in retry shells
(hadoop s3a retries / oss RetryPolicy) and ships orphan cleanup as
RemoveOrphanFilesAction over OrphanFilesClean.
"""

from .faults import CrashError, arm_crash_point, crash_point, disarm_crash_points
from .fileio import RetryingFileIO, wrap_file_io
from .orphan import remove_orphan_files
from .retry import IODeadlineExceeded, RetryPolicy, is_transient

__all__ = [
    "RetryPolicy",
    "RetryingFileIO",
    "wrap_file_io",
    "is_transient",
    "IODeadlineExceeded",
    "CrashError",
    "crash_point",
    "arm_crash_point",
    "disarm_crash_points",
    "remove_orphan_files",
]
