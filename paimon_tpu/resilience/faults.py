"""Named crash points: deterministic process-death simulation.

Instrumented code calls `crash_point("commit:manifests-written")` at exact
protocol steps; tests arm a point to kill the operation there (raise
CrashError, simulating the process dying with no cleanup running beyond what
an exception unwinds), to HARD-KILL the whole process (`kill=True` →
``os._exit(137)``, the process-grain death a SIGKILLed Flink task JVM dies —
no exception unwinding, no finally blocks, no atexit, torn `.tmp` files and
unflushed buffers left exactly where they were), or to run an arbitrary
action at the point — the hook that lets a test deterministically interleave
a competing commit between one committer's latest-snapshot read and its
snapshot CAS.

Env arming (the subprocess seam): ``PAIMON_TPU_CRASH_POINT`` is parsed when
this module imports (and re-parseable via `arm_from_env`), so a supervisor
can arm a crash in a child process it is about to spawn without any code
handshake:

    PAIMON_TPU_CRASH_POINT=<name>[:<nth>][:kill][,<spec>...]

`nth` (default 1) is the 1-based hit that fires; `:kill` selects the
hard-death mode (without it the point raises CrashError in-process). E.g.
``commit:manifests-written:2:kill`` lets the first commit land and kills the
process dead in the middle of the second.

Crash-point map of the commit protocol (FileStoreCommit._try_commit):

  commit:before-manifests    inside the (optional) catalog lock, after the
                             latest-snapshot read + conflict check, before
                             any manifest write. Crash leaves nothing.
  commit:manifests-written   all manifests / manifest lists / changelog and
                             index manifests durable, snapshot file NOT yet
                             renamed in. Crash leaves orphan manifests (and
                             possibly torn .tmp siblings) that no reader can
                             reach; remove_orphan_files reclaims them.
  commit:snapshot-committed  the snapshot CAS succeeded; hints not yet
                             written. Crash leaves a fully-visible commit —
                             replaying the committable must be filtered out
                             by filter_committed (idempotence contract), and
                             a journaling writer must resolve the lost ack
                             from the snapshot chain (find_landed_append).

Writer-side points (MergeTreeWriter, the flush/encode pipeline):

  flush:before-dispatch      the memtable is full but not yet drained; no
                             merge dispatched, no file written. Crash loses
                             only unacknowledged buffered rows.
  flush:files-written        the flushed level-0 data files are durable on
                             disk but referenced by no snapshot (the commit
                             that would reference them never ran). Crash
                             leaves orphan data files; remove_orphan_files
                             reclaims them.

Unarmed points are a dict lookup on a module-level map — zero cost in
production paths.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Callable

__all__ = [
    "CrashError",
    "crash_point",
    "arm_crash_point",
    "arm_from_env",
    "disarm_crash_points",
    "COMMIT_CRASH_POINTS",
    "WRITER_CRASH_POINTS",
    "CLUSTER_CRASH_POINTS",
    "SERVICE_CRASH_POINTS",
    "RESCALE_CRASH_POINTS",
    "HANDOFF_CRASH_POINTS",
    "ALL_CRASH_POINTS",
    "KILL_EXIT_CODE",
]

# the canonical points instrumented in core/commit.py (tests iterate this)
COMMIT_CRASH_POINTS = (
    "commit:before-manifests",
    "commit:manifests-written",
    "commit:snapshot-committed",
)

# the writer-side points instrumented in core/writer.py
WRITER_CRASH_POINTS = (
    "flush:before-dispatch",
    "flush:files-written",
)

# the cluster-worker points instrumented in service/cluster.py: a worker
# dying mid-compaction (rewrite executed, CommitMessage never shipped) and
# one dying between prepare_commit and shipping its ingest round
CLUSTER_CRASH_POINTS = (
    "cluster:compact-executing",
    "cluster:before-ship",
)

# the service-plane points: a gateway writer client dying between its put
# landing on the wire and journaling the ack (service/mega_soak.py), and a
# subscriber dying right after fsyncing a received batch into its journal
# (service/subscription.py) — both leave a landed-but-unacked protocol edge
# the respawned incarnation must resolve from durable state alone
SERVICE_CRASH_POINTS = (
    "gateway:put-sent",
    "subscriber:batch-journaled",
)

# the elastic-topology points (table/rescale.py + service/cluster.py): a
# worker dying with its rescale rewrite files durable but the shipment
# never prepared/sent (orphan files; the coordinator re-queues the buckets
# on whoever owns them next), and a retiring worker dying after draining
# but before its retire RPC (the planned handoff degrades to the
# missed-heartbeat death path — same reassignment, plus the timeout). The
# coordinator's commit half needs no points of its own: the schema bump is
# a CAS and the OVERWRITE snapshot runs through FileStoreCommit._try_commit,
# which the commit:* points already cover.
RESCALE_CRASH_POINTS = (
    "rescale:files-written",
    "rescale:before-ship",
)
HANDOFF_CRASH_POINTS = ("handoff:before-retire",)

ALL_CRASH_POINTS = (
    COMMIT_CRASH_POINTS
    + WRITER_CRASH_POINTS
    + CLUSTER_CRASH_POINTS
    + SERVICE_CRASH_POINTS
    + RESCALE_CRASH_POINTS
    + HANDOFF_CRASH_POINTS
)

# 128 + SIGKILL: a hard death at a crash point reports like a kill -9 victim
KILL_EXIT_CODE = 137

ENV_VAR = "PAIMON_TPU_CRASH_POINT"


class CrashError(BaseException):
    """Simulated process death at a named crash point.

    Deliberately NOT an Exception subclass: production code that swallows
    broad `except Exception` (cleanup paths, best-effort hints) must not
    accidentally survive a simulated crash — a real SIGKILL wouldn't."""

    def __init__(self, point: str):
        super().__init__(f"simulated crash at {point}")
        self.point = point


@dataclass
class _Armed:
    skip: int = 0  # let this many hits pass before acting
    count: int = 1  # act on this many hits after the skip (<=0 = forever)
    action: Callable[[], None] | None = None  # None = raise CrashError
    kill: bool = False  # hard death: os._exit, no unwinding at all
    hits: int = 0
    fired: int = 0


_armed: dict[str, _Armed] = {}
_lock = threading.Lock()


def arm_crash_point(
    name: str,
    skip: int = 0,
    count: int = 1,
    action: Callable[[], None] | None = None,
    kill: bool = False,
) -> None:
    """Arm `name`: after `skip` passes, the next `count` hits either raise
    CrashError (action=None), hard-kill the process (kill=True — use only in
    a subprocess you own!), or run `action()` at the point (the action may
    itself raise to crash, or just mutate the world — e.g. land a competing
    commit — and return to let the operation continue)."""
    with _lock:
        _armed[name] = _Armed(skip=skip, count=count, action=action, kill=kill)


def disarm_crash_points(*names: str) -> None:
    """Disarm the given points, or ALL points when called with none."""
    with _lock:
        if names:
            for n in names:
                _armed.pop(n, None)
        else:
            _armed.clear()


def _parse_spec(spec: str) -> tuple[str, int, bool]:
    """'<name>[:<nth>][:kill]' — name itself contains colons, so nth/kill
    are peeled off the right."""
    spec = spec.strip()
    kill = False
    if spec.endswith(":kill"):
        kill = True
        spec = spec[: -len(":kill")]
    name, _, nth = spec.rpartition(":")
    if name and nth.isdigit():
        return name, int(nth), kill
    return spec, 1, kill


def arm_from_env(value: str | None = None) -> list[str]:
    """Arm crash points from the PAIMON_TPU_CRASH_POINT spec (or an explicit
    `value`). Returns the armed point names. Called at module import so a
    freshly spawned subprocess is armed before any table code runs."""
    spec = os.environ.get(ENV_VAR) if value is None else value
    if not spec:
        return []
    armed = []
    for item in spec.split(","):
        if not item.strip():
            continue
        name, nth, kill = _parse_spec(item)
        arm_crash_point(name, skip=nth - 1, count=1, kill=kill)
        armed.append(name)
    return armed


def crash_point(name: str) -> None:
    """Called by instrumented code. No-op unless a test armed `name`."""
    if not _armed:  # fast path: nothing armed anywhere
        return
    with _lock:
        st = _armed.get(name)
        if st is None:
            return
        st.hits += 1
        if st.hits <= st.skip:
            return
        if st.count > 0 and st.fired >= st.count:
            return
        st.fired += 1
        action = st.action
        kill = st.kill
    if kill:
        # a real process death: no exception unwinding, no cleanup, no
        # atexit — buffered file contents and tmp siblings stay torn
        os._exit(KILL_EXIT_CODE)
    if action is None:
        raise CrashError(name)
    action()


# subprocess seam: a supervisor arms its children via the environment
arm_from_env()
