"""Named crash points: deterministic process-death simulation.

Instrumented code calls `crash_point("commit:manifests-written")` at exact
protocol steps; tests arm a point to kill the operation there (raise
CrashError, simulating the process dying with no cleanup running beyond what
an exception unwinds) or to run an arbitrary action at the point — the hook
that lets a test deterministically interleave a competing commit between one
committer's latest-snapshot read and its snapshot CAS.

Crash-point map of the commit protocol (FileStoreCommit._try_commit):

  commit:before-manifests    inside the (optional) catalog lock, after the
                             latest-snapshot read + conflict check, before
                             any manifest write. Crash leaves nothing.
  commit:manifests-written   all manifests / manifest lists / changelog and
                             index manifests durable, snapshot file NOT yet
                             renamed in. Crash leaves orphan manifests (and
                             possibly torn .tmp siblings) that no reader can
                             reach; remove_orphan_files reclaims them.
  commit:snapshot-committed  the snapshot CAS succeeded; hints not yet
                             written. Crash leaves a fully-visible commit —
                             replaying the committable must be filtered out
                             by filter_committed (idempotence contract).

Unarmed points are a dict lookup on a module-level map — zero cost in
production paths.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable

__all__ = ["CrashError", "crash_point", "arm_crash_point", "disarm_crash_points", "COMMIT_CRASH_POINTS"]

# the canonical points instrumented in core/commit.py (tests iterate this)
COMMIT_CRASH_POINTS = (
    "commit:before-manifests",
    "commit:manifests-written",
    "commit:snapshot-committed",
)


class CrashError(BaseException):
    """Simulated process death at a named crash point.

    Deliberately NOT an Exception subclass: production code that swallows
    broad `except Exception` (cleanup paths, best-effort hints) must not
    accidentally survive a simulated crash — a real SIGKILL wouldn't."""

    def __init__(self, point: str):
        super().__init__(f"simulated crash at {point}")
        self.point = point


@dataclass
class _Armed:
    skip: int = 0  # let this many hits pass before acting
    count: int = 1  # act on this many hits after the skip (<=0 = forever)
    action: Callable[[], None] | None = None  # None = raise CrashError
    hits: int = 0
    fired: int = 0


_armed: dict[str, _Armed] = {}
_lock = threading.Lock()


def arm_crash_point(
    name: str,
    skip: int = 0,
    count: int = 1,
    action: Callable[[], None] | None = None,
) -> None:
    """Arm `name`: after `skip` passes, the next `count` hits either raise
    CrashError (action=None) or run `action()` at the point (the action may
    itself raise to crash, or just mutate the world — e.g. land a competing
    commit — and return to let the operation continue)."""
    with _lock:
        _armed[name] = _Armed(skip=skip, count=count, action=action)


def disarm_crash_points(*names: str) -> None:
    """Disarm the given points, or ALL points when called with none."""
    with _lock:
        if names:
            for n in names:
                _armed.pop(n, None)
        else:
            _armed.clear()


def crash_point(name: str) -> None:
    """Called by instrumented code. No-op unless a test armed `name`."""
    if not _armed:  # fast path: nothing armed anywhere
        return
    with _lock:
        st = _armed.get(name)
        if st is None:
            return
        st.hits += 1
        if st.hits <= st.skip:
            return
        if st.count > 0 and st.fired >= st.count:
            return
        st.fired += 1
        action = st.action
    if action is None:
        raise CrashError(name)
    action()
