"""Retry policy: error classification + decorrelated-jitter backoff.

Transient faults are retried with exponential backoff and decorrelated
jitter; permanent faults (missing file, lost CAS, permission) propagate
immediately — retrying them only hides bugs and burns the op deadline.
Classification is an ALLOWLIST: connection/timeout exception types, OSErrors
whose errno denotes a moment-in-time fault (EIO, EAGAIN, ETIMEDOUT, …), and
exceptions carrying an explicit `transient = True` attribute — the marker
store adapters (and the fault harness's ArtificialException) set on
retryable blips that don't fit a stdlib type. Everything else, including
OSErrors without a recognized errno (wrapper-raised namespace collisions,
adapter bugs), is permanent and surfaces on the first attempt.

Backoff follows the decorrelated-jitter scheme (sleep_n = U(base, 3*prev)
capped at max): successive retries spread out AND desynchronize, so N writers
hammered by the same outage don't retry in lockstep against the store.
"""

from __future__ import annotations

import errno
import random
import threading
import time
from dataclasses import dataclass, field

__all__ = ["IODeadlineExceeded", "is_transient", "RetryPolicy"]


class IODeadlineExceeded(TimeoutError):
    """The per-op deadline (fs.io.timeout) elapsed across retries."""


# OSError errnos that denote a fault of the moment (store or network), not a
# property of the request — the only errnos worth a retry. Deliberately
# absent: ENOENT/EEXIST/EACCES (namespace/permission facts), ENOSPC/EDQUOT
# (a full disk does not drain on a 10ms backoff), EINVAL & friends (bugs).
_TRANSIENT_ERRNOS = frozenset(
    x
    for x in (
        errno.EIO,
        errno.EAGAIN,
        errno.EBUSY,
        errno.ETIMEDOUT,
        errno.ECONNRESET,
        errno.ECONNREFUSED,
        errno.ECONNABORTED,
        errno.EPIPE,
        errno.ENETDOWN,
        errno.ENETUNREACH,
        errno.ENETRESET,
        errno.EHOSTDOWN,
        errno.EHOSTUNREACH,
        errno.ESTALE,
        getattr(errno, "EREMOTEIO", None),
    )
    if x is not None
)

# Exception types that are permanent regardless of errno. NotImplementedError
# covers FileIO stubs; Value/TypeError are caller bugs surfacing through IO.
_PERMANENT_TYPES = (
    FileNotFoundError,
    FileExistsError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
    InterruptedError,
    NotImplementedError,
    ValueError,
    TypeError,
    KeyError,
    IODeadlineExceeded,
)

_TRANSIENT_TYPES = (ConnectionError, TimeoutError, BrokenPipeError)


def is_transient(exc: BaseException) -> bool:
    """True if retrying the op may plausibly succeed (see module docstring
    for the allowlist). An explicit `transient` attribute on the exception
    wins over every structural rule."""
    marker = getattr(exc, "transient", None)
    if marker is not None:
        return bool(marker)
    if isinstance(exc, _PERMANENT_TYPES):
        return False
    if isinstance(exc, _TRANSIENT_TYPES):
        return True
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    return False


@dataclass
class RetryPolicy:
    """max_attempts total tries per op; backoffs in millis; timeout_ms is a
    per-op wall-clock deadline spanning all attempts (None = unbounded)."""

    max_attempts: int = 3
    initial_backoff_ms: float = 10.0
    max_backoff_ms: float = 2000.0
    timeout_ms: float | None = None
    rng: random.Random = field(default_factory=random.Random)
    sleep: object = time.sleep  # injectable for tests
    _lock: threading.Lock = field(default_factory=threading.Lock)

    @property
    def enabled(self) -> bool:
        return self.max_attempts > 1 or self.timeout_ms is not None

    def next_backoff_ms(self, prev_ms: float | None) -> float:
        """Decorrelated jitter: U(base, 3*prev) capped at max_backoff_ms."""
        base = max(self.initial_backoff_ms, 0.0)
        if prev_ms is None:
            hi = base
        else:
            hi = min(self.max_backoff_ms, max(base, prev_ms * 3.0))
        with self._lock:  # random.Random is not thread-safe under mutation
            return self.rng.uniform(base, hi) if hi > base else base

    def run(self, op_name: str, fn, metrics=None):
        """Run fn() under the policy. Counts io{retries, giveups, timeouts}
        and records io{backoff_ms} on the given metric group."""
        t0 = time.monotonic()
        prev_backoff: float | None = None
        attempt = 1
        while True:
            try:
                return fn()
            except BaseException as exc:  # noqa: BLE001 — classified below
                if not is_transient(exc):
                    raise
                deadline_left = None
                if self.timeout_ms is not None:
                    deadline_left = self.timeout_ms - (time.monotonic() - t0) * 1000.0
                    if deadline_left <= 0:
                        if metrics is not None:
                            metrics.counter("timeouts").inc()
                            metrics.counter("giveups").inc()
                        raise IODeadlineExceeded(
                            f"fs.io.timeout ({self.timeout_ms:.0f} ms) exceeded after "
                            f"{attempt} attempt(s) of {op_name}"
                        ) from exc
                if attempt >= self.max_attempts:
                    if metrics is not None:
                        metrics.counter("giveups").inc()
                    raise
                prev_backoff = self.next_backoff_ms(prev_backoff)
                if deadline_left is not None and prev_backoff > deadline_left:
                    # sleeping past the deadline just to fail is pure waste
                    prev_backoff = max(deadline_left, 0.0)
                if metrics is not None:
                    metrics.counter("retries").inc()
                    metrics.histogram("backoff_ms").update(prev_backoff)
                if prev_backoff > 0:
                    self.sleep(prev_backoff / 1000.0)
                attempt += 1
