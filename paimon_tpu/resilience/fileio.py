"""RetryingFileIO: the FileIO wrapper every store-level path routes through.

Installed by core/store.py (KeyValueFileStore wraps its FileIO on
construction), so scan, merge read, commit, compaction and expire all get the
same behavior: transient faults retried under fs.retry.* with decorrelated
jitter, per-op deadlines from fs.io.timeout, everything counted in the
io{retries, giveups, backoff_ms, timeouts} metric group.

Semantics preserved through the wrapper:
- capability flags (atomic_write_supported / exclusive_create_supported)
  shine through, so commits engage the catalog lock exactly as they would on
  the bare store;
- local_path delegates, keeping pyarrow's mmap fast path (and the
  no-measurable-overhead property: with a local store, format reads never
  even enter the wrapper);
- try_atomic_write / try_overwrite delegate to the INNER implementation (an
  object store's conditional PUT must stay that store's protocol) and the
  whole primitive is the retry unit. A retried atomic write whose previous
  attempt tore (tmp written, rename never happened) simply stages a fresh
  uuid-named tmp; the torn sibling becomes an orphan that
  remove_orphan_files reclaims.

Retries of non-idempotent ops are safe against *our* failure modes: a
transient error is raised before the destination mutates (or the op is a
whole-primitive CAS whose loser is well-defined). The two residual races a
real store can produce — a rename that succeeded but whose ack was lost, and
an exclusive create whose first attempt half-landed — both surface as
permanent errors (False / FileExistsError) to the caller, and the commit
protocol resolves them by re-reading the snapshot chain (see
FileStoreCommit._find_own_commit).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ..fs import FileIO, FileStatus
from ..metrics import io_metrics
from .retry import RetryPolicy

if TYPE_CHECKING:
    from ..options import CoreOptions

__all__ = ["RetryingFileIO", "wrap_file_io"]


class RetryingFileIO(FileIO):
    def __init__(self, inner: FileIO, policy: RetryPolicy | None = None):
        self._inner = inner
        self.policy = policy or RetryPolicy()
        self.atomic_write_supported = getattr(inner, "atomic_write_supported", True)
        self.exclusive_create_supported = getattr(inner, "exclusive_create_supported", True)

    def _run(self, op: str, fn):
        return self.policy.run(op, fn, metrics=io_metrics())

    # ---- primitives ----------------------------------------------------
    def read_bytes(self, path: str) -> bytes:
        return self._run("read_bytes", lambda: self._inner.read_bytes(path))

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        return self._run("write_bytes", lambda: self._inner.write_bytes(path, data, overwrite))

    def exists(self, path: str) -> bool:
        return self._run("exists", lambda: self._inner.exists(path))

    def delete(self, path: str, recursive: bool = False) -> bool:
        return self._run("delete", lambda: self._inner.delete(path, recursive))

    def mkdirs(self, path: str) -> None:
        return self._run("mkdirs", lambda: self._inner.mkdirs(path))

    def rename(self, src: str, dst: str) -> bool:
        return self._run("rename", lambda: self._inner.rename(src, dst))

    def list_status(self, path: str) -> list[FileStatus]:
        return self._run("list_status", lambda: self._inner.list_status(path))

    def get_status(self, path: str) -> FileStatus:
        return self._run("get_status", lambda: self._inner.get_status(path))

    # ---- composite primitives (the inner's protocol is the retry unit) --
    def try_atomic_write(self, path: str, data: bytes) -> bool:
        return self._run("try_atomic_write", lambda: self._inner.try_atomic_write(path, data))

    def try_overwrite(self, path: str, data: bytes) -> bool:
        return self._run("try_overwrite", lambda: self._inner.try_overwrite(path, data))

    # ---- pass-throughs -------------------------------------------------
    def open_input(self, path: str):
        # the open is retried; reads on the returned stream are the format
        # layer's (a stream that dies mid-read re-opens via its own caller)
        return self._run("open_input", lambda: self._inner.open_input(path))

    def local_path(self, path: str) -> str | None:
        return self._inner.local_path(path)


def wrap_file_io(file_io: FileIO, options: "CoreOptions | None") -> FileIO:
    """RetryingFileIO per fs.retry.* / fs.io.timeout, or `file_io` unchanged
    when retries are disabled (fs.retry.max-attempts <= 1 and no timeout) or
    it is already wrapped — the disabled path adds zero indirection."""
    if isinstance(file_io, RetryingFileIO) or options is None:
        return file_io
    from ..options import CoreOptions

    opts = options.options
    max_attempts = opts.get(CoreOptions.FS_RETRY_MAX_ATTEMPTS)
    timeout = opts.get(CoreOptions.FS_IO_TIMEOUT)
    policy = RetryPolicy(
        max_attempts=max(1, int(max_attempts)),
        initial_backoff_ms=float(opts.get(CoreOptions.FS_RETRY_INITIAL_BACKOFF)),
        max_backoff_ms=float(opts.get(CoreOptions.FS_RETRY_MAX_BACKOFF)),
        timeout_ms=None if timeout is None else float(timeout),
    )
    if not policy.enabled:
        return file_io
    return RetryingFileIO(file_io, policy)
