"""Orphan-file crash recovery: reachability walk + sweep.

A crashed writer or commit leaks files at well-defined places: data files
whose commit never landed, manifests written before a lost/aborted snapshot
CAS, and torn `.tmp.*` siblings of atomic writes whose rename never ran.
None are reachable from any snapshot, so they are invisible to readers — but
they cost storage forever and, worse, a buggy cleaner that trusts anything
less than the full reachable closure deletes live data.

`remove_orphan_files` rebuilds that closure from every live root — all listed
snapshots, decoupled changelogs and tags of the main table AND of every
branch (branch manifests live under the branch dir; branch DATA files resolve
into the main table's bucket dirs, which is exactly why the reachability walk
must span branches before any bucket dir is swept) — then deletes
unreferenced files and stale tmp siblings older than the safety threshold
(default `orphan.clean.older-than`, 1 day: an in-flight commit's freshly
written files must survive). Every removed file is invalidated from the PR-1
byte-budget caches so no stale decoded object outlives its file.

Parity: reference RemoveOrphanFilesAction / OrphanFilesClean.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ..utils import now_millis

if TYPE_CHECKING:
    from ..table import FileStoreTable

__all__ = ["remove_orphan_files", "reachable_files"]

# directories under the table root that are metadata planes, never data
_RESERVED_DIRS = frozenset(
    {"snapshot", "manifest", "schema", "index", "changelog", "branch", "tag", "consumer", "statistics"}
)


def _is_tmp_name(base: str) -> bool:
    """Torn-write residue: FileIO._temp_sibling (.name.hex.tmp) and the
    LocalFileIO copy-fallback staging name (name.tmp-hex)."""
    return (base.startswith(".") and base.endswith(".tmp")) or ".tmp-" in base


def _root_snapshots(io, root: str):
    """Every snapshot-like object rooted at `root`: listed snapshots,
    decoupled changelogs, tags."""
    from ..core.snapshot import SnapshotManager
    from ..table.tags import TagManager

    sm = SnapshotManager(io, root)
    for snap in sm.snapshots():
        yield snap
    for cid in sm.changelog_ids():
        yield sm.changelog(cid)
    tags = TagManager(io, root)
    for name in tags.list_tags():
        yield tags.get(name)


def reachable_files(table: "FileStoreTable") -> dict:
    """The reachable closure of all live roots.

    Returns {"meta": {root: set(manifest-dir names)},
             "index": {root: set(index-dir names)},
             "data": set((bucket_dir, file_name))} — data is global because
    branch manifests reference the main table's bucket dirs."""
    from ..core.deletionvectors import DeletionVectorsIndexFile
    from ..core.indexmanifest import read_index_manifest
    from ..core.manifest import ManifestFile, ManifestList
    from ..table.branch import BranchManager

    io = table.file_io
    bm = BranchManager(io, table.path)
    roots = [table.path] + [bm.branch_path(b) for b in bm.list_branches()]

    meta: dict[str, set[str]] = {}
    index: dict[str, set[str]] = {}
    data: set[tuple[str, str]] = set()
    for root in roots:
        live_meta: set[str] = set()
        live_index: set[str] = set()
        manifest_file = ManifestFile(io, f"{root}/manifest")
        manifest_list = ManifestList(io, f"{root}/manifest")
        dv_io = DeletionVectorsIndexFile(io, root)
        for snap in _root_snapshots(io, root):
            for lst in (snap.base_manifest_list, snap.delta_manifest_list, snap.changelog_manifest_list):
                if not lst:
                    continue
                live_meta.add(lst)
                for m in manifest_list.read(lst):
                    live_meta.add(m.file_name)
                    for e in manifest_file.read(m.file_name):
                        # branch bucket dirs resolve into the MAIN tree
                        bucket_dir = table.store.bucket_dir(e.partition, e.bucket)
                        data.add((bucket_dir, e.file.file_name))
                        for x in e.file.extra_files:
                            data.add((bucket_dir, x))
            if snap.index_manifest:
                live_meta.add(snap.index_manifest)
                for ie in read_index_manifest(io, root, snap.index_manifest):
                    if ie.kind == "DELETION_VECTORS":
                        live_index.update(dv_io.chain_names(ie.file_name))
                    else:
                        live_index.add(ie.file_name)
        meta[root] = live_meta
        index[root] = live_index
    return {"meta": meta, "index": index, "data": data}


def remove_orphan_files(
    table: "FileStoreTable", older_than_millis: int | None = None, dry_run: bool = False
) -> list[str]:
    """Delete every file under the table tree that the reachable closure does
    not name and that is older than the threshold; afterwards the on-disk
    file set is exactly the closure plus table metadata (schemas, snapshot
    roots, hints, markers). Returns the removed (or would-remove) paths."""
    from ..metrics import io_metrics
    from ..options import CoreOptions
    from ..utils.cache import invalidate_data_file, invalidate_manifest_path

    io = table.file_io
    if older_than_millis is None:
        older_than_millis = table.options.options.get(CoreOptions.ORPHAN_CLEAN_OLDER_THAN)
    cutoff = now_millis() - older_than_millis
    live = reachable_files(table)
    removed: list[str] = []
    g = io_metrics()

    def rm(path: str, invalidate=None) -> None:
        removed.append(path)
        if dry_run:
            return
        try:
            io.delete(path)
        except Exception:
            # cleaner failures are never fatal: the file stays an orphan for
            # the next run (and the cache entry stays valid with it)
            g.counter("cleanup_failures").inc()
            removed.pop()
            return
        g.counter("orphans_removed").inc()
        if invalidate is not None:
            invalidate()

    # NOTE paths handed to io.delete are rebuilt as f"{directory}/{base}":
    # wrapper FileIOs (fail://, s3-like) list INNER paths in FileStatus, and
    # deleting those verbatim would silently miss the wrapped namespace
    def sweep(directory: str, keep: set[str], invalidator=None) -> None:
        """invalidator(path, base) -> zero-arg cache invalidation to run
        after a successful delete."""
        for st in io.list_files(directory):
            base = st.path.rsplit("/", 1)[-1]
            if base in keep or st.mtime_millis >= cutoff:
                continue
            path = f"{directory}/{base}"
            rm(path, None if invalidator is None else invalidator(path, base))

    def sweep_tmp_only(directory: str) -> None:
        """Snapshot/changelog dirs hold the commit roots themselves — only
        torn-write residue is ever garbage there."""
        for st in io.list_files(directory):
            base = st.path.rsplit("/", 1)[-1]
            if _is_tmp_name(base) and st.mtime_millis < cutoff:
                rm(f"{directory}/{base}")

    for root, keep in live["meta"].items():
        sweep(f"{root}/manifest", keep, lambda p, b: (lambda: invalidate_manifest_path(p)))
        sweep(f"{root}/index", live["index"][root])
        sweep_tmp_only(f"{root}/snapshot")
        sweep_tmp_only(f"{root}/changelog")

    # data planes: every bucket-* dir in the partition tree (including
    # partitions whose files are ALL orphaned — a crashed first commit into a
    # new partition leaves a bucket dir no live entry names)
    def walk_data(directory: str, at_root: bool) -> None:
        for st in io.list_status(directory):
            base = st.path.rsplit("/", 1)[-1]
            if not st.is_dir:
                continue
            if at_root and base in _RESERVED_DIRS:
                continue
            child = f"{directory}/{base}"
            if base.startswith("bucket-"):
                keep = {f for d, f in live["data"] if d == child}
                sweep(child, keep, lambda p, b: (lambda: invalidate_data_file(b)))
            else:
                walk_data(child, at_root=False)

    walk_data(table.path, at_root=True)
    return removed
