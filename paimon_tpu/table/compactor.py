"""Dedicated compaction: a separate job owns ALL compaction for a table
whose ingest writers run write-only.

Parity: /root/reference/paimon-flink/paimon-flink-common/.../sink/
CompactorSink.java + compact/ (the dedicated compaction job: ingest jobs set
write-only and a separate job scans buckets, compacts, commits COMPACT
snapshots), and /root/reference/paimon-core/.../append/
AppendOnlyTableCompactionCoordinator.java (unaware-bucket tables: a
coordinator plans small-file tasks, workers execute them, the coordinator
commits). Conflict safety comes from the commit protocol itself: a COMPACT
commit whose deleted files were concurrently removed fails the conflict
check and the compactor abandons that round (reference noConflictsOrFail).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from ..core.commit import CommitConflictError
from ..core.datafile import DataFileMeta
from ..core.manifest import CommitMessage

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["DedicatedCompactor", "AppendCompactionCoordinator", "CompactionTask", "execute_compaction_task"]


class DedicatedCompactor:
    """Runs compaction rounds against the latest snapshot and commits them.

    The ingest side sets write-only=true (writers skip compaction entirely);
    this job opens the same table with compaction enabled and periodically
    compacts every live bucket. Races with concurrent ingest commits are
    resolved by the snapshot CAS + conflict check: lost compactions are
    abandoned, never retried blindly (fresh state is picked up next round).
    """

    def __init__(self, table: "FileStoreTable"):
        # compaction must be ON in this job regardless of the table's
        # write-only ingest setting
        self.table = table.copy({"write-only": "false"}) if table.options.write_only else table

    def run_once(self, full: bool = False) -> bool:
        """One compaction round over every live bucket. Returns True when a
        COMPACT snapshot was committed; False when there was nothing to do
        or a concurrent commit won the race (abandoned, reference
        MergeTreeCompactManager loser semantics)."""
        from .write import BatchWriteBuilder, TableCommit

        wb = self.table.new_batch_write_builder()
        w = wb.new_write()
        try:
            w.compact(full=full)
            msgs = w.prepare_commit()
            if not msgs:
                return False
            TableCommit(self.table).commit_messages(BatchWriteBuilder.COMMIT_IDENTIFIER, msgs)
            return True
        except CommitConflictError:
            return False
        finally:
            w.close()


# ---------------------------------------------------------------------------
# unaware-bucket append tables: coordinator plans, workers execute
# ---------------------------------------------------------------------------


@dataclass
class CompactionTask:
    """One unit of work for a compaction worker (reference
    AppendOnlyCompactionTask): consecutive small files of one
    (partition, bucket)."""

    partition: tuple
    files: list[DataFileMeta] = field(default_factory=list)
    bucket: int = 0


class AppendCompactionCoordinator:
    """Plans small-file concat tasks across an append table (reference
    AppendOnlyTableCompactionCoordinator: the coordinator scans, emits tasks
    to distributed workers, and folds their results into one commit).
    Unaware-bucket tables get one namespace (bucket 0); fixed-bucket append
    tables plan per (partition, bucket)."""

    def __init__(self, table: "FileStoreTable"):
        if table.is_primary_key_table:
            raise ValueError(
                "AppendCompactionCoordinator serves append-only tables; "
                "primary-key tables compact through DedicatedCompactor"
            )
        self.table = table

    def plan(self, full: bool = False) -> list[CompactionTask]:
        store = self.table.store
        opts = store.options
        target = opts.target_file_size
        min_count = opts.compaction_min_file_num
        plan = store.new_scan().plan()
        by_pb: dict[tuple, list[DataFileMeta]] = {}
        for e in plan.entries:
            by_pb.setdefault((e.partition, e.bucket), []).append(e.file)
        tasks: list[CompactionTask] = []
        for (partition, bucket), files in by_pb.items():
            files = sorted(files, key=lambda f: (f.min_sequence_number, f.file_name))
            if full:
                if len(files) > 1:
                    tasks.append(CompactionTask(partition, files, bucket))
                continue
            small: list[DataFileMeta] = []
            for f in files:
                if f.file_size < target:
                    small.append(f)
                    if len(small) >= min_count or sum(x.file_size for x in small) >= target:
                        tasks.append(CompactionTask(partition, small, bucket))
                        small = []
                else:
                    if len(small) > 1:
                        tasks.append(CompactionTask(partition, small, bucket))
                    small = []
            if len(small) > 1:
                tasks.append(CompactionTask(partition, small, bucket))
        return tasks

    def commit(self, messages: list[CommitMessage]) -> None:
        """Fold the workers' results into ONE commit (the coordinator is the
        single-parallelism committer, reference CommitterOperator)."""
        from .write import BatchWriteBuilder, TableCommit

        messages = [m for m in messages if not m.is_empty()]
        if messages:
            TableCommit(self.table).commit_messages(BatchWriteBuilder.COMMIT_IDENTIFIER, messages)


def execute_compaction_task(table: "FileStoreTable", task: CompactionTask) -> CommitMessage:
    """Worker half: concat-rewrite one task's files (order-preserving, no
    merge function — reference AppendOnlyCompactionWorker; same body as the
    in-writer path via core.append.concat_rewrite). Returns the
    CommitMessage to ship back to the coordinator."""
    from ..core.append import concat_rewrite

    store = table.store
    rf = store.reader_factory(task.partition, task.bucket)
    wf = store.writer_factory(task.partition, task.bucket)
    out = concat_rewrite(rf, wf, task.files)
    return CommitMessage(
        partition=task.partition,
        bucket=task.bucket,
        total_buckets=max(store.options.bucket, -1),
        compact_before=list(task.files),
        compact_after=out,
    )
