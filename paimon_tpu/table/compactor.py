"""Dedicated + adaptive compaction: a separate job owns ALL compaction for
a table whose ingest writers run write-only.

Parity: /root/reference/paimon-flink/paimon-flink-common/.../sink/
CompactorSink.java + compact/ (the dedicated compaction job: ingest jobs set
write-only and a separate job scans buckets, compacts, commits COMPACT
snapshots), and /root/reference/paimon-core/.../append/
AppendOnlyTableCompactionCoordinator.java (unaware-bucket tables: a
coordinator plans small-file tasks, workers execute them, the coordinator
commits). Conflict safety comes from the commit protocol itself: a COMPACT
commit whose deleted files were concurrently removed fails the conflict
check and the compactor abandons that round (reference noConflictsOrFail).

The adaptive half (AdaptiveCompactorService + AdaptiveCompactionPolicy) is
the LUDA scheduling insight applied to this LSM: once compaction runs on the
accelerator it is cheap enough to schedule AHEAD of demand, so instead of a
fixed per-flush trigger inline with writers, a background service observes
every bucket's LSM shape from the snapshot chain (sorted runs, level-0
pileup, write rate) and drains compaction debt by priority — buckets over
the read-amplification ceiling first (the bound always wins), starving debt
next (no bucket waits forever), then the hottest eligible buckets, deeper
when their debt is deeper. Cold buckets defer, keeping background work off
the ingest path entirely.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

import numpy as np

from ..core.commit import CommitConflictError, CommitGiveUpError
from ..core.datafile import DataFileMeta
from ..core.manifest import CommitMessage
from ..options import CoreOptions

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = [
    "DedicatedCompactor",
    "AppendCompactionCoordinator",
    "CompactionTask",
    "execute_compaction_task",
    "BucketShape",
    "CompactionDecision",
    "AdaptiveCompactionPolicy",
    "AdaptiveCompactorService",
    "active_debt_gate",
]

# running services by table path, so write-only ingest writers can find the
# debt-admission gate of the compactor draining their table (ISSUE 12,
# declared PR 11 follow-up: the gate wired into MergeTreeWriter itself,
# not just harnesses that call admit() by hand)
_ACTIVE_GATES: dict[str, "AdaptiveCompactorService"] = {}
_GATES_LOCK = threading.Lock()


def active_debt_gate(table_path) -> "AdaptiveCompactorService | None":
    """The running AdaptiveCompactorService for a table path, if any."""
    with _GATES_LOCK:
        return _ACTIVE_GATES.get(str(table_path))


class DedicatedCompactor:
    """Runs compaction rounds against the latest snapshot and commits them.

    The ingest side sets write-only=true (writers skip compaction entirely);
    this job opens the same table with compaction enabled and periodically
    compacts every live bucket. Races with concurrent ingest commits are
    resolved by the snapshot CAS + conflict check: lost compactions are
    abandoned, never retried blindly (fresh state is picked up next round).
    """

    def __init__(self, table: "FileStoreTable"):
        # compaction must be ON in this job regardless of the table's
        # write-only ingest setting
        self.table = table.copy({"write-only": "false"}) if table.options.write_only else table

    def run_once(self, full: bool = False) -> bool:
        """One compaction round over every live bucket. Returns True when a
        COMPACT snapshot was committed; False when there was nothing to do
        or a concurrent commit won the race (abandoned, reference
        MergeTreeCompactManager loser semantics)."""
        from .write import BatchWriteBuilder, TableCommit

        wb = self.table.new_batch_write_builder()
        w = wb.new_write()
        try:
            w.compact(full=full)
            msgs = w.prepare_commit()
            if not msgs:
                return False
            TableCommit(self.table).commit_messages(BatchWriteBuilder.COMMIT_IDENTIFIER, msgs)
            return True
        except CommitConflictError:
            return False
        finally:
            w.close()


# ---------------------------------------------------------------------------
# unaware-bucket append tables: coordinator plans, workers execute
# ---------------------------------------------------------------------------


@dataclass
class CompactionTask:
    """One unit of work for a compaction worker (reference
    AppendOnlyCompactionTask): consecutive small files of one
    (partition, bucket)."""

    partition: tuple
    files: list[DataFileMeta] = field(default_factory=list)
    bucket: int = 0


class AppendCompactionCoordinator:
    """Plans small-file concat tasks across an append table (reference
    AppendOnlyTableCompactionCoordinator: the coordinator scans, emits tasks
    to distributed workers, and folds their results into one commit).
    Unaware-bucket tables get one namespace (bucket 0); fixed-bucket append
    tables plan per (partition, bucket)."""

    def __init__(self, table: "FileStoreTable"):
        if table.is_primary_key_table:
            raise ValueError(
                "AppendCompactionCoordinator serves append-only tables; "
                "primary-key tables compact through DedicatedCompactor"
            )
        self.table = table

    def plan(self, full: bool = False) -> list[CompactionTask]:
        store = self.table.store
        opts = store.options
        target = opts.target_file_size
        min_count = opts.compaction_min_file_num
        plan = store.new_scan().plan()
        by_pb: dict[tuple, list[DataFileMeta]] = {}
        for e in plan.entries:
            by_pb.setdefault((e.partition, e.bucket), []).append(e.file)
        tasks: list[CompactionTask] = []
        for (partition, bucket), files in by_pb.items():
            files = sorted(files, key=lambda f: (f.min_sequence_number, f.file_name))
            if full:
                if len(files) > 1:
                    tasks.append(CompactionTask(partition, files, bucket))
                continue
            small: list[DataFileMeta] = []
            for f in files:
                if f.file_size < target:
                    small.append(f)
                    if len(small) >= min_count or sum(x.file_size for x in small) >= target:
                        tasks.append(CompactionTask(partition, small, bucket))
                        small = []
                else:
                    if len(small) > 1:
                        tasks.append(CompactionTask(partition, small, bucket))
                    small = []
            if len(small) > 1:
                tasks.append(CompactionTask(partition, small, bucket))
        return tasks

    def commit(self, messages: list[CommitMessage]) -> None:
        """Fold the workers' results into ONE commit (the coordinator is the
        single-parallelism committer, reference CommitterOperator)."""
        from .write import BatchWriteBuilder, TableCommit

        messages = [m for m in messages if not m.is_empty()]
        if messages:
            TableCommit(self.table).commit_messages(BatchWriteBuilder.COMMIT_IDENTIFIER, messages)


def execute_compaction_task(table: "FileStoreTable", task: CompactionTask) -> CommitMessage:
    """Worker half: concat-rewrite one task's files (order-preserving, no
    merge function — reference AppendOnlyCompactionWorker; same body as the
    in-writer path via core.append.concat_rewrite). Returns the
    CommitMessage to ship back to the coordinator."""
    from ..core.append import concat_rewrite

    store = table.store
    rf = store.reader_factory(task.partition, task.bucket)
    wf = store.writer_factory(task.partition, task.bucket)
    out = concat_rewrite(rf, wf, task.files)
    return CommitMessage(
        partition=task.partition,
        bucket=task.bucket,
        total_buckets=max(store.options.bucket, -1),
        compact_before=list(task.files),
        compact_after=out,
    )


# ---------------------------------------------------------------------------
# adaptive background compaction (LUDA-style scheduling)
# ---------------------------------------------------------------------------


@dataclass
class BucketShape:
    """One bucket's observed LSM shape — everything the policy scores,
    derivable from any committed snapshot (the service never touches writer
    state, so it composes with concurrent ingest by construction)."""

    partition: tuple
    bucket: int
    runs: int  # sorted runs = level-0 files + populated levels > 0
    level0_files: int
    files: int
    bytes: int
    debt_files: int  # files not at the top non-empty level
    debt_bytes: int
    write_rate: float  # EMA of sequence-number advance per second
    max_seq: int

    @property
    def read_amp(self) -> int:
        """Merge-read amplification of a point in this bucket = sorted runs
        the merge must consult."""
        return self.runs


@dataclass
class CompactionDecision:
    partition: tuple
    bucket: int
    deep: bool  # full rewrite to the top level vs shallow universal pick
    reason: str  # "ceiling" | "starvation" | "hot"
    runs: int = 0  # sorted runs observed when the decision was made


class AdaptiveCompactionPolicy:
    """Pure scoring — no IO, fully unit-testable (tests/test_compactor.py).

    Priority order per round:
      1. ceiling: every bucket at/above `read_amp_ceiling` compacts NOW
         (deep) — the read-amplification bound is unconditional, so it is
         exempt from `max_buckets`.
      2. starvation: debt deferred longer than `starvation_s` promotes to
         mandatory — sustained skew cannot starve a cold bucket forever.
      3. hot: remaining slots (up to `max_buckets`) go to the buckets with
         the highest heat x debt score among those at/above `trigger` runs;
         `deep_runs` or more runs makes the pick deep (LUDA: hotter buckets
         compact deeper and earlier).
    Buckets with debt that were not chosen are the round's deferrals.
    """

    def __init__(
        self,
        read_amp_ceiling: int = 12,
        trigger: int = 3,
        deep_runs: int = 8,
        max_buckets: int = 2,
        starvation_s: float = 10.0,
    ):
        self.read_amp_ceiling = read_amp_ceiling
        self.trigger = trigger
        self.deep_runs = deep_runs
        self.max_buckets = max_buckets
        self.starvation_s = starvation_s
        # (partition, bucket) -> monotonic time its current debt was first
        # seen; cleared when the bucket compacts or drains below 2 runs
        self._debt_since: dict[tuple, float] = {}

    def _deep(self, shape: BucketShape) -> bool:
        return shape.runs >= self.deep_runs

    def decide(self, shapes: list[BucketShape], now_s: float) -> tuple[list[CompactionDecision], int]:
        """-> (decisions in execution-priority order, deferred bucket count)."""
        decisions: list[CompactionDecision] = []
        chosen: set[tuple] = set()
        live = set()
        for s in shapes:
            key = (s.partition, s.bucket)
            live.add(key)
            if s.runs > 1:
                self._debt_since.setdefault(key, now_s)
            else:
                self._debt_since.pop(key, None)
        for key in list(self._debt_since):
            if key not in live:
                self._debt_since.pop(key)

        # 1. read-amp ceiling: unconditional, uncapped, worst first. Depth
        # stays the policy's deep_runs call — restoring the bound needs the
        # CHEAPEST run-count reduction (an L0 merge), not necessarily a
        # full rewrite of the (large, already-merged) top level
        for s in sorted(shapes, key=lambda x: -x.runs):
            if s.read_amp >= self.read_amp_ceiling:
                decisions.append(
                    CompactionDecision(s.partition, s.bucket, self._deep(s), "ceiling", s.runs)
                )
                chosen.add((s.partition, s.bucket))

        # 2. starvation promotion: oldest debt first
        starving = [
            s
            for s in shapes
            if (s.partition, s.bucket) not in chosen
            and s.runs > 1
            and now_s - self._debt_since.get((s.partition, s.bucket), now_s) >= self.starvation_s
        ]
        for s in sorted(starving, key=lambda x: self._debt_since[(x.partition, x.bucket)]):
            decisions.append(CompactionDecision(s.partition, s.bucket, self._deep(s), "starvation", s.runs))
            chosen.add((s.partition, s.bucket))

        # 3. heat-ranked proactive picks under the per-round budget
        slots = max(0, self.max_buckets - len(decisions))
        eligible = [
            s for s in shapes if (s.partition, s.bucket) not in chosen and s.runs >= self.trigger
        ]
        eligible.sort(key=lambda s: (-(s.write_rate + 1.0) * s.debt_files, -s.runs))
        for s in eligible[:slots]:
            decisions.append(CompactionDecision(s.partition, s.bucket, self._deep(s), "hot", s.runs))
            chosen.add((s.partition, s.bucket))

        deferred = sum(
            1 for s in shapes if s.runs > 1 and (s.partition, s.bucket) not in chosen
        )
        return decisions, deferred

    def note_compacted(self, partition: tuple, bucket: int) -> None:
        self._debt_since.pop((partition, bucket), None)


class AdaptiveCompactorService:
    """Background compaction scheduler for one table (LUDA-style).

    Observation is snapshot-only: each round scans the latest plan, folds it
    into per-bucket `BucketShape`s (write rate = EMA of max-sequence-number
    advance between rounds), feeds the policy, and executes its decisions as
    per-bucket COMPACT commits through the normal snapshot-CAS path — a lost
    race is abandoned (compaction{adaptive_conflicts}) and re-observed next
    round, exactly the DedicatedCompactor loser semantics. Rides the PR 4
    flush-executor pattern: one dedicated `paimon-compactor` thread drains
    debt while writers keep filling memtables; `close()` (or the context
    manager) always tears it down, and tests/conftest.py asserts the thread
    never outlives a test."""

    THREAD_PREFIX = "paimon-compactor"

    def __init__(
        self,
        table: "FileStoreTable",
        policy: AdaptiveCompactionPolicy | None = None,
        execute_group: "callable | None" = None,
    ):
        """`execute_group(group, deep) -> int`: pluggable execution seam.
        None = the local path (_compact_group: rewrite + commit in this
        process). The cluster coordinator (service/cluster.py) plugs in a
        dispatcher that ships each decision to the worker OWNING that
        bucket; the worker rewrites through its local mesh engine and ships
        the CommitMessage back, and only the coordinator commits — the
        observation, policy, pacing loop, and debt-admission gate here stay
        identical, now enforced cluster-wide."""
        opts = table.options.options
        base = table.copy({"write-only": "false"}) if table.options.write_only else table
        if policy is None:
            policy = AdaptiveCompactionPolicy(
                read_amp_ceiling=opts.get(CoreOptions.COMPACTION_ADAPTIVE_READ_AMP_CEILING),
                trigger=opts.get(CoreOptions.COMPACTION_ADAPTIVE_TRIGGER),
                deep_runs=opts.get(CoreOptions.COMPACTION_ADAPTIVE_DEEP_RUNS),
                max_buckets=opts.get(CoreOptions.COMPACTION_ADAPTIVE_MAX_BUCKETS),
                starvation_s=opts.get(CoreOptions.COMPACTION_ADAPTIVE_STARVATION_TIMEOUT) / 1000.0,
            )
        self.policy = policy
        # shallow picks must fire at the ADAPTIVE trigger, not the writer's
        # inline one: the service's own handle lowers the universal pick
        # threshold so a decided bucket always produces work
        self.table = base.copy(
            {"num-sorted-run.compaction-trigger": str(max(policy.trigger - 1, 1))}
        )
        self.interval_s = opts.get(CoreOptions.COMPACTION_ADAPTIVE_INTERVAL) / 1000.0
        self.parallelism = max(1, opts.get(CoreOptions.COMPACTION_ADAPTIVE_PARALLELISM))
        self._execute_group = execute_group
        self._pool = None
        self._prev: dict[tuple, tuple[int, float]] = {}  # (p, b) -> (max_seq, t)
        self._rate: dict[tuple, float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._errors: list[str] = []
        self.rounds = 0
        self.compactions = 0
        # debt-admission surface: the latest observed per-bucket run counts,
        # published under a condition so ingest writers can block while any
        # bucket sits at/over the read-amp ceiling (the stop-trigger analog
        # for write-only ingest: PR 8's admission idea applied to compaction
        # debt instead of buffer bytes)
        self._runs_cond = threading.Condition()
        self._runs: dict[tuple, int] = {}
        self._inflight: dict[tuple, int] = {}
        # owner -> charged bucket keys (with multiplicity): the cluster
        # coordinator tags each worker's admissions so a worker killed
        # mid-commit or mid-compaction releases exactly its own charges
        self._owner_charges: dict[object, list[tuple]] = {}

    # ---- observation ---------------------------------------------------
    def observe(self) -> list[BucketShape]:
        now = time.monotonic()
        plan = self.table.store.new_scan().plan()
        shapes: list[BucketShape] = []
        for partition, buckets in plan.grouped().items():
            for bucket, files in buckets.items():
                level0 = [f for f in files if f.level == 0]
                upper = sorted({f.level for f in files if f.level > 0})
                runs = len(level0) + len(upper)
                top = upper[-1] if upper else None
                debt = [f for f in files if top is None or f.level != top]
                max_seq = max((f.max_sequence_number for f in files), default=0)
                key = (partition, bucket)
                prev = self._prev.get(key)
                if prev is not None and now > prev[1]:
                    inst = max(0.0, (max_seq - prev[0]) / (now - prev[1]))
                    self._rate[key] = 0.5 * self._rate.get(key, inst) + 0.5 * inst
                self._prev[key] = (max_seq, now)
                shapes.append(
                    BucketShape(
                        partition=partition,
                        bucket=bucket,
                        runs=runs,
                        level0_files=len(level0),
                        files=len(files),
                        bytes=sum(f.file_size for f in files),
                        debt_files=len(debt) if runs > 1 else 0,
                        debt_bytes=sum(f.file_size for f in debt) if runs > 1 else 0,
                        write_rate=self._rate.get(key, 0.0),
                        max_seq=max_seq,
                    )
                )
        with self._runs_cond:
            self._runs = {(s.partition, s.bucket): s.runs for s in shapes}
            self._runs_cond.notify_all()
        self._publish(shapes)
        return shapes

    # ---- debt admission (ingest-side backpressure) ----------------------
    def over_ceiling(self) -> list[tuple]:
        """Buckets at/over the read-amp ceiling as of the last observation."""
        bound = self.policy.read_amp_ceiling
        with self._runs_cond:
            return [k for k, r in self._runs.items() if r >= bound]

    def heat(self) -> dict[int, float]:
        """Per-bucket write-heat EMA (rows/s, from the sequence-number delta
        tracked across observations) folded over partitions. The elastic
        cluster's replica planner combines this with the serve-side get rate
        to decide which buckets deserve read replicas — the same LUDA-style
        heat signal that already orders the compaction queue."""
        out: dict[int, float] = {}
        # Snapshot: the observation loop mutates _rate concurrently.
        for (_, bucket), rate in list(self._rate.items()):
            out[bucket] = out.get(bucket, 0.0) + rate
        return out

    def wait_for_headroom(self, timeout_s: float = 30.0) -> bool:
        """Block the calling ingest writer until no bucket sits at/over the
        read-amp ceiling (re-evaluated at every observation round) — the
        num-sorted-run stop-trigger analog for write-only ingest, which
        bypasses the inline compaction manager entirely. Returns False on
        timeout (the caller may proceed; the breach is the scheduler's to
        drain)."""
        return self.admit(buckets=None, timeout_s=timeout_s, project=False)

    def _keys_for(self, b):
        if isinstance(b, tuple):
            return [b]
        hits = [k for k in self._runs if k[1] == b]
        return hits or [((), b)]

    def _projected(self, key) -> int:
        return self._runs.get(key, 0) + self._inflight.get(key, 0)

    def admit(
        self, buckets=None, timeout_s: float = 30.0, project: bool = True, owner=None
    ) -> bool:
        """Admission for one ingest commit against the compaction-debt
        budget: blocks while any target bucket's PROJECTED sorted-run count
        (last observed runs + in-flight admitted commits) sits at/over the
        read-amp ceiling, then (project=True) charges the admitted commit
        one in-flight run per target bucket. The in-flight charge is what
        makes the bound hold between observation rounds — observations are
        periodic, admissions are not, and an uncharged burst of commits
        would sail past the ceiling before the next scan. The caller
        releases the charge with settle() once its commit lands (or
        aborts); observe() then folds landed files into the observed half.
        `buckets` may hold ints (bucket ids, any partition) or
        (partition, bucket) tuples; None blocks on a breach anywhere and
        charges nothing. Returns False on timeout. Blocking admissions
        count in compaction{admission_waits}."""
        bound = self.policy.read_amp_ceiling
        waited = False
        with self._runs_cond:
            targets = (
                None if buckets is None else [k for b in buckets for k in self._keys_for(b)]
            )

            def ok():
                if self._stop.is_set():
                    return True  # a closing service must not strand waiters
                if targets is None:
                    return all(self._projected(k) < bound for k in self._runs)
                return all(self._projected(k) < bound for k in targets)

            if not ok():
                waited = True
                admitted = self._runs_cond.wait_for(ok, timeout_s)
            else:
                admitted = True
            if admitted and project and targets is not None:
                for k in targets:
                    self._inflight[k] = self._inflight.get(k, 0) + 1
                if owner is not None:
                    self._owner_charges.setdefault(owner, []).extend(targets)
        if waited:
            from ..metrics import compaction_metrics

            compaction_metrics().counter("admission_waits").inc()
        return admitted

    def settle(self, buckets, landed: bool = True, owner=None) -> None:
        """Release admit()'s in-flight charge after the commit landed or
        aborted (call from a finally:). A landed commit's charge moves into
        the observed half immediately — the next observation replaces it
        with scanned truth — so the ceiling has no uncharged window; an
        aborted commit's charge simply vanishes."""
        with self._runs_cond:
            for b in buckets:
                for k in self._keys_for(b):
                    self._settle_key(k, landed)
                    if owner is not None:
                        ledger = self._owner_charges.get(owner)
                        if ledger is not None and k in ledger:
                            ledger.remove(k)
                            if not ledger:
                                self._owner_charges.pop(owner, None)
            self._runs_cond.notify_all()

    def _settle_key(self, k: tuple, landed: bool) -> None:
        cur = self._inflight.get(k, 0)
        if cur <= 1:
            self._inflight.pop(k, None)
        else:
            self._inflight[k] = cur - 1
        if landed:
            self._runs[k] = self._runs.get(k, 0) + 1

    def release_owner(self, owner) -> int:
        """Drop every in-flight charge `owner` still holds — nothing of a
        kill -9'd worker's un-shipped rounds will ever land, so its charges
        must not keep blocking rival admissions at the ceiling. Returns the
        number of charges released."""
        with self._runs_cond:
            ledger = self._owner_charges.pop(owner, None) or []
            for k in ledger:
                self._settle_key(k, landed=False)
            if ledger:
                self._runs_cond.notify_all()
            return len(ledger)

    @staticmethod
    def _publish(shapes: list[BucketShape]) -> None:
        from ..metrics import compaction_metrics

        g = compaction_metrics()
        g.gauge("debt_files").set(sum(s.debt_files for s in shapes))
        g.gauge("debt_bytes").set(sum(s.debt_bytes for s in shapes))
        if shapes:
            g.gauge("read_amplification_p99").set(
                float(np.percentile([s.read_amp for s in shapes], 99))
            )

    # ---- execution -----------------------------------------------------
    def _compact_group(self, group: list[CompactionDecision], deep: bool) -> int:
        """One COMPACT commit covering every bucket of the group (one
        snapshot CAS instead of one per bucket — commit protocol cost is
        the background drain's main overhead). 0 = nothing to do or lost
        the race (abandoned, fresh state next round)."""
        from ..metrics import compaction_metrics
        from .write import BatchWriteBuilder, TableCommit, TableWrite

        if self._stop.is_set() or not group:
            return 0
        g = compaction_metrics()
        tw = TableWrite(self.table)
        try:
            for d in group:
                tw._writer(d.partition, d.bucket)  # register ONLY these buckets
            tw.compact(full=deep)
            msgs = tw.prepare_commit()
            if not msgs:
                return 0
            TableCommit(self.table).commit_messages(BatchWriteBuilder.COMMIT_IDENTIFIER, msgs)
        except (CommitConflictError, CommitGiveUpError):
            g.counter("adaptive_conflicts").inc()
            return 0
        finally:
            tw.close()
        g.counter("adaptive_runs").inc(len(group))
        self.note_compaction_landed(group)
        return len(group)

    def note_compaction_landed(self, group: list[CompactionDecision]) -> None:
        """Bookkeeping after a group's COMPACT commit landed — shared by the
        local path and a remote executor (the cluster coordinator calls this
        when a worker's shipped compaction result commits)."""
        for d in group:
            self.policy.note_compacted(d.partition, d.bucket)
            if d.deep:
                # a landed deep rewrite consumed the runs observed at
                # decision time (files landed SINCE the plan survive as
                # fresh level-0 runs — admissions charged mid-rewrite must
                # stay charged): fold that into the projection and wake
                # admission waiters now instead of at the next observation
                key = (d.partition, d.bucket)
                with self._runs_cond:
                    cur = self._runs.get(key, d.runs)
                    self._runs[key] = max(1, cur - d.runs + 1)
                    self._runs_cond.notify_all()

    def run_round(self) -> int:
        """One observe -> decide -> execute round; returns #buckets
        compacted. Safe to call from any thread (the soak harness drives it
        from its own churn thread instead of start())."""
        from ..metrics import compaction_metrics

        g = compaction_metrics()
        shapes = self.observe()
        decisions, deferred = self.policy.decide(shapes, time.monotonic())
        if deferred:
            g.counter("deferred_buckets").inc(deferred)
        deep_group = [d for d in decisions if d.deep]
        shallow_group = [d for d in decisions if not d.deep]
        groups = [(grp, deep) for grp, deep in ((deep_group, True), (shallow_group, False)) if grp]
        if self._execute_group is not None:
            # remote execution seam (cluster coordinator): dispatch is the
            # executor's business — it may be asynchronous (results commit
            # when workers ship them), so no pool fan-out here
            done = sum(self._execute_group(grp, deep) for grp, deep in groups)
            self.rounds += 1
            self.compactions += done
            return done
        if len(groups) > 1 and self.parallelism > 1:
            # the two groups commit independently (snapshot CAS absorbs the
            # interleaving): fan them over the worker pool so deep drains
            # don't serialize behind shallow maintenance. Buckets within a
            # group share ONE commit — protocol cost, not rewrite cost, is
            # the background drain's main overhead
            done = sum(self._executor().map(lambda gd: self._compact_group(*gd), groups))
        else:
            done = sum(self._compact_group(grp, deep) for grp, deep in groups)
        self.rounds += 1
        self.compactions += done
        return done

    def _executor(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self.parallelism, thread_name_prefix=f"{self.THREAD_PREFIX}-exec"
            )
        return self._pool

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> "AdaptiveCompactorService":
        if self._thread is not None:
            return self
        self._stop.clear()
        with _GATES_LOCK:
            _ACTIVE_GATES[str(self.table.path)] = self
        self._thread = threading.Thread(
            target=self._loop, name=f"{self.THREAD_PREFIX}-{id(self) & 0xFFFF:x}", daemon=False
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        import traceback

        while not self._stop.is_set():
            done = 0
            try:
                done = self.run_round()
            except Exception:
                # observation races (snapshot expired mid-plan) and injected
                # faults are survivable: record, back off, re-observe
                self._errors.append(traceback.format_exc())
                if len(self._errors) > 20:
                    del self._errors[:-20]
            # pressure-adaptive pacing: a round that compacted something
            # re-observes immediately (debt is live, writers may be blocked
            # on the ceiling); an idle round sleeps the configured interval
            self._stop.wait(self.interval_s if done == 0 else 0.005)

    def close(self) -> None:
        self._stop.set()
        with _GATES_LOCK:
            if _ACTIVE_GATES.get(str(self.table.path)) is self:
                _ACTIVE_GATES.pop(str(self.table.path))
        with self._runs_cond:
            self._runs_cond.notify_all()  # release admission waiters
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=120.0)
        if self._pool is not None:
            self._pool.shutdown(wait=True, cancel_futures=True)
            self._pool = None

    def __enter__(self) -> "AdaptiveCompactorService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
