"""Sort-compaction: rewrite a table clustered by a space-filling curve.

Parity: the reference's SortCompactAction + TableSorter (flink/sorter/:
ZorderSorter, HilbertSorter, order) over RangeShuffle — here the single-host
path sorts the whole table through the device sort kernel; the distributed
path is paimon_tpu.parallel.range_partition_lanes over the "key" mesh axis.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Sequence

import numpy as np

from ..core.kv import KVBatch
from ..core.manifest import CommitMessage, ManifestCommittable
from ..data.keys import encode_key_lanes, exact_string_pool
from ..ops.merge import merge_plan
from ..options import CoreOptions
from ..ops.zorder import hilbert_lanes, z_order_lanes
from ..types import TypeRoot

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["sort_compact"]


def sort_compact(
    table: "FileStoreTable",
    columns: Sequence[str],
    order: str = "zorder",
    commit_identifier: int | None = None,
) -> int:
    """Rewrites every bucket clustered by `columns` under the given curve
    (zorder | hilbert | order). Returns rows rewritten. Append tables only —
    PK tables are already key-clustered by the LSM."""
    if table.is_primary_key_table:
        raise ValueError("sort-compact applies to append-only tables (PK tables are key-clustered)")
    if order not in ("zorder", "hilbert", "order"):
        raise ValueError(f"unknown sort order {order!r}")
    store = table.store
    plan = store.new_scan().plan()
    messages: list[CommitMessage] = []
    total = 0
    from ..options import SortEngine

    # CPU-only backend: clustering is a plain stable sort of the curve
    # codes — the host lexsort wins (same adaptive rule as merge reads,
    # mergefn.effective_sort_engine); resolved once for the whole call
    effective_engine = store.merge_executor().effective_sort_engine()
    use_host_sort = effective_engine == SortEngine.NUMPY
    # sort-engine=pallas: the clustering sort inherits the fused kernel
    # through the same sorted_segments seam as every merge
    kernel_engine = "pallas" if effective_engine == SortEngine.PALLAS else "xla"
    jobs = [
        (partition, bucket, files)
        for partition, buckets in plan.grouped().items()
        for bucket, files in buckets.items()
    ]

    def read_job(job):
        partition, bucket, files = job
        rf = store.reader_factory(partition, bucket)
        ordered = sorted(files, key=lambda f: (f.min_sequence_number, f.file_name))
        from ..parallel.pipeline import bounded_map

        return KVBatch.concat(bounded_map(rf.read, ordered))

    # merge.engine = mesh: buckets stream through the host-side feeder (one
    # prefetch lane per device) so bucket i+1's reads overlap bucket i's
    # clustering sort; the per-bucket processing below is unchanged, so the
    # rewritten files are bit-identical to the serial loop
    from ..parallel.mesh_exec import mesh_feeder_lanes

    lanes_n = mesh_feeder_lanes(store.options)
    if lanes_n > 1 and len(jobs) > 1:
        from ..parallel.pipeline import SplitPipeline

        kv_iter = SplitPipeline(parallelism=lanes_n, depth=lanes_n, stage="compact").map_ordered(
            jobs, read_job
        )
    else:
        kv_iter = (read_job(j) for j in jobs)
    for (partition, bucket, files), kv in zip(jobs, kv_iter):
        if kv.num_rows == 0:
            continue
        var_roots = (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY)
        # exact pools (code-domain aware): len(pools[c]) must equal the
        # expanded build's so the zorder spread factor — and therefore the
        # clustering permutation — is identical with merge.dict-domain on
        pools = {
            c: exact_string_pool([kv.data.column(c)])
            for c in columns
            if kv.data.schema.field(c).type.root in var_roots
        }
        lanes = encode_key_lanes(kv.data, columns, pools)
        # zorder.var-length-contribution: how many BYTES a var-length
        # column contributes to the interleave (reference ZIndexer
        # varTypeSize). Ranks are dense; spread them over the full 32-bit
        # lane, then keep the top contribution*8 bits — fewer bits =
        # coarser clustering for that column.
        contrib = int(store.options.options.get(CoreOptions.ZORDER_VAR_LENGTH_CONTRIBUTION))
        if order in ("zorder", "hilbert") and contrib < 4:
            keep_bits = max(1, contrib * 8)
            for ci, c in enumerate(columns):
                if kv.data.schema.field(c).type.root in var_roots and len(pools.get(c, ())):
                    scale = np.uint64(0x100000000) // np.uint64(max(len(pools[c]), 1))
                    spread = (lanes[:, ci].astype(np.uint64) * scale).astype(np.uint32)
                    lanes[:, ci] = spread & np.uint32(~np.uint32((1 << (32 - keep_bits)) - 1))
        if order == "zorder":
            lanes = z_order_lanes(lanes)
        elif order == "hilbert":
            lanes = hilbert_lanes(lanes)
        # key-lane compression (ops/lanes.py): curve code lanes truncate
        # and pack like any key — identical clustering permutation
        # (order- and stability-preserving), fewer sort operands
        compress = store.options.lane_compression
        perm = None
        if not use_host_sort:
            # merge.engine = mesh: the clustering sort range-shuffles
            # rows over the mesh's key axis (range_partition_rows — the
            # RangeShuffle.java analog) and recovers the same stable
            # permutation; None below the key-axis threshold / off mesh
            from ..parallel.mesh_exec import mesh_cluster_permutation

            perm = mesh_cluster_permutation(lanes, store.options)
        if perm is None and use_host_sort:
            from ..data.keys import lexsort_rows
            from ..ops.lanes import compress_key_lanes

            sort_lanes, _plan = compress_key_lanes(lanes, compress, enable_ovc=False)
            perm = lexsort_rows(sort_lanes)
        elif perm is None:
            # device sort; stability keeps arrival order on ties
            p = merge_plan(lanes, compress=compress, engine=kernel_engine)
            perm = p.perm[p.valid_sorted]
        sorted_kv = kv.take(perm)
        wf = store.writer_factory(partition, bucket)
        # sort-compaction.range-strategy=size: roll output files by
        # MEASURED bytes (var-width skew packs evenly); quantity keeps
        # the schema estimate (row-count driven)
        measured = None
        if store.options.options.get(CoreOptions.SORT_COMPACTION_RANGE_STRATEGY).lower() == "size":
            total_bytes = 0.0
            n_rows = sorted_kv.num_rows
            for col in sorted_kv.data.columns.values():
                if col.values.dtype == np.dtype(object):
                    sample = col.values[: min(n_rows, 4096)]
                    # float scaling: integer floor undercounts up to 2x
                    total_bytes += sum(len(str(v)) for v in sample) * (n_rows / max(len(sample), 1))
                else:
                    total_bytes += col.values.nbytes
            measured = total_bytes / max(n_rows, 1)
        after = wf.write(sorted_kv, level=0, file_source="compact", measured_row_bytes=measured)
        messages.append(
            CommitMessage(
                partition,
                bucket,
                max(store.options.bucket, 1),
                compact_before=list(files),
                compact_after=after,
            )
        )
        total += kv.num_rows
    if messages:
        ident = commit_identifier if commit_identifier is not None else (1 << 63) - 3
        store.new_commit().commit(ManifestCommittable(ident, messages=messages))
    return total
