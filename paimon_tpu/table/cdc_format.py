"""CDC source formats: parse change-capture JSON streams into CdcRecords.

Parity: /root/reference/paimon-flink/paimon-flink-cdc/src/main/java/org/
apache/paimon/flink/action/cdc/format/ — RecordParser subclasses for
debezium (DebeziumRecordParser: payload/before/after/op c|u|d|r), canal
(CanalRecordParser: data[]/old[]/type INSERT|UPDATE|DELETE), maxwell
(MaxwellRecordParser: data/old/type insert|update|delete), and plain json.
Each parser turns one raw message into 0..2 CdcRecords (-U/+U pairs for
updates) plus optional primary-key hints; records feed the schema-evolving
CdcTableWrite sink, completing the source half the round-1 build lacked.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Iterable, Iterator, Mapping

from ..core.commit import BATCH_COMMIT_IDENTIFIER
from .cdc import CdcRecord, CdcTableWrite

__all__ = ["parse_debezium", "parse_canal", "parse_maxwell", "parse_json", "get_cdc_parser", "CdcStream"]


def _loads(message: str | bytes | Mapping | None):
    if message is None or isinstance(message, Mapping):
        return message
    return json.loads(message)


def parse_debezium(message: str | bytes | Mapping) -> list[CdcRecord]:
    """Debezium JSON (optionally schema-wrapped): op c/r -> +I, u -> -U/+U,
    d -> -D; tombstones (null payload / null message) are skipped
    (reference DebeziumRecordParser ignores null payloads)."""
    node = _loads(message)
    if node is None:
        return []
    if "payload" in node:
        node = node["payload"]
        if node is None:  # kafka compaction tombstone after a delete
            return []
    op = node.get("op")
    before = node.get("before")
    after = node.get("after")
    if op in ("c", "r"):
        return [CdcRecord(after, "+I")] if after else []
    if op == "u":
        out = []
        if before:
            out.append(CdcRecord(before, "-U"))
        if after:
            out.append(CdcRecord(after, "+U"))
        return out
    if op == "d":
        return [CdcRecord(before, "-D")] if before else []
    raise ValueError(f"unknown debezium op {op!r}")


def parse_canal(message: str | bytes | Mapping) -> list[CdcRecord]:
    """Canal JSON: type INSERT/UPDATE/DELETE with data[] rows and old[]
    pre-images (reference CanalRecordParser)."""
    node = _loads(message)
    typ = (node.get("type") or "").upper()
    rows = node.get("data") or []
    olds = node.get("old") or []
    out: list[CdcRecord] = []
    if typ == "INSERT":
        out.extend(CdcRecord(r, "+I") for r in rows)
    elif typ == "UPDATE":
        for i, r in enumerate(rows):
            old = olds[i] if i < len(olds) and olds[i] else {}
            # canal's old[] carries only changed fields: pre-image = row + old
            before = {**r, **old}
            out.append(CdcRecord(before, "-U"))
            out.append(CdcRecord(r, "+U"))
    elif typ == "DELETE":
        out.extend(CdcRecord(r, "-D") for r in rows)
    elif typ in ("CREATE", "ALTER", "QUERY", "TRUNCATE"):
        return []  # DDL events carry no rows; schema evolves from data
    else:
        raise ValueError(f"unknown canal type {typ!r}")
    return out


def parse_maxwell(message: str | bytes | Mapping) -> list[CdcRecord]:
    """Maxwell JSON: type insert/update/delete with data and old
    (reference MaxwellRecordParser)."""
    node = _loads(message)
    typ = node.get("type")
    data = node.get("data") or {}
    old = node.get("old") or {}
    if typ == "insert" or typ == "bootstrap-insert":
        return [CdcRecord(data, "+I")]
    if typ == "update":
        return [CdcRecord({**data, **old}, "-U"), CdcRecord(data, "+U")]
    if typ == "delete":
        return [CdcRecord(data, "-D")]
    if typ in ("bootstrap-start", "bootstrap-complete", "table-create", "table-alter"):
        return []
    raise ValueError(f"unknown maxwell type {typ!r}")


def parse_json(message: str | bytes | Mapping) -> list[CdcRecord]:
    """Plain JSON records: each message is one +I row."""
    return [CdcRecord(_loads(message), "+I")]


_PARSERS: dict[str, Callable[[Any], list[CdcRecord]]] = {
    "debezium-json": parse_debezium,
    "debezium": parse_debezium,
    "canal-json": parse_canal,
    "canal": parse_canal,
    "maxwell-json": parse_maxwell,
    "maxwell": parse_maxwell,
    "json": parse_json,
}


def get_cdc_parser(fmt: str) -> Callable[[Any], list[CdcRecord]]:
    if fmt not in _PARSERS:
        raise ValueError(f"unknown cdc format {fmt!r}; known: {sorted(_PARSERS)}")
    return _PARSERS[fmt]


class CdcStream:
    """The source->sink pipeline: parse raw messages with a format parser and
    feed the schema-evolving sink, committing per batch (the engine-neutral
    SyncTableAction analog — reference SynchronizationActionBase)."""

    def __init__(self, table, fmt: str = "debezium-json"):
        self.parser = get_cdc_parser(fmt)
        self.write = CdcTableWrite(table)
        # resume after the table's last commit by THIS user: restarting the
        # stream must not reuse identifiers the replay filter already saw
        # (it would silently drop the new batches).  Batch commits carry the
        # sentinel identifier 2^63-1 (reference BatchWriteBuilder MAX_VALUE)
        # and the same user may interleave batch maintenance with the stream;
        # resuming from the sentinel would push identifiers past int64 and
        # break format parity, so only streaming identifiers count.
        self._commit_id = 0
        sm = table.store.snapshot_manager
        for snap in sm.snapshots_of_user(table.store.commit_user):
            if snap.commit_identifier != BATCH_COMMIT_IDENTIFIER:
                self._commit_id = snap.commit_identifier
                break

    def ingest(self, messages: Iterable[str | bytes | Mapping]) -> int:
        """Parse + buffer one batch of raw messages, then flush as one
        commit. Returns the number of records applied (0 when the batch was
        a replay the commit filter dropped). Parsing completes for the WHOLE
        batch before anything is buffered, so a malformed message cannot
        leave half a batch behind to ride along with a later commit."""
        records = [record for m in messages for record in self.parser(m)]
        for record in records:
            self.write.write(record)
        self._commit_id += 1
        return self.write.flush(self._commit_id)

    @property
    def table(self):
        return self.write.table
