"""CDC source formats: parse change-capture JSON streams into CdcRecords.

Parity: /root/reference/paimon-flink/paimon-flink-cdc/src/main/java/org/
apache/paimon/flink/action/cdc/format/ — RecordParser subclasses for
debezium (DebeziumRecordParser: payload/before/after/op c|u|d|r), canal
(CanalRecordParser: data[]/old[]/type INSERT|UPDATE|DELETE), maxwell
(MaxwellRecordParser: data/old/type insert|update|delete), and plain json.
Each parser turns one raw message into 0..2 CdcRecords (-U/+U pairs for
updates) plus optional primary-key hints; records feed the schema-evolving
CdcTableWrite sink, completing the source half the round-1 build lacked.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Iterable, Iterator, Mapping

from ..core.commit import BATCH_COMMIT_IDENTIFIER
from .cdc import CdcRecord, CdcTableWrite

__all__ = [
    "parse_debezium",
    "parse_canal",
    "parse_maxwell",
    "parse_json",
    "get_cdc_parser",
    "format_debezium",
    "format_canal",
    "format_maxwell",
    "format_json",
    "get_cdc_formatter",
    "encode_changelog",
    "CdcStream",
]


def _loads(message: str | bytes | Mapping | None):
    if message is None or isinstance(message, Mapping):
        return message
    return json.loads(message)


def parse_debezium(message: str | bytes | Mapping) -> list[CdcRecord]:
    """Debezium JSON (optionally schema-wrapped): op c/r -> +I, u -> -U/+U,
    d -> -D; tombstones (null payload / null message) are skipped
    (reference DebeziumRecordParser ignores null payloads)."""
    node = _loads(message)
    if node is None:
        return []
    if "payload" in node:
        node = node["payload"]
        if node is None:  # kafka compaction tombstone after a delete
            return []
    op = node.get("op")
    before = node.get("before")
    after = node.get("after")
    if op in ("c", "r"):
        return [CdcRecord(after, "+I")] if after else []
    if op == "u":
        out = []
        if before:
            out.append(CdcRecord(before, "-U"))
        if after:
            out.append(CdcRecord(after, "+U"))
        return out
    if op == "d":
        return [CdcRecord(before, "-D")] if before else []
    raise ValueError(f"unknown debezium op {op!r}")


def parse_canal(message: str | bytes | Mapping) -> list[CdcRecord]:
    """Canal JSON: type INSERT/UPDATE/DELETE with data[] rows and old[]
    pre-images (reference CanalRecordParser)."""
    node = _loads(message)
    typ = (node.get("type") or "").upper()
    rows = node.get("data") or []
    olds = node.get("old") or []
    out: list[CdcRecord] = []
    if typ == "INSERT":
        out.extend(CdcRecord(r, "+I") for r in rows)
    elif typ == "UPDATE":
        for i, r in enumerate(rows):
            old = olds[i] if i < len(olds) and olds[i] else {}
            # canal's old[] carries only changed fields: pre-image = row + old
            before = {**r, **old}
            out.append(CdcRecord(before, "-U"))
            out.append(CdcRecord(r, "+U"))
    elif typ == "DELETE":
        out.extend(CdcRecord(r, "-D") for r in rows)
    elif typ in ("CREATE", "ALTER", "QUERY", "TRUNCATE"):
        return []  # DDL events carry no rows; schema evolves from data
    else:
        raise ValueError(f"unknown canal type {typ!r}")
    return out


def parse_maxwell(message: str | bytes | Mapping) -> list[CdcRecord]:
    """Maxwell JSON: type insert/update/delete with data and old
    (reference MaxwellRecordParser)."""
    node = _loads(message)
    typ = node.get("type")
    data = node.get("data") or {}
    old = node.get("old") or {}
    if typ == "insert" or typ == "bootstrap-insert":
        return [CdcRecord(data, "+I")]
    if typ == "update":
        return [CdcRecord({**data, **old}, "-U"), CdcRecord(data, "+U")]
    if typ == "delete":
        return [CdcRecord(data, "-D")]
    if typ in ("bootstrap-start", "bootstrap-complete", "table-create", "table-alter"):
        return []
    raise ValueError(f"unknown maxwell type {typ!r}")


def parse_json(message: str | bytes | Mapping) -> list[CdcRecord]:
    """Plain JSON records: each message is one +I row."""
    return [CdcRecord(_loads(message), "+I")]


# ---------------------------------------------------------------------------
# wire formatters: the encode half of each parser. The subscription service
# (service/subscription.py + the Flight subscribe endpoint) emits change
# events in any of these formats; the invariant, pinned by tests, is
# parse(format(events)) == events bit-identically — -U/+U pairs fold into one
# UPDATE wire message and come back out as the same pair.
# ---------------------------------------------------------------------------


def _pair_events(events: Iterable[tuple[str, Mapping]]) -> Iterator[tuple[str, Mapping, Mapping | None]]:
    """Group a changelog event stream into wire units: ('+I', row, None),
    ('-D', row, None), or ('U', after, before) for a -U immediately followed
    by its +U (the changelog producers always emit the pair adjacently)."""
    pending_before: Mapping | None = None
    for kind, row in events:
        if pending_before is not None:
            if kind != "+U":
                raise ValueError(f"-U not followed by +U (got {kind!r})")
            yield "U", row, pending_before
            pending_before = None
        elif kind == "-U":
            pending_before = row
        elif kind in ("+I", "+U"):
            # a lone +U (e.g. dedup dropped its -U) wires as an insert-style
            # upsert: the parsers return it as +I, which folds identically
            yield "+I", row, None
        elif kind == "-D":
            yield "-D", row, None
        else:
            raise ValueError(f"unknown row kind {kind!r}")
    if pending_before is not None:
        raise ValueError("dangling -U at end of stream")


def format_debezium(events: Iterable[tuple[str, Mapping]]) -> list[str]:
    """Changelog events -> debezium JSON messages (op c/u/d with
    before/after), the inverse of parse_debezium."""
    out = []
    for unit, after, before in _pair_events(events):
        if unit == "+I":
            node = {"op": "c", "before": None, "after": dict(after)}
        elif unit == "U":
            node = {"op": "u", "before": dict(before), "after": dict(after)}
        else:
            node = {"op": "d", "before": dict(after), "after": None}
        out.append(json.dumps(node))
    return out


def format_canal(events: Iterable[tuple[str, Mapping]]) -> list[str]:
    """Changelog events -> canal JSON (type INSERT/UPDATE/DELETE with data[]
    and old[]). old[] carries the FULL pre-image so parse_canal's
    {**row, **old} reconstruction returns it bit-identically."""
    out = []
    for unit, after, before in _pair_events(events):
        if unit == "+I":
            node = {"type": "INSERT", "data": [dict(after)], "old": None}
        elif unit == "U":
            node = {"type": "UPDATE", "data": [dict(after)], "old": [dict(before)]}
        else:
            node = {"type": "DELETE", "data": [dict(after)], "old": None}
        out.append(json.dumps(node))
    return out


def format_maxwell(events: Iterable[tuple[str, Mapping]]) -> list[str]:
    """Changelog events -> maxwell JSON (type insert/update/delete with
    data/old; old carries the full pre-image for bit-identical roundtrip)."""
    out = []
    for unit, after, before in _pair_events(events):
        if unit == "+I":
            node = {"type": "insert", "data": dict(after)}
        elif unit == "U":
            node = {"type": "update", "data": dict(after), "old": dict(before)}
        else:
            node = {"type": "delete", "data": dict(after)}
        out.append(json.dumps(node))
    return out


def format_json(events: Iterable[tuple[str, Mapping]]) -> list[str]:
    """Insert-only plain JSON: one row per message. Retractions cannot be
    expressed in this format — encoding them is an error, not silent loss."""
    out = []
    for kind, row in events:
        if kind != "+I":
            raise ValueError(f"plain json cannot encode {kind!r} rows")
        out.append(json.dumps(dict(row)))
    return out


def encode_changelog(data, kinds, fmt: str) -> list[str]:
    """ColumnBatch + RowKind vector -> wire messages in `fmt`. Values
    materialize per row via to_pylist (code-backed/dictionary columns expand
    lazily here and nowhere earlier — the decoded batch itself stays in the
    code domain for every other consumer)."""
    from ..types import RowKind

    names = data.schema.field_names
    events = [
        (RowKind(int(k)).short_string, dict(zip(names, row)))
        for row, k in zip(data.to_pylist(), kinds.tolist())
    ]
    return get_cdc_formatter(fmt)(events)


_FORMATTERS: dict[str, Callable[[Iterable[tuple[str, Mapping]]], list[str]]] = {
    "debezium-json": format_debezium,
    "debezium": format_debezium,
    "canal-json": format_canal,
    "canal": format_canal,
    "maxwell-json": format_maxwell,
    "maxwell": format_maxwell,
    "json": format_json,
}


def get_cdc_formatter(fmt: str) -> Callable[[Iterable[tuple[str, Mapping]]], list[str]]:
    if fmt not in _FORMATTERS:
        raise ValueError(f"unknown cdc format {fmt!r}; known: {sorted(_FORMATTERS)}")
    return _FORMATTERS[fmt]


_PARSERS: dict[str, Callable[[Any], list[CdcRecord]]] = {
    "debezium-json": parse_debezium,
    "debezium": parse_debezium,
    "canal-json": parse_canal,
    "canal": parse_canal,
    "maxwell-json": parse_maxwell,
    "maxwell": parse_maxwell,
    "json": parse_json,
}


def get_cdc_parser(fmt: str) -> Callable[[Any], list[CdcRecord]]:
    if fmt not in _PARSERS:
        raise ValueError(f"unknown cdc format {fmt!r}; known: {sorted(_PARSERS)}")
    return _PARSERS[fmt]


class CdcStream:
    """The source->sink pipeline: parse raw messages with a format parser and
    feed the schema-evolving sink, committing per batch (the engine-neutral
    SyncTableAction analog — reference SynchronizationActionBase)."""

    def __init__(self, table, fmt: str = "debezium-json"):
        self.parser = get_cdc_parser(fmt)
        self.write = CdcTableWrite(table)
        # resume after the table's last commit by THIS user: restarting the
        # stream must not reuse identifiers the replay filter already saw
        # (it would silently drop the new batches).  Batch commits carry the
        # sentinel identifier 2^63-1 (reference BatchWriteBuilder MAX_VALUE)
        # and the same user may interleave batch maintenance with the stream;
        # resuming from the sentinel would push identifiers past int64 and
        # break format parity, so only streaming identifiers count.
        self._commit_id = 0
        sm = table.store.snapshot_manager
        for snap in sm.snapshots_of_user(table.store.commit_user):
            if snap.commit_identifier != BATCH_COMMIT_IDENTIFIER:
                self._commit_id = snap.commit_identifier
                break

    def ingest(self, messages: Iterable[str | bytes | Mapping]) -> int:
        """Parse + buffer one batch of raw messages, then flush as one
        commit. Returns the number of records applied (0 when the batch was
        a replay the commit filter dropped). Parsing completes for the WHOLE
        batch before anything is buffered, so a malformed message cannot
        leave half a batch behind to ride along with a later commit."""
        records = [record for m in messages for record in self.parser(m)]
        for record in records:
            self.write.write(record)
        self._commit_id += 1
        return self.write.flush(self._commit_id)

    @property
    def table(self):
        return self.write.table
