"""System tables: table metadata queryable as tables.

Parity: /root/reference/paimon-core/.../table/system/ (21 virtual tables,
SystemTableLoader) — here: snapshots, schemas, options, files, manifests,
tags, branches, consumers, partitions, buckets, audit_log, read_optimized,
statistics, aggregation_fields.
Accessed as `table$snapshots` through the catalog or `system_table(t, name)`.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import numpy as np

from ..data.batch import ColumnBatch
from ..types import BIGINT, INT, STRING, RowKind, RowType

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["system_table", "SYSTEM_TABLES"]


def system_table(table: "FileStoreTable", name: str):
    try:
        fn = SYSTEM_TABLES[name]
    except KeyError:
        raise ValueError(f"unknown system table {name!r}; known: {sorted(SYSTEM_TABLES)}") from None
    return fn(table)


class _StaticTable:
    """A read-only snapshot of metadata as a ColumnBatch-producing table."""

    def __init__(self, name: str, batch: ColumnBatch):
        self.name = name
        self._batch = batch
        self.row_type = batch.schema

    def read(self) -> ColumnBatch:
        return self._batch

    def to_pylist(self):
        return self._batch.to_pylist()


def _snapshots(table: "FileStoreTable") -> _StaticTable:
    schema = RowType.of(
        ("snapshot_id", BIGINT(False)),
        ("schema_id", BIGINT(False)),
        ("commit_user", STRING(False)),
        ("commit_identifier", BIGINT(False)),
        ("commit_kind", STRING(False)),
        ("commit_time", BIGINT(False)),
        ("total_record_count", BIGINT()),
        ("delta_record_count", BIGINT()),
        ("watermark", BIGINT()),
    )
    rows = [
        (s.id, s.schema_id, s.commit_user, s.commit_identifier, s.commit_kind.value, s.time_millis,
         s.total_record_count, s.delta_record_count, s.watermark)
        for s in table.store.snapshot_manager.snapshots()
    ]
    return _StaticTable("snapshots", ColumnBatch.from_pylist(schema, rows))


def _schemas(table: "FileStoreTable") -> _StaticTable:
    schema = RowType.of(
        ("schema_id", BIGINT(False)),
        ("fields", STRING(False)),
        ("partition_keys", STRING(False)),
        ("primary_keys", STRING(False)),
        ("options", STRING(False)),
        ("update_time", BIGINT(False)),
    )
    from ..utils import dumps

    rows = [
        (sid, dumps([f.to_dict() for f in ts.fields]), dumps(list(ts.partition_keys)),
         dumps(list(ts.primary_keys)), dumps(ts.options), ts.time_millis)
        for sid, ts in sorted(table.store.schema_manager.all_schemas().items())
    ]
    return _StaticTable("schemas", ColumnBatch.from_pylist(schema, rows))


def _options(table: "FileStoreTable") -> _StaticTable:
    schema = RowType.of(("key", STRING(False)), ("value", STRING(False)))
    rows = sorted(table.schema.options.items())
    return _StaticTable("options", ColumnBatch.from_pylist(schema, rows))


def _files(table: "FileStoreTable") -> _StaticTable:
    schema = RowType.of(
        ("partition", STRING(False)),
        ("bucket", INT(False)),
        ("file_path", STRING(False)),
        ("level", INT(False)),
        ("record_count", BIGINT(False)),
        ("file_size_in_bytes", BIGINT(False)),
        ("min_key", STRING()),
        ("max_key", STRING()),
        ("min_sequence_number", BIGINT(False)),
        ("max_sequence_number", BIGINT(False)),
        ("creation_time", BIGINT(False)),
    )
    rows = []
    plan = table.store.new_scan().plan()
    for e in plan.entries:
        f = e.file
        rows.append(
            (str(list(e.partition)), e.bucket, f.file_name, f.level, f.row_count, f.file_size,
             str(list(f.min_key)), str(list(f.max_key)), f.min_sequence_number, f.max_sequence_number,
             f.creation_time_millis)
        )
    return _StaticTable("files", ColumnBatch.from_pylist(schema, rows))


def _manifests(table: "FileStoreTable") -> _StaticTable:
    schema = RowType.of(
        ("file_name", STRING(False)),
        ("file_size", BIGINT(False)),
        ("num_added_files", BIGINT(False)),
        ("num_deleted_files", BIGINT(False)),
        ("schema_id", BIGINT(False)),
    )
    snap = table.store.snapshot_manager.latest_snapshot()
    rows = []
    if snap is not None:
        from ..core.manifest import ManifestList

        ml = ManifestList(table.file_io, f"{table.path}/manifest")
        metas = ml.read(snap.base_manifest_list) + ml.read(snap.delta_manifest_list)
        rows = [(m.file_name, m.file_size, m.num_added_files, m.num_deleted_files, m.schema_id) for m in metas]
    return _StaticTable("manifests", ColumnBatch.from_pylist(schema, rows))


def _tags(table: "FileStoreTable") -> _StaticTable:
    schema = RowType.of(("tag_name", STRING(False)), ("snapshot_id", BIGINT(False)))
    rows = sorted(table.tags().items())
    return _StaticTable("tags", ColumnBatch.from_pylist(schema, rows))


def _branches(table: "FileStoreTable") -> _StaticTable:
    from ..core.schema import SchemaManager
    from ..core.snapshot import SnapshotManager
    from .branch import BranchManager

    schema = RowType.of(
        ("branch_name", STRING(False)),
        ("created_from_snapshot", BIGINT()),
        ("latest_snapshot", BIGINT()),
        ("latest_schema_id", BIGINT()),
    )
    bm = BranchManager(table.file_io, table.path)
    rows = []
    for name in bm.list_branches():
        bp = bm.branch_path(name)
        bsm = SnapshotManager(table.file_io, bp)
        latest_schema = SchemaManager(table.file_io, bp).latest()
        rows.append(
            (name, bm.created_from(name), bsm.latest_snapshot_id(), latest_schema.id if latest_schema else None)
        )
    return _StaticTable("branches", ColumnBatch.from_pylist(schema, rows))


def _consumers(table: "FileStoreTable") -> _StaticTable:
    from .consumer import ConsumerManager

    schema = RowType.of(("consumer_id", STRING(False)), ("next_snapshot_id", BIGINT(False)))
    rows = sorted(ConsumerManager(table.file_io, table.path).list_consumers().items())
    return _StaticTable("consumers", ColumnBatch.from_pylist(schema, rows))


def _partitions(table: "FileStoreTable") -> _StaticTable:
    schema = RowType.of(
        ("partition", STRING(False)),
        ("record_count", BIGINT(False)),
        ("file_size_in_bytes", BIGINT(False)),
        ("file_count", BIGINT(False)),
    )
    agg: dict[str, list[int]] = {}
    for e in table.store.new_scan().plan().entries:
        key = str(list(e.partition))
        acc = agg.setdefault(key, [0, 0, 0])
        acc[0] += e.file.row_count
        acc[1] += e.file.file_size
        acc[2] += 1
    rows = [(k, v[0], v[1], v[2]) for k, v in sorted(agg.items())]
    return _StaticTable("partitions", ColumnBatch.from_pylist(schema, rows))


def _buckets(table: "FileStoreTable") -> _StaticTable:
    schema = RowType.of(
        ("partition", STRING(False)),
        ("bucket", INT(False)),
        ("record_count", BIGINT(False)),
        ("file_size_in_bytes", BIGINT(False)),
        ("file_count", BIGINT(False)),
    )
    agg: dict[tuple, list[int]] = {}
    for e in table.store.new_scan().plan().entries:
        key = (str(list(e.partition)), e.bucket)
        acc = agg.setdefault(key, [0, 0, 0])
        acc[0] += e.file.row_count
        acc[1] += e.file.file_size
        acc[2] += 1
    rows = [(k[0], k[1], v[0], v[1], v[2]) for k, v in sorted(agg.items())]
    return _StaticTable("buckets", ColumnBatch.from_pylist(schema, rows))


class _AuditLogTable:
    """Rows with their changelog kind as a leading `rowkind` column
    (reference table/system/AuditLogTable — -U/-D rows are NOT dropped)."""

    def __init__(self, table: "FileStoreTable"):
        self.table = table
        self.name = f"{table.name}$audit_log"
        from ..types import DataField

        self.row_type = RowType(
            [DataField(-1, "rowkind", STRING(False)), *table.row_type.fields]
        )

    def read(self) -> ColumnBatch:
        from ..core.read import MergeFileSplitRead

        store = self.table.store
        splits = self.table.new_read_builder().new_scan().plan()
        batches = []
        for s in splits:
            read = MergeFileSplitRead(
                store.reader_factory(s.partition, s.bucket), store.merge_executor(), store.key_names
            )
            kv = read.read_kv(s.files)
            from ..data.batch import Column

            kinds = np.array([RowKind(int(k)).short_string for k in kv.kind], dtype=object)
            data = kv.data
            cols = {"rowkind": Column(kinds)}
            cols.update(data.columns)
            batches.append(ColumnBatch(self.row_type, cols))
        from ..data.batch import concat_batches

        return concat_batches(batches) if batches else ColumnBatch.empty(self.row_type)

    def to_pylist(self):
        return self.read().to_pylist()


class _ReadOptimizedTable:
    """Top-level-only read: no merge cost, possibly stale
    (reference table/system/ReadOptimizedTable)."""

    def __init__(self, table: "FileStoreTable"):
        self.table = table
        self.name = f"{table.name}$read_optimized"
        self.row_type = table.row_type

    def read(self) -> ColumnBatch:
        store = self.table.store
        max_level = store.options.num_levels - 1
        plan = store.new_scan().with_level(max_level).plan()
        batches = []
        for partition, buckets in sorted(plan.grouped().items()):
            for bucket, files in sorted(buckets.items()):
                batches.append(store.read_bucket(partition, bucket, files))
        from ..data.batch import concat_batches

        return concat_batches(batches) if batches else ColumnBatch.empty(self.row_type)

    def to_pylist(self):
        return self.read().to_pylist()


def _statistics(table: "FileStoreTable") -> _StaticTable:
    from .statistics import read_statistics

    schema = RowType.of(
        ("snapshot_id", BIGINT(False)),
        ("schema_id", BIGINT(False)),
        ("mergedRecordCount", BIGINT()),
        ("mergedRecordSize", BIGINT()),
        ("colstat", STRING()),
    )
    stats = read_statistics(table)
    rows = []
    if stats is not None:
        from ..utils import dumps

        rows = [(stats.snapshot_id, stats.schema_id, stats.merged_record_count, stats.merged_record_size, dumps(stats.col_stats))]
    return _StaticTable("statistics", ColumnBatch.from_pylist(schema, rows))


def _aggregation_fields(table: "FileStoreTable") -> _StaticTable:
    schema = RowType.of(
        ("field_name", STRING(False)),
        ("field_type", STRING(False)),
        ("function", STRING()),
        ("function_options", STRING()),
        ("comment", STRING()),
    )
    co = table.options
    rows = []
    for f in table.row_type.fields:
        fn = co.field_option(f.name, "aggregate-function")
        opts = []
        for suffix in ("ignore-retract", "distinct", "list-agg-delimiter", "sequence-group"):
            v = co.field_option(f.name, suffix)
            if v is not None:
                opts.append(f"{suffix}={v}")
        rows.append((f.name, str(f.type), fn, ",".join(opts) or None, f.description))
    return _StaticTable("aggregation_fields", ColumnBatch.from_pylist(schema, rows))


def _file_monitor(table: "FileStoreTable") -> _StaticTable:
    """Per-snapshot file changes (reference FileMonitorTable: _SNAPSHOT_ID,
    _PARTITION, _BUCKET, _BEFORE_FILES, _DATA_FILES) — the input of the
    dedicated-compaction and lookup-refresh topologies."""
    from json import dumps

    schema = RowType.of(
        ("_SNAPSHOT_ID", BIGINT(False)),
        ("_PARTITION", STRING(False)),
        ("_BUCKET", INT(False)),
        ("_BEFORE_FILES", STRING(False)),
        ("_DATA_FILES", STRING(False)),
    )
    store = table.store
    sm = store.snapshot_manager
    rows = []
    latest = sm.latest_snapshot_id()
    earliest = sm.earliest_snapshot_id()
    if latest is not None and earliest is not None:
        for sid in range(earliest, latest + 1):
            if not sm.snapshot_exists(sid):
                continue
            plan = store.new_scan().with_snapshot(sid).with_kind("delta").plan()
            by_pb: dict[tuple, dict[str, list]] = {}
            for e in plan.entries:
                slot = by_pb.setdefault((e.partition, e.bucket), {"before": [], "after": []})
                slot["after" if e.kind.name == "ADD" else "before"].append(e.file.file_name)
            for (partition, bucket), slot in sorted(by_pb.items()):
                rows.append(
                    (
                        sid,
                        dumps(list(partition)),
                        bucket,
                        dumps(sorted(slot["before"])),
                        dumps(sorted(slot["after"])),
                    )
                )
    return _StaticTable("file_monitor", ColumnBatch.from_pylist(schema, rows))


SYSTEM_TABLES = {
    "snapshots": _snapshots,
    "statistics": _statistics,
    "aggregation_fields": _aggregation_fields,
    "schemas": _schemas,
    "options": _options,
    "files": _files,
    "manifests": _manifests,
    "tags": _tags,
    "branches": _branches,
    "consumers": _consumers,
    "partitions": _partitions,
    "buckets": _buckets,
    "audit_log": _AuditLogTable,
    "read_optimized": _ReadOptimizedTable,
    "file_monitor": _file_monitor,
}
