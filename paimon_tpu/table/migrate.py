"""In-place migration: adopt existing parquet/orc files as a table.

Parity: /root/reference/paimon-core/.../migrate/Migrator.java + FileMetaUtils
— Hive-table migration reuses the existing ORC/Parquet data files and
synthesizes manifests around them; no data rewrite. Here: point at a
directory (optionally hive-partitioned `k=v` subdirs) of parquet/orc files
and commit them as an append-only table.
"""

from __future__ import annotations

from ..catalog import Catalog, Identifier
from ..core.datafile import DataFileMeta
from ..core.manifest import CommitMessage, ManifestCommittable
from ..format import collect_stats, get_format
from ..fs import get_file_io
from ..types import RowType
from ..utils import now_millis

__all__ = ["migrate_files", "adopt_table_files"]


def adopt_table_files(
    catalog: Catalog,
    source_identifier: "Identifier | str",
    target_identifier: "Identifier | str",
) -> int:
    """MigrateFileProcedure analog: adopt the data files of one append table
    into another existing append table with the same schema — a file-level
    adoption commit, no data rewrite (reference Migrator.executeMigrate's
    file-move path). Returns the number of files adopted. The source table is
    left intact for the caller to drop (which reclaims the originals)."""
    src = catalog.get_table(source_identifier)
    tgt = catalog.get_table(target_identifier)
    if src.primary_keys or tgt.primary_keys:
        raise ValueError("migrate_file supports append (no primary key) tables only")
    if [f.type for f in src.row_type.fields] != [f.type for f in tgt.row_type.fields]:
        raise ValueError("migrate_file requires identical schemas")
    import dataclasses

    plan = src.store.new_scan().plan()
    # rebase adopted sequence numbers above the target's current maximum so
    # commit-time ordering invariants hold
    base = 0
    for e in tgt.store.new_scan().plan().entries:
        base = max(base, e.file.max_sequence_number + 1)
    by_partition: dict[tuple, list[DataFileMeta]] = {}
    moved = 0
    from ..utils import new_file_name

    # COPY files in, then commit: a crash mid-adoption leaves only orphan
    # copies in the target (cleaned by remove_orphan_files) — the source
    # table stays fully intact either way (a move ordering would break the
    # source manifests on a mid-loop failure). The caller drops the source
    # table afterwards, which reclaims the originals.
    for e in plan.entries:
        src_dir = src.store.bucket_dir(e.partition, e.bucket)
        tgt_dir = tgt.store.bucket_dir(e.partition, 0)
        tgt.file_io.mkdirs(tgt_dir)
        # fresh target-local name: adopted tables may carry identical
        # foreign names (e.g. two hive dirs both holding part-0.parquet)
        ext = e.file.file_name.rsplit(".", 1)[-1]
        name = new_file_name("data", ext)
        tgt.file_io.write_bytes(
            f"{tgt_dir}/{name}", src.file_io.read_bytes(f"{src_dir}/{e.file.file_name}")
        )
        # index sidecars follow their data file, renamed to match
        new_extra = []
        for x in e.file.extra_files:
            if x == f"{e.file.file_name}.index":
                tgt.file_io.write_bytes(
                    f"{tgt_dir}/{name}.index", src.file_io.read_bytes(f"{src_dir}/{x}")
                )
                new_extra.append(f"{name}.index")
            else:
                new_extra.append(x)
        span = e.file.max_sequence_number - e.file.min_sequence_number
        meta = dataclasses.replace(
            e.file, file_name=name, extra_files=tuple(new_extra),
            min_sequence_number=base, max_sequence_number=base + span,
        )
        base += span + 1
        by_partition.setdefault(e.partition, []).append(meta)
        moved += 1
    if by_partition:
        messages = [
            CommitMessage(part, 0, 1, new_files=files)
            for part, files in by_partition.items()
        ]
        tgt.store.new_commit().commit(ManifestCommittable(now_millis(), messages=messages))
    return moved


def migrate_files(
    catalog: Catalog,
    identifier: "Identifier | str",
    source_dir: str,
    row_type: RowType,
    file_format: str = "parquet",
    partition_keys: tuple = (),
    options: dict | None = None,
):
    """Create an append-only table whose data files are the existing files
    under source_dir (moved, not rewritten)."""
    file_io = get_file_io(source_dir)
    opts = {"bucket": "1", "file.format": file_format}
    opts.update(options or {})
    table = catalog.create_table(
        identifier, row_type, partition_keys=partition_keys, options=opts, ignore_if_exists=False
    )
    fmt = get_format(file_format)
    messages = []
    seq = 0

    def adopt_dir(directory: str, partition: tuple):
        nonlocal seq
        files = []
        for st in sorted(file_io.list_files(directory), key=lambda s: s.path):
            if not st.path.endswith(f".{file_format}"):
                continue
            # read once to derive row count + stats (metadata-only pass would
            # need footer parsing; stats make the planner useful immediately)
            batches = list(fmt.read(file_io, st.path, row_type))
            rows = sum(b.num_rows for b in batches)
            if rows == 0:
                continue
            from ..data.batch import concat_batches

            stats = collect_stats(concat_batches(batches))
            name = st.path.rsplit("/", 1)[-1]
            bucket_dir = table.store.bucket_dir(partition, 0)
            file_io.mkdirs(bucket_dir)
            ok = file_io.rename(st.path, f"{bucket_dir}/{name}")
            if not ok:
                raise RuntimeError(f"cannot move {st.path} into the table (name collision)")
            files.append(
                DataFileMeta(
                    file_name=name,
                    file_size=st.size,
                    row_count=rows,
                    min_key=(),
                    max_key=(),
                    key_stats={},
                    value_stats=stats,
                    min_sequence_number=seq,
                    max_sequence_number=seq + rows - 1,
                    schema_id=table.schema.id,
                    level=0,
                    creation_time_millis=now_millis(),
                    file_source="append",
                )
            )
            seq += rows
        if files:
            messages.append(CommitMessage(partition, 0, 1, new_files=files))

    if partition_keys:
        for st in file_io.list_status(source_dir):
            if not st.is_dir:
                continue
            parts = st.path.rsplit("/", 1)[-1].split("=")
            if len(parts) == 2 and parts[0] == partition_keys[0]:
                adopt_dir(st.path, (parts[1],))
    else:
        adopt_dir(source_dir, ())
    if messages:
        table.store.new_commit().commit(ManifestCommittable(1, messages=messages))
    return table
