"""In-place migration: adopt existing parquet/orc files as a table.

Parity: /root/reference/paimon-core/.../migrate/Migrator.java + FileMetaUtils
— Hive-table migration reuses the existing ORC/Parquet data files and
synthesizes manifests around them; no data rewrite. Here: point at a
directory (optionally hive-partitioned `k=v` subdirs) of parquet/orc files
and commit them as an append-only table.
"""

from __future__ import annotations

from ..catalog import Catalog, Identifier
from ..core.datafile import DataFileMeta
from ..core.manifest import CommitMessage, ManifestCommittable
from ..format import collect_stats, get_format
from ..fs import get_file_io
from ..types import RowType
from ..utils import now_millis

__all__ = ["migrate_files"]


def migrate_files(
    catalog: Catalog,
    identifier: "Identifier | str",
    source_dir: str,
    row_type: RowType,
    file_format: str = "parquet",
    partition_keys: tuple = (),
    options: dict | None = None,
):
    """Create an append-only table whose data files are the existing files
    under source_dir (moved, not rewritten)."""
    file_io = get_file_io(source_dir)
    opts = {"bucket": "1", "file.format": file_format}
    opts.update(options or {})
    table = catalog.create_table(
        identifier, row_type, partition_keys=partition_keys, options=opts, ignore_if_exists=False
    )
    fmt = get_format(file_format)
    messages = []
    seq = 0

    def adopt_dir(directory: str, partition: tuple):
        nonlocal seq
        files = []
        for st in sorted(file_io.list_files(directory), key=lambda s: s.path):
            if not st.path.endswith(f".{file_format}"):
                continue
            # read once to derive row count + stats (metadata-only pass would
            # need footer parsing; stats make the planner useful immediately)
            batches = list(fmt.read(file_io, st.path, row_type))
            rows = sum(b.num_rows for b in batches)
            if rows == 0:
                continue
            from ..data.batch import concat_batches

            stats = collect_stats(concat_batches(batches))
            name = st.path.rsplit("/", 1)[-1]
            bucket_dir = table.store.bucket_dir(partition, 0)
            file_io.mkdirs(bucket_dir)
            ok = file_io.rename(st.path, f"{bucket_dir}/{name}")
            if not ok:
                raise RuntimeError(f"cannot move {st.path} into the table (name collision)")
            files.append(
                DataFileMeta(
                    file_name=name,
                    file_size=st.size,
                    row_count=rows,
                    min_key=(),
                    max_key=(),
                    key_stats={},
                    value_stats=stats,
                    min_sequence_number=seq,
                    max_sequence_number=seq + rows - 1,
                    schema_id=table.schema.id,
                    level=0,
                    creation_time_millis=now_millis(),
                    file_source="append",
                )
            )
            seq += rows
        if files:
            messages.append(CommitMessage(partition, 0, 1, new_files=files))

    if partition_keys:
        for st in file_io.list_status(source_dir):
            if not st.is_dir:
                continue
            parts = st.path.rsplit("/", 1)[-1].split("=")
            if len(parts) == 2 and parts[0] == partition_keys[0]:
                adopt_dir(st.path, (parts[1],))
    else:
        adopt_dir(source_dir, ())
    if messages:
        table.store.new_commit().commit(ManifestCommittable(1, messages=messages))
    return table
