"""Streaming scans: starting scanners + snapshot-by-snapshot follow-up.

Parity: /root/reference/paimon-core/.../table/source/DataTableStreamScan.java:51
with the StartingScanner variants (table/source/snapshot/: full, latest,
from-snapshot, from-timestamp, compacted-full) and DeltaFollowUpScanner.
A StreamTableScan yields (splits, checkpoint): first the starting plan, then
one delta plan per new snapshot; `restore(next)` resumes from a checkpoint
(the consumer-id mechanism persists it).
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING

from ..core.levels import IntervalPartition
from ..options import CoreOptions, StartupMode
from ..data.predicate import Predicate
from .consumer import ConsumerManager
from .read import DataSplit

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["StreamTableScan"]


class StreamTableScan:
    def __init__(self, table: "FileStoreTable", predicate: Predicate | None = None):
        self.table = table
        self.predicate = predicate
        self.store = table.store
        opts = self.store.options.options
        self.mode: StartupMode = opts.get(CoreOptions.SCAN_MODE)
        read_mode = opts.get(CoreOptions.STREAMING_READ_MODE)
        if read_mode != "file":
            raise ValueError(
                f"streaming-read-mode={read_mode!r}: only 'file' is supported "
                "('log' needs an external log system, which is out of scope)"
            )
        self.scan_mode = opts.get(CoreOptions.STREAM_SCAN_MODE)
        if self.scan_mode not in ("none", "file-monitor"):
            raise ValueError(f"unknown stream-scan-mode {self.scan_mode!r}")
        self.consumer_mode = opts.get(CoreOptions.CONSUMER_MODE)
        if self.consumer_mode not in ("exactly-once", "at-least-once"):
            raise ValueError(f"unknown consumer.mode {self.consumer_mode!r}")
        self.consumer_id = opts.get(CoreOptions.CONSUMER_ID)
        self._next: int | None = None  # next snapshot id to read
        self._started = False
        if self.consumer_id and not opts.get(CoreOptions.CONSUMER_IGNORE_PROGRESS):
            saved = ConsumerManager(table.store.file_io, table.path).consumer(self.consumer_id)
            if saved is not None:
                self._next = saved
                self._started = True  # consumer progress wins over startup mode

    # ---- checkpointing -------------------------------------------------
    def checkpoint(self) -> int | None:
        """The next snapshot to process (restore token). The value is
        remembered so notify_checkpoint_complete records exactly what the
        framework durably checkpointed — not whatever the scan advanced to
        since (the consumer must never run ahead of the restore token, or
        expiry could delete a snapshot the restore still needs)."""
        self._last_checkpoint = self._next
        return self._next

    def restore(self, next_snapshot: int | None) -> None:
        self._next = next_snapshot
        self._started = next_snapshot is not None
        self._ended = False  # a rollback may land before the bound again

    def notify_checkpoint_complete(self) -> None:
        cp = getattr(self, "_last_checkpoint", None)
        if self.consumer_id and cp is not None:
            ConsumerManager(self.table.store.file_io, self.table.path).record(self.consumer_id, cp)

    # ---- planning ------------------------------------------------------
    def plan_aligned(self, timeout_seconds: float = 60.0, poll_seconds: float | None = None) -> list[DataSplit] | None:
        """Checkpoint-aligned variant (reference flink/source/align/): blocks
        until the next snapshot is available or the timeout passes, so every
        checkpoint lands exactly on a snapshot boundary. Returns None only on
        timeout. Poll cadence defaults to continuous.discovery-interval."""
        if poll_seconds is None:
            poll_seconds = (self.store.options.options.get(CoreOptions.CONTINUOUS_DISCOVERY_INTERVAL) or 10_000) / 1000.0
        deadline = time.monotonic() + timeout_seconds
        while True:
            splits = self.plan()
            if splits is not None:
                return splits
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            time.sleep(min(poll_seconds, remaining))

    def current_watermark(self) -> int | None:
        """The watermark downstream operators should hold: normally the last
        emitted snapshot's watermark; when no snapshot has arrived for
        snapshot.watermark-idle-timeout, it advances to processing time so an
        idle table does not stall event-time windows (reference
        snapshot.watermark-idle-timeout)."""
        from ..utils import now_millis

        wm = getattr(self, "_last_watermark", None)
        idle_ms = self.store.options.options.get(CoreOptions.SNAPSHOT_WATERMARK_IDLE_TIMEOUT)
        if idle_ms is None:
            return wm
        last = getattr(self, "_last_emit_monotonic", None)
        if last is None or (time.monotonic() - last) * 1000 >= idle_ms:
            now = now_millis()
            return now if wm is None else max(wm, now)
        return wm

    def _past_bound(self, snap) -> bool:
        """scan.bounded.watermark: the stream ENDS once a snapshot's
        watermark passes the bound (reference BoundedChecker)."""
        bound = self.store.options.options.get(CoreOptions.SCAN_BOUNDED_WATERMARK)
        if bound is None or snap is None or snap.watermark is None:
            return False
        return snap.watermark > bound

    @property
    def ended(self) -> bool:
        return getattr(self, "_ended", False)

    def plan(self) -> list[DataSplit] | None:
        """None = nothing new yet. First call obeys the startup mode; later
        calls return the delta of one new snapshot each."""
        sm = self.store.snapshot_manager
        if self.ended:
            return None
        if not self._started:
            # the bound applies to the FIRST plan too (reference
            # DataTableStreamScan.tryFirstPlan + BoundedChecker): a starting
            # snapshot already past the bound ends the stream with no data
            if self._past_bound(sm.latest_snapshot()):
                self._ended = True
                return None
            self._started = True
            splits = self._starting_plan()
            if splits is not None:
                return splits
        latest = sm.latest_snapshot_id()
        if latest is None or self._next is None or self._next > latest:
            return None
        snap = sm.snapshot(self._next)
        if self._past_bound(snap):
            self._ended = True
            return None
        planned = self._next
        splits = self._delta_splits(planned, snap)
        self._next += 1
        self._last_watermark = snap.watermark
        self._last_emit_monotonic = time.monotonic()
        if self.consumer_id and self.consumer_mode == "at-least-once":
            # progress advances as soon as the plan is handed out — to the
            # PLANNED snapshot, not past it: a crash between plan and
            # processing replays this snapshot (at-least-once), and expiry
            # keeps protecting it while a reader may still be on it
            ConsumerManager(self.table.store.file_io, self.table.path).record(self.consumer_id, planned)
        return splits

    def _starting_plan(self) -> list[DataSplit] | None:
        sm = self.store.snapshot_manager
        opts = self.store.options.options
        latest = sm.latest_snapshot_id()
        mode = self.mode
        if mode == StartupMode.DEFAULT:
            mode = StartupMode.LATEST_FULL if opts.get(CoreOptions.SCAN_SNAPSHOT_ID) is None else StartupMode.FROM_SNAPSHOT
        if mode in (StartupMode.LATEST_FULL, StartupMode.COMPACTED_FULL):
            if latest is None:
                self._next = 1
                return None
            self._next = latest + 1
            return self._full_splits(latest, compacted=mode == StartupMode.COMPACTED_FULL)
        if mode == StartupMode.LATEST:
            self._next = (latest + 1) if latest is not None else 1
            return None
        if mode == StartupMode.FROM_SNAPSHOT:
            sid = opts.get(CoreOptions.SCAN_SNAPSHOT_ID) or 1
            self._next = sid
            return None
        if mode == StartupMode.FROM_SNAPSHOT_FULL:
            sid = opts.get(CoreOptions.SCAN_SNAPSHOT_ID) or latest
            if sid is None:
                self._next = 1
                return None
            self._next = sid + 1
            return self._full_splits(sid)
        if mode == StartupMode.FROM_TIMESTAMP:
            ts = opts.get(CoreOptions.SCAN_TIMESTAMP_MILLIS) or 0
            snap = sm.earlier_or_equal_time_millis(ts)
            self._next = (snap.id + 1) if snap else (sm.earliest_snapshot_id() or 1)
            return None
        raise ValueError(f"unsupported startup mode {mode}")

    def _full_splits(self, snapshot_id: int, compacted: bool = False) -> list[DataSplit]:
        scan = self.store.new_scan().with_snapshot(snapshot_id)
        if compacted:
            # read-optimized: only the highest level (no merge cost)
            max_level = self.store.options.num_levels - 1
            scan = scan.with_level(max_level)
        plan = scan.plan()
        out = []
        for partition, buckets in sorted(plan.grouped().items()):
            for bucket, files in sorted(buckets.items()):
                sections = IntervalPartition(files).partition()
                out.append(
                    DataSplit(
                        partition,
                        bucket,
                        files,
                        snapshot_id,
                        raw_convertible=all(len(s) == 1 for s in sections),
                        dv_index_file=plan.dv_index_for(partition, bucket),
                    )
                )
        return out

    def _delta_splits(self, snapshot_id: int, snap) -> list[DataSplit]:
        from ..core.snapshot import CommitKind
        from ..options import ChangelogProducer

        if self.scan_mode == "file-monitor":
            # compactor sources: raw delta files of EVERY snapshot, compaction
            # included — no changelog interpretation (reference
            # StreamScanMode.FILE_MONITOR)
            return self._raw_delta_splits(snapshot_id)
        if snap.commit_kind == CommitKind.OVERWRITE:
            if self.store.options.options.get(CoreOptions.STREAMING_READ_OVERWRITE):
                # surface the overwrite's new content as the change stream
                return self._raw_delta_splits(snapshot_id)
            return []
        producer = self.store.options.changelog_producer
        if producer in (ChangelogProducer.INPUT, ChangelogProducer.LOOKUP):
            # input: raw +I/-U/+U/-D input rides APPEND snapshots;
            # lookup: exact diffs computed at write time ride them too
            if snap.commit_kind != CommitKind.APPEND:
                return []
            return self._changelog_splits(snapshot_id)
        if producer == ChangelogProducer.FULL_COMPACTION:
            # exact changelog is produced by compaction snapshots
            if snap.commit_kind != CommitKind.COMPACT:
                return []
            return self._changelog_splits(snapshot_id)
        if snap.commit_kind != CommitKind.APPEND:
            return []  # compaction produces no new records (delta follow-up rule)
        plan = self.store.new_scan().with_snapshot(snapshot_id).with_kind("delta").plan()
        out = []
        for partition, buckets in sorted(plan.grouped().items()):
            for bucket, files in sorted(buckets.items()):
                out.append(
                    DataSplit(
                        partition,
                        bucket,
                        files,
                        snapshot_id,
                        raw_convertible=True,
                        dv_index_file=plan.dv_index_for(partition, bucket),
                    )
                )
        return out

    def _raw_delta_splits(self, snapshot_id: int) -> list[DataSplit]:
        plan = self.store.new_scan().with_snapshot(snapshot_id).with_kind("delta").plan()
        return [
            DataSplit(partition, bucket, files, snapshot_id, raw_convertible=True)
            for partition, buckets in sorted(plan.grouped().items())
            for bucket, files in sorted(buckets.items())
        ]

    def _changelog_splits(self, snapshot_id: int) -> list[DataSplit]:
        plan = self.store.new_scan().with_snapshot(snapshot_id).with_kind("changelog").plan()
        out = []
        for partition, buckets in sorted(plan.grouped().items()):
            for bucket, files in sorted(buckets.items()):
                out.append(
                    DataSplit(partition, bucket, files, snapshot_id, raw_convertible=True, is_changelog=True)
                )
        return out
