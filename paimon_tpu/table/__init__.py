"""L4: the engine-neutral Table API.

Parity: /root/reference/paimon-core/.../table/Table.java:41 —
newReadBuilder() / newBatchWriteBuilder() / newStreamWriteBuilder(), tags,
rollback; PrimaryKeyFileStoreTable / AppendOnlyFileStoreTable over the L3
store. This is the surface engines (and users) program against.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..core.schema import SchemaManager, TableSchema
from ..core.store import KeyValueFileStore
from ..fs import FileIO, get_file_io
from ..options import CoreOptions
from ..types import RowType
from .read import ReadBuilder
from .write import BatchWriteBuilder, StreamWriteBuilder

__all__ = ["Table", "FileStoreTable", "load_table"]


class Table:
    """A lake table: immutable snapshot-versioned data with builders for
    reading and writing."""

    name: str

    def new_read_builder(self) -> ReadBuilder:
        raise NotImplementedError

    def new_batch_write_builder(self) -> BatchWriteBuilder:
        raise NotImplementedError

    def new_stream_write_builder(self) -> StreamWriteBuilder:
        raise NotImplementedError


class FileStoreTable(Table):
    def __init__(self, file_io: FileIO, path: str, schema: TableSchema, commit_user: str = "anonymous"):
        self.file_io = file_io
        self.path = path
        self.schema = schema
        self.name = path.rstrip("/").rsplit("/", 1)[-1]
        if commit_user == "anonymous":
            # commit.user-prefix: attribute generated users to the job
            # (reference createCommitUser: prefix + UUID)
            prefix = schema.options.get("commit.user-prefix")
            if prefix:
                import uuid as _uuid

                commit_user = f"{prefix}-{_uuid.uuid4().hex[:12]}"
        if schema.primary_keys:
            self.store = KeyValueFileStore(file_io, path, schema, commit_user=commit_user)
        else:
            from ..core.store import AppendOnlyFileStore

            self.store = AppendOnlyFileStore(file_io, path, schema, commit_user=commit_user)

    @property
    def is_primary_key_table(self) -> bool:
        return bool(self.schema.primary_keys)

    @property
    def bucket_mode(self) -> str:
        if not self.schema.primary_keys:
            return "unaware" if self.store.options.bucket == -1 else "fixed"
        return "dynamic" if self.store.options.bucket == -1 else "fixed"

    # ---- metadata ------------------------------------------------------
    @property
    def row_type(self) -> RowType:
        return self.store.value_schema

    @property
    def primary_keys(self) -> list[str]:
        return list(self.schema.primary_keys)

    @property
    def partition_keys(self) -> list[str]:
        return list(self.schema.partition_keys)

    @property
    def options(self) -> CoreOptions:
        return self.store.options

    def copy(self, dynamic_options: dict[str, str]) -> "FileStoreTable":
        """Same table with option overrides (reference Table.copy)."""
        merged = dict(self.schema.options)
        merged.update(dynamic_options)
        from dataclasses import replace

        schema = replace(self.schema, options=merged)
        out = FileStoreTable(self.file_io, self.path, schema, self.store.commit_user)
        return self._carry_store_overrides(out)

    def with_user(self, commit_user: str) -> "FileStoreTable":
        out = FileStoreTable(self.file_io, self.path, self.schema, commit_user)
        return self._carry_store_overrides(out)

    def _carry_store_overrides(self, out: "FileStoreTable") -> "FileStoreTable":
        """A branch view resolves data files in the MAIN tree via an
        instance-level bucket_dir override (table.branch.branch_table); a
        copy/with_user rebuild must keep resolving there or pinned scans on
        the view 404 on every shared data file."""
        if "bucket_dir" in self.store.__dict__:
            out.store.bucket_dir = self.store.__dict__["bucket_dir"]
        return out

    # ---- builders ------------------------------------------------------
    def new_read_builder(self) -> ReadBuilder:
        return ReadBuilder(self)

    def new_batch_write_builder(self) -> BatchWriteBuilder:
        return BatchWriteBuilder(self)

    def new_stream_write_builder(self) -> StreamWriteBuilder:
        return StreamWriteBuilder(self)

    # ---- maintenance ---------------------------------------------------
    def create_tag(self, name: str, snapshot_id: int | None = None) -> None:
        from .tags import TagManager

        TagManager(self.file_io, self.path).create(name, snapshot_id)

    def delete_tag(self, name: str) -> None:
        from .tags import TagManager

        TagManager(self.file_io, self.path).delete(name)

    def tags(self) -> dict[str, int]:
        from .tags import TagManager

        return TagManager(self.file_io, self.path).list_tags()

    def rollback_to(self, snapshot_id: int | str) -> None:
        from .rollback import rollback_to

        rollback_to(self, snapshot_id)

    def delete_where(self, predicate) -> int:
        """DELETE FROM ... WHERE predicate (deletion-vector, -D retract, or
        copy-on-write rewrite depending on table configuration)."""
        from .delete import delete_where

        return delete_where(self, predicate)

    def update_where(self, predicate, assignments: dict) -> int:
        """UPDATE ... SET assignments WHERE predicate (reference
        UpdatePaimonTableCommand): upsert for PK tables, copy-on-write
        rewrite for append tables. Returns #rows updated."""
        from .rowops import update_where

        return update_where(self, predicate, assignments)

    def merge_into(self, source) -> "MergeInto":
        """MERGE INTO builder (reference MergeIntoPaimonTable):
        table.merge_into(source).when_matched_update(...).
        when_not_matched_insert().execute()."""
        from .rowops import MergeInto

        return MergeInto(self, source)

    # ---- Arrow-native engine surface (interop/arrow_surface.py) --------
    def to_record_batch_reader(self, predicate=None, projection=None, splits=None):
        """Lazy pyarrow.RecordBatchReader over the merge-read — the
        C-stream object any Arrow engine (duckdb/polars/pandas/datafusion)
        consumes directly."""
        from ..interop.arrow_surface import record_batch_reader

        return record_batch_reader(self, predicate=predicate, projection=projection, splits=splits)

    def to_arrow_scanner(self, predicate=None, projection=None):
        from ..interop.arrow_surface import arrow_scanner

        return arrow_scanner(self, predicate=predicate, projection=projection)

    def to_arrow_dataset(self, predicate=None, projection=None):
        from ..interop.arrow_surface import arrow_dataset

        return arrow_dataset(self, predicate=predicate, projection=projection)

    def to_arrow(self, predicate=None, projection=None):
        """Whole table as one pyarrow.Table (materializing convenience)."""
        return self.to_record_batch_reader(predicate=predicate, projection=projection).read_all()

    def to_pandas(self, predicate=None, projection=None):
        return self.to_arrow(predicate=predicate, projection=projection).to_pandas()

    def subscribe(self, consumer_id: str | None = None, from_snapshot: int | None = None):
        """Live changelog subscription (service/subscription.py): an iterator
        of decoded ChangelogBatch fed by the table's shared decode-once
        tailer. `consumer_id` makes progress durable (resume + expiry
        pinning); `from_snapshot` replays history through the data-file
        cache before going live."""
        from ..service.subscription import SubscriptionHub

        return SubscriptionHub.for_table(self).subscribe(
            consumer_id=consumer_id, from_snapshot=from_snapshot
        )

    def remove_orphan_files(self, older_than_millis: int | None = None, dry_run: bool = False) -> list[str]:
        """Crash recovery: delete files unreachable from every live snapshot/
        changelog/tag/branch plus torn .tmp.* residue (resilience/orphan.py);
        default threshold `orphan.clean.older-than`."""
        from .maintenance import remove_orphan_files

        return remove_orphan_files(self, older_than_millis=older_than_millis, dry_run=dry_run)

    def expire_snapshots(self) -> int:
        from .tags import TagManager

        tag_ids = lambda: TagManager(self.file_io, self.path).tagged_snapshot_ids()  # noqa: E731
        from .consumer import ConsumerManager

        from ..options import CoreOptions

        # consumer IO routes through the retrying wrapper: a transient
        # blip during expiry must retry (or abort expiry), never read as
        # "no consumers" and unpin a live subscriber's snapshots
        cm = ConsumerManager(self.store.file_io, self.path)
        ttl = self.options.options.get(CoreOptions.CONSUMER_EXPIRATION_TIME_MS)
        if ttl is not None:
            cm.expire_stale(ttl)  # abandoned readers stop pinning snapshots

        def protected():
            ids = set(tag_ids())
            nxt = cm.min_next_snapshot()
            if nxt is not None:
                latest = self.store.snapshot_manager.latest_snapshot_id() or 0
                ids |= set(range(nxt, latest + 1))
            return ids

        expire = self.store.new_expire(protected)
        mode = str(self.options.options.get(CoreOptions.SNAPSHOT_EXPIRE_EXECUTION_MODE)).lower()
        if mode == "async":
            # reference ExpireExecutionMode.ASYNC: expiry must never add
            # latency to the commit path — run it on a background thread.
            # The future is kept on the table (tests/join points).
            import concurrent.futures as cf

            if not hasattr(self, "_expire_executor"):
                self._expire_executor = cf.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="snapshot-expire"
                )
            self._expire_future = self._expire_executor.submit(expire.expire)

            def _surface(fut):  # async failures must not vanish silently
                exc = fut.exception()
                if exc is not None:
                    import sys

                    sys.stderr.write(f"[paimon-tpu] async snapshot expire failed: {exc!r}\n")

            self._expire_future.add_done_callback(_surface)
            return 0
        return expire.expire()


def load_table(
    path: str,
    commit_user: str = "anonymous",
    dynamic_options: dict[str, str] | None = None,
    row_type=None,
) -> FileStoreTable:
    """Open an existing table from its path. The 'branch' option (in the
    table's options or dynamic_options) pins the view to that branch.

    auto-create=true (reference CoreOptions.AUTO_CREATE): when no table
    exists at `path` and the caller supplies `row_type` (the engine-side
    schema), the underlying storage is created on first load — primary/
    partition keys come from the 'primary-key'/'partition' options."""
    file_io = get_file_io(path)
    schema = SchemaManager(file_io, path).latest()
    if schema is None:
        opts = dict(dynamic_options or {})
        if str(opts.get("auto-create", "")).lower() == "true" and row_type is not None:
            opts.pop("auto-create")
            pk = [c.strip() for c in opts.pop("primary-key", "").split(",") if c.strip()]
            parts = [c.strip() for c in opts.pop("partition", "").split(",") if c.strip()]
            # session-scoped options must NOT bake into schema-0 (the normal
            # path applies them via copy() without persisting) — only table-
            # shaping options persist
            session_prefixes = ("scan.", "consumer", "incremental-between", "streaming-read")
            persisted = {k: v for k, v in opts.items() if not k.startswith(session_prefixes)}
            session = {k: v for k, v in opts.items() if k.startswith(session_prefixes)}
            schema = SchemaManager(file_io, path).create_table(row_type, parts, pk, persisted)
            table = FileStoreTable(file_io, path, schema, commit_user)
            return table.copy(session) if session else table
        raise FileNotFoundError(f"no table at {path}")
    table = FileStoreTable(file_io, path, schema, commit_user)
    # branch first: branch_table rebuilds from the branch schema, so other
    # dynamic options must land on the BRANCH view, not the main table
    dynamic_options = dict(dynamic_options or {})
    branch = dynamic_options.pop("branch", None) or table.options.options.get(CoreOptions.BRANCH)
    if branch and branch != "main":
        from .branch import branch_table

        table = branch_table(table, branch)
    return table.copy(dynamic_options) if dynamic_options else table
