"""Table-level statistics (ANALYZE).

Parity: /root/reference/paimon-core/.../stats/ — Statistics/StatsFileHandler:
ANALYZE writes a stats file (row count + per-column stats) registered on the
next snapshot; engines use it for cost-based planning.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from ..utils import dumps, loads, new_file_name

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["Statistics", "analyze_table", "read_statistics"]


@dataclass
class Statistics:
    snapshot_id: int
    schema_id: int
    merged_record_count: int
    merged_record_size: int
    col_stats: dict[str, dict] = field(default_factory=dict)

    def to_json(self) -> str:
        return dumps(
            {
                "snapshotId": self.snapshot_id,
                "schemaId": self.schema_id,
                "mergedRecordCount": self.merged_record_count,
                "mergedRecordSize": self.merged_record_size,
                "colStats": self.col_stats,
            }
        )

    @staticmethod
    def from_json(s: bytes | str) -> "Statistics":
        d = loads(s)
        return Statistics(d["snapshotId"], d["schemaId"], d["mergedRecordCount"], d["mergedRecordSize"], d["colStats"])


def analyze_table(table: "FileStoreTable", with_columns: bool = True) -> Statistics:
    """Scan the merged table, compute stats, persist them, and record the
    stats file on a new ANALYZE snapshot."""
    rb = table.new_read_builder()
    splits = rb.new_scan().plan()
    out = rb.new_read().read_all(splits)
    sm = table.store.snapshot_manager
    latest = sm.latest_snapshot()
    col_stats: dict[str, dict] = {}
    if with_columns and out.num_rows:
        from ..format import collect_stats

        for name, st in collect_stats(out).items():
            col_stats[name] = {
                "distinctCount": None,
                "min": st.min if not isinstance(st.min, bytes) else None,
                "max": st.max if not isinstance(st.max, bytes) else None,
                "nullCount": st.null_count,
            }
    stats = Statistics(
        snapshot_id=latest.id if latest else 0,
        schema_id=table.schema.id,
        merged_record_count=out.num_rows,
        merged_record_size=sum(f.file_size for s in splits for f in s.files),
        col_stats=col_stats,
    )
    name = new_file_name("stats")
    table.file_io.write_bytes(f"{table.path}/statistics/{name}", stats.to_json().encode())
    # register on a fresh ANALYZE snapshot
    from ..core.manifest import ManifestCommittable
    from ..core.snapshot import CommitKind

    commit = table.store.new_commit()
    commit._try_commit(
        CommitKind.ANALYZE, [], ManifestCommittable((1 << 63) - 5), check_conflicts=False, statistics=name
    )
    return stats


def read_statistics(table: "FileStoreTable") -> Statistics | None:
    sm = table.store.snapshot_manager
    for snap in list(sm.snapshots())[::-1]:
        if snap.statistics:
            return Statistics.from_json(table.file_io.read_bytes(f"{table.path}/statistics/{snap.statistics}"))
    return None
