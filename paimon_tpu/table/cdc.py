"""Schema-evolving CDC ingestion.

Parity: the core semantic of paimon-flink-cdc (reference paimon-flink/
paimon-flink-cdc/.../sink/cdc/ — RichCdcMultiplexRecord pipelines apply
schema changes mid-stream: new columns are added, types are widened via
SchemaMergingUtils, then records write under the updated schema). Sources
(mysql/kafka/...) are engine-side; this is the engine-neutral sink half:
feed it dict-records with row kinds, it evolves the table as needed.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Mapping, Sequence

import numpy as np

from ..core.schema import SchemaChange, SchemaManager
from ..data.batch import ColumnBatch
from ..data.casting import can_cast
from ..types import BIGINT, BOOLEAN, DOUBLE, STRING, DataType, RowKind, TypeRoot

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["CdcRecord", "CdcTableWrite", "infer_type"]


class CdcRecord(dict):
    """A change record: field map + row kind (+I default)."""

    def __init__(self, fields: Mapping[str, Any], kind: str = "+I"):
        super().__init__(fields)
        self.kind = kind


def infer_type(value: Any) -> DataType:
    if isinstance(value, bool):
        return BOOLEAN()
    if isinstance(value, int):
        return BIGINT()
    if isinstance(value, float):
        return DOUBLE()
    return STRING()


class CdcTableWrite:
    """Buffers CDC records, evolving the table schema when records carry new
    columns or wider types, then writes through the normal Table API."""

    def __init__(self, table: "FileStoreTable"):
        self.table = table
        self._records: list[CdcRecord] = []

    def write(self, record: CdcRecord | Mapping[str, Any], kind: str = "+I") -> None:
        if not isinstance(record, CdcRecord):
            record = CdcRecord(record, kind)
        self._records.append(record)

    def flush(self, commit_identifier: int) -> int:
        """Evolve schema if needed, write all buffered records, commit."""
        if not self._records:
            return 0
        self._evolve_schema()
        table = self.table
        schema = table.row_type
        data: dict[str, list] = {f.name: [] for f in schema.fields}
        kinds = []
        for r in self._records:
            for f in schema.fields:
                data[f.name].append(self._coerce(r.get(f.name), f.type))
            kinds.append(int(RowKind.from_short_string(r.kind)))
        n = len(self._records)
        self._records = []
        wb = table.new_stream_write_builder()
        w = wb.new_write()
        w.write(ColumnBatch.from_pydict(schema, data), np.array(kinds, dtype=np.uint8))
        committed = wb.new_commit().commit_messages(commit_identifier, w.prepare_commit())
        # an already-seen identifier is filtered as a replay: report 0 applied
        return n if committed else 0

    @staticmethod
    def _coerce(value: Any, dtype: DataType):
        if value is None:
            return None
        root = dtype.root
        if root in (TypeRoot.VARCHAR, TypeRoot.CHAR):
            return str(value)
        if root in (TypeRoot.TINYINT, TypeRoot.SMALLINT, TypeRoot.INT, TypeRoot.BIGINT):
            return int(value)
        if root in (TypeRoot.FLOAT, TypeRoot.DOUBLE):
            return float(value)
        if root == TypeRoot.BOOLEAN:
            return bool(value)
        return value

    def _evolve_schema(self) -> None:
        table = self.table
        schema = table.row_type
        changes = []
        seen_new: dict[str, DataType] = {}
        for r in self._records:
            for name, value in r.items():
                if value is None:
                    continue
                inferred = infer_type(value)
                if name not in schema:
                    prev = seen_new.get(name)
                    if prev is None or (prev != inferred and can_cast(prev, inferred)):
                        seen_new[name] = inferred
                else:
                    current = schema.field(name).type
                    if current.root != inferred.root and can_cast(current, inferred):
                        changes.append(SchemaChange.update_column_type(name, inferred))
        for name, t in seen_new.items():
            changes.append(SchemaChange.add_column(name, t))
        if changes:
            # dedupe type updates, last wins
            dedup: dict[tuple, dict] = {}
            for ch in changes:
                dedup[(ch["op"], ch["name"])] = ch
            sm = SchemaManager(table.file_io, table.path)
            new_schema = sm.commit_changes(*dedup.values())
            from . import FileStoreTable

            self.table = FileStoreTable(table.file_io, table.path, new_schema, table.store.commit_user)
