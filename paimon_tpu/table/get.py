"""Batched device-side point gets: the serving fast path of LocalTableQuery.

"Fast Updates on Read-Optimized Databases Using Multi-Core CPUs" (PAPERS.md)
frames a store like this one as delta-plus-main: a read-optimized main
(compacted LSM levels) merged with an in-memory delta (the writer's
memtable) at query time. `batch_get` is that merge for primary-key point
lookups, batched:

  1. N probe keys normalize into ONE ColumnBatch; their combined uint64
     hashes (table/bucket.py — the same splitmix64 the bucket router and the
     bloom key indexes use) and a sorted key list are computed once.
  2. Keys route to buckets vectorized (fixed-bucket tables hash; dynamic
     tables probe every bucket of the partition with the full batch — the
     probe indexes' present masks make absent keys nearly free).
  3. Per bucket, BucketGetIndex (lookup/index.py) prunes files with zero
     data IO (manifest key range + PTIX bloom key index), then runs one
     vectorized JoinIndex probe per surviving file over the PR-1-cached
     decoded batch — code-domain columns are probed on dictionary codes,
     zero string materialization.
  4. The read-your-writes tier: when a TableWrite is attached, each target
     bucket's live memtable (plus its flushed-but-uncommitted level-0
     files) joins the candidate set, so gets serve committed-plus-buffered
     state ("the delta never outruns the reader").
  5. Resolution: one lexsort over (probe key, sequence, tier) picks the
     max-sequence winner per key — exactly the scalar LookupLevels merge
     rule — and DELETE/UPDATE_BEFORE winners mask to absent. Deletion
     vectors were already applied when the per-file indexes were built.

The scalar `LocalTableQuery.lookup` walk is the independent oracle: every
test and every timed benchmark pass asserts `batch_get` == the scalar loop.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Mapping

import numpy as np

from ..core.kv import KVBatch
from ..lookup.index import BucketGetIndex, FileProbeIndex, GetResult
from ..metrics import get_metrics
from ..types import RowKind

if TYPE_CHECKING:
    from .query import LocalTableQuery

__all__ = ["batch_get", "GetResult"]

# resolution tiers: higher wins a sequence tie (a raw memtable row and the
# level-0 file its in-flight flush is writing can carry the same sequence)
_TIER_MAIN, _TIER_DELTA_FILE, _TIER_MEMTABLE = 0, 1, 2


def probe_batch(query: "LocalTableQuery", keys):
    """Normalize probe input to a ColumnBatch over the trimmed-key schema:
    a ColumnBatch carrying the key columns, a {column: sequence} mapping,
    or a sequence of key tuples/scalars."""
    from ..data.batch import ColumnBatch

    key_names = query.store.key_names
    schema = query.store.value_schema.project(key_names)
    if hasattr(keys, "schema") and hasattr(keys, "columns"):
        return keys
    if isinstance(keys, Mapping):
        return ColumnBatch.from_pydict(schema, {k: keys[k] for k in key_names})
    rows = [tuple(k) if isinstance(k, (tuple, list)) else (k,) for k in keys]
    return ColumnBatch.from_pylist(schema, rows)


def _bucket_groups(query: "LocalTableQuery", probe, partition: tuple):
    """[(bucket, probe_row_indices | None)] — None means the whole batch
    (dynamic-bucket tables probe every bucket of the partition)."""
    n = getattr(query, "_probe_buckets", 0) or query.store.options.bucket
    if n > 0:
        from .bucket import bucket_ids

        # snapshot-consistent routing: the query's _probe_buckets tracks the
        # bucket count of the snapshot being served, which diverges from the
        # construction-time option during a live rescale
        ids = bucket_ids(probe, query.table.schema.bucket_keys, n)
        return [(int(b), np.flatnonzero(ids == b)) for b in np.unique(ids)]
    buckets = sorted({pb[1] for pb in query._get_indexes if pb[0] == partition})
    return [(b, None) for b in buckets]


class _Candidates:
    """Accumulates (probe_idx, seq, kind, source row) matches across files,
    buckets and tiers, then resolves max-sequence winners per probe key."""

    def __init__(self):
        self.sources: list[KVBatch] = []
        self.probe_idx: list[np.ndarray] = []
        self.seqs: list[np.ndarray] = []
        self.kinds: list[np.ndarray] = []
        self.src_ids: list[np.ndarray] = []
        self.rows: list[np.ndarray] = []
        self.tiers: list[np.ndarray] = []

    def add(self, kv: KVBatch, probe_idx: np.ndarray, rows: np.ndarray, tier: int) -> None:
        if len(probe_idx) == 0:
            return
        sid = len(self.sources)
        self.sources.append(kv)
        self.probe_idx.append(probe_idx)
        self.seqs.append(kv.seq[rows])
        self.kinds.append(kv.kind[rows])
        self.src_ids.append(np.full(len(rows), sid, dtype=np.int64))
        self.rows.append(rows)
        self.tiers.append(np.full(len(rows), tier, dtype=np.int8))

    def resolve(self, n: int, value_schema) -> GetResult:
        from ..data.batch import ColumnBatch, concat_batches

        g = get_metrics()
        if not self.sources:
            return GetResult(
                n, np.zeros(n, dtype=np.bool_), ColumnBatch.empty(value_schema),
                np.empty(0, dtype=np.int64),
            )
        pi = np.concatenate(self.probe_idx)
        seq = np.concatenate(self.seqs)
        kind = np.concatenate(self.kinds)
        src = np.concatenate(self.src_ids)
        row = np.concatenate(self.rows)
        tier = np.concatenate(self.tiers)
        # one lexsort resolves the whole batch: per probe key ascending by
        # (seq, tier) — the LAST entry of each group is the winning version
        order = np.lexsort((tier, seq, pi))
        ps = pi[order]
        last = np.ones(len(ps), dtype=np.bool_)
        last[:-1] = ps[1:] != ps[:-1]
        win = order[last]
        win_pi = pi[win]
        live = ~np.isin(kind[win], (int(RowKind.DELETE), int(RowKind.UPDATE_BEFORE)))
        g.counter("memtable_hits").inc(int((tier[win] > _TIER_MAIN)[live].sum()))
        win = win[live]
        win_pi = win_pi[live]
        found = np.zeros(n, dtype=np.bool_)
        found[win_pi] = True
        # `win` is already in ascending probe order (the lexsort's primary
        # key): gather winners source-by-source, then permute back
        w_src, w_row = src[win], row[win]
        by_src = np.argsort(w_src, kind="stable")
        parts = []
        for s in np.unique(w_src):
            sel = by_src[w_src[by_src] == s]
            parts.append(self.sources[s].data.take(w_row[sel]))
        combined = concat_batches(parts) if parts else ColumnBatch.empty(value_schema)
        if parts:
            inv = np.empty(len(by_src), dtype=np.int64)
            inv[by_src] = np.arange(len(by_src))
            combined = combined.take(inv)
        return GetResult(n, found, combined, win_pi.astype(np.int64))


def _delta_sources(query: "LocalTableQuery", partition: tuple, bucket: int):
    """[(KVBatch | BucketGetIndex tier pieces)] for one bucket's live delta:
    the attached TableWrite's buffered memtable batches (+ any in-flight
    flush) and its flushed-but-uncommitted level-0 files."""
    tw = query._write
    if tw is None:
        return None, ()
    snap = tw.delta_snapshot().get((partition, bucket))
    if snap is None:
        return None, ()
    batches, new_files = snap
    mem = None
    if batches:
        kv = KVBatch.concat(batches) if len(batches) > 1 else batches[0]
        if kv.num_rows:
            mem = FileProbeIndex(kv, query.store.key_names)
    files = ()
    if new_files:
        names = tuple(f.file_name for f in new_files)
        cached = query._delta_indexes.get((partition, bucket))
        if cached is None or cached[0] != names:
            idx = BucketGetIndex(
                new_files,
                query.store.reader_factory(partition, bucket),
                query.store.key_names,
                bloom_prune=query._bloom_prune,
            )
            query._delta_indexes[(partition, bucket)] = cached = (names, idx)
        files = (cached[1],)
    return mem, files


def batch_get(query: "LocalTableQuery", keys, partition: tuple = ()) -> GetResult:
    """Batched primary-key get against `query`'s current view (plus the
    attached writer's delta). Returns a GetResult aligned with `keys`."""
    from .bucket import key_hashes

    g = get_metrics()
    t0 = time.perf_counter()
    probe = probe_batch(query, keys)
    n = probe.num_rows
    cand = _Candidates()
    if n:
        hashes = key_hashes(probe, query.store.key_names)
        sorted_keys = sorted(probe.to_pylist())
        for bucket, rows in _bucket_groups(query, probe, partition):
            if rows is None or len(rows) == n:
                sub, sub_hashes, sub_keys, back = probe, hashes, sorted_keys, None
            else:
                sub = probe.take(rows)
                sub_hashes = hashes[rows]
                sub_keys = sorted(sub.to_pylist())
                back = rows
            idx = query._get_indexes.get((partition, bucket))
            if idx is not None:
                for fi, pi, rr in idx.probe(sub, sub_hashes, sub_keys):
                    cand.add(fi.kv, pi if back is None else back[pi], rr, _TIER_MAIN)
            mem, delta_files = _delta_sources(query, partition, bucket)
            for didx in delta_files:
                for fi, pi, rr in didx.probe(sub, sub_hashes, sub_keys):
                    cand.add(fi.kv, pi if back is None else back[pi], rr, _TIER_DELTA_FILE)
            if mem is not None:
                g.counter("keys_probed").inc(sub.num_rows)
                pi, rr = mem.probe(sub)
                cand.add(mem.kv, pi if back is None else back[pi], rr, _TIER_MEMTABLE)
    res = cand.resolve(n, query.store.value_schema)
    g.counter("gets").inc(n)
    g.histogram("probe_ms").update((time.perf_counter() - t0) * 1000)
    return res
