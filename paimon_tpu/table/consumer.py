"""Consumers: durable reader progress that blocks snapshot expiry.

Parity: /root/reference/paimon-core/.../consumer/ConsumerManager.java — a
consumer file holds the reader's next snapshot id; expiry must retain every
snapshot >= the minimum consumer position.
"""

from __future__ import annotations

from ..fs import FileIO
from ..utils import dumps, loads

__all__ = ["ConsumerManager"]


class ConsumerManager:
    def __init__(self, file_io: FileIO, table_path: str):
        self.file_io = file_io
        self.consumer_dir = f"{table_path}/consumer"

    def _path(self, consumer_id: str) -> str:
        return f"{self.consumer_dir}/consumer-{consumer_id}"

    def consumer(self, consumer_id: str) -> int | None:
        """The consumer's next-snapshot position, or None when no such
        consumer EXISTS. Only a missing file (ENOENT) maps to None: a
        transient IO error must propagate (into the resilience retry policy
        when the FileIO is the store's retrying wrapper) — treating it as
        "no consumer" would let min_next_snapshot() unpin a live subscriber
        and expiry delete snapshots it still needs."""
        try:
            raw = self.file_io.read_bytes(self._path(consumer_id))
        except FileNotFoundError:
            return None
        return loads(raw)["nextSnapshot"]

    def record(self, consumer_id: str, next_snapshot: int) -> None:
        self.file_io.try_overwrite(self._path(consumer_id), dumps({"nextSnapshot": next_snapshot}).encode())

    def delete(self, consumer_id: str) -> None:
        self.file_io.delete(self._path(consumer_id))

    def reset(self, consumer_id: str, next_snapshot: int) -> None:
        self.record(consumer_id, next_snapshot)

    def list_consumers(self) -> dict[str, int]:
        out = {}
        for st in self.file_io.list_files(self.consumer_dir):
            base = st.path.rsplit("/", 1)[-1]
            if base.startswith("consumer-"):
                cid = base[len("consumer-") :]
                nxt = self.consumer(cid)
                if nxt is not None:
                    out[cid] = nxt
        return out

    def min_next_snapshot(self) -> int | None:
        vals = list(self.list_consumers().values())
        return min(vals) if vals else None

    def expire_stale(self, expiration_millis: int) -> list[str]:
        """Drop consumers not updated within the TTL so abandoned readers stop
        pinning snapshots (reference consumer.expiration-time handling)."""
        from ..utils import now_millis

        cutoff = now_millis() - expiration_millis
        removed = []
        for st in self.file_io.list_files(self.consumer_dir):
            base = st.path.rsplit("/", 1)[-1]
            if base.startswith("consumer-") and st.mtime_millis < cutoff:
                removed.append(base[len("consumer-") :])
                self.file_io.delete(st.path)
        return removed
