"""DELETE FROM table WHERE <predicate>.

Parity: the reference implements row-level delete in the Spark connector
(paimon-spark/.../commands/DeleteFromPaimonTableCommand.scala — deletion-
vector mode or copy-on-write rewrite) and for PK tables as -D records. The
engine-neutral equivalent here picks the same three strategies:

  1. deletion-vectors.enabled  -> mark row positions in DV index files
                                  (merge-free, no data rewrite);
  2. primary-key table          -> write -D rows for the matching keys;
  3. append table (no DVs)      -> copy-on-write: rewrite affected files
                                  without the matching rows.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import numpy as np

from ..core.deletionvectors import DeletionVectorsIndexFile, DeletionVectorsMaintainer
from ..core.manifest import CommitMessage, ManifestCommittable
from ..data.predicate import Predicate
from ..options import CoreOptions
from ..types import RowKind

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["delete_where"]


def delete_where(table: "FileStoreTable", predicate: Predicate, commit_identifier: int | None = None) -> int:
    """Returns the number of rows deleted."""
    store = table.store
    dv_enabled = store.options.options.get(CoreOptions.DELETION_VECTORS_ENABLED)
    if dv_enabled:
        return _delete_with_dvs(table, predicate, commit_identifier)
    if table.is_primary_key_table:
        return _delete_with_retract(table, predicate)
    return _delete_with_rewrite(table, predicate, commit_identifier)


def _key_match_mask(batch, key_names, matching_batch) -> np.ndarray:
    """Exact membership of batch's key tuples in matching_batch's key set."""
    if len(key_names) == 1:
        k = key_names[0]
        return np.isin(batch.column(k).values, matching_batch.column(k).values)
    keys = set(zip(*(matching_batch.column(k).values.tolist() for k in key_names)))
    rows = zip(*(batch.column(k).values.tolist() for k in key_names))
    return np.fromiter((r in keys for r in rows), dtype=np.bool_, count=batch.num_rows)


def _delete_with_dvs(table: "FileStoreTable", predicate: Predicate, commit_identifier: int | None) -> int:
    store = table.store
    idx = DeletionVectorsIndexFile(
        table.file_io,
        table.path,
        target_size=int(store.options.options.get(CoreOptions.DELETION_VECTOR_INDEX_FILE_TARGET_SIZE)),
    )
    plan = store.new_scan().plan()
    # PK tables: deleting only the latest version's position would resurrect
    # an older version of the key on merge — so resolve the predicate against
    # the MERGED view first, then mark every stored version of matching keys.
    matching_keys = None
    deleted = 0
    if table.is_primary_key_table:
        rb = table.new_read_builder().with_filter(predicate)
        matching_keys = rb.new_read().read_all(rb.new_scan().plan())
        deleted = matching_keys.num_rows
        if deleted == 0:
            return 0
    messages: list[CommitMessage] = []
    for partition, buckets in plan.grouped().items():
        for bucket, files in buckets.items():
            dv_index = plan.dv_index_for(partition, bucket)
            restored = idx.read_all(dv_index) if dv_index else {}
            maintainer = DeletionVectorsMaintainer(idx, restored)
            rf = store.reader_factory(partition, bucket)
            changed = False
            for f in files:
                kv = rf.read(f)  # positions = file row order (no pruning)
                if matching_keys is not None:
                    mask = _key_match_mask(kv.data, store.key_names, matching_keys)
                else:
                    mask = predicate.eval(kv.data)
                existing = restored.get(f.file_name)
                if existing is not None:
                    mask = mask & ~existing.deleted_mask(kv.num_rows)
                positions = np.flatnonzero(mask)
                if len(positions):
                    maintainer.notify_deletion(f.file_name, positions.astype(np.uint32))
                    if matching_keys is None:
                        deleted += len(positions)
                    changed = True
            if changed:
                entry = maintainer.prepare_commit(partition, bucket)
                if entry:
                    messages.append(
                        CommitMessage(partition, bucket, max(store.options.bucket, 1), new_index_files=[entry])
                    )
    if messages:
        ident = commit_identifier if commit_identifier is not None else (1 << 63) - 2
        store.new_commit().commit(ManifestCommittable(ident, messages=messages))
    return deleted


def _delete_with_retract(table: "FileStoreTable", predicate: Predicate) -> int:
    """PK table: read the matching merged rows, write them back as -D."""
    from ..options import ChangelogProducer

    rb = table.new_read_builder().with_filter(predicate)
    splits = rb.new_scan().plan()
    matching = rb.new_read().read_all(splits)
    if matching.num_rows == 0:
        return 0
    opts = table.options.options
    if (
        opts.get(CoreOptions.DELETE_FORCE_PRODUCE_CHANGELOG)
        and table.options.changelog_producer == ChangelogProducer.NONE
    ):
        # downstream consumers see the retracts even on a changelog-less
        # table (reference delete.force-produce-changelog)
        table = table.copy({"changelog-producer": "input"})
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    kinds = np.full(matching.num_rows, int(RowKind.DELETE), dtype=np.uint8)
    w.write(matching, kinds)
    wb.new_commit().commit(w.prepare_commit())
    return matching.num_rows


def _delete_with_rewrite(table: "FileStoreTable", predicate: Predicate, commit_identifier: int | None) -> int:
    """Append table copy-on-write: rewrite each affected file without the
    matching rows."""
    return copy_on_write_rewrite(table, predicate, transform=None, commit_identifier=commit_identifier)


def copy_on_write_rewrite(
    table: "FileStoreTable",
    predicate: Predicate,
    transform,
    commit_identifier: int | None = None,
) -> int:
    """Shared copy-on-write scaffolding for row-level DELETE and UPDATE on
    append tables: rewrite every file containing predicate matches, with the
    matching rows dropped (transform=None) or replaced by transform(kv_match)
    (reference DeleteFromPaimonTableCommand / UpdatePaimonTableCommand
    copy-on-write strategy). Pre-existing deletion vectors are applied before
    the rewrite so dead rows never resurrect; the commit purges the DVs of
    rewritten files."""
    store = table.store
    plan = store.new_scan().plan()
    dv_by_pb: dict[tuple, dict] = {}
    if store.options.options.get(CoreOptions.DELETION_VECTORS_ENABLED):
        idx = DeletionVectorsIndexFile(table.file_io, table.path)
        for (partition, bucket), name in plan.dv_indexes().items():
            dv_by_pb[(partition, bucket)] = idx.read_all(name)
    messages: list[CommitMessage] = []
    affected = 0
    for partition, buckets in plan.grouped().items():
        for bucket, files in buckets.items():
            rf = store.reader_factory(partition, bucket)
            wf = store.writer_factory(partition, bucket)
            dvs = dv_by_pb.get((partition, bucket), {})
            before, after = [], []
            for f in files:
                kv = rf.read(f)
                dv = dvs.get(f.file_name)
                if dv is not None:
                    alive = ~dv.deleted_mask(kv.num_rows)
                    if not alive.all():
                        kv = kv.filter(alive)
                mask = predicate.eval(kv.data)
                hits = int(mask.sum())
                if hits == 0:
                    continue
                affected += hits
                before.append(f)
                kept = kv.filter(~mask)
                out = kept if transform is None else _concat_kv(kept, transform(kv.filter(mask)))
                if out.num_rows:
                    after.extend(wf.write(out, level=f.level, file_source="compact"))
            if before:
                messages.append(
                    CommitMessage(
                        partition,
                        bucket,
                        max(store.options.bucket, 1),
                        compact_before=before,
                        compact_after=after,
                    )
                )
    if messages:
        ident = commit_identifier if commit_identifier is not None else (1 << 63) - 2
        store.new_commit().commit(ManifestCommittable(ident, messages=messages))
    return affected


def _concat_kv(kept, changed):
    from ..core.kv import KVBatch

    if kept.num_rows == 0:
        return changed
    if changed.num_rows == 0:
        return kept
    return KVBatch.concat([kept, changed])
