"""Streaming split enumerator: distribute follow-up splits to N readers.

Parity: /root/reference/paimon-flink/paimon-flink-common/.../source/
ContinuousFileSplitEnumerator.java — the coordinator polls
StreamTableScan.plan() for new snapshots and assigns the resulting splits to
parallel readers; one bucket's splits always route to the SAME reader (so a
bucket's deltas apply in order), pending work and scan progress checkpoint
together and restore after failover. Engine-neutral: any runtime with N
workers drains next_splits(reader_id) and persists checkpoint()/restore().
"""

from __future__ import annotations

import zlib
from typing import TYPE_CHECKING

from .read import DataSplit

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["SplitEnumerator", "AlignedSplitEnumerator"]


class SplitEnumerator:
    def __init__(self, table: "FileStoreTable", num_readers: int, predicate=None):
        assert num_readers >= 1
        self.table = table
        self.num_readers = num_readers
        rb = table.new_read_builder()
        if predicate is not None:
            rb = rb.with_filter(predicate)
        self.scan = rb.new_stream_scan()
        self._pending: dict[int, list[DataSplit]] = {r: [] for r in range(num_readers)}

    def _owner(self, split: DataSplit) -> int:
        # bucket -> reader via a DETERMINISTIC hash (builtin hash() is
        # PYTHONHASHSEED-randomized across processes — failover would re-route
        # a bucket mid-history). Stable routing keeps the invariant that ONE
        # reader sees a bucket's whole delta history in order (the
        # reference's channel computation).
        key = repr((split.partition, split.bucket)).encode()
        return zlib.crc32(key) % self.num_readers

    def discover(self) -> int:
        """Poll the scan once; enqueue any new splits. Returns #discovered."""
        splits = self.scan.plan()
        if not splits:
            return 0
        for s in splits:
            self._pending[self._owner(s)].append(s)
        return len(splits)

    def next_splits(self, reader_id: int, max_splits: int | None = None) -> list[DataSplit]:
        """Drain up to max_splits pending splits for one reader (default:
        the table's scan.max-splits-per-task — one assignment batch stays
        bounded so failover never re-queues an unbounded backlog)."""
        if max_splits is None:
            from ..options import CoreOptions

            max_splits = self.table.options.options.get(CoreOptions.SCAN_MAX_SPLITS_PER_TASK)
        q = self._pending[reader_id]
        out, self._pending[reader_id] = q[:max_splits], q[max_splits:]
        return out

    @property
    def pending_count(self) -> int:
        return sum(len(q) for q in self._pending.values())

    # ---- checkpoint / failover -----------------------------------------
    def checkpoint(self) -> dict:
        """Serializable coordinator state: scan progress + undrained splits
        (reference: PendingSplitsCheckpoint)."""
        return {
            "nextSnapshot": self.scan.checkpoint(),
            "pending": {str(r): [s.to_dict() for s in q] for r, q in self._pending.items()},
        }

    def restore(self, state: dict) -> None:
        self.scan.restore(state.get("nextSnapshot"))
        self._pending = {r: [] for r in range(self.num_readers)}
        for r, splits in state.get("pending", {}).items():
            restored = [DataSplit.from_dict(d) for d in splits]
            for s in restored:
                # re-route: the reader count may differ after failover
                self._pending[self._owner(s)].append(s)

    def notify_checkpoint_complete(self) -> None:
        self.scan.notify_checkpoint_complete()


class AlignedSplitEnumerator(SplitEnumerator):
    """Checkpoint-aligned coordinator (reference flink/source/align/
    AlignedContinuousFileSplitEnumerator): discovery pulls EXACTLY ONE
    snapshot's splits at a time, and a checkpoint may only be taken once
    every split of the current snapshot has been drained by its reader —
    so each checkpoint corresponds to a consistent snapshot boundary.

    Protocol:
        n = enum.discover()            # <= one snapshot's splits enqueued
        ... readers drain via next_splits() ...
        state = enum.aligned_checkpoint(timeout)  # blocks for the barrier
    """

    def __init__(self, table, num_readers: int, predicate=None):
        super().__init__(table, num_readers, predicate)
        self._current_snapshot: int | None = None

    def discover(self) -> int:
        """One snapshot per call: a second discovery before the previous
        snapshot is drained is refused (alignment invariant)."""
        if self.pending_count:
            return 0
        splits = self.scan.plan()
        if not splits:
            self._current_snapshot = None
            return 0
        self._current_snapshot = splits[0].snapshot_id
        for s in splits:
            self._pending[self._owner(s)].append(s)
        return len(splits)

    def aligned_checkpoint(self, timeout_seconds: float = 10.0, poll_seconds: float = 0.02) -> dict:
        """Barrier: wait until readers drained the current snapshot, then
        checkpoint. TimeoutError when readers cannot drain in time
        (reference alignment timeout => checkpoint failure)."""
        import time as _time

        deadline = _time.monotonic() + timeout_seconds
        while self.pending_count:
            if _time.monotonic() >= deadline:
                raise TimeoutError(
                    f"alignment timeout: {self.pending_count} splits of snapshot "
                    f"{self._current_snapshot} still undrained"
                )
            _time.sleep(poll_seconds)
        state = self.checkpoint()
        state["alignedSnapshot"] = self._current_snapshot
        return state
