"""Cross-partition upsert: primary key does NOT contain the partition key.

Parity: /root/reference/paimon-core/.../crosspartition/ —
GlobalIndexAssigner.java:76 (a global key -> (partition, bucket) index,
RocksDB-backed in the reference; bootstrap via IndexBootstrap reads the key
columns of existing files) wired by GlobalDynamicBucketSink. Semantics: when
an incoming key already lives in a DIFFERENT partition, the old row is
retracted (-D to the old location) and the new row wins.

Here the index is a host hash map bootstrapped by a key-column-only scan;
assignment of a batch is vectorized around dictionary probes.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import numpy as np

from ..types import RowKind

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["GlobalIndexAssigner", "CrossPartitionUpsertWrite"]


class GlobalIndexAssigner:
    def __init__(
        self,
        table: "FileStoreTable",
        target_bucket_rows: int,
        bootstrap_parallelism: int = 10,
        index_ttl_millis: int | None = None,
    ):
        self.table = table
        self.key_names = table.store.key_names
        self.target = target_bucket_rows
        self.bootstrap_parallelism = max(1, bootstrap_parallelism)
        # cross-partition-upsert.index-ttl: entries silently expire (the
        # reference's rocksdb TTL) — an expired key re-allocates like a new
        # one, trading index memory for possible stale duplicates
        self.index_ttl_millis = index_ttl_millis
        self.index: dict[tuple, tuple] = {}  # key -> (partition, bucket, born_millis)
        self._bucket_counts: dict[tuple, int] = {}  # (partition, bucket) -> rows

    def _now(self) -> int:
        from ..utils import now_millis

        return now_millis()

    def _get_live(self, key: tuple):
        e = self.index.get(key)
        if e is None:
            return None
        if self.index_ttl_millis is not None and self._now() - e[2] > self.index_ttl_millis:
            del self.index[key]
            return None
        return e[:2]

    def bootstrap(self) -> None:
        """Read the key columns of every live file and resolve each key to its
        LATEST location by sequence number — applying -D/-U rows, so a moved
        or deleted key never resurrects its stale copy (reference
        IndexBootstrap projects key + partition + bucket the same way).
        Buckets read in parallel (cross-partition-upsert.bootstrap-parallelism)."""
        import concurrent.futures as cf

        store = self.table.store
        plan = store.new_scan().plan()
        jobs = [
            (partition, bucket, files)
            for partition, buckets in plan.grouped().items()
            for bucket, files in buckets.items()
        ]

        def read_bucket(job):
            """Folds this bucket's rows into a one-entry-per-key dict BEFORE
            returning: memory stays O(distinct keys), not O(row versions)."""
            partition, bucket, files = job
            rf = store.reader_factory(partition, bucket)
            local: dict[tuple, tuple] = {}  # key -> (seq, alive)
            for f in files:
                kv = rf.read(f, fields=self.key_names)
                alive = ~np.isin(kv.kind, (int(RowKind.DELETE), int(RowKind.UPDATE_BEFORE)))
                cols = [kv.data.column(k).values for k in self.key_names]
                seqs = kv.seq
                for i in range(kv.num_rows):
                    key = tuple(c[i] for c in cols)
                    prev = local.get(key)
                    if prev is None or seqs[i] > prev[0]:
                        local[key] = (int(seqs[i]), bool(alive[i]))
            return partition, bucket, sum(f.row_count for f in files), local

        latest: dict[tuple, tuple] = {}  # key -> (seq, partition, bucket, alive)
        with cf.ThreadPoolExecutor(max_workers=self.bootstrap_parallelism) as pool:
            for partition, bucket, count, local in pool.map(read_bucket, jobs):
                self._bucket_counts[(partition, bucket)] = count
                for key, (seq, alive) in local.items():
                    prev = latest.get(key)
                    if prev is None or seq > prev[0]:
                        latest[key] = (seq, partition, bucket, alive)
        born = self._now()
        for key, (_, partition, bucket, alive) in latest.items():
            if alive:
                self.index[key] = (partition, bucket, born)

    def assign(self, key: tuple, partition: tuple) -> tuple[tuple, int, tuple | None]:
        """(target_partition, bucket, old_location_or_None_if_same)."""
        existing = self._get_live(key)
        if existing is not None:
            old_partition, old_bucket = existing
            if old_partition == partition:
                return partition, old_bucket, None
            # partition changed: new row goes to the new partition; caller
            # retracts the old copy
            bucket = self._allocate(partition)
            self.index[key] = (partition, bucket, self._now())
            return partition, bucket, existing
        bucket = self._allocate(partition)
        self.index[key] = (partition, bucket, self._now())
        return partition, bucket, None

    def _allocate(self, partition: tuple) -> int:
        b = 0
        while self._bucket_counts.get((partition, b), 0) >= self.target:
            b += 1
        self._bucket_counts[(partition, b)] = self._bucket_counts.get((partition, b), 0) + 1
        return b

    def delete(self, key: tuple) -> tuple | None:
        e = self.index.pop(key, None)
        return None if e is None else e[:2]


class CrossPartitionUpsertWrite:
    """Write path for PK tables whose primary key omits the partition key
    (reference GlobalDynamicBucketSink: assigner stage -> writers)."""

    def __init__(self, table: "FileStoreTable"):
        from ..options import CoreOptions

        if not table.is_primary_key_table:
            raise ValueError("cross-partition upsert needs a primary-key table")
        store = table.store
        self.table = table
        self.partition_keys = store.partition_keys
        self.key_names = store.key_names
        target = store.options.options.get(CoreOptions.DYNAMIC_BUCKET_TARGET_ROW_NUM)
        self.assigner = GlobalIndexAssigner(
            table,
            target,
            bootstrap_parallelism=store.options.options.get(
                CoreOptions.CROSS_PARTITION_UPSERT_BOOTSTRAP_PARALLELISM
            ),
            index_ttl_millis=store.options.options.get(CoreOptions.CROSS_PARTITION_UPSERT_INDEX_TTL),
        )
        self.assigner.bootstrap()
        self._writers: dict[tuple, object] = {}

    def _writer(self, partition: tuple, bucket: int):
        key = (partition, bucket)
        if key not in self._writers:
            self._writers[key] = self.table.store.new_writer(partition, bucket, -1)
        return self._writers[key]

    def write(self, data, kinds=None) -> None:
        from ..data.batch import ColumnBatch

        if isinstance(data, dict):
            data = ColumnBatch.from_pydict(self.table.row_type, data)
        if kinds is not None and not isinstance(kinds, np.ndarray):
            kinds = np.array([int(RowKind.from_short_string(k)) for k in kinds], dtype=np.uint8)
        n = data.num_rows
        key_cols = [data.column(k).values for k in self.key_names]
        part_cols = [data.column(p).values for p in self.partition_keys]
        # the index probe is per key (hash-map), but the WRITES are batched:
        # per (partition, bucket), rows + kinds collect in input order and go
        # out as one sub-batch, so same-batch insert/delete chains keep their
        # sequence ordering
        ops: dict[tuple, list[tuple[int, int]]] = {}  # loc -> [(row, kind)]
        for i in range(n):
            key = tuple(c[i] for c in key_cols)
            partition = tuple(c.item() if hasattr((c := pc[i]), "item") else c for pc in part_cols)
            kind = int(kinds[i]) if kinds is not None else int(RowKind.INSERT)
            if kind in (int(RowKind.DELETE), int(RowKind.UPDATE_BEFORE)):
                old = self.assigner.delete(key)
                if old is not None:
                    ops.setdefault(old, []).append((i, kind))
                continue
            target_partition, bucket, old = self.assigner.assign(key, partition)
            if old is not None:
                # key moved partitions: retract the old copy
                ops.setdefault(old, []).append((i, int(RowKind.DELETE)))
            ops.setdefault((target_partition, bucket), []).append((i, kind))
        for loc, pairs in ops.items():
            pairs.sort(key=lambda p: p[0])  # input (sequence) order
            idx = np.array([r for r, _ in pairs], dtype=np.int64)
            ks = np.array([k for _, k in pairs], dtype=np.uint8)
            self._writer(*loc).write(data.take(idx), ks)

    def prepare_commit(self):
        msgs = [w.prepare_commit() for w in self._writers.values()]
        return [m for m in msgs if not m.is_empty()]
