"""Tags: named retained snapshots for time travel.

Parity: /root/reference/paimon-core/.../tag/ — Tag.java (a snapshot copy
stored under table/tag/tag-<name>), TagManager, and the expire protection
that keeps tagged snapshots' files alive.
"""

from __future__ import annotations

from ..core.snapshot import Snapshot, SnapshotManager
from ..fs import FileIO

__all__ = ["TagManager"]


class TagManager:
    def __init__(self, file_io: FileIO, table_path: str):
        self.file_io = file_io
        self.table_path = table_path
        self.tag_dir = f"{table_path}/tag"
        self.snapshot_manager = SnapshotManager(file_io, table_path)

    def tag_path(self, name: str) -> str:
        return f"{self.tag_dir}/tag-{name}"

    def create(self, name: str, snapshot_id: int | None = None) -> None:
        if self.file_io.exists(self.tag_path(name)):
            raise ValueError(f"tag {name!r} already exists")
        if snapshot_id is None:
            snapshot_id = self.snapshot_manager.latest_snapshot_id()
            if snapshot_id is None:
                raise ValueError("cannot tag an empty table")
        snap = self.snapshot_manager.snapshot(snapshot_id)
        if not self.file_io.try_atomic_write(self.tag_path(name), snap.to_json().encode()):
            raise ValueError(f"tag {name!r} already exists")

    def delete(self, name: str) -> None:
        self.file_io.delete(self.tag_path(name))

    def get(self, name: str) -> Snapshot:
        return Snapshot.from_json(self.file_io.read_bytes(self.tag_path(name)))

    def snapshot_id(self, name: str) -> int:
        return self.get(name).id

    def list_tags(self) -> dict[str, int]:
        out = {}
        for st in self.file_io.list_files(self.tag_dir):
            base = st.path.rsplit("/", 1)[-1]
            if base.startswith("tag-"):
                name = base[len("tag-") :]
                out[name] = self.get(name).id
        return out

    def tagged_snapshot_ids(self) -> set[int]:
        return set(self.list_tags().values())


class TagAutoCreation:
    """Automatic periodic tags (reference tag/TagAutoCreation.java +
    TagPeriodHandler/TagTimeExtractor): once a daily/hourly period closes
    (plus tag.creation-delay), the latest snapshot is tagged with the
    period's name; old auto tags are pruned by tag.num-retained-max and
    tag.default-time-retained.  Time source: process time, or the
    snapshot's watermark (tag.automatic-creation=watermark)."""

    def __init__(self, table):
        self.table = table
        self.tm = TagManager(table.file_io, table.path)

    def run(self) -> list[str]:
        import datetime as _dt

        from ..options import CoreOptions
        from ..utils import now_millis

        opts = self.table.options.options
        mode = opts.get(CoreOptions.TAG_AUTOMATIC_CREATION)
        if mode in (None, "none"):
            return []
        snap = self.tm.snapshot_manager.latest_snapshot()
        if snap is None:
            return []
        if mode == "watermark":
            if snap.watermark is None:
                return []
            t = snap.watermark
        else:  # process-time
            t = now_millis()
        delay = opts.get(CoreOptions.TAG_CREATION_DELAY) or 0
        period = opts.get(CoreOptions.TAG_CREATION_PERIOD)
        style = opts.get(CoreOptions.TAG_PERIOD_FORMATTER)
        ref = _dt.datetime.fromtimestamp((t - delay) / 1000)
        if period == "hourly":
            closed = ref.replace(minute=0, second=0, microsecond=0) - _dt.timedelta(hours=1)
            fmt = "%Y-%m-%d %H" if style == "with_dashes" else "%Y%m%d%H"
        else:  # daily
            closed = ref.replace(hour=0, minute=0, second=0, microsecond=0) - _dt.timedelta(days=1)
            fmt = "%Y-%m-%d" if style == "with_dashes" else "%Y%m%d"
        name = closed.strftime(fmt)
        created = []
        if name not in self.tm.list_tags():
            self.tm.create(name, snap.id)
            created.append(name)
            self._callbacks(name, snap)
        self._prune(fmt)
        return created

    def _callbacks(self, name: str, snap) -> None:
        from ..options import CoreOptions
        from .write import load_callbacks

        for fn in load_callbacks(self.table, CoreOptions.TAG_CALLBACKS):
            try:
                fn(self.table, name, snap)
            except Exception:
                pass  # callbacks must never fail tagging

    def _prune(self, fmt: str) -> None:
        """Apply retention to AUTO tags only (names matching the period
        format); user tags are never touched."""
        import datetime as _dt

        from ..options import CoreOptions
        from ..utils import now_millis

        opts = self.table.options.options
        auto = []
        for name, sid in self.tm.list_tags().items():
            try:
                _dt.datetime.strptime(name, fmt)
            except ValueError:
                continue
            auto.append(name)
        auto.sort()
        keep_n = opts.get(CoreOptions.TAG_NUM_RETAINED_MAX)
        if keep_n is not None and len(auto) > keep_n:
            for name in auto[: len(auto) - keep_n]:
                self.tm.delete(name)
            auto = auto[len(auto) - keep_n :]
        ttl = opts.get(CoreOptions.TAG_DEFAULT_TIME_RETAINED)
        if ttl is not None:
            cutoff = now_millis() - ttl
            for name in list(auto):
                if self.tm.get(name).time_millis < cutoff:
                    self.tm.delete(name)
