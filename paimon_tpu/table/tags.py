"""Tags: named retained snapshots for time travel.

Parity: /root/reference/paimon-core/.../tag/ — Tag.java (a snapshot copy
stored under table/tag/tag-<name>), TagManager, and the expire protection
that keeps tagged snapshots' files alive.
"""

from __future__ import annotations

from ..core.snapshot import Snapshot, SnapshotManager
from ..fs import FileIO

__all__ = ["TagManager"]


class TagManager:
    def __init__(self, file_io: FileIO, table_path: str):
        self.file_io = file_io
        self.table_path = table_path
        self.tag_dir = f"{table_path}/tag"
        self.snapshot_manager = SnapshotManager(file_io, table_path)

    def tag_path(self, name: str) -> str:
        return f"{self.tag_dir}/tag-{name}"

    def create(self, name: str, snapshot_id: int | None = None) -> None:
        if self.file_io.exists(self.tag_path(name)):
            raise ValueError(f"tag {name!r} already exists")
        if snapshot_id is None:
            snapshot_id = self.snapshot_manager.latest_snapshot_id()
            if snapshot_id is None:
                raise ValueError("cannot tag an empty table")
        snap = self.snapshot_manager.snapshot(snapshot_id)
        if not self.file_io.try_atomic_write(self.tag_path(name), snap.to_json().encode()):
            raise ValueError(f"tag {name!r} already exists")

    def delete(self, name: str) -> None:
        self.file_io.delete(self.tag_path(name))

    def get(self, name: str) -> Snapshot:
        return Snapshot.from_json(self.file_io.read_bytes(self.tag_path(name)))

    def snapshot_id(self, name: str) -> int:
        return self.get(name).id

    def list_tags(self) -> dict[str, int]:
        out = {}
        for st in self.file_io.list_files(self.tag_dir):
            base = st.path.rsplit("/", 1)[-1]
            if base.startswith("tag-"):
                name = base[len("tag-") :]
                out[name] = self.get(name).id
        return out

    def tagged_snapshot_ids(self) -> set[int]:
        return set(self.list_tags().values())
