"""Maintenance actions: orphan file cleanup, partition expiry.

Parity: /root/reference/paimon-core/.../operation/OrphanFilesClean (delete
files no snapshot/tag references, older than a safety TTL) and
PartitionExpire (drop whole partitions past their time-to-live based on a
partition-value timestamp).
"""

from __future__ import annotations

import datetime
from typing import TYPE_CHECKING

from ..core.manifest import ManifestCommittable
from ..utils import now_millis

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["remove_orphan_files", "expire_partitions", "drop_partition", "mark_partition_done"]


def remove_orphan_files(
    table: "FileStoreTable", older_than_millis: int | None = None, dry_run: bool = False
) -> list[str]:
    """Delete files referenced by NO snapshot, changelog, tag, or branch,
    plus torn `.tmp.*` write residue. Only files older than the threshold
    (default `orphan.clean.older-than`, 1 day) are touched — an in-flight
    commit's freshly written files must survive. The reachability walk and
    sweep live in resilience/orphan.py (crash-recovery subsystem)."""
    from ..resilience.orphan import remove_orphan_files as _impl

    return _impl(table, older_than_millis=older_than_millis, dry_run=dry_run)


def expire_partitions(table: "FileStoreTable", expiration_millis: int, time_col: str | None = None, pattern: str = "%Y-%m-%d") -> list[tuple]:
    """Drop partitions whose timestamp value is older than the TTL (reference
    PartitionExpire; partition.timestamp-pattern). The partition's files are
    logically deleted in one OVERWRITE-style commit."""
    keys = table.partition_keys
    if not keys:
        return []
    col = time_col or keys[0]
    if col not in keys:
        raise ValueError(f"time_col {col!r} is not a partition key (have {keys})")
    idx = keys.index(col)
    cutoff = now_millis() - expiration_millis
    store = table.store
    plan = store.new_scan().plan()
    expired: list[tuple] = []
    for partition in plan.grouped():
        value = partition[idx]
        try:
            ts = datetime.datetime.strptime(str(value), pattern).timestamp() * 1000
        except ValueError:
            continue
        if ts < cutoff:
            expired.append(partition)
    _commit_partition_drop(store, expired)
    return expired


def _commit_partition_drop(store, partitions: list[tuple]) -> None:
    """One OVERWRITE commit logically deleting the given partitions (shared
    by expire_partitions and drop_partition; identifier is the maintenance
    sentinel — see core/commit.py batch-commit sentinels)."""
    if not partitions:
        return
    dead = set(partitions)
    store.new_commit().overwrite(
        ManifestCommittable((1 << 63) - 4, messages=[]),
        partition_filter=lambda p: p in dead,
    )


def drop_partition(table: "FileStoreTable", *specs: dict[str, str]) -> list[tuple]:
    """Logically delete all partitions matching ANY of `specs` (each a
    possibly-partial, non-empty {partition_key: value} map) in ONE OVERWRITE
    commit — a reader never observes a partially-dropped state. Reference:
    flink/action/DropPartitionAction.java -> FileStoreCommit.dropPartitions.
    Returns the dropped partition tuples."""
    keys = table.partition_keys
    if not keys:
        raise ValueError("drop_partition requires a partitioned table")
    if not specs or any(not s for s in specs):
        raise ValueError("each partition spec must name at least one key=value")
    compiled = []
    for spec in specs:
        unknown = set(spec) - set(keys)
        if unknown:
            raise ValueError(f"not partition keys: {sorted(unknown)} (have {keys})")
        compiled.append([(keys.index(k), str(v)) for k, v in spec.items()])
    store = table.store
    plan = store.new_scan().plan()
    dead = [
        p
        for p in plan.grouped()
        if any(all(str(p[i]) == v for i, v in positions) for positions in compiled)
    ]
    _commit_partition_drop(store, dead)
    return dead


def mark_partition_done(table: "FileStoreTable", specs: list[dict[str, str]]) -> list[str]:
    """Write a _SUCCESS marker in each partition directory (reference
    flink/action/MarkPartitionDoneAction.java, success-file mode of
    partition.mark-done-action): downstream schedulers poll the marker to
    know the partition stopped receiving data. Marker content matches the
    reference's SuccessFile JSON ({creationTime, modificationTime})."""
    from ..utils import dumps, partition_path

    keys = table.partition_keys
    if not keys:
        raise ValueError("mark_partition_done requires a partitioned table")
    out = []
    for spec in specs:
        missing = [k for k in keys if k not in spec]
        if missing:
            raise ValueError(f"partition spec {spec} missing keys {missing}")
        pp = partition_path(keys, tuple(spec[k] for k in keys))
        path = f"{table.path}/{pp}/_SUCCESS"
        now = now_millis()
        try:
            prev = table.file_io.read_bytes(path)
            from ..utils import loads

            created = loads(prev).get("creationTime", now)
        except (FileNotFoundError, OSError, ValueError):
            created = now
        table.file_io.try_overwrite(path, dumps({"creationTime": created, "modificationTime": now}).encode())
        out.append(path)
    return out
