"""Read builders: scan planning -> splits -> merge reads.

Parity: /root/reference/paimon-core/.../table/source/ —
ReadBuilder.java:73 (scan -> plan -> splits -> read), DataSplit.java:48,
MergeTreeSplitGenerator.java:38 (section-aware split packing reusing
IntervalPartition), DataTableBatchScan with time travel via scan options
(CoreOptions.StartupMode).
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Sequence

from ..core.datafile import DataFileMeta
from ..core.levels import IntervalPartition
from ..data.predicate import Predicate
from ..options import CoreOptions

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["ReadBuilder", "TableScan", "TableRead", "DataSplit"]


@dataclass
class DataSplit:
    """A self-contained unit of read work (serializable for shipping to
    tasks/devices)."""

    partition: tuple
    bucket: int
    files: list[DataFileMeta]
    snapshot_id: int | None = None
    raw_convertible: bool = False  # single-run: no merge needed
    dv_index_file: str | None = None  # deletion-vector index for this bucket
    is_changelog: bool = False  # files are changelog (-U/+U kinds preserved)

    @property
    def row_count(self) -> int:
        return sum(f.row_count for f in self.files)

    def to_dict(self) -> dict:
        return {
            "partition": list(self.partition),
            "bucket": self.bucket,
            "files": [f.to_dict() for f in self.files],
            "snapshotId": self.snapshot_id,
            "rawConvertible": self.raw_convertible,
            "dvIndexFile": self.dv_index_file,
            "isChangelog": self.is_changelog,
        }

    @staticmethod
    def from_dict(d: dict) -> "DataSplit":
        return DataSplit(
            tuple(d["partition"]),
            d["bucket"],
            [DataFileMeta.from_dict(f) for f in d["files"]],
            d.get("snapshotId"),
            d.get("rawConvertible", False),
            d.get("dvIndexFile"),
            d.get("isChangelog", False),
        )


class ReadBuilder:
    def __init__(self, table: "FileStoreTable"):
        self.table = table
        self._predicate: Predicate | None = None
        self._projection: Sequence[str] | None = None
        self._limit: int | None = None

    def with_filter(self, predicate: Predicate) -> "ReadBuilder":
        self._predicate = predicate if self._predicate is None else (self._predicate & predicate)
        return self

    def with_projection(self, fields: Sequence[str]) -> "ReadBuilder":
        self._projection = list(fields)
        return self

    def with_limit(self, limit: int) -> "ReadBuilder":
        self._limit = limit
        return self

    def new_scan(self) -> "TableScan":
        return TableScan(self.table, self._predicate)

    def new_stream_scan(self):
        from .stream import StreamTableScan

        return StreamTableScan(self.table, self._predicate)

    def new_read(self) -> "TableRead":
        return TableRead(self.table, self._predicate, self._projection, self._limit)


class TableScan:
    def __init__(self, table: "FileStoreTable", predicate: Predicate | None):
        self.table = table
        self.predicate = predicate

    def _incremental_splits(self, spec: str) -> list[DataSplit]:
        """incremental-between='a,b' (snapshot ids or tag names): the union
        of APPEND deltas of snapshots (a, b], rows carrying their original
        kinds (reference IncrementalStartingScanner, delta scan mode)."""
        store = self.table.store
        sm = store.snapshot_manager

        def resolve(token: str) -> int:
            token = token.strip()
            if token.lstrip("-").isdigit():
                return int(token)
            from .tags import TagManager

            try:
                return TagManager(self.table.file_io, self.table.path).snapshot_id(token)
            except FileNotFoundError:
                raise ValueError(f"unknown tag {token!r} in incremental-between") from None

        parts = spec.split(",")
        if len(parts) != 2:
            raise ValueError(f"incremental-between expects 'start,end', got {spec!r}")
        start, end = resolve(parts[0]), resolve(parts[1])
        if start >= end:
            raise ValueError(
                f"incremental-between start must precede end, got {start} >= {end}"
            )
        from ..core.snapshot import CommitKind

        mode = store.options.options.get(CoreOptions.INCREMENTAL_BETWEEN_SCAN_MODE).lower()
        if mode not in ("delta", "changelog"):
            raise ValueError(f"unknown incremental-between-scan-mode {mode!r}")
        partition_accept = self._partition_predicate()
        splits: list[DataSplit] = []
        for sid in range(start + 1, end + 1):
            if not sm.snapshot_exists(sid):
                continue
            snap = sm.snapshot(sid)
            if mode == "changelog":
                # exact change events the producers recorded (reference
                # scan-mode=changelog); COMPACT snapshots carry the
                # full-compaction producer's files, so none are skipped
                if not snap.changelog_manifest_list:
                    continue
                kind = "changelog"
            else:
                if snap.commit_kind != CommitKind.APPEND:
                    continue  # COMPACT/OVERWRITE rewrite existing rows, no new changes
                kind = "delta"
            scan = store.new_scan().with_snapshot(sid).with_kind(kind)
            if partition_accept is not None:
                scan = scan.with_partition_filter(partition_accept)
            plan = scan.plan()
            for partition, buckets in sorted(plan.grouped().items()):
                for bucket, files in sorted(buckets.items()):
                    splits.append(
                        DataSplit(
                            partition,
                            bucket,
                            files,
                            snapshot_id=sid,
                            # raw per-file reads preserving row kinds: the
                            # delta IS the change stream for this snapshot
                            is_changelog=True,
                        )
                    )
        return splits

    def _file_index_predicate(self, keyed: bool):
        """The predicate to test against per-file bloom indexes, or None when
        index pruning is off/inapplicable. Keyed tables only test KEY-field
        conjuncts (a value match in an old file can be overridden by a newer
        one, but a key absent from every index cannot exist); append tables
        test everything — same safety split as the stats-based filters.
        Gated by file-index.read.enabled (reference FileIndexReadOptions)."""
        if self.predicate is None:
            return None
        co = self.table.store.options
        if not co.options.get(CoreOptions.FILE_INDEX_READ_ENABLED):
            return None
        if not keyed:
            return self.predicate
        from ..data.predicate import PredicateBuilder, and_

        parts = PredicateBuilder.pick_by_fields(
            PredicateBuilder.split_and(self.predicate), set(self.table.store.key_names)
        )
        return and_(*parts) if parts else None

    def _index_accepts(self, f, bucket_dir: str, pred) -> bool:
        """False only when the file's index PROVES no row matches."""
        from ..format.fileindex import FileIndexPredicate

        try:
            if f.embedded_index is not None:
                return FileIndexPredicate.from_bytes(f.embedded_index).test(pred)
            if f"{f.file_name}.index" in f.extra_files:
                return FileIndexPredicate(
                    self.table.file_io, f"{bucket_dir}/{f.file_name}.index"
                ).test(pred)
        except (FileNotFoundError, OSError):
            return True  # a missing/corrupt index never loses rows
        return True

    def _partition_predicate(self):
        """partition tuple -> bool from the scan predicate's partition
        conjuncts; None when nothing prunes."""
        if self.predicate is None:
            return None
        from ..data.predicate import PredicateBuilder, and_

        store = self.table.store
        parts = PredicateBuilder.split_and(self.predicate)
        part_parts = PredicateBuilder.pick_by_fields(parts, set(store.partition_keys))
        if not part_parts:
            return None
        pred = and_(*part_parts)
        keys = store.partition_keys

        def accept(partition: tuple) -> bool:
            from ..data.batch import ColumnBatch

            row = ColumnBatch.from_pydict(
                self.table.row_type.project(keys), {k: [v] for k, v in zip(keys, partition)}
            )
            return bool(pred.eval(row)[0])

        return accept

    def _resolve_snapshot(self) -> int | None:
        """Time travel via scan options (reference StartupMode/time-travel)."""
        store = self.table.store
        opts = store.options.options
        sid = opts.get(CoreOptions.SCAN_SNAPSHOT_ID)
        if sid is not None:
            return sid
        tag = opts.get(CoreOptions.SCAN_TAG_NAME)
        if tag:
            from .tags import TagManager

            return TagManager(self.table.file_io, self.table.path).snapshot_id(tag)
        ts = opts.get(CoreOptions.SCAN_TIMESTAMP_MILLIS)
        if ts is None:
            iso = opts.get(CoreOptions.SCAN_TIMESTAMP)
            if iso:
                import datetime as _dt

                ts = int(_dt.datetime.fromisoformat(iso).timestamp() * 1000)
        if ts is not None:
            snap = store.snapshot_manager.earlier_or_equal_time_millis(ts)
            return snap.id if snap else None
        version = opts.get(CoreOptions.SCAN_VERSION)
        if version:
            from .tags import TagManager

            tm = TagManager(self.table.file_io, self.table.path)
            if version in tm.list_tags():
                return tm.snapshot_id(version)
            return int(version)
        wm = opts.get(CoreOptions.SCAN_WATERMARK)
        if wm is not None:
            # earliest snapshot whose watermark passed the bound (reference
            # TimeTravelUtil watermark travel)
            for snap in store.snapshot_manager.snapshots():
                if snap.watermark is not None and snap.watermark >= wm:
                    return snap.id
            return None
        return None

    def plan(self) -> list[DataSplit]:
        store = self.table.store
        inc = store.options.options.get(CoreOptions.INCREMENTAL_BETWEEN)
        if inc:
            return self._incremental_splits(inc)
        inc_ts = store.options.options.get(CoreOptions.INCREMENTAL_BETWEEN_TIMESTAMP)
        if inc_ts:
            # resolve 't1,t2' epoch-millis to the snapshots at those times,
            # then reuse the id-based incremental machinery
            t1, t2 = (int(x) for x in inc_ts.split(","))
            sm = store.snapshot_manager
            s1 = sm.earlier_or_equal_time_millis(t1)
            s2 = sm.earlier_or_equal_time_millis(t2)
            if s2 is None:
                return []
            start = s1.id if s1 else 0
            if start >= s2.id:
                return []  # empty window: no snapshot landed between t1 and t2
            return self._incremental_splits(f"{start},{s2.id}")
        scan = store.new_scan()
        snapshot_id = self._resolve_snapshot()
        if snapshot_id is not None:
            scan = scan.with_snapshot(snapshot_id)
        if self.predicate is not None:
            from ..data.predicate import PredicateBuilder, and_

            parts = PredicateBuilder.split_and(self.predicate)
            key_parts = PredicateBuilder.pick_by_fields(parts, set(store.key_names))
            if key_parts:
                scan = scan.with_key_filter(and_(*key_parts))
            if not self.table.schema.primary_keys:
                # append tables: every row is final — value filters can
                # safely skip whole files (reference AppendOnlyFileStoreScan)
                scan = scan.with_value_filter(self.predicate)
            # partition predicate -> partition pruning
            accept = self._partition_predicate()
            if accept is not None:
                scan = scan.with_partition_filter(accept)
        plan = scan.plan()
        co = store.options
        target = int(co.options.get(CoreOptions.SOURCE_SPLIT_TARGET_SIZE))
        open_cost = int(co.options.get(CoreOptions.SOURCE_SPLIT_OPEN_FILE_COST))
        created_after = co.options.get(CoreOptions.SCAN_FILE_CREATION_TIME_MILLIS)
        splits = []
        keyed = bool(self.table.schema.primary_keys)
        index_pred = self._file_index_predicate(keyed)
        per_partition: dict[tuple, list[DataSplit]] = {}
        for partition, buckets in sorted(plan.grouped().items(), key=lambda kv: kv[0]):
            plist = per_partition.setdefault(partition, [])
            for bucket, files in sorted(buckets.items()):
                if created_after is not None:
                    # reference scan.file-creation-time-millis: only files
                    # born after the bound (append/log-style consumption)
                    files = [f for f in files if f.creation_time_millis > created_after]
                    if not files:
                        continue
                if index_pred is not None:
                    bd = store.bucket_dir(partition, bucket)
                    files = [f for f in files if self._index_accepts(f, bd, index_pred)]
                    if not files:
                        continue
                snapshot = plan.snapshot.id if plan.snapshot else None
                dv_index = plan.dv_index_for(partition, bucket)
                for pack, raw in _pack_bucket_splits(files, target, open_cost, keyed):
                    plist.append(
                        DataSplit(
                            partition,
                            bucket,
                            pack,
                            snapshot_id=snapshot,
                            raw_convertible=raw,
                            dv_index_file=dv_index,
                        )
                    )
        if co.options.get(CoreOptions.SCAN_PLAN_SORT_PARTITION):
            # strict partition-major order for sorted sequential consumption
            for p in sorted(per_partition):
                splits.extend(per_partition[p])
        else:
            # round-robin across partitions: parallel readers spread load
            lanes = [per_partition[p] for p in sorted(per_partition)]
            i = 0
            while True:
                emitted = False
                for lane in lanes:
                    if i < len(lane):
                        splits.append(lane[i])
                        emitted = True
                if not emitted:
                    break
                i += 1
        return splits


def _pack_bucket_splits(files, target: int, open_cost: int, keyed: bool) -> list[tuple[list, bool]]:
    """Weighted bin-packing of one bucket's files into read splits, returning
    (files, raw_convertible) per pack (reference
    MergeTreeSplitGenerator.splitForBatch + AppendOnlySplitGenerator +
    BinPacking.packForOrdered). Keyed tables pack SECTIONS — files that must
    merge together stay atomic, key-disjoint sections spread across splits —
    weighing each section max(total size, open-file-cost); append tables have
    no key ranges (one degenerate section), so their unit is the single file.
    Not ported: the reference's DV/first-row fast path that packs per-file
    raw groups even for overlapping keyed sections."""
    if not files:
        return []
    if keyed:
        sections = IntervalPartition(files).partition()
        units = [
            ([f for run in section for f in run.files], len(section) == 1)
            for section in sections
        ]
    else:
        ordered = sorted(files, key=lambda f: (f.min_sequence_number, f.file_name))
        units = [([f], True) for f in ordered]
    packs: list[tuple[list, bool]] = []
    cur: list = []
    cur_raw = True
    cur_weight = 0
    for unit_files, unit_raw in units:
        w = max(sum(f.file_size for f in unit_files), open_cost)
        if cur and cur_weight + w > target:
            packs.append((cur, cur_raw))
            cur, cur_raw, cur_weight = [], True, 0
        cur.extend(unit_files)
        cur_raw = cur_raw and unit_raw
        cur_weight += w
    if cur:
        packs.append((cur, cur_raw))
    return packs


@contextmanager
def _null_ctx():
    yield None


class TableRead:
    def __init__(
        self,
        table: "FileStoreTable",
        predicate: Predicate | None,
        projection: Sequence[str] | None,
        limit: int | None = None,
    ):
        self.table = table
        self.predicate = predicate
        self.projection = projection
        self.limit = limit

    def read_with_kinds(self, split: DataSplit):
        """(rows, RowKind uint8 vector) — the changelog-aware read used by
        streaming consumers. For data splits every merged row is +I."""
        import numpy as np

        from ..types import RowKind

        if split.is_changelog:
            store = self.table.store
            rf = store.reader_factory(split.partition, split.bucket)
            from ..core.kv import KVBatch

            ordered = sorted(split.files, key=lambda f: (f.min_sequence_number, f.file_name))
            kv = KVBatch.concat([rf.read(f) for f in ordered])
            data = kv.data
            kinds = kv.kind
            if self.predicate is not None and data.num_rows:
                mask = self.predicate.eval(data)
                if not mask.all():
                    data, kinds = data.filter(mask), kinds[mask]
            if self.projection is not None:
                data = data.select(self.projection)
            return data, kinds
        out = self.read(split)
        return out, np.full(out.num_rows, int(RowKind.INSERT), dtype=np.uint8)

    def read(self, split: DataSplit):
        if split.is_changelog:
            return self.read_with_kinds(split)[0]
        out = self._dispatch(split)()
        if self.limit is not None and out.num_rows > self.limit:
            out = out.slice(0, self.limit)
        return out

    def _dispatch(self, split: DataSplit):
        """Phase-1 read of one data split: returns a continuation."""
        dvs = None
        if split.dv_index_file:
            from ..core.deletionvectors import DeletionVectorsIndexFile

            all_dvs = DeletionVectorsIndexFile(self.table.file_io, self.table.path).read_all(split.dv_index_file)
            names = {f.file_name for f in split.files}
            dvs = {k: v for k, v in all_dvs.items() if k in names}
        return self.table.store.read_bucket_dispatch(
            split.partition,
            split.bucket,
            split.files,
            predicate=self.predicate,
            projection=self.projection,
            deletion_vectors=dvs,
        )

    def batches(self, splits: Sequence[DataSplit]):
        """Ordered generator of per-split batches (the ConcatRecordReader
        analog): each split's output is yielded as soon as its merge stage
        completes, in deterministic split order, instead of materializing
        every split before the first row is visible. Three execution modes,
        picked per call:

        * mesh execution (merge.engine = mesh, >1 device): the SplitPipeline
          becomes the host-side feeder — one prefetch lane per device — so
          IO/decode of split i+1 overlaps the batched shard_map merges of
          split i (parallel/mesh_exec.py);
        * mesh batching (parallel.mesh.enabled, >1 device): dispatch every
          split first so all merges run in one shard_map, then complete;
        * pipelined (scan.prefetch-splits > 0, the default): split i+1
          fetches bytes through RetryingFileIO and decodes on a pipeline
          worker while split i merges on device — output is bit-identical
          to the sequential path (parallel/pipeline.py contract);
        * sequential (scan.prefetch-splits = 0, or a limit wanting
          split-by-split early exit)."""
        from ..parallel.executor import maybe_mesh_batch

        splits = list(splits)
        remaining = self.limit
        # a limit wants early-exit split by split — dispatching every split
        # up front would turn a point query into a full scan, so limited
        # reads stay on the sequential path
        use_mesh = remaining is None
        with maybe_mesh_batch(self.table.store) if use_mesh else _null_ctx() as ctx:
            if ctx is None and remaining is None and len(splits) > 1:
                depth, parallelism = self.table.store.pipeline_config()
                if depth > 0:
                    from ..parallel.pipeline import SplitPipeline

                    pipe = SplitPipeline(parallelism, depth, stage="scan")
                    yield from pipe.map_ordered(splits, self.read)
                    return
            if ctx is not None and getattr(ctx, "plans_globally", False) and len(splits) > 1:
                # merge.engine = mesh: feeder-driven dispatch (one prefetch
                # lane per device) instead of reading every split up front
                yield from self._mesh_batches(ctx, splits)
                return
            if ctx is not None:
                # mesh mode: dispatch every split first — their merges run as
                # one batched shard_map over the bucket axis — then complete
                pending = [(s, self._dispatch(s)) for s in splits if not s.is_changelog]
                conts = dict((id(s), c) for s, c in pending)
            for s in splits:
                if ctx is not None and not s.is_changelog:
                    b = conts[id(s)]()
                else:
                    b = self.read(s)
                if remaining is not None:
                    if remaining <= 0:
                        break
                    if b.num_rows > remaining:
                        b = b.slice(0, remaining)
                    remaining -= b.num_rows
                yield b

    def _mesh_batches(self, mex, splits: Sequence[DataSplit]):
        """merge.engine = mesh scan: the PR 4 SplitPipeline is the host-side
        feeder with one prefetch lane per device, so the IO + decode of
        shard i+1 overlap the batched device merges of shard i. Each
        continuation's first resolve executes every merge job dispatched so
        far in family-batched shard_map calls over the mesh's bucket axis;
        emission stays in strict split order, so output is bit-identical to
        the single-device path."""
        import time

        from ..metrics import mesh_metrics
        from ..parallel.pipeline import SplitPipeline

        from ..parallel.executor import _ACTIVE

        lanes = mex.feeder_lanes
        pipe = SplitPipeline(parallelism=lanes, depth=lanes, stage="scan")
        wait = mesh_metrics().histogram("feeder_wait_ms")

        def dispatch(s: DataSplit):
            # changelog splits have no merge to batch: read on the consumer
            if s.is_changelog:
                return None
            # the mesh context is a ContextVar — invisible inside pipeline
            # worker threads unless re-installed, and without it the dispatch
            # would silently merge eagerly on the worker instead of enqueuing
            # the job for the batched shard_map
            token = _ACTIVE.set(mex)
            try:
                return self._dispatch(s)
            finally:
                _ACTIVE.reset(token)

        it = pipe.map_ordered(splits, dispatch)
        try:
            for s in splits:
                t0 = time.perf_counter()
                cont = next(it)
                wait.update((time.perf_counter() - t0) * 1000)
                yield self.read(s) if cont is None else cont()
        finally:
            it.close()

    def read_all(self, splits: Sequence[DataSplit]):
        from ..data.batch import concat_batches

        schema = self.table.row_type if self.projection is None else self.table.row_type.project(self.projection)
        batches = list(self.batches(splits))
        if not batches:
            from ..data.batch import ColumnBatch

            return ColumnBatch.empty(schema)
        return concat_batches(batches)
