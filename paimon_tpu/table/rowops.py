"""Row-level SQL commands: UPDATE and MERGE INTO, engine-neutral.

Parity: /root/reference/paimon-spark/paimon-spark-common/src/main/scala/org/
apache/paimon/spark/commands/UpdatePaimonTableCommand.scala and
MergeIntoPaimonTable.scala — the Spark catalyst commands lower to exactly
this: resolve affected rows against the merged view, build the changed rows,
and push them through the normal write path (upsert/-D retract for PK
tables, copy-on-write file rewrite for append tables). Here the "expression"
surface is engine-neutral: assignments and conditions are constants,
column-reference strings ("src.col" / "tgt.col"), or callables over the
aligned source/target ColumnBatches — an engine with a SQL frontend lowers
its expressions onto these.

WHEN MATCHED clauses apply in declaration order, first match wins per row —
SQL MERGE semantics, matching the reference's clause evaluation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable, Mapping, Sequence

import numpy as np

from ..core.kv import KVBatch
from ..data.batch import Column, ColumnBatch
from ..data.predicate import Predicate, and_, in_
from ..options import MergeEngine
from ..types import RowKind

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["update_where", "MergeInto", "MergeResult"]

builtins_set = set  # `set` is shadowed by the when_matched_update SQL-ish parameter name


def _require_deduplicate(table: "FileStoreTable", op: str) -> None:
    """Upsert-style row commands are only sound under last-write-wins: on an
    aggregation table a SET would become an ADD, on first-row it would be
    silently ignored (the reference UpdatePaimonTableCommand raises for
    unsupported merge engines the same way)."""
    if table.options.merge_engine != MergeEngine.DEDUPLICATE:
        raise ValueError(
            f"{op} requires merge-engine=deduplicate; "
            f"table uses {table.options.merge_engine.value!r}"
        )


# ---------------------------------------------------------------------------
# UPDATE table SET ... WHERE ...
# ---------------------------------------------------------------------------


def _assign(batch: ColumnBatch, assignments: Mapping[str, Any]) -> ColumnBatch:
    """Apply SET assignments to a batch of matching rows."""
    cols = dict(batch.columns)
    n = batch.num_rows
    for name, value in assignments.items():
        field = batch.schema.field(name)  # raises on unknown column
        if callable(value):
            out = value(batch)
            cols[name] = out if isinstance(out, Column) else Column.from_pylist(list(out), field.type)
        else:
            cols[name] = Column.from_pylist([value] * n, field.type)
    return ColumnBatch(batch.schema, cols)


def update_where(table: "FileStoreTable", predicate: Predicate, assignments: Mapping[str, Any]) -> int:
    """UPDATE ... SET assignments WHERE predicate. Returns #rows updated.
    PK tables upsert the changed rows (+U); append tables copy-on-write
    rewrite the affected files (reference UpdatePaimonTableCommand)."""
    pks = set(table.primary_keys)
    if pks & set(assignments):
        raise ValueError(f"cannot UPDATE primary key columns {sorted(pks & set(assignments))}")
    if table.is_primary_key_table:
        _require_deduplicate(table, "UPDATE")
        rb = table.new_read_builder().with_filter(predicate)
        matching = rb.new_read().read_all(rb.new_scan().plan())
        if matching.num_rows == 0:
            return 0
        updated = _assign(matching, assignments)
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write(updated, np.full(updated.num_rows, int(RowKind.UPDATE_AFTER), dtype=np.uint8))
        wb.new_commit().commit(w.prepare_commit())
        return updated.num_rows
    from .delete import copy_on_write_rewrite

    def transform(kv_match: KVBatch) -> KVBatch:
        return KVBatch(_assign(kv_match.data, assignments), kv_match.seq, kv_match.kind)

    return copy_on_write_rewrite(table, predicate, transform)


# ---------------------------------------------------------------------------
# MERGE INTO
# ---------------------------------------------------------------------------


@dataclass
class MergeResult:
    rows_updated: int = 0
    rows_deleted: int = 0
    rows_inserted: int = 0


def _resolve(value, src: ColumnBatch, tgt: ColumnBatch | None, field_type, n: int) -> Column:
    """An action value: "src.col" / "tgt.col" reference, callable(src, tgt),
    or a constant."""
    if callable(value):
        out = value(src, tgt)
        return out if isinstance(out, Column) else Column.from_pylist(list(out), field_type)
    if isinstance(value, str) and value.startswith(("src.", "tgt.")):
        side, _, col = value.partition(".")
        if side == "tgt":
            if tgt is None:
                raise ValueError("WHEN NOT MATCHED INSERT has no target row; 'tgt.*' is invalid")
            return tgt.column(col)
        return src.column(col)
    return Column.from_pylist([value] * n, field_type)


def _cond_mask(condition, src: ColumnBatch, tgt: ColumnBatch | None, n: int) -> np.ndarray:
    if condition is None:
        return np.ones(n, dtype=np.bool_)
    out = condition(src, tgt) if tgt is not None else condition(src)
    return np.asarray(out, dtype=np.bool_)


class MergeInto:
    """MERGE INTO target USING source ON <pk join> WHEN MATCHED ... WHEN NOT
    MATCHED ... (reference MergeIntoPaimonTable.scala). The join is on the
    target's primary key — the same restriction the reference enforces for
    primary-key tables (the merge condition must cover the primary key)."""

    def __init__(self, table: "FileStoreTable", source: ColumnBatch | Mapping[str, Sequence]):
        if not table.is_primary_key_table:
            raise ValueError("MERGE INTO requires a primary-key target table")
        _require_deduplicate(table, "MERGE INTO")
        self.table = table
        if isinstance(source, Mapping):
            names = set(source)
            schema = table.row_type.project([f.name for f in table.row_type.fields if f.name in names])
            source = ColumnBatch.from_pydict(schema, source)
        self.source = source
        missing = [k for k in table.primary_keys if k not in source.schema.field_names]
        if missing:
            raise ValueError(f"source must carry the target primary key columns; missing {missing}")
        # WHEN MATCHED clauses in declaration order: ("update", set, cond) or
        # ("delete", cond); first matching clause wins per row
        self._matched_clauses: list[tuple] = []
        self._not_matched_insert: tuple[Mapping[str, Any] | None, Callable | None] | None = None

    def when_matched_update(self, set: Mapping[str, Any], condition: Callable | None = None) -> "MergeInto":
        bad = set.keys() & builtins_set(self.table.primary_keys)
        if bad:
            raise ValueError(f"cannot UPDATE primary key columns {sorted(bad)}")
        self._matched_clauses.append(("update", set, condition))
        return self

    def when_matched_delete(self, condition: Callable | None = None) -> "MergeInto":
        self._matched_clauses.append(("delete", condition))
        return self

    def when_not_matched_insert(
        self, values: Mapping[str, Any] | None = None, condition: Callable | None = None
    ) -> "MergeInto":
        self._not_matched_insert = (values, condition)
        return self

    def execute(self) -> MergeResult:
        table = self.table
        pks = list(table.primary_keys)
        src = self.source
        src_keys = list(zip(*(src.column(k).to_pylist() for k in pks))) if src.num_rows else []
        seen: set = set()
        dup = [k for k in src_keys if k in seen or seen.add(k)]
        if dup:
            # the reference raises on multiple source rows matching one
            # target row (cardinality violation)
            raise ValueError(f"MERGE source has duplicate keys: {dup[:3]}")

        # prune the target read with the source's key set (the join is on the
        # PK, so a per-column IN superset is a safe prefilter)
        rb = table.new_read_builder()
        if src.num_rows:
            prefilter = and_(*(in_(k, sorted(builtins_set(src.column(k).to_pylist()))) for k in pks))
            rb = rb.with_filter(prefilter)
        tgt_all = rb.new_read().read_all(rb.new_scan().plan())
        tgt_keys = list(zip(*(tgt_all.column(k).to_pylist() for k in pks))) if tgt_all.num_rows else []
        tgt_index = {key: i for i, key in enumerate(tgt_keys)}
        matched_rows = [i for i, key in enumerate(src_keys) if key in tgt_index]
        not_matched_rows = [i for i, key in enumerate(src_keys) if key not in tgt_index]

        result = MergeResult()
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        wrote = False

        if matched_rows and self._matched_clauses:
            s_idx = np.array(matched_rows, dtype=np.int64)
            t_idx = np.array([tgt_index[src_keys[i]] for i in matched_rows], dtype=np.int64)
            src_m = src.take(s_idx)
            tgt_m = tgt_all.take(t_idx)
            n = len(s_idx)
            remaining = np.ones(n, dtype=np.bool_)
            for clause in self._matched_clauses:
                if not remaining.any():
                    break
                if clause[0] == "delete":
                    mask = _cond_mask(clause[1], src_m, tgt_m, n) & remaining
                    if mask.any():
                        dead = tgt_m.filter(mask)
                        w.write(dead, np.full(dead.num_rows, int(RowKind.DELETE), dtype=np.uint8))
                        wrote = True
                        result.rows_deleted += int(mask.sum())
                        remaining &= ~mask
                else:
                    _, set_map, cond = clause
                    mask = _cond_mask(cond, src_m, tgt_m, n) & remaining
                    if mask.any():
                        src_u, tgt_u = src_m.filter(mask), tgt_m.filter(mask)
                        cols = dict(tgt_u.columns)
                        for name, value in set_map.items():
                            cols[name] = _resolve(
                                value, src_u, tgt_u, table.row_type.field(name).type, tgt_u.num_rows
                            )
                        updated = ColumnBatch(table.row_type, cols)
                        w.write(
                            updated,
                            np.full(updated.num_rows, int(RowKind.UPDATE_AFTER), dtype=np.uint8),
                        )
                        wrote = True
                        result.rows_updated += int(mask.sum())
                        remaining &= ~mask

        if not_matched_rows and self._not_matched_insert is not None:
            values, cond = self._not_matched_insert
            s_idx = np.array(not_matched_rows, dtype=np.int64)
            src_n = src.take(s_idx)
            ins_mask = _cond_mask(cond, src_n, None, len(s_idx))
            if ins_mask.any():
                src_i = src_n.filter(ins_mask)
                cols = {}
                for f in table.row_type.fields:
                    if values is not None and f.name in values:
                        cols[f.name] = _resolve(values[f.name], src_i, None, f.type, src_i.num_rows)
                    elif f.name in src_i.schema.field_names:
                        cols[f.name] = src_i.column(f.name)
                    else:
                        cols[f.name] = Column.from_pylist([None] * src_i.num_rows, f.type)
                w.write(ColumnBatch(table.row_type, cols))
                wrote = True
                result.rows_inserted = src_i.num_rows

        if wrote:
            wb.new_commit().commit(w.prepare_commit())
        else:
            w.close()
        return result
