"""Write builders: batch and streaming ingestion.

Parity: /root/reference/paimon-core/.../table/sink/ —
BatchWriteBuilderImpl / StreamWriteBuilderImpl, TableWriteImpl.java:48 (row ->
SinkRecord with partition + bucket :129-160), TableCommitImpl.java:72
(filterAndCommit :183 for replay-safe streaming, expire hook :77-127).

A TableWrite routes incoming batches to per-(partition, bucket) merge-tree
writers; prepare_commit() drains them into CommitMessages; TableCommit turns
messages + a commit identifier into snapshots.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Sequence

import numpy as np

from ..core.commit import BATCH_COMMIT_IDENTIFIER
from ..core.manifest import CommitMessage, ManifestCommittable
from ..data.batch import ColumnBatch
from ..types import RowKind

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["BatchWriteBuilder", "StreamWriteBuilder", "TableWrite", "TableCommit"]


class TableWrite:
    def __init__(self, table: "FileStoreTable", buffer_controller=None):
        self.table = table
        store = table.store
        # admission control / memtable backpressure (core/admission.py):
        # built from write.buffer.max-memory when set, or injected — the
        # soak harness shares ONE controller across all writer threads to
        # model a global host-memory budget
        if buffer_controller is None:
            from ..core.admission import WriteBufferController

            buffer_controller = WriteBufferController.from_options(store.options)
        self.admission = buffer_controller
        self.partition_keys = store.partition_keys
        self.bucket_keys = table.schema.bucket_keys
        self.dynamic = table.is_primary_key_table and store.options.bucket == -1
        self.num_buckets = max(store.options.bucket, 1)
        self._writers: dict[tuple, object] = {}
        self._assigner = None
        self._cross = None
        if (
            self.dynamic
            and table.partition_keys
            and not set(table.partition_keys) <= set(table.primary_keys)
        ):
            # primary key omits the partition key: the standard dynamic path
            # cannot keep keys unique across partitions — delegate to the
            # global-index writer (reference GlobalDynamicBucketSink)
            from .crosspartition import CrossPartitionUpsertWrite

            self._init_local_merge()  # validate the option combo even here
            if self._local_merge_cap:
                raise ValueError(
                    "local-merge-buffer-size is not supported with cross-partition upsert"
                )
            self._cross = CrossPartitionUpsertWrite(table)
            return
        if self.dynamic:
            from ..core.bucket_index import HashIndexFile, SimpleHashBucketAssigner
            from ..options import CoreOptions

            target = store.options.options.get(CoreOptions.DYNAMIC_BUCKET_TARGET_ROW_NUM)
            # store.file_io, not table.file_io: the hash index rides the same
            # fs.retry budget as every other store-level IO path
            self._assigner = SimpleHashBucketAssigner(
                HashIndexFile(store.file_io, table.path),
                target,
                initial_buckets=store.options.options.get(CoreOptions.DYNAMIC_BUCKET_INITIAL_BUCKETS),
                num_assigners=store.options.options.get(CoreOptions.DYNAMIC_BUCKET_ASSIGNER_PARALLELISM) or 1,
            )
            self._bootstrapped: set[tuple] = set()
        self._init_local_merge()

    def _init_local_merge(self) -> None:
        """Local pre-merge (reference LocalMergeOperator / FlinkSinkBuilder's
        optional pre-shuffle merge): high-churn keys collapse in a small
        buffer BEFORE bucket routing, shrinking shuffle + memtable traffic.
        Deduplicate engine only — other engines need every record."""
        from ..options import CoreOptions, MergeEngine

        store = self.table.store
        size = int(store.options.options.get(CoreOptions.LOCAL_MERGE_BUFFER_SIZE))
        self._local_merge_bytes = 0
        self._local_buffer: list[tuple[ColumnBatch, np.ndarray | None]] = []
        self._local_merge_cap = 0
        if size > 0:
            if store.options.merge_engine != MergeEngine.DEDUPLICATE:
                raise ValueError("local-merge-buffer-size requires merge-engine=deduplicate")
            if not self.table.is_primary_key_table:
                raise ValueError("local-merge-buffer-size requires a primary-key table")
            if store.options.sequence_field:
                # the buffer dedups by ARRIVAL order; a user sequence field
                # could make a lower-seq late arrival evict a higher-seq row
                raise ValueError("local-merge-buffer-size cannot combine with sequence.field")
            if store.options.ignore_delete:
                # a trailing -D would evict its insert here, then be dropped
                # downstream — losing the row ignore-delete meant to keep
                raise ValueError("local-merge-buffer-size cannot combine with ignore-delete")
            self._local_merge_cap = size

    def _local_merge_flush(self) -> None:
        if not self._local_buffer:
            return
        from ..data.batch import concat_batches
        from ..data.keys import encode_key_lanes_with_pools
        from ..ops.merge import deduplicate_select

        batches = [b for b, _ in self._local_buffer]
        kinds = [
            k if k is not None else np.full(b.num_rows, int(RowKind.INSERT), dtype=np.uint8)
            for b, k in self._local_buffer
        ]
        self._local_buffer = []
        self._local_merge_bytes = 0
        data = concat_batches(batches) if len(batches) > 1 else batches[0]
        kind = np.concatenate(kinds)
        # the FULL primary key (partition columns included): the buffer spans
        # partitions, and trimmed keys would collapse same-id rows of
        # DIFFERENT partitions into one — routing separates them downstream
        keys = list(self.table.primary_keys)
        lanes = encode_key_lanes_with_pools(data, keys)
        # stability = arrival order: the LAST record per key (with its kind)
        # survives, exactly what dedup would do downstream
        take = deduplicate_select(lanes)
        self._route(data.take(take), kind.take(take))

    def write(self, data: ColumnBatch | dict, kinds: np.ndarray | Sequence[str] | None = None) -> None:
        if isinstance(data, dict):
            data = ColumnBatch.from_pydict(self.table.row_type, data)
        if kinds is not None and not isinstance(kinds, np.ndarray):
            kinds = np.array([int(RowKind.from_short_string(k)) for k in kinds], dtype=np.uint8)
        if kinds is None:
            # rowkind.field: the row kind rides in a data column ('+I'...)
            from ..options import CoreOptions

            rk_field = self.table.options.options.get(CoreOptions.ROWKIND_FIELD)
            if rk_field:
                vals = data.column(rk_field).values
                kinds = np.array([int(RowKind.from_short_string(str(v))) for v in vals], dtype=np.uint8)
        if self._cross is not None:
            self._cross.write(data, kinds)
            return
        if self._local_merge_cap:
            self._local_buffer.append((data, kinds))
            self._local_merge_bytes += data.byte_size()
            if self._local_merge_bytes >= self._local_merge_cap:
                self._local_merge_flush()
            return
        self._route(data, kinds)

    def _route(self, data: ColumnBatch, kinds: np.ndarray | None) -> None:
        from .bucket import group_by_partition_bucket

        if self.dynamic:
            self._write_dynamic(data, kinds)
            return
        for partition, bucket, rows in group_by_partition_bucket(
            data, self.partition_keys, self.bucket_keys, self.num_buckets
        ):
            w = self._writer(partition, bucket)
            sub = data.take(rows) if len(rows) != data.num_rows else data
            sub_kinds = kinds.take(rows) if kinds is not None and len(rows) != data.num_rows else kinds
            w.write(sub, sub_kinds)

    def _write_dynamic(self, data: ColumnBatch, kinds) -> None:
        """Dynamic bucket: assign each key a durable bucket via the hash
        index (reference DynamicBucketSink: assigner stage before writers)."""
        from .bucket import group_by_partition_bucket, key_hashes

        store = self.table.store
        for partition, _, rows in group_by_partition_bucket(data, self.partition_keys, [], 1):
            sub = data.take(rows) if len(rows) != data.num_rows else data
            sub_kinds = kinds.take(rows) if kinds is not None and len(rows) != data.num_rows else kinds
            self._bootstrap_partition(partition)
            hashes = key_hashes(sub, store.key_names)
            buckets = self._assigner.assign(partition, hashes)
            for b in np.unique(buckets):
                mask = buckets == b
                w = self._writer(partition, int(b))
                w.write(sub.filter(mask), sub_kinds[mask] if sub_kinds is not None else None)

    def _bootstrap_partition(self, partition: tuple) -> None:
        if partition in self._bootstrapped:
            return
        self._bootstrapped.add(partition)
        from ..core.bucket_index import HashIndexFile

        plan = self.table.store.new_scan().with_partition_filter(lambda p: p == partition).plan()
        hif = HashIndexFile(self.table.store.file_io, self.table.path)
        indexes = {
            e.bucket: hif.read(e.file_name)
            for e in plan.index_entries
            if e.kind == "HASH_INDEX" and e.partition == partition
        }
        if indexes:
            self._assigner.bootstrap(partition, indexes)

    def _writer(self, partition: tuple, bucket: int):
        key = (partition, bucket)
        if key not in self._writers:
            total = -1 if self.dynamic else self.num_buckets
            self._writers[key] = self.table.store.new_writer(
                partition, bucket, total, admission=self.admission
            )
        return self._writers[key]

    def delta_snapshot(self) -> dict[tuple, tuple]:
        """{(partition, bucket): (buffered KVBatches, uncommitted level-0
        DataFileMetas)} across every merge-tree writer this write opened —
        the read-your-writes delta tier LocalTableQuery.attach_write serves
        (committed-plus-buffered gets)."""
        out: dict[tuple, tuple] = {}
        for pb, w in list(self._writers.items()):
            ds = getattr(w, "delta_snapshot", None)
            if ds is not None:
                out[pb] = ds()
        return out

    def compact(self, full: bool = False) -> None:
        """Compact every bucket this write touched — or, when no rows were
        written (dedicated compact job), every live bucket of the table.
        Under parallel.mesh.enabled the per-bucket flushes and rewrite merges
        batch into shard_map calls over the mesh (the TPU analog of the
        reference's one-compaction-task-per-bucket topology)."""
        if not self._writers:
            plan = self.table.store.new_scan().plan()
            for partition, buckets in plan.grouped().items():
                for bucket in buckets:
                    self._writer(partition, bucket)
        from ..parallel.executor import maybe_mesh_batch

        with maybe_mesh_batch(self.table.store) as ctx:
            if ctx is None:
                for w in self._writers.values():
                    w.compact(full=full)
                return
            self._batched_flush()
            writers = list(self._writers.values())
            if getattr(ctx, "plans_globally", False) and len(writers) > 1:
                # merge.engine = mesh: bucket dispatches (input reads + merge
                # enqueue) stream through the feeder, one lane per device, so
                # bucket i+1's IO overlaps while bucket i's merges batch
                from ..parallel.executor import _ACTIVE
                from ..parallel.pipeline import SplitPipeline

                lanes = ctx.feeder_lanes
                pipe = SplitPipeline(parallelism=lanes, depth=lanes, stage="compact")

                def dispatch(w):
                    # re-install the mesh context: ContextVars don't cross
                    # into pipeline worker threads by themselves
                    token = _ACTIVE.set(ctx)
                    try:
                        return w.compact_dispatch(full)
                    finally:
                        _ACTIVE.reset(token)

                states = list(zip(writers, pipe.map_ordered(writers, dispatch)))
            else:
                states = [(w, w.compact_dispatch(full)) for w in writers]
            for w, st in states:
                w.compact_complete(st)

    def _batched_flush(self) -> None:
        """Dispatch every writer's memtable flush, then complete: the merges
        run in one batched mesh call (reference: one writer task per bucket)."""
        states = [(w, w.flush_dispatch()) for w in self._writers.values()]
        for w, st in states:
            if st is not None:
                w.flush_complete(st)

    def prepare_commit(self) -> list[CommitMessage]:
        if self._cross is not None:
            return self._cross.prepare_commit()
        if self._local_merge_cap:
            self._local_merge_flush()
        from ..options import CoreOptions

        if self.table.options.options.get(CoreOptions.COMMIT_FORCE_COMPACT) and not self.table.options.write_only:
            self.compact(full=True)
        from ..parallel.executor import maybe_mesh_batch

        with maybe_mesh_batch(self.table.store) as ctx:
            if ctx is not None:
                self._batched_flush()
            msgs = [m for m in (w.prepare_commit() for w in self._writers.values()) if not m.is_empty()]
        if self._assigner is not None:
            by_pb = {(m.partition, m.bucket): m for m in msgs}
            for partition, entries in self._assigner.prepare_commit().items():
                for e in entries:
                    msg = by_pb.get((partition, e.bucket))
                    if msg is None:
                        msg = CommitMessage(partition, e.bucket, -1)
                        msgs.append(msg)
                        by_pb[(partition, e.bucket)] = msg
                    msg.new_index_files.append(e)
        return msgs

    def close(self) -> None:
        """Tear down every per-bucket writer. Each close releases that
        writer's outstanding buffer reservation back to the (possibly
        shared) admission controller — abandoning a conflicted commit must
        re-admit blocked rivals, never leak budget."""
        for w in self._writers.values():
            close = getattr(w, "close", None)
            if close is not None:
                close()
        self._writers.clear()

    def health(self) -> dict:
        """Writer-side flow-control snapshot: the admission controller's
        backpressure state plus per-bucket buffer/flush depths (the health
        surface a serving layer polls to decide shedding vs routing)."""
        writers = {}
        for (partition, bucket), w in self._writers.items():
            h = getattr(w, "health", None)
            if h is not None:
                writers[f"{partition}/{bucket}"] = h()
        out = {"state": "ok", "writers": writers}
        if self.admission is not None:
            out.update(self.admission.health_dict())
        out["buffered_rows"] = sum(w.get("buffered_rows", 0) for w in writers.values())
        out["pending_flushes_writers"] = sum(w.get("pending_flushes", 0) for w in writers.values())
        return out


def load_callbacks(table, option) -> list:
    """Resolve a 'module:function,module:function' option into callables
    (reference commit.callbacks/tag.callbacks load classes by name; here the
    python-native form). Unresolvable specs raise at load time — a silently
    dropped callback is worse than a loud config error."""
    spec = table.options.options.get(option)
    if not spec:
        return []
    import importlib

    out = []
    for item in spec.split(","):
        mod, _, fn = item.strip().partition(":")
        out.append(getattr(importlib.import_module(mod), fn))
    return out


class TableCommit:
    def __init__(self, table: "FileStoreTable", expire_after_commit: bool = True):
        self.table = table
        self._commit = table.store.new_commit()
        self.expire_after_commit = expire_after_commit

    def commit_messages(self, identifier: int, messages: list[CommitMessage], watermark: int | None = None) -> list[int]:
        c = ManifestCommittable(identifier, watermark=watermark, messages=messages)
        if identifier != BatchWriteBuilder.COMMIT_IDENTIFIER:
            # streaming identifiers are monotonic per user: route through the
            # replay filter so a crash-retry with a rebuilt committable (same
            # identifier) cannot double-apply a phase that already landed
            remaining = self._commit.filter_committed([c])
            if not remaining:
                return []
            c = remaining[0]
        snapshot_ids = self._commit.commit(c)
        self._post_commit()
        return snapshot_ids

    def filter_and_commit(self, committables: list[ManifestCommittable]) -> int:
        """Replay-safe streaming commit (reference filterAndCommit): already-
        committed identifiers are skipped; returns #committed."""
        remaining = self._commit.filter_committed(committables)
        for c in sorted(remaining, key=lambda x: x.commit_identifier):
            self._commit.commit(c)
        if remaining:
            self._post_commit()
        return len(remaining)

    def overwrite(self, identifier: int, messages: list[CommitMessage], partition_filter=None) -> list[int]:
        c = ManifestCommittable(identifier, messages=messages)
        ids = self._commit.overwrite(c, partition_filter)
        self._post_commit()
        return ids

    def _post_commit(self) -> None:
        from ..options import CoreOptions

        snap = self.table.store.snapshot_manager.latest_snapshot()
        for fn in load_callbacks(self.table, CoreOptions.COMMIT_CALLBACKS):
            try:
                fn(self.table, snap)
            except Exception:
                pass  # callbacks must never fail a commit
        try:
            from .tags import TagAutoCreation

            TagAutoCreation(self.table).run()
        except Exception:
            pass  # tagging is maintenance
        if self.expire_after_commit:
            try:
                self.table.expire_snapshots()
            except Exception:
                pass  # expiry is maintenance, never fails a commit
            self._maybe_expire_partitions()

    def _maybe_expire_partitions(self) -> None:
        """Piggyback partition TTL sweeps on commits, rate-limited by
        partition.expiration-check-interval (reference PartitionExpire is
        wired into the committer the same way)."""
        from ..options import CoreOptions
        from ..utils import now_millis

        opts = self.table.options.options
        ttl = opts.get(CoreOptions.PARTITION_EXPIRATION_TIME_MS)
        if ttl is None or not self.table.partition_keys:
            return
        interval = opts.get(CoreOptions.PARTITION_EXPIRATION_CHECK_INTERVAL)
        now = now_millis()
        # rate-limit state lives on the STORE (one per table instance):
        # TableCommit objects are per-commit, so instance state here would
        # make the interval inert and put a full scan on every commit
        store = self.table.store
        last = getattr(store, "_last_partition_expire_check", 0)
        if now - last < (interval or 0):
            return
        store._last_partition_expire_check = now
        try:
            from .maintenance import expire_partitions

            # partition.timestamp-pattern picks the column ('$dt' form);
            # partition.timestamp-formatter is a strptime pattern here
            col_spec = opts.get(CoreOptions.PARTITION_TIMESTAMP_PATTERN)
            expire_partitions(
                self.table,
                ttl,
                time_col=col_spec.lstrip("$") if col_spec else None,
                pattern=opts.get(CoreOptions.PARTITION_TIMESTAMP_FORMATTER) or "%Y-%m-%d",
            )
        except Exception:
            pass  # maintenance must never fail the commit


class BatchWriteBuilder:
    """One-shot batch job: write() everything, then commit() once
    (identifier is fixed — batch jobs have a single commit)."""

    COMMIT_IDENTIFIER = BATCH_COMMIT_IDENTIFIER  # reference uses Long.MAX_VALUE

    def __init__(self, table: "FileStoreTable"):
        self.table = table
        self._overwrite = False
        self._partition_filter = None

    def with_overwrite(self, partition_filter=None) -> "BatchWriteBuilder":
        self._overwrite = True
        self._partition_filter = partition_filter
        return self

    def new_write(self) -> TableWrite:
        return TableWrite(self.table)

    def new_commit(self) -> "BatchTableCommit":
        return BatchTableCommit(self.table, self._overwrite, self._partition_filter)


class BatchTableCommit(TableCommit):
    def __init__(self, table: "FileStoreTable", overwrite: bool, partition_filter):
        super().__init__(table)
        self._overwrite = overwrite
        self._partition_filter = partition_filter

    def commit(self, messages: list[CommitMessage]) -> list[int]:
        from ..options import CoreOptions

        opts = self.table.options.options
        ident = BatchWriteBuilder.COMMIT_IDENTIFIER
        if self._overwrite:
            pf = self._partition_filter
            if pf is None and self.table.partition_keys and opts.get(CoreOptions.DYNAMIC_PARTITION_OVERWRITE):
                # dynamic mode (reference default): only the partitions the
                # new data touches are replaced, not the whole table
                touched = {m.partition for m in messages}
                pf = lambda p: p in touched  # noqa: E731
            return self.overwrite(ident, messages, pf)
        if not messages and not opts.get(CoreOptions.COMMIT_FORCE_CREATE_SNAPSHOT):
            return []  # reference batch commits ignore empty by default
        return self.commit_messages(ident, messages)


class StreamWriteBuilder:
    """Continuous ingestion: per-checkpoint identifiers, replay-safe commits."""

    def __init__(self, table: "FileStoreTable"):
        self.table = table

    def new_write(self) -> TableWrite:
        return TableWrite(self.table)

    def new_commit(self) -> TableCommit:
        return TableCommit(self.table)
