"""Rollback: move the table back to an earlier snapshot or tag.

Parity: /root/reference/paimon-core/.../table/RollbackHelper.java — delete
snapshots newer than the target, then purge files they referenced that the
target does not (so the rolled-back table is physically clean).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ..core.manifest import ManifestFile, ManifestList, merge_entries

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["rollback_to"]


def rollback_to(table: "FileStoreTable", target: "int | str") -> None:
    file_io = table.file_io
    sm = table.store.snapshot_manager
    if isinstance(target, str):
        from .tags import TagManager

        snap = TagManager(file_io, table.path).get(target)
        target_id = snap.id
        if not sm.snapshot_exists(target_id):
            # re-materialize the tagged snapshot as the table head
            file_io.try_atomic_write(sm.snapshot_path(target_id), snap.to_json().encode())
    else:
        target_id = target
    latest = sm.latest_snapshot_id()
    if latest is None or latest <= target_id:
        return
    if not sm.snapshot_exists(target_id):
        raise ValueError(f"rollback target snapshot {target_id} does not exist")

    manifest_file = ManifestFile(file_io, f"{table.path}/manifest")
    manifest_list = ManifestList(file_io, f"{table.path}/manifest")

    def live_set(snapshot_id: int):
        snap = sm.snapshot(snapshot_id)
        metas = manifest_list.read(snap.base_manifest_list) + manifest_list.read(snap.delta_manifest_list)
        entries = merge_entries(*(manifest_file.read(m.file_name) for m in metas))
        files = {(e.partition, e.bucket, e.file.file_name, e.file.extra_files) for e in entries}
        manifests = {m.file_name for m in metas} | {snap.base_manifest_list, snap.delta_manifest_list}
        return files, manifests

    keep_files, keep_manifests = live_set(target_id)
    # also keep anything referenced by snapshots older than the target
    # (they share manifests with the target's history) — only purge what is
    # exclusively reachable from the rolled-back snapshots
    drop_files: set = set()
    drop_manifests: set = set()
    for sid in range(target_id + 1, latest + 1):
        if not sm.snapshot_exists(sid):
            continue
        files, manifests = live_set(sid)
        drop_files |= files - keep_files
        drop_manifests |= manifests - keep_manifests
    earliest = sm.earliest_snapshot_id() or target_id
    for sid in range(earliest, target_id):
        if sm.snapshot_exists(sid):
            files, manifests = live_set(sid)
            drop_files -= files
            drop_manifests -= manifests

    from ..utils.cache import (
        invalidate_data_file,
        invalidate_latest_pointer,
        invalidate_manifest_path,
        invalidate_snapshot,
    )

    for partition, bucket, name, extra in drop_files:
        bucket_dir = table.store.bucket_dir(partition, bucket)
        file_io.delete(f"{bucket_dir}/{name}")
        invalidate_data_file(name)
        for x in extra:
            file_io.delete(f"{bucket_dir}/{x}")
    for name in drop_manifests:
        file_io.delete(f"{table.path}/manifest/{name}")
        invalidate_manifest_path(f"{table.path}/manifest/{name}")
    for sid in range(target_id + 1, latest + 1):
        file_io.delete(sm.snapshot_path(sid))
        # critical: future commits re-mint these ids with different content —
        # a stale cached snapshot would resurrect the rolled-back history
        invalidate_snapshot(table.path, sid)
    invalidate_latest_pointer(table.path)
    sm.commit_latest_hint(target_id)
