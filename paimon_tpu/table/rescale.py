"""Live dynamic-bucket rescale: rewrite a fixed-bucket table at a new bucket
count, committed as schema-(N+1) (``bucket`` option bump) plus ONE atomic
OVERWRITE snapshot.

The rewrite is a mesh repartition: every old bucket's merged rows are
clustered by their NEW bucket id through the same distributed clustering
sort the sort-compact path uses (`mesh_cluster_permutation`, PR 7), so the
per-new-bucket row order is deterministic and bit-identical between the
single-process path here and the cross-worker path in
``service/cluster.py`` (where each worker rewrites only the old buckets it
owns and ships the CommitMessages to the coordinator).

Protocol, shared by both paths:

1. pin a snapshot S (the latest at rescale start);
2. read each old bucket's merged rows (deletes dropped — the rewrite
   materializes the latest value per key), route every row to
   ``hash(key) % new_buckets`` and cluster rows by target bucket with the
   stable clustering permutation;
3. write the clustered rows through a TableWrite over a ``bucket=new``
   table copy (write-only: no inline compaction during the rewrite) —
   entries carry ``total_buckets=new``;
4. commit schema-(N+1) with ``bucket=new``, then commit one OVERWRITE
   snapshot that logically deletes every live pre-rescale entry and adds
   the rewritten files.

Readers pinned at <= S keep reading the old files — logically deleted but
on disk until snapshot expiry — so pre-rescale reads stay bit-identical;
readers planning after the OVERWRITE see only the new layout. Routing
atomicity between steps 4a and 4b is the caller's job: the cluster
coordinator epoch-fences every shipment for the whole window and only
republishes routes once both commits land; the single-process path is an
offline operation (the reference's rescale requires an offline INSERT
OVERWRITE for exactly this reason).

The rewrite reads go through the PR 1 data-file cache: the cache key is
content-addressed (uuid-unique file name, not bucket path), so surviving
files decoded by any earlier read — a serving query, a compaction — are
cache hits here instead of cold re-decodes.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterable, Sequence

import numpy as np

from ..core.manifest import CommitMessage, ManifestCommittable
from ..core.schema import SchemaChange, SchemaManager

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["rescale_messages", "commit_rescale", "rescale_table", "cluster_by_new_bucket"]


def cluster_by_new_bucket(table: "FileStoreTable", batch, new_buckets: int):
    """Stable-cluster `batch`'s rows by their new bucket id. Returns
    (clustered batch, new bucket ids aligned with the clustered batch).
    Uses the distributed clustering sort when the key mesh is live
    (`mesh_cluster_permutation` is bit-identical to the single-device
    stable sort by contract); falls back to the host stable argsort."""
    from .bucket import bucket_ids

    ids = bucket_ids(batch, table.schema.bucket_keys, new_buckets)
    perm = None
    try:
        from ..parallel.mesh_exec import mesh_cluster_permutation

        lanes = ids.astype(np.uint32).reshape(-1, 1)
        perm = mesh_cluster_permutation(lanes, table.store.options)
    except Exception:
        perm = None
    if perm is None:
        perm = np.argsort(ids, kind="stable")
    perm = np.asarray(perm, dtype=np.int64)
    return batch.take(perm), ids[perm]


def rescale_messages(
    table: "FileStoreTable",
    new_buckets: int,
    buckets: "Iterable[int] | None" = None,
    snapshot_id: "int | None" = None,
) -> tuple["int | None", list[CommitMessage], int]:
    """Rewrite the merged rows of `buckets` (default: every bucket) of the
    pinned snapshot at `new_buckets`. Returns (pinned snapshot id,
    CommitMessages with total_buckets=new, rows rewritten). Pure rewrite —
    nothing is committed; the caller (coordinator or `rescale_table`) owns
    the commit."""
    if new_buckets < 1:
        raise ValueError(f"new bucket count must be >= 1, got {new_buckets}")
    store = table.store
    if store.options.bucket < 1:
        raise ValueError("cross-bucket rescale applies to fixed-bucket tables (dynamic tables assign per key)")
    scan = store.new_scan()
    if snapshot_id is not None:
        scan = scan.with_snapshot(snapshot_id)
    plan = scan.plan()
    sid = plan.snapshot.id if plan.snapshot else None
    want = None if buckets is None else set(int(b) for b in buckets)

    from ..core.deletionvectors import DeletionVectorsIndexFile

    dv_io = DeletionVectorsIndexFile(table.file_io, table.path)
    target = table.copy({"bucket": str(new_buckets), "write-only": "true"})
    from .write import TableWrite

    tw = TableWrite(target)
    rows = 0
    try:
        for partition, pbuckets in sorted(plan.grouped().items()):
            for bucket, files in sorted(pbuckets.items()):
                if want is not None and bucket not in want:
                    continue
                dv_index = plan.dv_index_for(partition, bucket)
                dvs = dv_io.read_all(dv_index) if dv_index else None
                batch = store.read_bucket(partition, bucket, files, drop_delete=True, deletion_vectors=dvs)
                if batch.num_rows == 0:
                    continue
                clustered, _ = cluster_by_new_bucket(table, batch, new_buckets)
                tw.write(clustered)
                rows += clustered.num_rows
        msgs = tw.prepare_commit()
        from ..resilience.faults import crash_point

        crash_point("rescale:files-written")
    finally:
        tw.close()
    return sid, msgs, rows


def commit_rescale(
    table: "FileStoreTable",
    new_buckets: int,
    messages: Sequence[CommitMessage],
    commit_identifier: "int | None" = None,
) -> "int | None":
    """Commit half: schema bump to ``bucket=new`` then ONE OVERWRITE snapshot
    replacing every live entry with the rewritten files. Returns the
    OVERWRITE snapshot id."""
    from ..core.commit import BATCH_COMMIT_IDENTIFIER

    SchemaManager(table.file_io, str(table.path)).commit_changes(
        SchemaChange.set_option("bucket", str(new_buckets))
    )
    # commit through a table reloaded AT the bumped schema: the OVERWRITE
    # snapshot must record the new schema id — serving queries resolve
    # their probe-routing bucket count from the planned snapshot's schema,
    # so a snapshot carrying new-layout files under the old schema id would
    # mis-route every get until the next commit
    from . import load_table

    fresh = load_table(str(table.path), commit_user=table.store.commit_user)
    ident = commit_identifier if commit_identifier is not None else BATCH_COMMIT_IDENTIFIER
    sids = fresh.store.new_commit().overwrite(ManifestCommittable(ident, messages=list(messages)))
    return sids[-1] if sids else None


def rescale_table(table: "FileStoreTable", new_buckets: int) -> "FileStoreTable":
    """Single-process rescale: rewrite every bucket, commit, and return the
    reloaded table at the new bucket count. Offline operation — no rival
    writers may commit during the window (the cluster path in
    service/cluster.py fences them instead)."""
    _, msgs, _ = rescale_messages(table, new_buckets)
    commit_rescale(table, new_buckets, msgs)
    from . import load_table

    return load_table(str(table.path), commit_user=table.store.commit_user)
