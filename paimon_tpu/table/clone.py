"""Snapshot-consistent table clone.

Parity: /root/reference/paimon-flink/paimon-flink-common/.../flink/clone/
(CloneSourceBuilder.java, PickFilesUtil.java, CopyFileOperator.java,
SnapshotHintOperator.java) and action/CloneAction.java — clone the LATEST
snapshot of a table (or every table of a database / the whole warehouse)
into a target catalog by copying exactly the files that snapshot references.

Design differences from the reference (which runs a 4-operator Flink DAG):
the pick/copy/hint stages are plain functions driven by a thread pool; the
retry-on-expiry loop (reference PickFilesUtil.retryReadingFiles:3 tries)
becomes re-picking from the current latest snapshot when a referenced file
vanished mid-copy — same net semantics: the clone lands on a consistent
snapshot that existed during the run.

Copy order follows the reference comment (PickFilesUtil: newest data files
first, because they are the ones snapshot expiry deletes soonest).
"""

from __future__ import annotations

import concurrent.futures as cf
from typing import TYPE_CHECKING

from ..utils import partition_path

if TYPE_CHECKING:
    from ..catalog import Catalog
    from . import FileStoreTable

__all__ = ["pick_files", "clone_table", "clone_database", "clone_warehouse"]


def _stats_dir_files(table: "FileStoreTable", snap) -> list[str]:
    return [f"statistics/{snap.statistics}"] if snap.statistics else []


def pick_files(table: "FileStoreTable", snapshot_id: int | None = None):
    """(snapshot, [(source_abs_path, target_rel_path), ...]) referenced by
    the snapshot: manifest lists, every manifest file, index manifest + index
    files, statistics, data files (+ sidecars), all schemas. Data paths come
    from store.bucket_dir so a BRANCH table (data shared with the main tree,
    metadata branch-local) clones into a standalone table. The snapshot file
    itself is NOT in the list — clone_table writes its JSON directly, which
    also lets a tag whose snapshot/ file already expired be cloned (the tag
    file carries the full snapshot).

    Reference: PickFilesUtil.getUsedFilesForLatestSnapshot — same closure,
    newest-first data ordering."""
    from ..core.manifest import FileKind, ManifestFile, ManifestList, merge_entries
    from ..core.schema import SchemaManager

    sm = table.store.snapshot_manager
    sid = snapshot_id if snapshot_id is not None else sm.latest_snapshot_id()
    if sid is None:
        raise ValueError(f"table {table.path} has no snapshot to clone")
    try:
        snap = sm.snapshot(sid)
    except FileNotFoundError:
        from .tags import TagManager

        tm = TagManager(table.file_io, table.path)
        pinned = [t for t, s in tm.list_tags().items() if s == sid]
        if not pinned:
            raise
        snap = tm.get(pinned[0])

    rel: list[str] = []
    for ml in (snap.base_manifest_list, snap.delta_manifest_list, snap.changelog_manifest_list):
        if ml:
            rel.append(f"manifest/{ml}")
    if snap.index_manifest:
        rel.append(f"manifest/{snap.index_manifest}")
        from ..core.deletionvectors import DeletionVectorsIndexFile
        from ..core.indexmanifest import read_index_manifest

        dv_io = DeletionVectorsIndexFile(table.file_io, table.path)
        for e in read_index_manifest(table.file_io, table.path, snap.index_manifest):
            if e.kind == "DELETION_VECTORS":
                rel += [f"index/{n}" for n in dv_io.chain_names(e.file_name)]
            else:
                rel.append(f"index/{e.file_name}")
    rel += _stats_dir_files(table, snap)

    manifest_dir = f"{table.path}/manifest"
    ml_reader = ManifestList(table.file_io, manifest_dir)
    mf = ManifestFile(table.file_io, manifest_dir)
    metas = ml_reader.read(snap.base_manifest_list) + ml_reader.read(snap.delta_manifest_list)
    rel += [f"manifest/{m.file_name}" for m in metas]

    # live data files via the merged manifest view
    entries = []
    per_manifest = [mf.read(m.file_name) for m in metas]
    for e in merge_entries(*per_manifest):
        if e.kind == FileKind.ADD:
            entries.append(e)
    # changelog manifests + the changelog files they reference (a changelog
    # scan on the clone must work; see core/scan.py kind=="changelog")
    if snap.changelog_manifest_list:
        cl_metas = ml_reader.read(snap.changelog_manifest_list)
        rel += [f"manifest/{m.file_name}" for m in cl_metas]
        for m in cl_metas:
            entries += [e for e in mf.read(m.file_name) if e.kind == FileKind.ADD]
    pairs = [(f"{table.path}/{r}", r) for r in rel]
    # newest first: latest-partition files are the ones expiry deletes first
    entries.sort(key=lambda e: e.file.creation_time_millis, reverse=True)
    for e in entries:
        pp = partition_path(table.partition_keys, e.partition)
        rel_base = f"{pp}/bucket-{e.bucket}" if pp else f"bucket-{e.bucket}"
        src_base = table.store.bucket_dir(e.partition, e.bucket)
        for name in (e.file.file_name, *e.file.extra_files):
            pairs.append((f"{src_base}/{name}", f"{rel_base}/{name}"))

    for schema_id in SchemaManager(table.file_io, table.path)._listed_ids():
        r = f"schema/schema-{schema_id}"
        pairs.append((f"{table.path}/{r}", r))
    return snap, list(dict.fromkeys(pairs))  # dedupe, keep order


def _copy_one(src_io, dst_io, dst_root: str, pair: tuple[str, str]) -> bool:
    """Copy one file; False when the source vanished (snapshot expired)."""
    src, rel = pair
    try:
        data = src_io.read_bytes(src)
    except (FileNotFoundError, OSError):
        return False  # vanished (snapshot expired under the copy)
    # idempotent: a retry attempt re-copies over its own partial first pass
    dst_io.try_overwrite(f"{dst_root}/{rel}", data)
    return True


def clone_table(
    source: "FileStoreTable",
    target_catalog: "Catalog",
    target_identifier: str,
    snapshot_id: int | None = None,
    parallelism: int = 8,
    max_retries: int = 3,
) -> "FileStoreTable":
    """Clone `source`'s snapshot into `target_catalog` as `target_identifier`.

    snapshot_id=None clones the latest (reference CloneAction semantics); a
    pinned snapshot_id (e.g. a tag's) clones that exact snapshot — combine
    with `branch_table()`/`TagManager.snapshot_id()` to clone a branch or tag.
    Retries with a fresh latest snapshot when files vanish under the copy
    (only in latest mode; a pinned snapshot that expired is an error)."""
    from ..catalog import Identifier

    ident = Identifier.parse(target_identifier) if isinstance(target_identifier, str) else target_identifier
    target_catalog.create_database(ident.database, ignore_if_exists=True)
    dst_root = target_catalog.table_path(ident)
    dst_io = getattr(target_catalog, "file_io", source.file_io)

    pinned = snapshot_id is not None
    last_missing: str | None = None
    for _attempt in range(max_retries):
        snap, pairs = pick_files(source, snapshot_id)
        ok = True
        with cf.ThreadPoolExecutor(max_workers=max(1, parallelism)) as pool:
            for pair, copied in zip(
                pairs,
                pool.map(lambda p: _copy_one(source.file_io, dst_io, dst_root, p), pairs),
            ):
                if not copied:
                    ok, last_missing = False, pair[0]
                    break
        if ok:
            # snapshot file + hints last (reference SnapshotHintOperator): a
            # reader of the target only sees the table once the copy is done
            from ..core.snapshot import SnapshotManager

            tsm = SnapshotManager(dst_io, dst_root)
            existing = tsm.latest_snapshot_id()
            if existing is not None and existing != snap.id:
                # only a re-clone of the same snapshot is idempotent-safe;
                # anything else would intermix two tables' files/hints
                raise RuntimeError(
                    f"target {dst_root} already has snapshot {existing} != cloned "
                    f"{snap.id}; refusing to clone over an existing table"
                )
            dst_io.try_overwrite(tsm.snapshot_path(snap.id), snap.to_json().encode())
            tsm.commit_earliest_hint(snap.id)
            tsm.commit_latest_hint(snap.id)
            return target_catalog.get_table(ident)
        if pinned:
            break
    raise RuntimeError(
        f"clone of {source.path} failed after {max_retries} attempts: "
        f"{last_missing!r} vanished during copy (snapshot expired mid-clone?)"
    )


def clone_database(
    source_catalog: "Catalog",
    database: str,
    target_catalog: "Catalog",
    target_database: str | None = None,
    parallelism: int = 8,
) -> list[str]:
    """Clone every table of a database (reference CloneSourceBuilder.java:
    empty table name => whole database). Returns cloned identifiers."""
    target_database = target_database or database
    out = []
    for name in source_catalog.list_tables(database):
        t = source_catalog.get_table(f"{database}.{name}")
        if t.store.snapshot_manager.latest_snapshot_id() is None:
            continue  # empty table: nothing to clone (reference skips too)
        clone_table(t, target_catalog, f"{target_database}.{name}", parallelism=parallelism)
        out.append(f"{target_database}.{name}")
    return out


def clone_warehouse(
    source_catalog: "Catalog", target_catalog: "Catalog", parallelism: int = 8
) -> list[str]:
    """Clone every database (reference: empty database => whole warehouse)."""
    out = []
    for db in source_catalog.list_databases():
        out += clone_database(source_catalog, db, target_catalog, parallelism=parallelism)
    return out
