"""Branches: independent snapshot lineages sharing data files.

Parity: /root/reference/paimon-core/.../utils/BranchManager.java — a branch
lives under table/branch/branch-<name>/ with its own snapshot/ and schema/
dirs (data + manifest files are shared with main, since they are immutable);
create from a tag/snapshot, delete, and fast-forward main to a branch.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ..core.schema import SchemaManager
from ..core.snapshot import Snapshot, SnapshotManager
from ..fs import FileIO

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["BranchManager", "branch_table"]


class BranchManager:
    def __init__(self, file_io: FileIO, table_path: str):
        self.file_io = file_io
        self.table_path = table_path
        self.branch_root = f"{table_path}/branch"

    def branch_path(self, name: str) -> str:
        return f"{self.branch_root}/branch-{name}"

    def create(self, name: str, from_snapshot: int | None = None, from_tag: str | None = None) -> None:
        if self.file_io.exists(self.branch_path(name)):
            raise ValueError(f"branch {name!r} already exists")
        sm = SnapshotManager(self.file_io, self.table_path)
        if from_tag is not None:
            from .tags import TagManager

            snap = TagManager(self.file_io, self.table_path).get(from_tag)
        else:
            sid = from_snapshot if from_snapshot is not None else sm.latest_snapshot_id()
            if sid is None:
                snap = None
            else:
                snap = sm.snapshot(sid)
        bp = self.branch_path(name)
        # copy the schema lineage (schemas are tiny; data files stay shared)
        schema_manager = SchemaManager(self.file_io, self.table_path)
        for sid_, ts in schema_manager.all_schemas().items():
            if snap is None or sid_ <= snap.schema_id:
                self.file_io.write_bytes(f"{bp}/schema/schema-{sid_}", ts.to_json().encode())
        if snap is not None:
            self._copy_metadata(snap, bp)
            self.file_io.write_bytes(f"{bp}/snapshot/snapshot-{snap.id}", snap.to_json().encode())
            bsm = SnapshotManager(self.file_io, bp)
            bsm.commit_latest_hint(snap.id)
            bsm.commit_earliest_hint(snap.id)
        self.file_io.write_bytes(f"{bp}/CREATED_FROM", str(snap.id if snap else -1).encode())

    def _copy_metadata(self, snap: Snapshot, dst: str, src: str | None = None) -> None:
        """Copy a snapshot's manifest tree + index files between metadata
        roots (data files stay shared — they are immutable and resolved
        through the main bucket dirs)."""
        from ..core.manifest import ManifestList

        src = src or self.table_path
        ml = ManifestList(self.file_io, f"{src}/manifest")
        names: set[str] = set()
        for lst in (snap.base_manifest_list, snap.delta_manifest_list, snap.changelog_manifest_list):
            if not lst:
                continue
            names.add(lst)
            for meta in ml.read(lst):
                names.add(meta.file_name)
        if snap.index_manifest:
            names.add(snap.index_manifest)
            from ..core.indexmanifest import read_index_manifest

            for e in read_index_manifest(self.file_io, src, snap.index_manifest):
                self._copy_file(f"{src}/index/{e.file_name}", f"{dst}/index/{e.file_name}")
        for n in names:
            self._copy_file(f"{src}/manifest/{n}", f"{dst}/manifest/{n}")

    def _copy_file(self, src: str, dst: str) -> None:
        if not self.file_io.exists(dst):
            self.file_io.write_bytes(dst, self.file_io.read_bytes(src))

    def delete(self, name: str) -> None:
        self.file_io.delete(self.branch_path(name), recursive=True)
        # a recreated branch of the same name re-mints snapshot ids
        from ..utils.cache import invalidate_table_path

        invalidate_table_path(self.branch_path(name))

    def created_from(self, name: str) -> int | None:
        try:
            v = int(self.file_io.read_text(f"{self.branch_path(name)}/CREATED_FROM"))
            return None if v < 0 else v
        except Exception:
            return None

    def list_branches(self) -> list[str]:
        out = []
        for st in self.file_io.list_status(self.branch_root):
            base = st.path.rsplit("/", 1)[-1]
            if st.is_dir and base.startswith("branch-"):
                out.append(base[len("branch-") :])
        return sorted(out)

    def fast_forward(self, name: str) -> None:
        """Make main's head the branch's head (reference fastForward): copies
        the branch's snapshots/schemas above main's latest back into main."""
        bp = self.branch_path(name)
        bsm = SnapshotManager(self.file_io, bp)
        main_sm = SnapshotManager(self.file_io, self.table_path)
        b_latest = bsm.latest_snapshot_id()
        if b_latest is None:
            return
        main_latest = main_sm.latest_snapshot_id() or 0
        # main must not have diverged past the branch point
        for sid in range(bsm.earliest_snapshot_id() or b_latest, b_latest + 1):
            if bsm.snapshot_exists(sid) and not main_sm.snapshot_exists(sid):
                snap = bsm.snapshot(sid)
                self._copy_metadata(snap, self.table_path, src=bp)
                self.file_io.try_atomic_write(main_sm.snapshot_path(sid), snap.to_json().encode())
        bschemas = SchemaManager(self.file_io, bp)
        mschemas = SchemaManager(self.file_io, self.table_path)
        for sid_, ts in bschemas.all_schemas().items():
            if not self.file_io.exists(mschemas.schema_path(sid_)):
                self.file_io.write_bytes(mschemas.schema_path(sid_), ts.to_json().encode())
        main_sm.commit_latest_hint(max(b_latest, main_latest))


def branch_table(table: "FileStoreTable", name: str) -> "FileStoreTable":
    """A Table view rooted at the branch directory. Data file paths are
    resolved relative to the MAIN table (files are shared), so the branch
    store overrides bucket_dir back to the main tree."""
    from . import FileStoreTable

    bm = BranchManager(table.file_io, table.path)
    bp = bm.branch_path(name)
    if not table.file_io.exists(bp):
        raise ValueError(f"branch {name!r} does not exist")
    schema = SchemaManager(table.file_io, bp).latest() or table.schema
    bt = FileStoreTable(table.file_io, bp, schema, table.store.commit_user)
    main_store = table.store

    def shared_bucket_dir(partition: tuple, bucket: int) -> str:
        return main_store.bucket_dir(partition, bucket)

    bt.store.bucket_dir = shared_bucket_dir  # type: ignore[method-assign]
    return bt
