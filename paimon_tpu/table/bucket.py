"""Row -> (partition, bucket) routing.

Parity: /root/reference/paimon-core/.../table/sink/ — RowKeyExtractor /
FixedBucketRowKeyExtractor (hash(bucket key) % numBuckets) and
ChannelComputer. Hashing is the vectorized splitmix64 used by the bloom
index; routing a batch is a handful of numpy ops, not a per-row loop.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..data.batch import ColumnBatch
from ..format.fileindex import _hash64

__all__ = ["bucket_ids", "group_by_partition_bucket"]


def key_hashes(batch: ColumnBatch, key_names: Sequence[str]) -> np.ndarray:
    """(n,) uint64 combined hash of the key columns. Columns carrying a
    full-length dict_cache hash their POOL once and gather through the codes
    (elementwise hashing commutes with the gather — bit-identical to hashing
    the expanded values), so routing and key-bloom construction on the write
    path never materialize strings out of the code domain."""
    from ..ops.dicts import cache_usable

    h = np.zeros(batch.num_rows, dtype=np.uint64)
    for name in key_names:
        col = batch.column(name)
        if cache_usable(col) and col.validity is None:
            pool, codes = col.dict_cache
            hv = _hash64(pool)[codes] if len(pool) else np.zeros(len(col), dtype=np.uint64)
        else:
            hv = _hash64(col.values)
        h = h * np.uint64(0x100000001B3) ^ hv
    return h


def bucket_ids(batch: ColumnBatch, bucket_keys: Sequence[str], num_buckets: int) -> np.ndarray:
    """(n,) int32 bucket per row: combined column hashes mod num_buckets."""
    return (key_hashes(batch, bucket_keys) % np.uint64(num_buckets)).astype(np.int32)


def group_by_partition_bucket(
    batch: ColumnBatch,
    partition_keys: Sequence[str],
    bucket_keys: Sequence[str],
    num_buckets: int,
) -> list[tuple[tuple, int, np.ndarray]]:
    """[(partition, bucket, row_indices)] — vectorized group-by: per-column
    code factorization, one np.unique over combined codes."""
    n = batch.num_rows
    buckets = bucket_ids(batch, bucket_keys, num_buckets) if num_buckets > 1 else np.zeros(n, dtype=np.int32)
    if not partition_keys:
        out = []
        for b in np.unique(buckets):
            out.append(((), int(b), np.flatnonzero(buckets == b)))
        return out
    codes = buckets.astype(np.int64)
    uniques: list[np.ndarray] = []
    for name in partition_keys:
        vals = batch.column(name).values
        u, inv = np.unique(vals, return_inverse=True)
        uniques.append(u)
        codes = codes * np.int64(len(u)) + inv
    out = []
    for code in np.unique(codes):
        rows = np.flatnonzero(codes == code)
        r0 = rows[0]
        partition = tuple(
            v.item() if hasattr((v := batch.column(k).values[r0]), "item") else v for k in partition_keys
        )
        out.append((partition, int(buckets[r0]), rows))
    return out
