"""LocalTableQuery: point lookups against a table's current snapshot.

Parity: /root/reference/paimon-core/.../table/query/LocalTableQuery.java:55 —
the engine-side primitive behind lookup joins and the KV query service:
per-bucket LookupLevels over the latest snapshot's files, refreshed on
demand.

Two probe paths share the per-bucket state:
  * `lookup(partition, key)` — the scalar walk (LookupLevels): level-0
    newest-first, then each level's run by key range. Kept as the
    independent oracle the batched path is verified against.
  * `get_batch(keys)` — the serving fast path (table/get.py): N keys encode
    once, files prune via manifest key ranges + PTIX bloom key indexes with
    zero data IO, one vectorized probe per surviving file, winners resolved
    by sequence. `attach_write` adds the read-your-writes delta tier.

`refresh()` diffs the plan per bucket: a snapshot advance only rebuilds the
buckets whose (file set, deletion vectors) actually changed, so streaming
ingest into bucket 3 never evicts bucket 5's built lookup files or probe
indexes (cache-friendly under sustained commit churn).
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Sequence

from ..lookup import LookupFileCache, LookupLevels
from ..lookup.index import BucketGetIndex, GetResult

if TYPE_CHECKING:
    from . import FileStoreTable
    from .write import TableWrite

__all__ = ["LocalTableQuery", "execute_scan_fragment", "partition_agg_partial"]


def execute_scan_fragment(table: "FileStoreTable", frag: dict) -> dict:
    """Execute one distributed-SQL scan fragment (the sql.cluster protocol)
    against a local table: rebuild the shipped DataSplits, scan them with
    predicate + projection pushdown, then either stream row batches back per
    split (mode "rows") or segment-reduce the fragment into ONE partial
    aggregate on device (mode "agg" — ops.aggregates.segment_reduce keyed on
    dictionary codes, row positions offset by each split's global sequence
    number so the coordinator's combine reconstructs first-appearance order
    exactly). Returns a numpy-level payload; sql.cluster owns wire encoding.

    Fragment fields: splits [(seq, DataSplit.to_dict())...], projection,
    where (SQL text, re-lowered through the predicate algebra), mode,
    group_cols, kern (the shared _agg_kernel_plan output), limit, engine."""
    import numpy as np

    from ..sql.expr import parse_expr, to_predicate
    from .read import DataSplit

    splits = sorted(
        ((int(seq), DataSplit.from_dict(d)) for seq, d in frag["splits"]),
        key=lambda p: p[0],
    )
    rb = table.new_read_builder()
    if frag.get("where"):
        rb = rb.with_filter(to_predicate(parse_expr(frag["where"]), frag["where"]))
    if frag.get("projection") is not None:
        rb = rb.with_projection(list(frag["projection"]))
    read = rb.new_read()

    if frag.get("mode") != "agg":
        # non-aggregate: per-split row batches, Arrow-encoded by the caller.
        # A cumulative per-fragment LIMIT trim is safe: a row's global index
        # is never smaller than its fragment-local index.
        limit = frag.get("limit")
        out = []
        total = 0
        for seq, sp in splits:
            if limit is not None and total >= limit:
                break
            b = read.read_all([sp])
            if limit is not None and total + b.num_rows > limit:
                b = b.slice(0, limit - total)
            total += b.num_rows
            out.append((seq, b))
        return {"mode": "rows", "batches": out, "rows": total}

    from ..data.batch import concat_batches
    from ..metrics import sql_metrics
    from ..ops.aggregates import segment_reduce
    from ..sql import select as _sel

    batches = []
    positions = []
    for seq, sp in splits:
        b = read.read_all([sp])
        batches.append(b)
        # 2^40 rows per split keeps positions int64-exact and globally ordered
        positions.append(np.arange(b.num_rows, dtype=np.int64) + (seq << 40))
    batch = concat_batches(batches) if batches else None
    n = batch.num_rows if batch is not None else 0
    pos = (
        np.concatenate(positions)
        if positions
        else np.zeros(0, np.int64)
    )
    group_cols = list(frag.get("group_cols") or [])
    kern = [tuple(k) for k in frag.get("kern") or []]
    if n == 0:
        return {
            "mode": "agg",
            "pools": [np.empty(0, dtype=object) for _ in group_cols],
            "group_codes": [np.zeros(0, np.uint32) for _ in group_cols],
            "outs": [],
            "anyv": [],
            "first_pos": np.zeros(0, np.int64),
            "rows": 0,
            "rows_reduced_device": 0,
        }
    if group_cols:
        pools, codes_list, lanes = _sel._encode_group_lanes(batch, group_cols)
    else:
        # no GROUP BY: one synthetic constant lane — the whole fragment is
        # a single group and the coordinator combines the singletons
        pools, codes_list = [], []
        lanes = np.zeros((n, 1), np.uint32)
    cols, fns = _sel._kernel_columns(batch, kern)
    counter = sql_metrics().counter("rows_reduced_device")
    before = counter.count
    rep, outs, anyv, first_pos = segment_reduce(lanes, cols, fns, pos=pos, engine=frag.get("engine", "xla"))
    return {
        "mode": "agg",
        "pools": pools,
        "group_codes": [c[rep] for c in codes_list],
        "outs": outs,
        "anyv": anyv,
        "first_pos": first_pos,
        "rows": n,
        "rows_reduced_device": counter.count - before,
    }


def _prune_with_sentinel(pool, codes):
    """prune_pool for shuffle parts: codes may carry the NULL sentinel
    ``len(pool)``, which the generic prune would gather out of bounds.
    Returns (pruned pool, codes) with the sentinel re-seated at the pruned
    pool's length — the exact shape encode_partial/combine expect."""
    import numpy as np

    from ..ops.dicts import prune_pool

    n = len(pool)
    if n == 0:  # all rows NULL: sentinel is 0 before and after
        return pool, codes.astype(np.uint32, copy=False)
    valid = codes < n
    if bool(valid.all()):
        return prune_pool(pool, codes)
    used = np.zeros(n, dtype=np.bool_)
    used[codes[valid]] = True
    if bool(used.all()):
        p2, remap = pool, None
    else:
        remap = np.cumsum(used, dtype=np.int64) - 1
        p2 = pool[used]
    out = np.full(len(codes), len(p2), dtype=np.uint32)  # sentinel slots
    live = codes[valid].astype(np.int64, copy=False)
    out[valid] = (live if remap is None else remap[live]).astype(np.uint32)
    return p2, out


def partition_agg_partial(part: dict, num_parts: int) -> list:
    """Split one mode-"agg" fragment partial into `num_parts` shuffle parts
    by hashing group-key VALUES (ops.dicts.partition_rows), so every worker
    agrees on each key's range despite disjoint per-worker code spaces.
    Returns a list of length num_parts; entry i is a partial dict holding
    exactly the groups whose hash lands in range i (pools pruned to the
    part's referenced values — wire bytes scale ~1/R), or None when the
    fragment has no groups in that range (nothing is shipped for it).
    Disjointness by value means a range owner's combine is the final
    reduction for its groups; min-reducing first_pos inside each range
    preserves global first-appearance order."""
    import numpy as np

    from ..ops.dicts import partition_rows

    pools = part["pools"]
    codes_list = part["group_codes"]
    n = int(len(part["first_pos"]))
    if num_parts <= 1 or not pools:
        # no key columns (scalar agg) or degenerate R: everything is range 0
        return [part if n else None] + [None] * max(0, num_parts - 1)
    pids = partition_rows(pools, codes_list, num_parts)
    out = []
    for r in range(num_parts):
        mask = pids == np.uint32(r)
        cnt = int(mask.sum())
        if cnt == 0:
            out.append(None)
            continue
        sub_pools, sub_codes = [], []
        for p, c in zip(pools, codes_list):
            p2, c2 = _prune_with_sentinel(p, c[mask])
            sub_pools.append(p2)
            sub_codes.append(c2)
        out.append(
            {
                "mode": "agg",
                "pools": sub_pools,
                "group_codes": sub_codes,
                "outs": [o[mask] for o in part["outs"]],
                "anyv": [a[mask] for a in part["anyv"]],
                "first_pos": part["first_pos"][mask],
                "rows": cnt,
                "rows_reduced_device": 0,
            }
        )
    return out


class LocalTableQuery:
    def __init__(
        self, table: "FileStoreTable", cache_bytes: int | None = None, local_store_dir: str | None = None
    ):
        if not table.is_primary_key_table:
            raise ValueError("point lookup requires a primary-key table")
        self.table = table
        self.store = table.store
        from ..options import CoreOptions

        opts = self.store.options.options
        if cache_bytes is None:
            cache_bytes = int(opts.get(CoreOptions.LOOKUP_CACHE_MAX_MEMORY_SIZE))
        self.cache = LookupFileCache(cache_bytes)
        self._bloom_fpp = (
            opts.get(CoreOptions.LOOKUP_CACHE_BLOOM_FILTER_FPP)
            if opts.get(CoreOptions.LOOKUP_CACHE_BLOOM_FILTER_ENABLED)
            else None
        )
        self._hash_load_factor = opts.get(CoreOptions.LOOKUP_HASH_LOAD_FACTOR)
        self._max_disk_bytes = int(opts.get(CoreOptions.LOOKUP_CACHE_MAX_DISK_SIZE))
        self._file_retention_ms = opts.get(CoreOptions.LOOKUP_CACHE_FILE_RETENTION)
        self._bloom_prune = bool(opts.get(CoreOptions.LOOKUP_GET_BLOOM_PRUNE))
        self.local_store_dir = local_store_dir
        self._levels: dict[tuple, LookupLevels] = {}
        self._get_indexes: dict[tuple, BucketGetIndex] = {}
        self._bucket_sigs: dict[tuple, tuple] = {}
        self._delta_indexes: dict[tuple, tuple] = {}  # (pb) -> (file names, BucketGetIndex)
        self._write: "TableWrite | None" = None
        self._snapshot_id: int | None = None
        # probe-routing bucket count, kept consistent with the snapshot
        # being SERVED (not the construction-time options): after a live
        # rescale the plan's files carry the new layout while this query
        # object still holds the old schema — bucketizing probes with the
        # stale count would silently miss. refresh() re-resolves it from
        # the planned snapshot's schema.
        self._probe_buckets: int = max(self.store.options.bucket, 0)
        from ..core.schema import SchemaManager

        self._schemas = SchemaManager(self.table.file_io, str(self.table.path))
        self._follow_thread: threading.Thread | None = None
        self._follow_stop: threading.Event | None = None
        self._follow_sub = None
        self._follow_lock: threading.Lock | None = None
        self.refresh()

    def attach_write(self, table_write: "TableWrite | None") -> "LocalTableQuery":
        """Read-your-writes: gets additionally consult `table_write`'s live
        memtables and its flushed-but-uncommitted level-0 files, so a query
        colocated with an ingest job serves committed-plus-buffered state."""
        self._write = table_write
        self._delta_indexes.clear()
        return self

    def refresh(self, swap_lock: "threading.Lock | None" = None) -> None:
        """Re-plan against the latest snapshot (reference: file-change
        monitoring feeds refresh in the query service). Per-bucket diff:
        buckets whose file set + DV index are unchanged keep their built
        LookupLevels and BucketGetIndex; changed buckets carry the warm
        per-file probe indexes of files that persist.

        `swap_lock` is the serving-plane two-phase mode: the replacement
        state is built AND prewarmed without the lock — gets keep serving
        the previous snapshot — and only the dict swap happens under it.
        Without it (one-shot/constructor use) nothing is prewarmed: a
        non-serving query should only ever read the files it probes."""
        plan = self.store.new_scan().plan()
        sid = plan.snapshot.id if plan.snapshot else None
        if sid == self._snapshot_id:
            return
        probe_buckets = self._probe_buckets
        if plan.snapshot is not None and self.store.options.bucket > 0:
            try:
                sch = self._schemas.schema(plan.snapshot.schema_id)
                probe_buckets = int(sch.options.get("bucket", probe_buckets))
            except Exception:  # noqa: BLE001 — fall back to the last-known count
                pass
        from ..core.deletionvectors import DeletionVectorsIndexFile

        dv_io = DeletionVectorsIndexFile(self.table.file_io, self.table.path)
        seen: set[tuple] = set()
        staged: dict[tuple, tuple] = {}  # pb -> (levels, get_index, sig)
        stale_cache: list[str] = []
        for partition, buckets in plan.grouped().items():
            for bucket, files in buckets.items():
                pb = (partition, bucket)
                seen.add(pb)
                dv_index = plan.dv_index_for(partition, bucket)
                sig = (tuple(sorted((f.file_name, f.level) for f in files)), dv_index)
                if self._bucket_sigs.get(pb) == sig:
                    continue  # unchanged bucket: keep the warm state
                dvs = dv_io.read_all(dv_index) if dv_index else {}
                stale_cache += list(dvs)  # DV changed: cached rows stale
                levels = LookupLevels(
                    files,
                    self.store.reader_factory(partition, bucket),
                    self.store.key_names,
                    cache=self.cache,
                    deletion_vectors=dvs,
                    local_store_dir=self.local_store_dir,
                    file_io=self.table.file_io,
                    bloom_fpp=self._bloom_fpp,
                    hash_load_factor=self._hash_load_factor,
                    max_disk_bytes=self._max_disk_bytes,
                    file_retention_millis=self._file_retention_ms,
                )
                get_index = BucketGetIndex(
                    files,
                    self.store.reader_factory(partition, bucket),
                    self.store.key_names,
                    deletion_vectors=dvs,
                    bloom_prune=self._bloom_prune,
                    warm_from=self._get_indexes.get(pb),
                )
                if swap_lock is not None:
                    get_index.prewarm()
                staged[pb] = (levels, get_index, sig)
        import contextlib

        with swap_lock if swap_lock is not None else contextlib.nullcontext():
            for name in stale_cache:
                self.cache.invalidate(name)
            for pb, (levels, get_index, sig) in staged.items():
                self._levels[pb] = levels
                self._get_indexes[pb] = get_index
                self._bucket_sigs[pb] = sig
            for pb in list(self._levels):
                if pb not in seen:
                    del self._levels[pb]
                    self._get_indexes.pop(pb, None)
                    self._bucket_sigs.pop(pb, None)
            self._snapshot_id = sid
            self._probe_buckets = probe_buckets

    # ---- subscription-driven refresh ------------------------------------
    def follow(self, hub=None, lock: "threading.Lock | None" = None) -> "LocalTableQuery":
        """Subscription-driven refresh (the PR 13/14 declared follow-up):
        instead of callers invoking refresh() per request, a hub
        subscription (one shared decode-once tailer per table —
        service.subscription.SubscriptionHub) signals every new snapshot
        and refresh()'s existing per-bucket diff invalidates/rebuilds ONLY
        the touched buckets. Compaction-only snapshots carry no changelog
        rows, so the follower also compares the latest snapshot id on each
        poll timeout — refresh() no-ops when nothing advanced.

        `lock` (optional) serializes refresh against concurrent gets; pass
        the same lock the serving layer wraps get_batch with (the cluster
        worker serving plane and KvQueryServer do). Stop with unfollow()."""
        if self._follow_thread is not None:
            return self
        from ..service.subscription import SubscriptionHub
        from ..utils import new_file_name

        hub = hub if hub is not None else SubscriptionHub.for_table(self.table)
        self._follow_lock = lock if lock is not None else threading.Lock()
        self._follow_stop = threading.Event()
        # ephemeral consumer id, deleted on unfollow: a refresher must not
        # pin snapshot expiry after it is gone
        self._follow_sub = hub.subscribe(consumer_id=f"qryref-{new_file_name('c')}")
        stop, sub, flock = self._follow_stop, self._follow_sub, self._follow_lock

        def _loop():
            while not stop.is_set():
                advanced = False
                try:
                    batch = sub.poll(timeout=0.2)
                    advanced = batch is not None
                except Exception:
                    # shed or hub teardown: fall back to snapshot-id polling
                    # (refresh() keeps working without the signal)
                    stop.wait(0.2)
                try:
                    if advanced or (
                        self.store.snapshot_manager.latest_snapshot_id() != self._snapshot_id
                    ):
                        # two-phase: build + prewarm outside the serving
                        # lock, swap under it — a snapshot advance must not
                        # head-of-line-block the gets it races with
                        self.refresh(swap_lock=flock)
                except Exception:
                    pass  # transient plan/IO failure: retried next poll

        self._follow_thread = threading.Thread(
            target=_loop, name=f"paimon-qryref-{id(self) & 0xFFFF:x}", daemon=False
        )
        self._follow_thread.start()
        return self

    def unfollow(self) -> None:
        """Stop the subscription-driven refresher and release its consumer
        pin. Safe to call when follow() was never started."""
        t, self._follow_thread = self._follow_thread, None
        if self._follow_stop is not None:
            self._follow_stop.set()
        if t is not None:
            t.join(timeout=30.0)
        sub, self._follow_sub = self._follow_sub, None
        if sub is not None:
            try:
                sub.close(delete_consumer=True)
            except Exception:
                pass

    def close(self) -> None:
        self.unfollow()

    # ---- batched path ---------------------------------------------------
    def get_batch(self, keys, partition: tuple = ()) -> GetResult:
        """Vectorized primary-key gets: `keys` is a sequence of key tuples
        (or scalars for single-column keys), a {column: values} mapping, or
        a ColumnBatch carrying the key columns. Returns a GetResult aligned
        with the probe keys; `to_pylist()` matches a scalar lookup() loop
        entry for entry."""
        from .get import batch_get

        return batch_get(self, keys, partition)

    # ---- scalar path (the oracle) ---------------------------------------
    def lookup(self, partition: tuple, key: "tuple | object"):
        """Latest value row for `key` (a tuple over the trimmed primary key,
        or a scalar for single-column keys); None if absent/deleted."""
        if not isinstance(key, tuple):
            key = (key,)
        # route to the right bucket: fixed-bucket tables hash the key;
        # dynamic tables may hold the key in any bucket — probe all
        candidates: Sequence[tuple] = [
            pb for pb in self._levels if pb[0] == partition
        ]
        if self._probe_buckets > 0:
            from ..data.batch import ColumnBatch
            from .bucket import bucket_ids

            key_schema = self.store.value_schema.project(self.store.key_names)
            probe = ColumnBatch.from_pydict(key_schema, {k: [v] for k, v in zip(self.store.key_names, key)})
            # _probe_buckets, NOT options.bucket: routing must match the
            # layout of the snapshot being served (see refresh)
            b = int(bucket_ids(probe, self.table.schema.bucket_keys, self._probe_buckets)[0])
            candidates = [(partition, b)] if (partition, b) in self._levels else []
        for pb in candidates:
            out = self._levels[pb].lookup(key)
            if out is not None:
                return out
        return None
