"""LocalTableQuery: point lookups against a table's current snapshot.

Parity: /root/reference/paimon-core/.../table/query/LocalTableQuery.java:55 —
the engine-side primitive behind lookup joins and the KV query service:
per-bucket LookupLevels over the latest snapshot's files, refreshed on
demand.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Sequence

from ..lookup import LookupFileCache, LookupLevels

if TYPE_CHECKING:
    from . import FileStoreTable

__all__ = ["LocalTableQuery"]


class LocalTableQuery:
    def __init__(
        self, table: "FileStoreTable", cache_bytes: int | None = None, local_store_dir: str | None = None
    ):
        if not table.is_primary_key_table:
            raise ValueError("point lookup requires a primary-key table")
        self.table = table
        self.store = table.store
        from ..options import CoreOptions

        opts = self.store.options.options
        if cache_bytes is None:
            cache_bytes = int(opts.get(CoreOptions.LOOKUP_CACHE_MAX_MEMORY_SIZE))
        self.cache = LookupFileCache(cache_bytes)
        self._bloom_fpp = (
            opts.get(CoreOptions.LOOKUP_CACHE_BLOOM_FILTER_FPP)
            if opts.get(CoreOptions.LOOKUP_CACHE_BLOOM_FILTER_ENABLED)
            else None
        )
        self._hash_load_factor = opts.get(CoreOptions.LOOKUP_HASH_LOAD_FACTOR)
        self._max_disk_bytes = int(opts.get(CoreOptions.LOOKUP_CACHE_MAX_DISK_SIZE))
        self._file_retention_ms = opts.get(CoreOptions.LOOKUP_CACHE_FILE_RETENTION)
        self.local_store_dir = local_store_dir
        self._levels: dict[tuple, LookupLevels] = {}
        self._snapshot_id: int | None = None
        self.refresh()

    def refresh(self) -> None:
        """Re-plan against the latest snapshot (reference: file-change
        monitoring feeds refresh in the query service)."""
        plan = self.store.new_scan().plan()
        sid = plan.snapshot.id if plan.snapshot else None
        if sid == self._snapshot_id:
            return
        self._snapshot_id = sid
        self._levels.clear()
        from ..core.deletionvectors import DeletionVectorsIndexFile

        dv_io = DeletionVectorsIndexFile(self.table.file_io, self.table.path)
        for partition, buckets in plan.grouped().items():
            for bucket, files in buckets.items():
                dv_index = plan.dv_index_for(partition, bucket)
                dvs = dv_io.read_all(dv_index) if dv_index else {}
                for name in dvs:
                    self.cache.invalidate(name)  # DV changed: cached rows stale
                self._levels[(partition, bucket)] = LookupLevels(
                    files,
                    self.store.reader_factory(partition, bucket),
                    self.store.key_names,
                    cache=self.cache,
                    deletion_vectors=dvs,
                    local_store_dir=self.local_store_dir,
                    file_io=self.table.file_io,
                    bloom_fpp=self._bloom_fpp,
                    hash_load_factor=self._hash_load_factor,
                    max_disk_bytes=self._max_disk_bytes,
                    file_retention_millis=self._file_retention_ms,
                )

    def lookup(self, partition: tuple, key: "tuple | object"):
        """Latest value row for `key` (a tuple over the trimmed primary key,
        or a scalar for single-column keys); None if absent/deleted."""
        if not isinstance(key, tuple):
            key = (key,)
        # route to the right bucket: fixed-bucket tables hash the key;
        # dynamic tables may hold the key in any bucket — probe all
        candidates: Sequence[tuple] = [
            pb for pb in self._levels if pb[0] == partition
        ]
        if self.store.options.bucket > 0:
            from ..data.batch import ColumnBatch
            from .bucket import bucket_ids

            key_schema = self.store.value_schema.project(self.store.key_names)
            probe = ColumnBatch.from_pydict(key_schema, {k: [v] for k, v in zip(self.store.key_names, key)})
            b = int(bucket_ids(probe, self.table.schema.bucket_keys, self.store.options.bucket)[0])
            candidates = [(partition, b)] if (partition, b) in self._levels else []
        for pb in candidates:
            out = self._levels[pb].lookup(key)
            if out is not None:
                return out
        return None
