"""LocalTableQuery: point lookups against a table's current snapshot.

Parity: /root/reference/paimon-core/.../table/query/LocalTableQuery.java:55 —
the engine-side primitive behind lookup joins and the KV query service:
per-bucket LookupLevels over the latest snapshot's files, refreshed on
demand.

Two probe paths share the per-bucket state:
  * `lookup(partition, key)` — the scalar walk (LookupLevels): level-0
    newest-first, then each level's run by key range. Kept as the
    independent oracle the batched path is verified against.
  * `get_batch(keys)` — the serving fast path (table/get.py): N keys encode
    once, files prune via manifest key ranges + PTIX bloom key indexes with
    zero data IO, one vectorized probe per surviving file, winners resolved
    by sequence. `attach_write` adds the read-your-writes delta tier.

`refresh()` diffs the plan per bucket: a snapshot advance only rebuilds the
buckets whose (file set, deletion vectors) actually changed, so streaming
ingest into bucket 3 never evicts bucket 5's built lookup files or probe
indexes (cache-friendly under sustained commit churn).
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Sequence

from ..lookup import LookupFileCache, LookupLevels
from ..lookup.index import BucketGetIndex, GetResult

if TYPE_CHECKING:
    from . import FileStoreTable
    from .write import TableWrite

__all__ = ["LocalTableQuery"]


class LocalTableQuery:
    def __init__(
        self, table: "FileStoreTable", cache_bytes: int | None = None, local_store_dir: str | None = None
    ):
        if not table.is_primary_key_table:
            raise ValueError("point lookup requires a primary-key table")
        self.table = table
        self.store = table.store
        from ..options import CoreOptions

        opts = self.store.options.options
        if cache_bytes is None:
            cache_bytes = int(opts.get(CoreOptions.LOOKUP_CACHE_MAX_MEMORY_SIZE))
        self.cache = LookupFileCache(cache_bytes)
        self._bloom_fpp = (
            opts.get(CoreOptions.LOOKUP_CACHE_BLOOM_FILTER_FPP)
            if opts.get(CoreOptions.LOOKUP_CACHE_BLOOM_FILTER_ENABLED)
            else None
        )
        self._hash_load_factor = opts.get(CoreOptions.LOOKUP_HASH_LOAD_FACTOR)
        self._max_disk_bytes = int(opts.get(CoreOptions.LOOKUP_CACHE_MAX_DISK_SIZE))
        self._file_retention_ms = opts.get(CoreOptions.LOOKUP_CACHE_FILE_RETENTION)
        self._bloom_prune = bool(opts.get(CoreOptions.LOOKUP_GET_BLOOM_PRUNE))
        self.local_store_dir = local_store_dir
        self._levels: dict[tuple, LookupLevels] = {}
        self._get_indexes: dict[tuple, BucketGetIndex] = {}
        self._bucket_sigs: dict[tuple, tuple] = {}
        self._delta_indexes: dict[tuple, tuple] = {}  # (pb) -> (file names, BucketGetIndex)
        self._write: "TableWrite | None" = None
        self._snapshot_id: int | None = None
        self._follow_thread: threading.Thread | None = None
        self._follow_stop: threading.Event | None = None
        self._follow_sub = None
        self._follow_lock: threading.Lock | None = None
        self.refresh()

    def attach_write(self, table_write: "TableWrite | None") -> "LocalTableQuery":
        """Read-your-writes: gets additionally consult `table_write`'s live
        memtables and its flushed-but-uncommitted level-0 files, so a query
        colocated with an ingest job serves committed-plus-buffered state."""
        self._write = table_write
        self._delta_indexes.clear()
        return self

    def refresh(self) -> None:
        """Re-plan against the latest snapshot (reference: file-change
        monitoring feeds refresh in the query service). Per-bucket diff:
        buckets whose file set + DV index are unchanged keep their built
        LookupLevels and BucketGetIndex."""
        plan = self.store.new_scan().plan()
        sid = plan.snapshot.id if plan.snapshot else None
        if sid == self._snapshot_id:
            return
        self._snapshot_id = sid
        from ..core.deletionvectors import DeletionVectorsIndexFile

        dv_io = DeletionVectorsIndexFile(self.table.file_io, self.table.path)
        seen: set[tuple] = set()
        for partition, buckets in plan.grouped().items():
            for bucket, files in buckets.items():
                pb = (partition, bucket)
                seen.add(pb)
                dv_index = plan.dv_index_for(partition, bucket)
                sig = (tuple(sorted((f.file_name, f.level) for f in files)), dv_index)
                if self._bucket_sigs.get(pb) == sig:
                    continue  # unchanged bucket: keep the warm state
                dvs = dv_io.read_all(dv_index) if dv_index else {}
                for name in dvs:
                    self.cache.invalidate(name)  # DV changed: cached rows stale
                self._levels[pb] = LookupLevels(
                    files,
                    self.store.reader_factory(partition, bucket),
                    self.store.key_names,
                    cache=self.cache,
                    deletion_vectors=dvs,
                    local_store_dir=self.local_store_dir,
                    file_io=self.table.file_io,
                    bloom_fpp=self._bloom_fpp,
                    hash_load_factor=self._hash_load_factor,
                    max_disk_bytes=self._max_disk_bytes,
                    file_retention_millis=self._file_retention_ms,
                )
                self._get_indexes[pb] = BucketGetIndex(
                    files,
                    self.store.reader_factory(partition, bucket),
                    self.store.key_names,
                    deletion_vectors=dvs,
                    bloom_prune=self._bloom_prune,
                )
                self._bucket_sigs[pb] = sig
        for pb in list(self._levels):
            if pb not in seen:
                del self._levels[pb]
                self._get_indexes.pop(pb, None)
                self._bucket_sigs.pop(pb, None)

    # ---- subscription-driven refresh ------------------------------------
    def follow(self, hub=None, lock: "threading.Lock | None" = None) -> "LocalTableQuery":
        """Subscription-driven refresh (the PR 13/14 declared follow-up):
        instead of callers invoking refresh() per request, a hub
        subscription (one shared decode-once tailer per table —
        service.subscription.SubscriptionHub) signals every new snapshot
        and refresh()'s existing per-bucket diff invalidates/rebuilds ONLY
        the touched buckets. Compaction-only snapshots carry no changelog
        rows, so the follower also compares the latest snapshot id on each
        poll timeout — refresh() no-ops when nothing advanced.

        `lock` (optional) serializes refresh against concurrent gets; pass
        the same lock the serving layer wraps get_batch with (the cluster
        worker serving plane and KvQueryServer do). Stop with unfollow()."""
        if self._follow_thread is not None:
            return self
        from ..service.subscription import SubscriptionHub
        from ..utils import new_file_name

        hub = hub if hub is not None else SubscriptionHub.for_table(self.table)
        self._follow_lock = lock if lock is not None else threading.Lock()
        self._follow_stop = threading.Event()
        # ephemeral consumer id, deleted on unfollow: a refresher must not
        # pin snapshot expiry after it is gone
        self._follow_sub = hub.subscribe(consumer_id=f"qryref-{new_file_name('c')}")
        stop, sub, flock = self._follow_stop, self._follow_sub, self._follow_lock

        def _loop():
            while not stop.is_set():
                advanced = False
                try:
                    batch = sub.poll(timeout=0.2)
                    advanced = batch is not None
                except Exception:
                    # shed or hub teardown: fall back to snapshot-id polling
                    # (refresh() keeps working without the signal)
                    stop.wait(0.2)
                try:
                    if advanced:
                        with flock:
                            self.refresh()
                    elif (
                        self.store.snapshot_manager.latest_snapshot_id() != self._snapshot_id
                    ):
                        with flock:
                            self.refresh()
                except Exception:
                    pass  # transient plan/IO failure: retried next poll

        self._follow_thread = threading.Thread(
            target=_loop, name=f"paimon-qryref-{id(self) & 0xFFFF:x}", daemon=False
        )
        self._follow_thread.start()
        return self

    def unfollow(self) -> None:
        """Stop the subscription-driven refresher and release its consumer
        pin. Safe to call when follow() was never started."""
        t, self._follow_thread = self._follow_thread, None
        if self._follow_stop is not None:
            self._follow_stop.set()
        if t is not None:
            t.join(timeout=30.0)
        sub, self._follow_sub = self._follow_sub, None
        if sub is not None:
            try:
                sub.close(delete_consumer=True)
            except Exception:
                pass

    def close(self) -> None:
        self.unfollow()

    # ---- batched path ---------------------------------------------------
    def get_batch(self, keys, partition: tuple = ()) -> GetResult:
        """Vectorized primary-key gets: `keys` is a sequence of key tuples
        (or scalars for single-column keys), a {column: values} mapping, or
        a ColumnBatch carrying the key columns. Returns a GetResult aligned
        with the probe keys; `to_pylist()` matches a scalar lookup() loop
        entry for entry."""
        from .get import batch_get

        return batch_get(self, keys, partition)

    # ---- scalar path (the oracle) ---------------------------------------
    def lookup(self, partition: tuple, key: "tuple | object"):
        """Latest value row for `key` (a tuple over the trimmed primary key,
        or a scalar for single-column keys); None if absent/deleted."""
        if not isinstance(key, tuple):
            key = (key,)
        # route to the right bucket: fixed-bucket tables hash the key;
        # dynamic tables may hold the key in any bucket — probe all
        candidates: Sequence[tuple] = [
            pb for pb in self._levels if pb[0] == partition
        ]
        if self.store.options.bucket > 0:
            from ..data.batch import ColumnBatch
            from .bucket import bucket_ids

            key_schema = self.store.value_schema.project(self.store.key_names)
            probe = ColumnBatch.from_pydict(key_schema, {k: [v] for k, v in zip(self.store.key_names, key)})
            b = int(bucket_ids(probe, self.table.schema.bucket_keys, self.store.options.bucket)[0])
            candidates = [(partition, b)] if (partition, b) in self._levels else []
        for pb in candidates:
            out = self._levels[pb].lookup(key)
            if out is not None:
                return out
        return None
