"""The actions CLI: `python -m paimon_tpu <action> ...`.

Parity: /root/reference/paimon-flink/paimon-flink-common/.../action/ (47
`flink run` actions, mirrored as SQL CALL procedures) — the maintenance and
ingestion surface operators drive without writing code: compact,
sort-compact, delete, tag/branch management, rollback, expiry, migration,
orphan cleanup, CDC sync, scans. Each action binds to the same engine-neutral
Table API the connectors use.
"""

from __future__ import annotations

import argparse
import json
import sys


def _infer_row_type(first_file: str, fmt: str):
    """Row type from the first data file's own schema (migrate actions)."""
    from .data.batch import ColumnBatch

    if fmt == "parquet":
        import pyarrow.parquet as pq

        arrow_schema = pq.read_schema(first_file)
    else:
        import pyarrow.orc as po

        arrow_schema = po.ORCFile(first_file).schema
    return ColumnBatch.row_type_from_arrow(arrow_schema)


def _table(args):
    from .catalog import FileSystemCatalog

    cat = FileSystemCatalog(args.warehouse, commit_user=getattr(args, "user", "cli"))
    return cat, cat.get_table(args.table)


def _add_common(p):
    p.add_argument("--warehouse", required=True, help="warehouse directory")
    p.add_argument("--table", required=True, help="db.table identifier")
    p.add_argument("--user", default="cli", help="commit user")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="paimon_tpu", description=__doc__)
    sub = ap.add_subparsers(dest="action", required=True)

    for name in (
        "compact",
        "sort_compact",
        "delete",
        "create_tag",
        "delete_tag",
        "list_tags",
        "rollback_to",
        "expire_snapshots",
        "remove_orphan_files",
        "migrate_table",
        "query",
        "sync_table",
        "create_branch",
        "fast_forward",
        "clone",
        "compact_database",
        "reset_consumer",
        "expire_partitions",
        "drop_partition",
        "mark_partition_done",
        "query_service",
        "repair",
        "migrate_database",
    ):
        p = sub.add_parser(name.replace("_", "-"))
        if name not in ("migrate_table", "clone", "compact_database", "repair", "migrate_database"):
            _add_common(p)
        if name == "compact":
            p.add_argument("--full", action="store_true")
        elif name == "sort_compact":
            p.add_argument("--order-by", required=True, help="comma-separated cluster columns")
            p.add_argument("--strategy", default="zorder", choices=["zorder", "hilbert", "order"])
        elif name == "delete":
            p.add_argument("--where", required=True, help='predicate json: {"field":..,"op":..,"value":..}')
        elif name in ("create_tag", "delete_tag"):
            p.add_argument("--tag", required=True)
            if name == "create_tag":
                p.add_argument("--snapshot", type=int, default=None)
        elif name == "rollback_to":
            p.add_argument("--to", required=True, help="snapshot id or tag name")
        elif name == "remove_orphan_files":
            p.add_argument("--older-than-hours", type=float, default=None,
                           help="safety threshold (default: table option "
                                "orphan.clean.older-than, 1 day)")
            p.add_argument("--dry-run", action="store_true")
        elif name == "migrate_table":
            p.add_argument("--warehouse", required=True)
            p.add_argument("--table", required=True, help="target db.table")
            p.add_argument("--source-dir", required=True, help="directory of parquet/orc files")
            p.add_argument("--format", default="parquet")
            p.add_argument("--user", default="cli")
        elif name == "query":
            p.add_argument("--limit", type=int, default=20)
            p.add_argument("--filter", default=None, help="predicate json")
        elif name == "sync_table":
            p.add_argument("--format", default="debezium-json", help="cdc format")
            p.add_argument("--input", default="-", help="file of json messages (- = stdin)")
        elif name in ("create_branch", "fast_forward"):
            p.add_argument("--branch", required=True)
        elif name == "clone":
            p.add_argument("--warehouse", required=True, help="source warehouse")
            p.add_argument("--database", default=None, help="source database (omit = all)")
            p.add_argument("--table", default=None, help="source table (omit = whole database)")
            p.add_argument("--target-warehouse", required=True)
            p.add_argument("--target-database", default=None)
            p.add_argument("--target-table", default=None)
            p.add_argument("--tag", default=None, help="clone this tag's snapshot")
            p.add_argument("--branch", default=None, help="clone from this branch")
            p.add_argument("--parallelism", type=int, default=8)
            p.add_argument("--user", default="cli")
        elif name == "compact_database":
            p.add_argument("--warehouse", required=True)
            p.add_argument("--including-databases", default=None, help="regex (default .*)")
            p.add_argument("--including-tables", default=None, help="regex (default .*)")
            p.add_argument("--excluding-tables", default=None, help="regex")
            p.add_argument("--full", action="store_true")
            p.add_argument("--user", default="cli")
        elif name == "reset_consumer":
            p.add_argument("--consumer-id", required=True)
            p.add_argument("--next-snapshot", type=int, default=None, help="omit = delete consumer")
        elif name == "expire_partitions":
            p.add_argument("--expiration-time-hours", type=float, required=True)
            p.add_argument("--timestamp-formatter", default="%Y-%m-%d")
            p.add_argument("--time-col", default=None, help="partition key holding the timestamp")
        elif name == "drop_partition":
            p.add_argument("--partition", required=True, action="append",
                           help="k=v[,k=v...] (repeatable)")
        elif name == "mark_partition_done":
            p.add_argument("--partition", required=True, action="append",
                           help="k=v[,k=v...] (repeatable)")
        elif name == "query_service":
            p.add_argument("--host", default="127.0.0.1")
            p.add_argument("--port", type=int, default=0, help="0 = ephemeral")
            p.add_argument("--serve-seconds", type=float, default=None,
                           help="exit after this many seconds (tests); default: run until interrupted")
        elif name == "repair":
            p.add_argument("--warehouse", required=True)
            p.add_argument("--jdbc-path", required=True, help="sqlite db of the JdbcCatalog to repair")
            p.add_argument("--user", default="cli")
        elif name == "migrate_database":
            p.add_argument("--warehouse", required=True)
            p.add_argument("--database", required=True, help="target database")
            p.add_argument("--source-dir", required=True,
                           help="directory of per-table subdirectories of parquet/orc files")
            p.add_argument("--format", default="parquet")
            p.add_argument("--user", default="cli")

    p = sub.add_parser("call", help="execute a SQL CALL procedure statement")
    p.add_argument("--warehouse", required=True)
    p.add_argument("--user", default="cli")
    p.add_argument("statement", help="e.g. \"CALL sys.compact(`table` => 'db.t')\"")

    p = sub.add_parser("sql", help="execute SQL statements (SELECT/DDL/DML/CALL)")
    p.add_argument("--warehouse", required=True)
    p.add_argument("--user", default="cli")
    p.add_argument("--file", help="run a multi-statement .sql script file")
    p.add_argument("statement", nargs="?", default=None,
                   help="e.g. \"SELECT k, v FROM db.t WHERE k > 5 LIMIT 10\"")

    args = ap.parse_args(argv)
    action = args.action.replace("-", "_")

    # Wedge-proof device policy, gated to actions that actually reach a
    # kernel: on a healthy rig ensure_live_backend takes the chip
    # (single-flight lock, held for the process lifetime); on a wedged
    # tunnel it pins CPU loudly instead of hanging the CLI in backend init.
    # Metadata-only actions (tags, branches, clone, expiry, repair, ...)
    # must NOT probe or contend for the grant — they pin CPU outright, so a
    # trivial `create-tag` never stalls behind a running bench.
    # (The env's sitecustomize pins the accelerator platform
    # programmatically, so JAX_PLATFORMS=cpu alone would not protect a CLI
    # user either way.)
    _KERNEL_ACTIONS = {"query", "compact", "sort_compact", "compact_database",
                       "sync_table", "query_service", "delete"}
    _KERNEL_PROCEDURES = {"compact", "compact_database", "delete", "merge_into",
                          "rewrite_file_index", "query_service"}
    reaches_kernel = action in _KERNEL_ACTIONS
    if action == "sql":
        import re as _re

        # argument validation BEFORE any device-policy work: a usage mistake
        # must never probe the tunnel or contend for the chip grant
        if args.file and args.statement:
            ap.error("pass a statement or --file, not both")
        if not args.file and args.statement is None:
            ap.error("sql needs a statement or --file")
        # SELECT merges on read -> kernel, EXCEPT system tables ($snapshots,
        # $files, ...): those are static metadata batches with no merge.
        # DDL (CREATE/DROP/SHOW/DESCRIBE) is metadata-only; ANALYZE and
        # INSERT scan/flush through the merge kernels. CALL statements gate
        # by procedure name, same as the dedicated `call` action. Script
        # files and multi-statement strings take the safe kernel path
        # (classified with the real quote-aware splitter).
        from .sql import split_statements as _split

        single = None if args.file else _split(args.statement)
        if single is not None and len(single) == 1:
            stmt = single[0]
        else:
            stmt = None  # script: mixed statements -> safe path
        if stmt is None:
            reaches_kernel = True
        elif _re.match(r"^\s*SELECT\b", stmt, _re.I):
            fm = _re.search(r"\bFROM\s+`?([\w.$]+)`?", stmt, _re.I)
            reaches_kernel = not (fm and "$" in fm.group(1))
        elif _re.match(r"^\s*(CREATE|DROP|ALTER|SHOW|DESC(RIBE)?)\b", stmt, _re.I):
            reaches_kernel = False  # DDL is metadata-only
        elif _re.match(r"^\s*(INSERT|UPDATE|DELETE|ANALYZE)\b", stmt, _re.I):
            reaches_kernel = True  # writes/scans flush through the merge kernels
        elif _re.match(r"^\s*TRUNCATE\b", stmt, _re.I):
            reaches_kernel = False  # empty overwrite commit: metadata-only
        else:
            try:
                from .sql import parse_call

                reaches_kernel = parse_call(stmt)[0] in _KERNEL_PROCEDURES
            except Exception:
                reaches_kernel = True  # unparseable: keep the safe path
    elif action == "call":
        try:
            from .sql import parse_call

            reaches_kernel = parse_call(args.statement)[0] in _KERNEL_PROCEDURES
        except Exception:
            reaches_kernel = True  # unparseable: keep the safe path
    if reaches_kernel:
        from .utils.tpuguard import ensure_live_backend

        ensure_live_backend(probe_timeout_s=float(__import__("os").environ.get("PAIMON_TPU_PROBE_TIMEOUT", "60")))
    else:
        import jax

        jax.config.update("jax_platforms", "cpu")

    if action == "call":
        from .catalog import FileSystemCatalog
        from .sql import call as sql_call

        cat = FileSystemCatalog(args.warehouse, commit_user=args.user)
        print(json.dumps(sql_call(cat, args.statement), default=str))
        return 0

    if action == "sql":
        from .catalog import FileSystemCatalog
        from .sql import execute as sql_execute, split_statements

        cat = FileSystemCatalog(args.warehouse, commit_user=args.user)
        if args.file:
            with open(args.file) as f:
                statements = split_statements(f.read())
        elif args.statement is not None:
            statements = split_statements(args.statement)
        else:
            ap.error("sql needs a statement or --file")

        def emit(out):
            if hasattr(out, "to_pylist"):  # SELECT/SHOW -> one JSON row per line
                for row in out.to_pylist():
                    print(json.dumps(list(row), default=str))
            elif isinstance(out, str):  # SHOW CREATE TABLE
                print(out)
            else:
                print(json.dumps(out, default=str))

        for stmt in statements:
            emit(sql_execute(cat, stmt))
        return 0

    if action == "clone":
        from .catalog import FileSystemCatalog
        from .table import clone as C

        if not args.table and (args.tag or args.branch or args.target_table):
            ap.error("--tag/--branch/--target-table require --table")
        if not args.database and args.target_database:
            ap.error("--target-database requires --database")
        src_cat = FileSystemCatalog(args.warehouse, commit_user=args.user)
        dst_cat = FileSystemCatalog(args.target_warehouse, commit_user=args.user)
        if args.table:
            if not args.database:
                ap.error("--table requires --database")
            t = src_cat.get_table(f"{args.database}.{args.table}")
            sid = None
            if args.branch:
                from .table.branch import branch_table

                t = branch_table(t, args.branch)
            if args.tag:
                from .table.tags import TagManager

                sid = TagManager(t.file_io, t.path).snapshot_id(args.tag)
            target = f"{args.target_database or args.database}.{args.target_table or args.table}"
            C.clone_table(t, dst_cat, target, snapshot_id=sid, parallelism=args.parallelism)
            cloned = [target]
        elif args.database:
            cloned = C.clone_database(
                src_cat, args.database, dst_cat, args.target_database, parallelism=args.parallelism
            )
        else:
            cloned = C.clone_warehouse(src_cat, dst_cat, parallelism=args.parallelism)
        print(json.dumps({"cloned": cloned}))
        return 0

    if action == "compact_database":
        # single implementation: the SQL procedure (CLI and CALL must agree)
        from .catalog import FileSystemCatalog
        from .sql import _proc_compact_database

        cat = FileSystemCatalog(args.warehouse, commit_user=args.user)
        out = _proc_compact_database(
            cat,
            including_databases=args.including_databases,
            including_tables=args.including_tables,
            excluding_tables=args.excluding_tables,
            full=args.full,
        )
        print(json.dumps({**out, "full": args.full}))
        return 0

    if action == "repair":
        from .catalog.jdbc import JdbcCatalog

        cat = JdbcCatalog(args.jdbc_path, args.warehouse, commit_user=args.user)
        print(json.dumps(cat.repair()))
        return 0

    if action == "migrate_database":
        # reference MigrateDatabaseAction: one migrate_table per subdirectory
        import os as _os

        from .catalog import FileSystemCatalog
        from .table.migrate import migrate_files

        cat = FileSystemCatalog(args.warehouse, commit_user=args.user)
        migrated = []
        for entry in sorted(_os.listdir(args.source_dir)):
            sub = _os.path.join(args.source_dir, entry)
            if not _os.path.isdir(sub):
                continue
            candidates = sorted(
                _os.path.join(sub, f)
                for f in _os.listdir(sub)
                if f.endswith(f".{args.format}")
            )
            if not candidates:
                continue
            row_type = _infer_row_type(candidates[0], args.format)
            migrate_files(cat, f"{args.database}.{entry}", sub, row_type, file_format=args.format)
            migrated.append(f"{args.database}.{entry}")
        print(json.dumps({"migrated": migrated}))
        return 0

    if action == "migrate_table":
        import glob

        from .catalog import FileSystemCatalog
        from .table.migrate import migrate_files

        cat = FileSystemCatalog(args.warehouse, commit_user=args.user)
        # infer the row type from the first data file (reference Migrator
        # reads the hive schema; here the files carry it themselves)
        candidates = sorted(glob.glob(f"{glob.escape(args.source_dir)}/*.{args.format}"))
        if not candidates:
            ap.error(f"no *.{args.format} files found in {args.source_dir}")
        row_type = _infer_row_type(candidates[0], args.format)
        t = migrate_files(cat, args.table, args.source_dir, row_type, file_format=args.format)
        print(json.dumps({"migrated": args.table, "snapshot": t.store.snapshot_manager.latest_snapshot_id()}))
        return 0

    cat, t = _table(args)

    if action == "compact":
        from .table.compactor import DedicatedCompactor

        # DedicatedCompactor re-enables compaction even on write-only tables
        # (the CLI IS the dedicated compaction job, reference CompactAction)
        done = DedicatedCompactor(t).run_once(full=args.full)
        print(json.dumps({"compacted": done, "full": args.full}))
    elif action == "sort_compact":
        from .table.sort_compact import sort_compact

        n = sort_compact(t, [c.strip() for c in args.order_by.split(",")], order=args.strategy)
        print(json.dumps({"rows_clustered": n, "strategy": args.strategy}))
    elif action == "delete":
        n = t.delete_where(_predicate(args.where))
        print(json.dumps({"rows_deleted": n}))
    elif action == "create_tag":
        t.create_tag(args.tag, snapshot_id=args.snapshot)
        print(json.dumps({"tag": args.tag}))
    elif action == "delete_tag":
        t.delete_tag(args.tag)
        print(json.dumps({"deleted_tag": args.tag}))
    elif action == "list_tags":
        print(json.dumps(t.tags()))
    elif action == "rollback_to":
        target = int(args.to) if args.to.isdigit() else args.to
        t.rollback_to(target)
        print(json.dumps({"rolled_back_to": target}))
    elif action == "expire_snapshots":
        n = t.expire_snapshots()
        print(json.dumps({"expired": n}))
    elif action == "remove_orphan_files":
        from .table.maintenance import remove_orphan_files

        removed = remove_orphan_files(
            t,
            older_than_millis=None
            if args.older_than_hours is None
            else int(args.older_than_hours * 3600_000),
            dry_run=args.dry_run,
        )
        print(json.dumps({"orphans": removed, "dry_run": args.dry_run}))
    elif action == "query":
        rb = t.new_read_builder()
        if args.filter:
            rb = rb.with_filter(_predicate(args.filter))
        rb = rb.with_limit(args.limit)
        out = rb.new_read().read_all(rb.new_scan().plan())
        for row in out.to_pylist():
            print(json.dumps(list(row), default=str))
    elif action == "sync_table":
        from contextlib import nullcontext

        from .table.cdc_format import CdcStream

        stream = CdcStream(t, args.format)
        ctx = nullcontext(sys.stdin) if args.input == "-" else open(args.input)
        with ctx as source:
            n = stream.ingest(line for line in source if line.strip())
        print(json.dumps({"records_applied": n}))
    elif action == "reset_consumer":
        from .table.consumer import ConsumerManager

        cm = ConsumerManager(t.file_io, t.path)
        if args.next_snapshot is None:
            cm.delete(args.consumer_id)
            print(json.dumps({"deleted_consumer": args.consumer_id}))
        else:
            cm.reset(args.consumer_id, args.next_snapshot)
            print(json.dumps({"consumer": args.consumer_id, "next_snapshot": args.next_snapshot}))
    elif action == "expire_partitions":
        from .table.maintenance import expire_partitions

        expired = expire_partitions(
            t,
            int(args.expiration_time_hours * 3600_000),
            time_col=args.time_col,
            pattern=args.timestamp_formatter,
        )
        print(json.dumps({"expired_partitions": [list(p) for p in expired]}))
    elif action == "drop_partition":
        from .table.maintenance import drop_partition

        specs = [dict(kv.split("=", 1) for kv in s.split(",")) for s in args.partition]
        dropped = [list(p) for p in drop_partition(t, *specs)]  # one atomic commit
        print(json.dumps({"dropped_partitions": dropped}))
    elif action == "mark_partition_done":
        from .table.maintenance import mark_partition_done

        specs = [dict(kv.split("=", 1) for kv in s.split(",")) for s in args.partition]
        paths = mark_partition_done(t, specs)
        print(json.dumps({"markers": paths}))
    elif action == "query_service":
        # reference flink/action/QueryServiceActionFactory: run the KV query
        # service for a table; the address registers in the table's FS
        # registry so RemoteTableQuery/KvQueryClient.for_table finds it
        import time as _time

        from .service import KvQueryServer

        server = KvQueryServer(t, host=args.host, port=args.port)
        host, port = server.start()
        print(json.dumps({"service": "kv-query", "host": host, "port": port}), flush=True)
        try:
            if args.serve_seconds is not None:
                _time.sleep(args.serve_seconds)
            else:
                while True:
                    _time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            server.shutdown()
    elif action == "create_branch":
        from .table.branch import BranchManager

        BranchManager(t.file_io, t.path).create(args.branch)
        print(json.dumps({"branch": args.branch}))
    elif action == "fast_forward":
        from .table.branch import BranchManager

        BranchManager(t.file_io, t.path).fast_forward(args.branch)
        print(json.dumps({"fast_forwarded": args.branch}))
    return 0


def _predicate(spec: str):
    from .data import predicate as P

    d = json.loads(spec)
    op = d.get("op", "=")
    fns = {
        "=": P.equal,
        "!=": P.not_equal,
        ">": P.greater_than,
        ">=": P.greater_or_equal,
        "<": P.less_than,
        "<=": P.less_or_equal,
    }
    if op == "in":
        return P.in_(d["field"], d["value"])
    if op == "is_null":
        return P.is_null(d["field"])
    return fns[op](d["field"], d["value"])


if __name__ == "__main__":
    sys.exit(main())
