"""The actions CLI: `python -m paimon_tpu <action> ...`.

Parity: /root/reference/paimon-flink/paimon-flink-common/.../action/ (47
`flink run` actions, mirrored as SQL CALL procedures) — the maintenance and
ingestion surface operators drive without writing code: compact,
sort-compact, delete, tag/branch management, rollback, expiry, migration,
orphan cleanup, CDC sync, scans. Each action binds to the same engine-neutral
Table API the connectors use.
"""

from __future__ import annotations

import argparse
import json
import sys


def _table(args):
    from .catalog import FileSystemCatalog

    cat = FileSystemCatalog(args.warehouse, commit_user=getattr(args, "user", "cli"))
    return cat, cat.get_table(args.table)


def _add_common(p):
    p.add_argument("--warehouse", required=True, help="warehouse directory")
    p.add_argument("--table", required=True, help="db.table identifier")
    p.add_argument("--user", default="cli", help="commit user")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="paimon_tpu", description=__doc__)
    sub = ap.add_subparsers(dest="action", required=True)

    for name in (
        "compact",
        "sort_compact",
        "delete",
        "create_tag",
        "delete_tag",
        "list_tags",
        "rollback_to",
        "expire_snapshots",
        "remove_orphan_files",
        "migrate_table",
        "query",
        "sync_table",
        "create_branch",
        "fast_forward",
    ):
        p = sub.add_parser(name.replace("_", "-"))
        if name != "migrate_table":
            _add_common(p)
        if name == "compact":
            p.add_argument("--full", action="store_true")
        elif name == "sort_compact":
            p.add_argument("--order-by", required=True, help="comma-separated cluster columns")
            p.add_argument("--strategy", default="zorder", choices=["zorder", "hilbert", "order"])
        elif name == "delete":
            p.add_argument("--where", required=True, help='predicate json: {"field":..,"op":..,"value":..}')
        elif name in ("create_tag", "delete_tag"):
            p.add_argument("--tag", required=True)
            if name == "create_tag":
                p.add_argument("--snapshot", type=int, default=None)
        elif name == "rollback_to":
            p.add_argument("--to", required=True, help="snapshot id or tag name")
        elif name == "remove_orphan_files":
            p.add_argument("--older-than-hours", type=float, default=24.0)
            p.add_argument("--dry-run", action="store_true")
        elif name == "migrate_table":
            p.add_argument("--warehouse", required=True)
            p.add_argument("--table", required=True, help="target db.table")
            p.add_argument("--source-dir", required=True, help="directory of parquet/orc files")
            p.add_argument("--format", default="parquet")
            p.add_argument("--user", default="cli")
        elif name == "query":
            p.add_argument("--limit", type=int, default=20)
            p.add_argument("--filter", default=None, help="predicate json")
        elif name == "sync_table":
            p.add_argument("--format", default="debezium-json", help="cdc format")
            p.add_argument("--input", default="-", help="file of json messages (- = stdin)")
        elif name in ("create_branch", "fast_forward"):
            p.add_argument("--branch", required=True)

    args = ap.parse_args(argv)
    action = args.action.replace("-", "_")

    if action == "migrate_table":
        import glob

        from .catalog import FileSystemCatalog
        from .data.batch import ColumnBatch
        from .table.migrate import migrate_files

        cat = FileSystemCatalog(args.warehouse, commit_user=args.user)
        # infer the row type from the first data file (reference Migrator
        # reads the hive schema; here the files carry it themselves)
        candidates = sorted(glob.glob(f"{args.source_dir}/*.{args.format}"))
        if not candidates:
            ap.error(f"no *.{args.format} files found in {args.source_dir}")
        first = candidates[0]
        if args.format == "parquet":
            import pyarrow.parquet as pq

            arrow_schema = pq.read_schema(first)
        else:
            import pyarrow.orc as po

            arrow_schema = po.ORCFile(first).schema
        row_type = ColumnBatch.row_type_from_arrow(arrow_schema)
        t = migrate_files(cat, args.table, args.source_dir, row_type, file_format=args.format)
        print(json.dumps({"migrated": args.table, "snapshot": t.store.snapshot_manager.latest_snapshot_id()}))
        return 0

    cat, t = _table(args)

    if action == "compact":
        from .table.compactor import DedicatedCompactor

        # DedicatedCompactor re-enables compaction even on write-only tables
        # (the CLI IS the dedicated compaction job, reference CompactAction)
        done = DedicatedCompactor(t).run_once(full=args.full)
        print(json.dumps({"compacted": done, "full": args.full}))
    elif action == "sort_compact":
        from .table.sort_compact import sort_compact

        n = sort_compact(t, [c.strip() for c in args.order_by.split(",")], order=args.strategy)
        print(json.dumps({"rows_clustered": n, "strategy": args.strategy}))
    elif action == "delete":
        n = t.delete_where(_predicate(args.where))
        print(json.dumps({"rows_deleted": n}))
    elif action == "create_tag":
        t.create_tag(args.tag, snapshot_id=args.snapshot)
        print(json.dumps({"tag": args.tag}))
    elif action == "delete_tag":
        t.delete_tag(args.tag)
        print(json.dumps({"deleted_tag": args.tag}))
    elif action == "list_tags":
        print(json.dumps(t.tags()))
    elif action == "rollback_to":
        target = int(args.to) if args.to.isdigit() else args.to
        t.rollback_to(target)
        print(json.dumps({"rolled_back_to": target}))
    elif action == "expire_snapshots":
        n = t.expire_snapshots()
        print(json.dumps({"expired": n}))
    elif action == "remove_orphan_files":
        from .table.maintenance import remove_orphan_files

        removed = remove_orphan_files(
            t, older_than_millis=int(args.older_than_hours * 3600_000), dry_run=args.dry_run
        )
        print(json.dumps({"orphans": removed, "dry_run": args.dry_run}))
    elif action == "query":
        rb = t.new_read_builder()
        if args.filter:
            rb = rb.with_filter(_predicate(args.filter))
        rb = rb.with_limit(args.limit)
        out = rb.new_read().read_all(rb.new_scan().plan())
        for row in out.to_pylist():
            print(json.dumps(list(row), default=str))
    elif action == "sync_table":
        from contextlib import nullcontext

        from .table.cdc_format import CdcStream

        stream = CdcStream(t, args.format)
        ctx = nullcontext(sys.stdin) if args.input == "-" else open(args.input)
        with ctx as source:
            n = stream.ingest(line for line in source if line.strip())
        print(json.dumps({"records_applied": n}))
    elif action == "create_branch":
        from .table.branch import BranchManager

        BranchManager(t.file_io, t.path).create(args.branch)
        print(json.dumps({"branch": args.branch}))
    elif action == "fast_forward":
        from .table.branch import BranchManager

        BranchManager(t.file_io, t.path).fast_forward(args.branch)
        print(json.dumps({"fast_forwarded": args.branch}))
    return 0


def _predicate(spec: str):
    from .data import predicate as P

    d = json.loads(spec)
    op = d.get("op", "=")
    fns = {
        "=": P.equal,
        "!=": P.not_equal,
        ">": P.greater_than,
        ">=": P.greater_or_equal,
        "<": P.less_than,
        "<=": P.less_or_equal,
    }
    if op == "in":
        return P.in_(d["field"], d["value"])
    if op == "is_null":
        return P.is_null(d["field"])
    return fns[op](d["field"], d["value"])


if __name__ == "__main__":
    sys.exit(main())
