"""LSM-OPD-style compressed-domain predicate pushdown (arxiv 2508.11862).

Before any page expands, each AND-conjunct of the read predicate is tried
against the compressed domain of its column:

  1. row-group gate — chunk Statistics (thrift, no arrow) through the same
     `Predicate.test_stats` the planner uses for file pruning: a group whose
     min/max cannot match never opens a single page;
  2. dictionary gate — for a dictionary-encoded chunk the leaf evaluates
     ONCE over the dictionary values (a |dict|-sized vectorized eval, not a
     |rows|-sized one) giving the surviving-code set; per page, only the
     index runs decode and `surviving[codes]` marks live rows. A page whose
     codes all miss is never expanded.

The masks of every conjunct AND together into one per-row keep mask for the
row group. The mask depends only on (file bytes, predicate) — never on the
projection — so the two projection passes of the pipelined merge read stay
row-aligned, which the datafile.read contract requires. Rows the mask kills
are rows the caller's later `predicate.eval` would kill anyway (a code that
fails a conjunct fails the conjunction), so dropping them early is safe on
every path that pushes predicates down.
"""

from __future__ import annotations

import numpy as np

from ..data.predicate import FieldStats, LeafPredicate, Predicate, PredicateBuilder
from ..types import RowType
from .container import ParquetFooter, RowGroupInfo, chunk_field_stats
from .pages import chunk_code_pages

__all__ = ["row_group_keep_mask", "dict_surviving_codes"]

# leaf functions whose data-eval on the dictionary domain transfers to rows:
# value-determined predicates (NULL rows fail them all, matching eval()'s
# `mask & valid`). isNull/isNotNull are row-level, not value-level — excluded.
_VALUE_FUNCS = frozenset(
    {
        "equal",
        "notEqual",
        "lessThan",
        "lessOrEqual",
        "greaterThan",
        "greaterOrEqual",
        "in",
        "notIn",
        "between",
        "startsWith",
        "endsWith",
        "contains",
    }
)


def dict_surviving_codes(leaf: LeafPredicate, dictionary: np.ndarray) -> np.ndarray:
    """Bool vector over dictionary codes: True where the dictionary value
    can satisfy the leaf. One vectorized eval over the dict domain."""
    from ..data.batch import Column, ColumnBatch
    from ..types import DataField, STRING

    # the leaf's eval only touches values + validity, so a synthetic
    # single-column batch over the dictionary domain reuses it verbatim
    # (the declared type is irrelevant to eval; STRING is a placeholder)
    schema = RowType([DataField(0, leaf.field, STRING())])
    batch = ColumnBatch(schema, {leaf.field: Column(dictionary)})
    return leaf.eval(batch)


def _rowgroup_stats(
    rg: RowGroupInfo, fields: set[str], schema: RowType
) -> dict[str, FieldStats]:
    out: dict[str, FieldStats] = {}
    for name in fields:
        chunk = rg.columns.get(name)
        if chunk is None or name not in schema:
            continue
        st = chunk_field_stats(chunk, schema.field(name).type, rg.num_rows)
        if st is not None:
            out[name] = st
    return out


def row_group_keep_mask(
    data,
    footer: ParquetFooter,
    rg: RowGroupInfo,
    predicate: Predicate | None,
    schema: RowType,
    metrics=None,
    code_cache: dict | None = None,
):
    """False → the whole row group is skipped; None → keep every row;
    ndarray[bool] → per-row keep mask (some pages/rows pruned).

    `code_cache` (a per-row-group dict the caller owns) collects the
    (dictionary, pages) pairs this gate decodes, keyed by field name — the
    code-domain reader re-uses them as its keep-masked code source instead
    of decompressing the same index runs a second time."""
    if predicate is None:
        return None
    # stage 1: statistics gate (native analog of the arrow path's
    # row-group skipping — same test_stats, stats parsed from thrift)
    stats = _rowgroup_stats(rg, predicate.referenced_fields(), schema)
    if stats and not predicate.test_stats(stats):
        return False
    # stage 2: dictionary gate per AND-conjunct
    mask: np.ndarray | None = None
    for part in PredicateBuilder.split_and(predicate):
        if not isinstance(part, LeafPredicate) or part.function not in _VALUE_FUNCS:
            continue
        chunk = rg.columns.get(part.field)
        if chunk is None or not chunk.has_dictionary or part.field not in schema:
            continue
        dictionary, pages = chunk_code_pages(data, chunk, schema.field(part.field).type)
        if code_cache is not None:
            code_cache[part.field] = (dictionary, pages)
        if dictionary is None:
            continue
        surviving = dict_surviving_codes(part, dictionary)
        if surviving.all():
            continue  # conjunct prunes nothing in this group
        part_mask = np.zeros(rg.num_rows, dtype=np.bool_)
        for row_start, n, codes, validity in pages:
            if codes is None:
                # PLAIN fallback page mid-chunk: conservatively alive
                part_mask[row_start : row_start + n] = True
            elif validity is None:
                part_mask[row_start : row_start + n] = surviving[codes]
            else:
                # NULL rows carry no code and fail every value predicate
                sl = part_mask[row_start : row_start + n]
                sl[validity] = surviving[codes]
        mask = part_mask if mask is None else (mask & part_mask)
        if not mask.any():
            break
    if mask is None:
        return None
    if not mask.any():
        if metrics is not None:
            metrics.counter("rows_pruned").inc(rg.num_rows)
        return False
    if mask.all():
        return None
    if metrics is not None:
        metrics.counter("rows_pruned").inc(int((~mask).sum()))
    return mask
