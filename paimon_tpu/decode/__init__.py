"""Native vectorized Parquet page-decode subsystem.

Takes Paimon data files from raw bytes to device-ready ColumnBatches
without pyarrow's decoder on the hot path (SURVEY §7 stage 2; round-5
verdict: host-side decode is 66% of the pipeline and the one `partial`
format component). The layers:

  thrift.py    — compact-protocol parser (footer + page headers)
  container.py — footer model, chunk slicing, page iteration, codecs
  kernels.py   — vectorized decoders: bit-unpack, RLE/bit-packed hybrid,
                 PLAIN, DELTA_BINARY_PACKED, dictionary gather, levels →
                 validity (numpy engine + jittable JAX twins)
  pages.py     — page → (values, validity) assembly with page skipping
  pushdown.py  — compressed-domain predicates: chunk stats + dictionary
                 code sets decide which pages ever expand (LSM-OPD)

Entry point `read_native` mirrors `ParquetFormat.read`'s arrow semantics:
one ColumnBatch per row group, rows in file order, fixed-width nulls filled
with zeros, predicate used for skipping only in ways the caller's later
dense `predicate.eval` makes exact. Files needing features outside the
native envelope raise UnsupportedParquetFeature and the format falls back
to the arrow decoder per file (counter decode.files_fallback).

Surfaced behind the FileFormat registry as table option
`format.parquet.decoder = arrow | native` (default arrow).
"""

from __future__ import annotations

import time
from typing import Sequence

import numpy as np

from ..data.batch import Column, ColumnBatch
from ..data.predicate import Predicate
from ..fs import FileIO
from ..metrics import decode_metrics
from ..types import RowType
from .container import (
    UnsupportedParquetFeature,
    expected_physical_type,
    parse_footer,
)
from .pages import decode_chunk
from .pushdown import row_group_keep_mask

__all__ = ["read_native", "UnsupportedParquetFeature"]


def read_native(
    file_io: FileIO,
    path: str,
    schema: RowType,
    projection: Sequence[str] | None = None,
    predicate: Predicate | None = None,
    dict_domain: bool = False,
    pool_limit: int | None = None,
) -> list[ColumnBatch]:
    """Decode one parquet file natively: list of ColumnBatches (one per
    surviving row group) under `schema` projected to `projection`.

    dict_domain=True (merge.dict-domain): string/bytes chunks that are fully
    dictionary-encoded come back as CODE-BACKED columns — (sorted pool,
    uint32 codes) via one dictionary sort + one code gather, no string
    object per row — re-using the index runs the pushdown gate already
    decoded. Chunks outside the envelope (PLAIN pages, a dictionary past
    pool_limit) expand exactly as before, per chunk."""
    metrics = decode_metrics()
    t0 = time.perf_counter()
    cols = list(projection) if projection is not None else list(schema.field_names)
    read_schema = schema.project(cols)
    data = file_io.read_bytes(path)
    footer = parse_footer(data)
    for f in read_schema.fields:
        if f.name not in footer.column_names:
            raise UnsupportedParquetFeature(f"column {f.name!r} not in file")
    # logical-type envelope check up front (nested types never decode
    # natively); the physical-type check happens lazily in decode_chunk so
    # all-null chunks — whose physical type arrow picks arbitrarily — pass
    expected = {f.name: expected_physical_type(f.type) for f in read_schema.fields}
    out: list[ColumnBatch] = []
    for rg in footer.row_groups:
        for f in read_schema.fields:
            if rg.columns.get(f.name) is None:
                raise UnsupportedParquetFeature(f"row group missing column {f.name!r}")
        if rg.num_rows == 0:
            continue
        tp = time.perf_counter()
        code_cache: dict | None = {} if dict_domain else None
        keep = row_group_keep_mask(
            data, footer, rg, predicate, schema, metrics=metrics, code_cache=code_cache
        )
        metrics.histogram("pushdown_ms").update((time.perf_counter() - tp) * 1000)
        if keep is False:
            continue
        columns: dict[str, Column] = {}
        for f in read_schema.fields:
            if dict_domain:
                col = _code_domain_column(
                    data, rg, f, keep, pool_limit, code_cache, metrics
                )
                if col is not None:
                    columns[f.name] = col
                    continue
            values, validity = decode_chunk(
                data,
                rg.columns[f.name],
                f.type,
                rg.num_rows,
                keep=keep,
                metrics=metrics,
                expected_physical=expected[f.name],
            )
            if keep is not None:
                values = values[keep]
                validity = None if validity is None else validity[keep]
            if validity is not None and validity.all():
                validity = None
            columns[f.name] = Column(values, validity)
        out.append(ColumnBatch(read_schema, columns))
    metrics.counter("files_native").inc()
    metrics.histogram("file_ms").update((time.perf_counter() - t0) * 1000)
    return out


_STRING_ROOTS = None
_FIXED_CODE_ROOTS = None


def _code_domain_column(data, rg, f, keep, pool_limit, code_cache, metrics):
    """One chunk as a code-backed Column, or None for the expanded path.
    Covers dictionary-encoded BYTE_ARRAY chunks (string/bytes) and — ISSUE
    12 — fixed-width INT32/INT64 chunks (int/bigint/date/timestamp), whose
    sorted pools keep their native dtype so low-cardinality numeric join
    keys match in the code domain too."""
    global _STRING_ROOTS, _FIXED_CODE_ROOTS
    from ..decode.container import T_BYTE_ARRAY, T_INT32, T_INT64

    if _STRING_ROOTS is None:
        from ..types import TypeRoot

        _STRING_ROOTS = (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY)
        _FIXED_CODE_ROOTS = {
            TypeRoot.TINYINT: T_INT32,
            TypeRoot.SMALLINT: T_INT32,
            TypeRoot.INT: T_INT32,
            TypeRoot.DATE: T_INT32,
            TypeRoot.TIME: T_INT32,
            TypeRoot.BIGINT: T_INT64,
            TypeRoot.TIMESTAMP: T_INT64,
            TypeRoot.TIMESTAMP_LTZ: T_INT64,
        }
    chunk = rg.columns[f.name]
    root = f.type.root
    if root in _STRING_ROOTS:
        if chunk.physical_type != T_BYTE_ARRAY:
            return None
    elif _FIXED_CODE_ROOTS.get(root) != chunk.physical_type:
        return None
    if not chunk.has_dictionary:
        return None
    from ..metrics import dict_metrics
    from ..ops.dicts import remap_codes, resolve_pool_limit, sort_dictionary
    from .pages import chunk_codes

    g = dict_metrics()
    got = chunk_codes(
        data, chunk, f.type, rg.num_rows, keep=keep,
        metrics=metrics, reuse=(code_cache or {}).get(f.name),
    )
    if got is None:
        g.counter("fallback_expanded").inc(rg.num_rows)
        return None
    dictionary, codes, validity = got
    if root not in _STRING_ROOTS:
        np_dtype = f.type.numpy_dtype()
        if dictionary.dtype != np_dtype:
            dictionary = dictionary.astype(np_dtype, copy=False)
    if len(dictionary) > resolve_pool_limit(pool_limit):
        g.counter("fallback_expanded").inc(rg.num_rows)
        return None
    pool, remap = sort_dictionary(dictionary)
    codes = remap_codes(remap, codes)
    if keep is not None:
        codes = codes[keep]
        validity = None if validity is None else validity[keep]
    if validity is not None and validity.all():
        validity = None
    g.counter("rows_code_domain").inc(len(codes))
    return Column.from_codes(pool, codes, validity)
