"""Vectorized Parquet page-decode kernels.

Every decoder here is array-at-a-time: run headers are parsed in a thin
Python loop (runs are few), but the values of every run/miniblock/page
expand through one numpy expression — no per-value Python. The numpy forms
are the default engine (tier-1 runs under JAX_PLATFORMS=cpu where per-page
jit dispatch would dominate); the jittable JAX twins (`unpack_bits_jax`,
`gather_jax`) express the same math as XLA ops so the expansion can run
device-side, and the parity tests pin them to the numpy oracles.

Kernel inventory (SURVEY §7 stage 2: TPU-resident dict/RLE expansion):
  * unpack_bits            — LSB-first bit-unpacking, the primitive under
                             both RLE/bit-packed hybrid and DELTA miniblocks
  * decode_rle_hybrid      — parquet's <bit-packed|RLE> hybrid runs
                             (definition levels + dictionary indices)
  * decode_plain           — PLAIN for all six physical types
  * decode_delta_binary_packed — DELTA_BINARY_PACKED int32/int64
  * def_levels_to_validity / scatter_values — levels → bool mask, compact
                             value vector → full row vector
  * gather                 — dictionary expansion (np.take / jnp.take)
"""

from __future__ import annotations

import os
import struct

import numpy as np

from .container import (
    T_BOOLEAN,
    T_BYTE_ARRAY,
    T_DOUBLE,
    T_FLOAT,
    T_INT32,
    T_INT64,
    UnsupportedParquetFeature,
)
from .thrift import read_varint, zigzag

__all__ = [
    "decode_engine",
    "set_decode_engine",
    "unpack_bits",
    "unpack_bits_jax",
    "decode_rle_hybrid",
    "decode_plain",
    "decode_delta_binary_packed",
    "def_levels_to_validity",
    "scatter_values",
    "gather",
    "gather_jax",
]

# "numpy" (default) or "jax": which engine expands fixed-width gathers and
# bit-unpacks. numpy stays the tier-1 default — correctness is identical
# (tests pin it) and per-page dispatch overhead favors the host for small
# pages; flip via env or set_decode_engine() when pages are device-bound.
_ENGINE = os.environ.get("PAIMON_TPU_DECODE_ENGINE", "numpy")


def decode_engine() -> str:
    return _ENGINE


def set_decode_engine(name: str) -> None:
    global _ENGINE
    if name not in ("numpy", "jax"):
        raise ValueError(f"decode engine must be 'numpy' or 'jax', got {name!r}")
    _ENGINE = name


# ---- bit unpacking -------------------------------------------------------


def unpack_bits(data: np.ndarray, bit_width: int, count: int) -> np.ndarray:
    """`count` unsigned values of `bit_width` bits from an LSB-first packed
    byte stream (parquet RLE/bit-packed + DELTA miniblock layout). Returns
    uint64."""
    if count == 0:
        return np.empty(0, dtype=np.uint64)
    if bit_width == 0:
        return np.zeros(count, dtype=np.uint64)
    if bit_width > 64:
        raise UnsupportedParquetFeature(f"bit width {bit_width}")
    bits = np.unpackbits(np.ascontiguousarray(data, dtype=np.uint8), bitorder="little")
    need = count * bit_width
    if len(bits) < need:
        raise ValueError(f"bit stream too short: {len(bits)} < {need}")
    weights = np.left_shift(np.uint64(1), np.arange(bit_width, dtype=np.uint64))
    return (bits[:need].reshape(count, bit_width).astype(np.uint64) * weights).sum(
        axis=1, dtype=np.uint64
    )


def unpack_bits_jax(data, bit_width: int, count: int):
    """Jittable twin of `unpack_bits` (bit_width/count are static under jit:
    page shapes are trace constants). Width capped at 32 — dictionary
    indices and levels never exceed it."""
    import jax.numpy as jnp

    if bit_width == 0:
        return jnp.zeros(count, dtype=jnp.uint32)
    if bit_width > 32:
        raise UnsupportedParquetFeature(f"jax unpack width {bit_width}")
    d = jnp.asarray(data, dtype=jnp.uint8)
    bits = (d[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & jnp.uint8(1)
    bits = bits.reshape(-1)[: count * bit_width].reshape(count, bit_width)
    weights = jnp.left_shift(jnp.uint32(1), jnp.arange(bit_width, dtype=jnp.uint32))
    return (bits.astype(jnp.uint32) * weights).sum(axis=1)


# ---- RLE / bit-packed hybrid --------------------------------------------


def decode_rle_hybrid(buf, pos: int, end: int, bit_width: int, count: int) -> np.ndarray:
    """Parquet's hybrid run stream → int32 vector of `count` values.

    Run headers parse sequentially (a handful per page); each run's values
    expand vectorized — an RLE run is one slice-fill, a bit-packed run one
    unpack_bits call."""
    out = np.empty(count, dtype=np.int32)
    filled = 0
    byte_w = (bit_width + 7) >> 3
    while filled < count:
        if pos >= end:
            raise UnsupportedParquetFeature(
                f"RLE stream exhausted at {filled}/{count} values"
            )
        header, pos = read_varint(buf, pos)
        if header & 1:  # bit-packed run: (header >> 1) groups of 8 values
            groups = header >> 1
            nvals = groups * 8
            nbytes = groups * bit_width
            vals = unpack_bits(
                np.frombuffer(buf, dtype=np.uint8, count=nbytes, offset=pos),
                bit_width,
                nvals,
            )
            take = min(nvals, count - filled)
            out[filled : filled + take] = vals[:take].astype(np.int32)
            pos += nbytes
            filled += take
        else:  # RLE run: one value repeated (header >> 1) times
            run = header >> 1
            v = int.from_bytes(bytes(buf[pos : pos + byte_w]), "little") if byte_w else 0
            pos += byte_w
            take = min(run, count - filled)
            out[filled : filled + take] = v
            filled += take
    return out


# ---- PLAIN ---------------------------------------------------------------

_PLAIN_DTYPES = {
    T_INT32: np.dtype("<i4"),
    T_INT64: np.dtype("<i8"),
    T_FLOAT: np.dtype("<f4"),
    T_DOUBLE: np.dtype("<f8"),
}


def decode_plain(
    buf, pos: int, physical_type: int, count: int, utf8: bool = False
) -> np.ndarray:
    """PLAIN-encoded values. Fixed-width types are one frombuffer view;
    booleans one unpackbits; BYTE_ARRAY walks the (u32 length, payload)
    stream — inherently sequential, the one loop the format forces."""
    if physical_type in _PLAIN_DTYPES:
        dt = _PLAIN_DTYPES[physical_type]
        return np.frombuffer(buf, dtype=dt, count=count, offset=pos)
    if physical_type == T_BOOLEAN:
        nbytes = (count + 7) >> 3
        bits = np.unpackbits(
            np.frombuffer(buf, dtype=np.uint8, count=nbytes, offset=pos),
            bitorder="little",
        )
        return bits[:count].astype(np.bool_)
    if physical_type == T_BYTE_ARRAY:
        out = np.empty(count, dtype=object)
        mv = memoryview(buf)
        for i in range(count):
            n = struct.unpack_from("<I", mv, pos)[0]
            pos += 4
            raw = bytes(mv[pos : pos + n])
            out[i] = raw.decode("utf-8") if utf8 else raw
            pos += n
        return out
    raise UnsupportedParquetFeature(f"PLAIN physical type {physical_type}")


# ---- DELTA_BINARY_PACKED -------------------------------------------------

_U64 = np.uint64


def decode_delta_binary_packed(buf, pos: int, count: int, physical_type: int) -> np.ndarray:
    """DELTA_BINARY_PACKED int32/int64. Deltas live in bit-packed miniblocks
    (unpacked vectorized per miniblock); the value stream is first_value +
    prefix-sum — one wrap-around uint64 cumsum."""
    if physical_type not in (T_INT32, T_INT64):
        raise UnsupportedParquetFeature("DELTA_BINARY_PACKED on non-int column")
    block_size, pos = read_varint(buf, pos)
    n_mini, pos = read_varint(buf, pos)
    total, pos = read_varint(buf, pos)
    v, pos = read_varint(buf, pos)
    first = zigzag(v)
    n = min(count, total)
    if n == 0:
        dt = np.int32 if physical_type == T_INT32 else np.int64
        return np.empty(0, dtype=dt)
    if n_mini == 0 or block_size % n_mini:
        raise UnsupportedParquetFeature("malformed delta header")
    per_mini = block_size // n_mini
    deltas = np.empty(max(n - 1, 0), dtype=_U64)
    got = 0
    while got < n - 1:
        v, pos = read_varint(buf, pos)
        min_delta = _U64(zigzag(v) & 0xFFFFFFFFFFFFFFFF)
        widths = bytes(buf[pos : pos + n_mini])
        pos += n_mini
        for w in widths:
            if got >= total - 1:
                break  # trailing miniblocks of the last block carry no data
            nbytes = (w * per_mini) >> 3
            vals = unpack_bits(
                np.frombuffer(buf, dtype=np.uint8, count=nbytes, offset=pos), w, per_mini
            )
            pos += nbytes
            take = min(per_mini, (n - 1) - got, (total - 1) - got)
            if take > 0:
                deltas[got : got + take] = vals[:take] + min_delta
            got += min(per_mini, (total - 1) - got)
    out = np.empty(n, dtype=_U64)
    out[0] = _U64(first & 0xFFFFFFFFFFFFFFFF)
    if n > 1:
        np.cumsum(deltas, dtype=_U64, out=deltas)
        out[1:] = out[0] + deltas
    signed = out.view(np.int64)
    if physical_type == T_INT32:
        return (out & _U64(0xFFFFFFFF)).astype(np.uint32).view(np.int32)
    return signed


# ---- levels & assembly ---------------------------------------------------


def def_levels_to_validity(levels: np.ndarray, max_def: int) -> np.ndarray:
    return levels == max_def


def scatter_values(
    compact: np.ndarray, validity: np.ndarray, np_dtype: np.dtype
) -> np.ndarray:
    """Compact (nulls-stripped) value vector → full row vector, nulls filled
    with 0/False/None exactly like ColumnBatch.from_arrow's fill_null."""
    n = len(validity)
    if np_dtype == np.dtype(object):
        out = np.empty(n, dtype=object)
    else:
        out = np.zeros(n, dtype=np_dtype)
    out[validity] = compact
    return out


# ---- dictionary expansion ------------------------------------------------


def gather(dictionary: np.ndarray, codes: np.ndarray) -> np.ndarray:
    """dictionary[codes] — the dict-expansion gather. Fixed-width columns
    route through the configured engine; object dictionaries (strings)
    always gather on host."""
    if _ENGINE == "jax" and dictionary.dtype != np.dtype(object):
        return np.asarray(gather_jax(dictionary, codes))
    return dictionary.take(codes)


def gather_jax(dictionary, codes):
    import jax.numpy as jnp

    return jnp.take(jnp.asarray(dictionary), jnp.asarray(codes), axis=0)
