"""Page → column assembly: decompressed pages through the kernels into one
(values, validity) pair per column chunk.

The unit of skipping is the page: `decode_chunk` takes an optional per-row
keep mask (from decode.pushdown) and any data page whose row range is fully
dead is never decompressed, never level-decoded, never expanded — its slot
in the output stays at the null fill and the keep mask drops those rows
before the batch is built. That is the LSM-OPD shape: predicates ran on the
compressed/dictionary domain, only survivors expand.

Metrics (group "decode"): pages_decoded / pages_skipped counters count data
pages; bytes_expanded accumulates the materialized value bytes.
"""

from __future__ import annotations

import numpy as np

from ..types import DataType, TypeRoot
from . import kernels
from .container import (
    ENC_DELTA_BINARY_PACKED,
    ENC_PLAIN,
    ENC_PLAIN_DICTIONARY,
    ENC_RLE,
    ENC_RLE_DICTIONARY,
    PAGE_DATA,
    PAGE_DATA_V2,
    PAGE_DICTIONARY,
    T_BOOLEAN,
    ColumnChunkInfo,
    PageInfo,
    UnsupportedParquetFeature,
    decompress,
    iter_pages,
)

__all__ = ["decode_chunk", "chunk_codes", "chunk_code_pages", "decode_dictionary", "object_nbytes"]


def _is_utf8(dtype: DataType) -> bool:
    return dtype.root in (TypeRoot.CHAR, TypeRoot.VARCHAR)


def decode_dictionary(page: PageInfo, chunk: ColumnChunkInfo, dtype: DataType) -> np.ndarray:
    if page.encoding not in (ENC_PLAIN, ENC_PLAIN_DICTIONARY):
        raise UnsupportedParquetFeature(f"dictionary page encoding {page.encoding}")
    raw = decompress(chunk.codec, page.payload, page.uncompressed_size)
    return kernels.decode_plain(
        raw, 0, chunk.physical_type, page.num_values, utf8=_is_utf8(dtype)
    )


def _page_levels(
    raw: bytes, page: PageInfo, chunk: ColumnChunkInfo
) -> tuple[np.ndarray | None, int]:
    """(validity, values_offset) for one decompressed v1 page / raw v2 page
    prefix. validity None means every slot valid."""
    n = page.num_values
    if chunk.max_def == 0:
        return None, 0
    if page.kind == PAGE_DATA:
        # v1: 4-byte length + RLE levels (bit width from max_def, here 1)
        ln = int.from_bytes(raw[0:4], "little")
        levels = kernels.decode_rle_hybrid(raw, 4, 4 + ln, 1, n)
        off = 4 + ln
    else:
        # v2: RLE levels without length prefix, length from the header
        ln = page.def_levels_byte_length
        levels = kernels.decode_rle_hybrid(raw, 0, ln, 1, n)
        off = ln
    validity = kernels.def_levels_to_validity(levels, chunk.max_def)
    if validity.all():
        return None, off
    return validity, off


def _decode_values(
    raw: bytes,
    off: int,
    page: PageInfo,
    chunk: ColumnChunkInfo,
    dtype: DataType,
    dictionary: np.ndarray | None,
    n_valid: int,
) -> np.ndarray:
    enc = page.encoding
    if enc in (ENC_RLE_DICTIONARY, ENC_PLAIN_DICTIONARY):
        if dictionary is None:
            raise UnsupportedParquetFeature("dictionary-encoded page without dictionary")
        width = raw[off]
        codes = kernels.decode_rle_hybrid(raw, off + 1, len(raw), width, n_valid)
        return kernels.gather(dictionary, codes)
    if enc == ENC_PLAIN:
        return kernels.decode_plain(raw, off, chunk.physical_type, n_valid, utf8=_is_utf8(dtype))
    if enc == ENC_DELTA_BINARY_PACKED:
        return kernels.decode_delta_binary_packed(raw, off, n_valid, chunk.physical_type)
    if enc == ENC_RLE and chunk.physical_type == T_BOOLEAN:
        # v2 boolean pages: RLE values behind a 4-byte length prefix
        ln = int.from_bytes(raw[off : off + 4], "little")
        return kernels.decode_rle_hybrid(raw, off + 4, off + 4 + ln, 1, n_valid).astype(np.bool_)
    raise UnsupportedParquetFeature(f"data page encoding {enc}")


def _split_v2(raw_payload: bytes, page: PageInfo, chunk: ColumnChunkInfo) -> bytes:
    """v2 pages keep levels uncompressed ahead of the (optionally)
    compressed values; normalize to one flat buffer like v1."""
    ln = page.def_levels_byte_length
    levels = raw_payload[:ln]
    body = raw_payload[ln:]
    if page.v2_compressed:
        body = decompress(chunk.codec, body, page.uncompressed_size - ln)
    return levels + body


def object_nbytes(values: np.ndarray) -> int:
    """Expansion weight of an object vector (bytes_expanded metric)."""
    if values.dtype != np.dtype(object):
        return values.nbytes
    return int(
        sum(len(x) if isinstance(x, (str, bytes)) else 8 for x in values if x is not None)
    )


def decode_chunk(
    data,
    chunk: ColumnChunkInfo,
    dtype: DataType,
    num_rows: int,
    keep: np.ndarray | None = None,
    metrics=None,
    expected_physical: int | None = None,
) -> tuple[np.ndarray, np.ndarray | None]:
    """Decode one column chunk into (values, validity) over the row group's
    `num_rows` rows. Pages whose row range is dead under `keep` are skipped
    before decompression; their rows keep the null fill (the caller drops
    them via `keep` right after).

    The physical-type envelope is enforced only when values actually decode:
    an all-null column (arrow writes those with a `null` type whose parquet
    physical is arbitrary) never materializes a value, so its physical type
    never matters — parity with the arrow reader."""
    np_dtype = dtype.numpy_dtype()
    if np_dtype == np.dtype(object):
        values = np.empty(num_rows, dtype=object)
    else:
        values = np.zeros(num_rows, dtype=np_dtype)
    validity = np.ones(num_rows, dtype=np.bool_)
    any_null = False
    dict_page: PageInfo | None = None
    dictionary: np.ndarray | None = None
    row = 0
    for page in iter_pages(data, chunk):
        if page.kind == PAGE_DICTIONARY:
            dict_page = page  # decoded lazily, on first page that needs it
            continue
        n = page.num_values
        sl = slice(row, row + n)
        row += n
        if keep is not None and not keep[sl].any():
            validity[sl] = False  # dead rows; dropped by keep before assembly
            any_null = True
            if metrics is not None:
                metrics.counter("pages_skipped").inc()
            continue
        if page.kind == PAGE_DATA:
            raw = decompress(chunk.codec, page.payload, page.uncompressed_size)
        else:
            raw = _split_v2(page.payload, page, chunk)
        page_validity, off = _page_levels(raw, page, chunk)
        n_valid = n if page_validity is None else int(page_validity.sum())
        if n_valid == 0:
            any_null = True
            validity[sl] = False
            continue
        if expected_physical is not None and chunk.physical_type != expected_physical:
            raise UnsupportedParquetFeature(
                f"column {chunk.name}: physical type {chunk.physical_type}, "
                f"expected {expected_physical}"
            )
        if dictionary is None and dict_page is not None:
            dictionary = decode_dictionary(dict_page, chunk, dtype)
        compact = _decode_values(raw, off, page, chunk, dtype, dictionary, n_valid)
        compact = _cast_physical(compact, chunk.physical_type, np_dtype)
        if page_validity is None:
            values[sl] = compact
        else:
            any_null = True
            validity[sl] = page_validity
            values[sl] = kernels.scatter_values(compact, page_validity, np_dtype)
        if metrics is not None:
            metrics.counter("pages_decoded").inc()
            metrics.counter("bytes_expanded").inc(object_nbytes(compact))
    if row != num_rows:
        raise UnsupportedParquetFeature(
            f"column {chunk.name}: pages cover {row} rows, row group has {num_rows}"
        )
    return values, (validity if any_null else None)


def _cast_physical(compact: np.ndarray, physical: int, np_dtype: np.dtype) -> np.ndarray:
    if compact.dtype == np_dtype or np_dtype == np.dtype(object):
        return compact
    # INT32 physical backing int8/int16/date columns etc.
    return compact.astype(np_dtype, copy=False)


def chunk_codes(
    data,
    chunk: ColumnChunkInfo,
    dtype: DataType,
    num_rows: int,
    keep: np.ndarray | None = None,
    metrics=None,
    reuse: tuple[np.ndarray | None, list] | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray | None] | None:
    """The code-domain read of one chunk: (dictionary, full-length uint32
    codes, validity) with the values never expanded — the reader mode of
    merge.dict-domain. Returns None when the chunk is not fully
    dictionary-encoded (a mid-chunk PLAIN fallback page); the caller then
    takes the expanded decode_chunk path for this chunk.

    `reuse` is the (dictionary, pages) pair pushdown already decoded for
    this chunk (chunk_code_pages): the dictionary-domain predicate verdicts
    and these index runs are the SAME bytes, so the reader assembles codes
    from them instead of decompressing the pages a second time. Without
    reuse, pages whose row range is dead under `keep` are skipped before
    decompression exactly like decode_chunk."""
    if not chunk.has_dictionary:
        return None
    codes_full = np.zeros(num_rows, dtype=np.uint32)
    validity = np.ones(num_rows, dtype=np.bool_)
    any_null = False
    if reuse is not None:
        dictionary, pages = reuse
        if dictionary is None or any(codes is None for _, _, codes, _ in pages):
            return None
        for row_start, n, codes, page_validity in pages:
            sl = slice(row_start, row_start + n)
            if page_validity is None:
                codes_full[sl] = codes
            else:
                any_null = True
                validity[sl] = page_validity
                codes_full[sl][page_validity] = codes
        return dictionary, codes_full, (validity if any_null else None)
    dict_page: PageInfo | None = None
    dictionary = None
    row = 0
    for page in iter_pages(data, chunk):
        if page.kind == PAGE_DICTIONARY:
            dict_page = page
            continue
        if page.encoding not in (ENC_RLE_DICTIONARY, ENC_PLAIN_DICTIONARY):
            return None  # PLAIN fallback page mid-chunk: expanded path owns it
        n = page.num_values
        sl = slice(row, row + n)
        row += n
        if keep is not None and not keep[sl].any():
            validity[sl] = False  # dead rows; dropped by keep before assembly
            any_null = True
            if metrics is not None:
                metrics.counter("pages_skipped").inc()
            continue
        if page.kind == PAGE_DATA:
            raw = decompress(chunk.codec, page.payload, page.uncompressed_size)
        else:
            raw = _split_v2(page.payload, page, chunk)
        page_validity, off = _page_levels(raw, page, chunk)
        n_valid = n if page_validity is None else int(page_validity.sum())
        if n_valid == 0:
            any_null = True
            validity[sl] = False
            continue
        width = raw[off]
        codes = kernels.decode_rle_hybrid(raw, off + 1, len(raw), width, n_valid)
        if page_validity is None:
            codes_full[sl] = codes
        else:
            any_null = True
            validity[sl] = page_validity
            codes_full[sl][page_validity] = codes
        if metrics is not None:
            # decoded, yes — but never expanded: only the index runs and
            # levels touched, so bytes_expanded stays untouched
            metrics.counter("pages_decoded").inc()
    if row != num_rows:
        raise UnsupportedParquetFeature(
            f"column {chunk.name}: pages cover {row} rows, row group has {num_rows}"
        )
    if dict_page is None:
        return None
    dictionary = decode_dictionary(dict_page, chunk, dtype)
    return dictionary, codes_full, (validity if any_null else None)


def chunk_code_pages(
    data, chunk: ColumnChunkInfo, dtype: DataType
) -> tuple[np.ndarray | None, list[tuple[int, int, np.ndarray | None, np.ndarray | None]]]:
    """The compressed-domain view of one chunk for pushdown: the decoded
    dictionary (None when the chunk is not dictionary-encoded) and, per data
    page, (row_start, num_rows, codes, validity) — codes None for non-dict
    pages (a mid-chunk PLAIN fallback keeps those pages conservatively
    alive). Values are never expanded here: only levels and index runs
    decode, which is the cheap fraction of a page."""
    dictionary: np.ndarray | None = None
    pages: list[tuple[int, int, np.ndarray | None, np.ndarray | None]] = []
    row = 0
    for page in iter_pages(data, chunk):
        if page.kind == PAGE_DICTIONARY:
            dictionary = decode_dictionary(page, chunk, dtype)
            continue
        n = page.num_values
        if page.encoding in (ENC_RLE_DICTIONARY, ENC_PLAIN_DICTIONARY):
            if page.kind == PAGE_DATA:
                raw = decompress(chunk.codec, page.payload, page.uncompressed_size)
            else:
                raw = _split_v2(page.payload, page, chunk)
            page_validity, off = _page_levels(raw, page, chunk)
            n_valid = n if page_validity is None else int(page_validity.sum())
            width = raw[off]
            codes = kernels.decode_rle_hybrid(raw, off + 1, len(raw), width, n_valid)
            pages.append((row, n, codes, page_validity))
        else:
            pages.append((row, n, None, None))
        row += n
    return dictionary, pages
