"""Parquet container walk: footer → row groups → column chunks → pages.

Maps the thrift dicts from `decode.thrift` onto light typed views, slices
raw column-chunk byte ranges out of the file, and iterates (PageHeader,
payload) pairs. Decompression goes through pyarrow's codec objects (the
page header carries the exact uncompressed size, so every codec — zstd,
snappy, gzip, brotli — decompresses one-shot); the *decoding* of the
decompressed pages is pure kernels (decode.kernels / decode.pages).

Only the container features this repo's writer (and pyarrow generally)
emits are handled natively; anything else raises UnsupportedParquetFeature
and the caller falls back to the arrow decoder for that file:
  * flat schemas (no REPEATED fields, no groups below the root)
  * physical types BOOLEAN/INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY
  * data pages v1 and v2, dictionary pages PLAIN/PLAIN_DICTIONARY
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from ..data.predicate import FieldStats
from ..types import DataType, TypeRoot
from .thrift import ThriftError, read_struct

__all__ = [
    "UnsupportedParquetFeature",
    "ParquetFooter",
    "RowGroupInfo",
    "ColumnChunkInfo",
    "PageInfo",
    "parse_footer",
    "iter_pages",
    "decompress",
    "chunk_field_stats",
    "expected_physical_type",
]

MAGIC = b"PAR1"

# parquet.thrift Type enum
T_BOOLEAN, T_INT32, T_INT64, T_INT96, T_FLOAT, T_DOUBLE, T_BYTE_ARRAY, T_FLBA = range(8)

# parquet.thrift CompressionCodec enum
CODEC_NAMES = {
    0: None,  # UNCOMPRESSED
    1: "snappy",
    2: "gzip",
    4: "brotli",
    6: "zstd",
    7: "lz4_raw",
}

# parquet.thrift Encoding enum values used below
ENC_PLAIN = 0
ENC_PLAIN_DICTIONARY = 2
ENC_RLE = 3
ENC_DELTA_BINARY_PACKED = 5
ENC_RLE_DICTIONARY = 8

# parquet.thrift PageType enum
PAGE_DATA = 0
PAGE_INDEX = 1
PAGE_DICTIONARY = 2
PAGE_DATA_V2 = 3


class UnsupportedParquetFeature(Exception):
    """This file needs a container/encoding feature outside the native
    decoder's envelope — the read falls back to the arrow path."""


@dataclass(frozen=True)
class ColumnChunkInfo:
    name: str
    physical_type: int
    codec: int
    num_values: int
    max_def: int
    start_offset: int  # first page (dictionary page when present)
    total_compressed_size: int
    has_dictionary: bool
    encodings: tuple[int, ...]
    stats: dict | None  # raw thrift Statistics struct ({field_id: value})


@dataclass(frozen=True)
class RowGroupInfo:
    num_rows: int
    columns: dict[str, ColumnChunkInfo]


@dataclass(frozen=True)
class ParquetFooter:
    num_rows: int
    row_groups: tuple[RowGroupInfo, ...]
    column_names: tuple[str, ...]


@dataclass(frozen=True)
class PageInfo:
    kind: int  # PAGE_DATA | PAGE_DICTIONARY | PAGE_DATA_V2
    num_values: int  # rows incl. nulls for data pages; dict size for dict pages
    encoding: int
    uncompressed_size: int
    # v2 only:
    num_nulls: int = 0
    def_levels_byte_length: int = 0
    v2_compressed: bool = True
    payload: bytes = field(default=b"", repr=False, compare=False)  # raw (compressed) page bytes


# ---- footer --------------------------------------------------------------

# FieldRepetitionType
_REQUIRED, _OPTIONAL, _REPEATED = 0, 1, 2


def parse_footer(data) -> ParquetFooter:
    if len(data) < 12 or bytes(data[:4]) != MAGIC or bytes(data[-4:]) != MAGIC:
        raise UnsupportedParquetFeature("not a parquet file (bad magic)")
    meta_len = struct.unpack_from("<I", data, len(data) - 8)[0]
    meta_start = len(data) - 8 - meta_len
    if meta_start < 4:
        raise UnsupportedParquetFeature("footer length exceeds file")
    try:
        fmd, _ = read_struct(data[meta_start : len(data) - 8])
    except ThriftError as e:
        raise UnsupportedParquetFeature(f"footer parse: {e}") from e

    # SchemaElement list: [0] is the root; a flat file has exactly its
    # children after it, none of which has children of its own
    schema_elems = fmd.get(2) or []
    if not schema_elems:
        raise UnsupportedParquetFeature("no schema elements")
    root = schema_elems[0]
    n_children = root.get(5, 0)
    if n_children != len(schema_elems) - 1:
        raise UnsupportedParquetFeature("nested schema (grouped fields)")
    col_meta: dict[str, dict] = {}
    names = []
    for elem in schema_elems[1:]:
        if elem.get(5):  # num_children on a leaf => group node
            raise UnsupportedParquetFeature("nested schema (grouped fields)")
        rep = elem.get(3, _REQUIRED)
        if rep == _REPEATED:
            raise UnsupportedParquetFeature("repeated field")
        name = elem[4].decode("utf-8")
        names.append(name)
        col_meta[name] = {"type": elem.get(1), "max_def": 1 if rep == _OPTIONAL else 0}

    groups = []
    for rg in fmd.get(4) or []:
        cols: dict[str, ColumnChunkInfo] = {}
        for cc in rg.get(1) or []:
            md = cc.get(3)
            if md is None:
                raise UnsupportedParquetFeature("column chunk without inline metadata")
            path = md.get(3) or []
            if len(path) != 1:
                raise UnsupportedParquetFeature("nested column path")
            name = path[0].decode("utf-8")
            data_off = md[9]
            dict_off = md.get(11)
            has_dict = dict_off is not None and 0 < dict_off < data_off
            cols[name] = ColumnChunkInfo(
                name=name,
                physical_type=md[1],
                codec=md.get(4, 0),
                num_values=md[5],
                max_def=col_meta[name]["max_def"],
                start_offset=dict_off if has_dict else data_off,
                total_compressed_size=md[7],
                has_dictionary=has_dict,
                encodings=tuple(md.get(2) or ()),
                stats=md.get(12),
            )
        groups.append(RowGroupInfo(num_rows=rg[3], columns=cols))
    return ParquetFooter(
        num_rows=fmd.get(3, sum(g.num_rows for g in groups)),
        row_groups=tuple(groups),
        column_names=tuple(names),
    )


# ---- pages ---------------------------------------------------------------


def iter_pages(data, chunk: ColumnChunkInfo):
    """Yield PageInfo for every page of one column chunk, payloads still
    compressed (decode.pages decompresses lazily so skipped pages never
    even decompress)."""
    pos = chunk.start_offset
    end = chunk.start_offset + chunk.total_compressed_size
    values_seen = 0
    while pos < end and values_seen < chunk.num_values:
        try:
            hdr, body = read_struct(data[pos:end])
        except ThriftError as e:
            raise UnsupportedParquetFeature(f"page header parse: {e}") from e
        pos += body
        kind = hdr[1]
        comp_size = hdr[3]
        payload = bytes(data[pos : pos + comp_size])
        if len(payload) < comp_size:
            raise UnsupportedParquetFeature("truncated page payload")
        pos += comp_size
        if kind == PAGE_DICTIONARY:
            dh = hdr.get(7) or {}
            yield PageInfo(
                kind=kind,
                num_values=dh.get(1, 0),
                encoding=dh.get(2, ENC_PLAIN),
                uncompressed_size=hdr[2],
                payload=payload,
            )
        elif kind == PAGE_DATA:
            dh = hdr.get(5) or {}
            n = dh[1]
            values_seen += n
            yield PageInfo(
                kind=kind,
                num_values=n,
                encoding=dh[2],
                uncompressed_size=hdr[2],
                payload=payload,
            )
        elif kind == PAGE_DATA_V2:
            dh = hdr.get(8) or {}
            n = dh[1]
            values_seen += n
            if dh.get(6, 0):
                raise UnsupportedParquetFeature("repetition levels in flat file")
            yield PageInfo(
                kind=kind,
                num_values=n,
                encoding=dh[4],
                uncompressed_size=hdr[2],
                num_nulls=dh.get(2, 0),
                def_levels_byte_length=dh.get(5, 0),
                v2_compressed=dh.get(7, True),
                payload=payload,
            )
        elif kind == PAGE_INDEX:
            continue  # offset/column index pages carry no row data
        else:
            raise UnsupportedParquetFeature(f"page type {kind}")


def decompress(codec: int, data: bytes, uncompressed_size: int) -> bytes:
    if codec == 0 or len(data) == uncompressed_size == 0:
        return data
    name = CODEC_NAMES.get(codec)
    if name is None:
        raise UnsupportedParquetFeature(f"compression codec {codec}")
    import pyarrow as pa

    try:
        return pa.Codec(name).decompress(
            data, decompressed_size=uncompressed_size, asbytes=True
        )
    except (ValueError, NotImplementedError) as e:  # codec not built into this pyarrow
        raise UnsupportedParquetFeature(f"codec {name}: {e}") from e


# ---- statistics ----------------------------------------------------------


def expected_physical_type(dtype: DataType) -> int:
    """The parquet physical type this repo's writer produces for a logical
    type (ColumnBatch.to_arrow hands pyarrow the internal representation:
    int64 micros for timestamps, unscaled int64 for decimals, int32 days
    for dates)."""
    root = dtype.root
    if root == TypeRoot.BOOLEAN:
        return T_BOOLEAN
    if root in (TypeRoot.TINYINT, TypeRoot.SMALLINT, TypeRoot.INT, TypeRoot.DATE, TypeRoot.TIME):
        return T_INT32
    if root in (TypeRoot.BIGINT, TypeRoot.TIMESTAMP, TypeRoot.TIMESTAMP_LTZ, TypeRoot.DECIMAL):
        return T_INT64
    if root == TypeRoot.FLOAT:
        return T_FLOAT
    if root == TypeRoot.DOUBLE:
        return T_DOUBLE
    if root in (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY):
        return T_BYTE_ARRAY
    raise UnsupportedParquetFeature(f"logical type {root} has no native decode")


_STAT_UNPACK = {T_INT32: "<i", T_INT64: "<q", T_FLOAT: "<f", T_DOUBLE: "<d"}

# a truncated BYTE_ARRAY max is only a valid upper bound if the writer bumped
# it; below this length pyarrow never truncates, so the bound is exact
_STAT_TRUST_LEN = 64


def _stat_value(raw: bytes | None, physical: int, dtype: DataType):
    if raw is None:
        return None
    if physical == T_BOOLEAN:
        return bool(raw[0]) if raw else None
    fmt = _STAT_UNPACK.get(physical)
    if fmt is not None:
        return struct.unpack(fmt, raw)[0] if len(raw) == struct.calcsize(fmt) else None
    if physical == T_BYTE_ARRAY:
        if len(raw) >= _STAT_TRUST_LEN:
            return None  # possibly truncated: don't prune on it
        if dtype.root in (TypeRoot.BINARY, TypeRoot.VARBINARY):
            return raw
        try:
            # UTF-8 byte order == codepoint order, so the comparison
            # semantics match predicate literals
            return raw.decode("utf-8")
        except UnicodeDecodeError:
            return None
    return None


def chunk_field_stats(chunk: ColumnChunkInfo, dtype: DataType, num_rows: int) -> FieldStats | None:
    """Thrift Statistics → FieldStats for Predicate.test_stats row-group
    pruning (the native analog of parquet.py::_row_group_stats)."""
    st = chunk.stats
    if not st:
        return None
    # prefer min_value/max_value (6/5, well-defined order); the deprecated
    # min/max (2/1) only for signed numeric types where old order == new
    lo_raw = st.get(6) if 6 in st else (st.get(2) if chunk.physical_type != T_BYTE_ARRAY else None)
    hi_raw = st.get(5) if 5 in st else (st.get(1) if chunk.physical_type != T_BYTE_ARRAY else None)
    lo = _stat_value(lo_raw, chunk.physical_type, dtype)
    hi = _stat_value(hi_raw, chunk.physical_type, dtype)
    nulls = st.get(3)
    if lo is None or hi is None:
        if nulls is None:
            return None
        return FieldStats(None, None, nulls, num_rows)
    return FieldStats(lo, hi, nulls, num_rows)
