"""Minimal Thrift compact-protocol reader for Parquet metadata.

Parquet's footer (FileMetaData) and every page header are TCompactProtocol
structs. The arrow path parses them inside C++; the native decode subsystem
parses them here so the whole container walk — footer → row groups → column
chunks → page headers — happens without pyarrow on the hot path.

The parser is generic: `read_struct` returns {field_id: value} dicts with
nested structs/lists parsed recursively. The parquet.thrift field-id → name
mapping lives in container.py, which wraps these dicts in typed views. Only
the protocol features parquet metadata actually uses are implemented (no
maps with non-byte keys beyond the wire format, no exotic types).

Wire format (thrift compact protocol spec):
  * varint       — ULEB128
  * i16/i32/i64  — zigzag varint
  * field header — one byte: (id-delta << 4) | type; delta 0 = long form
                   (type byte, then zigzag varint field id)
  * bool         — encoded IN the field-header type nibble (1=true, 2=false);
                   a full byte inside collections
  * binary       — varint length + bytes
  * list/set     — one byte (size << 4 | elem-type); size 15 = varint follows
  * double       — 8 bytes little-endian (compact protocol, unlike binary)
"""

from __future__ import annotations

import struct

__all__ = ["ThriftError", "read_struct", "read_varint", "zigzag"]


class ThriftError(ValueError):
    """Malformed compact-protocol bytes (truncated varint, bad type nibble)."""


# compact-protocol type nibbles
CT_STOP = 0
CT_TRUE = 1
CT_FALSE = 2
CT_BYTE = 3
CT_I16 = 4
CT_I32 = 5
CT_I64 = 6
CT_DOUBLE = 7
CT_BINARY = 8
CT_LIST = 9
CT_SET = 10
CT_MAP = 11
CT_STRUCT = 12


def read_varint(buf, pos: int) -> tuple[int, int]:
    """(value, new_pos) — ULEB128."""
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ThriftError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ThriftError("varint too long")


def zigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _read_value(buf, pos: int, ctype: int):
    if ctype == CT_BYTE:
        v = buf[pos]
        return v - 256 if v >= 128 else v, pos + 1
    if ctype in (CT_I16, CT_I32, CT_I64):
        v, pos = read_varint(buf, pos)
        return zigzag(v), pos
    if ctype == CT_DOUBLE:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if ctype == CT_BINARY:
        n, pos = read_varint(buf, pos)
        return bytes(buf[pos : pos + n]), pos + n
    if ctype in (CT_LIST, CT_SET):
        return _read_list(buf, pos)
    if ctype == CT_MAP:
        return _read_map(buf, pos)
    if ctype == CT_STRUCT:
        return read_struct(buf, pos)
    raise ThriftError(f"unexpected compact type {ctype}")


def _read_list(buf, pos: int):
    header = buf[pos]
    pos += 1
    size = header >> 4
    etype = header & 0xF
    if size == 15:
        size, pos = read_varint(buf, pos)
    out = []
    for _ in range(size):
        if etype in (CT_TRUE, CT_FALSE):
            # bool elements are full bytes inside collections
            out.append(buf[pos] == CT_TRUE)
            pos += 1
        else:
            v, pos = _read_value(buf, pos, etype)
            out.append(v)
    return out, pos


def _read_map(buf, pos: int):
    size, pos = read_varint(buf, pos)
    out = {}
    if size == 0:
        return out, pos
    kv = buf[pos]
    pos += 1
    ktype, vtype = kv >> 4, kv & 0xF
    for _ in range(size):
        k, pos = _read_value(buf, pos, ktype)
        v, pos = _read_value(buf, pos, vtype)
        out[k] = v
    return out, pos


def read_struct(buf, pos: int = 0) -> tuple[dict[int, object], int]:
    """Parse one struct starting at `pos`: ({field_id: value}, end_pos).

    Booleans folded into field headers come back as Python bools; nested
    structs as dicts; lists as Python lists; binaries as bytes.
    """
    out: dict[int, object] = {}
    fid = 0
    while True:
        if pos >= len(buf):
            raise ThriftError("truncated struct (no STOP)")
        header = buf[pos]
        pos += 1
        if header == CT_STOP:
            return out, pos
        delta = header >> 4
        ctype = header & 0xF
        if delta:
            fid += delta
        else:
            v, pos = read_varint(buf, pos)
            fid = zigzag(v)
        if ctype == CT_TRUE:
            out[fid] = True
        elif ctype == CT_FALSE:
            out[fid] = False
        else:
            out[fid], pos = _read_value(buf, pos, ctype)
