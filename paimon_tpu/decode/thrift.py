"""Minimal Thrift compact-protocol reader AND writer for Parquet metadata.

Parquet's footer (FileMetaData) and every page header are TCompactProtocol
structs. The arrow path parses them inside C++; the native decode subsystem
parses them here so the whole container walk — footer → row groups → column
chunks → page headers — happens without pyarrow on the hot path.

The writer dual lives here too: `build_struct` takes (field_id, type,
value) triples and emits the exact wire bytes `read_struct` parses — the
native encode subsystem (paimon_tpu.encode) uses it for page headers and
the footer, so encoder and decoder share one protocol implementation.

The parser is generic: `read_struct` returns {field_id: value} dicts with
nested structs/lists parsed recursively. The parquet.thrift field-id → name
mapping lives in container.py, which wraps these dicts in typed views. Only
the protocol features parquet metadata actually uses are implemented (no
maps with non-byte keys beyond the wire format, no exotic types).

Wire format (thrift compact protocol spec):
  * varint       — ULEB128
  * i16/i32/i64  — zigzag varint
  * field header — one byte: (id-delta << 4) | type; delta 0 = long form
                   (type byte, then zigzag varint field id)
  * bool         — encoded IN the field-header type nibble (1=true, 2=false);
                   a full byte inside collections
  * binary       — varint length + bytes
  * list/set     — one byte (size << 4 | elem-type); size 15 = varint follows
  * double       — 8 bytes little-endian (compact protocol, unlike binary)
"""

from __future__ import annotations

import struct

__all__ = [
    "ThriftError",
    "read_struct",
    "read_varint",
    "zigzag",
    "zigzag_encode",
    "append_uvarint",
    "build_struct",
]


class ThriftError(ValueError):
    """Malformed compact-protocol bytes (truncated varint, bad type nibble)."""


# compact-protocol type nibbles
CT_STOP = 0
CT_TRUE = 1
CT_FALSE = 2
CT_BYTE = 3
CT_I16 = 4
CT_I32 = 5
CT_I64 = 6
CT_DOUBLE = 7
CT_BINARY = 8
CT_LIST = 9
CT_SET = 10
CT_MAP = 11
CT_STRUCT = 12


def read_varint(buf, pos: int) -> tuple[int, int]:
    """(value, new_pos) — ULEB128."""
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ThriftError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ThriftError("varint too long")


def zigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _read_value(buf, pos: int, ctype: int):
    if ctype == CT_BYTE:
        v = buf[pos]
        return v - 256 if v >= 128 else v, pos + 1
    if ctype in (CT_I16, CT_I32, CT_I64):
        v, pos = read_varint(buf, pos)
        return zigzag(v), pos
    if ctype == CT_DOUBLE:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if ctype == CT_BINARY:
        n, pos = read_varint(buf, pos)
        return bytes(buf[pos : pos + n]), pos + n
    if ctype in (CT_LIST, CT_SET):
        return _read_list(buf, pos)
    if ctype == CT_MAP:
        return _read_map(buf, pos)
    if ctype == CT_STRUCT:
        return read_struct(buf, pos)
    raise ThriftError(f"unexpected compact type {ctype}")


def _read_list(buf, pos: int):
    header = buf[pos]
    pos += 1
    size = header >> 4
    etype = header & 0xF
    if size == 15:
        size, pos = read_varint(buf, pos)
    out = []
    for _ in range(size):
        if etype in (CT_TRUE, CT_FALSE):
            # bool elements are full bytes inside collections
            out.append(buf[pos] == CT_TRUE)
            pos += 1
        else:
            v, pos = _read_value(buf, pos, etype)
            out.append(v)
    return out, pos


def _read_map(buf, pos: int):
    size, pos = read_varint(buf, pos)
    out = {}
    if size == 0:
        return out, pos
    kv = buf[pos]
    pos += 1
    ktype, vtype = kv >> 4, kv & 0xF
    for _ in range(size):
        k, pos = _read_value(buf, pos, ktype)
        v, pos = _read_value(buf, pos, vtype)
        out[k] = v
    return out, pos


def read_struct(buf, pos: int = 0) -> tuple[dict[int, object], int]:
    """Parse one struct starting at `pos`: ({field_id: value}, end_pos).

    Booleans folded into field headers come back as Python bools; nested
    structs as dicts; lists as Python lists; binaries as bytes.
    """
    out: dict[int, object] = {}
    fid = 0
    while True:
        if pos >= len(buf):
            raise ThriftError("truncated struct (no STOP)")
        header = buf[pos]
        pos += 1
        if header == CT_STOP:
            return out, pos
        delta = header >> 4
        ctype = header & 0xF
        if delta:
            fid += delta
        else:
            v, pos = read_varint(buf, pos)
            fid = zigzag(v)
        if ctype == CT_TRUE:
            out[fid] = True
        elif ctype == CT_FALSE:
            out[fid] = False
        else:
            out[fid], pos = _read_value(buf, pos, ctype)


# ---- writer (the encode dual) --------------------------------------------


def zigzag_encode(n: int) -> int:
    """Signed int → zigzag unsigned (inverse of `zigzag`)."""
    return (n << 1) ^ (n >> 63)


def append_uvarint(out: bytearray, v: int) -> None:
    while v > 0x7F:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _append_value(out: bytearray, ctype: int, value) -> None:
    if ctype in (CT_I16, CT_I32, CT_I64):
        append_uvarint(out, zigzag_encode(int(value)))
    elif ctype == CT_BYTE:
        out.append(int(value) & 0xFF)
    elif ctype == CT_DOUBLE:
        out += struct.pack("<d", float(value))
    elif ctype == CT_BINARY:
        raw = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        append_uvarint(out, len(raw))
        out += raw
    elif ctype == CT_STRUCT:
        # nested structs are pre-built bytes (build_struct output) or
        # field-triple lists, appended in place
        out += value if isinstance(value, (bytes, bytearray)) else build_struct(value)
    elif ctype in (CT_LIST, CT_SET):
        etype, elems = value
        if len(elems) < 15:
            out.append((len(elems) << 4) | etype)
        else:
            out.append((15 << 4) | etype)
            append_uvarint(out, len(elems))
        for e in elems:
            if etype in (CT_TRUE, CT_FALSE):
                out.append(CT_TRUE if e else CT_FALSE)
            else:
                _append_value(out, etype, e)
    else:
        raise ThriftError(f"cannot write compact type {ctype}")


def build_struct(fields) -> bytes:
    """(field_id, ctype, value) triples → compact-protocol struct bytes.

    None values are skipped (optional thrift fields). Bools use CT_TRUE with
    a bool value — the writer folds them into the field header exactly like
    the spec. Nested structs pass pre-built bytes (or a triple list); lists
    pass (elem_ctype, [values]). Fields are sorted by id so the short-form
    delta header applies wherever it can."""
    out = bytearray()
    prev = 0
    for fid, ctype, value in sorted(fields, key=lambda f: f[0]):
        if value is None:
            continue
        if ctype in (CT_TRUE, CT_FALSE):
            ctype = CT_TRUE if value else CT_FALSE
            delta = fid - prev
            if 0 < delta <= 15:
                out.append((delta << 4) | ctype)
            else:
                out.append(ctype)
                append_uvarint(out, zigzag_encode(fid))
            prev = fid
            continue
        delta = fid - prev
        if 0 < delta <= 15:
            out.append((delta << 4) | ctype)
        else:
            out.append(ctype)
            append_uvarint(out, zigzag_encode(fid))
        prev = fid
        _append_value(out, ctype, value)
    out.append(CT_STOP)
    return bytes(out)
