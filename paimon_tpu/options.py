"""Typed configuration system.

Capability parity with the reference options kernel
(/root/reference/paimon-common/.../options/Options.java, ConfigOption with
typed defaults + fallback keys; CoreOptions.java — the table option surface
with MergeEngine/StartupMode/ChangelogProducer/SortEngine enums). Options are
plain string maps persisted inside the schema JSON; ConfigOption gives them
types, defaults, and fallback keys.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Generic, Mapping, TypeVar

T = TypeVar("T")

__all__ = [
    "ConfigOption",
    "Options",
    "MemorySize",
    "CoreOptions",
    "MergeEngine",
    "StartupMode",
    "ChangelogProducer",
    "SortEngine",
    "BucketMode",
]


_DURATION_UNITS = {
    "ms": 1,
    "s": 1000,
    "sec": 1000,
    "min": 60_000,
    "m": 60_000,
    "h": 3_600_000,
    "d": 86_400_000,
}


def parse_duration_millis(v: "str | int | float") -> int:
    """'1 h' / '30s' / '100 ms' / bare number (millis) -> millis int
    (reference TimeUtils.parseDuration)."""
    if isinstance(v, (int, float)):
        return int(v)
    t = str(v).strip().lower().replace(" ", "")
    for u in ("ms", "sec", "min", "s", "m", "h", "d"):
        if t.endswith(u) and t[: -len(u)].replace(".", "", 1).isdigit():
            return int(float(t[: -len(u)]) * _DURATION_UNITS[u])
    return int(float(t))


class MemorySize(int):
    """Bytes, parseable from '128 mb' style strings."""

    _UNITS = {"b": 1, "kb": 1 << 10, "mb": 1 << 20, "gb": 1 << 30, "tb": 1 << 40}

    @staticmethod
    def parse(s: "str | int | MemorySize") -> "MemorySize":
        if isinstance(s, int):
            return MemorySize(s)
        t = s.strip().lower().replace(" ", "")
        for u in ("tb", "gb", "mb", "kb", "b"):
            if t.endswith(u):
                return MemorySize(int(float(t[: -len(u)]) * MemorySize._UNITS[u]))
        return MemorySize(int(t))

    def __str__(self) -> str:
        return f"{int(self)} b"


@dataclass(frozen=True)
class ConfigOption(Generic[T]):
    key: str
    default: T
    parser: Callable[[Any], T]
    description: str = ""
    fallback_keys: tuple[str, ...] = ()

    @staticmethod
    def string(key: str, default: str | None = None, description: str = "", fallback: tuple[str, ...] = ()):
        return ConfigOption(key, default, lambda v: None if v is None else str(v), description, fallback)

    @staticmethod
    def int_(key: str, default: int | None = None, description: str = "", fallback: tuple[str, ...] = ()):
        return ConfigOption(key, default, lambda v: None if v is None else int(v), description, fallback)

    @staticmethod
    def float_(key: str, default: float | None = None, description: str = ""):
        return ConfigOption(key, default, lambda v: None if v is None else float(v), description)

    @staticmethod
    def bool_(key: str, default: bool = False, description: str = "", fallback: tuple[str, ...] = ()):
        return ConfigOption(
            key, default, lambda v: v if isinstance(v, bool) else str(v).lower() == "true", description, fallback
        )

    @staticmethod
    def memory(key: str, default: str, description: str = ""):
        return ConfigOption(key, MemorySize.parse(default), MemorySize.parse, description)

    @staticmethod
    def duration(key: str, default: "str | None", description: str = "", fallback: tuple[str, ...] = ()):
        """Duration in MILLIS, parsed from '1 h' / '30 s' / '100 ms' / bare
        millis (reference TimeUtils.parseDuration). Value type: int | None."""
        d = None if default is None else parse_duration_millis(default)
        return ConfigOption(key, d, lambda v: None if v is None else parse_duration_millis(v), description, fallback)

    @staticmethod
    def enum(key: str, enum_cls, default, description: str = "", fallback: tuple[str, ...] = ()):
        def parse(v):
            if isinstance(v, enum_cls):
                return v
            return enum_cls(str(v).lower().replace("_", "-"))

        return ConfigOption(key, default, parse, description, fallback)


class Options:
    """A string->value map with typed access via ConfigOption."""

    def __init__(self, data: Mapping[str, Any] | None = None):
        self._data: dict[str, Any] = dict(data or {})

    def get(self, option: ConfigOption[T]) -> T:
        for key in (option.key, *option.fallback_keys):
            if key in self._data:
                return option.parser(self._data[key])
        return option.default

    def set(self, option: "ConfigOption | str", value: Any) -> "Options":
        key = option if isinstance(option, str) else option.key
        self._data[key] = value
        return self

    def contains(self, option: "ConfigOption | str") -> bool:
        key = option if isinstance(option, str) else option.key
        return key in self._data

    def remove(self, key: str) -> None:
        self._data.pop(key, None)

    def to_map(self) -> dict[str, str]:
        return {k: (v if isinstance(v, str) else str(v)) for k, v in self._data.items()}

    def copy(self) -> "Options":
        return Options(self._data)

    def update(self, other: "Options | Mapping[str, Any]") -> "Options":
        self._data.update(other._data if isinstance(other, Options) else other)
        return self

    def __eq__(self, o):
        return isinstance(o, Options) and self._data == o._data

    def __repr__(self):
        return f"Options({self._data})"


# ---- enums mirroring CoreOptions (reference CoreOptions.java:1937,1966,2107,2321)


class MergeEngine(str, enum.Enum):
    DEDUPLICATE = "deduplicate"
    PARTIAL_UPDATE = "partial-update"
    AGGREGATE = "aggregation"
    FIRST_ROW = "first-row"


class StartupMode(str, enum.Enum):
    DEFAULT = "default"
    LATEST_FULL = "latest-full"
    LATEST = "latest"
    FROM_TIMESTAMP = "from-timestamp"
    FROM_SNAPSHOT = "from-snapshot"
    FROM_SNAPSHOT_FULL = "from-snapshot-full"
    COMPACTED_FULL = "compacted-full"

    @classmethod
    def _missing_(cls, value):
        if value == "full":  # deprecated legacy value (reference StartupMode.FULL)
            return cls.LATEST_FULL
        return None


class ChangelogProducer(str, enum.Enum):
    NONE = "none"
    INPUT = "input"
    FULL_COMPACTION = "full-compaction"
    LOOKUP = "lookup"


class SortEngine(str, enum.Enum):
    XLA_SEGMENTED = "xla-segmented"  # device sort+segment-reduce (default)
    PALLAS = "pallas"  # lax.sort + pallas fused boundary/keep-last pass
    NUMPY = "numpy"  # host oracle


class BucketMode(str, enum.Enum):
    FIXED = "fixed"
    DYNAMIC = "dynamic"
    UNAWARE = "unaware"


class CoreOptions:
    """The table option surface (reference CoreOptions.java — 149 options;
    the ones that drive behavior here, same keys where concepts map 1:1)."""

    BUCKET = ConfigOption.int_("bucket", -1, "Number of buckets (-1 = dynamic/unaware).")
    BUCKET_KEY = ConfigOption.string("bucket-key", None, "Comma-separated bucket key columns (default: primary key).")
    PATH = ConfigOption.string("path", None, "Table path.")
    FILE_FORMAT = ConfigOption.string("file.format", "parquet", "Data file format: parquet|orc|lance.")
    FILE_COMPRESSION = ConfigOption.string("file.compression", "zstd", "Data file compression codec.")
    FILE_COMPRESSION_ZSTD_LEVEL = ConfigOption.int_(
        "file.compression.zstd-level", 1, "zstd level for data files (higher = smaller + slower)."
    )
    FILE_COMPRESSION_PER_LEVEL = ConfigOption.string(
        "file.compression.per.level",
        None,
        "Per-LSM-level compression override, e.g. '0:lz4,5:zstd' (level-0 "
        "files are short-lived: cheap codec; bottom level: dense codec).",
    )
    FILE_FORMAT_PER_LEVEL = ConfigOption.string(
        "file.format.per.level",
        None,
        "Per-LSM-level format override, e.g. '0:avro,5:parquet' (row format "
        "for hot small runs, columnar for the settled bottom level).",
    )
    FILE_BLOCK_SIZE = ConfigOption(
        "file.block-size",
        None,
        lambda v: None if v is None else MemorySize.parse(v),
        "Write block size: orc stripe / parquet row-group bytes.",
    )
    PARQUET_ENABLE_DICTIONARY = ConfigOption.bool_(
        "parquet.enable.dictionary", True, "Dictionary encoding for parquet data files."
    )
    FORMAT_PARQUET_DECODER = ConfigOption.string(
        "format.parquet.decoder",
        "arrow",
        "Parquet read decoder: 'arrow' (pyarrow C++ columnar decode) or "
        "'native' (paimon_tpu.decode: thrift-parsed pages, vectorized "
        "RLE/dict/delta kernels, compressed-domain predicate pushdown that "
        "expands only surviving pages; falls back to arrow per file on "
        "unsupported container features).",
    )
    FORMAT_PARQUET_ENCODER = ConfigOption.string(
        "format.parquet.encoder",
        "arrow",
        "Parquet write encoder: 'arrow' (ColumnBatch.to_arrow + pyarrow "
        "pq.write_table) or 'native' (paimon_tpu.encode: vectorized "
        "PLAIN/RLE/DELTA/dictionary kernels writing pages straight from "
        "columnar arrays, reusing the merge path's string pools for "
        "dictionary pages; falls back to arrow per file on unsupported "
        "shapes such as nested columns).",
    )
    READ_BATCH_SIZE = ConfigOption.int_(
        "read.batch-size", None, "Rows per record batch handed to engine surfaces (unset: 1M-row chunks)."
    )
    MANIFEST_FORMAT = ConfigOption.string("manifest.format", "jsonl", "Manifest file format.")
    MANIFEST_COMPRESSION = ConfigOption.string(
        "manifest.compression",
        "default",
        "Manifest codec: default (zstd for jsonl / deflate for avro) or none.",
    )
    TARGET_FILE_SIZE = ConfigOption.memory("target-file-size", "128 mb", "Rolling target size for data files.")
    WRITE_BUFFER_SIZE = ConfigOption.memory("write-buffer-size", "256 mb", "Memtable size before flush.")
    WRITE_BUFFER_ROWS = ConfigOption.int_("write-buffer-rows", 1_000_000, "Memtable row cap before flush.")
    WRITE_ONLY = ConfigOption.bool_(
        "write-only",
        False,
        "Skip compaction (dedicated compact job mode).",
        fallback=("write.compaction-skip",),
    )
    WRITE_BUFFER_MAX_MEMORY = ConfigOption.memory(
        "write.buffer.max-memory",
        "0 b",
        "Admission-control byte budget over ALL buffered memtables and "
        "in-flight offloaded flushes of a write job (0 = off). Above "
        "write.buffer.stop-trigger of this budget new writes first throttle "
        "(bounded block while flushes drain, deadline "
        "write.buffer.block-timeout) and then reject with "
        "WriterBackpressureError.",
    )
    WRITE_BUFFER_STOP_TRIGGER = ConfigOption.float_(
        "write.buffer.stop-trigger",
        0.9,
        "Fraction of write.buffer.max-memory at which incoming writes stop "
        "being admitted immediately and start throttling.",
    )
    WRITE_BUFFER_BLOCK_TIMEOUT = ConfigOption.duration(
        "write.buffer.block-timeout",
        "10 s",
        "How long a throttled write blocks waiting for flushes to release "
        "buffer budget before it is rejected with WriterBackpressureError.",
    )
    WRITE_BUFFER_MAX_PENDING_FLUSHES = ConfigOption.int_(
        "write.buffer.max-pending-flushes",
        4,
        "Cap on memtables queued behind the offloaded flush workers across "
        "a write job (0 = unlimited). At the cap the writer encodes inline — "
        "the caller pays — so a slow encoder can never queue unbounded "
        "memtables.",
    )
    WRITE_BUFFER_SPILLABLE = ConfigOption.bool_(
        "write-buffer-spillable", False, "Spill the write buffer to local disk under memory pressure."
    )
    WRITE_BUFFER_SPILL_ROWS = ConfigOption.int_(
        "write-buffer-spill.rows", 256 * 1024, "In-memory rows before a spill segment is written."
    )
    LOCAL_MERGE_BUFFER_SIZE = ConfigOption.memory(
        "local-merge-buffer-size",
        "0 b",
        "When >0, pre-merge high-churn keys in a local buffer BEFORE bucket "
        "routing (reference LocalMergeOperator; deduplicate engine only).",
    )
    WRITE_BUFFER_SPILL_SIZE = ConfigOption.memory(
        "write-buffer-spill.size", "64 mb", "In-memory bytes before a spill segment is written."
    )
    MERGE_ENGINE = ConfigOption.enum("merge-engine", MergeEngine, MergeEngine.DEDUPLICATE, "How same-key records merge.")
    IGNORE_DELETE = ConfigOption.bool_(
        "ignore-delete",
        False,
        "Ignore -D records on write/merge.",
        fallback=(
            "first-row.ignore-delete",
            "deduplicate.ignore-delete",
            "partial-update.ignore-delete",
        ),
    )
    SORT_ENGINE = ConfigOption.enum("sort-engine", SortEngine, SortEngine.XLA_SEGMENTED, "Merge kernel backend.")
    MERGE_LANE_COMPRESSION = ConfigOption.bool_(
        "merge.lane-compression",
        True,
        "Compress uint32 key lanes before every merge, compaction rewrite, "
        "and sort-compact sort: drop batch-constant lanes, bit-pack adjacent "
        "narrowed lanes into fused uint32 operands, and lead wide keys with "
        "a device-computed offset-value code lane (OVC). Output is "
        "bit-identical to the uncompressed path; off restores it.",
    )
    MERGE_DICT_DOMAIN = ConfigOption.bool_(
        "merge.dict-domain",
        False,
        "Carry dictionary codes as the merge currency end-to-end: readers "
        "return (pool, codes) columns for dictionary-encoded string/bytes "
        "chunks instead of expanding them, per-file pools unify into one "
        "sorted merge domain (ops.dicts — the LSM-OPD/LUDA move), re-mapped "
        "codes become key lanes with zero searchsorted, dedup/partial-"
        "update/aggregation and sort-compact run on codes, and flush/"
        "compaction encode emits dictionary pages straight from the unified "
        "pool. Falls back to the expanded path per file/merge when a column "
        "is not dictionary-encoded or the domain exceeds "
        "merge.dict-domain.pool-limit. Output rows are bit-identical to the "
        "expanded path. PAIMON_TPU_DICT_DOMAIN overrides.",
    )
    MERGE_DICT_DOMAIN_POOL_LIMIT = ConfigOption.int_(
        "merge.dict-domain.pool-limit",
        1 << 20,
        "Largest dictionary domain (distinct values per column) the "
        "code-domain merge path will carry — a single file dictionary or a "
        "unified merge pool above this expands to strings instead "
        "(dict{fallback_expanded}). PAIMON_TPU_DICT_POOL_LIMIT overrides.",
    )
    JOIN_ALGORITHM = ConfigOption.string(
        "join.algorithm",
        "auto",
        "Equi-join kernel: 'hash' probes a sorted single-operand key by "
        "binary search, 'sort-merge' routes multi-operand keys through the "
        "merge kernel's sorted_segments seam (inheriting sort-engine=pallas), "
        "'auto' picks hash exactly when the global lane plan packed the key "
        "into one fused uint32 operand.",
    )
    JOIN_ENGINE = ConfigOption.string(
        "join.engine",
        "auto",
        "Join execution backend: 'numpy' (host lexsort/searchsorted), 'xla' "
        "or 'pallas' (device kernels). 'auto' mirrors the merge rule — host "
        "below join.device-rows or on a CPU-only platform, device otherwise, "
        "with the device flavor following sort-engine. "
        "PAIMON_TPU_JOIN_ENGINE overrides.",
    )
    JOIN_DEVICE_ROWS = ConfigOption.int_(
        "join.device-rows",
        4096,
        "Smallest combined row count (probe + build) the auto engine sends "
        "to the device kernels; smaller joins stay on the host where "
        "dispatch overhead dominates.",
    )
    JOIN_CHUNK_ROWS = ConfigOption.int_(
        "join.chunk-rows",
        1 << 20,
        "Probe rows per join partition: a probe side larger than this "
        "splits into ceil(rows / chunk) key-disjoint partitions (bounding "
        "device batch size), with heavy-hitter keys skew-split across all "
        "partitions (JSPIM). join.partitions overrides the count directly.",
    )
    JOIN_PARTITIONS = ConfigOption.int_(
        "join.partitions",
        0,
        "Explicit join partition count (0 = derive from join.chunk-rows). "
        "Values > 1 enable the skew-aware split even for small probes.",
    )
    JOIN_SKEW_FACTOR = ConfigOption.float_(
        "join.skew-factor",
        0.5,
        "A join key is a heavy hitter when it holds >= this fraction of "
        "the fair per-partition probe share (probe_rows / partitions) — a "
        "hot key cannot be subdivided by hashing, so it is dealt "
        "round-robin across every partition with its build rows "
        "replicated, and never serializes one partition (JSPIM).",
    )
    JOIN_PUSHDOWN_IN_LIMIT = ConfigOption.int_(
        "join.pushdown-in-limit",
        1024,
        "SELECT ... JOIN planning: when the smaller side's distinct join "
        "keys number at most this, the big side's scan is pruned with an "
        "IN predicate over those keys (file/row-group skipping); above it, "
        "a BETWEEN over the small side's key range is pushed instead.",
    )
    MERGE_EXEC_ENGINE = ConfigOption.string(
        "merge.engine",
        "single",
        "Merge EXECUTION engine (orthogonal to merge-engine, which picks the "
        "per-key semantics): 'single' runs each bucket's sort-merge as its "
        "own device call; 'mesh' routes scans, compaction rewrites and "
        "writer flushes through the mesh-sharded execution layer "
        "(parallel.mesh_exec.MeshExecutor) — per-bucket merges batch into "
        "one shard_map per merge-function family over the mesh's bucket "
        "axis with globally-agreed lane plans, oversized buckets "
        "range-shuffle over the key axis, and the split pipeline feeds one "
        "prefetch lane per device. Output is bit-identical to 'single'; a "
        "1-device or shard_map-less environment degrades to 'single' "
        "automatically (cpu fallback). PAIMON_TPU_MERGE_ENGINE overrides.",
    )
    PARALLEL_MESH_ENABLED = ConfigOption.bool_(
        "parallel.mesh.enabled",
        False,
        "Execute write flush / compaction rewrite / merge-read over the device "
        "mesh: per-bucket merge jobs batch into one shard_map over the bucket "
        "axis; oversized buckets range-shuffle over the key axis.",
    )
    DATA_FILE_INCLUDE_KEY_COLUMNS = ConfigOption.bool_(
        "data-file.include-key-columns",
        False,
        "Duplicate the trimmed primary key as _KEY_<name> columns at the "
        "front of every data file (the reference KeyValue.schema layout) — "
        "with manifest.format=avro this makes the whole table "
        "reference-layout on disk.",
    )
    SOURCE_SPLIT_TARGET_SIZE = ConfigOption.memory(
        "source.split.target-size", "128 mb", "Target size of one batch-read split."
    )
    SOURCE_SPLIT_OPEN_FILE_COST = ConfigOption.memory(
        "source.split.open-file-cost", "4 mb", "Weight floor per file when packing splits."
    )
    FS_RETRY_MAX_ATTEMPTS = ConfigOption.int_(
        "fs.retry.max-attempts",
        3,
        "Total tries per FileIO op before a transient fault becomes fatal "
        "(resilience.RetryingFileIO, installed by the store). 1 disables "
        "retrying entirely — the wrapper is then not even constructed.",
    )
    FS_RETRY_INITIAL_BACKOFF = ConfigOption.duration(
        "fs.retry.initial-backoff",
        "10 ms",
        "Base backoff between IO retries; actual sleeps use decorrelated "
        "jitter (U(base, 3*prev), capped by fs.retry.max-backoff).",
    )
    FS_RETRY_MAX_BACKOFF = ConfigOption.duration(
        "fs.retry.max-backoff", "2 s", "Cap on a single IO retry backoff."
    )
    FS_IO_TIMEOUT = ConfigOption.duration(
        "fs.io.timeout",
        None,
        "Per-op wall-clock deadline spanning all retry attempts; past it the "
        "op fails with IODeadlineExceeded (counted in io{timeouts}). Unset = "
        "unbounded.",
    )
    COMMIT_MAX_RETRIES = ConfigOption.int_(
        "commit.max-retries",
        10,
        "Bounded commit retry loop: snapshot-CAS races (and conflict "
        "re-plans) are retried this many times with commit.retry-backoff "
        "between rounds before the commit gives up (CommitGiveUpError). The "
        "seed looped forever — a livelock under heavy contention.",
    )
    COMMIT_RETRY_BACKOFF = ConfigOption.duration(
        "commit.retry-backoff",
        "10 ms",
        "Base backoff between commit retry rounds (decorrelated jitter, "
        "capped at 100x base) so racing committers desynchronize.",
    )
    SOAK_DURATION = ConfigOption.duration(
        "soak.duration",
        "45 s",
        "Traffic-soak harness (service.soak): how long the concurrent "
        "writer/reader/churn threads run before the final drain and orphan "
        "sweep.",
    )
    SOAK_WRITERS = ConfigOption.int_(
        "soak.writers", 3, "Traffic-soak harness: number of concurrent committer threads."
    )
    SOAK_READERS = ConfigOption.int_(
        "soak.readers",
        2,
        "Traffic-soak harness: number of concurrent snapshot-reader threads "
        "(each read is verified against the serialized oracle log).",
    )
    SOAK_FAULT_POSSIBILITY = ConfigOption.int_(
        "soak.fault.possibility",
        0,
        "Traffic-soak harness: inject a transient IO fault on 1/N of "
        "filesystem ops (0 = no faults; 20 = the 5% headline rate).",
    )
    SOAK_ROWS_PER_COMMIT = ConfigOption.int_(
        "soak.rows-per-commit", 400, "Traffic-soak harness: rows each writer commits per round."
    )
    SOAK_COMPACT_EVERY = ConfigOption.int_(
        "soak.compact-every",
        4,
        "Traffic-soak harness: every Nth commit of a writer forces a full "
        "compaction, driving the commit-conflict re-plan path on shared "
        "buckets.",
    )
    SOAK_PROCESS_DURATION = ConfigOption.duration(
        "soak.process.duration",
        "60 s",
        "Process-grain crash soak (service.proc_soak): how long the "
        "supervisor runs writer/reader OS processes (killing and respawning "
        "them) before the drain, oracle fold, and final sweep/audit.",
    )
    SOAK_PROCESS_WRITERS = ConfigOption.int_(
        "soak.process.writers",
        2,
        "Process-grain crash soak: number of concurrent writer OS processes "
        "(each with its own intent/ack journal, sharing only the warehouse "
        "filesystem).",
    )
    SOAK_PROCESS_READERS = ConfigOption.int_(
        "soak.process.readers",
        1,
        "Process-grain crash soak: number of reader OS processes pinning and "
        "scanning snapshots throughout the kill/respawn churn.",
    )
    SOAK_PROCESS_KILL_PERIOD = ConfigOption.duration(
        "soak.process.kill-period",
        "8 s",
        "Process-grain crash soak: mean interval between random SIGKILLs of "
        "writer processes (seeded; on top of the scripted "
        "PAIMON_TPU_CRASH_POINT kills). 0 = scripted kills only.",
    )
    SOAK_PROCESS_SWEEP_PERIOD = ConfigOption.duration(
        "soak.process.sweep-period",
        "12 s",
        "Process-grain crash soak: cadence of the supervisor's mid-soak "
        "orphan sweep (threshold soak.process kill debris older than ~45 s; "
        "a final sweep at threshold 0 runs after the drain regardless). "
        "0 = final sweep only.",
    )
    SOAK_MEGA_DURATION = ConfigOption.duration(
        "soak.mega.duration",
        "45 s",
        "Production mega-soak (service.mega_soak): how long each scenario "
        "cell runs its full process census (cluster mesh, gateway writers, "
        "getters, subscribers, SQL clients, churn threads) before the drain "
        "and the multi-plane oracle verdict.",
    )
    SOAK_MEGA_CLUSTER_WORKERS = ConfigOption.int_(
        "soak.mega.cluster-workers",
        2,
        "Production mega-soak: worker OS processes in the cluster plane of "
        "cells that enable it (mesh engine, adaptive compaction on).",
    )
    SOAK_MEGA_KILL_PERIOD = ConfigOption.duration(
        "soak.mega.kill-period",
        "9 s",
        "Production mega-soak: mean interval between seeded random SIGKILLs "
        "across all process kinds, on top of the scripted "
        "PAIMON_TPU_CRASH_POINT kill schedule. 0 = scripted kills only.",
    )
    SOAK_MEGA_CHAOS_READ = ConfigOption.float_(
        "soak.mega.chaos.read-ms",
        1.0,
        "Production mega-soak: mean injected read latency (ms) of the "
        "composed chaos store the whole warehouse lives on.",
    )
    SOAK_MEGA_CHAOS_WRITE = ConfigOption.float_(
        "soak.mega.chaos.write-ms",
        0.5,
        "Production mega-soak: mean injected write latency (ms) of the "
        "composed chaos store.",
    )
    SOAK_MEGA_CHAOS_POSSIBILITY = ConfigOption.int_(
        "soak.mega.chaos.possibility",
        200,
        "Production mega-soak: inject a transient IO fault on 1/N of "
        "filesystem ops across every plane (absorbed by the fs.retry "
        "budget; 0 = latency shaping only).",
    )
    CLUSTER_WORKERS = ConfigOption.int_(
        "cluster.workers",
        2,
        "Cluster service (service.cluster): number of worker OS processes "
        "the supervisor spawns. The coordinator splits the table's buckets "
        "into contiguous ranges, one per worker; each worker runs its local "
        "merge.engine=mesh executor over its shard and ships CommitMessages "
        "back — only the coordinator commits (the reference's "
        "single-parallelism committer).",
    )
    CLUSTER_DEVICES_PER_WORKER = ConfigOption.int_(
        "cluster.devices-per-worker",
        2,
        "Cluster service: virtual (forced-host) or real devices each worker "
        "process spans with its local mesh executor "
        "(--xla_force_host_platform_device_count in the spawned child).",
    )
    CLUSTER_HEARTBEAT_INTERVAL = ConfigOption.duration(
        "cluster.heartbeat-interval",
        "500 ms",
        "Cluster service: cadence of each worker's background heartbeat to "
        "the coordinator (also how it learns of assignment epoch changes).",
    )
    CLUSTER_HEARTBEAT_TIMEOUT = ConfigOption.duration(
        "cluster.heartbeat-timeout",
        "4 s",
        "Cluster service: a worker silent for this long is declared dead — "
        "its bucket range is reassigned (exactly once) to live workers, its "
        "in-flight debt-gate charges are released, and any CommitMessage it "
        "later ships for a reassigned bucket is rejected as stale.",
    )
    CLUSTER_ROUND_ROWS = ConfigOption.int_(
        "cluster.round-rows",
        256,
        "Cluster service soak/bench workers: rows per ingest round per "
        "owned bucket.",
    )
    CLUSTER_ADMIT_TIMEOUT = ConfigOption.duration(
        "cluster.admit-timeout",
        "30 s",
        "Cluster service: how long a worker keeps retrying the "
        "coordinator's debt-admission gate (read-amp ceiling enforced "
        "cluster-wide) before giving up on an ingest round.",
    )
    CLUSTER_COMPACTION_ENABLED = ConfigOption.bool_(
        "cluster.compaction.enabled",
        True,
        "Cluster service: run the coordinator-scheduled, worker-executed "
        "adaptive compaction drain (table.compactor policy deciding, the "
        "bucket's owning worker rewriting, the coordinator committing). "
        "Off = ingest only (read amplification unbounded).",
    )
    CLUSTER_RESCALE_TIMEOUT = ConfigOption.duration(
        "cluster.rescale.timeout",
        "120 s",
        "Elastic cluster: how long the coordinator waits for every owner's "
        "rescale rewrite shipment before abandoning the rescale (fence "
        "lifted, old bucket count kept, rewritten files left as orphans for "
        "the sweep). Worker deaths inside the window do not abort it — the "
        "reassignment machinery re-queues the dead owner's buckets on "
        "whoever inherits them.",
    )
    CLUSTER_REPLICA_HEAT_THRESHOLD = ConfigOption.float_(
        "cluster.replica.heat-threshold",
        0.0,
        "Elastic cluster: a bucket whose heat EMA (serve-side get rate plus "
        "the adaptive compactor's write-rate EMA, ops/s) crosses this gets "
        "a read replica on another live worker — the replica serves "
        "get_batch/subscribe/scan_frag off the shared-FS snapshot while the "
        "primary retains writes. 0 disables replica placement.",
    )
    CLUSTER_REPLICA_MAX_PER_BUCKET = ConfigOption.int_(
        "cluster.replica.max-per-bucket",
        1,
        "Elastic cluster: replica owners per hot bucket beyond the primary. "
        "Replicas decay back off when the bucket's heat EMA falls under "
        "half the threshold (hysteresis against flapping).",
    )
    CLUSTER_REPLICA_INTERVAL = ConfigOption.duration(
        "cluster.replica.interval",
        "1 s",
        "Elastic cluster: cadence of the coordinator's replica-placement "
        "pass (heat EMA refresh + promote/demote decisions). Every change "
        "bumps the route epoch so clients refresh immediately.",
    )
    SQL_CLUSTER_CODE_DOMAIN = ConfigOption.bool_(
        "sql.cluster.code-domain",
        True,
        "Distributed SQL (sql.cluster): ship GROUP BY keys coordinator-ward "
        "as (pruned dictionary pool, uint32 codes) and combine partials in "
        "the code domain via pool unification — no group key string ever "
        "expands on the wire or at the coordinator. Off = workers expand "
        "group key values and the coordinator re-encodes them. The "
        "PAIMON_TPU_SQL_CODE_DOMAIN env var overrides in either direction "
        "(the verify stage forces both paths).",
    )
    SQL_CLUSTER_SCAN_MAX_INFLIGHT = ConfigOption.int_(
        "sql.cluster.scan.max-inflight",
        4,
        "Distributed SQL: concurrent scan_frag fragments a worker serving "
        "plane executes before answering a typed BUSY (retry_after_ms) — "
        "a scan storm must not starve get_batch/subscribe serving. Shed "
        "fragments count into soak{shed_requests} beside every other "
        "serving-plane BUSY.",
    )
    SQL_CLUSTER_RETRY_TIMEOUT = ConfigOption.duration(
        "sql.cluster.retry-timeout",
        "30 s",
        "Distributed SQL: how long the coordinator keeps re-dispatching a "
        "query's unfinished fragments across route refreshes (worker "
        "deaths, reassignments, BUSY sheds) before the query fails.",
    )
    SQL_CLUSTER_FRAGMENT_CACHE = ConfigOption.bool_(
        "sql.cluster.fragment-cache",
        True,
        "Distributed SQL: cache aggregate fragment partials at the "
        "coordinator keyed on (snapshot id, bucket-layout epoch, fragment "
        "signature — semantic template plus every planned split). A "
        "repeated aggregate over an unchanged table answers without any "
        "worker RPC (sql{fragment_cache_hits}); a plan at a newer snapshot "
        "or under a rescaled bucket layout purges the table's stale "
        "entries.",
    )
    SQL_CLUSTER_SHUFFLE_THRESHOLD = ConfigOption.int_(
        "sql.cluster.shuffle.threshold",
        50_000,
        "Distributed SQL: estimated distinct-group count above which a "
        "GROUP BY combines via worker↔worker shuffle instead of at the "
        "coordinator. The estimate comes from the planned splits' file "
        "stats (integer key: global max-min+1; otherwise row count) at "
        "zero extra IO. The PAIMON_TPU_SQL_SHUFFLE env var forces the "
        "path on/off regardless of the estimate (the verify stage runs "
        "the parity suite both ways).",
    )
    SQL_CLUSTER_SHUFFLE_RANGES = ConfigOption.int_(
        "sql.cluster.shuffle.ranges",
        0,
        "Distributed SQL: number R of group-domain hash ranges a shuffle "
        "aggregation partitions into (each range owner unifies and "
        "reduces its range; the coordinator only concatenates). 0 = one "
        "range per live worker, the balanced default.",
    )
    GATEWAY_MAX_INFLIGHT = ConfigOption.int_(
        "gateway.max-inflight",
        64,
        "Multi-tenant gateway: default concurrent in-flight requests a "
        "tenant may hold before the gateway sheds with a typed "
        "'busy-inflight' ShedInfo (retry_after_ms hinted). Overridable "
        "per tenant via gateway.tenant.<id>.max-inflight.",
    )
    GATEWAY_BYTES_PER_SEC = ConfigOption.memory(
        "gateway.bytes-per-sec",
        "0 b",
        "Multi-tenant gateway: total request-byte budget per second shared "
        "weighted-fair across tenants (tenant i receives rate * w_i / sum "
        "of all configured weights, further capped by its own "
        "gateway.tenant.<id>.bytes-per-sec). 0 = unlimited; a tenant whose "
        "token bucket runs dry is shed with a typed 'throttling-bytes' "
        "ShedInfo whose retry_after_ms is the exact refill deadline.",
    )
    GATEWAY_TENANT_WEIGHT = ConfigOption.float_(
        "gateway.tenant.<id>.weight",
        1.0,
        "Multi-tenant gateway (templated key): tenant <id>'s weighted-fair "
        "share of gateway.bytes-per-sec. Untagged traffic lands in the "
        "'default' tenant with weight 1.0.",
    )
    GATEWAY_TENANT_MAX_INFLIGHT = ConfigOption.int_(
        "gateway.tenant.<id>.max-inflight",
        None,
        "Multi-tenant gateway (templated key): tenant <id>'s concurrent "
        "in-flight request cap, overriding gateway.max-inflight.",
    )
    GATEWAY_TENANT_BYTES_PER_SEC = ConfigOption.memory(
        "gateway.tenant.<id>.bytes-per-sec",
        "0 b",
        "Multi-tenant gateway (templated key): hard per-second byte cap for "
        "tenant <id>, applied on top of its weighted-fair share of the "
        "global gateway.bytes-per-sec budget. 0 = no per-tenant cap.",
    )
    GATEWAY_HEDGE_ENABLED = ConfigOption.bool_(
        "gateway.hedge.enabled",
        True,
        "Multi-tenant gateway: re-issue a point-get or scan fragment whose "
        "primary (owning worker) misses gateway.hedge.deadline-ms to a "
        "secondary live non-owner worker serving the same committed "
        "snapshot from the shared filesystem — first non-BUSY answer wins, "
        "the loser is cancelled and counted (gateway{hedges_cancelled}).",
    )
    GATEWAY_HEDGE_DEADLINE = ConfigOption.int_(
        "gateway.hedge.deadline-ms",
        50,
        "Multi-tenant gateway: milliseconds the primary worker gets before "
        "the gateway hedges the read to a secondary. Tail-latency armor — "
        "set near the healthy-path p99 so only stragglers pay the second "
        "RPC.",
    )
    GATEWAY_HEDGE_MAX_FRACTION = ConfigOption.float_(
        "gateway.hedge.max-fraction",
        0.25,
        "Multi-tenant gateway: upper bound on hedged requests as a fraction "
        "of all hedgeable requests — a cluster-wide brownout must not "
        "double every read. Beyond the bound the gateway waits out the "
        "primary instead of hedging.",
    )
    GATEWAY_SLO_DECAY_WINDOW = ConfigOption.duration(
        "gateway.slo.decay-window",
        "30 s",
        "Multi-tenant gateway: exponential-decay time constant of the SLO "
        "surface's latency histograms (gateway.slo() p50/p99 per tenant "
        "and request kind). Old samples fade with exp(-age/window) so the "
        "surface tracks current behavior, not the run's whole history.",
    )
    GATEWAY_RETRY_AFTER = ConfigOption.int_(
        "gateway.retry-after-ms",
        25,
        "Multi-tenant gateway: backoff hint stamped into inflight-cap sheds "
        "(byte-budget sheds compute their exact refill deadline instead).",
    )
    ORPHAN_CLEAN_OLDER_THAN = ConfigOption.duration(
        "orphan.clean.older-than",
        "1 d",
        "remove_orphan_files safety threshold: only files older than this "
        "are eligible for deletion (an in-flight commit's freshly written "
        "files must survive the sweep).",
    )
    COMMIT_CATALOG_LOCK = ConfigOption.bool_(
        "commit.catalog-lock.enabled",
        False,
        "Run snapshot commits under an external catalog lock (required on "
        "stores whose rename is not atomic; reference CatalogLock SPI).",
    )
    COMMIT_CATALOG_LOCK_TYPE = ConfigOption.string(
        "commit.catalog-lock.type",
        "file",
        "Catalog lock implementation: 'file' (lock object in the table dir; "
        "needs exclusive-create, i.e. conditional PUT on object stores) or "
        "'jdbc' (external lock database — the only safe choice on legacy "
        "object stores without conditional PUT).",
    )
    COMMIT_CATALOG_LOCK_JDBC_PATH = ConfigOption.string(
        "commit.catalog-lock.jdbc-path",
        None,
        "Database path for commit.catalog-lock.type=jdbc.",
    )
    COMMIT_CATALOG_LOCK_TIMEOUT = ConfigOption.float_(
        "commit.catalog-lock.acquire-timeout",
        60.0,
        "Seconds to wait for the catalog lock before the commit fails "
        "(reference catalog option lock-acquire-timeout).",
    )
    COMMIT_CATALOG_LOCK_STALE_TTL = ConfigOption.float_(
        "commit.catalog-lock.check-max-sleep",
        300.0,
        "Seconds after which a non-heartbeating lock holder is presumed "
        "crashed and its lock is swept (reference lock-check-max-sleep).",
    )
    PARALLEL_KEY_AXIS_ROWS = ConfigOption.int_(
        "parallel.key-axis.rows",
        4 * 1024 * 1024,
        "Row threshold above which one bucket's merge is range-partitioned "
        "over the mesh's key axis instead of running on a single device.",
    )
    CHANGELOG_NUM_RETAINED_MIN = ConfigOption.int_(
        "changelog.num-retained.min", None, "Min decoupled changelogs retained (enables the decoupled lifecycle)."
    )
    CHANGELOG_NUM_RETAINED_MAX = ConfigOption.int_(
        "changelog.num-retained.max", None, "Max decoupled changelogs retained."
    )
    CHANGELOG_TIME_RETAINED = ConfigOption.duration(
        "changelog.time-retained", None, "Decoupled changelog retention time (enables the decoupled lifecycle)."
    )
    CHANGELOG_PRODUCER_ROW_DEDUPLICATE = ConfigOption.bool_(
        "changelog-producer.row-deduplicate",
        True,
        "Drop -U/+U changelog pairs whose values did not change "
        "(full-compaction/lookup producers). Default true here: the diff is "
        "a vectorized compare, effectively free (reference defaults false "
        "because its row-by-row compare costs).",
    )
    DELETE_FORCE_PRODUCE_CHANGELOG = ConfigOption.bool_(
        "delete.force-produce-changelog",
        False,
        "DELETE/UPDATE commands produce input changelog even when "
        "changelog-producer=none.",
    )
    STREAMING_READ_OVERWRITE = ConfigOption.bool_(
        "streaming-read-overwrite",
        False,
        "Streaming reads also emit the new content of OVERWRITE snapshots.",
    )
    STREAMING_READ_MODE = ConfigOption.string(
        "streaming-read-mode", "file", "Streaming source: file (lake files). 'log' needs an external log system."
    )
    STREAM_SCAN_MODE = ConfigOption.string(
        "stream-scan-mode",
        "none",
        "none: normal changelog-aware follow-up; file-monitor: raw delta "
        "files of EVERY snapshot incl. compaction (compactor sources).",
    )
    CONTINUOUS_DISCOVERY_INTERVAL = ConfigOption.duration(
        "continuous.discovery-interval", "10 s", "Poll interval for discovering new snapshots in streaming reads."
    )
    CONSUMER_IGNORE_PROGRESS = ConfigOption.bool_(
        "consumer.ignore-progress", False, "Start from the startup mode, ignoring saved consumer progress."
    )
    SUBSCRIPTION_QUEUE_DEPTH = ConfigOption.int_(
        "subscription.queue-depth",
        16,
        "CDC subscription service: max decoded changelog batches buffered "
        "per subscriber. A queue full past subscription.shed-timeout sheds "
        "that subscriber (typed BUSY) — it never stalls the tailer.",
    )
    SUBSCRIPTION_POLL_BACKOFF = ConfigOption.duration(
        "subscription.poll-backoff",
        "20 ms",
        "CDC subscription service: initial tailer backoff when no new "
        "snapshot is available, doubling up to "
        "continuous.discovery-interval (blocking poll, no busy loop).",
    )
    SUBSCRIPTION_SHED_TIMEOUT = ConfigOption.duration(
        "subscription.shed-timeout",
        "2 s",
        "CDC subscription service: how long the tailer waits on one "
        "subscriber's full queue (or the shared buffer budget) before "
        "shedding that subscriber with a typed SubscriberShedError carrying "
        "its durable restart offset.",
    )
    SUBSCRIPTION_HEARTBEAT_INTERVAL = ConfigOption.duration(
        "subscription.heartbeat-interval",
        "5 s",
        "CDC subscription service: cadence of durable consumer-position "
        "re-records. Each record refreshes the consumer file's mtime, so "
        "consumer.expiration-time only collects readers that stopped "
        "heartbeating.",
    )
    SUBSCRIPTION_MAX_SUBSCRIBERS = ConfigOption.int_(
        "subscription.max-subscribers",
        1024,
        "CDC subscription service: subscriber cap per table hub; subscribe() "
        "past it answers a typed BUSY immediately.",
    )
    SUBSCRIPTION_REPLAY_CACHE_MAX_MEMORY = ConfigOption.memory(
        "subscription.replay-cache.max-memory",
        "32 mb",
        "CDC subscription service: byte budget for the hub's replay cache of "
        "decoded ChangelogBatches (LRU by snapshot). The data-file cache "
        "already makes PAGE decode once-per-process; this extends decode-once "
        "to the merged batch, so catch-up replay and shed-resume reuse the "
        "tailer's decode+merge instead of re-merging per subscriber. "
        "0 b = off.",
    )
    SUBSCRIPTION_BUFFER_MAX_MEMORY = ConfigOption.memory(
        "subscription.buffer.max-memory",
        "64 mb",
        "CDC subscription service: shared byte budget for queued decoded "
        "batches across ALL subscribers of a table (the PR 8 "
        "WriteBufferController riding the fan-out path). 0 b = unbounded.",
    )
    CONSUMER_MODE = ConfigOption.string(
        "consumer.mode",
        "exactly-once",
        "exactly-once: progress advances on checkpoint ack; at-least-once: on every plan.",
    )
    BRANCH = ConfigOption.string("branch", "main", "Branch this table view reads and writes.")
    CHANGELOG_PRODUCER = ConfigOption.enum(
        "changelog-producer", ChangelogProducer, ChangelogProducer.NONE, "How changelog files are produced."
    )
    SCAN_MODE = ConfigOption.enum(
        "scan.mode", StartupMode, StartupMode.DEFAULT, "Startup mode for scans.", fallback=("log.scan",)
    )
    SCAN_SNAPSHOT_ID = ConfigOption.int_("scan.snapshot-id", None, "Snapshot id for time travel.")
    SCAN_TIMESTAMP_MILLIS = ConfigOption.int_(
        "scan.timestamp-millis", None, "Timestamp for time travel.", fallback=("log.scan.timestamp-millis",)
    )
    SCAN_TIMESTAMP = ConfigOption.string(
        "scan.timestamp", None, "Timestamp for time travel as 'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' (local time)."
    )
    SCAN_TAG_NAME = ConfigOption.string("scan.tag-name", None, "Tag name for time travel.")
    SCAN_VERSION = ConfigOption.string(
        "scan.version", None, "Unified time travel: a tag name, or a snapshot id (tag wins on ambiguity)."
    )
    SCAN_WATERMARK = ConfigOption.int_(
        "scan.watermark", None, "Travel to the earliest snapshot whose watermark is >= this value."
    )
    SCAN_FILE_CREATION_TIME_MILLIS = ConfigOption.int_(
        "scan.file-creation-time-millis", None, "Only read data files created after this epoch-millis."
    )
    SCAN_PLAN_SORT_PARTITION = ConfigOption.bool_(
        "scan.plan-sort-partition",
        False,
        "true: splits strictly partition-major (sorted sequential consumption); "
        "false: round-robin across partitions (spreads parallel readers).",
    )
    SCAN_MAX_SPLITS_PER_TASK = ConfigOption.int_(
        "scan.max-splits-per-task", 10, "Split-assignment batch cap per reader task in the enumerator."
    )
    SCAN_MANIFEST_PARALLELISM = ConfigOption.int_(
        "scan.manifest.parallelism", None, "Threads for reading manifests during scan planning (default: scan.parallelism)."
    )
    SCAN_PREFETCH_SPLITS = ConfigOption.int_(
        "scan.prefetch-splits",
        2,
        "Readahead depth of the pipelined split scheduler: how many splits/"
        "compaction sections/flush encodes may run ahead of the consumer. "
        "0 disables pipelining everywhere (strictly sequential execution; "
        "output is bit-identical either way).",
    )
    SCAN_PARALLELISM = ConfigOption.int_(
        "scan.parallelism",
        None,
        "Worker threads per pipeline stage, and the in-flight bound of the "
        "per-file/manifest decode fan-out (default: min(prefetch+1, 4) for "
        "stages, shared-pool width for decode fan-out).",
    )
    INCREMENTAL_BETWEEN_TIMESTAMP = ConfigOption.string(
        "incremental-between-timestamp",
        None,
        "Incremental read between two epoch-millis timestamps 't1,t2' (resolved to snapshots).",
    )
    INCREMENTAL_BETWEEN = ConfigOption.string(
        "incremental-between",
        None,
        "Read incremental changes between two snapshots or tags "
        "('3,7' or 'tagA,tagB'): start exclusive, end inclusive.",
    )
    INCREMENTAL_BETWEEN_SCAN_MODE = ConfigOption.string(
        "incremental-between-scan-mode",
        "delta",
        "Incremental read source: delta (APPEND snapshot deltas) or "
        "changelog (changelog files of the range).",
    )
    SCAN_BOUNDED_WATERMARK = ConfigOption.int_(
        "scan.bounded.watermark",
        None,
        "Streaming reads end once a snapshot's watermark passes this bound.",
    )
    SNAPSHOT_EXPIRE_LIMIT = ConfigOption.int_(
        "snapshot.expire.limit", 50, "Max snapshots processed per expire run."
    )
    SNAPSHOT_EXPIRE_CLEAN_EMPTY_DIRS = ConfigOption.bool_(
        "snapshot.expire.clean-empty-directories",
        False,
        "Also remove bucket/partition directories left empty by expiry.",
    )
    SNAPSHOT_NUM_RETAINED_MIN = ConfigOption.int_("snapshot.num-retained.min", 10, "Min snapshots retained.")
    SNAPSHOT_NUM_RETAINED_MAX = ConfigOption.int_("snapshot.num-retained.max", 2147483647, "Max snapshots retained.")
    SNAPSHOT_TIME_RETAINED_MS = ConfigOption.duration(
        "snapshot.time-retained", "1 h", "Snapshot retention time.", fallback=("snapshot.time-retained.ms",)
    )
    NUM_SORTED_RUNS_COMPACTION_TRIGGER = ConfigOption.int_(
        "num-sorted-run.compaction-trigger", 5, "Sorted runs per bucket that trigger compaction."
    )
    NUM_SORTED_RUNS_STOP_TRIGGER = ConfigOption.int_(
        "num-sorted-run.stop-trigger", None, "Sorted runs that block writes (default trigger+3)."
    )
    NUM_LEVELS = ConfigOption.int_("num-levels", None, "LSM levels (default trigger+1).")
    COMPACTION_MAX_SIZE_AMP_PERCENT = ConfigOption.int_(
        "compaction.max-size-amplification-percent", 200, "Universal compaction size-amp trigger."
    )
    COMPACTION_SIZE_RATIO = ConfigOption.int_("compaction.size-ratio", 1, "Universal compaction size ratio percent.")
    COMPACTION_MIN_FILE_NUM = ConfigOption.int_("compaction.min.file-num", 5, "Min files for size-ratio pick.")
    COMPACTION_MAX_FILE_NUM = ConfigOption.int_(
        "compaction.max.file-num",
        50,
        "Cap on files merged by one size-ratio/file-num pick (bounds a "
        "single compaction's input; reference compaction.max.file-num).",
        fallback=("compaction.early-max.file-num",),
    )
    COMPACTION_OPTIMIZATION_INTERVAL = ConfigOption.int_(
        "compaction.optimization-interval", None, "Force full compaction every N millis."
    )
    FULL_COMPACTION_DELTA_COMMITS = ConfigOption.int_(
        "full-compaction.delta-commits", None, "Full compaction every N commits."
    )
    COMPACTION_ADAPTIVE_ENABLED = ConfigOption.bool_(
        "compaction.adaptive.enabled",
        False,
        "Drain compaction debt through the LUDA-style adaptive background "
        "scheduler (table.compactor.AdaptiveCompactorService) instead of "
        "inline with writers: hot buckets compact deeper and earlier, cold "
        "ones defer, and per-bucket read amplification stays under "
        "compaction.adaptive.read-amp-ceiling. Ingest writers typically run "
        "write-only alongside it.",
    )
    COMPACTION_ADAPTIVE_INTERVAL = ConfigOption.duration(
        "compaction.adaptive.interval",
        "200 ms",
        "Pause between adaptive-scheduler observation rounds (each round "
        "scans the latest snapshot's per-bucket LSM shape and compacts the "
        "buckets the policy picks).",
    )
    COMPACTION_ADAPTIVE_READ_AMP_CEILING = ConfigOption.int_(
        "compaction.adaptive.read-amp-ceiling",
        12,
        "Per-bucket sorted-run ceiling: a bucket at or above it is compacted "
        "with mandatory priority regardless of heat, bounding merge-read "
        "amplification under sustained ingest.",
    )
    COMPACTION_ADAPTIVE_TRIGGER = ConfigOption.int_(
        "compaction.adaptive.trigger",
        3,
        "Sorted runs before a bucket becomes eligible for proactive adaptive "
        "compaction; below it the bucket is deferred (counted in "
        "compaction{deferred_buckets}).",
    )
    COMPACTION_ADAPTIVE_MAX_BUCKETS = ConfigOption.int_(
        "compaction.adaptive.max-buckets-per-round",
        2,
        "Proactive buckets compacted per scheduler round — bounds the "
        "background work one round can steal from ingest (ceiling breaches "
        "are exempt: the read-amp bound always wins).",
    )
    COMPACTION_ADAPTIVE_DEEP_RUNS = ConfigOption.int_(
        "compaction.adaptive.deep-runs",
        8,
        "Sorted runs at or above which an adaptive compaction goes deep "
        "(full rewrite to the top level) instead of a shallow universal "
        "pick — LUDA's compact-hotter-buckets-deeper rule.",
    )
    COMPACTION_ADAPTIVE_PARALLELISM = ConfigOption.int_(
        "compaction.adaptive.parallelism",
        2,
        "Worker threads executing the adaptive scheduler's per-bucket "
        "compactions concurrently (distinct buckets commit independently "
        "through the snapshot CAS; LUDA's premise is that compaction is "
        "cheap enough to run ahead of demand — parallel workers are how "
        "the drain rate scales past one bucket at a time).",
    )
    COMPACTION_ADAPTIVE_INGEST_GATE = ConfigOption.bool_(
        "compaction.adaptive.ingest-gate",
        True,
        "Bound write-only ingest by the adaptive scheduler's debt-admission "
        "gate: when an AdaptiveCompactorService is running for the table, "
        "every MergeTreeWriter flush first admits against the read-amp "
        "ceiling (blocking while the target bucket's projected sorted-run "
        "count sits at/over it, up to "
        "compaction.adaptive.ingest-gate-timeout) and settles its one-run "
        "charge when the flush lands — so ANY write-only writer is "
        "read-amp-bounded, not just harnesses that call admit() by hand.",
    )
    COMPACTION_ADAPTIVE_INGEST_GATE_TIMEOUT = ConfigOption.duration(
        "compaction.adaptive.ingest-gate-timeout",
        "30 s",
        "Longest a gated write-only flush blocks waiting for compaction "
        "headroom; on timeout the flush proceeds (the breach is the "
        "scheduler's to drain) — the gate bounds read amplification, it "
        "must never wedge ingest on a stalled compactor.",
    )
    COMPACTION_ADAPTIVE_STARVATION_TIMEOUT = ConfigOption.duration(
        "compaction.adaptive.starvation-timeout",
        "10 s",
        "A bucket whose compaction debt has been deferred longer than this "
        "is promoted to mandatory priority — cold buckets cannot starve "
        "under sustained skewed writes.",
    )
    DYNAMIC_BUCKET_TARGET_ROW_NUM = ConfigOption.int_(
        "dynamic-bucket.target-row-num", 2_000_000, "Rows per dynamic bucket."
    )
    DELETION_VECTORS_ENABLED = ConfigOption.bool_("deletion-vectors.enabled", False, "Deletion-vector mode.")
    SEQUENCE_FIELD = ConfigOption.string("sequence.field", None, "User-defined sequence column(s).")
    PARTIAL_UPDATE_REMOVE_RECORD_ON_DELETE = ConfigOption.bool_(
        "partial-update.remove-record-on-delete", False, "-D removes whole row under partial-update."
    )
    AGGREGATE_DEFAULT_FUNC = ConfigOption.string(
        "fields.default-aggregate-function", None, "Default aggregate for unconfigured fields."
    )
    WRITE_MAX_WRITERS_TO_SPILL = ConfigOption.int_("write-max-writers-to-spill", 5, "Writers before spill.")
    SORT_SPILL_THRESHOLD = ConfigOption.int_("sort-spill-threshold", None, "Merge fan-in before spill.")
    # tiles keep one merge step within device memory; per-dispatch latency
    # makes small tiles counterproductive, so the default only kicks in for
    # genuinely large sections
    MERGE_READ_BATCH_ROWS = ConfigOption.int_(
        "merge.read-batch-rows", 8 << 20, "Row tile per device merge step (key-range tiling)."
    )
    CONSUMER_ID = ConfigOption.string("consumer-id", None, "Consumer id protecting read progress.")
    CONSUMER_EXPIRATION_TIME_MS = ConfigOption.duration(
        "consumer.expiration-time", None, "Consumer expiry.", fallback=("consumer.expiration-time.ms",)
    )
    TAG_AUTOMATIC_CREATION = ConfigOption.string("tag.automatic-creation", "none", "none|process-time|watermark.")
    TAG_CREATION_DELAY = ConfigOption.duration(
        "tag.creation-delay", "0 ms", "Extra wait after a period closes before its tag is created."
    )
    TAG_PERIOD_FORMATTER = ConfigOption.string(
        "tag.period-formatter", "with_dashes", "Tag name style: with_dashes (2024-01-02[ 03]) | without_dashes (20240102[03])."
    )
    TAG_NUM_RETAINED_MAX = ConfigOption.int_(
        "tag.num-retained-max", None, "Max auto-created tags kept (oldest pruned first)."
    )
    TAG_DEFAULT_TIME_RETAINED = ConfigOption.duration(
        "tag.default-time-retained", None, "Auto tags older than this (by tagged snapshot time) are removed."
    )
    TAG_CALLBACKS = ConfigOption.string(
        "tag.callbacks", None, "Comma list of 'module:function' callables invoked as fn(table, tag_name, snapshot)."
    )
    COMMIT_CALLBACKS = ConfigOption.string(
        "commit.callbacks", None, "Comma list of 'module:function' callables invoked as fn(table, snapshot) after commit."
    )
    COMMIT_USER_PREFIX = ConfigOption.string(
        "commit.user-prefix", None, "Generated commit users become '<prefix>-<uuid>' (job attribution)."
    )
    COMMIT_FORCE_COMPACT = ConfigOption.bool_(
        "commit.force-compact", False, "Run a full compaction as part of every batch prepare_commit."
    )
    COMMIT_FORCE_CREATE_SNAPSHOT = ConfigOption.bool_(
        "commit.force-create-snapshot", False, "Create a snapshot even for an empty commit."
    )
    DYNAMIC_PARTITION_OVERWRITE = ConfigOption.bool_(
        "dynamic-partition-overwrite",
        True,
        "INSERT OVERWRITE without a partition filter clears only the "
        "partitions present in the new data (false: whole table).",
    )
    ROWKIND_FIELD = ConfigOption.string(
        "rowkind.field", None, "Column holding the row kind ('+I'/'-U'/'+U'/'-D') extracted on write."
    )
    PARTITION_DEFAULT_NAME = ConfigOption.string(
        "partition.default-name", "__DEFAULT_PARTITION__", "Path name used for null/empty partition values."
    )
    TAG_CREATION_PERIOD = ConfigOption.string("tag.creation-period", "daily", "daily|hourly.")
    METADATA_STATS_MODE = ConfigOption.string("metadata.stats-mode", "truncate(16)", "Stats collection mode.")
    MANIFEST_TARGET_SIZE = ConfigOption.memory("manifest.target-file-size", "8 mb", "Manifest merge target size.")
    MANIFEST_MERGE_MIN_COUNT = ConfigOption.int_("manifest.merge-min-count", 30, "Small manifests before merge.")
    PARTITION_EXPIRATION_TIME_MS = ConfigOption.duration(
        "partition.expiration-time", None, "Partition TTL.", fallback=("partition.expiration-time.ms",)
    )
    PARTITION_EXPIRATION_CHECK_INTERVAL = ConfigOption.duration(
        "partition.expiration-check-interval", "1 h",
        "Min interval between partition-expiry sweeps piggybacked on commits.",
    )
    PARTITION_TIMESTAMP_FORMATTER = ConfigOption.string("partition.timestamp-formatter", None)
    PARTITION_TIMESTAMP_PATTERN = ConfigOption.string("partition.timestamp-pattern", None)
    RECORD_LEVEL_EXPIRE_TIME_MS = ConfigOption.duration(
        "record-level.expire-time", None, "Row TTL on read/compact.", fallback=("record-level.expire-time.ms",)
    )
    RECORD_LEVEL_TIME_FIELD = ConfigOption.string("record-level.time-field", None, "Row TTL time column.")
    RECORD_LEVEL_TIME_FIELD_TYPE = ConfigOption.string(
        "record-level.time-field-type", "seconds", "Row TTL column unit: seconds|millis|micros."
    )
    FILE_INDEX_BLOOM_COLUMNS = ConfigOption.string(
        "file-index.bloom-filter.columns", None, "Columns with bloom file index."
    )
    FILE_INDEX_BLOOM_FPP = ConfigOption.float_("file-index.bloom-filter.fpp", 0.05, "Bloom false-positive rate.")
    FILE_INDEX_READ_ENABLED = ConfigOption.bool_(
        "file-index.read.enabled", True, "Evaluate file index (bloom sidecars / embedded) during planning."
    )
    FILE_INDEX_BLOOM_KEY_ENABLED = ConfigOption.bool_(
        "file-index.bloom-filter.primary-key.enabled", False,
        "Primary-key tables: write a composite key bloom (one __KEY__ entry "
        "over the combined key-column hash) into every data file's PTIX "
        "index at flush/compaction time, so batched point-get planning can "
        "prune files with zero data IO. PAIMON_TPU_KEY_BLOOM=1/0 overrides.",
    )
    FILE_INDEX_BLOOM_KEY_FPP = ConfigOption.float_(
        "file-index.bloom-filter.primary-key.fpp", 0.001,
        "Key bloom false-positive rate. Tighter than the per-column default "
        "because a batched get probes MANY keys per file: the per-file "
        "false-positive budget must survive the union over the batch.",
    )
    FILE_INDEX_IN_MANIFEST_THRESHOLD = ConfigOption.memory(
        "file-index.in-manifest-threshold",
        "500 b",
        "Index payloads smaller than this embed in the manifest entry "
        "instead of a sidecar file (saves one open per file per scan).",
    )
    AUTO_CREATE = ConfigOption.bool_(
        "auto-create", False, "Create the underlying table storage on first load when a schema is supplied."
    )
    PRIMARY_KEY = ConfigOption.string(
        "primary-key", None,
        "Define the primary key via options (comma-separated) when the "
        "creating surface cannot express constraints (reference: cannot be "
        "combined with an explicit primary key).",
    )
    PARTITION = ConfigOption.string(
        "partition", None, "Define partition keys via options (comma-separated); same contract as primary-key."
    )
    CHANGELOG_PRODUCER_LOOKUP_WAIT = ConfigOption.bool_(
        "changelog-producer.lookup-wait",
        True,
        "changelog-producer=lookup: commit waits for the lookup compaction "
        "(false: defer changelog production to a later compaction).",
    )
    SNAPSHOT_EXPIRE_EXECUTION_MODE = ConfigOption.string(
        "snapshot.expire.execution-mode", "sync", "sync | async (expire runs on a background thread)."
    )
    SNAPSHOT_WATERMARK_IDLE_TIMEOUT = ConfigOption.duration(
        "snapshot.watermark-idle-timeout",
        None,
        "Streaming reads: advance the watermark to the snapshot commit time "
        "when no new snapshot arrived for this long.",
    )
    DYNAMIC_BUCKET_INITIAL_BUCKETS = ConfigOption.int_(
        "dynamic-bucket.initial-buckets", None, "Dynamic bucket mode: buckets pre-created per assigner."
    )
    DYNAMIC_BUCKET_ASSIGNER_PARALLELISM = ConfigOption.int_(
        "dynamic-bucket.assigner-parallelism", None,
        "Dynamic bucket mode: assigner operators; new buckets are striped "
        "bucket %% parallelism == assigner_id (default: writer parallelism).",
    )
    CROSS_PARTITION_UPSERT_BOOTSTRAP_PARALLELISM = ConfigOption.int_(
        "cross-partition-upsert.bootstrap-parallelism", 10,
        "Threads reading existing keys when bootstrapping the cross-partition index.",
    )
    CROSS_PARTITION_UPSERT_INDEX_TTL = ConfigOption.duration(
        "cross-partition-upsert.index-ttl", None,
        "TTL for rows in the cross-partition key->(partition,bucket) index "
        "(0/None = keep forever; shorter = less memory, risk of stale rows).",
    )
    DELETION_VECTOR_INDEX_FILE_TARGET_SIZE = ConfigOption.memory(
        "deletion-vector.index-file.target-size", "2 mb",
        "Roll the packed deletion-vector container at this size.",
    )
    CACHE_MANIFEST_MAX_MEMORY = ConfigOption.memory(
        "cache.manifest.max-memory-size",
        "256 mb",
        "Byte budget of the process-wide decoded manifest/metadata object "
        "cache (manifest entry lists, manifest-list metas, snapshots, the "
        "latest-snapshot pointer). '0 b' opts this table out.",
    )
    CACHE_DATA_FILE_MAX_MEMORY = ConfigOption.memory(
        "cache.data-file.max-memory-size",
        "128 mb",
        "Byte budget of the process-wide decoded data-file (KVBatch) cache "
        "over predicate-free reader_factory reads. '0 b' opts this table out.",
    )
    LOOKUP_CACHE_MAX_MEMORY_SIZE = ConfigOption.memory(
        "lookup.cache-max-memory-size", "256 mb", "Lookup in-memory cache byte budget."
    )
    LOOKUP_CACHE_MAX_DISK_SIZE = ConfigOption.memory(
        "lookup.cache-max-disk-size", f"{1 << 50} b",
        "Lookup on-disk cache byte budget (oldest persisted lookup files evicted first).",
    )
    LOOKUP_CACHE_FILE_RETENTION = ConfigOption.duration(
        "lookup.cache-file-retention", "1 h", "Persisted lookup files older than this are re-buildable garbage."
    )
    LOOKUP_CACHE_BLOOM_FILTER_ENABLED = ConfigOption.bool_(
        "lookup.cache.bloom.filter.enabled", True, "Guard lookup files with a bloom filter of their keys."
    )
    LOOKUP_CACHE_BLOOM_FILTER_FPP = ConfigOption.float_(
        "lookup.cache.bloom.filter.fpp", 0.05, "Lookup bloom filter false-positive rate."
    )
    LOOKUP_HASH_LOAD_FACTOR = ConfigOption.float_(
        "lookup.hash-load-factor", 0.75, "Fill ratio of the sorted-hash lookup sidecar's slot table."
    )
    LOOKUP_GET_BLOOM_PRUNE = ConfigOption.bool_(
        "lookup.get.bloom-prune.enabled", True,
        "Batched gets consult per-file key blooms (and key ranges) to prune "
        "files before any data IO. Off = every candidate file is probed.",
    )
    LOOKUP_GET_MAX_INFLIGHT = ConfigOption.int_(
        "lookup.get.max-inflight", 64,
        "Concurrent get_batch requests a serving endpoint (KV server / "
        "Flight do_action) admits before answering a typed BUSY instead of "
        "queueing into a timeout.",
    )
    MANIFEST_FULL_COMPACTION_THRESHOLD_SIZE = ConfigOption.memory(
        "manifest.full-compaction-threshold-size", "16 mb",
        "Rewrite ALL manifests into compacted base manifests once the "
        "unmerged (delta) manifests exceed this total size.",
    )
    SORT_COMPACTION_RANGE_STRATEGY = ConfigOption.string(
        "sort-compaction.range-strategy", "quantity",
        "quantity: range-split sort compaction by row count; size: by bytes "
        "(skewed row widths pack ranges evenly).",
    )
    SORT_COMPACTION_SAMPLE_MAGNIFICATION = ConfigOption.int_(
        "sort-compaction.local-sample.magnification", 1000,
        "Local sample size = magnification x parallelism when choosing range boundaries.",
    )
    WRITE_BUFFER_FOR_APPEND = ConfigOption.bool_(
        "write-buffer-for-append", False,
        "Append tables: buffer rows (with spill) instead of flushing a file per write call.",
    )
    WRITE_BUFFER_SPILL_MAX_DISK_SIZE = ConfigOption.memory(
        "write-buffer-spill.max-disk-size", f"{1 << 50} b",
        "Cap on bytes of spill segments on local disk; past it the buffer flushes instead of spilling.",
    )
    ZORDER_VAR_LENGTH_CONTRIBUTION = ConfigOption.int_(
        "zorder.var-length-contribution", 8,
        "Bytes a var-length column (string/bytes) contributes to the z-order interleave.",
    )
    FIELDS_PREFIX = "fields."  # fields.<name>.aggregate-function / .sequence-group / .ignore-retract

    def __init__(self, options: Options | Mapping[str, Any] | None = None):
        self.options = options if isinstance(options, Options) else Options(options)

    # typed views ---------------------------------------------------------
    @property
    def bucket(self) -> int:
        return self.options.get(CoreOptions.BUCKET)

    @property
    def bucket_mode_hint(self) -> BucketMode:
        return BucketMode.FIXED if self.bucket > 0 else BucketMode.DYNAMIC

    @property
    def file_format(self) -> str:
        return self.options.get(CoreOptions.FILE_FORMAT)

    @property
    def file_compression(self) -> str:
        return self.options.get(CoreOptions.FILE_COMPRESSION)

    @property
    def merge_engine(self) -> MergeEngine:
        return self.options.get(CoreOptions.MERGE_ENGINE)

    @property
    def sort_engine(self) -> SortEngine:
        return self.options.get(CoreOptions.SORT_ENGINE)

    @property
    def lane_compression(self) -> bool:
        return self.options.get(CoreOptions.MERGE_LANE_COMPRESSION)

    @property
    def dict_domain(self) -> bool:
        return self.options.get(CoreOptions.MERGE_DICT_DOMAIN)

    @property
    def dict_domain_pool_limit(self) -> int:
        return self.options.get(CoreOptions.MERGE_DICT_DOMAIN_POOL_LIMIT)

    @property
    def changelog_producer(self) -> ChangelogProducer:
        return self.options.get(CoreOptions.CHANGELOG_PRODUCER)

    @property
    def target_file_size(self) -> int:
        return int(self.options.get(CoreOptions.TARGET_FILE_SIZE))

    @property
    def write_buffer_rows(self) -> int:
        return self.options.get(CoreOptions.WRITE_BUFFER_ROWS)

    @property
    def write_buffer_size(self) -> int:
        return int(self.options.get(CoreOptions.WRITE_BUFFER_SIZE))

    @property
    def write_buffer_max_memory(self) -> int:
        return int(self.options.get(CoreOptions.WRITE_BUFFER_MAX_MEMORY))

    @property
    def write_buffer_block_timeout_ms(self) -> int:
        return self.options.get(CoreOptions.WRITE_BUFFER_BLOCK_TIMEOUT)

    @property
    def write_only(self) -> bool:
        return self.options.get(CoreOptions.WRITE_ONLY)

    @property
    def num_sorted_runs_compaction_trigger(self) -> int:
        return self.options.get(CoreOptions.NUM_SORTED_RUNS_COMPACTION_TRIGGER)

    @property
    def num_sorted_runs_stop_trigger(self) -> int:
        v = self.options.get(CoreOptions.NUM_SORTED_RUNS_STOP_TRIGGER)
        return v if v is not None else self.num_sorted_runs_compaction_trigger + 3

    @property
    def num_levels(self) -> int:
        v = self.options.get(CoreOptions.NUM_LEVELS)
        return v if v is not None else self.num_sorted_runs_compaction_trigger + 1

    @property
    def max_size_amplification_percent(self) -> int:
        return self.options.get(CoreOptions.COMPACTION_MAX_SIZE_AMP_PERCENT)

    @property
    def size_ratio(self) -> int:
        return self.options.get(CoreOptions.COMPACTION_SIZE_RATIO)

    @property
    def compaction_min_file_num(self) -> int:
        return self.options.get(CoreOptions.COMPACTION_MIN_FILE_NUM)

    @property
    def snapshot_num_retained_min(self) -> int:
        return self.options.get(CoreOptions.SNAPSHOT_NUM_RETAINED_MIN)

    @property
    def snapshot_num_retained_max(self) -> int:
        return self.options.get(CoreOptions.SNAPSHOT_NUM_RETAINED_MAX)

    @property
    def snapshot_time_retained_ms(self) -> int:
        return self.options.get(CoreOptions.SNAPSHOT_TIME_RETAINED_MS)

    @property
    def sequence_field(self) -> list[str]:
        v = self.options.get(CoreOptions.SEQUENCE_FIELD)
        return [s.strip() for s in v.split(",")] if v else []

    @property
    def ignore_delete(self) -> bool:
        return self.options.get(CoreOptions.IGNORE_DELETE)

    def field_option(self, field_name: str, suffix: str) -> str | None:
        key = f"fields.{field_name}.{suffix}"
        return self.options._data.get(key)

    def to_map(self) -> dict[str, str]:
        return self.options.to_map()
