"""Vectorized Parquet page-encode kernels — the write-side duals of
decode/kernels.py.

Every encoder is array-at-a-time: run boundaries are found with one
np.diff/flatnonzero pass and the values of every run/miniblock/page pack
through one numpy expression — no per-value Python. The numpy forms are the
default engine (tier-1 runs under JAX_PLATFORMS=cpu where per-page jit
dispatch would dominate); the jittable JAX twin (`pack_bits_jax`) expresses
the same math as XLA ops so the packing can run device-side, and the parity
tests pin it to the numpy oracle.

Kernel inventory (dual to the decode set):
  * pack_bits              — LSB-first bit-packing, the primitive under both
                             RLE/bit-packed hybrid and DELTA miniblocks
  * encode_rle_hybrid      — parquet's <bit-packed|RLE> hybrid runs
                             (definition levels + dictionary indices):
                             runs >= 8 become RLE, everything between packs
                             as multiple-of-8 bit-packed spans
  * encode_plain / encode_plain_boolean / encode_plain_byte_array
                             — PLAIN for all six physical types; the
                             byte-array stream builds with a vectorized
                             scatter (no per-value loop)
  * encode_delta_binary_packed — DELTA_BINARY_PACKED int32/int64
  * validity_to_def_levels — bool mask → levels (max_def = 1 flat schemas)
  * byte_array_parts       — object str/bytes vector → (lengths, payload)
                             via np.char vectorized utf-8 encode
"""

from __future__ import annotations

import os
import struct

import numpy as np

from ..decode.container import (
    T_BOOLEAN,
    T_BYTE_ARRAY,
    T_DOUBLE,
    T_FLOAT,
    T_INT32,
    T_INT64,
    UnsupportedParquetFeature,
)
from ..decode.thrift import append_uvarint, zigzag_encode

__all__ = [
    "encode_engine",
    "set_encode_engine",
    "pack_bits",
    "pack_bits_jax",
    "encode_rle_hybrid",
    "encode_plain",
    "encode_plain_boolean",
    "encode_plain_byte_array",
    "encode_delta_binary_packed",
    "validity_to_def_levels",
    "byte_array_parts",
    "bit_width_for",
]

# "numpy" (default) or "jax": which engine packs fixed-width bit streams.
# numpy stays the tier-1 default — correctness is identical (tests pin it)
# and per-page dispatch overhead favors the host for small pages.
_ENGINE = os.environ.get("PAIMON_TPU_ENCODE_ENGINE", "numpy")


def encode_engine() -> str:
    return _ENGINE


def set_encode_engine(name: str) -> None:
    global _ENGINE
    if name not in ("numpy", "jax"):
        raise ValueError(f"encode engine must be 'numpy' or 'jax', got {name!r}")
    _ENGINE = name


def bit_width_for(max_value: int) -> int:
    """Bits needed for unsigned values up to max_value (0 for a single-entry
    domain, matching the dictionary-index convention)."""
    return int(max_value).bit_length()


# ---- bit packing ---------------------------------------------------------


def pack_bits(values: np.ndarray, bit_width: int) -> bytes:
    """LSB-first pack of unsigned values into a byte stream (inverse of
    decode.kernels.unpack_bits). Pads the final byte with zero bits."""
    count = len(values)
    if count == 0 or bit_width == 0:
        return b""
    if bit_width > 64:
        raise UnsupportedParquetFeature(f"bit width {bit_width}")
    if bit_width % 8 == 0:
        # byte-aligned width: LSB-first bit layout == truncated little-endian
        # bytes — one cast + reshape instead of a bit-matrix expansion
        v = np.ascontiguousarray(values, dtype="<u8")
        return v.view(np.uint8).reshape(count, 8)[:, : bit_width >> 3].tobytes()
    if _ENGINE == "jax" and bit_width <= 32:
        return np.asarray(pack_bits_jax(values, bit_width)).tobytes()
    v = np.ascontiguousarray(values, dtype=np.uint64)
    bits = ((v[:, None] >> np.arange(bit_width, dtype=np.uint64)) & np.uint64(1)).astype(
        np.uint8
    )
    return np.packbits(bits.reshape(-1), bitorder="little").tobytes()


def pack_bits_jax(values, bit_width: int):
    """Jittable twin of `pack_bits` (bit_width is a trace constant). Width
    capped at 32 — dictionary indices and levels never exceed it. Returns a
    uint8 array of ceil(count*bit_width/8) bytes."""
    import jax.numpy as jnp

    if bit_width > 32:
        raise UnsupportedParquetFeature(f"jax pack width {bit_width}")
    v = jnp.asarray(values, dtype=jnp.uint32)
    bits = ((v[:, None] >> jnp.arange(bit_width, dtype=jnp.uint32)) & jnp.uint32(1)).astype(
        jnp.uint8
    )
    flat = bits.reshape(-1)
    pad = (-flat.shape[0]) % 8
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, dtype=jnp.uint8)])
    byte_bits = flat.reshape(-1, 8)
    weights = jnp.left_shift(jnp.uint8(1), jnp.arange(8, dtype=jnp.uint8))
    return (byte_bits * weights).sum(axis=1).astype(jnp.uint8)


# ---- RLE / bit-packed hybrid --------------------------------------------

_MIN_RLE_RUN = 8


def encode_rle_hybrid(values: np.ndarray, bit_width: int) -> bytes:
    """Non-negative int vector → parquet hybrid run stream (the inverse of
    decode.kernels.decode_rle_hybrid).

    Run boundaries come from one vectorized diff; the Python loop below
    iterates only over runs long enough to become RLE — random data (no long
    runs) packs as a single bit-packed span, constant data as a single RLE
    run. Mid-stream bit-packed spans are kept multiple-of-8 by borrowing the
    first values of the following RLE run, so the reader never misaligns."""
    n = len(values)
    out = bytearray()
    if n == 0:
        return b""
    v = np.ascontiguousarray(values, dtype=np.int64)
    byte_w = (bit_width + 7) >> 3
    if bit_width == 0:
        # single-entry domain: one RLE run, no value bytes
        append_uvarint(out, n << 1)
        return bytes(out)
    change = np.flatnonzero(v[1:] != v[:-1]) + 1
    starts = np.concatenate([np.zeros(1, dtype=np.int64), change])
    lengths = np.diff(np.append(starts, n))
    long_runs = np.flatnonzero(lengths >= _MIN_RLE_RUN)
    mask = (1 << (8 * byte_w)) - 1

    def flush_bitpack(lo: int, hi: int) -> None:
        if hi <= lo:
            return
        groups = (hi - lo + 7) >> 3
        append_uvarint(out, (groups << 1) | 1)
        vals = v[lo:hi]
        if len(vals) < groups * 8:  # a group always carries 8 values' bits
            vals = np.concatenate([vals, np.zeros(groups * 8 - len(vals), dtype=np.int64)])
        out.extend(pack_bits(vals, bit_width))

    pos = 0
    for ri in long_runs:
        rs, rl = int(starts[ri]), int(lengths[ri])
        pend = rs - pos
        borrow = (-pend) % 8  # align the pending span to whole groups
        if rl - borrow < _MIN_RLE_RUN:
            continue  # not worth RLE once aligned: absorb into pending
        flush_bitpack(pos, rs + borrow)
        append_uvarint(out, (rl - borrow) << 1)
        out += (int(v[rs]) & mask).to_bytes(byte_w, "little")
        pos = rs + rl
    flush_bitpack(pos, n)  # final span may pad its last group
    return bytes(out)


# ---- PLAIN ---------------------------------------------------------------

_PLAIN_DTYPES = {
    T_INT32: np.dtype("<i4"),
    T_INT64: np.dtype("<i8"),
    T_FLOAT: np.dtype("<f4"),
    T_DOUBLE: np.dtype("<f8"),
}


def encode_plain(values: np.ndarray, physical_type: int) -> bytes:
    """PLAIN for the fixed-width physical types: one contiguous cast +
    tobytes (a memcpy when the dtype already matches)."""
    if physical_type in _PLAIN_DTYPES:
        return np.ascontiguousarray(values, dtype=_PLAIN_DTYPES[physical_type]).tobytes()
    if physical_type == T_BOOLEAN:
        return encode_plain_boolean(values)
    raise UnsupportedParquetFeature(f"PLAIN encode physical type {physical_type}")


def encode_plain_boolean(values: np.ndarray) -> bytes:
    return np.packbits(np.ascontiguousarray(values, dtype=np.bool_), bitorder="little").tobytes()


def encode_plain_byte_array(lengths: np.ndarray, payload: bytes) -> bytes:
    """(lengths, concatenated payload) → PLAIN BYTE_ARRAY stream
    (u32-length-prefixed values), built with one vectorized scatter: every
    payload byte and every length byte computes its destination offset and
    lands in a single fancy-index assignment."""
    n = len(lengths)
    if n == 0:
        return b""
    lens = np.ascontiguousarray(lengths, dtype=np.int64)
    if len(payload) != int(lens.sum()):
        raise ValueError(f"payload is {len(payload)} bytes, lengths sum to {int(lens.sum())}")
    if n > 1 and int(lens.min()) == int(lens.max()):
        # uniform lengths (zero-padded key pools): one reshape, no scatter
        w = int(lens[0])
        out = np.empty((n, 4 + w), dtype=np.uint8)
        out[:, :4] = np.frombuffer(struct.pack("<I", w), dtype=np.uint8)
        if w:
            out[:, 4:] = np.frombuffer(payload, dtype=np.uint8).reshape(n, w)
        return out.tobytes()
    total = int(lens.sum()) + 4 * n
    out = np.zeros(total, dtype=np.uint8)
    src_starts = np.concatenate([np.zeros(1, dtype=np.int64), np.cumsum(lens)[:-1]])
    len_pos = src_starts + 4 * np.arange(n, dtype=np.int64)
    le = lens.astype("<u4").view(np.uint8).reshape(n, 4)
    out[(len_pos[:, None] + np.arange(4, dtype=np.int64)).reshape(-1)] = le.reshape(-1)
    src = np.frombuffer(payload, dtype=np.uint8)
    if len(src):
        value_id = np.repeat(np.arange(n, dtype=np.int64), lens)
        out[np.arange(len(src), dtype=np.int64) + 4 * (value_id + 1)] = src
    return out.tobytes()


_BIG_FIXED_WIDTH = 4096  # np.str_ blow-up guard: one huge value → loop path


def byte_array_parts(values: np.ndarray) -> tuple[np.ndarray, bytes]:
    """Object vector of str/bytes → (byte lengths, concatenated payload).

    Strings take the vectorized path: one np.asarray(.., np.str_) +
    np.char.encode pass (C loops, no Python-level per-value work). Values
    containing NUL (which the S dtype would silently trim) or pathologically
    wide rows fall back to the join loop. Bytes vectors use the C-speed
    b''.join. Callers only reach this for dictionary pools and the rare
    non-dictionary string chunk — dictionary indices never materialize
    strings at all."""
    n = len(values)
    if n == 0:
        return np.zeros(0, dtype=np.int64), b""
    first = values[0]
    if isinstance(first, str):
        try:
            u = np.asarray(values, dtype=np.str_)
            k = u.dtype.itemsize // 4
            if k == 0:  # every string empty
                return np.zeros(n, dtype=np.int64), b""
            if k <= _BIG_FIXED_WIDTH:
                # fixed-width U matrix of codepoints; per-row length = last
                # non-zero position (the U dtype pads with NULs). A string
                # with a TRAILING NUL char would lose it here — the total-
                # length check below catches that and falls to the loop.
                mat = np.ascontiguousarray(u).view(np.uint32).reshape(n, k)
                lens = (k - (mat[:, ::-1] != 0).argmax(axis=1)).astype(np.int64)
                lens[~(mat != 0).any(axis=1)] = 0
                if int(lens.sum()) == sum(map(len, values)):
                    if int(mat.max()) < 128:
                        # pure ASCII: utf-8 bytes == codepoints
                        payload = mat[np.arange(k) < lens[:, None]].astype(np.uint8).tobytes()
                        return lens, payload
                    enc = np.char.encode(u, "utf-8")
                    ek_ = enc.dtype.itemsize
                    blens = np.char.str_len(enc).astype(np.int64)
                    bmat = np.frombuffer(enc.tobytes(), dtype=np.uint8).reshape(n, ek_)
                    payload = bmat[np.arange(ek_) < blens[:, None]].tobytes()
                    return blens, payload
        except (TypeError, ValueError, UnicodeEncodeError):
            pass
    elif isinstance(first, (bytes, bytearray)):
        try:
            payload = b"".join(values)
            lens = np.fromiter((len(x) for x in values), dtype=np.int64, count=n)
            return lens, payload
        except TypeError:
            pass
    encoded = [
        x.encode("utf-8") if isinstance(x, str) else (b"" if x is None else bytes(x))
        for x in values
    ]
    lens = np.fromiter((len(p) for p in encoded), dtype=np.int64, count=n)
    return lens, b"".join(encoded)


# ---- DELTA_BINARY_PACKED -------------------------------------------------

_DELTA_BLOCK = 1024  # multiple of 128 per spec
_DELTA_MINI = 4  # miniblocks per block; 256 values each (multiple of 32)


def encode_delta_binary_packed(values: np.ndarray, physical_type: int) -> bytes:
    """DELTA_BINARY_PACKED int32/int64 (inverse of the decode kernel).
    Deltas compute in wrap-around uint64 space; per block one signed min
    subtracts out and each miniblock packs at its own bit width."""
    if physical_type not in (T_INT32, T_INT64):
        raise UnsupportedParquetFeature("DELTA_BINARY_PACKED on non-int column")
    v = np.ascontiguousarray(values, dtype=np.int64)
    n = len(v)
    out = bytearray()
    per = _DELTA_BLOCK // _DELTA_MINI
    append_uvarint(out, _DELTA_BLOCK)
    append_uvarint(out, _DELTA_MINI)
    append_uvarint(out, n)
    append_uvarint(out, zigzag_encode(int(v[0]) if n else 0))
    if n <= 1:
        return bytes(out)
    u = v.view(np.uint64)
    deltas = u[1:] - u[:-1]  # wrap-around uint64
    signed = deltas.view(np.int64)
    for bs in range(0, len(deltas), _DELTA_BLOCK):
        block = deltas[bs : bs + _DELTA_BLOCK]
        mind = int(signed[bs : bs + _DELTA_BLOCK].min())
        append_uvarint(out, zigzag_encode(mind))
        adj = block - np.uint64(mind & 0xFFFFFFFFFFFFFFFF)
        widths = bytearray(_DELTA_MINI)
        packs: list[bytes] = []
        for mi in range(_DELTA_MINI):
            mini = adj[mi * per : (mi + 1) * per]
            if len(mini) == 0:
                continue  # trailing miniblocks of the last block: width 0, no bytes
            w = bit_width_for(int(mini.max()))
            widths[mi] = w
            if w:
                if len(mini) < per:
                    mini = np.concatenate([mini, np.zeros(per - len(mini), dtype=np.uint64)])
                packs.append(pack_bits(mini, w))
        out += bytes(widths)
        for p in packs:
            out += p
    return bytes(out)


# ---- levels --------------------------------------------------------------


def validity_to_def_levels(validity: np.ndarray | None, n: int) -> np.ndarray:
    """Bool validity → def-level vector (max_def 1: flat OPTIONAL columns).
    None validity means every slot valid — one constant vector that the RLE
    encoder collapses to a single run."""
    if validity is None:
        return np.ones(n, dtype=np.int64)
    return validity.astype(np.int64)
