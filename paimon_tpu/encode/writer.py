"""Parquet container assembly: column chunks → row groups → footer bytes
(the write-side dual of decode/container.py).

Everything thrift-shaped goes through decode.thrift.build_struct; offsets
are tracked as pages append so ColumnMetaData carries exact
dictionary/data-page offsets, and the footer writes ColumnOrder
TYPE_DEFINED_ORDER for every leaf so readers (pyarrow included) trust the
min_value/max_value statistics for row-group pruning.

Envelope (mirrors the decoder's): flat schemas only, physical types
BOOLEAN/INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY, codecs the repo uses
(uncompressed/snappy/gzip/brotli/zstd/lz4). Anything else raises
UnsupportedParquetFeature before a single byte is written, so the caller
falls back to the arrow writer for that file only.
"""

from __future__ import annotations

import struct

from ..data.batch import ColumnBatch
from ..decode.container import (
    CODEC_NAMES,
    MAGIC,
    T_INT32,
    UnsupportedParquetFeature,
    expected_physical_type,
)
from ..decode.thrift import build_struct
from ..types import TypeRoot
from .pages import encode_chunk

__all__ = ["encode_parquet_bytes"]

# thrift compact type nibbles
_BOOL, _I32, _I64, _BINARY, _LIST, _STRUCT = 1, 5, 6, 8, 9, 12

# parquet.thrift ConvertedType values the arrow writer emits for this
# repo's logical types (everything else stays unannotated, matching
# ColumnBatch.to_arrow's physical-representation columns)
_CONVERTED_UTF8 = 0
_CONVERTED_INT8 = 15
_CONVERTED_INT16 = 16

_CODEC_IDS = {name: cid for cid, name in CODEC_NAMES.items() if name}
_CODEC_IDS.update({"lz4": 7, "uncompressed": 0, "none": 0})

_CREATED_BY = b"paimon_tpu version 1.0.0 (build native-encode)"

_DEFAULT_PAGE_SIZE = 1 << 20  # pyarrow's data_page_size default
_DEFAULT_ROW_GROUP_ROWS = 1 << 20  # pyarrow's row_group_size default


def _codec_for(compression: str | None) -> tuple[int, str | None]:
    if compression is None:
        return 0, None
    name = str(compression).lower()
    if name not in _CODEC_IDS:
        raise UnsupportedParquetFeature(f"compression codec {compression!r}")
    cid = _CODEC_IDS[name]
    return cid, CODEC_NAMES.get(cid)


def _converted_type(root: TypeRoot) -> int | None:
    if root in (TypeRoot.CHAR, TypeRoot.VARCHAR):
        return _CONVERTED_UTF8
    if root == TypeRoot.TINYINT:
        return _CONVERTED_INT8
    if root == TypeRoot.SMALLINT:
        return _CONVERTED_INT16
    return None


def _schema_elements(schema) -> list[bytes]:
    elems = [build_struct([(4, _BINARY, b"schema"), (5, _I32, len(schema.fields))])]
    for f in schema.fields:
        root = f.type.root
        if root in (TypeRoot.ARRAY, TypeRoot.MAP, TypeRoot.ROW):
            raise UnsupportedParquetFeature(f"nested column {f.name!r}")
        physical = expected_physical_type(f.type)
        elems.append(
            build_struct(
                [
                    (1, _I32, physical),
                    (3, _I32, 1),  # OPTIONAL, like every arrow-written leaf
                    (4, _BINARY, f.name),
                    (6, _I32, _converted_type(root)),
                ]
            )
        )
    return elems


def _row_group_rows(batch: ColumnBatch, format_options: dict) -> int:
    if "parquet.row-group.rows" in format_options:
        return max(1, int(format_options["parquet.row-group.rows"]))
    if "file.block-size" in format_options and batch.num_rows:
        per_row = max(1, batch.byte_size() // batch.num_rows)
        return max(1024, int(format_options["file.block-size"]) // per_row)
    return _DEFAULT_ROW_GROUP_ROWS


def encode_parquet_bytes(
    batch: ColumnBatch,
    compression: str | None = "zstd",
    format_options: dict | None = None,
    metrics=None,
) -> bytes:
    """One ColumnBatch → complete parquet file bytes, or raise
    UnsupportedParquetFeature (before any output) when the batch needs a
    feature outside the native envelope."""
    opts = format_options or {}
    codec_id, codec_name = _codec_for(compression)
    page_size = int(opts.get("parquet.page-size", _DEFAULT_PAGE_SIZE))
    page_v2 = str(opts.get("parquet.data-page-version", "1.0")).strip() in ("2.0", "2")
    enable_dict = str(opts.get("parquet.enable.dictionary", "true")).lower() != "false"
    zstd_level = (
        int(opts["file.compression.zstd-level"])
        if codec_name == "zstd" and "file.compression.zstd-level" in opts
        else None
    )

    schema_elems = _schema_elements(batch.schema)  # validates the envelope up front
    physicals = {f.name: expected_physical_type(f.type) for f in batch.schema.fields}

    body = bytearray(MAGIC)
    row_groups: list[bytes] = []
    n = batch.num_rows
    rg_rows = _row_group_rows(batch, opts)
    for rg_start in range(0, n, rg_rows):
        # whole-batch shortcut: Column.slice materializes lazy values, which
        # would defeat the dict-cache pool-reuse path for the (default)
        # single-row-group file
        rg = batch if rg_rows >= n else batch.slice(rg_start, min(rg_start + rg_rows, n))
        chunk_structs: list[bytes] = []
        rg_total_bytes = 0
        for f in rg.schema.fields:
            chunk = encode_chunk(
                rg.column(f.name),
                f.type,
                physicals[f.name],
                page_size=page_size,
                page_v2=page_v2,
                enable_dict=enable_dict,
                codec_id=codec_id,
                codec_name=codec_name,
                zstd_level=zstd_level,
                metrics=metrics,
            )
            chunk_start = len(body)
            for page in chunk.pages:
                body += page
            dict_off = chunk_start if chunk.dict_page_len else None
            data_off = chunk_start + chunk.dict_page_len
            meta = build_struct(
                [
                    (1, _I32, chunk.physical_type),
                    (2, _LIST, (_I32, list(chunk.encodings))),
                    (3, _LIST, (_BINARY, [f.name])),
                    (4, _I32, codec_id),
                    (5, _I64, chunk.num_values),
                    (6, _I64, chunk.total_uncompressed),
                    (7, _I64, chunk.total_compressed),
                    (9, _I64, data_off),
                    (11, _I64, dict_off),
                    (12, _STRUCT, chunk.stats),
                ]
            )
            chunk_structs.append(
                build_struct([(2, _I64, chunk_start), (3, _STRUCT, meta)])
            )
            rg_total_bytes += chunk.total_uncompressed
        row_groups.append(
            build_struct(
                [
                    (1, _LIST, (_STRUCT, chunk_structs)),
                    (2, _I64, rg_total_bytes),
                    (3, _I64, rg.num_rows),
                ]
            )
        )

    type_order = build_struct([(1, _STRUCT, build_struct([]))])
    footer = build_struct(
        [
            (1, _I32, 2 if page_v2 else 1),
            (2, _LIST, (_STRUCT, schema_elems)),
            (3, _I64, n),
            (4, _LIST, (_STRUCT, row_groups)),
            (6, _BINARY, _CREATED_BY),
            (7, _LIST, (_STRUCT, [type_order] * len(batch.schema.fields))),
        ]
    )
    body += footer
    body += struct.pack("<I", len(footer))
    body += MAGIC
    return bytes(body)
