"""Column → page assembly: one ColumnBatch column into a dictionary page +
data pages + chunk-level statistics (the write-side dual of decode/pages.py).

Encoding selection per chunk:
  * BYTE_ARRAY with a merge-path dict cache (data/keys.py attached the
    sorted string pool + rank vector while encoding key lanes) — dictionary
    page straight from the pool, RLE_DICTIONARY codes straight from the
    ranks: no string object is touched between the merge and the file bytes;
  * other BYTE_ARRAY — one arrow conversion (C, the same first step the
    arrow writer pays) yields the offsets/data buffers; dictionary-encode
    when the domain is small enough, PLAIN from the buffers otherwise —
    either way the page bytes build through the vectorized kernels;
  * INT32/INT64 — DELTA_BINARY_PACKED when the valid values are
    non-decreasing (merge output key columns are), PLAIN otherwise;
  * BOOLEAN / FLOAT / DOUBLE — PLAIN.

Definition levels always write (columns are OPTIONAL, matching the arrow
writer); an all-valid page collapses to a single RLE run. Chunk min/max
stats compute vectorized and feed both `_row_group_stats` (arrow read path)
and the decode subsystem's chunk-stats pushdown gate.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

import numpy as np

from ..data.batch import Column
from ..decode.container import (
    ENC_PLAIN,
    ENC_RLE,
    ENC_RLE_DICTIONARY,
    ENC_DELTA_BINARY_PACKED,
    PAGE_DATA,
    PAGE_DATA_V2,
    PAGE_DICTIONARY,
    T_BOOLEAN,
    T_BYTE_ARRAY,
    T_DOUBLE,
    T_FLOAT,
    T_INT32,
    T_INT64,
    UnsupportedParquetFeature,
)
from ..decode.thrift import build_struct
from ..types import DataType, TypeRoot
from . import kernels

__all__ = ["EncodedChunk", "encode_chunk"]

# thrift compact type nibbles used for header building
_I32, _I64, _BOOL, _STRUCT = 5, 6, 1, 12

# dictionary domains above this fraction of the valid rows fall back to
# PLAIN — the page would carry the whole domain anyway (unique PK strings
# with a merge pool are exempt: their codes are already free)
_DICT_RATIO_NUM, _DICT_RATIO_DEN = 2, 3

_STAT_PACK = {T_INT32: "<i", T_INT64: "<q", T_FLOAT: "<f", T_DOUBLE: "<d"}
# decode.container._STAT_TRUST_LEN: byte-array stats at or past this length
# are treated as possibly-truncated by readers — omit instead of writing
_STAT_MAX_LEN = 64


@dataclass
class EncodedChunk:
    """One column chunk, ready for file assembly."""

    pages: list[bytes] = field(default_factory=list)  # header+body, dict page first
    physical_type: int = 0
    encodings: tuple[int, ...] = ()
    num_values: int = 0  # incl. nulls
    total_uncompressed: int = 0
    total_compressed: int = 0
    dict_page_len: int = 0  # 0 = no dictionary page
    stats: bytes | None = None  # pre-built thrift Statistics struct
    num_pages: int = 0  # data pages (metrics)


def _is_utf8(dtype: DataType) -> bool:
    return dtype.root in (TypeRoot.CHAR, TypeRoot.VARCHAR)


def _compressor(codec_id: int, codec_name: str | None, zstd_level: int | None):
    if codec_id == 0:
        return lambda b: b
    import pyarrow as pa

    try:
        if codec_name == "zstd" and zstd_level is not None:
            codec = pa.Codec("zstd", compression_level=zstd_level)
        else:
            codec = pa.Codec(codec_name)
    except (ValueError, NotImplementedError) as e:
        raise UnsupportedParquetFeature(f"codec {codec_name}: {e}") from e
    return lambda b: codec.compress(b, asbytes=True)


def _stats_struct(min_raw: bytes | None, max_raw: bytes | None, null_count: int) -> bytes:
    return build_struct(
        [
            (3, _I64, null_count),
            (5, 8, max_raw),  # 8 = CT_BINARY
            (6, 8, min_raw),
        ]
    )


def _fixed_stat_bytes(compact: np.ndarray, physical: int) -> tuple[bytes | None, bytes | None]:
    if len(compact) == 0:
        return None, None
    if physical == T_BOOLEAN:
        b = compact.astype(np.bool_)
        return (b"\x01" if bool(b.min()) else b"\x00"), (b"\x01" if bool(b.max()) else b"\x00")
    fmt = _STAT_PACK[physical]
    if physical in (T_FLOAT, T_DOUBLE):
        with np.errstate(invalid="ignore"):
            lo, hi = np.nanmin(compact), np.nanmax(compact)
        if np.isnan(lo) or np.isnan(hi):
            return None, None
    else:
        lo, hi = compact.min(), compact.max()
    return struct.pack(fmt, lo), struct.pack(fmt, hi)


def _byte_stat(value, utf8: bool) -> bytes | None:
    raw = value.encode("utf-8") if isinstance(value, str) else bytes(value)
    return raw if len(raw) < _STAT_MAX_LEN else None


class _IdentityIndex:
    """cidx stand-in for all-valid columns: row index == compact index,
    without materializing an arange."""

    def __getitem__(self, i):
        return i


def _compact_index(validity: np.ndarray | None, n: int):
    """Prefix-sum mapping row index → index into the nulls-stripped value
    vector (page slicing of compact arrays)."""
    if validity is None:
        return _IdentityIndex()
    out = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(validity, out=out[1:])
    return out


class _PageSink:
    """Accumulates pages of one chunk and assembles v1/v2 page bytes."""

    def __init__(self, chunk: EncodedChunk, compress, page_v2: bool, codec_id: int):
        self.chunk = chunk
        self.compress = compress
        self.page_v2 = page_v2
        self.codec_id = codec_id

    def add_dict_page(self, payload: bytes, num_values: int, is_sorted: bool) -> None:
        body = self.compress(payload)
        header = build_struct(
            [
                (1, _I32, PAGE_DICTIONARY),
                (2, _I32, len(payload)),
                (3, _I32, len(body)),
                (
                    7,
                    _STRUCT,
                    build_struct(
                        [(1, _I32, num_values), (2, _I32, ENC_PLAIN), (3, _BOOL, is_sorted)]
                    ),
                ),
            ]
        )
        self.chunk.pages.append(header + body)
        self.chunk.dict_page_len = len(header) + len(body)
        self.chunk.total_uncompressed += len(header) + len(payload)
        self.chunk.total_compressed += len(header) + len(body)

    def add_data_page(self, levels: bytes, values: bytes, n: int, n_valid: int, enc: int) -> None:
        if self.page_v2:
            body = self.compress(values) if self.codec_id else values
            header = build_struct(
                [
                    (1, _I32, PAGE_DATA_V2),
                    (2, _I32, len(levels) + len(values)),
                    (3, _I32, len(levels) + len(body)),
                    (
                        8,
                        _STRUCT,
                        build_struct(
                            [
                                (1, _I32, n),
                                (2, _I32, n - n_valid),
                                (3, _I32, n),
                                (4, _I32, enc),
                                (5, _I32, len(levels)),
                                (6, _I32, 0),
                                (7, _BOOL, bool(self.codec_id)),
                            ]
                        ),
                    ),
                ]
            )
            page = header + levels + body
            self.chunk.total_uncompressed += len(header) + len(levels) + len(values)
            self.chunk.total_compressed += len(page)
        else:
            raw = struct.pack("<I", len(levels)) + levels + values
            body = self.compress(raw)
            header = build_struct(
                [
                    (1, _I32, PAGE_DATA),
                    (2, _I32, len(raw)),
                    (3, _I32, len(body)),
                    (
                        5,
                        _STRUCT,
                        build_struct(
                            [(1, _I32, n), (2, _I32, enc), (3, _I32, ENC_RLE), (4, _I32, ENC_RLE)]
                        ),
                    ),
                ]
            )
            page = header + body
            self.chunk.total_uncompressed += len(header) + len(raw)
            self.chunk.total_compressed += len(page)
        self.chunk.pages.append(page)
        self.chunk.num_pages += 1


def _page_bounds(n: int, bytes_per_value: float, page_size: int) -> range:
    rows = max(1, int(page_size / max(bytes_per_value, 1e-9)))
    return range(0, n, rows)


def _valid_arrow_array(col: Column, validity: np.ndarray | None):
    """Nulls-stripped pyarrow array for a byte-array column — reuses the
    column's arrow backing when present, else pays the one object→arrow
    conversion (the same cost the arrow writer's to_arrow pays)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if col.arrow is not None and col._values is None:
        arr = col.arrow
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if arr.null_count:
            arr = pc.drop_null(arr)
        return arr
    vals = col.values if validity is None else col.values[validity]
    return pa.array(vals, from_pandas=True)


def _arrow_parts(arr) -> tuple[np.ndarray, bytes]:
    """(lengths, payload) straight from a string/binary array's buffers."""
    import pyarrow as pa

    if pa.types.is_large_string(arr.type) or pa.types.is_large_binary(arr.type):
        off_dt = np.dtype(np.int64)
    elif pa.types.is_string(arr.type) or pa.types.is_binary(arr.type):
        off_dt = np.dtype(np.int32)
    else:
        raise UnsupportedParquetFeature(f"arrow type {arr.type} is not string-like")
    bufs = arr.buffers()
    offsets = np.frombuffer(
        bufs[1], dtype=off_dt, count=len(arr) + 1, offset=arr.offset * off_dt.itemsize
    ).astype(np.int64)
    data = np.frombuffer(bufs[2] or b"", dtype=np.uint8)
    lengths = np.diff(offsets)
    payload = data[offsets[0] : offsets[-1]].tobytes()
    return lengths, payload


def encode_chunk(
    col: Column,
    dtype: DataType,
    physical: int,
    *,
    page_size: int,
    page_v2: bool,
    enable_dict: bool,
    codec_id: int,
    codec_name: str | None,
    zstd_level: int | None,
    metrics=None,
) -> EncodedChunk:
    """Encode one column (one row group's worth) into an EncodedChunk."""
    n = len(col)
    validity = col.validity
    n_valid = n if validity is None else int(validity.sum())
    levels = None if validity is None else kernels.validity_to_def_levels(validity, n)
    cidx = _compact_index(validity, n)
    chunk = EncodedChunk(physical_type=physical, num_values=n)
    sink = _PageSink(chunk, _compressor(codec_id, codec_name, zstd_level), page_v2, codec_id)
    utf8 = _is_utf8(dtype)

    stats_min: bytes | None = None
    stats_max: bytes | None = None
    encodings = {ENC_RLE}
    t_stats = 0.0

    if physical == T_BYTE_ARRAY:
        dict_route = _byte_array_route(col, validity, n_valid, enable_dict)
        if dict_route is not None:
            codes, pool_lens, pool_payload, is_sorted, lo, hi = dict_route
            if lo is not None:
                t0 = time.perf_counter()
                stats_min, stats_max = _byte_stat(lo, utf8), _byte_stat(hi, utf8)
                t_stats += time.perf_counter() - t0
            dict_size = len(pool_lens)
            sink.add_dict_page(
                kernels.encode_plain_byte_array(pool_lens, pool_payload), dict_size, is_sorted
            )
            if metrics is not None:
                metrics.counter("dict_pages").inc()
            width = kernels.bit_width_for(max(dict_size - 1, 0))
            if n_valid > 50_000 and 0 < width < 32 and width % 8:
                # byte-aligned widths pack as a cast+reshape instead of a
                # bit-matrix expansion; the compression codec absorbs the
                # few padding bits per value (any width >= needed is legal)
                width = (width + 7) & ~7
            encodings |= {ENC_PLAIN, ENC_RLE_DICTIONARY}
            bounds = _page_bounds(n, max(width, 1) / 8 + 0.125, page_size)
            for start in bounds:
                stop = min(start + bounds.step, n)
                page_codes = codes[cidx[start] : cidx[stop]]
                body = bytes([width]) + kernels.encode_rle_hybrid(page_codes, width)
                sink.add_data_page(
                    _level_bytes(levels, start, stop),
                    body,
                    stop - start,
                    len(page_codes),
                    ENC_RLE_DICTIONARY,
                )
        else:
            lengths, payload, lo, hi = _byte_array_plain(col, validity, n_valid)
            if lo is not None:
                t0 = time.perf_counter()
                stats_min, stats_max = _byte_stat(lo, utf8), _byte_stat(hi, utf8)
                t_stats += time.perf_counter() - t0
            encodings.add(ENC_PLAIN)
            pay_off = np.zeros(len(lengths) + 1, dtype=np.int64)
            np.cumsum(lengths, out=pay_off[1:])
            bpv = 4 + (float(lengths.mean()) if len(lengths) else 0.0)
            bounds = _page_bounds(n, bpv, page_size)
            for start in bounds:
                stop = min(start + bounds.step, n)
                vs, ve = cidx[start], cidx[stop]
                body = kernels.encode_plain_byte_array(
                    lengths[vs:ve], payload[pay_off[vs] : pay_off[ve]]
                )
                sink.add_data_page(
                    _level_bytes(levels, start, stop), body, stop - start, int(ve - vs), ENC_PLAIN
                )
    else:
        compact, enc = _fixed_values(col, dtype, physical, validity, n_valid)
        dict_route = (
            _fixed_dict_route(compact, n_valid) if enc == ENC_PLAIN and enable_dict and physical in (T_INT32, T_INT64) else None
        )
        if dict_route is not None:
            # numeric dictionary route (ISSUE 13, declared PR 12 follow-up):
            # low-cardinality int32/int64/date columns emit a sorted
            # dictionary page + RLE_DICTIONARY codes, so NATIVE-written
            # files join the fixed-width code-domain reads (merge.
            # dict-domain) the arrow path already enables — lookups and
            # joins on these columns then match on codes, zero expansion
            pool, codes = dict_route
            if n_valid:
                t0 = time.perf_counter()
                # np.unique pools are sorted and fully referenced: chunk
                # stats reduce over the pool edges, no row-sized pass
                stats_min, stats_max = _fixed_stat_bytes(pool[[0, -1]], physical)
                t_stats += time.perf_counter() - t0
            sink.add_dict_page(kernels.encode_plain(pool, physical), len(pool), True)
            if metrics is not None:
                metrics.counter("dict_pages").inc()
            width = kernels.bit_width_for(max(len(pool) - 1, 0))
            if n_valid > 50_000 and 0 < width < 32 and width % 8:
                width = (width + 7) & ~7  # byte-aligned pack fast path
            encodings |= {ENC_PLAIN, ENC_RLE_DICTIONARY}
            bounds = _page_bounds(n, max(width, 1) / 8 + 0.125, page_size)
            for start in bounds:
                stop = min(start + bounds.step, n)
                page_codes = codes[cidx[start] : cidx[stop]]
                body = bytes([width]) + kernels.encode_rle_hybrid(page_codes, width)
                sink.add_data_page(
                    _level_bytes(levels, start, stop),
                    body,
                    stop - start,
                    len(page_codes),
                    ENC_RLE_DICTIONARY,
                )
            null_count = n - n_valid
            chunk.stats = _stats_struct(stats_min, stats_max, null_count)
            chunk.encodings = tuple(sorted(encodings))
            if metrics is not None:
                metrics.counter("pages_written").inc(chunk.num_pages)
                metrics.histogram("stats_ms").update(t_stats * 1000)
            return chunk
        if stats_min is None and n_valid:
            t0 = time.perf_counter()
            stats_min, stats_max = _fixed_stat_bytes(compact, physical)
            t_stats += time.perf_counter() - t0
        encodings.add(enc)
        bpv = 0.125 if physical == T_BOOLEAN else _STAT_ITEMSIZE[physical]
        bounds = _page_bounds(n, bpv, page_size)
        for start in bounds:
            stop = min(start + bounds.step, n)
            vs, ve = cidx[start], cidx[stop]
            page_vals = compact[vs:ve]
            if enc == ENC_DELTA_BINARY_PACKED and len(page_vals):
                body = kernels.encode_delta_binary_packed(page_vals, physical)
            elif physical == T_BOOLEAN:
                body = kernels.encode_plain_boolean(page_vals)
            else:
                body = kernels.encode_plain(page_vals, physical)
            sink.add_data_page(
                _level_bytes(levels, start, stop), body, stop - start, int(ve - vs), enc
            )
    null_count = n - n_valid
    chunk.stats = _stats_struct(stats_min, stats_max, null_count)
    chunk.encodings = tuple(sorted(encodings))
    if metrics is not None:
        metrics.counter("pages_written").inc(chunk.num_pages)
        metrics.histogram("stats_ms").update(t_stats * 1000)
    return chunk


_STAT_ITEMSIZE = {T_INT32: 4, T_INT64: 8, T_FLOAT: 4, T_DOUBLE: 8, T_BOOLEAN: 1}


def _level_bytes(levels: np.ndarray | None, start: int, stop: int) -> bytes:
    if levels is None:  # all valid: one RLE run of level 1, no vectors at all
        from ..decode.thrift import append_uvarint

        out = bytearray()
        append_uvarint(out, (stop - start) << 1)
        out += b"\x01"
        return bytes(out)
    return kernels.encode_rle_hybrid(levels[start:stop], 1)


def _fixed_values(col: Column, dtype: DataType, physical: int, validity, n_valid: int):
    """Nulls-stripped fixed-width values + the encoding to use."""
    values = col.values
    if validity is not None:
        values = values[validity]
    if physical == T_BOOLEAN:
        return np.ascontiguousarray(values, dtype=np.bool_), ENC_PLAIN
    np_dt = kernels._PLAIN_DTYPES[physical]
    compact = np.ascontiguousarray(values, dtype=np_dt)
    if (
        physical in (T_INT32, T_INT64)
        and n_valid >= 64
        and bool(np.all(np.diff(compact) >= 0))
    ):
        # sorted int columns (merge output keys, sequence runs): the delta
        # stream compresses far below PLAIN and packs vectorized
        return compact, ENC_DELTA_BINARY_PACKED
    return compact, ENC_PLAIN


def _fixed_dict_route(compact: np.ndarray, n_valid: int):
    """(sorted pool, int64 codes) for a low-cardinality fixed-width column,
    or None for the PLAIN/DELTA path. Small columns (< 64 valid values)
    stay PLAIN — a dictionary page cannot pay for itself there."""
    if n_valid < 64:
        return None
    pool, codes = np.unique(compact, return_inverse=True)
    if len(pool) * _DICT_RATIO_DEN > n_valid * _DICT_RATIO_NUM:
        return None  # domain ~as large as the data: PLAIN wins
    return pool, codes.astype(np.int64)


def _byte_array_route(col: Column, validity, n_valid: int, enable_dict: bool):
    """Dictionary route for a BYTE_ARRAY column, or None for PLAIN.

    Returns (codes, pool_lengths, pool_payload, is_sorted, min, max)."""
    if not enable_dict or n_valid == 0:
        return None
    cache = getattr(col, "dict_cache", None)
    if cache is not None and len(cache[1]) == len(col):
        pool, codes = cache
        if validity is not None:
            codes = codes[validity]
        # pool entries no surviving row references (filtered deletes, merge
        # losers, unified-domain strays) must not reach the file: pruning
        # keeps dictionaries minimal across compaction chains and equal to
        # the expanded path's exact pools
        from ..ops.dicts import prune_pool

        pool, codes = prune_pool(pool, codes)
        codes = np.ascontiguousarray(codes, dtype=np.int64)
        pool_lens, pool_payload = kernels.byte_array_parts(pool)
        lo = pool[int(codes.min())] if len(codes) else None
        hi = pool[int(codes.max())] if len(codes) else None
        return codes, pool_lens, pool_payload, True, lo, hi
    import pyarrow.compute as pc

    arr = _valid_arrow_array(col, validity)
    denc = arr.dictionary_encode()
    dict_size = len(denc.dictionary)
    if dict_size * _DICT_RATIO_DEN > n_valid * _DICT_RATIO_NUM:
        return None  # domain ~as large as the data: PLAIN wins
    codes = denc.indices.to_numpy(zero_copy_only=False).astype(np.int64)
    pool_lens, pool_payload = _arrow_parts(denc.dictionary)
    mm = pc.min_max(arr).as_py() if n_valid else {"min": None, "max": None}
    return codes, pool_lens, pool_payload, False, mm["min"], mm["max"]


def _byte_array_plain(col: Column, validity, n_valid: int):
    """PLAIN route: (lengths, payload, min, max) for the valid values."""
    if n_valid == 0:
        return np.zeros(0, dtype=np.int64), b"", None, None
    cache = getattr(col, "dict_cache", None)
    if cache is not None and len(cache[1]) == len(col):
        # dictionary disabled but the merge pool still pays for stats: the
        # pool is sorted, so min/max come from the code range without any
        # object comparison; the values stream packs via the np.char path
        pool, codes = cache
        if validity is not None:
            codes = codes[validity]
        values = col.values if validity is None else col.values[validity]
        lens, payload = kernels.byte_array_parts(values)
        return lens, payload, pool[int(codes.min())], pool[int(codes.max())]
    import pyarrow.compute as pc

    arr = _valid_arrow_array(col, validity)
    lengths, payload = _arrow_parts(arr)
    mm = pc.min_max(arr).as_py()
    return lengths, payload, mm["min"], mm["max"]
