"""Native vectorized Parquet page-encode subsystem — the write-side dual of
paimon_tpu.decode.

Takes merge-kernel output (padded columnar ndarrays, string keys already
dictionary ranks against a sorted pool) to parquet file bytes without
routing through ColumnBatch.to_arrow + pq.write_table. The layers:

  decode/thrift.py — compact-protocol writer (build_struct) shared with the
                     parser, for page headers and the footer
  kernels.py       — vectorized encoders: LSB bit-pack, RLE/bit-packed
                     hybrid, PLAIN (incl. booleans + byte arrays),
                     DELTA_BINARY_PACKED, validity → def-levels (numpy
                     engine + jittable JAX twin pack_bits_jax)
  pages.py         — column → dictionary page + data pages + chunk stats;
                     consumes the merge path's string pools/rank vectors
                     directly (Column.dict_cache) so no string object
                     materializes between merge and file bytes
  writer.py        — chunk/row-group/footer assembly with vectorized
                     min/max statistics and TYPE_DEFINED_ORDER column
                     orders, so both `_row_group_stats` pruning and the
                     decode subsystem's chunk-stats gate keep working

Entry point `write_native` mirrors `ParquetFormat.write`'s arrow semantics:
same schema annotations (UTF8 / INT_8 / INT_16), OPTIONAL leaves, same
writer knobs (`parquet.page-size`, `parquet.data-page-version`,
`parquet.row-group.rows`, `file.block-size`, `parquet.enable.dictionary`,
`file.compression.zstd-level`). Batches needing features outside the
native envelope raise UnsupportedParquetFeature BEFORE any byte is written
and the format falls back to the arrow writer per file (counter
encode.files_fallback).

Surfaced behind the FileFormat registry as table option
`format.parquet.encoder = arrow | native` (default arrow).
"""

from __future__ import annotations

from ..data.batch import ColumnBatch
from ..decode.container import UnsupportedParquetFeature
from ..fs import FileIO
from ..metrics import encode_metrics, timed
from .writer import encode_parquet_bytes

__all__ = ["write_native", "encode_parquet_bytes", "UnsupportedParquetFeature"]

# process-lifetime counter, deliberately OUTSIDE the metrics registry so
# registry.reset() in tests cannot zero it: scripts/verify.sh stages that
# force PAIMON_TPU_PARQUET_ENCODER=native assert at session end that the
# native encoder actually ran (conftest._forced_encoder_coverage)
_files_native_total = 0


def files_native_total() -> int:
    return _files_native_total


def write_native(
    file_io: FileIO,
    path: str,
    batch: ColumnBatch,
    compression: str | None = "zstd",
    format_options: dict | None = None,
) -> None:
    """Encode one batch natively and write it. Raises
    UnsupportedParquetFeature (without writing anything) when the batch is
    outside the native envelope — the caller falls back to arrow per file."""
    global _files_native_total
    metrics = encode_metrics()
    with timed(metrics.histogram("encode_ms")):
        data = encode_parquet_bytes(batch, compression, format_options, metrics=metrics)
    file_io.write_bytes(path, data)
    metrics.counter("files_native").inc()
    metrics.counter("bytes_written").inc(len(data))
    _files_native_total += 1
