"""zstd codec facade: the real `zstandard` module when installed, else
pyarrow's bundled zstd (always present — pyarrow is a hard dependency).

The on-disk bytes are identical either way (standard zstd frames, content
size embedded in the frame header), so files written under one backend read
under the other. pyarrow's Codec.decompress needs the decompressed size up
front, which both backends' one-shot compress embed in the frame header —
`_frame_content_size` parses it (RFC 8878 §3.1.1). Streaming-written frames
without a content size only occur on foreign files; those need the real
`zstandard` module and fail with a clear message otherwise.
"""

from __future__ import annotations

__all__ = ["ZSTD_MAGIC", "zstd_available", "zstd_compress", "zstd_decompress"]

ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"

try:  # pragma: no cover - depends on environment
    import zstandard as _zstd
except ImportError:
    _zstd = None


def zstd_available() -> bool:
    """True when SOME zstd backend exists (practically always: pyarrow)."""
    if _zstd is not None:
        return True
    import pyarrow as pa

    return pa.Codec.is_available("zstd")


def zstd_compress(data: bytes, level: int = 3) -> bytes:
    if _zstd is not None:
        return _zstd.ZstdCompressor(level=level).compress(data)
    import pyarrow as pa

    return pa.Codec("zstd", compression_level=level).compress(data, asbytes=True)


def _frame_content_size(data: bytes) -> int | None:
    """Decompressed size from the zstd frame header, None when absent."""
    if len(data) < 6 or data[:4] != ZSTD_MAGIC:
        return None
    fhd = data[4]
    fcs_flag = fhd >> 6
    single_segment = (fhd >> 5) & 1
    dict_flag = fhd & 3
    pos = 5 + (0 if single_segment else 1) + (0, 1, 2, 4)[dict_flag]
    if fcs_flag == 0:
        if not single_segment:
            return None
        return data[pos] if pos < len(data) else None
    size_bytes = (0, 2, 4, 8)[fcs_flag]
    field = data[pos : pos + size_bytes]
    if len(field) < size_bytes:
        return None
    value = int.from_bytes(field, "little")
    return value + 256 if fcs_flag == 1 else value


def zstd_decompress(data: bytes) -> bytes:
    if _zstd is not None:
        return _zstd.ZstdDecompressor().decompress(data)
    import pyarrow as pa

    size = _frame_content_size(data)
    if size is None:
        raise ValueError(
            "zstd frame carries no content size (streaming-written?); "
            "decoding it needs the optional 'zstandard' module"
        )
    return pa.Codec("zstd").decompress(data, decompressed_size=size, asbytes=True)
