"""Byte-budget caching subsystem: process-wide size-aware LRU caches for
immutable decoded objects.

Parity: /root/reference/paimon-common/.../memory/MemoryPoolFactory +
paimon-core/.../utils/ObjectsCache / SegmentsCache — upstream Paimon treats
manifest caching as a first-class perf feature: manifest files, manifest
lists, and snapshots are immutable once written, so their decoded forms are
cached process-wide and keyed by file name. This module grows the same idea
two ways:

  * the **manifest cache** holds decoded metadata objects — ManifestEntry
    lists, ManifestFileMeta lists, parsed Snapshots, and the validated
    latest-snapshot pointer — weighted by their decoded (uncompressed) byte
    size;
  * the **data-file cache** holds decoded KVBatch/ColumnBatch results of
    `KeyValueFileReaderFactory.read`, keyed by (file name, projection,
    system-columns mode, read-schema signature, decoder identity — the
    `format.parquet.decoder` backend that produced the batch, so switching
    arrow↔native can never alias a batch decoded by the other backend) and
    weighted by `KVBatch.byte_size()`.

Both caches are module-level singletons (file names embed uuid4, so keys are
globally unique across tables and processes can share one budget), budgeted
through table options `cache.manifest.max-memory-size` /
`cache.data-file.max-memory-size` ('0 b' opts a table out entirely), and
observable through the metrics registry as group "cache" tagged by cache
name: counters hits/misses/evictions/invalidations, gauges bytes/entries.

Invalidation contract: cached values are treated as immutable by every
client (readers copy-on-filter, never mutate in place). Physical deletions —
snapshot expiry, changelog expiry, rollback, compaction dropping files from
the LSM view — call the invalidate_* helpers below so the budget tracks the
live working set and deleted snapshots stop resolving.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import TYPE_CHECKING, Any, Callable

if TYPE_CHECKING:
    from ..options import CoreOptions

__all__ = [
    "ByteBudgetLRU",
    "manifest_cache",
    "data_file_cache",
    "table_caches",
    "configure",
    "clear_all",
    "invalidate_data_file",
    "invalidate_manifest_path",
    "invalidate_snapshot",
    "invalidate_latest_pointer",
]

# process-wide defaults, overridable per table via options (the most recent
# explicitly-configured table wins — budgets are process-global, like the
# reference CacheManager created from catalog options)
DEFAULT_MANIFEST_BUDGET = 256 << 20
DEFAULT_DATA_FILE_BUDGET = 128 << 20


class ByteBudgetLRU:
    """Thread-safe size-aware LRU keyed by immutable identity.

    Entries carry an explicit byte weight; inserts evict from the cold end
    until the total fits `max_bytes`. A value heavier than the whole budget
    is simply not cached (loader result is still returned). An optional
    per-entry `file_id` feeds a secondary index so every projection/variant
    of one physical file can be dropped with a single `invalidate_file`.
    """

    def __init__(self, name: str, max_bytes: int):
        self.name = name
        self.max_bytes = int(max_bytes)
        self._lock = threading.RLock()
        self._entries: "OrderedDict[Any, tuple[Any, int, str | None]]" = OrderedDict()
        self._by_file: dict[str, set] = {}
        self._bytes = 0
        self._metrics()

    def _metrics(self):
        """The cache's metric group, resolved per call: registry.reset()
        (tests) replaces the group, and counters bound at construction would
        keep counting into orphaned objects."""
        from ..metrics import registry

        g = registry.group("cache", cache=self.name)
        if "bytes" not in g.metrics:
            g.gauge("bytes", lambda: self._bytes)
            g.gauge("entries", lambda: len(self._entries))
            g.gauge("max_bytes", lambda: self.max_bytes)
        return g

    # ---- core ops ------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    @property
    def total_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def contains_file(self, file_id: str) -> bool:
        with self._lock:
            return file_id in self._by_file

    def get(self, key):
        """The cached value, or None on miss (values are never None)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self._metrics().counter("misses").inc()
                return None
            self._entries.move_to_end(key)
            self._metrics().counter("hits").inc()
            return entry[0]

    def put(self, key, value, weight: int, file_id: str | None = None) -> None:
        if not self.enabled or value is None:
            return
        weight = max(int(weight), 64)  # floor: key + bookkeeping overhead
        if weight > self.max_bytes:
            return  # oversized value would evict the whole working set
        with self._lock:
            if key in self._entries:
                self._drop(key)
            self._entries[key] = (value, weight, file_id)
            self._bytes += weight
            if file_id is not None:
                self._by_file.setdefault(file_id, set()).add(key)
            while self._bytes > self.max_bytes and self._entries:
                cold_key, (_, w, fid) = self._entries.popitem(last=False)
                self._bytes -= w
                if fid is not None:
                    keys = self._by_file.get(fid)
                    if keys is not None:
                        keys.discard(cold_key)
                        if not keys:
                            del self._by_file[fid]
                self._metrics().counter("evictions").inc()

    def get_or_load(
        self,
        key,
        loader: Callable[[], Any],
        weigher: Callable[[Any], int],
        file_id: str | None = None,
    ):
        """Cached value or `loader()` (run OUTSIDE the lock — concurrent
        misses may load twice; last writer wins, both results identical
        because the underlying file is immutable)."""
        if not self.enabled:
            return loader()
        value = self.get(key)
        if value is not None:
            return value
        value = loader()
        self.put(key, value, weigher(value), file_id)
        return value

    # ---- invalidation --------------------------------------------------
    def _drop(self, key) -> None:
        value, weight, file_id = self._entries.pop(key)
        self._bytes -= weight
        if file_id is not None:
            keys = self._by_file.get(file_id)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._by_file[file_id]

    def invalidate(self, key) -> bool:
        with self._lock:
            if key not in self._entries:
                return False
            self._drop(key)
            self._metrics().counter("invalidations").inc()
            return True

    def invalidate_file(self, file_id: str) -> int:
        """Drop every entry derived from one physical file."""
        with self._lock:
            keys = self._by_file.pop(file_id, None)
            if not keys:
                return 0
            n = 0
            for key in list(keys):
                if key in self._entries:
                    value, weight, _ = self._entries.pop(key)
                    self._bytes -= weight
                    self._metrics().counter("invalidations").inc()
                    n += 1
            return n

    def invalidate_prefix(self, path_prefix: str) -> int:
        """Drop every entry whose file_id lives under `path_prefix` — the
        recursive-delete hook (drop table, delete branch): file names under
        the deleted tree can be re-minted with different content."""
        with self._lock:
            victims = [fid for fid in self._by_file if fid.startswith(path_prefix)]
        n = 0
        for fid in victims:
            n += self.invalidate_file(fid)
        return n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_file.clear()
            self._bytes = 0

    def set_budget(self, max_bytes: int) -> None:
        with self._lock:
            self.max_bytes = int(max_bytes)
            while self._bytes > self.max_bytes and self._entries:
                cold_key, (_, w, fid) = self._entries.popitem(last=False)
                self._bytes -= w
                if fid is not None:
                    keys = self._by_file.get(fid)
                    if keys is not None:
                        keys.discard(cold_key)
                        if not keys:
                            del self._by_file[fid]
                self._metrics().counter("evictions").inc()


# ---------------------------------------------------------------------------
# process-wide instances
# ---------------------------------------------------------------------------

_caches: dict[str, ByteBudgetLRU] = {}
_caches_lock = threading.Lock()


def _reset_after_fork() -> None:
    # a forked child inherits cache RLocks that another thread may have held
    # at fork time (dead-thread locks never release), and a fork mid-put can
    # leave entries/bytes torn. Re-arm the locks IN PLACE (pre-fork store
    # objects keep their references) and start the child cold.
    global _caches_lock
    _caches_lock = threading.Lock()
    for c in _caches.values():
        c._lock = threading.RLock()
        c._entries.clear()
        c._by_file.clear()
        c._bytes = 0


import os as _os  # noqa: E402

if hasattr(_os, "register_at_fork"):
    _os.register_at_fork(after_in_child=_reset_after_fork)


def _get(name: str, default_budget: int) -> ByteBudgetLRU:
    cache = _caches.get(name)
    if cache is None:
        with _caches_lock:
            cache = _caches.get(name)
            if cache is None:
                cache = ByteBudgetLRU(name, default_budget)
                _caches[name] = cache
    return cache


def manifest_cache() -> ByteBudgetLRU:
    """Decoded metadata objects: manifest entry lists, manifest-list metas,
    parsed snapshots, the validated latest-snapshot pointer."""
    return _get("manifest", DEFAULT_MANIFEST_BUDGET)


def data_file_cache() -> ByteBudgetLRU:
    """Decoded KVBatch results of reader_factory.read (predicate-free reads
    only — predicate pushdown changes the row set)."""
    return _get("data-file", DEFAULT_DATA_FILE_BUDGET)


def configure(manifest_bytes: int | None = None, data_file_bytes: int | None = None) -> None:
    if manifest_bytes is not None:
        manifest_cache().set_budget(manifest_bytes)
    if data_file_bytes is not None:
        data_file_cache().set_budget(data_file_bytes)


def table_caches(options: "CoreOptions") -> tuple[ByteBudgetLRU | None, ByteBudgetLRU | None]:
    """(manifest cache, data-file cache) for one table's options — None when
    the table opted out with a 0 budget. An explicitly-set option resizes the
    process-wide budget (last writer wins; budgets are global like the
    reference CacheManager's)."""
    from ..options import CoreOptions

    m_opt, d_opt = CoreOptions.CACHE_MANIFEST_MAX_MEMORY, CoreOptions.CACHE_DATA_FILE_MAX_MEMORY
    m_budget = int(options.options.get(m_opt))
    d_budget = int(options.options.get(d_opt))
    m = manifest_cache() if m_budget > 0 else None
    d = data_file_cache() if d_budget > 0 else None
    if m is not None and options.options.contains(m_opt) and m.max_bytes != m_budget:
        m.set_budget(m_budget)
    if d is not None and options.options.contains(d_opt) and d.max_bytes != d_budget:
        d.set_budget(d_budget)
    return m, d


def clear_all() -> None:
    for cache in list(_caches.values()):
        cache.clear()


# ---- invalidation helpers (called from deletion paths regardless of any
# single table's enablement — dropping from an empty cache is a no-op) ------


def invalidate_data_file(file_name: str) -> None:
    """A data file left the filesystem (expire/rollback) or the live LSM
    view (compaction drop): every cached projection of it goes."""
    data_file_cache().invalidate_file(file_name)


def invalidate_manifest_path(path: str) -> None:
    """`path` is the full manifest/manifest-list/snapshot file path."""
    manifest_cache().invalidate_file(path)


def invalidate_snapshot(table_path: str, snapshot_id: int) -> None:
    manifest_cache().invalidate_file(f"{table_path}/snapshot/snapshot-{snapshot_id}")


def invalidate_latest_pointer(table_path: str) -> None:
    manifest_cache().invalidate(("latest", table_path))


def invalidate_table_path(table_path: str) -> None:
    """A whole table (or branch) directory was recursively deleted: snapshot
    ids under it can be re-minted with different content, so every metadata
    entry below the path goes, plus its latest pointer. Data-file entries are
    keyed by uuid-unique names and can never be re-minted — left to LRU."""
    manifest_cache().invalidate_prefix(table_path.rstrip("/") + "/")
    manifest_cache().invalidate(("latest", table_path))
