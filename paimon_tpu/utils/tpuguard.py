"""Wedge-proof access to the (single, tunnelled) TPU chip.

Operational lessons baked in (round 2 lost ALL its chip benchmark data to
one killed process):

1. **Never kill a process mid-backend-init or mid-RPC.** A SIGKILLed client
   leaves the device grant unreclaimed and the tunnel answers nobody for
   hours ("grant unclaimed").  So the probe here launches a DETACHED child
   (own session, never killed); on timeout the parent just stops waiting —
   the child either completes later and caches its verdict, or idles
   harmlessly queued on the grant.
2. **Never run two TPU processes concurrently.** Every TPU user — the probe
   child included — takes an exclusive flock on a well-known lock file
   before backend init; a second user waits or fails fast instead of racing
   for the grant.
3. **Exit cleanly on SIGTERM/SIGINT.** Default SIGTERM disposition skips
   atexit, so the jax client never tears down its grant.  `install_signal_
   handlers` converts both to `SystemExit` so teardown runs.  (SIGKILL is
   out of our hands — the runbook below is the mitigation.)
4. **Fail loudly, never silently.** `ensure_live_backend` prints a WEDGE
   warning on stderr when it pins CPU, and `PAIMON_TPU_REQUIRE=1` (or
   `require_tpu=True`) turns the fallback into exit code 3 so a perf run
   can never masquerade as healthy.

Runbook when the tunnel is wedged: do NOT keep spawning probes (each one
queues on the dead grant).  Leave ONE detached probe running — it doubles as
a recovery sentinel: the cached verdict flips to reachable the moment the
grant frees (freshness is measured from probe COMPLETION, so a verdict that
took hours to arrive is still trusted).  All benchmarks poll only that cache.

No reference counterpart: the reference benchmarks on a local JVM
(paimon-benchmarks/README.md); a remote single-grant accelerator needs this
discipline layer.
"""

from __future__ import annotations

import atexit
import errno
import fcntl
import json
import os
import signal
import subprocess
import sys
import time

PROBE_CACHE = "/tmp/paimon_tpu_probe_cache.json"
PROBE_PIDFILE = "/tmp/paimon_tpu_probe.pid"
TPU_LOCK = "/tmp/paimon_tpu_device.lock"
PROBE_TTL_S = 600.0  # a reachable/unreachable verdict is trusted this long
_PROBE_MARKER = "paimon-tpu-probe"

# The child takes the single-flight lock BEFORE importing jax (rule 2), holds
# it until process exit (flock drops with the fd), and removes its pidfile on
# the way out so a recycled pid can't impersonate a live probe.
_PROBE_CHILD = r"""
import fcntl, json, os, sys, time
lock_fd = os.open(%(lock)r, os.O_CREAT | os.O_RDWR, 0o666)
fcntl.flock(lock_fd, fcntl.LOCK_EX)  # waits for any active TPU user
t0 = time.time()
res = {"pid": os.getpid(), "started": t0, "done": True,
       "platforms_env": os.environ.get("JAX_PLATFORMS", "")}
try:
    import jax
    devs = jax.devices()
    res.update(n=len(devs), backend=jax.default_backend(),
               init_s=round(time.time() - t0, 1))
except Exception as e:  # noqa: BLE001
    res.update(n=0, backend="error", err=repr(e)[:300],
               init_s=round(time.time() - t0, 1))
res["completed"] = time.time()
tmp = %(cache)r + ".tmp"
with open(tmp, "w") as f:
    json.dump(res, f)
os.replace(tmp, %(cache)r)
try:
    os.remove(%(pidfile)r)
except OSError:
    pass
"""


def _read_cache() -> dict | None:
    """The cached verdict, or None when absent/stale/from another env.

    A verdict is only valid for the same JAX_PLATFORMS environment: a
    JAX_PLATFORMS=cpu probe answering (1, "cpu") says nothing about the
    accelerator and must not convince a TPU run to skip its guard."""
    try:
        with open(PROBE_CACHE) as f:
            c = json.load(f)
    except Exception:
        return None
    if not c.get("done"):
        return None
    if c.get("platforms_env", "") != os.environ.get("JAX_PLATFORMS", ""):
        return None
    # freshness from COMPLETION: a sentinel probe that sat hours queued on a
    # wedged grant still delivers a trusted verdict the moment it lands
    if (time.time() - c.get("completed", c.get("started", 0))) >= PROBE_TTL_S:
        return None
    return c


def _probe_child_alive() -> int | None:
    """Pid of a live in-flight probe child, else None.

    Guards against pid recycling: the pid must look like a probe (cmdline
    carries the marker, or imports jax+devices for pre-marker sentinels).
    EPERM means *something* lives at that pid but it isn't our probe child
    (probes run as this user) — treat as dead."""
    try:
        with open(PROBE_PIDFILE) as f:
            pid = int(f.read().strip())
        os.kill(pid, 0)  # existence check only — NEVER an actual kill
    except Exception:
        return None
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read()
        if _PROBE_MARKER.encode() in cmdline or (b"jax" in cmdline and b"devices" in cmdline):
            return pid
        return None
    except OSError:
        return pid  # no /proc: keep the conservative existence answer


def probe_devices(timeout_s: float = 120.0, stale_negative_after_s: float | None = None) -> tuple[int, str]:
    """(device_count, backend) — detached-probe edition.

    Spawns (or reuses) a detached child that initializes jax and writes its
    verdict to PROBE_CACHE; waits up to timeout_s for the verdict but NEVER
    kills the child on timeout (killing mid-init is what wedges the tunnel).
    A cached verdict completed less than PROBE_TTL_S ago (same JAX_PLATFORMS
    env) is returned without any probe. stale_negative_after_s tightens that
    TTL for NEGATIVE verdicts only — a retry loop wants a fresh probe soon
    after a fast failure (connection refused completes in seconds and would
    otherwise pin the negative answer for the full TTL), while positive
    verdicts stay trusted."""
    stale_completed = None
    cached = _read_cache()
    if (
        cached
        and stale_negative_after_s is not None
        and int(cached.get("n", 0)) == 0
        and (time.time() - cached.get("completed", 0)) >= stale_negative_after_s
    ):
        # treat as stale: respawn below, and remember this verdict's stamp so
        # the wait loop doesn't hand the SAME still-on-disk negative straight
        # back (which would skip the whole timeout)
        stale_completed = cached.get("completed", 0)
        cached = None
    if cached:
        return int(cached.get("n", 0)), str(cached.get("backend", "unreachable"))

    if _probe_child_alive() is None:
        # fresh probe, fully detached: its own session, no inherited fds
        script = _PROBE_CHILD % {"cache": PROBE_CACHE, "pidfile": PROBE_PIDFILE, "lock": TPU_LOCK}
        with open(PROBE_CACHE + ".log", "ab") as log:
            child = subprocess.Popen(
                [sys.executable, "-c", script, _PROBE_MARKER],
                stdout=log,
                stderr=log,
                start_new_session=True,
            )
        with open(PROBE_PIDFILE, "w") as f:
            f.write(str(child.pid))

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        cached = _read_cache()
        if cached and (stale_completed is None or cached.get("completed", 0) > stale_completed):
            return int(cached.get("n", 0)), str(cached.get("backend", "unreachable"))
        if _probe_child_alive() is None:
            # child exited without a fresh verdict (crashed): report, don't respawn in a loop
            break
        time.sleep(1.0)
    return 0, "unreachable (probe still initializing — tunnel wedged?)"


class SingleFlight:
    """Exclusive flock held for the lifetime of any TPU-using process.

    Two concurrent grant requests can wedge the tunnel; this makes the
    second requester wait (bounded) or fail fast instead."""

    def __init__(self, path: str = TPU_LOCK):
        self.path = path
        self._fd: int | None = None

    def acquire(self, timeout_s: float = 0.0) -> bool:
        """Try now; with timeout_s > 0, poll (non-blocking flock each round)
        until the deadline.  Always bounded — a plain blocking flock would
        hang forever on a lock orphaned by a SIGKILLed holder's child."""
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o666)
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError as e:
                if e.errno not in (errno.EAGAIN, errno.EACCES):
                    os.close(fd)
                    raise
                if time.monotonic() >= deadline:
                    os.close(fd)
                    return False
                time.sleep(0.25)
        os.ftruncate(fd, 0)
        os.write(fd, f"{os.getpid()} {time.time()}\n".encode())
        self._fd = fd
        atexit.register(self.release)
        return True

    def release(self) -> None:
        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None


def install_signal_handlers() -> None:
    """SIGTERM/SIGINT -> SystemExit so atexit (lock release, jax client
    teardown) runs instead of the process vanishing mid-RPC."""

    def _exit(sig, frame):  # noqa: ANN001
        sys.stderr.write(f"[tpuguard] signal {sig}: exiting cleanly to release device grant\n")
        raise SystemExit(128 + sig)

    for s in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(s, _exit)
        except (ValueError, OSError):
            pass  # non-main thread / restricted env


def ensure_live_backend(require_tpu: bool | None = None, probe_timeout_s: float = 180.0) -> str:
    """Benchmark entrypoint: returns the platform tag to publish.

    JAX_PLATFORMS=cpu -> honor the explicit request (every entrypoint, no
    probe).  Accelerator reachable -> take the single-flight lock (waiting
    out the probe child's teardown, which holds it until exit), install
    signal handlers, return the backend name.  Unreachable -> LOUD stderr
    warning + CPU pin, or exit(3) when required (PAIMON_TPU_REQUIRE=1)."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # config.update too: sitecustomize may pin the env var after us
        jax.config.update("jax_platforms", "cpu")
        return "cpu (requested)"
    if require_tpu is None:
        require_tpu = os.environ.get("PAIMON_TPU_REQUIRE", "") == "1"

    count, backend = probe_devices(timeout_s=probe_timeout_s)
    if count > 0:
        sf = SingleFlight()
        # a fresh probe child holds the lock until its jax client tears down,
        # which on the tunnel can take minutes — wait it out rather than
        # failing a perf run that already knows the chip is reachable
        if not sf.acquire(timeout_s=240.0):
            sys.stderr.write(
                "[tpuguard] another TPU process holds the single-flight lock; "
                "refusing to race for the device grant\n"
            )
            if require_tpu:
                raise SystemExit(3)
            jax.config.update("jax_platforms", "cpu")
            return "cpu (device busy: single-flight lock held)"
        install_signal_handlers()
        return backend

    sys.stderr.write(
        f"[tpuguard] *** ACCELERATOR UNREACHABLE ({backend}) — see runbook in "
        "paimon_tpu/utils/tpuguard.py; falling back to CPU ***\n"
    )
    if require_tpu:
        sys.stderr.write("[tpuguard] PAIMON_TPU_REQUIRE=1: refusing CPU fallback\n")
        raise SystemExit(3)
    jax.config.update("jax_platforms", "cpu")
    return "cpu (accelerator unreachable)"


def ensure_live_backend_retrying(budget_s: float | None = None) -> str:
    """Round-end benchmark entrypoint (VERDICT r3 #1): like
    ensure_live_backend, but when the accelerator is unreachable keep
    polling the probe-cache verdict for up to budget_s
    (PAIMON_TPU_BENCH_RETRY_S, default 900) before accepting the CPU
    fallback.  The poll is cheap (reads the cache file); new probes are
    respawned by probe_devices whenever the cached verdict goes stale, and
    a long-lived sentinel probe flips the verdict the moment a wedged
    grant frees — so the artifact says "tpu" whenever the chip answers
    within the budget, instead of silently pinning CPU on the first miss."""
    if budget_s is None:
        budget_s = float(os.environ.get("PAIMON_TPU_BENCH_RETRY_S", "900"))
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return "cpu (requested)"
    deadline = time.monotonic() + budget_s
    while True:
        remaining = deadline - time.monotonic()
        count, _backend = probe_devices(
            timeout_s=max(10.0, min(180.0, remaining)),
            # a fast-failing probe (connection refused) must not pin its
            # negative verdict for the whole TTL while we still have budget
            stale_negative_after_s=60.0,
        )
        if count > 0:
            return ensure_live_backend()
        if time.monotonic() >= deadline:
            # deadline path: the verdict is already known negative — don't
            # let ensure_live_backend spend another full probe window
            return ensure_live_backend(probe_timeout_s=10.0)
        sys.stderr.write(
            f"[tpuguard] accelerator not answering; retrying for another "
            f"{int(remaining)}s before CPU fallback\n"
        )
        time.sleep(20.0)
