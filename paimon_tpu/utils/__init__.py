"""Small shared utilities (naming, paths, json, shared decode pool)."""

from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Any, Sequence

__all__ = [
    "new_file_name",
    "partition_path",
    "now_millis",
    "dumps",
    "loads",
    "enable_compile_cache",
    "shared_executor",
]


_SHARED_POOL = None
_SHARED_POOL_LOCK = threading.Lock()


def _reset_shared_pool_after_fork() -> None:
    # a forked child inherits the pool OBJECT but none of its worker
    # threads — submitting to it would block forever. Drop it (and the lock,
    # which another thread may have held at fork time); the child lazily
    # builds its own.
    global _SHARED_POOL, _SHARED_POOL_LOCK
    _SHARED_POOL = None
    _SHARED_POOL_LOCK = threading.Lock()


import os as _os  # noqa: E402

if hasattr(_os, "register_at_fork"):
    _os.register_at_fork(after_in_child=_reset_shared_pool_after_fork)


def shared_executor():
    """The process-wide decode thread pool (lazily created, never torn down
    mid-run). Manifest and data-file decodes release the GIL in pyarrow/zstd,
    so threads give real parallelism — but constructing a ThreadPoolExecutor
    per call costs thread spawn/join on every small read. One shared pool
    amortizes that. Tasks submitted here must never themselves submit to this
    pool (deadlock under a full queue); both call sites (scan manifest reads,
    read-path file decodes) are leaf work. Fork-safe: see
    _reset_shared_pool_after_fork.

    Sizing: PAIMON_TPU_SHARED_POOL_WORKERS env overrides; default covers the
    common 8-way decode fan-out even on small hosts."""
    global _SHARED_POOL
    if _SHARED_POOL is None:
        with _SHARED_POOL_LOCK:
            if _SHARED_POOL is None:
                import os
                from concurrent.futures import ThreadPoolExecutor

                workers = int(os.environ.get("PAIMON_TPU_SHARED_POOL_WORKERS", "0"))
                if workers <= 0:
                    workers = min(16, max(8, (os.cpu_count() or 4) + 4))
                _SHARED_POOL = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="paimon-decode"
                )
    return _SHARED_POOL


def _host_fingerprint() -> str:
    """Stable id for THIS host's CPU ISA. XLA:CPU cache entries are AOT
    machine code for the exact feature set of the compiling host; loading a
    foreign host's entry degrades or breaks (cpu_aot_loader: "machine type
    doesn't match ... could lead to SIGILL", and mismatched
    +prefer-no-gather scalarizes every gather — the r03 CPU bench ran 19%
    below r02 on exactly this). Scoping the cache dir by fingerprint keeps
    same-host reuse (incl. remote-TPU compiles, which is the point of the
    cache) while making cross-host pollution structurally impossible."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            text = f.read()
        # x86 lists ISA extensions under "flags", aarch64 under "Features";
        # if neither matches (exotic kernel), hash the whole first processor
        # block — never a constant, or two different hosts would share a dir
        sig = "\n".join(
            line for line in text.splitlines() if line.startswith(("flags", "Features"))
        ) or text.split("\n\n")[0]
    except OSError:
        import platform

        sig = platform.processor() or platform.machine()
    return hashlib.sha256(sig.encode()).hexdigest()[:12]


def enable_compile_cache(path: str = "/root/.cache/jax") -> None:
    """Persistent XLA compile cache: remote compiles through the device
    tunnel cost 15-40s each; repeat runs become compile-free. The cache
    lives under a per-host-ISA subdirectory (see _host_fingerprint)."""
    import os

    import jax

    for key, value in (
        ("jax_compilation_cache_dir", os.path.join(path, _host_fingerprint())),
        ("jax_persistent_cache_min_compile_time_secs", 0.5),
    ):
        try:
            jax.config.update(key, value)
        except Exception:
            pass


def new_file_name(prefix: str, ext: str | None = None) -> str:
    n = f"{prefix}-{uuid.uuid4().hex}"
    return f"{n}.{ext}" if ext else n


def partition_path(
    partition_keys: Sequence[str],
    partition: Sequence[Any],
    default_name: str = "__DEFAULT_PARTITION__",
) -> str:
    """Hive-style partition directory: k1=v1/k2=v2 ('' for unpartitioned).
    Null/empty values take partition.default-name (reference
    PartitionPathUtils.generatePartitionPath)."""
    if not partition_keys:
        return ""
    return "/".join(
        f"{k}={default_name if v is None or v == '' else v}"
        for k, v in zip(partition_keys, partition)
    )


def now_millis() -> int:
    return int(time.time() * 1000)


def dumps(obj: Any) -> str:
    return json.dumps(obj, separators=(",", ":"), default=_default)


def loads(s: str | bytes) -> Any:
    return json.loads(s)


def _default(o):
    import numpy as np

    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, (np.bool_,)):
        return bool(o)
    raise TypeError(f"not JSON serializable: {type(o)}")


def probe_devices(timeout_s: float = 120.0) -> tuple[int, str]:
    """(device_count, backend) probed by a DETACHED subprocess with a
    timeout: a wedged accelerator tunnel can hang jax backend init
    indefinitely, and killing the prober mid-init is itself what wedges the
    tunnel — so the child is never killed, its verdict is cached, and on
    timeout callers get (0, "unreachable...") and fall back to CPU.  Full
    discipline layer (single-flight lock, signals, runbook): tpuguard.py."""
    from .tpuguard import probe_devices as _probe

    return _probe(timeout_s=timeout_s)
