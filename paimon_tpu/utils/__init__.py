"""Small shared utilities (naming, paths, json)."""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Sequence

__all__ = ["new_file_name", "partition_path", "now_millis", "dumps", "loads"]


def new_file_name(prefix: str, ext: str | None = None) -> str:
    n = f"{prefix}-{uuid.uuid4().hex}"
    return f"{n}.{ext}" if ext else n


def partition_path(partition_keys: Sequence[str], partition: Sequence[Any]) -> str:
    """Hive-style partition directory: k1=v1/k2=v2 ('' for unpartitioned)."""
    if not partition_keys:
        return ""
    return "/".join(f"{k}={v}" for k, v in zip(partition_keys, partition))


def now_millis() -> int:
    return int(time.time() * 1000)


def dumps(obj: Any) -> str:
    return json.dumps(obj, separators=(",", ":"), default=_default)


def loads(s: str | bytes) -> Any:
    return json.loads(s)


def _default(o):
    import numpy as np

    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, (np.bool_,)):
        return bool(o)
    raise TypeError(f"not JSON serializable: {type(o)}")
