"""Small shared utilities (naming, paths, json)."""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Sequence

__all__ = ["new_file_name", "partition_path", "now_millis", "dumps", "loads", "enable_compile_cache"]


def enable_compile_cache(path: str = "/root/.cache/jax") -> None:
    """Persistent XLA compile cache: remote compiles through the device
    tunnel cost 15-40s each; repeat runs become compile-free."""
    import jax

    for key, value in (
        ("jax_compilation_cache_dir", path),
        ("jax_persistent_cache_min_compile_time_secs", 0.5),
    ):
        try:
            jax.config.update(key, value)
        except Exception:
            pass


def new_file_name(prefix: str, ext: str | None = None) -> str:
    n = f"{prefix}-{uuid.uuid4().hex}"
    return f"{n}.{ext}" if ext else n


def partition_path(partition_keys: Sequence[str], partition: Sequence[Any]) -> str:
    """Hive-style partition directory: k1=v1/k2=v2 ('' for unpartitioned)."""
    if not partition_keys:
        return ""
    return "/".join(f"{k}={v}" for k, v in zip(partition_keys, partition))


def now_millis() -> int:
    return int(time.time() * 1000)


def dumps(obj: Any) -> str:
    return json.dumps(obj, separators=(",", ":"), default=_default)


def loads(s: str | bytes) -> Any:
    return json.loads(s)


def _default(o):
    import numpy as np

    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, (np.bool_,)):
        return bool(o)
    raise TypeError(f"not JSON serializable: {type(o)}")


def probe_devices(timeout_s: int = 120) -> tuple[int, str]:
    """(device_count, backend) probed in a SUBPROCESS with a timeout: a
    wedged accelerator tunnel can hang jax backend init indefinitely (an
    observed killed client left the device grant unreclaimed for hours).
    (0, "unreachable") when the probe fails — callers fall back to CPU."""
    import os
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()), jax.default_backend())"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=repo_root,
        )
        if proc.returncode == 0:
            count, backend = proc.stdout.strip().splitlines()[-1].split()
            return int(count), backend
    except Exception:
        pass
    return 0, "unreachable"
