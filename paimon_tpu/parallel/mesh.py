"""Mesh construction helpers."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh"]


def make_mesh(
    n_devices: int | None = None,
    bucket_parallel: int | None = None,
    axis_names: tuple[str, str] = ("bucket", "key"),
) -> Mesh:
    """A 2D (bucket, key) mesh. bucket_parallel defaults to all devices
    (key axis 1 — pure bucket data-parallelism); set it lower to give each
    bucket a key-range-parallel group."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    bp = bucket_parallel if bucket_parallel is not None else n
    assert n % bp == 0, f"{n} devices not divisible into bucket_parallel={bp}"
    arr = np.array(devices).reshape(bp, n // bp)
    return Mesh(arr, axis_names)
