"""Distributed execution over jax device meshes.

The reference scales by running one task per (partition, bucket) on a Flink/
Spark cluster and shuffling rows by bucket hash over the engine's network
stack (SURVEY §2.9). The TPU-native mapping:

  * mesh axis "bucket"  — data parallelism: buckets are key-disjoint, so
    per-bucket merges run embarrassingly parallel, one shard each
    (shard_map; no collectives on this axis);
  * mesh axis "key"     — the long-context analog: one bucket's key space is
    range-partitioned across devices; a distributed merge/sort first
    redistributes rows to their range owner with an all_to_all over ICI
    (Paimon's RangeShuffle for sort-compact), then merges locally;
  * the commit protocol stays host-side (snapshot CAS on the shared FS) —
    exactly like the reference, where the filesystem is the metadata plane.

Multi-host: the same mesh spans hosts via jax.distributed; the all_to_all
rides ICI within a slice and DCN across slices — no NCCL/MPI analog needed,
XLA owns the collectives.

Within one host, pipeline.py supplies the orthogonal axis: staged overlap of
IO / decode / device merge across splits, compaction sections, and writer
flushes (scan.prefetch-splits / scan.parallelism).
"""

from .distributed import global_mesh, init_multi_host, is_commit_coordinator
from .mesh import make_mesh
from .mesh_exec import MeshExecutor, maybe_mesh_exec, mesh_available, resolve_merge_engine
from .pipeline import SplitPipeline, bounded_map, pipeline_config
from .merge import (
    bucket_parallel_dedup,
    distributed_aggregate_step,
    distributed_changelog_step,
    distributed_merge_step,
    distributed_partial_update_step,
    range_partition_lanes,
    range_partition_rows,
)

__all__ = [
    "make_mesh",
    "MeshExecutor",
    "maybe_mesh_exec",
    "mesh_available",
    "resolve_merge_engine",
    "SplitPipeline",
    "bounded_map",
    "pipeline_config",
    "bucket_parallel_dedup",
    "distributed_merge_step",
    "distributed_partial_update_step",
    "distributed_aggregate_step",
    "distributed_changelog_step",
    "range_partition_lanes",
    "range_partition_rows",
    "init_multi_host",
    "is_commit_coordinator",
    "global_mesh",
]
