"""Distributed merge kernels: bucket data-parallelism + key-range parallelism.

Two levels, mirroring how the reference distributes work (SURVEY §2.9) but
expressed as XLA collectives instead of engine shuffle:

  bucket_parallel_dedup — buckets are key-disjoint, so B buckets' merges run
  as one shard_map over the "bucket" mesh axis with zero communication (the
  TPU analog of one Flink task per bucket).

  distributed_merge_step — one (huge) bucket's rows range-partitioned over
  the "key" mesh axis: sample splitters (all_gather), route rows to their
  range owner (all_to_all over ICI — Paimon's RangeShuffle analog,
  flink/shuffle/RangeShuffle.java), then sort-merge locally. Equal keys
  always land on one device (routing is by the most-significant key lane),
  so segments never straddle devices and the merge semantics stay exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 promotes shard_map
    from jax import shard_map as _shard_map_mod

    shard_map = _shard_map_mod.shard_map if hasattr(_shard_map_mod, "shard_map") else _shard_map_mod
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from ..ops.merge import _plan_fn

__all__ = [
    "bucket_parallel_dedup",
    "bucket_parallel_dedup_fn",
    "bucket_parallel_plan_fn",
    "range_partition_lanes",
    "range_partition_rows",
    "distributed_merge_step",
    "distributed_partial_update_step",
    "distributed_aggregate_step",
    "distributed_changelog_step",
]


def _local_plan(num_key: int, num_seq: int, key_lanes, seq_lanes, pad_flag):
    """(K,m),(S,m),(m,) -> perm, seg_start, keep_last, seg_id (single shard)."""
    return _plan_fn(num_key, num_seq)(key_lanes, seq_lanes, pad_flag)


# ---------------------------------------------------------------------------
# bucket axis: embarrassingly parallel per-bucket merges
# ---------------------------------------------------------------------------

def bucket_parallel_dedup(mesh: Mesh, key_lanes: np.ndarray, seq_lanes: np.ndarray, pad: np.ndarray):
    """key_lanes (B, m, K), seq_lanes (B, m, S), pad (B, m) uint32.
    Returns (perm, keep_last) each (B, m): per-bucket dedup selection, buckets
    sharded over the "bucket" axis. B must be divisible by the axis size."""
    b, m, k = key_lanes.shape
    s = seq_lanes.shape[2]

    def per_bucket(kl, sl, pf):
        # kl (m, K) -> (K, m)
        perm, _, keep_last, _ = _local_plan(k, s, kl.T, sl.T, pf)
        return perm, keep_last

    def shard_fn(kl, sl, pf):
        return jax.vmap(per_bucket)(kl, sl, pf)

    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P("bucket", None, None), P("bucket", None, None), P("bucket", None)),
        out_specs=(P("bucket", None), P("bucket", None)),
    )
    return jax.jit(fn)(key_lanes, seq_lanes, pad)


@functools.lru_cache(maxsize=None)
def bucket_parallel_dedup_fn(mesh: Mesh, k: int, s: int):
    """Cached jit+shard_map of the DEDUP family over the mesh's bucket axis:
    (B, m, K) key lanes, (B, m, S) seq lanes, (B, m) pad -> per-bucket packed
    selected input indices + counts (the minimal download — pack_selected on
    device). The kernel body is ops.merge.sorted_segments/pack_selected, so
    mesh and single-device selection share one copy of the semantics. The
    cache key includes the Mesh (hashable, one per process via the executor's
    mesh factory), so each (mesh, lane arity) compiles exactly once."""
    from ..ops.merge import pack_selected, sorted_segments

    def per_bucket(kl, sl, pf):  # (m, K), (m, S), (m,)
        pad_sorted, perm, _, keep_last, _ = sorted_segments(k, s, kl.T, sl.T, pf)
        return pack_selected(keep_last & (pad_sorted == 0), perm)

    fn = shard_map(
        lambda kl, sl, pf: jax.vmap(per_bucket)(kl, sl, pf),
        mesh=mesh,
        in_specs=(P("bucket", None, None), P("bucket", None, None), P("bucket", None)),
        out_specs=(P("bucket", None), P("bucket")),
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def bucket_parallel_plan_fn(mesh: Mesh, k: int, s: int):
    """Cached jit+shard_map of the PLAN families (partial-update, aggregate,
    changelog rewrite — engines whose segment reductions finish host-side
    with arbitrary per-field aggregators) over the bucket axis: the full
    merge plan arrays (perm, seg_start, keep_last, seg_id) per bucket."""
    from ..ops.merge import sorted_segments

    def per_bucket(kl, sl, pf):
        _, perm, seg_start, keep_last, seg_id = sorted_segments(k, s, kl.T, sl.T, pf)
        return perm, seg_start, keep_last, seg_id

    fn = shard_map(
        lambda kl, sl, pf: jax.vmap(per_bucket)(kl, sl, pf),
        mesh=mesh,
        in_specs=(P("bucket", None, None), P("bucket", None, None), P("bucket", None)),
        out_specs=(
            P("bucket", None),
            P("bucket", None),
            P("bucket", None),
            P("bucket", None),
        ),
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# key axis: range shuffle + local merge
# ---------------------------------------------------------------------------

def _range_exchange(
    key_lanes, seq_lanes, pad_flag, axis: str, p: int, num_key: int, num_seq: int,
    sample: int = 64, extra_lanes=None,
):
    """Runs INSIDE shard_map on the `axis` group. Inputs are this device's
    shard: key_lanes (K, m), seq_lanes (S, m), pad_flag (m,). Returns the
    re-partitioned shard (K, P*m), (S, P*m), (P*m,) where this device now
    owns a contiguous key range."""
    m = pad_flag.shape[0]
    lane0 = key_lanes[0]
    # --- splitters: evenly-spaced sample of each device's sorted lane0 ------
    big = jnp.uint32(0xFFFFFFFF)
    masked = jnp.where(pad_flag == 0, lane0, big)
    local_sorted = jnp.sort(masked)
    idx = jnp.linspace(0, m - 1, sample).astype(jnp.int32)
    local_sample = local_sorted[idx]
    all_samples = jax.lax.all_gather(local_sample, axis)  # (P, sample)
    flat = jnp.sort(all_samples.reshape(-1))
    cut = jnp.linspace(0, p * sample - 1, p + 1).astype(jnp.int32)[1:-1]
    splitters = flat[cut]  # (P-1,)
    # --- destination of each row -------------------------------------------
    dest = jnp.searchsorted(splitters, masked, side="right").astype(jnp.int32)
    dest = jnp.where(pad_flag == 0, dest, p - 1)  # pads route anywhere (stay padded)
    # --- group rows by destination into (P, m) send buffers -----------------
    iota = jnp.arange(m, dtype=jnp.int32)
    _, order = jax.lax.sort([dest, iota], num_keys=1, is_stable=True)
    dest_sorted = dest[order]
    ones = jnp.ones_like(dest_sorted)
    counts = jax.ops.segment_sum(ones, dest_sorted, num_segments=p)  # rows per dest
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    rank = iota - offsets[dest_sorted]  # position within its dest block
    # scatter into padded (P, m) buffers; unfilled slots stay pad
    def build(buf_dtype, values_sorted, fill):
        buf = jnp.full((p, m), fill, dtype=buf_dtype)
        return buf.at[dest_sorted, rank].set(values_sorted)

    send_pad = build(jnp.uint32, pad_flag[order], jnp.uint32(1))
    send_keys = [build(jnp.uint32, key_lanes[i][order], big) for i in range(num_key)]
    send_seqs = [build(jnp.uint32, seq_lanes[i][order], jnp.uint32(0)) for i in range(num_seq)]
    num_extra = 0 if extra_lanes is None else extra_lanes.shape[0]
    send_extra = [build(jnp.uint32, extra_lanes[i][order], jnp.uint32(0)) for i in range(num_extra)]
    # --- the collective ------------------------------------------------------
    def a2a(x):  # (P, m) -> (P, m): row i goes to device i
        return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)

    recv_pad = a2a(send_pad).reshape(-1)
    recv_keys = jnp.stack([a2a(x).reshape(-1) for x in send_keys], axis=0)
    recv_seqs = (
        jnp.stack([a2a(x).reshape(-1) for x in send_seqs], axis=0)
        if num_seq
        else jnp.zeros((0, p * m), jnp.uint32)
    )
    if extra_lanes is None:
        return recv_keys, recv_seqs, recv_pad
    recv_extra = (
        jnp.stack([a2a(x).reshape(-1) for x in send_extra], axis=0)
        if num_extra
        else jnp.zeros((0, p * m), jnp.uint32)
    )
    return recv_keys, recv_seqs, recv_pad, recv_extra


def range_partition_lanes(
    mesh: Mesh,
    key_lanes: np.ndarray,
    seq_lanes: np.ndarray,
    pad: np.ndarray,
    sample_per_device: int = 64,
):
    """Standalone range shuffle over the "key" axis (the distributed sort /
    clustering primitive). Inputs (n, K)/(n, S)/(n,) sharded on rows; output:
    per-device contiguous key ranges, each locally merged (perm + keep_last
    in the exchanged coordinate system). sample_per_device tunes splitter
    fidelity (reference sort-compaction.local-sample.magnification:
    sample = magnification x parallelism)."""
    n, k = key_lanes.shape
    s = seq_lanes.shape[1]
    p_key = mesh.shape["key"]

    def shard_fn(kl, sl, pf):
        rk, rs, rp = _range_exchange(
            kl.T, sl.T, pf, "key", p_key, k, s, sample=sample_per_device
        )
        perm, _, keep_last, _ = _local_plan(k, s, rk, rs, rp)
        # emit everything in SORTED order so row i of lanes aligns with
        # keep_last[i] / pad[i] (one coordinate system for downstream)
        return rk[:, perm].T, perm, keep_last, rp[perm]

    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P("key", None), P("key", None), P("key")),
        out_specs=(P("key", None), P("key"), P("key"), P("key")),
    )
    return jax.jit(fn)(key_lanes, seq_lanes, pad)


@functools.lru_cache(maxsize=None)
def _range_partition_rows_fn(mesh: Mesh, k: int, sample: int):
    """Cached kernel behind range_partition_rows: one row-id lane rides the
    all_to_all as the sole sequence lane, so after the exchange + local sort
    each device can name the GLOBAL input row at every sorted position."""
    p = mesh.shape["key"]

    def shard_fn(kl, rid, pf):
        rk, rs, rp = _range_exchange(kl.T, rid[None, :], pf, "key", p, k, 1, sample=sample)
        perm, _, _, _ = _local_plan(k, 1, rk, rs, rp)
        return rs[0][perm], rp[perm]

    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P("key", None), P("key"), P("key")),
        out_specs=(P("key"), P("key")),
    )
    return jax.jit(fn)


def range_partition_rows(
    mesh: Mesh,
    key_lanes: np.ndarray,
    row_ids: np.ndarray,
    pad: np.ndarray,
    sample_per_device: int = 64,
):
    """Globally-stable distributed sort of row ids by key: rows sharded over
    the "key" axis are range-shuffled to their owner (all_gather splitter
    sample + all_to_all — the RangeShuffle.java analog), locally sorted with
    the row id as the tie-break lane, and returned as (row_ids_sorted,
    pad_sorted) concatenated in ascending device-range order. Because routing
    is a pure function of the leading lane, device ranges are disjoint; and
    because the row id orders ties, the concatenation equals the SINGLE-device
    stable sort permutation bit-for-bit — the property sort-compact and
    dynamic-bucket rescale rely on (paimon_tpu.parallel.mesh_exec)."""
    n, k = key_lanes.shape
    out_rows, out_pad = _range_partition_rows_fn(mesh, k, sample_per_device)(
        key_lanes, row_ids, pad
    )
    return np.asarray(out_rows), np.asarray(out_pad)


# ---------------------------------------------------------------------------
# the full step: both axes composed (the dryrun_multichip target)
# ---------------------------------------------------------------------------

def distributed_merge_step(mesh: Mesh, key_lanes: np.ndarray, seq_lanes: np.ndarray, pad: np.ndarray):
    """One full distributed write/compact step on a (bucket, key) mesh:
    buckets sharded over "bucket" (pure data parallel), each bucket's rows
    sharded over "key" (range exchange + local merge). Shapes:
    key_lanes (B, n, K), seq_lanes (B, n, S), pad (B, n); B divisible by the
    bucket axis, n by the key axis. Returns (out_key_lanes, out_seq_lanes,
    perm, merged_valid) all in the post-exchange sorted coordinate system, so
    callers can check not just WHICH keys survived but which sequence number
    (i.e. which original row) won each key's merge."""
    b, n, k = key_lanes.shape
    s = seq_lanes.shape[2]
    p_key = mesh.shape["key"]

    def shard_fn(kl, sl, pf):
        # local shapes: kl (B_loc, n_loc, K), sl (B_loc, n_loc, S), pf (B_loc, n_loc)
        def one_bucket(kb, sb, pb):
            rk, rs, rp = _range_exchange(kb.T, sb.T, pb, "key", p_key, k, s)
            perm, _, keep_last, _ = _local_plan(k, s, rk, rs, rp)
            merged_valid = keep_last & (rp[perm] == 0)
            # sorted order: lanes[i] corresponds to merged_valid[i]
            return rk[:, perm].T, rs[:, perm].T, perm, merged_valid

        return jax.vmap(one_bucket)(kl, sl, pf)

    # each key-shard returns its received range block (rows grow to
    # p_key * n_loc locally => global row dim is p_key * n)
    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P("bucket", "key", None), P("bucket", "key", None), P("bucket", "key")),
        out_specs=(
            P("bucket", "key", None),
            P("bucket", "key", None),
            P("bucket", "key"),
            P("bucket", "key"),
        ),
    )
    return jax.jit(fn)(key_lanes, seq_lanes, pad)


def distributed_partial_update_step(
    mesh: Mesh,
    key_lanes: np.ndarray,  # (B, n, K) uint32
    seq_lanes: np.ndarray,  # (B, n, S) uint32
    pad: np.ndarray,  # (B, n) uint32
    field_valid: np.ndarray,  # (B, n, F) bool — per-field non-null mask
):
    """The partial-update merge engine ACROSS the range shuffle: per-field
    payload masks ride the all_to_all with the lanes; after the exchange each
    device owns a complete key range, so the per-key per-field "latest
    non-null wins" segment reduction (reference
    PartialUpdateMergeFunction.java:57) is locally exact.

    Returns (out_keys (B, N, K), out_seqs (B, N, S), merged_valid (B, N),
    field_src (B, F, N)) in the post-exchange SORTED coordinate system:
    field_src[b, f, i] is the sorted-row index holding field f's winning
    value for the key ending at sorted row i (-1 => field null), meaningful
    where merged_valid is True. out_seqs lets callers verify WHICH row won
    (latest-non-null contract), not just which key.
    """
    _, _, k = key_lanes.shape
    s = seq_lanes.shape[2]
    p_key = mesh.shape["key"]

    def shard_fn(kl, sl, pf, fv):
        def one_bucket(kb, sb, pb, fb):
            rk, rs, rp, rx = _range_exchange(
                kb.T, sb.T, pb, "key", p_key, k, s, extra_lanes=fb.T.astype(jnp.uint32)
            )
            perm, _, keep_last, seg_id = _local_plan(k, s, rk, rs, rp)
            m = rp.shape[0]
            from ..ops.merge import segment_last_where

            fv_sorted = rx[:, perm] != 0  # (F, m) in sorted coords
            last_per_field = segment_last_where(seg_id, fv_sorted)  # (F, m) by segment
            src = last_per_field[:, seg_id]  # broadcast back to rows
            merged_valid = keep_last & (rp[perm] == 0)
            # src is shard-local sorted position; offset to GLOBAL sorted
            # coords (each key-shard's block lands at axis_index * m)
            offset = jax.lax.axis_index("key").astype(jnp.int32) * m
            return (
                rk[:, perm].T,
                rs[:, perm].T,
                merged_valid,
                jnp.where(src >= 0, src + offset, -1),
            )

        return jax.vmap(one_bucket)(kl, sl, pf, fv)

    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P("bucket", "key", None),
            P("bucket", "key", None),
            P("bucket", "key"),
            P("bucket", "key", None),
        ),
        out_specs=(
            P("bucket", "key", None),
            P("bucket", "key", None),
            P("bucket", "key"),
            P("bucket", None, "key"),
        ),
    )
    return jax.jit(fn)(key_lanes, seq_lanes, pad, field_valid)

def _keyed_payload_step(mesh: Mesh, key_lanes, seq_lanes, pad, extra, payload_fn):
    """Shared scaffold for merge engines whose mesh form is: one uint32
    payload lane rides the all_to_all, then a per-segment reduction after the
    local plan. payload_fn(rx0, perm, seg_id, live, m) -> (m,) payload.
    Returns (out_keys (B, N, K), merged_valid (B, N), payload (B, N))."""
    _, _, k = key_lanes.shape
    s = seq_lanes.shape[2]
    p_key = mesh.shape["key"]

    def shard_fn(kl, sl, pf, xv):
        def one_bucket(kb, sb, pb, xb):
            rk, rs, rp, rx = _range_exchange(
                kb.T, sb.T, pb, "key", p_key, k, s, extra_lanes=xb[None, :]
            )
            perm, _, keep_last, seg_id = _local_plan(k, s, rk, rs, rp)
            live = rp[perm] == 0
            payload = payload_fn(rx[0], perm, seg_id, live, rp.shape[0])
            return rk[:, perm].T, keep_last & live, payload

        return jax.vmap(one_bucket)(kl, sl, pf, xv)

    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P("bucket", "key", None),
            P("bucket", "key", None),
            P("bucket", "key"),
            P("bucket", "key"),
        ),
        out_specs=(P("bucket", "key", None), P("bucket", "key"), P("bucket", "key")),
    )
    return jax.jit(fn)(key_lanes, seq_lanes, pad, extra)


def distributed_aggregate_step(
    mesh: Mesh,
    key_lanes: np.ndarray,  # (B, n, K) uint32
    seq_lanes: np.ndarray,  # (B, n, S) uint32
    pad: np.ndarray,  # (B, n) uint32
    values: np.ndarray,  # (B, n) float32 — the aggregated payload column
):
    """The AGGREGATION merge engine across the range shuffle (reference
    mergetree/compact/aggregate/FieldSumAgg.java under
    AggregateMergeFunction): payload values ride the all_to_all bitcast to
    uint32 lanes; after the exchange each device owns a complete key range,
    so the per-key segment SUM is locally exact. Insert-only rows (retract
    handling lives in the host aggregators, ops/aggregates.py).

    Returns (out_keys (B, N, K), merged_valid (B, N), sums (B, N)) in the
    post-exchange sorted coordinate system: sums[b, i] is key i's total where
    merged_valid[b, i] is True."""

    def seg_sum(rx0, perm, seg_id, live, m):
        vals = jax.lax.bitcast_convert_type(rx0, jnp.float32)[perm]
        vals = jnp.where(live, vals, 0.0)
        return jax.ops.segment_sum(vals, seg_id, num_segments=m)[seg_id]

    extra = jax.lax.bitcast_convert_type(jnp.asarray(values), jnp.uint32)
    return _keyed_payload_step(mesh, key_lanes, seq_lanes, pad, extra, seg_sum)


# changelog row codes emitted by distributed_changelog_step
CHANGELOG_NONE = 0     # key unchanged by this batch (or batch rows all lost)
CHANGELOG_INSERT = 1   # key is new: emit +I
CHANGELOG_UPDATE = 2   # key existed and the batch won: emit -U (old) / +U (new)


def distributed_changelog_step(
    mesh: Mesh,
    key_lanes: np.ndarray,  # (B, n, K) uint32 — OLD state rows + NEW batch rows
    seq_lanes: np.ndarray,  # (B, n, S) uint32 — new rows carry higher seqs
    pad: np.ndarray,  # (B, n) uint32
    is_new: np.ndarray,  # (B, n) uint32 — 1 = row belongs to the incoming batch
):
    """The changelog-producing rewrite ACROSS the mesh shuffle (reference
    mergetree/compact/ChangelogMergeTreeRewriter.java:47 /
    FullChangelogMergeFunctionWrapper): merge OLD top-level state with the
    NEW batch in one distributed pass and derive, per key, which changelog
    rows a full-compaction producer must emit — +I for a previously-unseen
    key, -U/+U when an existing key's winner comes from the batch, nothing
    when the batch lost or didn't touch the key. The is_new source flag rides
    the all_to_all with the lanes, so the derivation is exact after the
    exchange.

    Returns (out_keys (B, N, K), merged_valid (B, N), code (B, N)) sorted;
    code uses CHANGELOG_{NONE,INSERT,UPDATE}, meaningful where merged_valid
    (the code at a key's keep_last row decides from src_new there whether the
    winner came from the batch)."""

    def derive_code(rx0, perm, seg_id, live, m):
        src_new = (rx0[perm] != 0) & live
        src_old = (rx0[perm] == 0) & live
        any_new = jax.ops.segment_max(src_new.astype(jnp.int32), seg_id, num_segments=m)
        any_old = jax.ops.segment_max(src_old.astype(jnp.int32), seg_id, num_segments=m)
        return jnp.where(
            any_new[seg_id] == 0,
            CHANGELOG_NONE,
            jnp.where(
                any_old[seg_id] == 0,
                CHANGELOG_INSERT,
                jnp.where(src_new, CHANGELOG_UPDATE, CHANGELOG_NONE),
            ),
        )

    return _keyed_payload_step(
        mesh, key_lanes, seq_lanes, pad, jnp.asarray(is_new), derive_code
    )
