"""Multi-host initialization.

The reference scales out with Flink/Spark clusters over NCCL-free engine
shuffle (SURVEY §2.9); the TPU-native equivalent is one jax.distributed
process group per host, a global mesh spanning every host's devices, and XLA
placing collectives on ICI within a slice / DCN across slices. The commit
protocol needs no changes: it is a filesystem CAS, and only the coordinator
(process_index 0) runs commits — exactly the reference's single-parallelism
committer operator.
"""

from __future__ import annotations

import jax

from .mesh import make_mesh

__all__ = ["init_multi_host", "is_commit_coordinator", "global_mesh"]


def init_multi_host(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize the jax distributed runtime (env-driven on TPU pods: with
    no args, jax discovers the topology from the TPU metadata)."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_commit_coordinator() -> bool:
    """Only one process commits (the reference's single-parallelism
    CommitterOperator); everyone else ships CommitMessages to it."""
    return jax.process_index() == 0


def global_mesh(bucket_parallel: int | None = None):
    """A (bucket, key) mesh over every device of every host."""
    return make_mesh(n_devices=None, bucket_parallel=bucket_parallel)
