"""Multi-host initialization.

The reference scales out with Flink/Spark clusters over NCCL-free engine
shuffle (SURVEY §2.9); the TPU-native equivalent is one jax.distributed
process group per host, a global mesh spanning every host's devices, and XLA
placing collectives on ICI within a slice / DCN across slices. The commit
protocol needs no changes: it is a filesystem CAS, and only the coordinator
(process_index 0) runs commits — exactly the reference's single-parallelism
committer operator.

`init_worker_runtime` is the cluster-service entry (service/cluster.py):
a worker process either joins a real jax.distributed group (multi-host mode:
coordinator address + process id provided) or falls back to its own
single-process device set (forced-host virtual devices on CPU, the local
chips on TPU) — the same mesh/executor code runs either way. The cluster
role rides in PAIMON_TPU_CLUSTER_ROLE so `is_commit_coordinator` stays
truthful even when jax.distributed was never initialized: a cluster worker
must NEVER commit, no matter what process_index says in its private
single-process runtime.
"""

from __future__ import annotations

import os

import jax

from .mesh import make_mesh

__all__ = [
    "init_multi_host",
    "init_worker_runtime",
    "is_commit_coordinator",
    "global_mesh",
    "ROLE_ENV",
]

# "coordinator" | "worker" — set by service/cluster.py in its children; when
# absent the jax process index decides (single-process runs are coordinator)
ROLE_ENV = "PAIMON_TPU_CLUSTER_ROLE"


def init_multi_host(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize the jax distributed runtime (env-driven on TPU pods: with
    no args, jax discovers the topology from the TPU metadata)."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def init_worker_runtime(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
):
    """Cluster-worker device runtime: join the jax.distributed group when a
    multi-host topology is configured, else the single-process fallback (the
    worker's own devices — virtual forced-host devices on CPU). Returns the
    (bucket, key) mesh the worker's mesh executor should span.

    The fallback is the production path for the OS-process cluster on one
    host (service/cluster.py): each worker owns a private XLA runtime sized
    by --xla_force_host_platform_device_count, and cross-worker exchange
    rides the table protocol (CommitMessages to the coordinator), not
    collectives — exactly the reference's task-manager topology."""
    if num_processes is not None and num_processes > 1:
        init_multi_host(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return global_mesh()


def is_commit_coordinator() -> bool:
    """Only one process commits (the reference's single-parallelism
    CommitterOperator); everyone else ships CommitMessages to it. The
    cluster role env wins over process_index: a cluster worker running its
    own single-process jax runtime reports process_index 0, but it still
    must ship, not commit."""
    role = os.environ.get(ROLE_ENV)
    if role:
        return role == "coordinator"
    return jax.process_index() == 0


def global_mesh(bucket_parallel: int | None = None):
    """A (bucket, key) mesh over every device of every host."""
    return make_mesh(n_devices=None, bucket_parallel=bucket_parallel)
