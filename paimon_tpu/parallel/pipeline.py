r"""Pipelined split scheduler: overlap IO, decode, and device merge across
buckets.

The reference's only cross-file parallelism is running many Flink/Spark tasks
at once (one split per task, MergeTreeSplitGenerator.java:38); inside one
process our hot paths used to drive splits, compaction sections, and flush
encodes strictly serially, so the device merge kernel idled while parquet
bytes were fetched and decoded — and vice versa. This module supplies the
staged execution the decode subsystem and caches were missing: a
bounded-readahead, ordered, multi-stage scheduler in the MonetDB/X100
pipelined-vectorized tradition, the cross-file analog of the double-buffered
tile transfer already used inside ops/merge (deduplicate_tiled_dispatch).

Stage map (who overlaps with whom):

    fetch bytes -> decode to KVBatch -> device merge -> emit
    \_________________  _____________/   \____  ____/     \_ consumer thread,
                      \/                      \/              strict input order
         pipeline worker threads        dispatched by the
         (split i+1, i+2, ...)          worker, so split i's
                                        kernel runs while
                                        split i+1 decodes

Three consumers ride the same primitive:

  * table/read.py — a multi-bucket scan prefetches and decodes split i+1
    (file bytes through RetryingFileIO, so PR 3's transient-retry
    classification applies inside the worker) while split i merges on
    device; batches emit in deterministic split order regardless of
    completion order.
  * core/compact.py — a rewrite's sections overlap file reads, merge
    dispatch, and output encode instead of reading every input before the
    first merge.
  * core/writer.py — the parquet/native encode of a rolled file runs on a
    flush worker while the next memtable fills; prepare_commit is the
    barrier.

Configuration: `scan.prefetch-splits` (readahead depth, default 2; 0 disables
pipelining everywhere and restores the strictly sequential path) and
`scan.parallelism` (stage worker threads; also bounds the per-file decode
fan-out of bounded_map).

Determinism contract: map_ordered emits results in submission order, and each
item's work function is self-contained, so pipelined output is BIT-IDENTICAL
to the sequential path (the randomized oracle pins this). Exceptions from any
worker propagate to the consumer at that item's position; the pool always
shuts down (no leaked threads) whether the generator is exhausted, closed
early, or unwound by an error.

Pool discipline: pipeline stages run on their OWN short-lived executor, never
on the process-wide shared decode pool — stage work itself fans out per-file
decodes to that shared pool (utils.shared_executor), and submitting to a pool
from one of its own workers deadlocks once the queue fills. bounded_map is
the leaf-level helper that does use the shared pool, with a sliding window so
`scan.parallelism` bounds in-flight decodes without a pool per call.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Iterable, Iterator, Sequence, TypeVar

__all__ = ["SplitPipeline", "bounded_map", "pipeline_config"]

T = TypeVar("T")
R = TypeVar("R")

# thread-name prefixes (the conftest leak assertion keys off these: pipeline
# pools are per-run and must be gone after every test; the shared decode pool
# is process-wide by design and exempt)
PIPELINE_THREAD_PREFIX = "paimon-pipeline"
FLUSH_THREAD_PREFIX = "paimon-flush"


def pipeline_config(options) -> tuple[int, int | None]:
    """(depth, parallelism) from a CoreOptions — the one seam every consumer
    reads, so `scan.prefetch-splits = 0` disables pipelining everywhere."""
    from ..options import CoreOptions

    depth = options.options.get(CoreOptions.SCAN_PREFETCH_SPLITS)
    par = options.options.get(CoreOptions.SCAN_PARALLELISM)
    return (max(int(depth or 0), 0), None if par is None else max(int(par), 1))


def _warm_decode_state() -> None:
    """pyarrow's lazily-initialized process globals segfault when first-ever
    init races across two threads (see core.read._ensure_arrow_decode_
    initialized) — warm them on the submitting thread before any worker
    decodes."""
    from ..core.read import _ensure_arrow_decode_initialized

    _ensure_arrow_decode_initialized()


class SplitPipeline:
    """Bounded-readahead ordered executor over per-item work functions.

    depth D keeps at most D+1 items in flight (the one the consumer waits on
    plus D prefetched), bounding the memory high-water at D+1 decoded splits.
    parallelism caps concurrent workers (default min(depth+1, 4) — readahead
    deeper than the worker count just queues).
    """

    def __init__(
        self,
        parallelism: int | None = None,
        depth: int = 2,
        stage: str = "scan",
    ):
        self.depth = max(int(depth), 0)
        self.parallelism = parallelism
        self.stage = stage

    def _workers(self) -> int:
        if self.parallelism is not None and self.parallelism > 0:
            return self.parallelism
        return max(1, min(self.depth + 1, 4))

    def map_ordered(self, items: Iterable[T], fn: Callable[[T], R]) -> Iterator[R]:
        """Yield fn(item) for every item, in input order, computing up to
        `depth` items ahead of the consumer. Exceptions raised by fn surface
        at that item's position; on error or early close every in-flight
        task is cancelled/awaited and the pool is torn down."""
        items = list(items)
        if self.depth == 0 or len(items) <= 1:
            for x in items:
                yield fn(x)
            return
        from concurrent.futures import ThreadPoolExecutor

        from ..metrics import pipeline_metrics

        _warm_decode_state()
        g = pipeline_metrics()
        prefetched = g.counter("splits_prefetched")
        busy = g.histogram(f"{self.stage}_busy_ms")
        wait = g.histogram(f"{self.stage}_wait_ms")
        high_water = g.gauge("queue_depth_high_water")

        def timed_fn(x: T) -> R:
            t0 = time.perf_counter()
            try:
                return fn(x)
            finally:
                busy.update((time.perf_counter() - t0) * 1000)

        window = self.depth + 1
        ex = ThreadPoolExecutor(
            max_workers=min(self._workers(), window),
            thread_name_prefix=f"{PIPELINE_THREAD_PREFIX}-{self.stage}",
        )
        inflight: deque = deque()
        try:
            it = iter(items)
            for x in it:
                inflight.append(ex.submit(timed_fn, x))
                if len(inflight) > 1:
                    prefetched.inc()
                if len(inflight) > high_water.value:
                    high_water.set(len(inflight))
                if len(inflight) >= window:
                    break
            while inflight:
                t0 = time.perf_counter()
                result = inflight.popleft().result()  # re-raises worker errors
                wait.update((time.perf_counter() - t0) * 1000)
                for x in it:  # top the window back up before yielding
                    inflight.append(ex.submit(timed_fn, x))
                    prefetched.inc()
                    if len(inflight) > high_water.value:
                        high_water.set(len(inflight))
                    break
                yield result
        finally:
            for f in inflight:
                f.cancel()
            # wait=True: a worker mid-decode finishes (its result is dropped),
            # so no thread outlives the generator — the conftest leak
            # assertion pins this
            ex.shutdown(wait=True, cancel_futures=True)


def bounded_map(
    fn: Callable[[T], R], items: Sequence[T], parallelism: int | None = None
) -> list[R]:
    """Ordered map over the process-wide shared decode pool with at most
    `parallelism` items in flight (None = pool width, 1 = strictly serial).

    This is the leaf-level decode fan-out (per-file reads, manifest decodes):
    tasks submitted here must never themselves submit to the shared pool.
    A sliding window instead of executor.map lets `scan.parallelism` bound
    concurrency without constructing a pool per call."""
    items = list(items)
    if len(items) <= 1 or (parallelism is not None and parallelism <= 1):
        return [fn(x) for x in items]
    _warm_decode_state()
    from ..utils import shared_executor

    ex = shared_executor()
    if parallelism is None or parallelism >= len(items):
        return list(ex.map(fn, items))
    results: list[R] = []
    window: deque = deque()
    try:
        for x in items:
            window.append(ex.submit(fn, x))
            if len(window) >= parallelism:
                results.append(window.popleft().result())
        while window:
            results.append(window.popleft().result())
    finally:
        for f in window:
            f.cancel()
    return results
