"""Mesh-sharded execution layer: real scans and compactions bucket-parallel
across the device mesh (ISSUE 7 tentpole).

`MeshExecutor` is the bridge the shard_map primitives in parallel/merge.py
were missing: table operations (merge read, compaction rewrite, writer flush)
dispatch their per-bucket merge jobs into it, and it executes everything
pending in ONE shard_map call per merge-function family over the mesh's
"bucket" axis — the TPU-native mapping of the reference running one
Flink/Spark task per bucket (SURVEY §2.9, MergeTreeSplitGenerator.java:38).
Oversized buckets leave the bucket axis and range-shuffle over the "key"
axis instead (distributed_dedup_select: all_gather splitter sample +
all_to_all — the RangeShuffle.java analog), and sort-compact / dynamic-bucket
rescale use the same collective through `mesh_cluster_permutation` /
`range_partition_rows`.

Three properties distinguish it from the older `MeshBatchContext`
(parallel.mesh.enabled), which it supersedes when enabled:

  GLOBAL LANE PLANNING — every job in a family batch shares ONE `LanePlan`
  computed from lane stats reduced across all shards
  (ops.lanes.plan_lanes_global). Per-shard plans can disagree on packed
  widths (a lane spanning 8 bits on shard A and 20 on shard B fuses
  differently), and packed operands from different plans are not comparable —
  fatal the moment values cross devices (range-shuffle splitters, stacked
  shard_map lanes). The parity suite pins a case where per-shard planning
  provably corrupts the distributed selection.

  HOST-SIDE FEEDER — the PR 4 SplitPipeline feeds the executor with one
  prefetch lane per device (table/read._mesh_batches, compact
  rewrite_dispatch), so IO + decode of shard i+1 overlap the batched device
  merge of shard i.

  CPU FALLBACK — gated behind `merge.engine = mesh` (default `single`); a
  1-device or shard_map-less environment silently degrades to the existing
  single-device path, bit-identically (the SNIPPETS pjit_with_cpu_fallback
  pattern applied at the executor seam rather than per-kernel).

Observability: the mesh{buckets_sharded, shards, pad_rows, exchange_rows,
device_busy_ms, feeder_wait_ms} metric group, surfaced as a breakdown line
in bench.py.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

import numpy as np

__all__ = [
    "MeshExecutor",
    "mesh_available",
    "resolve_merge_engine",
    "maybe_mesh_exec",
    "mesh_cluster_permutation",
    "mesh_feeder_lanes",
]


def _metrics():
    from ..metrics import mesh_metrics

    return mesh_metrics()


def mesh_available() -> bool:
    """True when the process can actually shard: >= 2 visible devices and an
    importable shard_map. Everything else falls back to the single-device
    path — callers never see a partially-working mesh."""
    try:
        from .merge import shard_map  # noqa: F401  (import proves availability)
    except Exception:  # pragma: no cover - jax without shard_map
        return False
    try:
        import jax

        return len(jax.devices()) >= 2
    except Exception:  # pragma: no cover - no backend at all
        return False


def resolve_merge_engine(options) -> str:
    """One resolution order everywhere: the PAIMON_TPU_MERGE_ENGINE env var
    (verify stages force both paths) beats the table's `merge.engine` option,
    which beats the default (`single`). Returns "mesh" or "single"; "mesh"
    still degrades to single at the call sites when mesh_available() is
    False — that IS the cpu-fallback contract."""
    env = os.environ.get("PAIMON_TPU_MERGE_ENGINE", "").strip().lower()
    if env in ("mesh", "single"):
        return env
    from ..options import CoreOptions

    v = (options.options.get(CoreOptions.MERGE_EXEC_ENGINE) or "single").lower()
    return "mesh" if v == "mesh" else "single"


def maybe_mesh_exec(options):
    """Context manager: install a MeshExecutor as the active mesh context iff
    `merge.engine = mesh` resolves, the mesh is usable, and no context is
    already active (nesting would double-batch); yields None otherwise so
    callers keep their single-device path unchanged."""
    from contextlib import contextmanager

    from .executor import _ACTIVE, current_mesh_context

    @contextmanager
    def _cm():
        if (
            resolve_merge_engine(options) != "mesh"
            or current_mesh_context() is not None
            or not mesh_available()
        ):
            yield None
            return
        from ..options import CoreOptions

        ctx = MeshExecutor(
            key_axis_rows=options.options.get(CoreOptions.PARALLEL_KEY_AXIS_ROWS)
        )
        token = _ACTIVE.set(ctx)
        try:
            yield ctx
        finally:
            _ACTIVE.reset(token)

    return _cm()


# one batched call is chunked so padded lanes stay under this many uint32s
_DEVICE_BUDGET_WORDS = 64 * 1024 * 1024


@dataclass
class _Job:
    kind: str  # "dedup" | "plan"
    lanes: np.ndarray  # (n, K) uint32 — RAW key lanes (planning is global)
    seq_lanes: np.ndarray | None  # (n, S) uint32
    compress: bool  # merge.lane-compression resolved by the submitter


class MeshExecutor:
    """Collects per-bucket merge jobs and executes them in family-batched
    shard_map calls over the bucket mesh. Implements the mesh-context
    protocol of core.mergefn (submit_dedup / submit_plan / result), so every
    dispatch/complete consumer (merge read, compaction, writer flush) routes
    through it unchanged. `plans_globally` tells submitters to hand over RAW
    lanes — compression is decided here, once per family batch, from stats
    reduced over every shard (ops.lanes.plan_lanes_global)."""

    plans_globally = True

    def __init__(self, mesh=None, key_axis_rows: int = 1 << 22):
        from .executor import _meshes

        self.bucket_mesh, self.key_mesh = (mesh, mesh) if mesh is not None else _meshes()
        self.key_axis_rows = key_axis_rows
        self._jobs: dict[int, _Job] = {}
        self._results: dict[int, object] = {}
        self._next = 0
        self._lock = threading.Lock()
        self.executed_batches = 0  # observability: how many shard_map calls ran

    @property
    def feeder_lanes(self) -> int:
        """Host-side feeder width: one prefetch lane per device on the bucket
        axis (the SplitPipeline parallelism/depth the consumers use)."""
        return int(self.bucket_mesh.shape["bucket"])

    # ---- submission (thread-safe: feeder workers dispatch concurrently) ---
    def submit_dedup(self, lanes, seq_lanes, compress: bool = True) -> int:
        return self._submit(_Job("dedup", lanes, seq_lanes, compress))

    def submit_plan(self, lanes, seq_lanes, compress: bool = True) -> int:
        return self._submit(_Job("plan", lanes, seq_lanes, compress))

    def _submit(self, job: _Job) -> int:
        with self._lock:
            jid = self._next
            self._next += 1
            self._jobs[jid] = job
            return jid

    def result(self, job_id: int):
        if job_id not in self._results:
            self.execute()
        return self._results.pop(job_id)

    # ---- execution --------------------------------------------------------
    def execute(self) -> None:
        with self._lock:
            pending = self._jobs
            self._jobs = {}
        if not pending:
            return
        g = _metrics()
        g.counter("buckets_sharded").inc(len(pending))
        # family batches: one global plan and one shard_map program per
        # (family, lane arity, compression) group
        groups: dict[tuple, list[tuple[int, _Job]]] = {}
        huge: list[tuple[int, _Job]] = []
        p_key = self.key_mesh.shape.get("key", 1)
        for jid, job in pending.items():
            if (
                job.kind == "dedup"
                and p_key > 1
                and job.lanes.shape[0] >= self.key_axis_rows
            ):
                huge.append((jid, job))
            else:
                groups.setdefault(
                    (job.kind, job.lanes.shape[1], job.compress), []
                ).append((jid, job))
        for key, jobs in groups.items():
            kind, _, compress = key
            self._run_family(kind, jobs, compress)
        for jid, job in huge:
            # one hot bucket bigger than the key-axis threshold: leave the
            # bucket axis and range-shuffle its rows over the key axis
            self._results[jid] = self._run_key_axis(job)

    def _packed_lanes(self, jobs: list[tuple[int, _Job]], compress: bool):
        """Apply the ONE global plan to every job's lanes (or pass them
        through untouched when the compression layer is off — identity keeps
        the off-switch bit-exact)."""
        if not compress:
            return [j.lanes for _, j in jobs], None
        from ..ops.lanes import _record, apply_plan, plan_lanes_global

        plan = plan_lanes_global([j.lanes for _, j in jobs])
        packed = [apply_plan(plan, j.lanes) for _, j in jobs]
        _record(plan, sum(j.lanes.shape[0] for _, j in jobs))
        return packed, plan

    def _run_family(self, kind: str, jobs: list[tuple[int, _Job]], compress: bool) -> None:
        from ..ops.merge import pad_size

        packed, _plan = self._packed_lanes(jobs, compress)
        axis = self.bucket_mesh.shape["bucket"]
        k_star = max(p.shape[1] for p in packed)
        s_star = max(
            (0 if j.seq_lanes is None else j.seq_lanes.shape[1]) for _, j in jobs
        )
        per_row_words = k_star + s_star + 1
        budget_rows = max(_DEVICE_BUDGET_WORDS // per_row_words, 1)
        # sort by padded size so similar-size jobs share a chunk (a chunk is
        # allocated at its max m; mixing one huge bucket with many tiny ones
        # would multiply the real footprint)
        order = sorted(range(len(jobs)), key=lambda i: jobs[i][1].lanes.shape[0])
        chunk: list[int] = []
        chunk_m = 0
        for i in order:
            m = pad_size(packed[i].shape[0])
            new_m = max(chunk_m, m)
            if chunk and (len(chunk) + 1) * new_m > budget_rows:
                self._run_chunk(kind, [(jobs[i2], packed[i2]) for i2 in chunk], axis, k_star, s_star)
                chunk, chunk_m = [], 0
                new_m = m
            chunk.append(i)
            chunk_m = new_m
        if chunk:
            self._run_chunk(kind, [(jobs[i2], packed[i2]) for i2 in chunk], axis, k_star, s_star)

    def _run_chunk(self, kind: str, items, axis: int, k: int, s: int) -> None:
        from ..metrics import timed
        from ..ops.merge import MergePlan, pad_size

        from .merge import bucket_parallel_dedup_fn, bucket_parallel_plan_fn

        g = _metrics()
        m = max(pad_size(p.shape[0]) for _, p in items)
        # power-of-two multiples of the axis bound the jit cache to O(log n)
        # leading-dim shapes (same reasoning as ops/merge.pad_size)
        per_dev = -(-len(items) // axis)
        p2 = 1
        while p2 < per_dev:
            p2 <<= 1
        b = p2 * axis
        kl = np.full((b, m, k), 0xFFFFFFFF, dtype=np.uint32)
        sl = np.zeros((b, m, s), dtype=np.uint32)
        pad = np.ones((b, m), dtype=np.uint32)
        total_valid = 0
        for i, ((_, job), packed) in enumerate(items):
            n = packed.shape[0]
            total_valid += n
            kl[i, :n, : packed.shape[1]] = packed
            # missing lanes beyond a job's arity stay constant — constant
            # lanes affect neither ordering nor segmentation
            kl[i, :n, packed.shape[1] :] = 0
            if job.seq_lanes is not None and job.seq_lanes.shape[1]:
                sl[i, :n, : job.seq_lanes.shape[1]] = job.seq_lanes
            pad[i, :n] = 0
        g.counter("shards").inc()
        g.counter("pad_rows").inc(b * m - total_valid)
        self.executed_batches += 1
        with timed(g.histogram("device_busy_ms")):
            if kind == "dedup":
                packed_out, counts = bucket_parallel_dedup_fn(self.bucket_mesh, k, s)(kl, sl, pad)
                packed_out = np.asarray(packed_out)
                counts = np.asarray(counts)
                for i, ((jid, _), _p) in enumerate(items):
                    self._results[jid] = packed_out[i, : int(counts[i])]
            else:
                perm, seg_start, keep_last, seg_id = map(
                    np.asarray, bucket_parallel_plan_fn(self.bucket_mesh, k, s)(kl, sl, pad)
                )
                for i, ((jid, job), _p) in enumerate(items):
                    self._results[jid] = MergePlan(
                        perm=perm[i],
                        seg_start=seg_start[i],
                        keep_last=keep_last[i],
                        seg_id=seg_id[i],
                        n=job.lanes.shape[0],
                        m=m,
                    )

    def _run_key_axis(self, job: _Job) -> np.ndarray:
        """One oversized bucket's dedup range-shuffled over the key axis.
        The global-plan rule matters most here: every device packs its row
        range with the SAME plan, so the all_gather'd splitter sample and the
        exchanged lanes stay comparable."""
        from ..metrics import timed

        from .executor import distributed_dedup_select

        g = _metrics()
        lanes = job.lanes
        if job.compress:
            from ..ops.lanes import _record, apply_plan, plan_lanes_global

            plan = plan_lanes_global([lanes])
            lanes = apply_plan(plan, lanes)
            _record(plan, lanes.shape[0])
        g.counter("shards").inc()
        g.counter("exchange_rows").inc(lanes.shape[0])
        self.executed_batches += 1
        if lanes.shape[1] == 0:
            # globally constant key: one winner, no device trip
            from ..ops.lanes import scalar_dedup_winner

            return scalar_dedup_winner(job.seq_lanes, lanes.shape[0])
        with timed(g.histogram("device_busy_ms")):
            return distributed_dedup_select(self.key_mesh, lanes, job.seq_lanes)


def mesh_feeder_lanes(options) -> int:
    """Feeder width for mesh-driven host pipelines outside an installed
    executor (sort-compact's bucket loop): one lane per device on the bucket
    axis, or 0 when the mesh engine is off/unusable (callers keep their
    serial loop)."""
    if resolve_merge_engine(options) != "mesh" or not mesh_available():
        return 0
    from .executor import _meshes

    return int(_meshes()[0].shape["bucket"])


# ---------------------------------------------------------------------------
# cross-bucket repartition: sort-compact clustering / dynamic-bucket rescale
# ---------------------------------------------------------------------------


def mesh_cluster_permutation(lanes: np.ndarray, options) -> np.ndarray | None:
    """Distributed clustering sort for sort-compact (and the row-repartition
    primitive a dynamic-bucket rescale uses): rows range-shuffled over the
    mesh's key axis, each device sorting its key range locally, the global
    permutation recovered from the row-id lane that rides the exchange.
    Returns the STABLE sort permutation — bit-identical to the single-device
    `merge_plan(...)` path — or None when the mesh engine is off, the mesh is
    unusable, or the batch is below `parallel.key-axis.rows` (collective
    latency would beat the win on small batches)."""
    from ..options import CoreOptions

    if resolve_merge_engine(options) != "mesh" or not mesh_available():
        return None
    n = lanes.shape[0]
    threshold = options.options.get(CoreOptions.PARALLEL_KEY_AXIS_ROWS)
    if n < max(int(threshold), 2):
        return None
    from ..ops.lanes import apply_plan, plan_lanes_global
    from .executor import _meshes
    from .merge import range_partition_rows

    key_mesh = _meshes()[1]
    p = key_mesh.shape["key"]
    if p < 2 or n < p:
        return None
    compress = options.lane_compression
    if compress:
        packed = apply_plan(plan_lanes_global([lanes]), lanes)
    else:
        packed = np.ascontiguousarray(lanes, dtype=np.uint32)
    if packed.shape[1] == 0:
        # every row carries the same curve code: the stable sort is the
        # identity permutation
        return np.arange(n, dtype=np.int64)
    from ..ops.merge import pad_size

    # power-of-two per-device shards bound the jit cache to O(log n) shapes
    # (same reasoning as ops/merge.pad_size)
    m_loc = pad_size(-(-n // p))
    total = m_loc * p
    kl = np.full((total, packed.shape[1]), 0xFFFFFFFF, dtype=np.uint32)
    kl[:n] = packed
    rid = np.arange(total, dtype=np.uint32)
    pad = np.zeros(total, dtype=np.uint32)
    pad[n:] = 1
    g = _metrics()
    g.counter("shards").inc()
    g.counter("exchange_rows").inc(n)
    g.counter("pad_rows").inc(total - n)
    t0 = time.perf_counter()
    rows_sorted, pad_sorted = range_partition_rows(key_mesh, kl, rid, pad)
    out = rows_sorted[pad_sorted == 0].astype(np.int64)
    g.histogram("device_busy_ms").update((time.perf_counter() - t0) * 1000)
    return out
