"""Mesh execution of table work: the bridge from Table operations to the
distributed kernels.

The reference distributes table work by running one task per (partition,
bucket) on a Flink/Spark cluster (FlinkSinkBuilder.java:223 topology,
MergeTreeSplitGenerator.java:38 split generation). The TPU-native mapping
implemented here: table operations (write flush, compaction rewrite,
merge-read) run in two phases — a *dispatch* phase that reads inputs and
submits per-bucket merge jobs, and a *complete* phase that consumes results —
and a `MeshBatchContext` collects every job dispatched in between and executes
them all in ONE shard_map over the mesh's "bucket" axis (buckets are
key-disjoint: pure data parallelism, zero collectives). Oversized buckets are
instead range-partitioned over the "key" axis (all_gather splitter sample +
all_to_all shuffle + local merge — the RangeShuffle.java analog), so a single
hot bucket scales past one device too.

Commit stays host-side: in multi-process runs only the process-0 coordinator
commits (distributed.is_commit_coordinator), exactly like the reference's
single-parallelism committer operator.
"""

from __future__ import annotations

import contextvars
import functools
from contextlib import contextmanager
from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "MeshBatchContext",
    "mesh_batch",
    "maybe_mesh_batch",
    "current_mesh_context",
    "distributed_dedup_select",
]

_ACTIVE: contextvars.ContextVar["MeshBatchContext | None"] = contextvars.ContextVar(
    "paimon_mesh_batch", default=None
)

# one batched call is chunked so padded lanes stay under this many uint32s
_DEVICE_BUDGET_WORDS = 64 * 1024 * 1024


def current_mesh_context() -> "MeshBatchContext | None":
    return _ACTIVE.get()


@contextmanager
def mesh_batch(mesh=None, key_axis_rows: int = 1 << 22):
    """Install a MeshBatchContext for the dynamic extent. Dispatch-phase
    merge_async calls enqueue jobs; the first result() executes everything
    pending in one batched mesh call."""
    ctx = MeshBatchContext(mesh, key_axis_rows=key_axis_rows)
    token = _ACTIVE.set(ctx)
    try:
        yield ctx
    finally:
        _ACTIVE.reset(token)


@contextmanager
def maybe_mesh_batch(store):
    """The one mesh-entry seam for table operations. `merge.engine = mesh`
    (the ISSUE 7 executor: family-batched shard_maps, global lane plans,
    per-device feeder — parallel.mesh_exec) takes precedence; otherwise the
    legacy parallel.mesh.enabled batching context; no-op when neither is on,
    a context is already active, or <2 devices are visible (cpu fallback)."""
    from ..options import CoreOptions

    from .mesh_exec import maybe_mesh_exec, resolve_merge_engine

    if resolve_merge_engine(store.options) == "mesh" and current_mesh_context() is None:
        with maybe_mesh_exec(store.options) as ctx:
            yield ctx
        return
    enabled = store.options.options.get(CoreOptions.PARALLEL_MESH_ENABLED)
    if not enabled or current_mesh_context() is not None:
        yield None
        return
    import jax

    if len(jax.devices()) < 2:
        yield None
        return
    threshold = store.options.options.get(CoreOptions.PARALLEL_KEY_AXIS_ROWS)
    with mesh_batch(key_axis_rows=threshold) as ctx:
        yield ctx


# ---------------------------------------------------------------------------
# batched kernels (bucket axis)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _meshes():
    """(bucket_mesh, key_mesh) over every visible device: all devices on the
    bucket axis for batched per-bucket jobs, all on the key axis for the
    range-shuffle path of one oversized bucket."""
    from .mesh import make_mesh

    bucket = make_mesh(None)  # {"bucket": N, "key": 1}
    key = make_mesh(None, bucket_parallel=1)  # {"bucket": 1, "key": N}
    return bucket, key


class _KernelCache:
    """jit+shard_map programs keyed by (kind, lane arities); the mesh is fixed
    per process so one cache serves every context."""

    def __init__(self):
        self._fns: dict = {}

    def batched_dedup(self, mesh, k: int, s: int):
        key = ("dedup", id(mesh), k, s)
        fn = self._fns.get(key)
        if fn is None:
            fn = _make_batched_dedup(mesh, k, s)
            self._fns[key] = fn
        return fn

    def batched_plan(self, mesh, k: int, s: int):
        key = ("plan", id(mesh), k, s)
        fn = self._fns.get(key)
        if fn is None:
            fn = _make_batched_plan(mesh, k, s)
            self._fns[key] = fn
        return fn

    def key_axis_dedup(self, mesh, k: int, s: int):
        key = ("keyaxis", id(mesh), k, s)
        fn = self._fns.get(key)
        if fn is None:
            fn = _make_key_axis_dedup(mesh, k, s)
            self._fns[key] = fn
        return fn


_KERNELS = _KernelCache()


def _shard_map():
    import jax

    try:
        from jax import shard_map as mod

        return mod.shard_map if hasattr(mod, "shard_map") else mod
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

        return shard_map


def _make_batched_dedup(mesh, k: int, s: int):
    """(B, m, K) uint32 key lanes, (B, m, S) seq lanes, (B, m) pad ->
    per-bucket packed selected input indices + counts, buckets sharded over
    the mesh's bucket axis. The kernel body IS ops.merge.sorted_segments /
    pack_selected — one copy of the semantics for mesh and single-device."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..ops.merge import pack_selected, sorted_segments

    def per_bucket(kl, sl, pf):  # (m, K), (m, S), (m,)
        pad_sorted, perm, _, keep_last, _ = sorted_segments(k, s, kl.T, sl.T, pf)
        return pack_selected(keep_last & (pad_sorted == 0), perm)

    def shard_fn(kl, sl, pf):
        return jax.vmap(per_bucket)(kl, sl, pf)

    fn = _shard_map()(
        shard_fn,
        mesh=mesh,
        in_specs=(P("bucket", None, None), P("bucket", None, None), P("bucket", None)),
        out_specs=(P("bucket", None), P("bucket")),
    )
    return jax.jit(fn)


def _make_batched_plan(mesh, k: int, s: int):
    """Like _make_batched_dedup but returns the full merge plan arrays
    (perm, seg_start, keep_last, seg_id) per bucket — the non-dedup engines
    continue host-side with segment reductions."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..ops.merge import sorted_segments

    def per_bucket(kl, sl, pf):
        _, perm, seg_start, keep_last, seg_id = sorted_segments(k, s, kl.T, sl.T, pf)
        return perm, seg_start, keep_last, seg_id

    def shard_fn(kl, sl, pf):
        return jax.vmap(per_bucket)(kl, sl, pf)

    fn = _shard_map()(
        shard_fn,
        mesh=mesh,
        in_specs=(P("bucket", None, None), P("bucket", None, None), P("bucket", None)),
        out_specs=(P("bucket", None), P("bucket", None), P("bucket", None), P("bucket", None)),
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# key-axis path: one oversized bucket range-partitioned over all devices
# ---------------------------------------------------------------------------


def _make_key_axis_dedup(mesh, k: int, s: int):
    """jitted range-shuffle dedup over the mesh's key axis (cached per
    (mesh, lane arity) like the bucket-axis kernels)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .merge import _local_plan, _range_exchange

    p = mesh.shape["key"]
    sentinel = np.uint32(0xFFFFFFFF)

    def shard_fn(klx, slx, pfx):
        rk, rs, rp = _range_exchange(klx.T, slx.T, pfx, "key", p, k, s + 1)
        perm, _, keep_last, _ = _local_plan(k, s + 1, rk, rs, rp)
        sel = keep_last & (rp[perm] == 0)
        rowids = rs[s][perm]
        return jnp.where(sel, rowids, sentinel)

    fn = _shard_map()(
        shard_fn,
        mesh=mesh,
        in_specs=(P("key", None), P("key", None), P("key")),
        out_specs=P("key"),
    )
    return jax.jit(fn)


def distributed_dedup_select(mesh, key_lanes: np.ndarray, seq_lanes: np.ndarray | None = None) -> np.ndarray:
    """Dedup selection for ONE bucket whose rows are sharded over the mesh's
    "key" axis: sample splitters (all_gather), range-shuffle rows to their
    owner (all_to_all over ICI), locally sort + keep-last, return the winning
    INPUT row indices in global key order. The row id rides the shuffle as the
    final sort lane, which reproduces input-order tie-break across devices."""
    n, k = key_lanes.shape
    p = mesh.shape["key"]
    if seq_lanes is None:
        seq_lanes = np.zeros((n, 0), dtype=np.uint32)
    s = seq_lanes.shape[1]
    m_loc = -(-n // p)  # ceil
    total = m_loc * p
    kl = np.full((total, k), 0xFFFFFFFF, dtype=np.uint32)
    kl[:n] = key_lanes
    sl = np.zeros((total, s + 1), dtype=np.uint32)
    sl[:n, :s] = seq_lanes
    sl[:, s] = np.arange(total, dtype=np.uint32)  # row id = last tie-break lane
    pad = np.zeros(total, dtype=np.uint32)
    pad[n:] = 1
    out = np.asarray(_KERNELS.key_axis_dedup(mesh, k, s)(kl, sl, pad))
    # shards own ascending key ranges and emit sorted order -> already key order
    return out[out != np.uint32(0xFFFFFFFF)].astype(np.int32)


# ---------------------------------------------------------------------------
# the batch context
# ---------------------------------------------------------------------------


@dataclass
class _Job:
    kind: str  # "dedup" | "plan"
    lanes: np.ndarray  # (n, K) uint32
    seq_lanes: np.ndarray | None  # (n, S) uint32


@dataclass
class MeshBatchContext:
    """Collects merge jobs dispatched by table operations and executes them
    in batched mesh calls. Results are MergePlan objects for "plan" jobs and
    selected input-index arrays for "dedup" jobs."""

    mesh: object = None
    key_axis_rows: int = 1 << 22
    _jobs: dict[int, _Job] = field(default_factory=dict)
    _results: dict[int, object] = field(default_factory=dict)
    _next: int = 0
    executed_batches: int = 0  # observability: how many mesh calls ran

    def submit_dedup(self, lanes: np.ndarray, seq_lanes: np.ndarray | None) -> int:
        return self._submit(_Job("dedup", lanes, seq_lanes))

    def submit_plan(self, lanes: np.ndarray, seq_lanes: np.ndarray | None) -> int:
        return self._submit(_Job("plan", lanes, seq_lanes))

    def _submit(self, job: _Job) -> int:
        jid = self._next
        self._next += 1
        self._jobs[jid] = job
        return jid

    def result(self, job_id: int):
        if job_id not in self._results:
            self.execute()
        return self._results.pop(job_id)

    # ---- execution -----------------------------------------------------
    def execute(self) -> None:
        if not self._jobs:
            return
        bucket_mesh, key_mesh = (self.mesh, self.mesh) if self.mesh is not None else _meshes()
        pending = self._jobs
        self._jobs = {}
        huge: list[tuple[int, _Job]] = []
        by_kind: dict[str, list[tuple[int, _Job]]] = {"dedup": [], "plan": []}
        p_key = key_mesh.shape.get("key", 1)
        for jid, job in pending.items():
            if job.kind == "dedup" and p_key > 1 and job.lanes.shape[0] >= self.key_axis_rows:
                huge.append((jid, job))
            else:
                by_kind[job.kind].append((jid, job))
        for jid, job in huge:
            self._results[jid] = distributed_dedup_select(key_mesh, job.lanes, job.seq_lanes)
            self.executed_batches += 1
        for kind, jobs in by_kind.items():
            if jobs:
                self._execute_bucket_batch(bucket_mesh, kind, jobs)

    def _execute_bucket_batch(self, mesh, kind: str, jobs: list[tuple[int, _Job]]) -> None:
        from ..ops.merge import pad_size

        axis = mesh.shape["bucket"]
        k_star = max(j.lanes.shape[1] for _, j in jobs)
        k_star = max(k_star, 1)
        s_star = max((0 if j.seq_lanes is None else j.seq_lanes.shape[1]) for _, j in jobs)
        per_row_words = k_star + s_star + 1
        budget_rows = max(_DEVICE_BUDGET_WORDS // per_row_words, 1)
        # sort by padded size so similar-size jobs share a chunk: every job in
        # a chunk is allocated at the chunk MAX m, so mixing one huge bucket
        # with many tiny ones would multiply the real footprint (and inflate
        # the tiny jobs' MergePlan.m downstream)
        jobs = sorted(jobs, key=lambda item: item[1].lanes.shape[0])
        chunk: list[tuple[int, _Job]] = []
        chunk_m = 0
        for item in jobs:
            m = pad_size(item[1].lanes.shape[0])
            new_m = max(chunk_m, m)
            if chunk and (len(chunk) + 1) * new_m > budget_rows:
                self._run_chunk(mesh, kind, chunk, axis, k_star, s_star)
                chunk, chunk_m = [], 0
                new_m = m
            chunk.append(item)
            chunk_m = new_m
        if chunk:
            self._run_chunk(mesh, kind, chunk, axis, k_star, s_star)

    def _run_chunk(self, mesh, kind: str, jobs, axis: int, k: int, s: int) -> None:
        from ..ops.merge import MergePlan, pad_size

        m = max(pad_size(j.lanes.shape[0]) for _, j in jobs)
        # power-of-two multiples of the axis bound the jit cache to
        # O(log n) leading-dim shapes (same reasoning as ops/merge.pad_size)
        per_dev = -(-len(jobs) // axis)
        p2 = 1
        while p2 < per_dev:
            p2 <<= 1
        b = p2 * axis
        kl = np.full((b, m, k), 0xFFFFFFFF, dtype=np.uint32)
        sl = np.zeros((b, m, s), dtype=np.uint32)
        pad = np.ones((b, m), dtype=np.uint32)
        for i, (_, job) in enumerate(jobs):
            n = job.lanes.shape[0]
            kl[i, :n, : job.lanes.shape[1]] = job.lanes
            # missing lanes beyond a job's arity stay constant 0xFF.. / 0 —
            # constant lanes affect neither ordering nor segmentation
            kl[i, :n, job.lanes.shape[1] :] = 0
            if job.seq_lanes is not None and job.seq_lanes.shape[1]:
                sl[i, :n, : job.seq_lanes.shape[1]] = job.seq_lanes
            pad[i, :n] = 0
        self.executed_batches += 1
        if kind == "dedup":
            packed, counts = _KERNELS.batched_dedup(mesh, k, s)(kl, sl, pad)
            packed = np.asarray(packed)
            counts = np.asarray(counts)
            for i, (jid, _) in enumerate(jobs):
                self._results[jid] = packed[i, : int(counts[i])]
        else:
            perm, seg_start, keep_last, seg_id = map(
                np.asarray, _KERNELS.batched_plan(mesh, k, s)(kl, sl, pad)
            )
            for i, (jid, job) in enumerate(jobs):
                self._results[jid] = MergePlan(
                    perm=perm[i],
                    seg_start=seg_start[i],
                    keep_last=keep_last[i],
                    seg_id=seg_id[i],
                    n=job.lanes.shape[0],
                    m=m,
                )
