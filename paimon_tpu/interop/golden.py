"""Reference-layout tables: write golden fixtures, read them back.

Byte-format parity targets (studied, not copied):
  snapshot JSON     Snapshot.java:68-183 (field names, commitKind enum)
  schema JSON       schema/SchemaSerializer.java (version 2, compact types)
  manifest avro     manifest/ManifestEntry.schema() + DataFileMeta.SCHEMA +
                    stats/SimpleStatsConverter.schema(), wrapped with the
                    _VERSION field (utils/VersionedObjectSerializer.java:40),
                    avro naming per format/avro/AvroSchemaConverter.java:56
  manifest list     manifest/ManifestFileMeta.schema(), version 2
  binary rows       data/BinaryRow.java layout via interop.binary_row
  data files        KeyValue.schema(): _KEY_<pk> fields + _SEQUENCE_NUMBER +
                    _VALUE_KIND + value fields (KeyValue.java:115-120),
                    parquet via the shared format layer

write_reference_table builds a complete single-bucket PK table in this
layout; read_reference_table scans ANY such table (fixture or produced by
the reference) through the normal merge path.
"""

from __future__ import annotations

import json
import time
import uuid

import numpy as np

from ..data.batch import ColumnBatch
from ..fs import FileIO, LocalFileIO
from ..types import BIGINT, INT, TINYINT, DataField, RowType
from .avro_io import read_ocf, write_ocf
from .binary_row import deserialize_binary_row, serialize_binary_row

__all__ = ["write_reference_table", "read_reference_table"]

_RECORD = "org.apache.paimon.avro.generated.record"


def _nullable(t):
    return ["null", t]


def _stats_schema(name: str) -> dict:
    return {
        "type": "record",
        "name": name,
        "fields": [
            {"name": "_MIN_VALUES", "type": "bytes"},
            {"name": "_MAX_VALUES", "type": "bytes"},
            {"name": "_NULL_COUNTS", "type": _nullable({"type": "array", "items": _nullable("long")})},
        ],
    }


def manifest_entry_schema() -> dict:
    file_rec = {
        "type": "record",
        "name": f"{_RECORD}__FILE",
        "fields": [
            {"name": "_FILE_NAME", "type": "string"},
            {"name": "_FILE_SIZE", "type": "long"},
            {"name": "_ROW_COUNT", "type": "long"},
            {"name": "_MIN_KEY", "type": "bytes"},
            {"name": "_MAX_KEY", "type": "bytes"},
            {"name": "_KEY_STATS", "type": _stats_schema(f"{_RECORD}__FILE__KEY_STATS")},
            {"name": "_VALUE_STATS", "type": _stats_schema(f"{_RECORD}__FILE__VALUE_STATS")},
            {"name": "_MIN_SEQUENCE_NUMBER", "type": "long"},
            {"name": "_MAX_SEQUENCE_NUMBER", "type": "long"},
            {"name": "_SCHEMA_ID", "type": "long"},
            {"name": "_LEVEL", "type": "int"},
            {"name": "_EXTRA_FILES", "type": {"type": "array", "items": "string"}},
            {
                "name": "_CREATION_TIME",
                "type": _nullable({"type": "long", "logicalType": "timestamp-millis"}),
                "default": None,
            },
            {"name": "_DELETE_ROW_COUNT", "type": _nullable("long"), "default": None},
            {"name": "_EMBEDDED_FILE_INDEX", "type": _nullable("bytes"), "default": None},
            {"name": "_FILE_SOURCE", "type": _nullable("int"), "default": None},
        ],
    }
    return {
        "type": "record",
        "name": _RECORD,
        "fields": [
            {"name": "_VERSION", "type": "int"},
            {"name": "_KIND", "type": "int"},
            {"name": "_PARTITION", "type": "bytes"},
            {"name": "_BUCKET", "type": "int"},
            {"name": "_TOTAL_BUCKETS", "type": "int"},
            {"name": "_FILE", "type": file_rec},
        ],
    }


def manifest_meta_schema() -> dict:
    return {
        "type": "record",
        "name": _RECORD,
        "fields": [
            {"name": "_VERSION", "type": "int"},
            {"name": "_FILE_NAME", "type": "string"},
            {"name": "_FILE_SIZE", "type": "long"},
            {"name": "_NUM_ADDED_FILES", "type": "long"},
            {"name": "_NUM_DELETED_FILES", "type": "long"},
            {"name": "_PARTITION_STATS", "type": _stats_schema(f"{_RECORD}__PARTITION_STATS")},
            {"name": "_SCHEMA_ID", "type": "long"},
        ],
    }


def _kv_disk_schema(schema: RowType, primary_keys: list[str]) -> RowType:
    """KeyValue on-disk schema (KeyValue.java:115-120) — ONE builder shared
    with the store's write path (KVBatch.to_disk_batch carries the same
    layout and key-id offset)."""
    from ..core.kv import KVBatch, kv_disk_schema

    fields: list[DataField] = []
    for pk in primary_keys:
        f = schema.field(pk)
        fields.append(DataField(KVBatch._KEY_FIELD_ID_OFFSET + f.id, f"_KEY_{f.name}", f.type))
    fields.extend(kv_disk_schema(schema).fields)
    return RowType(tuple(fields))


def _empty_stats(arity: int, types) -> dict:
    return {
        "_MIN_VALUES": serialize_binary_row([None] * arity, types),
        "_MAX_VALUES": serialize_binary_row([None] * arity, types),
        "_NULL_COUNTS": [0] * arity,
    }


def write_reference_table(
    path: str,
    schema: RowType,
    primary_keys: list[str],
    batches: list[dict],
    file_io: FileIO | None = None,
    options: dict | None = None,
) -> None:
    """Write `batches` (one data file + snapshot per batch, ascending seq) as
    a complete reference-layout table: schema-0, bucket-0 parquet KV files,
    avro manifests + manifest lists, snapshot JSONs + LATEST hint."""
    io = file_io or LocalFileIO()
    from ..format import get_format

    opts = {"bucket": "1", **(options or {})}
    key_types = [schema.field(pk).type for pk in primary_keys]
    disk_schema = _kv_disk_schema(schema, primary_keys)
    schema_json = {
        "version": 2,
        "id": 0,
        "fields": [f.to_dict() for f in schema.fields],
        "highestFieldId": max(f.id for f in schema.fields),
        "partitionKeys": [],
        "primaryKeys": list(primary_keys),
        "options": opts,
        "timeMillis": int(time.time() * 1000),
    }
    io.mkdirs(f"{path}/schema")
    io.mkdirs(f"{path}/manifest")
    io.mkdirs(f"{path}/snapshot")
    io.mkdirs(f"{path}/bucket-0")
    io.write_bytes(f"{path}/schema/schema-0", json.dumps(schema_json).encode())

    fmt = get_format("parquet")
    seq = 0
    entry_schema = manifest_entry_schema()
    meta_schema = manifest_meta_schema()
    base_entries: list[dict] = []
    total_rows = 0
    for snap_id, data in enumerate(batches, start=1):
        batch = ColumnBatch.from_pydict(schema, data)
        n = batch.num_rows
        order = np.lexsort([batch.column(pk).values for pk in reversed(primary_keys)])
        batch = batch.take(order)
        cols = {}
        for pk in primary_keys:
            cols[f"_KEY_{pk}"] = batch.column(pk)
        from ..data.batch import Column

        cols["_SEQUENCE_NUMBER"] = Column(np.arange(seq, seq + n, dtype=np.int64))
        cols["_VALUE_KIND"] = Column(np.zeros(n, dtype=np.int8))
        for f in schema.fields:
            cols[f.name] = batch.column(f.name)
        disk = ColumnBatch(disk_schema, cols)
        file_name = f"data-{uuid.uuid4().hex}-0.parquet"
        fmt.write(io, f"{path}/bucket-0/{file_name}", disk)
        size = io.get_status(f"{path}/bucket-0/{file_name}").size
        min_key = [batch.column(pk).values[0] for pk in primary_keys]
        max_key = [batch.column(pk).values[-1] for pk in primary_keys]
        entry = {
            "_VERSION": 2,
            "_KIND": 0,  # ADD
            "_PARTITION": serialize_binary_row([], []),
            "_BUCKET": 0,
            "_TOTAL_BUCKETS": 1,
            "_FILE": {
                "_FILE_NAME": file_name,
                "_FILE_SIZE": size,
                "_ROW_COUNT": n,
                "_MIN_KEY": serialize_binary_row([_py(v) for v in min_key], key_types),
                "_MAX_KEY": serialize_binary_row([_py(v) for v in max_key], key_types),
                "_KEY_STATS": {
                    "_MIN_VALUES": serialize_binary_row([_py(v) for v in min_key], key_types),
                    "_MAX_VALUES": serialize_binary_row([_py(v) for v in max_key], key_types),
                    "_NULL_COUNTS": [0] * len(primary_keys),
                },
                "_VALUE_STATS": _empty_stats(len(schema.fields), [f.type for f in schema.fields]),
                "_MIN_SEQUENCE_NUMBER": seq,
                "_MAX_SEQUENCE_NUMBER": seq + n - 1,
                "_SCHEMA_ID": 0,
                "_LEVEL": 0,
                "_EXTRA_FILES": [],
                "_CREATION_TIME": int(time.time() * 1000),
                "_DELETE_ROW_COUNT": 0,
                "_EMBEDDED_FILE_INDEX": None,
                "_FILE_SOURCE": 0,
            },
        }
        seq += n
        total_rows += n

        delta_manifest = f"manifest-{uuid.uuid4().hex}-0"
        io.write_bytes(f"{path}/manifest/{delta_manifest}", write_ocf(entry_schema, [entry]))
        delta_meta = {
            "_VERSION": 2,
            "_FILE_NAME": delta_manifest,
            "_FILE_SIZE": io.get_status(f"{path}/manifest/{delta_manifest}").size,
            "_NUM_ADDED_FILES": 1,
            "_NUM_DELETED_FILES": 0,
            "_PARTITION_STATS": _empty_stats(0, []),
            "_SCHEMA_ID": 0,
        }
        base_manifest = f"manifest-{uuid.uuid4().hex}-0"
        io.write_bytes(f"{path}/manifest/{base_manifest}", write_ocf(entry_schema, list(base_entries)))
        base_meta = {
            "_VERSION": 2,
            "_FILE_NAME": base_manifest,
            "_FILE_SIZE": io.get_status(f"{path}/manifest/{base_manifest}").size,
            "_NUM_ADDED_FILES": len(base_entries),
            "_NUM_DELETED_FILES": 0,
            "_PARTITION_STATS": _empty_stats(0, []),
            "_SCHEMA_ID": 0,
        }
        base_list = f"manifest-list-{uuid.uuid4().hex}-0"
        delta_list = f"manifest-list-{uuid.uuid4().hex}-1"
        io.write_bytes(f"{path}/manifest/{base_list}", write_ocf(meta_schema, [base_meta] if base_entries else []))
        io.write_bytes(f"{path}/manifest/{delta_list}", write_ocf(meta_schema, [delta_meta]))
        base_entries.append(entry)

        snapshot = {
            "version": 3,
            "id": snap_id,
            "schemaId": 0,
            "baseManifestList": base_list,
            "deltaManifestList": delta_list,
            "changelogManifestList": None,
            "commitUser": "golden-fixture",
            "commitIdentifier": 9223372036854775807,
            "commitKind": "APPEND",
            "timeMillis": int(time.time() * 1000),
            "logOffsets": {},
            "totalRecordCount": total_rows,
            "deltaRecordCount": n,
            "changelogRecordCount": 0,
            "watermark": -9223372036854775808,
        }
        io.write_bytes(f"{path}/snapshot/snapshot-{snap_id}", json.dumps(snapshot).encode())
    io.write_bytes(f"{path}/snapshot/LATEST", str(len(batches)).encode())
    io.write_bytes(f"{path}/snapshot/EARLIEST", b"1")


def _py(v):
    return v.item() if hasattr(v, "item") else v


def read_reference_table(path: str, file_io: FileIO | None = None) -> tuple[RowType, ColumnBatch]:
    """Scan a reference-layout table (latest snapshot, merge-on-read with
    deduplicate semantics) into (value schema, rows). Works on golden
    fixtures and on unpartitioned single-bucket reference tables."""
    from ..core.datafile import DataFileMeta
    from ..core.kv import KVBatch
    from ..core.mergefn import MergeExecutor
    from ..core.schema import TableSchema
    from ..core.snapshot import SnapshotManager
    from ..format import get_format

    io = file_io or LocalFileIO()
    sm = SnapshotManager(io, path)
    snap = sm.latest_snapshot()
    assert snap is not None, f"no snapshots under {path}"
    ts = TableSchema.from_json(io.read_bytes(f"{path}/schema/schema-{snap.schema_id}"))
    schema = RowType(ts.fields)
    primary_keys = list(ts.primary_keys)
    key_types = [schema.field(pk).type for pk in primary_keys]
    disk_schema = _kv_disk_schema(schema, primary_keys)

    # manifest lists -> entries (live files of the latest snapshot)
    def read_list(name: str) -> list[dict]:
        _, metas = read_ocf(io.read_bytes(f"{path}/manifest/{name}"))
        entries: list[dict] = []
        for m in metas:
            _, es = read_ocf(io.read_bytes(f"{path}/manifest/{m['_FILE_NAME']}"))
            entries.extend(es)
        return entries

    entries = read_list(snap.base_manifest_list) + read_list(snap.delta_manifest_list)
    live: dict[str, dict] = {}
    for e in entries:
        f = e["_FILE"]
        if e["_KIND"] == 0:
            live[f["_FILE_NAME"]] = e
        else:
            live.pop(f["_FILE_NAME"], None)

    files = []
    for e in live.values():
        f = e["_FILE"]
        files.append(
            (e["_BUCKET"],
            DataFileMeta(
                file_name=f["_FILE_NAME"],
                file_size=f["_FILE_SIZE"],
                row_count=f["_ROW_COUNT"],
                min_key=tuple(deserialize_binary_row(f["_MIN_KEY"], key_types)),
                max_key=tuple(deserialize_binary_row(f["_MAX_KEY"], key_types)),
                key_stats={},
                value_stats={},
                min_sequence_number=f["_MIN_SEQUENCE_NUMBER"],
                max_sequence_number=f["_MAX_SEQUENCE_NUMBER"],
                schema_id=f["_SCHEMA_ID"],
                level=f["_LEVEL"],
            ))
        )

    # schema evolution: each file reads under the schema that WROTE it, then
    # aligns to the latest schema by field id (missing columns -> null)
    schemas_cache: dict[int, RowType] = {snap.schema_id: schema}

    def value_schema_of(schema_id: int) -> RowType:
        if schema_id not in schemas_cache:
            old = TableSchema.from_json(io.read_bytes(f"{path}/schema/schema-{schema_id}"))
            schemas_cache[schema_id] = RowType(old.fields)
        return schemas_cache[schema_id]

    fmt = get_format("parquet")
    from ..data.batch import Column, concat_batches

    parts = []
    for bucket, meta in sorted(files, key=lambda x: x[1].min_sequence_number):
        file_value_schema = value_schema_of(meta.schema_id)
        file_disk = _kv_disk_schema(file_value_schema, primary_keys)
        for b in fmt.read(io, f"{path}/bucket-{bucket}/{meta.file_name}", file_disk):
            by_id = {f.id: f for f in file_value_schema.fields}
            cols = {}
            for f in schema.fields:
                src = by_id.get(f.id)
                cols[f.name] = (
                    b.column(src.name)
                    if src is not None
                    else Column.from_pylist([None] * b.num_rows, f.type)
                )
            value = ColumnBatch(schema, cols)
            seqs = b.column("_SEQUENCE_NUMBER").values.astype(np.int64)
            kinds = b.column("_VALUE_KIND").values.astype(np.uint8)
            parts.append(KVBatch(value, seqs, kinds))
    if not parts:
        return schema, ColumnBatch.empty(schema)
    kv = KVBatch.concat(parts)
    merged = MergeExecutor(schema, primary_keys).merge(kv).drop_deletes()
    return schema, merged.data
