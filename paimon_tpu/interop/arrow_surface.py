"""Arrow-native engine surface: any Arrow-speaking engine can scan a table.

The reference's entire L5 exists so other engines can consume tables
(paimon-hive PaimonInputFormat hands table splits to the engine as its
splits; flink/source/FlinkSourceBuilder builds the scan topology).  The
Arrow-ecosystem analog needs no per-engine glue: a table exposes

- ``arrow_schema(row_type)`` — logical Arrow schema (timestamps/dates as
  real Arrow temporal types, not the int64/int32 device encoding),
- ``record_batch_reader(table, ...)`` — a lazy streaming
  ``pyarrow.RecordBatchReader``, one merge-read per split at a time; this is
  the C-stream-protocol object duckdb/polars/pandas/datafusion all accept,
- ``arrow_scanner(table, ...)`` / ``arrow_dataset(table, ...)`` —
  ``pyarrow.dataset`` views (the scanner stays lazy; the dataset
  materializes, documented),

plus per-split readers so a distributed engine can schedule one split per
worker exactly like PaimonInputFormat does (splits serialize via
``DataSplit.to_dict``).  The Flight server (service/flight.py) carries the
same surface over the network.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator, Sequence

if TYPE_CHECKING:
    from ..data.predicate import Predicate
    from ..table import FileStoreTable
    from ..table.read import DataSplit
    from ..types import DataType, RowType

__all__ = [
    "arrow_schema",
    "arrow_type",
    "record_batch_reader",
    "split_record_batches",
    "arrow_scanner",
    "arrow_dataset",
]


def arrow_type(dtype: "DataType"):
    """DataType -> logical pyarrow type (temporal types are real Arrow
    temporals; the internal columnar encoding keeps them as int64 micros /
    int32 days for the device path)."""
    import pyarrow as pa

    from ..types import TypeRoot

    r = dtype.root
    if r == TypeRoot.TIMESTAMP:
        return pa.timestamp("us")
    if r == TypeRoot.TIMESTAMP_LTZ:
        return pa.timestamp("us", tz="UTC")
    if r == TypeRoot.DATE:
        return pa.date32()
    if r == TypeRoot.TIME:
        return pa.time32("ms")  # internal encoding IS millis-of-day (int32)
    if r == TypeRoot.DECIMAL:
        return pa.decimal128(dtype.precision or 38, dtype.scale or 0)
    from ..data.batch import _pa_nested_type

    return _pa_nested_type(dtype)


def arrow_schema(row_type: "RowType"):
    import pyarrow as pa

    return pa.schema(
        [pa.field(f.name, arrow_type(f.type), nullable=f.type.nullable) for f in row_type.fields]
    )


def _cast_to_logical(tbl, schema):
    """Internal to_arrow() output -> the logical surface schema (int64
    micros -> timestamp[us], int32 days -> date32, int32 millis ->
    time32[ms], unscaled int64 -> decimal128)."""
    import pyarrow as pa

    cols = []
    for fld in schema:
        col = tbl.column(fld.name)
        if col.type != fld.type:
            if pa.types.is_decimal(fld.type):
                # internal DECIMAL is the UNSCALED long (value * 10^scale):
                # a value-cast would multiply by 10^scale again, so rebuild
                # from the raw ints via python Decimal (decimals are an edge
                # surface; correctness over speed here)
                from decimal import Decimal

                scale = fld.type.scale
                vals = [
                    None if v is None else Decimal(v).scaleb(-scale)
                    for chunk in col.chunks
                    for v in chunk.to_pylist()
                ]
                col = pa.chunked_array([pa.array(vals, type=fld.type)])
            else:
                col = col.cast(fld.type)
        cols.append(col)
    return pa.table(dict(zip(schema.names, cols)), schema=schema)


def _surface_schema(table: "FileStoreTable", projection: Sequence[str] | None):
    rt = table.row_type if projection is None else table.row_type.project(projection)
    return arrow_schema(rt)


def split_record_batches(
    table: "FileStoreTable",
    split: "DataSplit",
    predicate: "Predicate | None" = None,
    projection: Sequence[str] | None = None,
    max_chunksize: int = 1 << 20,
) -> Iterator:
    """Arrow RecordBatches of one split's merge-read (an engine worker's
    unit of work, reference PaimonInputFormat.RecordReader)."""
    rb = table.new_read_builder()
    if predicate is not None:
        rb = rb.with_filter(predicate)
    if projection is not None:
        rb = rb.with_projection(list(projection))
    out = rb.new_read().read(split)
    tbl = _cast_to_logical(out.to_arrow(), _surface_schema(table, projection))
    yield from tbl.to_batches(max_chunksize=max_chunksize)


def record_batch_reader(
    table: "FileStoreTable",
    predicate: "Predicate | None" = None,
    projection: Sequence[str] | None = None,
    splits: "Sequence[DataSplit] | None" = None,
    max_chunksize: int | None = None,
):
    """Lazy streaming reader over the whole table (or given splits): splits
    merge one at a time, so peak memory is one split's worth regardless of
    table size.  Batch granularity: explicit max_chunksize, else the table's
    read.batch-size option if set, else 1M rows."""
    import pyarrow as pa

    if max_chunksize is None:
        from ..options import CoreOptions

        max_chunksize = table.options.options.get(CoreOptions.READ_BATCH_SIZE) or 1 << 20
    schema = _surface_schema(table, projection)
    if splits is None:
        rb = table.new_read_builder()
        if predicate is not None:
            rb = rb.with_filter(predicate)
        splits = rb.new_scan().plan()

    def gen():
        for s in splits:
            yield from split_record_batches(
                table, s, predicate=predicate, projection=projection, max_chunksize=max_chunksize
            )

    return pa.RecordBatchReader.from_batches(schema, gen())


def arrow_scanner(table: "FileStoreTable", predicate=None, projection=None, splits=None):
    """Lazy ``pyarrow.dataset.Scanner`` (duckdb: ``duckdb.from_arrow``)."""
    import pyarrow.dataset as ds

    reader = record_batch_reader(table, predicate=predicate, projection=projection, splits=splits)
    return ds.Scanner.from_batches(reader)


def arrow_dataset(table: "FileStoreTable", predicate=None, projection=None):
    """``pyarrow.dataset.Dataset`` view.  NOTE: InMemoryDataset materializes
    the merge-read once; use record_batch_reader/arrow_scanner for streaming."""
    import pyarrow.dataset as ds

    reader = record_batch_reader(table, predicate=predicate, projection=projection)
    return ds.dataset(reader.read_all())
