"""Reference-layout interop: byte-compatible metadata read/write.

The reference persists table metadata as JSON snapshots/schemas, Avro
manifests, and BinaryRow-serialized keys/partitions/stats
(/root/reference/paimon-core/.../manifest/ManifestFile.java:48,
Snapshot.java:68-183, utils/SerializationUtils.java:75-89). This package
implements those byte formats natively so a table laid out by the reference
can be scanned here, and golden fixtures written here follow the reference's
layout exactly:

  binary_row  — BinaryRow encode/decode (null bitset + 8B slots + var part)
  avro_io     — generic Avro object-container file read/write for the
                manifest record schemas
  golden      — reference-layout table writer (fixtures) + reader/scanner
"""

from .golden import read_reference_table, write_reference_table

__all__ = ["read_reference_table", "write_reference_table"]
