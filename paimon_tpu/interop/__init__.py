"""Reference-layout interop: byte-compatible metadata read/write.

The reference persists table metadata as JSON snapshots/schemas, Avro
manifests, and BinaryRow-serialized keys/partitions/stats
(/root/reference/paimon-core/.../manifest/ManifestFile.java:48,
Snapshot.java:68-183, utils/SerializationUtils.java:75-89). This package
implements those byte formats natively so a table laid out by the reference
can be scanned here, and golden fixtures written here follow the reference's
layout exactly:

  binary_row  — BinaryRow encode/decode (null bitset + 8B slots + var part)
  avro_io     — generic Avro object-container file read/write for the
                manifest record schemas
  golden      — reference-layout table writer (fixtures) + reader/scanner

Engine-facing consumption surfaces live here too:

  arrow_surface — RecordBatchReader / pyarrow Dataset / Arrow Flight server
  ml            — jax / torch input pipelines over table scans (the L5
                  analog for TPU-native consumers)
"""

from .golden import read_reference_table, write_reference_table

__all__ = [
    "read_reference_table",
    "write_reference_table",
    "iter_batches",
    "to_jax",
    "TorchIterableDataset",
]


def __getattr__(name):  # lazy: ml pulls in torch/jax only when asked for
    if name in ("iter_batches", "to_jax", "TorchIterableDataset"):
        from . import ml

        return getattr(ml, name)
    raise AttributeError(name)
