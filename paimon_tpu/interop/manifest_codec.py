"""Reference-format Avro codec for the store's OWN manifests.

Bridges the store's ManifestEntry/ManifestFileMeta (python dataclasses with
per-field FieldStats dicts) to the reference's on-disk Avro records
(ManifestEntry.schema() + DataFileMeta.SCHEMA + SimpleStatsConverter.schema()
with BinaryRow-serialized partition/keys/stats — see interop.golden for the
schema derivations). Behind `manifest.format=avro` a table's metadata becomes
reference-layout end to end: snapshot JSON + schema JSON already match, and
with this codec the manifests do too.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..data.predicate import FieldStats
from ..types import DataField, DataType
from .avro_io import read_ocf, write_ocf
from .binary_row import deserialize_binary_row, serialize_binary_row
from .golden import manifest_entry_schema, manifest_meta_schema

__all__ = ["StatsContext", "write_entries_avro", "read_entries_avro", "write_metas_avro", "read_metas_avro"]

# a resolver maps schema_id -> StatsContext; stats travel as positional
# BinaryRows, so they MUST decode under the schema that wrote them (jsonl
# manifests key stats by name and don't care — avro ones do)

_FILE_SOURCES = {"append": 0, "compact": 1}
_FILE_SOURCES_BACK = {0: "append", 1: "compact"}


@dataclass
class StatsContext:
    """Field order + types for the BinaryRow-encoded parts, derived from the
    table schema (partition keys, trimmed primary keys, value fields)."""

    partition_types: list[DataType]
    key_fields: list[DataField]  # trimmed primary key fields, in order
    value_fields: list[DataField]  # full value row fields, in order

    @staticmethod
    def from_table_schema(ts) -> "StatsContext":
        by_name = {f.name: f for f in ts.fields}
        return StatsContext(
            partition_types=[by_name[k].type for k in ts.partition_keys],
            key_fields=[by_name[k] for k in ts.trimmed_primary_keys],
            value_fields=list(ts.fields),
        )


def _stats_to_avro(stats: dict[str, FieldStats], fields: list[DataField]) -> dict:
    mins, maxs, nulls = [], [], []
    for f in fields:
        st = stats.get(f.name)
        if st is None:
            mins.append(None)
            maxs.append(None)
            nulls.append(None)
            continue
        mins.append(_safe(st.min))
        maxs.append(_safe(st.max))
        nulls.append(st.null_count)
    types = [f.type for f in fields]
    return {
        "_MIN_VALUES": serialize_binary_row(mins, types),
        "_MAX_VALUES": serialize_binary_row(maxs, types),
        "_NULL_COUNTS": nulls,
    }


def _safe(v):
    """Stats values the BinaryRow subset can't carry become null (pruning
    then stays conservative for that field)."""
    if isinstance(v, (bool, int, float, str, bytes)) or v is None:
        return v
    return None


def _stats_from_avro(node: dict, fields: list[DataField], row_count: int) -> dict[str, FieldStats]:
    types = [f.type for f in fields]
    try:
        mins = deserialize_binary_row(node["_MIN_VALUES"], types)
        maxs = deserialize_binary_row(node["_MAX_VALUES"], types)
    except Exception:
        return {}
    nulls = node.get("_NULL_COUNTS") or [None] * len(fields)
    out = {}
    for f, mn, mx, nc in zip(fields, mins, maxs, nulls):
        out[f.name] = FieldStats(mn, mx, nc, row_count)
    return out


def entry_to_avro(entry, resolver) -> dict:
    f = entry.file
    ctx = resolver(f.schema_id)
    key_types = [kf.type for kf in ctx.key_fields]
    return {
        "_VERSION": 2,
        "_KIND": int(entry.kind),
        "_PARTITION": serialize_binary_row([_safe(v) for v in entry.partition], ctx.partition_types),
        "_BUCKET": entry.bucket,
        "_TOTAL_BUCKETS": entry.total_buckets,
        "_FILE": {
            "_FILE_NAME": f.file_name,
            "_FILE_SIZE": f.file_size,
            "_ROW_COUNT": f.row_count,
            "_MIN_KEY": serialize_binary_row([_safe(v) for v in f.min_key], key_types),
            "_MAX_KEY": serialize_binary_row([_safe(v) for v in f.max_key], key_types),
            "_KEY_STATS": _stats_to_avro(f.key_stats, ctx.key_fields),
            "_VALUE_STATS": _stats_to_avro(f.value_stats, ctx.value_fields),
            "_MIN_SEQUENCE_NUMBER": f.min_sequence_number,
            "_MAX_SEQUENCE_NUMBER": f.max_sequence_number,
            "_SCHEMA_ID": f.schema_id,
            "_LEVEL": f.level,
            "_EXTRA_FILES": list(f.extra_files),
            "_CREATION_TIME": f.creation_time_millis or None,
            "_DELETE_ROW_COUNT": f.delete_row_count,
            "_EMBEDDED_FILE_INDEX": None,
            "_FILE_SOURCE": _FILE_SOURCES.get(f.file_source, 0),
        },
    }


def entry_from_avro(node: dict, resolver):
    from ..core.datafile import DataFileMeta
    from ..core.manifest import FileKind, ManifestEntry

    f = node["_FILE"]
    ctx = resolver(f["_SCHEMA_ID"])
    key_types = [kf.type for kf in ctx.key_fields]
    meta = DataFileMeta(
        file_name=f["_FILE_NAME"],
        file_size=f["_FILE_SIZE"],
        row_count=f["_ROW_COUNT"],
        min_key=tuple(deserialize_binary_row(f["_MIN_KEY"], key_types)),
        max_key=tuple(deserialize_binary_row(f["_MAX_KEY"], key_types)),
        key_stats=_stats_from_avro(f["_KEY_STATS"], ctx.key_fields, f["_ROW_COUNT"]),
        value_stats=_stats_from_avro(f["_VALUE_STATS"], ctx.value_fields, f["_ROW_COUNT"]),
        min_sequence_number=f["_MIN_SEQUENCE_NUMBER"],
        max_sequence_number=f["_MAX_SEQUENCE_NUMBER"],
        schema_id=f["_SCHEMA_ID"],
        level=f["_LEVEL"],
        delete_row_count=f.get("_DELETE_ROW_COUNT") or 0,
        creation_time_millis=f.get("_CREATION_TIME") or 0,
        file_source=_FILE_SOURCES_BACK.get(f.get("_FILE_SOURCE") or 0, "append"),
        extra_files=tuple(f.get("_EXTRA_FILES") or ()),
    )
    return ManifestEntry(
        FileKind(node["_KIND"]),
        tuple(deserialize_binary_row(node["_PARTITION"], ctx.partition_types)),
        node["_BUCKET"],
        node["_TOTAL_BUCKETS"],
        meta,
    )


def write_entries_avro(entries, resolver, codec: str = "deflate") -> bytes:
    return write_ocf(manifest_entry_schema(), [entry_to_avro(e, resolver) for e in entries], codec=codec)


def read_entries_avro(data: bytes, resolver):
    _, records = read_ocf(data)
    return [entry_from_avro(r, resolver) for r in records]


def write_metas_avro(metas, resolver, codec: str = "deflate") -> bytes:
    records = []
    for m in metas:
        ctx = resolver(m.schema_id)
        arity = len(ctx.partition_types)
        records.append(
            {
                "_VERSION": 2,
                "_FILE_NAME": m.file_name,
                "_FILE_SIZE": m.file_size,
                "_NUM_ADDED_FILES": m.num_added_files,
                "_NUM_DELETED_FILES": m.num_deleted_files,
                # all-null stats at the REAL partition arity (a reference
                # reader deserializes this against the partition row type)
                "_PARTITION_STATS": {
                    "_MIN_VALUES": serialize_binary_row([None] * arity, ctx.partition_types),
                    "_MAX_VALUES": serialize_binary_row([None] * arity, ctx.partition_types),
                    "_NULL_COUNTS": [None] * arity,
                },
                "_SCHEMA_ID": m.schema_id,
            }
        )
    return write_ocf(manifest_meta_schema(), records, codec=codec)


def read_metas_avro(data: bytes):
    from ..core.manifest import ManifestFileMeta

    _, records = read_ocf(data)
    return [
        ManifestFileMeta(
            r["_FILE_NAME"], r["_FILE_SIZE"], r["_NUM_ADDED_FILES"], r["_NUM_DELETED_FILES"], r["_SCHEMA_ID"]
        )
        for r in records
    ]
