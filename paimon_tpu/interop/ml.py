"""ML-engine serving: the TPU-native analog of the reference's L5 engine
connectors.

The reference's connectors exist so *compute engines* can consume tables:
Flink (`paimon-flink/.../source/FlinkSourceBuilder.java` builds a source
whose splits are table splits), Spark (DataSourceV2), Hive
(`PaimonInputFormat` — splits as engine splits). A TPU-native lake's
first-class consumers are training and evaluation loops, so this module
serves table scans as:

- `iter_batches`   — dicts of numpy arrays (any framework, zero deps)
- `to_jax`         — dicts of jax arrays, optionally `device_put` against a
                     `jax.sharding.Mesh` axis (data-parallel input pipeline;
                     multi-host callers shard splits by `process_index`)
- `TorchIterableDataset` — a picklable torch `IterableDataset` that shards
                     splits across DataLoader workers (the same split ->
                     worker mapping the reference's enumerator does across
                     Flink subtasks, `flink/source/ContinuousFileSplitEnumerator`)

Splits remain the unit of work distribution exactly as in the reference;
merge-on-read, predicate/projection pushdown, and time travel all come from
the normal ReadBuilder path, so a training job sees the same snapshot
semantics as any other reader.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator, Mapping, Sequence

import numpy as np

if TYPE_CHECKING:
    from ..data.predicate import Predicate
    from ..table import FileStoreTable

try:  # subclass torch's IterableDataset so DataLoader streams (not indexes);
    from torch.utils.data import IterableDataset as _TorchIterableBase
except Exception:  # torch absent: plain iterable (still works standalone)
    _TorchIterableBase = object

__all__ = ["iter_batches", "to_jax", "TorchIterableDataset"]


def _numeric_names(schema, include_strings: bool) -> list[str]:
    out = []
    for f in schema.fields:
        is_obj = f.type.numpy_dtype() == np.dtype(object)
        if include_strings or not is_obj:
            out.append(f.name)
    return out


def _batch_to_numpy(batch, names: Sequence[str], include_validity: bool) -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    for name in names:
        col = batch.column(name)
        out[name] = col.values
        if include_validity and col.validity is not None:
            out[f"{name}__valid"] = col.valid_mask()
    return out


def iter_batches(
    table: "FileStoreTable",
    *,
    batch_rows: int = 65536,
    projection: Sequence[str] | None = None,
    predicate: "Predicate | None" = None,
    shuffle_splits: bool = False,
    seed: int | None = None,
    include_strings: bool = True,
    include_validity: bool = False,
    splits=None,
) -> Iterator[dict[str, np.ndarray]]:
    """Stream a batch scan as dicts of numpy arrays of <= batch_rows rows.

    `shuffle_splits` permutes split order per epoch (seeded) — the standard
    input-pipeline trick of shuffling at the shard level while each shard
    stays sequential. Pass `splits` to serve a pre-planned/pre-assigned
    subset (distributed workers split the plan among themselves the way
    engine tasks split the reference's `FileStoreSourceSplit`s)."""
    rb = table.new_read_builder()
    if predicate is not None:
        rb = rb.with_filter(predicate)
    if projection is not None:
        rb = rb.with_projection(list(projection))
    if splits is None:
        splits = rb.new_scan().plan()
    splits = list(splits)
    if shuffle_splits:
        np.random.default_rng(seed).shuffle(splits)
    schema = table.row_type if projection is None else table.row_type.project(list(projection))
    names = _numeric_names(schema, include_strings)
    read = rb.new_read()
    for split in splits:
        batch = read.read(split)
        for lo in range(0, batch.num_rows, batch_rows):
            part = batch.slice(lo, min(lo + batch_rows, batch.num_rows))
            yield _batch_to_numpy(part, names, include_validity)


def to_jax(
    table: "FileStoreTable",
    *,
    batch_rows: int = 65536,
    projection: Sequence[str] | None = None,
    predicate: "Predicate | None" = None,
    shuffle_splits: bool = False,
    seed: int | None = None,
    include_validity: bool = False,
    mesh=None,
    data_axis: str = "data",
    drop_remainder: bool | None = None,
    splits=None,
) -> Iterator[Mapping[str, "object"]]:
    """`iter_batches` with jax placement. Strings are excluded (no jax
    dtype). With `mesh`, every batch is `device_put` with a NamedSharding
    over `data_axis` (row dimension sharded across the mesh axis — the
    data-parallel feed); batches are trimmed to a multiple of the axis size
    unless drop_remainder=False, in which case the tail pads by repeating
    the last row (weights should mask it). Multi-host data parallelism:
    plan once, shard the split list by `jax.process_index()`, and pass each
    host its subset via `splits` — each host then feeds only its shard."""
    import jax
    import jax.numpy as jnp

    sharding = None
    axis = 1
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        sharding = NamedSharding(mesh, PartitionSpec(data_axis))
        axis = int(np.prod([mesh.shape[a] for a in (data_axis,)]))
    for np_batch in iter_batches(
        table,
        batch_rows=batch_rows,
        projection=projection,
        predicate=predicate,
        shuffle_splits=shuffle_splits,
        seed=seed,
        include_strings=False,
        include_validity=include_validity,
        splits=splits,
    ):
        if not np_batch:
            continue
        n = len(next(iter(np_batch.values())))
        if sharding is not None and n % axis:
            if drop_remainder is None or drop_remainder:
                n_keep = (n // axis) * axis
                dropped = n - n_keep
                # silent loss is worse than noise: a small table (or a tail
                # batch) contributing zero rows to training must be visible
                import warnings

                warnings.warn(
                    f"to_jax: dropping {dropped} tail row(s) of a {n}-row batch "
                    f"(not a multiple of data-axis size {axis}); pass "
                    f"drop_remainder=False to pad instead",
                    stacklevel=2,
                )
                if n_keep == 0:
                    continue
                np_batch = {k: v[:n_keep] for k, v in np_batch.items()}
            else:
                pad = axis - (n % axis)
                np_batch = {k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)]) for k, v in np_batch.items()}
        if sharding is not None:
            yield {k: jax.device_put(v, sharding) for k, v in np_batch.items()}
        else:
            yield {k: jnp.asarray(v) for k, v in np_batch.items()}


class TorchIterableDataset(_TorchIterableBase):
    """A picklable torch IterableDataset over a table scan.

    Constructed from (warehouse, identifier) rather than a live table so
    DataLoader workers can rebuild the catalog in their own process. The
    scan is PLANNED ONCE at construction (in the parent) and the serialized
    split list is what workers inherit — every worker shards the identical
    snapshot-pinned plan round-robin by `get_worker_info()`, so one split is
    read by exactly one worker even while writers keep committing (the
    reference's enumerator assigns one immutable plan to subtasks the same
    way). Shuffling permutes that one plan with a seed that is drawn once in
    the parent; call `set_epoch(e)` between epochs to reshuffle
    deterministically (DistributedSampler convention). Numeric columns
    become torch tensors; string columns are excluded unless
    `as_numpy=True` (then dicts of numpy arrays are yielded instead,
    strings included)."""

    def __init__(
        self,
        warehouse: str,
        identifier: str,
        *,
        batch_rows: int = 65536,
        projection: Sequence[str] | None = None,
        options: Mapping[str, str] | None = None,
        shuffle_splits: bool = False,
        seed: int | None = None,
        as_numpy: bool = False,
    ):
        self.warehouse = warehouse
        self.identifier = identifier
        self.batch_rows = batch_rows
        self.projection = list(projection) if projection is not None else None
        self.options = dict(options or {})
        self.shuffle_splits = shuffle_splits
        # drawn once in the parent so every forked worker shuffles the same
        # permutation (a per-worker fresh seed would duplicate/drop splits)
        self.seed = int(np.random.default_rng(seed).integers(1 << 31)) if shuffle_splits else 0
        self.epoch = 0
        self.as_numpy = as_numpy
        self._split_dicts = [s.to_dict() for s in self._plan()]

    def _plan(self):
        table = self._table(in_worker=False)
        rb = table.new_read_builder()
        if self.projection is not None:
            rb = rb.with_projection(self.projection)
        return rb.new_scan().plan()

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle deterministically for a new epoch (call BEFORE creating
        the DataLoader iterator, i.e. before workers fork)."""
        self.epoch = int(epoch)

    def _table(self, in_worker: bool):
        from ..catalog import FileSystemCatalog

        opts = dict(self.options)
        if in_worker:
            # forked DataLoader workers must not touch jax (a forked child
            # inherits the parent's jax runtime locks and deadlocks); the
            # numpy merge engine is byte-identical and fork-safe
            opts.setdefault("sort-engine", "numpy")
        t = FileSystemCatalog(self.warehouse).get_table(self.identifier)
        return t.copy(opts) if opts else t

    def __iter__(self):
        from ..table.read import DataSplit

        try:
            from torch.utils.data import get_worker_info

            info = get_worker_info()
        except Exception:  # torch absent: single-worker semantics
            info = None
        table = self._table(in_worker=info is not None)
        splits = [DataSplit.from_dict(d) for d in self._split_dicts]
        if self.shuffle_splits:
            np.random.default_rng((self.seed, self.epoch)).shuffle(splits)
        if info is not None and info.num_workers > 1:
            splits = splits[info.id :: info.num_workers]
        it = iter_batches(
            table,
            batch_rows=self.batch_rows,
            projection=self.projection,
            include_strings=self.as_numpy,
            splits=splits,
        )
        if self.as_numpy:
            yield from it
            return
        import torch

        for np_batch in it:
            yield {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in np_batch.items()}
