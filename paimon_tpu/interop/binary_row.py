"""BinaryRow byte format (reference data/BinaryRow.java:33-55).

Layout of one row over a little-endian memory segment:
  [null bitset]  ((arity + 63 + 8) / 64) * 8 bytes; bit 0-7 of byte 0 hold
                 the RowKind header, field i's null bit is bit (i + 8),
                 LSB-first within each byte
  [fixed part]   8 bytes per field: primitives stored directly (LE);
                 var-length values <= 7 bytes inline (mark bit 0x80 of the
                 last byte + length in bits 56-62, payload at byte 0);
                 longer values as (offset << 32 | length) pointing into
  [var part]     8-byte-aligned payloads appended after the fixed part

The serialized form used inside manifests prefixes the row bytes with a
4-byte BIG-endian arity (reference utils/SerializationUtils.java:75-89).

Only flat rows of the types that appear in partitions / keys / stats rows
are supported (bool, int8..64, float32/64, string, bytes, date, compact
timestamp) — exactly what the metadata plane needs.
"""

from __future__ import annotations

import struct

from ..types import DataType, RowType, TypeRoot

__all__ = ["encode_binary_row", "decode_binary_row", "serialize_binary_row", "deserialize_binary_row"]

_FIXED8 = {
    TypeRoot.BIGINT: "<q",
    TypeRoot.DOUBLE: "<d",
    TypeRoot.TIMESTAMP: "<q",
    TypeRoot.TIMESTAMP_LTZ: "<q",
}
_FIXED4 = {
    TypeRoot.INT: "<i",
    TypeRoot.DATE: "<i",
    TypeRoot.TIME: "<i",
    TypeRoot.FLOAT: "<f",
}


def _bitset_bytes(arity: int) -> int:
    return ((arity + 63 + 8) // 64) * 8


def encode_binary_row(values: list, types: list[DataType], row_kind: int = 0) -> bytes:
    """values -> BinaryRow bytes (no arity prefix)."""
    arity = len(values)
    nb = _bitset_bytes(arity)
    fixed = nb + 8 * arity
    buf = bytearray(fixed)
    buf[0] = row_kind & 0xFF
    var = bytearray()

    def set_null(i: int) -> None:
        idx = i + 8
        buf[idx >> 3] |= 1 << (idx & 7)

    for i, (v, t) in enumerate(zip(values, types)):
        off = nb + 8 * i
        if v is None:
            set_null(i)
            continue
        root = t.root
        if root == TypeRoot.BOOLEAN:
            buf[off] = 1 if v else 0
        elif root in (TypeRoot.TINYINT, TypeRoot.SMALLINT):
            struct.pack_into("<h" if root == TypeRoot.SMALLINT else "<b", buf, off, int(v))
        elif root in _FIXED4:
            struct.pack_into(_FIXED4[root], buf, off, v if root == TypeRoot.FLOAT else int(v))
        elif root in _FIXED8:
            struct.pack_into(_FIXED8[root], buf, off, float(v) if root == TypeRoot.DOUBLE else int(v))
        elif root in (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY):
            data = v.encode("utf-8") if isinstance(v, str) else bytes(v)
            if len(data) <= 7:
                buf[off : off + len(data)] = data
                buf[off + 7] = 0x80 | len(data)
            else:
                # var part is 8-byte aligned; offset is from row start
                cursor = fixed + len(var)
                var += data
                pad = (-len(data)) % 8
                var += b"\x00" * pad
                struct.pack_into("<q", buf, off, (cursor << 32) | len(data))
        else:
            raise ValueError(f"binary-row type {root} not supported in metadata rows")
    return bytes(buf) + bytes(var)


def decode_binary_row(data: bytes, types: list[DataType]) -> list:
    """BinaryRow bytes (no prefix) -> values."""
    arity = len(types)
    nb = _bitset_bytes(arity)
    out = []
    for i, t in enumerate(types):
        idx = i + 8
        if data[idx >> 3] & (1 << (idx & 7)):
            out.append(None)
            continue
        off = nb + 8 * i
        root = t.root
        if root == TypeRoot.BOOLEAN:
            out.append(bool(data[off]))
        elif root == TypeRoot.TINYINT:
            out.append(struct.unpack_from("<b", data, off)[0])
        elif root == TypeRoot.SMALLINT:
            out.append(struct.unpack_from("<h", data, off)[0])
        elif root in _FIXED4:
            out.append(struct.unpack_from(_FIXED4[root], data, off)[0])
        elif root in _FIXED8:
            out.append(struct.unpack_from(_FIXED8[root], data, off)[0])
        elif root in (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY):
            slot = struct.unpack_from("<Q", data, off)[0]
            if slot & (0x80 << 56):
                ln = (slot >> 56) & 0x7F
                raw = data[off : off + ln]
            else:
                sub = slot >> 32
                ln = slot & 0xFFFFFFFF
                raw = data[sub : sub + ln]
            out.append(raw.decode("utf-8") if root in (TypeRoot.CHAR, TypeRoot.VARCHAR) else bytes(raw))
        else:
            raise ValueError(f"binary-row type {root} not supported in metadata rows")
    return out


def serialize_binary_row(values: list, types: list[DataType], row_kind: int = 0) -> bytes:
    """4-byte big-endian arity + row bytes (SerializationUtils.serializeBinaryRow)."""
    row = encode_binary_row(values, types, row_kind)
    return struct.pack(">i", len(values)) + row


def deserialize_binary_row(data: bytes, types: list[DataType]) -> list:
    arity = struct.unpack_from(">i", data, 0)[0]
    assert arity == len(types), (arity, len(types))
    return decode_binary_row(data[4:], types)
