"""Generic Avro object-container-file io for python dict records.

The columnar codec in format/avro.py is the data-plane fast path (flat
schemas, block-vectorized). Manifests need the opposite trade: tiny files,
deeply nested records (ManifestEntry -> DataFileMeta -> SimpleStats), exact
schema naming — so this module walks arbitrary record/array/union schemas
recursively, the way the reference's manifest serializers use the Avro
library (/root/reference/paimon-core/.../manifest/ManifestFile.java:48).
Supported types: null, boolean, int, long, float, double, bytes, string,
record, array, union (logical types pass through their base type).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib

__all__ = ["write_ocf", "read_ocf"]

_MAGIC = b"Obj\x01"


def _zz(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzz(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _write_long(out: bytearray, v: int) -> None:
    v = _zz(v)
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_long(buf, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return _unzz(result), pos
        shift += 7


def _encode(out: bytearray, schema, value) -> None:
    if isinstance(schema, list):  # union
        for idx, branch in enumerate(schema):
            btype = branch if isinstance(branch, str) else branch.get("type")
            if value is None and btype == "null":
                _write_long(out, idx)
                return
            if value is not None and btype != "null":
                _write_long(out, idx)
                _encode(out, branch, value)
                return
        raise ValueError(f"no union branch for {value!r} in {schema}")
    stype = schema if isinstance(schema, str) else schema["type"]
    if stype == "null":
        return
    if stype == "boolean":
        out.append(1 if value else 0)
    elif stype in ("int", "long"):
        _write_long(out, int(value))
    elif stype == "float":
        out += struct.pack("<f", value)
    elif stype == "double":
        out += struct.pack("<d", value)
    elif stype == "bytes":
        data = bytes(value)
        _write_long(out, len(data))
        out += data
    elif stype == "string":
        data = value.encode("utf-8")
        _write_long(out, len(data))
        out += data
    elif stype == "record":
        for f in schema["fields"]:
            _encode(out, f["type"], value.get(f["name"]))
    elif stype == "array":
        items = list(value)
        if items:
            _write_long(out, len(items))
            for item in items:
                _encode(out, schema["items"], item)
        _write_long(out, 0)
    else:
        raise ValueError(f"unsupported avro type {stype}")


def _decode(buf, pos: int, schema):
    if isinstance(schema, list):  # union
        idx, pos = _read_long(buf, pos)
        return _decode(buf, pos, schema[idx])
    stype = schema if isinstance(schema, str) else schema["type"]
    if stype == "null":
        return None, pos
    if stype == "boolean":
        return bool(buf[pos]), pos + 1
    if stype in ("int", "long"):
        return _read_long(buf, pos)
    if stype == "float":
        return struct.unpack_from("<f", buf, pos)[0], pos + 4
    if stype == "double":
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if stype == "bytes":
        ln, pos = _read_long(buf, pos)
        return bytes(buf[pos : pos + ln]), pos + ln
    if stype == "string":
        ln, pos = _read_long(buf, pos)
        return bytes(buf[pos : pos + ln]).decode("utf-8"), pos + ln
    if stype == "record":
        rec = {}
        for f in schema["fields"]:
            rec[f["name"]], pos = _decode(buf, pos, f["type"])
        return rec, pos
    if stype == "array":
        items = []
        while True:
            count, pos = _read_long(buf, pos)
            if count == 0:
                return items, pos
            if count < 0:  # block with byte size
                _, pos = _read_long(buf, pos)
                count = -count
            for _ in range(count):
                v, pos = _decode(buf, pos, schema["items"])
                items.append(v)
    raise ValueError(f"unsupported avro type {stype}")


def write_ocf(schema: dict, records: list[dict], codec: str = "deflate") -> bytes:
    """Records -> Avro object container file bytes."""
    out = bytearray(_MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(), "avro.codec": codec.encode()}
    _write_long(out, len(meta))
    for k, v in meta.items():
        kb = k.encode()
        _write_long(out, len(kb))
        out += kb
        _write_long(out, len(v))
        out += v
    _write_long(out, 0)
    sync = os.urandom(16)
    out += sync
    if records:
        body = bytearray()
        for r in records:
            _encode(body, schema, r)
        payload = bytes(body)
        if codec == "deflate":
            payload = zlib.compress(payload)[2:-4]  # raw deflate per avro spec
        _write_long(out, len(records))
        _write_long(out, len(payload))
        out += payload
        out += sync
    return bytes(out)


def read_ocf(data: bytes) -> tuple[dict, list[dict]]:
    """Avro OCF bytes -> (schema, records)."""
    assert data[:4] == _MAGIC, "not an avro object container file"
    buf = memoryview(data)
    pos = 4
    meta: dict[str, bytes] = {}
    while True:
        count, pos = _read_long(buf, pos)
        if count == 0:
            break
        if count < 0:
            _, pos = _read_long(buf, pos)
            count = -count
        for _ in range(count):
            kl, pos = _read_long(buf, pos)
            k = bytes(buf[pos : pos + kl]).decode()
            pos += kl
            vl, pos = _read_long(buf, pos)
            meta[k] = bytes(buf[pos : pos + vl])
            pos += vl
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    pos += 16  # sync
    records: list[dict] = []
    n = len(data)
    while pos < n:
        count, pos = _read_long(buf, pos)
        size, pos = _read_long(buf, pos)
        payload = bytes(buf[pos : pos + size])
        pos += size + 16  # skip sync
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec}")
        p2 = 0
        pv = memoryview(payload)
        for _ in range(count):
            rec, p2 = _decode(pv, p2, schema)
            records.append(rec)
    return schema, records
