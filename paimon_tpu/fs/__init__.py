"""L0: filesystem abstraction.

The lake is just files; the only primitive the commit protocol needs from the
filesystem is an *atomic rename* (write temp file, rename into place, rename
fails if destination exists). Everything above — manifests, snapshots, data
files — is immutable once written.

Capability parity with the reference:
  /root/reference/paimon-common/src/main/java/org/apache/paimon/fs/FileIO.java:62
  (scheme-based discovery :336/:459, tryToWriteAtomic :235), fs/local/.

TPU note: FileIO is pure host-side; device code never touches it. Reads hand
bytes (or pyarrow readers) to the format layer which materializes column
batches for device transfer.
"""

from __future__ import annotations

import io
import os
import threading
import uuid
from dataclasses import dataclass
from typing import Callable, Iterator
from urllib.parse import urlparse

__all__ = [
    "FileStatus",
    "FileIO",
    "LocalFileIO",
    "register_file_io",
    "get_file_io",
    "split_scheme",
]


@dataclass(frozen=True)
class FileStatus:
    path: str
    size: int
    is_dir: bool
    mtime_millis: int = 0


def split_scheme(path: str) -> tuple[str, str]:
    """("file", "/a/b") from "file:///a/b" or bare "/a/b"."""
    if "://" not in path:
        return "file", path
    p = urlparse(path)
    return p.scheme, (p.netloc + p.path if p.netloc else p.path)


class FileIO:
    """Abstract filesystem. All paths are absolute strings (optionally with a
    scheme prefix, which implementations strip via split_scheme)."""

    # object-store adapters without a no-clobber rename set this False;
    # commits then automatically run under the catalog lock
    atomic_write_supported: bool = True
    # False on stores without exclusive create (no conditional PUT): the
    # file-based catalog lock cannot work there — commits must configure an
    # external lock (commit.catalog-lock.type=jdbc)
    exclusive_create_supported: bool = True

    # ---- required primitives ------------------------------------------
    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        """overwrite=False MUST be an atomic exclusive create (raise
        FileExistsError on a loser) — the catalog lock's mutual exclusion
        rests on it; a check-then-write implementation breaks commits on
        stores without atomic rename."""
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def delete(self, path: str, recursive: bool = False) -> bool:
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> bool:
        """Atomic move; returns False (no partial state) if dst exists."""
        raise NotImplementedError

    def list_status(self, path: str) -> list[FileStatus]:
        raise NotImplementedError

    def get_status(self, path: str) -> FileStatus:
        raise NotImplementedError

    # ---- derived helpers ----------------------------------------------
    def try_atomic_write(self, path: str, data: bytes) -> bool:
        """The commit primitive (reference FileIO#tryToWriteAtomic): write to a
        hidden temp sibling then rename. Returns False if `path` already
        exists (lost the CAS race); never leaves a partial destination."""
        tmp = self._temp_sibling(path)
        self.write_bytes(tmp, data, overwrite=True)
        try:
            ok = self.rename(tmp, path)
        finally:
            if self.exists(tmp):
                try:
                    self.delete(tmp)
                except Exception:
                    pass
        return ok

    def _temp_sibling(self, path: str) -> str:
        d, b = os.path.split(path)
        return os.path.join(d, f".{b}.{uuid.uuid4().hex}.tmp")

    def read_text(self, path: str) -> str:
        return self.read_bytes(path).decode("utf-8")

    def write_text(self, path: str, text: str, overwrite: bool = False) -> None:
        self.write_bytes(path, text.encode("utf-8"), overwrite)

    def try_overwrite(self, path: str, data: bytes) -> bool:
        """Overwrite via temp+delete+rename (used for hint files; readers may
        transiently miss the file but never see partial content). Returns
        False if a concurrent writer won the re-create race; never leaks the
        temp file either way."""
        tmp = self._temp_sibling(path)
        self.write_bytes(tmp, data, overwrite=True)
        try:
            self.delete(path)
            ok = self.rename(tmp, path)
        finally:
            if self.exists(tmp):
                try:
                    self.delete(tmp)
                except Exception:
                    pass
        return ok

    def list_files(self, path: str) -> list[FileStatus]:
        return [s for s in self.list_status(path) if not s.is_dir]

    def open_input(self, path: str) -> io.BufferedIOBase:
        """Seekable stream for format readers (pyarrow accepts file objects)."""
        return io.BytesIO(self.read_bytes(path))

    def local_path(self, path: str) -> str | None:
        """OS filesystem path for `path`, or None when the backing store is
        not the local filesystem. Format readers prefer a real path: pyarrow
        then does its own C++ file IO (memory-mappable) instead of calling
        back into a Python file object — the Python-file shim is also unsafe
        under concurrent multi-threaded reads (flaky segfaults when two pool
        threads hit first-use lazily-initialized state). Wrappers that
        intercept reads (Failing/Traceable) inherit this None default, so
        fault injection always sees the stream path."""
        return None


def _rename_noreplace(src: str, dst: str) -> bool:
    """renameat2(AT_FDCWD, src, AT_FDCWD, dst, RENAME_NOREPLACE): atomically
    publish src at dst iff dst does not exist. True on win, False when dst
    already exists, OSError when the kernel/filesystem lacks the flag."""
    import ctypes
    import errno as _errno

    libc = ctypes.CDLL(None, use_errno=True)
    renameat2 = getattr(libc, "renameat2", None)
    if renameat2 is None:  # libc without the symbol (macOS, old glibc/musl)
        raise OSError(_errno.ENOSYS, "renameat2 not available")
    AT_FDCWD = -100
    RENAME_NOREPLACE = 1
    r = renameat2(
        AT_FDCWD, os.fsencode(src), AT_FDCWD, os.fsencode(dst), RENAME_NOREPLACE
    )
    if r == 0:
        return True
    e = ctypes.get_errno()
    if e == _errno.EEXIST:
        return False
    raise OSError(e, os.strerror(e))


class LocalFileIO(FileIO):
    """Local/POSIX filesystem. os.rename within one FS is atomic; we emulate
    rename-fails-if-exists with os.link+unlink to get true no-clobber CAS."""

    def _p(self, path: str) -> str:
        return split_scheme(path)[1]

    def read_bytes(self, path: str) -> bytes:
        with open(self._p(path), "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        p = self._p(path)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        if overwrite:
            with open(p, "wb") as f:
                f.write(data)
            return
        # O_EXCL: creation is a true CAS (check-then-write would let two
        # writers both succeed), which the catalog lock relies on
        fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o666)
        with os.fdopen(fd, "wb") as f:
            f.write(data)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._p(path))

    def delete(self, path: str, recursive: bool = False) -> bool:
        p = self._p(path)
        try:
            if os.path.isdir(p):
                if recursive:
                    import shutil

                    shutil.rmtree(p)
                else:
                    os.rmdir(p)
            else:
                os.remove(p)
            return True
        except FileNotFoundError:
            return False

    def mkdirs(self, path: str) -> None:
        os.makedirs(self._p(path), exist_ok=True)

    def rename(self, src: str, dst: str) -> bool:
        s, d = self._p(src), self._p(dst)
        os.makedirs(os.path.dirname(d), exist_ok=True)
        try:
            # hard-link is atomic and fails with EEXIST if dst exists -> CAS
            os.link(s, d)
        except FileExistsError:
            return False
        except OSError:
            if os.path.isdir(s):
                # directory rename (catalog-level, not the commit CAS):
                # os.rename refuses to clobber a non-empty dst on POSIX
                if os.path.exists(d):
                    return False
                os.rename(s, d)
                return True
            # Filesystems without hard links (some FUSE/NFS mounts). Two
            # invariants must survive: (a) CAS — exactly one of N racing
            # committers wins; (b) dst only ever appears FULLY formed (a
            # reader polling for snapshot-N must never parse a partial
            # file, and a crash must never wedge the path). So: stage a
            # complete same-directory copy, then publish it with
            # renameat2(RENAME_NOREPLACE) — one atomic syscall does both.
            import shutil

            tmp = f"{d}.tmp-{uuid.uuid4().hex}"
            shutil.copyfile(s, tmp)
            tf = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(tf)
            finally:
                os.close(tf)
            try:
                won = _rename_noreplace(tmp, d)
            except OSError:
                # kernel/FS without renameat2 flags: content atomicity still
                # holds (rename of a complete temp), exclusivity degrades to
                # best-effort check-then-rename
                if os.path.exists(d):
                    won = False
                else:
                    os.rename(tmp, d)
                    won = True
            if not won:
                os.unlink(tmp)
                return False
            os.unlink(s)
            return True
        os.unlink(s)
        return True

    def list_status(self, path: str) -> list[FileStatus]:
        p = self._p(path)
        if not os.path.isdir(p):
            return []
        out = []
        for name in sorted(os.listdir(p)):
            fp = os.path.join(p, name)
            try:
                st = os.stat(fp)
            except FileNotFoundError:
                continue
            out.append(
                FileStatus(fp, st.st_size, os.path.isdir(fp), int(st.st_mtime * 1000))
            )
        return out

    def get_status(self, path: str) -> FileStatus:
        p = self._p(path)
        st = os.stat(p)
        return FileStatus(p, st.st_size, os.path.isdir(p), int(st.st_mtime * 1000))

    def open_input(self, path: str) -> io.BufferedIOBase:
        return open(self._p(path), "rb")

    def local_path(self, path: str) -> str:
        return self._p(path)


_REGISTRY: dict[str, Callable[[], FileIO]] = {}
_LOCK = threading.Lock()


def register_file_io(scheme: str, factory: Callable[[], FileIO]) -> None:
    """SPI-style registration (reference FileIO.discoverLoaders)."""
    with _LOCK:
        _REGISTRY[scheme] = factory


def get_file_io(path: str) -> FileIO:
    scheme, _ = split_scheme(path)
    with _LOCK:
        factory = _REGISTRY.get(scheme)
    if factory is None:
        # lazy SPI load (reference FileIO.discoverLoaders loads plugin
        # modules on first use of an unknown scheme); the plugin module owns
        # the scheme->factory knowledge, nothing is hardcoded here
        from . import object_store  # noqa: F401  (registers on import)

        with _LOCK:
            factory = _REGISTRY.get(scheme)
    if factory is not None:
        return factory()
    if scheme == "file":
        return LocalFileIO()
    raise ValueError(f"no FileIO registered for scheme {scheme!r} ({path})")
