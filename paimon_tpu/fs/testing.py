"""Fault-injecting and leak-tracking FileIO wrappers for tests.

Capability parity with the reference test infrastructure:
  /root/reference/paimon-core/src/test/java/org/apache/paimon/utils/FailingFileIO.java:44
  (reset(name, maxFails, possibility) :57) and TraceableFileIO (open-stream
  leak tracking). Registered under their own schemes so the whole store stack
  runs against them unchanged — that is how commit crash-safety is proven.
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field, replace

from . import FileIO, FileStatus, LocalFileIO, register_file_io, split_scheme


def _posix_backed(io: FileIO) -> bool:
    """Walk a wrapper chain's `_inner` links: does this stack bottom out on
    plain POSIX rename (LocalFileIO)? Wrappers are composable (chaos =
    faults over latency over local), so the decision "decompose
    try_atomic_write into write+rename with crash-realistic injection" vs
    "delegate to an overriding commit primitive (object-store conditional
    PUT)" must look through every layer, not just the immediate inner."""
    seen: set[int] = set()
    while not isinstance(io, LocalFileIO):
        if id(io) in seen:
            return False
        seen.add(id(io))
        nxt = getattr(io, "_inner", None)
        if nxt is None:
            return False
        io = nxt
    return True


class ArtificialException(IOError):
    """Deliberately injected failure. Carries the resilience layer's explicit
    `transient = True` marker (see resilience.retry.is_transient), so it
    classifies TRANSIENT exactly like a real object-store blip and retry
    behavior is provable with it."""

    transient = True


@dataclass
class FaultRule:
    """One deterministic scripted fault: fail ops whose kind matches `op`
    ('read' | 'write' | 'rename' | 'delete' | 'atomic' | '*') and whose
    LOGICAL path (scheme and domain stripped; for renames, the destination)
    contains `path`. Fires on the nth..nth+count-1 matching ops (1-based);
    count <= 0 keeps firing forever.

    The two canonical shapes: FaultRule(op, path, nth=N) = "fail the Nth op
    matching this pattern"; FaultRule(op, path) = fail-once-then-succeed.
    A rule on op='rename' against a path written with try_atomic_write is a
    TORN write: the tmp sibling is already on disk and stays there (crash
    semantics — see FailingFileIO.try_atomic_write)."""

    op: str = "*"
    path: str | None = None
    nth: int = 1
    count: int = 1
    _seen: int = 0

    def fire(self, op: str, path: str) -> bool:
        if self.op != "*" and self.op != op:
            return False
        if self.path is not None and self.path not in path:
            return False
        self._seen += 1
        if self._seen < self.nth:
            return False
        return self.count <= 0 or self._seen < self.nth + self.count


@dataclass
class _FailState:
    max_fails: int = 0
    possibility: int = 0  # fail with probability 1/possibility
    fails: int = 0
    rng: random.Random = field(default_factory=lambda: random.Random(0))
    lock: threading.Lock = field(default_factory=threading.Lock)
    rules: list[FaultRule] = field(default_factory=list)

    def check(self, op: str, path: str, probabilistic: bool = True) -> None:
        with self.lock:
            for rule in self.rules:
                if rule.fire(op, path):
                    self.fails += 1
                    raise ArtificialException(f"scheduled fault: {op} {path}")
            if probabilistic and self.possibility > 0 and self.fails < self.max_fails:
                if self.rng.randrange(self.possibility) == 0:
                    self.fails += 1
                    raise ArtificialException("artificial failure")

    # back-compat shim for callers scripted against the seed API
    def maybe_fail(self) -> None:
        self.check("*", "")


class FailingFileIO(FileIO):
    """Randomly throws ArtificialException on read/write, per named domain.

    Usage:
        FailingFileIO.reset("mytest", max_fails=100, possibility=10)
        path = f"fail://mytest{local_dir}"

    Any FileIO can be wrapped (scheme "fail-s3" injects over the
    object-store semantics, proving the commit protocol for that store the
    same way "fail" proves it for POSIX)."""

    _states: dict[str, _FailState] = {}

    def __init__(self, inner: FileIO | None = None):
        self._inner = inner or LocalFileIO()
        # capability flags must shine through the wrapper: a commit over
        # fail-s3 engages the catalog lock exactly like over s3
        self.atomic_write_supported = getattr(self._inner, "atomic_write_supported", True)
        self.exclusive_create_supported = getattr(self._inner, "exclusive_create_supported", True)

    @classmethod
    def reset(cls, name: str, max_fails: int, possibility: int, seed: int = 0) -> None:
        st = _FailState(max_fails, possibility)
        st.rng = random.Random(seed)
        cls._states[name] = st

    @classmethod
    def schedule(cls, name: str, *rules: FaultRule) -> None:
        """Install a DETERMINISTIC fault schedule for `name` (replaces any
        probabilistic state): each rule scripts exactly which ops fail."""
        st = _FailState()
        st.rules = list(rules)
        cls._states[name] = st

    @classmethod
    def fails_injected(cls, name: str) -> int:
        st = cls._states.get(name)
        return 0 if st is None else st.fails

    @classmethod
    def retry_until_success(cls, name: str, fn):
        """Disable injection for `name`, then run fn (for final verification)."""
        cls._states.pop(name, None)
        return fn()

    def _strip(self, path: str) -> tuple[_FailState | None, str]:
        if "://" not in path:
            # already a bare inner path (e.g. a FileStatus.path handed back
            # by a caller) — stripping would eat its first segment as a
            # phantom domain name
            return None, path
        scheme, rest = split_scheme(path)
        # path layout: fail://<name><abs-path>
        name, sep, tail = rest.lstrip("/").partition("/")
        local = "/" + tail
        return self._states.get(name), local

    def _wrap(self, path: str, op: str) -> str:
        st, local = self._strip(path)
        if st is not None:
            st.check(op, local)
        return local

    def read_bytes(self, path: str) -> bytes:
        return self._inner.read_bytes(self._wrap(path, "read"))

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        self._inner.write_bytes(self._wrap(path, "write"), data, overwrite)

    def exists(self, path: str) -> bool:
        _, local = self._strip(path)
        return self._inner.exists(local)

    def delete(self, path: str, recursive: bool = False) -> bool:
        # deterministic rules only: the probabilistic oracle never failed
        # deletes (seed behavior), but scheduled delete faults let tests
        # prove cleanup failures are non-fatal
        st, local = self._strip(path)
        if st is not None:
            st.check("delete", local, probabilistic=False)
        return self._inner.delete(local, recursive)

    def mkdirs(self, path: str) -> None:
        _, local = self._strip(path)
        self._inner.mkdirs(local)

    def rename(self, src: str, dst: str) -> bool:
        st, s = self._strip(src)
        _, d = self._strip(dst)
        if st is not None:
            st.check("rename", d)
        return self._inner.rename(s, d)

    def list_status(self, path: str) -> list[FileStatus]:
        _, local = self._strip(path)
        children = self._inner.list_status(local)
        if "://" not in path:
            return children
        # re-prefix children so round-trips (exists/get_table on a listed
        # path) keep the scheme + domain and stay under fault injection
        scheme, rest = split_scheme(path)
        name, _, _ = rest.lstrip("/").partition("/")
        return [replace(st, path=f"{scheme}://{name}{st.path}") for st in children]

    def get_status(self, path: str) -> FileStatus:
        _, local = self._strip(path)
        return self._inner.get_status(local)

    def open_input(self, path: str):
        return self._inner.open_input(self._wrap(path, "read"))

    def try_atomic_write(self, path: str, data: bytes) -> bool:
        st, local = self._strip(path)
        if not _posix_backed(self._inner):
            # inner overrides the commit primitive (object store: conditional
            # PUT, no rename) — delegate so the oracle exercises THAT protocol
            if st is not None:
                st.check("atomic", local)
            return self._inner.try_atomic_write(local, data)
        # POSIX temp+rename, decomposed with CRASH-realistic injection:
        # - a fault on the write phase fires before any bytes land;
        # - a fault on the rename phase fires AFTER the tmp write, and the
        #   torn tmp sibling STAYS on disk (a crashed process runs no
        #   cleanup) — reclaiming it is remove_orphan_files' job. The seed
        #   harness cleaned the tmp in a finally block, which made
        #   torn-write recovery untestable.
        if st is not None:
            st.check("write", local)
        tmp = self._temp_sibling(local)
        self._inner.write_bytes(tmp, data, overwrite=True)
        if st is not None:
            st.check("rename", local)
        ok = self._inner.rename(tmp, local)
        if not ok:
            # graceful CAS loser (no crash): clean our own staging file
            try:
                self._inner.delete(tmp)
            except Exception:
                pass
        return ok

    def try_overwrite(self, path: str, data: bytes) -> bool:
        if _posix_backed(self._inner):
            return super().try_overwrite(path, data)
        st, local = self._strip(path)
        if st is not None:
            st.check("atomic", local)
        return self._inner.try_overwrite(local, data)


class LatencyFileIO(FileIO):
    """Injects a fixed per-op sleep over LocalFileIO — object-store
    first-byte latency as a local, deterministic effect, so benchmarks and
    tests can measure how much of it the pipelined split scheduler hides
    (overlapped fetches pay the RTT concurrently; a serial scan pays it once
    per file). Paths: ``latency://<abs-path>``. Inherits the base
    local_path=None, so format readers take the stream path where the
    latency is injected — exactly the code path a remote store would use."""

    read_ms: float = 0.0
    write_ms: float = 0.0

    @classmethod
    def configure(cls, read_ms: float = 0.0, write_ms: float = 0.0) -> None:
        cls.read_ms = read_ms
        cls.write_ms = write_ms

    def __init__(self, inner: FileIO | None = None):
        self._inner = inner or LocalFileIO()
        # capability flags shine through, same contract as FailingFileIO —
        # latency over an object store must still engage that store's
        # commit protocol (conditional PUT / catalog lock)
        self.atomic_write_supported = getattr(self._inner, "atomic_write_supported", True)
        self.exclusive_create_supported = getattr(self._inner, "exclusive_create_supported", True)

    def _p(self, path: str) -> str:
        return split_scheme(path)[1]

    def try_atomic_write(self, path: str, data: bytes) -> bool:
        # POSIX bottom: the base temp+rename decomposition routes through
        # self.write_bytes/self.rename, so the write nap is paid exactly once
        # (rename is metadata-only — no first-byte latency on a real store
        # either). Non-POSIX bottom: delegate the overriding commit primitive.
        if _posix_backed(self._inner):
            return super().try_atomic_write(path, data)
        self._nap(LatencyFileIO.write_ms)
        return self._inner.try_atomic_write(self._p(path), data)

    def _nap(self, ms: float) -> None:
        if ms > 0:
            import time

            time.sleep(ms / 1000.0)

    def read_bytes(self, path: str) -> bytes:
        self._nap(LatencyFileIO.read_ms)
        return self._inner.read_bytes(self._p(path))

    def open_input(self, path: str):
        self._nap(LatencyFileIO.read_ms)
        return self._inner.open_input(self._p(path))

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        self._nap(LatencyFileIO.write_ms)
        self._inner.write_bytes(self._p(path), data, overwrite)

    def exists(self, path: str) -> bool:
        return self._inner.exists(self._p(path))

    def delete(self, path: str, recursive: bool = False) -> bool:
        return self._inner.delete(self._p(path), recursive)

    def mkdirs(self, path: str) -> None:
        self._inner.mkdirs(self._p(path))

    def rename(self, src: str, dst: str) -> bool:
        return self._inner.rename(self._p(src), self._p(dst))

    def list_status(self, path: str) -> list[FileStatus]:
        return self._inner.list_status(self._p(path))

    def get_status(self, path: str) -> FileStatus:
        return self._inner.get_status(self._p(path))


class TraceableFileIO(FileIO):
    """Tracks open streams so tests can assert no reader/writer leaks."""

    open_streams: list[str] = []
    _lock = threading.Lock()

    def __init__(self):
        self._inner = LocalFileIO()

    @classmethod
    def assert_no_leaks(cls) -> None:
        with cls._lock:
            assert not cls.open_streams, f"leaked streams: {cls.open_streams}"

    def _p(self, path: str) -> str:
        return split_scheme(path)[1]

    def open_input(self, path: str):
        f = self._inner.open_input(self._p(path))
        with TraceableFileIO._lock:
            TraceableFileIO.open_streams.append(path)
        orig_close = f.close

        def close():
            with TraceableFileIO._lock:
                if path in TraceableFileIO.open_streams:
                    TraceableFileIO.open_streams.remove(path)
            orig_close()

        f.close = close  # type: ignore[method-assign]
        return f

    # explicit delegation (base-class stubs would otherwise shadow __getattr__)
    def read_bytes(self, path: str) -> bytes:
        return self._inner.read_bytes(self._p(path))

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        self._inner.write_bytes(self._p(path), data, overwrite)

    def exists(self, path: str) -> bool:
        return self._inner.exists(self._p(path))

    def delete(self, path: str, recursive: bool = False) -> bool:
        return self._inner.delete(self._p(path), recursive)

    def mkdirs(self, path: str) -> None:
        self._inner.mkdirs(self._p(path))

    def rename(self, src: str, dst: str) -> bool:
        return self._inner.rename(self._p(src), self._p(dst))

    def list_status(self, path: str) -> list[FileStatus]:
        return self._inner.list_status(self._p(path))

    def get_status(self, path: str) -> FileStatus:
        return self._inner.get_status(self._p(path))


CHAOS_ENV = "PAIMON_TPU_CHAOS"


def apply_chaos_env(spec: str | None = None) -> None:
    """Parse a chaos spec — ``read_ms=40,write_ms=15,domain=mega0,
    possibility=150,max_fails=100000,seed=7`` — and shape this process's
    chaos stack: class-level latency plus a probabilistic fault domain.
    Reads PAIMON_TPU_CHAOS when `spec` is None, so OS-process children of a
    soak supervisor inherit the exact same store shape with no code
    handshake (the crash-point env idiom applied to IO). The fault domain
    is created only if absent: re-entering the factory mid-run must not
    reset injected-fault counters."""
    if spec is None:
        spec = os.environ.get(CHAOS_ENV, "")
    if not spec:
        return
    cfg = dict(kv.split("=", 1) for kv in spec.split(",") if kv)
    LatencyFileIO.configure(
        read_ms=float(cfg.get("read_ms", 0)), write_ms=float(cfg.get("write_ms", 0))
    )
    domain = cfg.get("domain")
    if domain and domain not in FailingFileIO._states:
        FailingFileIO.reset(
            domain,
            max_fails=int(cfg.get("max_fails", 1 << 30)),
            possibility=int(cfg.get("possibility", 0)),
            seed=int(cfg.get("seed", 0)),
        )


def chaos_spec(
    domain: str,
    read_ms: float = 0.0,
    write_ms: float = 0.0,
    possibility: int = 0,
    max_fails: int = 1 << 30,
    seed: int = 0,
) -> str:
    """Build the PAIMON_TPU_CHAOS value for `apply_chaos_env` — the
    supervisor composes this once, exports it to every child, and applies
    it locally; paths then use ``chaos://<domain><abs-path>``."""
    return (
        f"domain={domain},read_ms={read_ms},write_ms={write_ms},"
        f"possibility={possibility},max_fails={max_fails},seed={seed}"
    )


def _chaos() -> FailingFileIO:
    """The composed chaos store: scripted/probabilistic faults layered over
    latency shaping over local disk, in ONE FileIO stack. Faults are
    checked before the latency nap (a failed op never reaches the store, so
    it must not pay first-byte latency), and try_atomic_write keeps the
    decomposed POSIX crash semantics — a rename-phase fault leaves the torn
    tmp sibling on disk THROUGH the latency layer."""
    apply_chaos_env()
    return FailingFileIO(inner=LatencyFileIO())


def _fail_s3() -> FailingFileIO:
    from .object_store import ObjectStoreFileIO

    return FailingFileIO(inner=ObjectStoreFileIO(conditional_put=True))


def _fail_s3_legacy() -> FailingFileIO:
    from .object_store import ObjectStoreFileIO

    return FailingFileIO(inner=ObjectStoreFileIO(conditional_put=False))


register_file_io("fail", FailingFileIO)
register_file_io("latency", LatencyFileIO)
register_file_io("chaos", _chaos)
register_file_io("fail-s3", _fail_s3)
register_file_io("fail-s3-legacy", _fail_s3_legacy)
register_file_io("traceable", TraceableFileIO)
